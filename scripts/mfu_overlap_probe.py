"""Overlap-aware FSDP gather + step-autotune probe on a forced CPU mesh.

Self-contained: forces ``JAX_PLATFORMS=cpu`` with 8 virtual devices
BEFORE importing jax, so it produces a real number on any machine —
including one whose accelerator backend is wedged, which is exactly when
bench.py falls back to it.

Two claims, both measured through scripts/mfu_sweep.py's variant
machinery (bench-honesty: the same ``_bench_gpt`` timed-window / sync
discipline as the driver bench, on the ``small`` CPU-measurable model):

1. **Scan-gather ≤ whole-tree gather.**  The compressed-FSDP train step
   with the layer-wise bf16 param all-gather INSIDE the transformer scan
   (``Trainer(gather_mode="scan")``) vs the PR 8 whole-tree up-front
   gather, both under remat (the composition the scan gather exists
   for: the backward re-gathers per layer instead of holding the
   replicated tree live).  Headline value = tree/scan step-time ratio
   (>= 1 means scan wins); the record also carries the analytic
   EXPOSED-comm reduction (wire_bytes_per_step's exposed/hidden split —
   bytes that serialize with compute vs bytes the scan overlaps).

2. **The closed loop improves on the default.**  ``tune.autotune_step``
   — the repo's own TPE searcher driving remat_policy x flash blocks x
   gather_mode against measured step time — returns a config whose
   measured step time is <= the default's (the default is trial 0, so
   the loop can only refine it).  The record reports best-vs-default
   and the winning config so the bench trajectory shows whether the
   search moved off the default.

CPU honesty note: with no async dispatch on the host backend, the
gather cannot hide under compute the way it does on TPU — the step-time
win here comes from the remat composition (no full replicated tree held
live) and is reported next to a no-remat context field; the
exposed-byte reduction is the claim that transfers to real
interconnects.

Emits one bench.py-shaped JSON line on stdout, with the bench-honesty
compile-count record and the telemetry snapshot printed BEFORE it (the
parser takes the newest value-bearing line).
"""

from __future__ import annotations

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _exposed_bytes(gather_mode: str) -> dict:
    """Analytic exposed/hidden wire split for the probe model's step
    (collectives.wire_bytes_per_step on the small GPT's fsdp layout)."""
    import jax

    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    from ray_lightning_accelerators_tpu.parallel import (
        collectives as C)
    from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib
    from ray_lightning_accelerators_tpu.parallel import (
        sharding as sharding_lib)

    cfg = TransformerConfig(vocab_size=2048, d_model=192, n_heads=6,
                            d_ff=768, n_layers=6, max_seq_len=128)
    model = GPT(cfg, lr=3e-4)
    params = model.init_params(jax.random.PRNGKey(0))
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, fsdp=8))
    psh = sharding_lib.tree_logical_to_shardings(
        mesh, model.param_logical_axes())
    rep = C.wire_bytes_per_step(
        params, C.dp_size(mesh), C.ExchangeConfig(mode="int8"),
        param_shardings=psh, gather_mode=gather_mode,
        scanned=model.scanned_param_subtrees()
        if gather_mode == "scan" else ())
    return {"exposed": rep["exposed_bytes_per_step"],
            "hidden": rep["hidden_bytes_per_step"],
            "report": rep}


def main() -> None:
    from mfu_sweep import VARIANTS, run_variant

    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg

    cg.install()

    tree_rec, _ = run_variant("gather-tree-smoke",
                              VARIANTS["gather-tree-smoke"])
    scan_rec, _ = run_variant("gather-scan-smoke",
                              VARIANTS["gather-scan-smoke"])
    auto_rec, _ = run_variant("autotuned-smoke",
                              VARIANTS["autotuned-smoke"])

    wire_tree = _exposed_bytes("tree")
    wire_scan = _exposed_bytes("scan")
    ratio = tree_rec["step_ms"] / scan_rec["step_ms"]
    exposed_reduction = (wire_tree["exposed"] / wire_scan["exposed"]
                         if wire_scan["exposed"] else float("inf"))
    # measured-vs-analytic exposed-comm crosscheck (telemetry/perf.py):
    # the PR 10 overlap claim as a measured, exported number — direction
    # agreement AND the per-mode discrepancy, never asserted away
    from ray_lightning_accelerators_tpu.telemetry import (
        exposed_comm_crosscheck)
    crosscheck = exposed_comm_crosscheck(
        {"tree": tree_rec["step_ms"] / 1e3,
         "scan": scan_rec["step_ms"] / 1e3},
        {"tree": wire_tree["report"], "scan": wire_scan["report"]})
    record = {
        "metric": "mfu_overlap_scan_vs_tree_step_time_ratio",
        "value": round(ratio, 3),
        "unit": "x",
        "tree_step_ms": tree_rec["step_ms"],
        "scan_step_ms": scan_rec["step_ms"],
        "tree_window_compiles": tree_rec["measured_window_compiles"],
        "scan_window_compiles": scan_rec["measured_window_compiles"],
        "exposed_bytes_tree": wire_tree["exposed"],
        "exposed_bytes_scan": wire_scan["exposed"],
        "hidden_bytes_scan": wire_scan["hidden"],
        "exposed_comm_reduction": round(exposed_reduction, 2),
        "exposed_comm_direction_agrees": crosscheck["direction_agrees"],
        "measured_exposed_fraction_tree": crosscheck["modes"]["tree"][
            "measured_exposed_fraction"],
        "measured_exposed_fraction_scan": crosscheck["modes"]["scan"][
            "measured_exposed_fraction"],
        "analytic_exposed_fraction_tree": crosscheck["modes"]["tree"][
            "analytic_exposed_fraction"],
        "analytic_exposed_fraction_scan": crosscheck["modes"]["scan"][
            "analytic_exposed_fraction"],
        "exposed_comm_discrepancy_tree": crosscheck["modes"]["tree"][
            "discrepancy"],
        "exposed_comm_discrepancy_scan": crosscheck["modes"]["scan"][
            "discrepancy"],
        "autotune_default_step_ms": auto_rec["default_step_ms"],
        "autotune_best_step_ms": auto_rec["step_ms"],
        "autotune_speedup": auto_rec["speedup_vs_default"],
        "autotune_best_config": auto_rec["best_config"],
        "autotune_trials": auto_rec["n_trials"],
        "fsdp": 8,
        "remat_policy": "nothing",
        "platform": "cpu-forced-host",
        "note": "both modes under remat (the composition the scan "
                "gather exists for); exposed-byte reduction is the "
                "claim that transfers to real interconnects",
        # the bar: scan-gather step time <= whole-tree at fsdp=8
        "vs_baseline": round(ratio, 3),
    }
    compile_rec = dict(
        cg.compile_count_record("mfu_overlap"),
        # steady-state retrace check for BOTH timed windows
        measured_window_compiles=(tree_rec["measured_window_compiles"]
                                  + scan_rec["measured_window_compiles"]))
    print(json.dumps(compile_rec), flush=True)
    from ray_lightning_accelerators_tpu.telemetry import (
        probe_snapshot_record)
    print(json.dumps(probe_snapshot_record("mfu_overlap")), flush=True)
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
