"""Prefix-affinity probe: affinity-vs-least-loaded p99 TTFT A/B plus a
disaggregated-lane decode-cadence window, on a forced host-platform CPU
mesh.

Self-contained: forces ``JAX_PLATFORMS=cpu`` with 8 virtual devices
BEFORE importing jax (matching the other CPU-mesh fallback probes), so
it produces a real number on any machine — including one whose
accelerator backend is wedged, which is exactly when bench.py falls
back to it.

Two parts:

1. **Affinity A/B (lanes off)**: the SAME skewed shared-prefix workload
   (4 hot 384-token prefix families x repeated suffix variants, each
   wave's arrival order shuffled the way real traffic interleaves) is
   served twice by a 3-replica tier whose block pools hold roughly two
   families each — once with affinity routing disabled (pure
   least-loaded spray: families wander across replicas with the
   shuffled arrivals, so the bounded prefix caches keep evicting and
   re-prefilling whole families under LRU) and once enabled (each
   family converges on the replica whose cache already holds its
   blocks, so repeats prefill only the suffix).  Requests route
   one-per-chunk so the comparison is pure routing policy, not chunk
   grouping.  The first two waves are a routing/cache warmup excluded
   from BOTH arms' windows — the A/B measures steady state, where a
   production tier lives.  The headline is the steady-state p99 TTFT
   ratio least-loaded/affinity (>1 = affinity faster) with the tier
   prefix-route hit rate as the mechanism evidence (driver bar:
   >= 0.5).

2. **Disaggregated lanes**: 1 prefill + 2 decode replicas; the same
   long-prompt stream prefills in the prefill lane and hands each KV
   block span to a decode replica (block-id remap + wave-bounded
   object-store copy).  Reported: decode-cadence p99 while the long
   prefill stream runs, and the KV handoff count (>= 1 proves the lane
   path served).

Output (compile-count line, telemetry line, metric line LAST —
the bench parser contract)::

    {"probe": "prefix_affinity", "kind": "compile_count", ...}
    {"probe": "prefix_affinity", "kind": "telemetry", ...}
    {"metric": "prefix_affinity_ttft_ratio", "value": ...,
     "unit": "ratio", "vs_baseline": ..., "prefix_hit_rate": ...,
     "decode_cadence_p99_ms": ..., "kv_handoffs": ..., ...}
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_FAMILIES = 4
REPEATS = 10                 # waves; one request per family per wave
WARMUP_WAVES = 2             # excluded from both arms' TTFT windows
PREFIX_LEN = 384             # 48 full blocks at block_len 8
BLOCK_LEN = 8
HEARTBEAT_S = 0.1
TTFT_RATIO_BAR = 1.0         # affinity must not lose to least-loaded
HIT_RATE_BAR = 0.5

_MODEL_CFG = dict(vocab_size=61, d_model=64, n_heads=4, d_ff=256,
                  n_layers=3, max_seq_len=512)


def _engine_factory(np_params, n_blocks):
    def make():
        from ray_lightning_accelerators_tpu.models.transformer import (
            GPT, TransformerConfig)
        from ray_lightning_accelerators_tpu.serve import ServeEngine
        model = GPT(TransformerConfig(**_MODEL_CFG))
        return ServeEngine(model, np_params, max_slots=4,
                           queue_depth=64, block_len=BLOCK_LEN,
                           n_blocks=n_blocks, idle_poll_s=0.005,
                           slo=None)
    return make


def _skewed_requests(rng):
    """Shared-prefix workload: each request is one of N_FAMILIES hot
    384-token prefixes + a short random suffix — the shape prefix
    routing exists for.  One request per family per wave, with each
    wave's arrival order shuffled: real traffic interleaves families
    arbitrarily, and a fixed arrival order would let least-loaded
    routing degenerate into an accidental stable family->replica
    assignment (queue order decides placement), hiding the re-prefill
    cost affinity exists to avoid."""
    import numpy as np
    prefixes = [rng.integers(1, 60, size=PREFIX_LEN).astype(np.int32)
                for _ in range(N_FAMILIES)]
    reqs = []
    for _ in range(REPEATS):
        for fam in rng.permutation(N_FAMILIES):
            suffix = rng.integers(1, 60, size=int(
                rng.integers(4, 9))).astype(np.int32)
            reqs.append((np.concatenate([prefixes[fam], suffix]), 2))
    return reqs


def _drive(group, reqs):
    """One wave of N_FAMILIES requests in flight at a time, so TTFT
    measures routing + prefill, not an ever-deepening queue.  The first
    WARMUP_WAVES waves run but are excluded from the returned TTFT
    window (routing + caches converge there in both A/B arms)."""
    import numpy as np
    ttfts, cadences = [], []
    for i in range(0, len(reqs), N_FAMILIES):
        handles = [(group.submit(p, n), n, time.monotonic())
                   for p, n in reqs[i:i + N_FAMILIES]]
        for h, n, t0 in handles:
            np.asarray(h.result(timeout=300))
            t_done = time.monotonic()
            if h.ttft_s is None or i < WARMUP_WAVES * N_FAMILIES:
                continue
            ttfts.append(h.ttft_s)
            if n > 1:
                cadences.append((t_done - t0 - h.ttft_s) / (n - 1))
    return ttfts, cadences


def _p99(values):
    import numpy as np
    return float(np.percentile(np.asarray(values), 99)) if values else 0.0


def _tier(factory, **cfg_overrides):
    from ray_lightning_accelerators_tpu.serve import (ControllerConfig,
                                                      ServeReplicas)
    cfg = ControllerConfig(hedge=False, poll_s=0.05, **cfg_overrides)
    return ServeReplicas(factory, num_replicas=3, chunk_size=1,
                         heartbeat_s=HEARTBEAT_S, queue_depth=64,
                         controller=cfg, affinity_block_len=BLOCK_LEN)


def _warm(group):
    """Warm every replica's compile path with a prompt DISJOINT from
    the measured families (vocab-0 filler never appears in the
    workload), so both A/B arms start with hot programs."""
    import numpy as np
    for _ in group.pool.workers:
        p = np.zeros(PREFIX_LEN + 4, np.int32)
        group.submit(p, 2).result(timeout=300)
    group.metrics.reset()


def probe(seed: int) -> tuple:
    import jax
    import numpy as np

    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)

    cg.install()
    model = GPT(TransformerConfig(**_MODEL_CFG))
    params = model.init_params(jax.random.PRNGKey(seed))
    np_params = jax.tree.map(np.asarray, params)
    rng = np.random.default_rng(seed)
    reqs = _skewed_requests(rng)
    # A/B pool: ~2 families' worth of cache per replica (48 blocks per
    # family + in-flight reservations) — under least-loaded spray the
    # shuffled arrivals walk every family across every replica and the
    # LRU prefix cache keeps evicting whole families; under affinity
    # each replica's 1-2 resident families fit stably
    ab_factory = _engine_factory(np_params, n_blocks=120)

    # -- part 1a: least-loaded spray (affinity off) -------------------- #
    with _tier(ab_factory, affinity=False) as spray:
        _warm(spray)
        window_start = cg.compile_count()
        ll_ttfts, _ = _drive(spray, reqs)
    # -- part 1b: the same workload under affinity routing ------------- #
    with _tier(ab_factory, affinity=True) as aff:
        _warm(aff)
        af_ttfts, _ = _drive(aff, reqs)
        aff_snap = aff.stats()
    compile_rec = cg.compile_count_record("prefix_affinity",
                                          window_start)
    hits = aff_snap["prefix_route_hits"]
    misses = aff_snap["prefix_route_misses"]
    hit_rate = hits / (hits + misses) if hits + misses else 0.0

    # -- part 2: disaggregated lanes (1 prefill + 2 decode) ------------ #
    # much bigger pool: the single prefill-lane replica carries every
    # in-flight request's export reservation PLUS the source holds of
    # already-handed-off requests (released asynchronously after the
    # decode side finishes), so a couple of waves of 48-block prompts
    # can be committed at once
    lane_factory = _engine_factory(np_params, n_blocks=640)
    with _tier(lane_factory, affinity=True, prefill_replicas=1,
               handoff_min_blocks=1) as lanes:
        _warm(lanes)
        _, lane_cadences = _drive(lanes, reqs)
        lanes_snap = lanes.stats()

    from ray_lightning_accelerators_tpu.telemetry import (
        probe_snapshot_record)
    telemetry_rec = probe_snapshot_record("prefix_affinity",
                                          serve=lanes_snap)

    ll_p99, af_p99 = _p99(ll_ttfts), _p99(af_ttfts)
    ratio = ll_p99 / af_p99 if af_p99 else 0.0
    return compile_rec, telemetry_rec, {
        "metric": "prefix_affinity_ttft_ratio",
        "value": round(ratio, 4),
        "unit": "ratio",
        "vs_baseline": round(ratio / TTFT_RATIO_BAR, 4),
        "requests": len(reqs),
        "families": N_FAMILIES,
        "prefix_len": PREFIX_LEN,
        "warmup_waves": WARMUP_WAVES,
        "p99_ttft_ms_least_loaded": round(1e3 * ll_p99, 3),
        "p99_ttft_ms_affinity": round(1e3 * af_p99, 3),
        "prefix_hit_rate": round(hit_rate, 4),
        "prefix_route_hits": int(hits),
        "prefix_route_misses": int(misses),
        "hit_rate_bar": HIT_RATE_BAR,
        "decode_cadence_p99_ms": round(1e3 * _p99(lane_cadences), 3),
        "kv_handoffs": int(lanes_snap["kv_handoffs"]),
        "kv_handoff_bytes": int(lanes_snap["kv_handoff_bytes"]),
        "lanes_completed": int(lanes_snap["completed"]),
        "lanes_failed": int(lanes_snap["failed"]),
        "affinity_accounting_exact": bool(
            aff_snap["completed"] + aff_snap["failed"]
            + aff_snap["cancelled"] == aff_snap["submitted"]),
        "lanes_accounting_exact": bool(
            lanes_snap["completed"] + lanes_snap["failed"]
            + lanes_snap["cancelled"] == lanes_snap["submitted"]),
    }


def main() -> None:
    compile_rec = telemetry_rec = None
    try:
        compile_rec, telemetry_rec, rec = probe(
            int(sys.argv[sys.argv.index("--seed") + 1])
            if "--seed" in sys.argv else 0)
    except Exception as e:
        rec = {"metric": "prefix_affinity_ttft_ratio",
               "value": 0, "unit": "ratio", "vs_baseline": 0.0,
               "error": f"{type(e).__name__}: {e}"[:400]}
    if compile_rec is not None:
        print(json.dumps(compile_rec), flush=True)
    if telemetry_rec is not None:
        print(json.dumps(telemetry_rec), flush=True)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
