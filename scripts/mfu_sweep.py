"""MFU sweep harness for the GPT flagship bench config.

Runs one bench-shaped GPT training measurement per requested variant and
prints a JSON line each, so the BASELINE.md tuned ladder can be
re-measured (and extended) on hardware in one command:

    python scripts/mfu_sweep.py tuned remat-dots remat-dots-nbd b20

Variants (all deltas are against the tuned r4 config: flash 1024x1024,
loss_chunk 2048, 24-step epochs, per-chip batch 16, seq 1024):

- ``r3``            the round-3 conservative config (512 blocks, chunk
                    4096, 12-step epochs) -- the cross-round anchor
- ``tuned``         the r4 tuned config exactly
- ``remat-dots``    + per-layer jax.checkpoint, dots_saveable: keeps
                    matmul outputs, recomputes elementwise (norm/rope/
                    gelu) in the backward -- trades recompute VPU time
                    for the residual-stacking HBM traffic the XPlane
                    trace prices at ~30 ms/step (BASELINE.md)
- ``remat-dots-nbd``+ dots_with_no_batch_dims_saveable (keeps only
                    batch-free dots; more recompute, less traffic)
- ``b20`` / ``b24`` per-chip batch 20 / 24 (b24 OOMed by 0.85 GB on the
                    no-remat config; remat variants may fit -- a bigger
                    batch amortizes fixed per-step costs)
- ``chunk1024`` / ``chunk4096``  loss-chunk pipeline re-check

Each variant is measured through the same public-API fit + epoch-clock
discipline as bench.py (epoch 1 absorbs compile; scalar-readback sync).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

VARIANTS = {
    # CPU-runnable plumbing check (tiny model; MFU meaningless)
    "smoke": dict(loss_chunk=256, flash_block=128, steps_per_epoch=2,
                  tiny=True),
    "smoke-remat": dict(loss_chunk=256, flash_block=128,
                        steps_per_epoch=2, tiny=True, remat=True,
                        remat_policy="dots"),
    "r3": dict(loss_chunk=4096, flash_block=512, steps_per_epoch=12),
    "tuned": dict(loss_chunk=2048, flash_block=1024, steps_per_epoch=24),
    "remat-dots": dict(loss_chunk=2048, flash_block=1024,
                       steps_per_epoch=24, remat=True,
                       remat_policy="dots"),
    "remat-dots-nbd": dict(loss_chunk=2048, flash_block=1024,
                           steps_per_epoch=24, remat=True,
                           remat_policy="dots_with_no_batch_dims"),
    "b20": dict(loss_chunk=2048, flash_block=1024, steps_per_epoch=24,
                per_chip_batch=20),
    "b24": dict(loss_chunk=2048, flash_block=1024, steps_per_epoch=24,
                per_chip_batch=24),
    "b20-remat-dots": dict(loss_chunk=2048, flash_block=1024,
                           steps_per_epoch=24, per_chip_batch=20,
                           remat=True, remat_policy="dots"),
    "chunk1024": dict(loss_chunk=1024, flash_block=1024,
                      steps_per_epoch=24),
    "chunk4096": dict(loss_chunk=4096, flash_block=1024,
                      steps_per_epoch=24),
}


def run_variant(name: str, spec: dict) -> tuple:
    # the measurement itself lives in bench.py so every sweep number is
    # produced under exactly the timed-window/sync discipline the
    # driver's bench uses (bench-honesty: one shared implementation)
    from bench import _bench_gpt
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg

    c0 = cg.compile_count()
    rec = _bench_gpt(loss_chunk=spec["loss_chunk"],
                     flash_block=spec["flash_block"],
                     steps_per_epoch=spec["steps_per_epoch"],
                     per_chip_batch=spec.get("per_chip_batch", 16),
                     remat=spec.get("remat", False),
                     remat_policy=spec.get("remat_policy", "nothing"),
                     tiny=spec.get("tiny", False))
    # compile-count alongside the metric (bench-honesty tie-in): the
    # train step must compile a FIXED program count per variant — a
    # growing number across bench rounds is a retrace regression even
    # when step_ms still looks plausible
    compile_rec = dict(cg.compile_count_record(f"mfu_sweep:{name}"),
                       variant_new_compiles=cg.compile_count() - c0)
    return ({"variant": name, "step_ms": rec["step_ms"],
             "mfu": rec["mfu"],
             "tokens_per_sec_per_chip": rec["value"], **spec},
            compile_rec)


def main() -> None:
    names = sys.argv[1:] or ["tuned", "remat-dots"]
    for name in names:
        try:
            metric_rec, compile_rec = run_variant(name, VARIANTS[name])
            print(json.dumps(metric_rec), flush=True)
            print(json.dumps(compile_rec), flush=True)
        except Exception as e:
            print(json.dumps({"variant": name, "error":
                              f"{type(e).__name__}: {e}"[:500]}),
                  flush=True)


if __name__ == "__main__":
    main()
