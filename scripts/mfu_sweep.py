"""MFU sweep harness for the GPT flagship bench config.

Runs one bench-shaped GPT training measurement per requested variant and
prints a JSON line each, so the BASELINE.md tuned ladder can be
re-measured (and extended) on hardware in one command:

    python scripts/mfu_sweep.py tuned remat-dots remat-dots-nbd b20

Variants (all deltas are against the tuned r4 config: flash 1024x1024,
loss_chunk 2048, 24-step epochs, per-chip batch 16, seq 1024):

- ``r3``            the round-3 conservative config (512 blocks, chunk
                    4096, 12-step epochs) -- the cross-round anchor
- ``tuned``         the r4 tuned config exactly
- ``remat-dots``    + per-layer jax.checkpoint, dots_saveable: keeps
                    matmul outputs, recomputes elementwise (norm/rope/
                    gelu) in the backward -- trades recompute VPU time
                    for the residual-stacking HBM traffic the XPlane
                    trace prices at ~30 ms/step (BASELINE.md)
- ``remat-dots-nbd``+ dots_with_no_batch_dims_saveable (keeps only
                    batch-free dots; more recompute, less traffic)
- ``b20`` / ``b24`` per-chip batch 20 / 24 (b24 OOMed by 0.85 GB on the
                    no-remat config; remat variants may fit -- a bigger
                    batch amortizes fixed per-step costs)
- ``chunk1024`` / ``chunk4096``  loss-chunk pipeline re-check

Each variant is measured through the same public-API fit + epoch-clock
discipline as bench.py (epoch 1 absorbs compile; scalar-readback sync).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

VARIANTS = {
    # CPU-runnable plumbing check (tiny model; MFU meaningless)
    "smoke": dict(loss_chunk=256, flash_block=128, steps_per_epoch=2,
                  tiny=True),
    "smoke-remat": dict(loss_chunk=256, flash_block=128,
                        steps_per_epoch=2, tiny=True, remat=True,
                        remat_policy="dots"),
    "r3": dict(loss_chunk=4096, flash_block=512, steps_per_epoch=12),
    "tuned": dict(loss_chunk=2048, flash_block=1024, steps_per_epoch=24),
    "remat-dots": dict(loss_chunk=2048, flash_block=1024,
                       steps_per_epoch=24, remat=True,
                       remat_policy="dots"),
    "remat-dots-nbd": dict(loss_chunk=2048, flash_block=1024,
                           steps_per_epoch=24, remat=True,
                           remat_policy="dots_with_no_batch_dims"),
    "b20": dict(loss_chunk=2048, flash_block=1024, steps_per_epoch=24,
                per_chip_batch=20),
    "b24": dict(loss_chunk=2048, flash_block=1024, steps_per_epoch=24,
                per_chip_batch=24),
    "b20-remat-dots": dict(loss_chunk=2048, flash_block=1024,
                           steps_per_epoch=24, per_chip_batch=20,
                           remat=True, remat_policy="dots"),
    "chunk1024": dict(loss_chunk=1024, flash_block=1024,
                      steps_per_epoch=24),
    "chunk4096": dict(loss_chunk=4096, flash_block=1024,
                      steps_per_epoch=24),
}


def run_variant(name: str, spec: dict) -> dict:
    import jax
    import numpy as np

    from ray_lightning_accelerators_tpu import (Callback, DataLoader,
                                                RayTPUAccelerator, Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    from ray_lightning_accelerators_tpu.utils import profiler as prof
    from bench import _EpochClock

    n_devices = jax.device_count()
    tiny = spec.get("tiny", False)
    seq = 256 if tiny else 1024
    per_chip_batch = spec.get("per_chip_batch", 2 if tiny else 16)
    steps_per_epoch = spec["steps_per_epoch"]
    batch = per_chip_batch * n_devices
    cfg = TransformerConfig(vocab_size=512 if tiny else 50304,
                            d_model=128 if tiny else 768,
                            n_heads=4 if tiny else 12,
                            d_ff=512 if tiny else 3072,
                            n_layers=2 if tiny else 12, max_seq_len=seq,
                            fused_loss=True,
                            loss_chunk_rows=spec["loss_chunk"],
                            flash_block_q=spec["flash_block"],
                            flash_block_k=spec["flash_block"],
                            remat=spec.get("remat", False),
                            remat_policy=spec.get("remat_policy",
                                                  "nothing"))
    model = GPT(cfg, lr=3e-4)
    tokens = np.asarray(
        np.random.default_rng(0).integers(
            0, cfg.vocab_size, size=(batch * steps_per_epoch, seq)),
        dtype=np.int32)
    loader = DataLoader(ArrayDataset(tokens), batch_size=batch,
                        shuffle=False)
    clock = _EpochClock(Callback)
    epochs = 3
    trainer = Trainer(max_epochs=epochs, accelerator=RayTPUAccelerator(),
                      precision="bf16", enable_checkpointing=False,
                      log_every_n_steps=10 ** 9, seed=0,
                      callbacks=[clock.cb],
                      default_root_dir="/tmp/rla_tpu_sweep")
    trainer.fit(model, loader)
    dt = clock.steady_state_seconds()
    timed_steps = steps_per_epoch * (epochs - 1)
    step_time = dt / timed_steps
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(model.params))
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * cfg.d_model * seq
    flops_per_step = flops_per_token * batch * seq
    mfu = prof.mfu(flops_per_step / n_devices, step_time)
    return {"variant": name, "step_ms": round(step_time * 1e3, 1),
            "mfu": round(mfu, 4),
            "tokens_per_sec_per_chip":
                round(batch * seq / step_time / n_devices, 1),
            "per_chip_batch": per_chip_batch, **{
                k: v for k, v in spec.items() if k != "per_chip_batch"}}


def main() -> None:
    names = sys.argv[1:] or ["tuned", "remat-dots"]
    for name in names:
        try:
            print(json.dumps(run_variant(name, VARIANTS[name])),
                  flush=True)
        except Exception as e:
            print(json.dumps({"variant": name, "error":
                              f"{type(e).__name__}: {e}"[:500]}),
                  flush=True)


if __name__ == "__main__":
    main()
