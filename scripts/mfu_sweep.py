"""MFU sweep harness for the GPT flagship bench config.

Runs one bench-shaped GPT training measurement per requested variant and
prints a JSON line each, so the BASELINE.md tuned ladder can be
re-measured (and extended) on hardware in one command:

    python scripts/mfu_sweep.py tuned remat-dots gather-scan

Variants (all deltas are against the tuned r4 config: flash 1024x1024,
loss_chunk 2048, 24-step epochs, per-chip batch 16, seq 1024):

- ``r3``            the round-3 conservative config (512 blocks, chunk
                    4096, 12-step epochs) -- the cross-round anchor
- ``tuned``         the r4 tuned config exactly
- ``remat-dots``    + per-layer jax.checkpoint, dots_saveable: keeps
                    matmul outputs, recomputes elementwise (norm/rope/
                    gelu) in the backward -- trades recompute VPU time
                    for the residual-stacking HBM traffic the XPlane
                    trace prices at ~30 ms/step (BASELINE.md)
- ``remat-dots-nbd``+ dots_with_no_batch_dims_saveable (keeps only
                    batch-free dots; more recompute, less traffic)
- ``b20`` / ``b24`` per-chip batch 20 / 24 (b24 OOMed by 0.85 GB on the
                    no-remat config; remat variants may fit -- a bigger
                    batch amortizes fixed per-step costs)
- ``chunk1024`` / ``chunk4096``  loss-chunk pipeline re-check

Overlap-aware FSDP (compressed-FSDP step, parallel/collectives.py):

- ``gather-tree`` / ``gather-scan``   fsdp + int8 reduce-scatter with
                    the whole-tree up-front bf16 param gather vs the
                    layer-wise gather INSIDE the transformer scan
                    (overlaps layer k+1's gather with layer k's
                    matmuls; backward re-gathers under remat).  Both
                    run under remat so the schedules are compared on
                    the composition the scan gather exists for.
- ``gather-*-smoke``  the same A/B at the CPU-mesh-measurable ``small``
                    size -- what scripts/mfu_overlap_probe.py runs on
                    the forced 8-device host mesh.
- ``int8-matmul``   tuned config + int8 forward MLP matmuls with
                    straight-through gradients (ops/quant.py)
- ``autotuned`` / ``autotuned-smoke``  the closed loop: the in-repo TPE
                    searcher (tune.autotune_step) drives remat_policy x
                    flash blocks x gather_mode against measured step
                    time, then the record reports best-vs-default.

Each variant is measured through the same public-API fit + epoch-clock
discipline as bench.py (epoch 1 absorbs compile; scalar-readback sync),
and every record carries ``measured_window_compiles`` (0 = no retrace
landed inside the timed window).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_FSDP_SWEEP = dict(loss_chunk=2048, flash_block=1024, steps_per_epoch=24,
                   use_fsdp=True, grad_compression="int8", remat=True,
                   remat_policy="nothing")
# CPU-mesh-measurable size: 4 epochs x 6 steps keeps one variant under
# ~a minute on an 8-device host mesh while the steady-state window still
# spans 18 steps
_FSDP_SMOKE = dict(loss_chunk=256, flash_block=128, steps_per_epoch=6,
                   epochs=4, small=True, precision="f32", use_fsdp=True,
                   grad_compression="int8", remat=True,
                   remat_policy="nothing")

VARIANTS = {
    # CPU-runnable plumbing check (tiny model; MFU meaningless)
    "smoke": dict(loss_chunk=256, flash_block=128, steps_per_epoch=2,
                  tiny=True),
    "smoke-remat": dict(loss_chunk=256, flash_block=128,
                        steps_per_epoch=2, tiny=True, remat=True,
                        remat_policy="dots"),
    "r3": dict(loss_chunk=4096, flash_block=512, steps_per_epoch=12),
    "tuned": dict(loss_chunk=2048, flash_block=1024, steps_per_epoch=24),
    "remat-dots": dict(loss_chunk=2048, flash_block=1024,
                       steps_per_epoch=24, remat=True,
                       remat_policy="dots"),
    "remat-dots-nbd": dict(loss_chunk=2048, flash_block=1024,
                           steps_per_epoch=24, remat=True,
                           remat_policy="dots_with_no_batch_dims"),
    "b20": dict(loss_chunk=2048, flash_block=1024, steps_per_epoch=24,
                per_chip_batch=20),
    "b24": dict(loss_chunk=2048, flash_block=1024, steps_per_epoch=24,
                per_chip_batch=24),
    "b20-remat-dots": dict(loss_chunk=2048, flash_block=1024,
                           steps_per_epoch=24, per_chip_batch=20,
                           remat=True, remat_policy="dots"),
    "chunk1024": dict(loss_chunk=1024, flash_block=1024,
                      steps_per_epoch=24),
    "chunk4096": dict(loss_chunk=4096, flash_block=1024,
                      steps_per_epoch=24),
    # overlap-aware FSDP A/B (compressed-FSDP step)
    "gather-tree": dict(_FSDP_SWEEP, gather_mode="tree"),
    "gather-scan": dict(_FSDP_SWEEP, gather_mode="scan"),
    "gather-tree-smoke": dict(_FSDP_SMOKE, gather_mode="tree"),
    "gather-scan-smoke": dict(_FSDP_SMOKE, gather_mode="scan"),
    # int8 forward matmuls in the train step (MLP projections)
    "int8-matmul": dict(loss_chunk=2048, flash_block=1024,
                        steps_per_epoch=24, int8_matmul=True),
    "int8-matmul-smoke": dict(loss_chunk=256, flash_block=128,
                              steps_per_epoch=2, tiny=True,
                              int8_matmul=True),
    # closed-loop step autotuning (special-cased in run_variant)
    "autotuned": dict(autotune=True, smoke=False),
    "autotuned-smoke": dict(autotune=True, smoke=True),
}


def _autotune_measure(smoke: bool):
    """measure(config) -> step seconds for tune.autotune_step, produced
    by the same _bench_gpt timed-window discipline as every other sweep
    number (reduced budget: trials are search probes, not headlines)."""
    from bench import _bench_gpt

    def measure(config):
        remat_policy = config.get("remat_policy", "none")
        base = (dict(loss_chunk=256, flash_block=128, steps_per_epoch=4,
                     epochs=3, small=True, precision="f32",
                     use_fsdp=True, grad_compression="int8")
                if smoke else
                dict(loss_chunk=2048, flash_block=1024,
                     steps_per_epoch=8, epochs=3, use_fsdp=True,
                     grad_compression="int8"))
        base["flash_block"] = int(config.get("flash_block_q",
                                             base["flash_block"]))
        rec = _bench_gpt(**dict(
            base,
            remat=remat_policy != "none",
            remat_policy=(remat_policy if remat_policy != "none"
                          else "nothing"),
            gather_mode=config.get("gather_mode", "tree")))
        return rec["step_ms"] / 1e3

    return measure


def _run_autotuned(name: str, smoke: bool) -> tuple:
    from ray_lightning_accelerators_tpu import tune
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg

    space = {
        "remat_policy": tune.choice(["none", "nothing", "dots"]),
        "flash_block_q": tune.choice([64, 128] if smoke
                                     else [256, 512, 1024]),
        "gather_mode": tune.choice(["tree", "scan"]),
    }
    default = {"remat_policy": "none",
               "flash_block_q": 128 if smoke else 1024,
               "gather_mode": "tree"}
    c0 = cg.compile_count()
    result = tune.autotune_step(_autotune_measure(smoke), space=space,
                                default_config=default,
                                n_trials=6 if smoke else 10)
    compile_rec = dict(cg.compile_count_record(f"mfu_sweep:{name}"),
                       variant_new_compiles=cg.compile_count() - c0)

    def ms(v):
        # failed measurements are inf; keep the record strict JSON
        # (json.dumps would emit the non-standard Infinity token)
        import math
        return round(v * 1e3, 1) if math.isfinite(v) else None

    return ({"variant": name,
             "step_ms": ms(result["best_step_time_s"]),
             "default_step_ms": ms(result["default_step_time_s"]),
             "speedup_vs_default": (
                 None if result["speedup_vs_default"] is None
                 else round(result["speedup_vs_default"], 3)),
             "best_config": result["best_config"],
             "n_trials": result["n_trials"]},
            compile_rec)


def run_variant(name: str, spec: dict) -> tuple:
    # the measurement itself lives in bench.py so every sweep number is
    # produced under exactly the timed-window/sync discipline the
    # driver's bench uses (bench-honesty: one shared implementation)
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg

    if spec.get("autotune"):
        return _run_autotuned(name, spec.get("smoke", False))

    from bench import _bench_gpt

    c0 = cg.compile_count()
    rec = _bench_gpt(**spec)
    # compile-count alongside the metric (bench-honesty tie-in): the
    # train step must compile a FIXED program count per variant — a
    # growing number across bench rounds is a retrace regression even
    # when step_ms still looks plausible.  measured_window_compiles in
    # the metric record pins the stronger claim: ZERO of them landed
    # inside the timed window.
    compile_rec = dict(cg.compile_count_record(f"mfu_sweep:{name}"),
                       variant_new_compiles=cg.compile_count() - c0)
    out = {"variant": name, "step_ms": rec["step_ms"], "mfu": rec["mfu"],
           "tokens_per_sec_per_chip": rec["value"],
           "measured_window_compiles": rec["measured_window_compiles"],
           **spec}
    for k in ("gather_mode", "exposed_bytes_per_step",
              "hidden_bytes_per_step"):
        if k in rec:
            out[k] = rec[k]
    return out, compile_rec


def main() -> None:
    names = sys.argv[1:] or ["tuned", "remat-dots"]
    for name in names:
        try:
            metric_rec, compile_rec = run_variant(name, VARIANTS[name])
            print(json.dumps(metric_rec), flush=True)
            print(json.dumps(compile_rec), flush=True)
        except Exception as e:
            print(json.dumps({"variant": name, "error":
                              f"{type(e).__name__}: {e}"[:500]}),
                  flush=True)


if __name__ == "__main__":
    main()
