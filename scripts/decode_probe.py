"""Decode int8 kernel microbench: isolates WHERE the int8 speedup lives
(or dies) on the real chip, one JSON line per probe.

bench.py's decode `int8_ratio` measures the whole generate loop; when it
lands near 1.0 this script says why, by timing the two layers of the
stack separately on the exact bench decode shapes:

1. ``kernel``  — `ops/quant.int8_matmul` vs the XLA dequant dot vs a
   plain bf16 dot on one [16, 768] @ [768, 768] decode matmul (the
   qkv/out shape) and the [16, 768] @ [768, 50304] unembed: pure
   kernel-vs-XLA, no scan.
2. ``scanned`` — the same matmuls inside a `lax.scan` over a 12-layer
   stacked weight tree (decode's actual access pattern: a stream of
   weight matrices through one small activation block).  Its
   ``kernel_int8_gbps`` / ``bf16_gbps`` fields ARE the per-dtype
   effective stream rates on this pattern (BASELINE.md measured the
   bf16 side at ~46 GB/s, latency-bound) -- if the int8 rate matches
   bf16's BYTE rate, the kernel pipeline is the bottleneck, not HBM.

Sync discipline per bench-honesty rules: chain reps, one scalar
readback at the end; per-call sync would bill tunnel round-trips to
bandwidth.
"""

import functools
import json
import sys
import time

import os

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _timed(fn, *args, reps=20):
    import jax
    import numpy as np

    out = fn(*args)
    jax.tree.leaves(out)[0].block_until_ready()
    np.asarray(jax.tree.leaves(out)[0])  # honest sync: host readback
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        acc = None
        for _ in range(reps):
            acc = fn(*args)
        np.asarray(jax.tree.leaves(acc)[0])
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def probe_kernel(m, k, n, interpret=False) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_lightning_accelerators_tpu.ops import quant

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(m, k)), jnp.bfloat16)
    wq = jnp.asarray(rng.integers(-127, 128, size=(k, n)), jnp.int8)
    scale = jnp.asarray(rng.uniform(0.01, 0.1, size=(n,)), jnp.float32)

    kern = jax.jit(functools.partial(quant.int8_matmul,
                                     interpret=interpret))
    deq = jax.jit(lambda x, wq, s:
                  x @ (wq.astype(jnp.bfloat16) * s[None, :].astype(
                      jnp.bfloat16)))
    bf16_w = (wq.astype(jnp.bfloat16) * scale[None, :].astype(
        jnp.bfloat16))
    plain = jax.jit(lambda x, w: x @ w)

    t_kernel = _timed(kern, x, wq, scale)
    t_dequant = _timed(deq, x, wq, scale)
    t_bf16 = _timed(plain, x, bf16_w)
    return {"probe": "kernel", "shape": [m, k, n],
            "kernel_us": round(t_kernel * 1e6, 1),
            "xla_dequant_us": round(t_dequant * 1e6, 1),
            "bf16_us": round(t_bf16 * 1e6, 1),
            "kernel_vs_bf16": round(t_bf16 / t_kernel, 3),
            "int8_bytes_over_bf16": 0.5}


def probe_scanned(m=16, d=768, layers=12, interpret=False) -> dict:
    """Decode's real pattern: scan one activation block through a
    stacked weight tree, q8-kernel vs XLA dequant vs plain bf16."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_lightning_accelerators_tpu.ops import quant

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(m, d)), jnp.bfloat16)
    wq_stack = jnp.asarray(rng.integers(-127, 128, size=(layers, d, d)),
                           jnp.int8)
    sc_stack = jnp.asarray(rng.uniform(0.01, 0.1, size=(layers, d)),
                           jnp.float32)
    wbf_stack = (wq_stack.astype(jnp.bfloat16)
                 * sc_stack[:, None, :].astype(jnp.bfloat16))

    @jax.jit
    def scan_kernel(x, wq, sc):
        def body(h, ws):
            w, s = ws
            return quant.int8_matmul(h, w, s,
                                     interpret=interpret), ()
        out, _ = jax.lax.scan(body, x, (wq, sc))
        return out.astype(jnp.float32).sum()

    @jax.jit
    def scan_dequant(x, wq, sc):
        def body(h, ws):
            w, s = ws
            wf = w.astype(jnp.bfloat16) * s[None, :].astype(jnp.bfloat16)
            return (h @ wf).astype(jnp.bfloat16), ()
        out, _ = jax.lax.scan(body, x, (wq, sc))
        return out.astype(jnp.float32).sum()

    @jax.jit
    def scan_bf16(x, w):
        def body(h, wl):
            return (h @ wl).astype(jnp.bfloat16), ()
        out, _ = jax.lax.scan(body, x, w)
        return out.astype(jnp.float32).sum()

    t_kernel = _timed(scan_kernel, x, wq_stack, sc_stack)
    t_dequant = _timed(scan_dequant, x, wq_stack, sc_stack)
    t_bf16 = _timed(scan_bf16, x, wbf_stack)
    int8_bytes = wq_stack.nbytes
    return {"probe": "scanned", "layers": layers, "d": d, "m": m,
            "kernel_ms": round(t_kernel * 1e3, 2),
            "xla_dequant_ms": round(t_dequant * 1e3, 2),
            "bf16_ms": round(t_bf16 * 1e3, 2),
            "kernel_vs_bf16": round(t_bf16 / t_kernel, 3),
            "kernel_int8_gbps": round(int8_bytes / t_kernel / 1e9, 1),
            "bf16_gbps": round(2 * int8_bytes / t_bf16 / 1e9, 1)}


def main() -> None:
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg

    cg.install()
    interpret = "--interpret" in sys.argv
    for fn in (lambda: probe_kernel(16, 768, 768, interpret),
               lambda: probe_kernel(16, 768, 50304, interpret),
               lambda: probe_scanned(interpret=interpret)):
        try:
            c0 = cg.compile_count()
            rec = fn()
            print(json.dumps(rec), flush=True)
            print(json.dumps(dict(
                cg.compile_count_record("decode"),
                probe_new_compiles=cg.compile_count() - c0)), flush=True)
        except Exception as e:
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:400]}),
                  flush=True)


if __name__ == "__main__":
    main()
