"""Input-pipeline microbench on a forced-host-platform CPU mesh.

Self-contained (the gradexchange_probe.py pattern): forces
``JAX_PLATFORMS=cpu`` with 8 virtual devices BEFORE importing jax, so it
produces a real number on any machine — including one whose accelerator
backend is wedged, which is exactly when bench.py falls back to it.

What it measures: steps/s through the full Trainer fit loop on a
synthetic INPUT-BOUND loader (a collate_fn that sleeps a configurable
per-batch host latency — the stand-in for decode/augment/tokenize cost;
a custom collate also keeps the device cache and the native engine out
of the way, so this is the honest host-fed hot loop), with
``prefetch_batches=0`` (fully synchronous: collate -> H2D -> dispatch)
vs ``prefetch_batches=2`` (data/prefetch.py overlaps collate + H2D with
compute).  The host latency is CALIBRATED to the measured compute step
time of this machine — overlap hides ``min(host, compute)``, so pinning
host ≈ compute makes the ~2x ideal portable instead of
machine-dependent.  Env overrides: ``RLA_TPU_INPUT_LATENCY_MS`` (skip
calibration), ``RLA_TPU_INPUT_STEPS`` (steps per epoch, default 12).

Emits one bench.py-shaped JSON line on stdout.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEPS_PER_EPOCH = int(os.environ.get("RLA_TPU_INPUT_STEPS", "12"))
EPOCHS = 3  # epoch 1 absorbs compile; epochs 2..N are the timed window


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_lightning_accelerators_tpu import (Callback, DataLoader,
                                                RayTPUAccelerator, Trainer,
                                                TpuModule)
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.utils.profiler import Profiler

    cg.install()  # count XLA compiles across the whole probe
    n_devices = jax.device_count()
    batch = 64 * n_devices
    dim, hidden, classes = 256, 1024, 10

    class _MLP(TpuModule):
        def init_params(self, rng):
            k1, k2, k3 = jax.random.split(rng, 3)
            s = 0.02
            return {"w1": jax.random.normal(k1, (dim, hidden)) * s,
                    "w2": jax.random.normal(k2, (hidden, hidden)) * s,
                    "w3": jax.random.normal(k3, (hidden, classes)) * s}

        def training_step(self, params, batch_, rng):
            x, y = batch_
            h = jnp.tanh(x @ params["w1"])
            h = jnp.tanh(h @ params["w2"])
            logits = h @ params["w3"]
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
            return loss, {"train_loss": loss}

        def configure_optimizers(self):
            return optax.sgd(0.01)

    # PRE-BATCHED samples: each dataset element is one whole (batch, dim)
    # step batch and the collate just sleeps and unwraps it.  The host
    # latency is then pure sleep (GIL-free, needs no CPU), so on a
    # forced-CPU mesh — where a real collate would contend with XLA's
    # compute threads for the same cores and inflate under overlap, a
    # contention a real accelerator's host loop doesn't have — the
    # measured ratio isolates what the bench claims: overlap of host
    # latency with compute.
    rng = np.random.default_rng(0)
    x = rng.standard_normal(
        (STEPS_PER_EPOCH, batch, dim)).astype(np.float32)
    y = rng.integers(0, classes,
                     size=(STEPS_PER_EPOCH, batch)).astype(np.int32)

    class _Clock(Callback):
        """Device-synced wall time at epoch boundaries (bench.py's
        _EpochClock discipline: epoch 1 absorbs compile)."""

        def __init__(self):
            self.starts, self.ends = [], []

        def _sync(self, trainer):
            if trainer._state is not None:
                int(np.asarray(jax.device_get(trainer._state.step)))
            return time.perf_counter()

        def on_train_epoch_start(self, trainer, module):
            self.starts.append(self._sync(trainer))

        def on_train_epoch_end(self, trainer, module):
            self.ends.append(self._sync(trainer))

    def run(latency_s: float, prefetch: int, profiler=None) -> float:
        """One fit; returns steady-state steps/s."""

        def slow_collate(samples):
            if latency_s:
                time.sleep(latency_s)
            return samples[0]  # pre-batched: one element IS the batch

        loader = DataLoader(ArrayDataset(x, y), batch_size=1,
                            shuffle=False, collate_fn=slow_collate)
        clock = _Clock()
        trainer = Trainer(max_epochs=EPOCHS,
                          accelerator=RayTPUAccelerator(),
                          precision="f32", enable_checkpointing=False,
                          log_every_n_steps=10 ** 9, seed=0,
                          callbacks=[clock], profiler=profiler,
                          cache_dataset_on_device=False,
                          prefetch_batches=prefetch,
                          default_root_dir="/tmp/rla_tpu_bench_input")
        trainer.fit(_MLP(), loader)
        dt = clock.ends[-1] - clock.starts[1]
        return STEPS_PER_EPOCH * (EPOCHS - 1) / dt

    latency_ms = os.environ.get("RLA_TPU_INPUT_LATENCY_MS")
    if latency_ms is not None:
        latency_s = float(latency_ms) / 1e3
        calibrated_ms = None
    else:
        # calibrate: host latency = this machine's compute step time, so
        # overlap has an honest ~2x to win.  Calibration runs with a
        # fixed sleep INTERLEAVED (and subtracts it) rather than
        # back-to-back: a saturated all-core burn throttles/queues
        # differently than the sleep-interleaved regime the timed runs
        # actually operate in, and overestimates compute by up to 2x
        cal_sleep_ms = 60.0
        cal_sps = run(cal_sleep_ms / 1e3, 0)
        calibrated_ms = max(1e3 / cal_sps - cal_sleep_ms, 1.0)
        # 1.4x: host strictly dominating compute keeps the overlapped
        # loop host-bound, so prefetch=2 throughput is the (exact) sleep
        # rate and the measured ratio survives a +-30% compute swing
        # between calibration and the timed runs
        latency_s = min(max(1.4 * calibrated_ms, 15.0), 200.0) / 1e3

    sps0 = run(latency_s, 0)
    prof = Profiler()
    sps2 = run(latency_s, 2, profiler=prof)
    ratio = sps2 / sps0
    starved = prof.counters().get("prefetch_starved_steps", 0)
    h2d_wait = prof.summary().get("h2d_wait", {})
    record = {
        "metric": "input_pipeline_prefetch_speedup",
        "value": round(ratio, 3),
        "unit": "x",
        "steps_per_sec_prefetch0": round(sps0, 2),
        "steps_per_sec_prefetch2": round(sps2, 2),
        "host_latency_ms": round(latency_s * 1e3, 2),
        "calibrated_step_ms": (round(calibrated_ms, 2)
                               if calibrated_ms is not None else None),
        "starved_steps_prefetch2": int(starved),
        "h2d_wait_mean_ms": round(h2d_wait.get("mean_s", 0.0) * 1e3, 3),
        "devices": n_devices,
        "platform": "cpu-forced-host",
        "note": "synthetic input-bound loader (collate sleeps "
                "host_latency per pre-batched element); overlap hides "
                "min(host, compute), latency calibrated ~= compute",
        # the driver bar: >= 1.5x steps/s from prefetch on this loader
        "vs_baseline": round(ratio / 1.5, 3),
    }
    # both timed runs share shapes: compile totals drifting up across
    # bench rounds means the fit loop started retracing.  Printed BEFORE
    # the metric record: bench.py takes the LAST JSON line of probe
    # stdout as the bench result.
    print(json.dumps(cg.compile_count_record("input_pipeline")),
          flush=True)
    # unified telemetry snapshot (telemetry/registry.py): the prefetch
    # run's profiler (h2d_wait span, starvation counter, depth gauge) +
    # recorder events + compile count in one registry export — value-
    # less and kind-tagged, so the metric line below stays the result
    from ray_lightning_accelerators_tpu.telemetry import (
        probe_snapshot_record)
    print(json.dumps(probe_snapshot_record("input_pipeline",
                                           profiler=prof)), flush=True)
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
