"""Serve engine probe: one JSON line of throughput/latency truth.

Bench-honesty rules (the decode_probe.py discipline, applied to
serving): the numbers come from the engine's own metrics — reservoir
percentiles plus an EXACT max — over real requests driven through the
real admission/decode path, with compile/warmup excluded by a warmup
request per prompt bucket before the measured window.  Failures emit an
``{"error": ...}`` line instead of a traceback so a wedged backend still
produces a parseable record.

Usage::

    python scripts/serve_probe.py [--requests N] [--slots S] [--seed K]
        [--workload uniform|mixed] [--shared-prefix L]

``--workload mixed`` swaps the uniform 4..31-token prompts for a
production-shaped LOGNORMAL prompt-length distribution (most prompts
short, a heavy tail near the budget), reporting the paged pool's
measured cache-waste ratio next to TTFT/p99.  ``--shared-prefix L``
additionally prepends one shared L-token system prompt to every request
— the prefix-reuse mode: full blocks of the shared prefix are mapped
copy-on-write from the LRU prefix index instead of re-prefilled, and
the record carries the hit counters.

Output (compile-count line, telemetry line, metric line LAST)::

    {"probe": "serve", "kind": "compile_count",
     "total_backend_compiles": ..., "measured_window_compiles": 0}
    {"probe": "serve", "kind": "telemetry", "snapshot": {...}, ...}
    {"probe": "serve", "requests": ..., "max_slots": ...,
     "throughput_tok_s": ..., "ttft_p50_ms": ..., "ttft_p99_ms": ...,
     "token_p50_ms": ..., "token_p99_ms": ..., "token_max_ms": ...,
     "steps": ..., "steps_batch_gt1": ..., "max_batch": ...}

The ``kind="telemetry"`` line is the unified MetricsRegistry export
(telemetry/registry.py).  The serve metric record carries no ``value``
key, so it is printed last: a bench-style newest-line-fallback parser
(bench._last_metric_record) finds it by position, while the kind-tagged
records never displace it.

A nonzero ``measured_window_compiles`` means the engine retraced inside
the measured window — the 3-program invariant broke (see
analysis/compile_guard.py; tests/test_analysis.py asserts it too).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _arg(flag: str, default: int) -> int:
    if flag in sys.argv:
        return int(sys.argv[sys.argv.index(flag) + 1])
    return default


def _arg_str(flag: str, default: str) -> str:
    if flag in sys.argv:
        return sys.argv[sys.argv.index(flag) + 1]
    return default


def mixed_prompts(rng, n, vocab, max_len, shared=None):
    """Production-shaped prompt lengths: lognormal (most prompts short,
    a heavy tail toward the budget), clipped to ``max_len``; with
    ``shared`` every prompt is that system prompt + a short unique
    suffix (the prefix-reuse traffic shape)."""
    import numpy as np
    out = []
    for _ in range(n):
        if shared is not None:
            sfx = int(rng.integers(2, 17))
            p = np.concatenate([
                shared,
                rng.integers(0, vocab, size=(sfx,)).astype(np.int32)])
        else:
            ln = int(np.clip(np.round(rng.lognormal(np.log(16.0), 0.8)),
                             2, max_len))
            p = rng.integers(0, vocab, size=(ln,)).astype(np.int32)
        out.append(p)
    return out


def probe(n_requests: int, max_slots: int, seed: int,
          workload: str = "uniform", shared_prefix: int = 0) -> tuple:
    import jax
    import numpy as np

    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    from ray_lightning_accelerators_tpu.serve import ServeEngine

    cg.install()  # count XLA compiles from before the first trace

    cfg = TransformerConfig(vocab_size=512, d_model=128, n_heads=4,
                            d_ff=256, n_layers=4, max_seq_len=256)
    model = GPT(cfg)
    params = model.init_params(jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)
    shared = (rng.integers(0, cfg.vocab_size,
                           size=(shared_prefix,)).astype(np.int32)
              if shared_prefix else None)

    def prompts(n):
        if workload == "mixed":
            return mixed_prompts(rng, n, cfg.vocab_size, 64,
                                 shared=shared)
        return [rng.integers(0, cfg.vocab_size,
                             size=(int(rng.integers(4, 32)),)
                             ).astype(np.int32) for _ in range(n)]

    with ServeEngine(model, params, max_slots=max_slots,
                     queue_depth=max(64, 2 * n_requests)) as engine:
        # warmup: touch EVERY prompt-length bucket the measured window
        # can hit, plus the join/step programs, so the window bills
        # decode, not XLA compiles
        blk = engine.prompt_block
        top = 65 if workload == "mixed" else 33
        for s0 in range(blk, top, blk):
            p = rng.integers(0, cfg.vocab_size,
                             size=(max(1, s0 - 1),)).astype(np.int32)
            engine.submit(p, 2).result(timeout=600)
        if shared is not None:
            # shared-prefix mode additionally hits SUFFIX buckets: a
            # first request seeds the prefix index, a second (per suffix
            # bucket edge) compiles the hit path's chunk program
            for sfx in (2, 16):
                for _ in range(2):
                    p = np.concatenate([shared, rng.integers(
                        0, cfg.vocab_size, size=(sfx,)).astype(np.int32)])
                    engine.submit(p, 2).result(timeout=600)
        engine.metrics.reset()
        window_start = cg.compile_count()  # warmup done: window begins

        handles = [engine.submit(p, int(rng.integers(8, 33)))
                   for p in prompts(n_requests)]
        for h in handles:
            h.result(timeout=600)
        snap = engine.stats()
        compile_rec = cg.compile_count_record("serve", window_start)
        # unified telemetry snapshot (telemetry/registry.py): serve
        # counters/latency reservoirs + recorder event tallies + compile
        # count in ONE registry export.  kind-tagged and value-less, so
        # bench.py's newest-value-bearing-line parser still picks the
        # metric record (tests/test_bench_probe.py pins this).
        from ray_lightning_accelerators_tpu.telemetry import (
            probe_snapshot_record)
        telemetry_rec = probe_snapshot_record("serve",
                                              serve=engine.metrics)

    def ms(fam, key):
        row = snap.get(fam) or {}
        return round(1e3 * row.get(key, 0.0), 3)

    rec = {
        "probe": "serve", "requests": n_requests, "max_slots": max_slots,
        "workload": workload, "shared_prefix": shared_prefix,
        "tokens_generated": snap["tokens_generated"],
        "busy_s": round(snap["busy_s"], 3),
        "throughput_tok_s": round(snap["throughput_tok_s"], 1),
        "ttft_p50_ms": ms("ttft_s", "p50_s"),
        "ttft_p99_ms": ms("ttft_s", "p99_s"),
        "ttft_max_ms": ms("ttft_s", "max_s"),
        "token_p50_ms": ms("token_latency_s", "p50_s"),
        "token_p99_ms": ms("token_latency_s", "p99_s"),
        "token_max_ms": ms("token_latency_s", "max_s"),
        "steps": snap["steps"],
        "steps_batch_gt1": snap["steps_batch_gt1"],
        "max_batch": snap["max_batch"],
    }
    if "block_pool_total" in snap:  # paged engine: pool/prefix truth
        peak_c = snap["peak_concurrent"]
        peak_u = snap["peak_used_blocks"]
        per_slot = engine.max_blocks_per_slot
        rec.update({
            "block_len": snap["block_len"],
            "peak_concurrent": peak_c,
            "peak_used_blocks": peak_u,
            "hbm_cache_bytes": snap["hbm_cache_bytes"],
            # measured waste the dense allocator would have carried for
            # the peak concurrent set: blocks actually placed vs one
            # full-budget row per live sequence
            "cache_waste_ratio": round(
                1.0 - peak_u / (peak_c * per_slot), 4)
            if peak_c else 0.0,
            "prefix_hits": snap["prefix_hits"],
            "prefix_hit_blocks": snap["prefix_hit_blocks"],
        })
    return compile_rec, telemetry_rec, rec


def main() -> None:
    compile_rec = telemetry_rec = None
    try:
        compile_rec, telemetry_rec, rec = probe(
            _arg("--requests", 16), _arg("--slots", 4), _arg("--seed", 0),
            workload=_arg_str("--workload", "uniform"),
            shared_prefix=_arg("--shared-prefix", 0))
    except Exception as e:
        rec = {"probe": "serve",
               "error": f"{type(e).__name__}: {e}"[:400]}
    if compile_rec is not None:
        # a measured-window compile count > 0 means the decode loop
        # retraced mid-flight — visible here even when nothing asserts
        print(json.dumps(compile_rec), flush=True)
    if telemetry_rec is not None:
        print(json.dumps(telemetry_rec), flush=True)
    # metric record LAST: the serve metric line carries no "value" key,
    # so bench-style newest-line-fallback parsers must find it newest
    # (the other probes' metric lines are value-bearing and win on key;
    # this one wins on position)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
