"""Long-context probe: chunked-vs-blocking decode cadence A/B plus a
sequence-parallel training parity check, on a forced host-platform CPU
mesh.

Self-contained: forces ``JAX_PLATFORMS=cpu`` with 8 virtual devices
BEFORE importing jax (matching the other CPU-mesh fallback probes), so
it produces a real number on any machine — including one whose
accelerator backend is wedged, which is exactly when bench.py falls
back to it.

Two parts:

1. **Chunked-prefill cadence A/B**: the SAME workload — three live
   decode streams plus two 320-token prompts (40 full blocks, well past
   the chunk threshold) joining mid-stream — is served twice by a paged
   engine, once with ``chunked_prefill=False`` (the whole 320-token
   prefill runs as one program call between decode waves, stalling
   every live stream for its full duration) and once with the default
   chunked streaming (the prefill advances in small cadence-aware
   chunks between waves).  The headline is the inter-token p99 ratio
   blocking/chunked (>1 = chunking protects decode cadence).  The
   chunked arm's long outputs are asserted token-identical to
   standalone ``generate()``, its measured window is compile-guard
   clean (every possible chunk bucket is a multiple of ``block_len``
   at or under the big chunk quantum, so warming the whole-path
   buckets 8..64 warms the entire chunk program family), and the HBM
   ledger (pool bytes, peak blocks, per-slot table span) rides along.

2. **Sequence-parallel parity**: the same 2-layer GPT fit twice on the
   8-device mesh — data=2 x fsdp=2 baseline vs seq_parallel=2 (ulysses,
   data=2 x fsdp=2 x seq=2 is 8 devices) — and the relative train-loss
   difference is reported as ``seq_parallel_parity_rel_err`` (gated
   direction=lower in PERF_BASELINE.json; ring parity is pinned in
   tests/test_seq_parallel.py).

Output (compile-count line, telemetry line, metric line LAST —
the bench parser contract)::

    {"probe": "long_context", "kind": "compile_count", ...}
    {"probe": "long_context", "kind": "telemetry", ...}
    {"metric": "long_context_cadence_ratio", "value": ...,
     "unit": "ratio", "vs_baseline": ..., "token_identical": true,
     "measured_window_compiles": 0, "seq_parallel_parity_rel_err": ...}
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BLOCK_LEN = 8
LONG_LEN = 320               # 40 full blocks: 5x the 64-token chunk bar
N_LONG = 2
N_DECODE = 3                 # live decode streams the stall would hit
DECODE_NEW = 48
MAX_TOTAL_LEN = 384
CADENCE_BAR = 1.0            # chunking must not lose to blocking

_MODEL_CFG = dict(vocab_size=61, d_model=64, n_heads=4, d_ff=256,
                  n_layers=3, max_seq_len=384)


def _build(seed: int):
    import jax

    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)

    model = GPT(TransformerConfig(**_MODEL_CFG))
    return model, model.init_params(jax.random.PRNGKey(seed))


def _engine(model, params, chunked: bool):
    from ray_lightning_accelerators_tpu.serve import ServeEngine
    return ServeEngine(model, params, max_slots=N_DECODE + 1,
                       queue_depth=32, max_total_len=MAX_TOTAL_LEN,
                       block_len=BLOCK_LEN, n_blocks=112,
                       prefix_cache=False, idle_poll_s=0.002,
                       chunked_prefill=chunked, slo=None)


def _warm(eng, rng, vocab):
    """Warm every program the measured window can touch: the decode
    step, the long whole-prompt bucket (blocking arm), and — because
    the whole-prompt paged path and the chunk path share one program
    family keyed by padded suffix length — every chunk bucket, by
    driving whole-path prompts at each multiple of block_len up to the
    big chunk quantum (distinct random tokens: no accidental shared
    prefix shortening a warm bucket)."""
    import numpy as np
    big = eng._chunk_blocks * eng.block_len
    for s0 in list(range(BLOCK_LEN, big + 1, BLOCK_LEN)) + [LONG_LEN]:
        p = rng.integers(1, vocab, size=(s0,)).astype(np.int32)
        eng.submit(p, 2).result(timeout=300)


def _drive(eng, short_prompts, long_prompts):
    """Three decode streams, then the long prompts joining mid-stream
    (one free slot each: admission is immediate, so the A/B contrasts
    the PREFILL execution policy, not queueing)."""
    import numpy as np
    dec = [eng.submit(p, DECODE_NEW) for p in short_prompts]
    time.sleep(0.05)
    longs = []
    for p in long_prompts:
        longs.append(eng.submit(p, 4))
        time.sleep(0.05)
    outs = [np.asarray(h.result(timeout=300)) for h in longs]
    for h in dec:
        h.result(timeout=300)
    return outs, eng.stats()


def _sp_parity(seed: int) -> dict:
    """Train-loss parity of seq_parallel=2 (ulysses) vs the plain
    data=2 x fsdp=2 baseline on the forced 8-device mesh."""
    import numpy as np

    from ray_lightning_accelerators_tpu import DataLoader, Trainer
    from ray_lightning_accelerators_tpu.accelerators.base import (
        Accelerator)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib

    tokens = np.asarray(np.random.default_rng(seed).integers(
        0, 64, size=(16, 16)), np.int32)

    def fit(seqp, mode):
        cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                d_ff=64, n_layers=2, max_seq_len=16,
                                fused_loss=True, loss_chunk_rows=64)
        tr = Trainer(max_epochs=1, precision="f32", seed=0,
                     enable_checkpointing=False,
                     log_every_n_steps=10 ** 9,
                     accelerator=Accelerator(
                         mesh_lib.MeshConfig(data=2, fsdp=2)),
                     seq_parallel=seqp, seq_parallel_mode=mode)
        tr.fit(GPT(cfg), DataLoader(ArrayDataset(tokens), batch_size=8))
        return float(tr.callback_metrics["train_loss"])

    base = fit(1, None)
    sp = fit(2, "ulysses")
    return {"seq_parallel_parity_rel_err":
            abs(sp - base) / max(abs(base), 1e-12),
            "seq_parallel_loss": round(sp, 6),
            "baseline_loss": round(base, 6),
            "seq_parallel_mode": "ulysses"}


def _p99(vals):
    import numpy as np
    return float(np.percentile(np.asarray(vals), 99)) if vals else 0.0


def probe(seed: int) -> tuple:
    import numpy as np

    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg

    cg.install()
    model, params = _build(seed)
    vocab = model.cfg.vocab_size
    rng = np.random.default_rng(seed)
    short_prompts = [rng.integers(1, vocab, size=(12,)).astype(np.int32)
                     for _ in range(N_DECODE)]
    long_prompts = [rng.integers(1, vocab,
                                 size=(LONG_LEN,)).astype(np.int32)
                    for _ in range(N_LONG)]

    import jax.numpy as jnp
    refs = [np.asarray(model.generate(params, jnp.asarray(p[None]),
                                      max_new_tokens=4))[0]
            for p in long_prompts]

    # -- arm A: blocking whole-prompt prefill -------------------------- #
    with _engine(model, params, chunked=False) as blk:
        _warm(blk, rng, vocab)
        blk.metrics.reset()
        _, blk_snap = _drive(blk, short_prompts, long_prompts)
    blk_p99 = blk_snap["token_latency_s"]["p99_s"]

    # -- arm B: chunked streaming prefill (the fast path) -------------- #
    with _engine(model, params, chunked=True) as chk:
        _warm(chk, rng, vocab)
        chk.metrics.reset()
        window_start = cg.compile_count()
        outs, chk_snap = _drive(chk, short_prompts, long_prompts)
        window_compiles = cg.compile_count() - window_start
        compile_rec = cg.compile_count_record("long_context",
                                              window_start)
        pool_bytes = chk._pool_bytes
        table_blocks = chk.table_blocks
        slot_blocks = chk.max_blocks_per_slot
    chk_p99 = chk_snap["token_latency_s"]["p99_s"]
    identical = all(np.array_equal(o, r) for o, r in zip(outs, refs))
    ratio = blk_p99 / chk_p99 if chk_p99 else 0.0

    from ray_lightning_accelerators_tpu.telemetry import (
        probe_snapshot_record)
    telemetry_rec = probe_snapshot_record("long_context", serve=chk_snap)

    rec = {
        "metric": "long_context_cadence_ratio",
        "value": round(ratio, 4),
        "unit": "ratio",
        "vs_baseline": round(ratio / CADENCE_BAR, 4),
        "long_prompt_tokens": LONG_LEN,
        "long_prompt_blocks": LONG_LEN // BLOCK_LEN,
        "decode_streams": N_DECODE,
        "token_gap_p99_ms_blocking": round(1e3 * blk_p99, 3),
        "token_gap_p99_ms_chunked": round(1e3 * chk_p99, 3),
        "token_identical": bool(identical),
        "measured_window_compiles": int(window_compiles),
        "prefill_chunks": int(chk_snap["prefill_chunks"]),
        "longest_prefill_tokens": int(chk_snap["longest_prefill_tokens"]),
        "pool_bytes": int(pool_bytes),
        "peak_used_blocks": int(chk_snap["peak_used_blocks"]),
        "table_blocks_per_slot": int(table_blocks),
        "admission_blocks_per_slot_dense_equiv": int(slot_blocks),
        "accounting_exact": bool(
            chk_snap["completed"] + chk_snap["failed"]
            + chk_snap["cancelled"] == chk_snap["submitted"]),
    }
    rec.update(_sp_parity(seed))
    return compile_rec, telemetry_rec, rec


def main() -> None:
    compile_rec = telemetry_rec = None
    try:
        compile_rec, telemetry_rec, rec = probe(
            int(sys.argv[sys.argv.index("--seed") + 1])
            if "--seed" in sys.argv else 0)
    except Exception as e:
        rec = {"metric": "long_context_cadence_ratio",
               "value": 0, "unit": "ratio", "vs_baseline": 0.0,
               "error": f"{type(e).__name__}: {e}"[:400]}
    if compile_rec is not None:
        print(json.dumps(compile_rec), flush=True)
    if telemetry_rec is not None:
        print(json.dumps(telemetry_rec), flush=True)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
