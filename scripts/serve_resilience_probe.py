"""Serve-resilience probe: completed-request fraction + p99 TTFT across
a replica chaos window, on a forced host-platform CPU mesh.

Self-contained: forces ``JAX_PLATFORMS=cpu`` with 8 virtual devices
BEFORE importing jax (matching the other CPU-mesh fallback probes), so
it produces a real number on any machine — including one whose
accelerator backend is wedged, which is exactly when bench.py falls
back to it.

Two phases over the same mixed-length sustained workload:

1. **No-chaos baseline**: a 2-replica ``ServeReplicas`` tier serves the
   stream; p99 TTFT and completed fraction recorded.
2. **Chaos window**: a 3-replica tier with one replica KILLED
   (``crash@replica1:chunk3:once``) and one HUNG
   (``hang@replica2:chunk3:once``) mid-run.  The controller
   (serve/controller.py) requeues the lost chunks head-of-line with
   retry backoff, opens the failed replicas' circuits, auto-revives
   them through the half-open probe, and the headline is the fraction
   of admitted requests that still resolved — the driver bar is 1.0
   (zero lost requests), with the chaos-vs-baseline p99 TTFT ratio
   reported as the recovery-latency evidence.

Output (compile-count line, telemetry line, metric line LAST —
the bench parser contract)::

    {"probe": "serve_resilience", "kind": "compile_count", ...}
    {"probe": "serve_resilience", "kind": "telemetry", ...}
    {"metric": "serve_resilience_completed_fraction", "value": ...,
     "unit": "fraction", "vs_baseline": ..., "p99_ttft_ratio": ..., ...}
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_REQUESTS = 24
WAVES = 6
WAVE_SLEEP_S = 0.25
HEARTBEAT_S = 0.1
WEDGE_TIMEOUT_S = 1.5
COMPLETED_BAR = 1.0

_MODEL_CFG = dict(vocab_size=61, d_model=32, n_heads=2, d_ff=64,
                  n_layers=2, max_seq_len=48)


def _engine_factory(np_params):
    def make():
        from ray_lightning_accelerators_tpu.models.transformer import (
            GPT, TransformerConfig)
        from ray_lightning_accelerators_tpu.serve import ServeEngine
        model = GPT(TransformerConfig(**_MODEL_CFG))
        return ServeEngine(model, np_params, max_slots=4, queue_depth=64)
    return make


def _requests(rng, n):
    import numpy as np
    out = []
    for _ in range(n):
        s0 = int(rng.integers(3, 13))
        out.append((rng.integers(0, _MODEL_CFG["vocab_size"],
                                 size=(s0,)).astype(np.int32),
                    int(rng.integers(3, 7))))
    return out


def _drive(group, reqs):
    """Sustained mixed load: waves of submissions across the window so
    the chaos faults land while requests are genuinely in flight."""
    import numpy as np
    handles = []
    per_wave = -(-len(reqs) // WAVES)
    for w in range(WAVES):
        for p, n in reqs[w * per_wave:(w + 1) * per_wave]:
            handles.append(group.submit(p, n))
        time.sleep(WAVE_SLEEP_S)
    done = failed = 0
    for h in handles:
        try:
            np.asarray(h.result(timeout=300))
            done += 1
        except Exception:
            failed += 1
    return done, failed, [h.ttft_s for h in handles
                          if h.ttft_s is not None]


def _p99(values):
    import numpy as np
    return float(np.percentile(np.asarray(values), 99)) if values else 0.0


def probe(seed: int) -> tuple:
    import jax
    import numpy as np

    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    from ray_lightning_accelerators_tpu.serve import ServeReplicas

    cg.install()
    model = GPT(TransformerConfig(**_MODEL_CFG))
    params = model.init_params(jax.random.PRNGKey(seed))
    np_params = jax.tree.map(np.asarray, params)
    factory = _engine_factory(np_params)
    rng = np.random.default_rng(seed)
    reqs = _requests(rng, N_REQUESTS)
    hb = {"RLA_TPU_WORKER_HEARTBEAT_S": str(HEARTBEAT_S)}

    # -- phase 1: no-chaos baseline ------------------------------------ #
    with ServeReplicas(factory, num_replicas=2, chunk_size=2,
                       heartbeat_s=HEARTBEAT_S,
                       wedge_timeout_s=WEDGE_TIMEOUT_S) as base:
        # warm every replica's compile path before the timed window
        for p, _ in reqs[:4]:
            base.submit(p, 2).result(timeout=300)
        base.metrics.reset()
        window_start = cg.compile_count()
        b_done, b_failed, b_ttfts = _drive(base, reqs)
        base_snap = base.stats()
    compile_rec = cg.compile_count_record("serve_resilience",
                                          window_start)

    # -- phase 2: chaos window (1 killed + 1 hung mid-run) ------------- #
    ns = tempfile.mkdtemp(prefix="rla-serve-resilience-chaos-")
    envs = [
        dict(hb),
        dict(hb, RLA_TPU_CHAOS="crash@replica1:chunk3:once",
             RLA_TPU_CHAOS_NS=ns),
        dict(hb, RLA_TPU_CHAOS="hang@replica2:chunk3:once",
             RLA_TPU_CHAOS_NS=ns),
    ]
    with ServeReplicas(factory, num_replicas=3, chunk_size=2,
                       heartbeat_s=HEARTBEAT_S,
                       wedge_timeout_s=WEDGE_TIMEOUT_S,
                       env_per_worker=envs) as tier:
        for p, _ in reqs[:4]:
            tier.submit(p, 2).result(timeout=300)
        tier.metrics.reset()
        c_done, c_failed, c_ttfts = _drive(tier, reqs)
        # bounded recovery: both faulted replicas must rejoin rotation
        # through the circuit breaker before teardown
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if tier.metrics.snapshot()["revived"] >= 2:
                break
            time.sleep(0.2)
        chaos_snap = tier.stats()

    from ray_lightning_accelerators_tpu.telemetry import (
        probe_snapshot_record)
    telemetry_rec = probe_snapshot_record("serve_resilience",
                                          serve=chaos_snap)

    submitted = chaos_snap["submitted"]
    fraction = c_done / submitted if submitted else 0.0
    b_p99, c_p99 = _p99(b_ttfts), _p99(c_ttfts)
    return compile_rec, telemetry_rec, {
        "metric": "serve_resilience_completed_fraction",
        "value": round(fraction, 4),
        "unit": "fraction",
        "vs_baseline": round(fraction / COMPLETED_BAR, 4),
        "requests": N_REQUESTS,
        "chaos": "crash@replica1:chunk3:once,hang@replica2:chunk3:once",
        "completed_chaos": int(c_done),
        "failed_chaos": int(c_failed),
        "completed_baseline": int(b_done),
        "failed_baseline": int(b_failed),
        "p99_ttft_ms_baseline": round(1e3 * b_p99, 3),
        "p99_ttft_ms_chaos": round(1e3 * c_p99, 3),
        "p99_ttft_ratio": round(c_p99 / b_p99, 3) if b_p99 else 0.0,
        "requeued": int(chaos_snap["requeued"]),
        "wedge_events": int(chaos_snap["wedge_events"]),
        "revived": int(chaos_snap["revived"]),
        "hedged": int(chaos_snap["hedged"]),
        "baseline_accounting_exact": bool(
            base_snap["completed"] + base_snap["failed"]
            + base_snap["cancelled"] == base_snap["submitted"]),
        "chaos_accounting_exact": bool(
            chaos_snap["completed"] + chaos_snap["failed"]
            + chaos_snap["cancelled"] == chaos_snap["submitted"]),
    }


def main() -> None:
    compile_rec = telemetry_rec = None
    try:
        compile_rec, telemetry_rec, rec = probe(
            int(sys.argv[sys.argv.index("--seed") + 1])
            if "--seed" in sys.argv else 0)
    except Exception as e:
        rec = {"metric": "serve_resilience_completed_fraction",
               "value": 0, "unit": "fraction", "vs_baseline": 0.0,
               "error": f"{type(e).__name__}: {e}"[:400]}
    if compile_rec is not None:
        print(json.dumps(compile_rec), flush=True)
    if telemetry_rec is not None:
        print(json.dumps(telemetry_rec), flush=True)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
