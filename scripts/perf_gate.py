"""Bench regression gate: diff a bench window against pinned floors.

The repo's perf evidence is a trail of one-JSON-line-per-metric bench
windows (``bench.py`` stdout, archived as ``BENCH_r*.json`` driver
records).  Nothing ever *compared* consecutive windows — a silent
regression (or a backend dead for five rounds, BENCH_r04/r05) just
became the new normal.  This gate makes the trajectory enforceable:

- ``PERF_BASELINE.json`` pins a per-metric floor: ``baseline`` (the
  last accepted value), ``tolerance`` (allowed fractional slack), and
  optionally ``direction: "lower"`` for metrics where smaller is
  better and ``field`` for records whose gated number is not ``value``.
- ``gate_records()`` takes one window's parsed JSON records and returns
  PASS / REGRESSION / UNGATED with a per-metric verdict table.
- **Dead-backend windows are handled explicitly**: a window carrying a
  ``backend_probe`` error record gates only the metrics that actually
  landed (the CPU-mesh fallback set) and reports the accelerator
  metrics as UNGATED.  A window with **zero** value-bearing records is
  UNGATED as a whole and exits 2 — never silently green.

Exit codes: 0 = every gated metric passed, 1 = at least one regression,
2 = UNGATED (no gateable numbers).  ``bench.py --gate`` and format.sh
both drive this module; stdlib-only, never imports jax (it must run on
the machine whose backend just died).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Mapping, Optional, Sequence

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "PERF_BASELINE.json")

PASS, REGRESSION, UNGATED = "PASS", "REGRESSION", "UNGATED"


def parse_window(text: str) -> List[Dict[str, Any]]:
    """JSON records of one bench window.  Accepts bench.py stdout (one
    JSON object per line amid warmup chatter) AND a driver
    ``BENCH_r*.json`` archive (one object whose ``tail`` holds the
    stdout) — the two shapes a gate run actually meets."""
    text = text.strip()
    if text.startswith("{"):
        try:
            obj = json.loads(text)
        except ValueError:
            obj = None
        if isinstance(obj, dict) and "tail" in obj:
            return parse_window(obj["tail"])
    records: List[Dict[str, Any]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            records.append(rec)
    return records


def newest_window(root: str = REPO_ROOT) -> Optional[str]:
    """Newest committed ``BENCH_r*.json`` driver record (lexicographic =
    chronological for the rNN naming)."""
    paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    return paths[-1] if paths else None


def _is_dead_backend(records: Sequence[Mapping[str, Any]]) -> bool:
    return any(r.get("metric") == "backend_probe" and r.get("error")
               for r in records)


def gate_records(records: Sequence[Mapping[str, Any]],
                 baseline: Mapping[str, Any]) -> Dict[str, Any]:
    """One window vs the pinned floors.  Returns ``{status, dead_backend,
    results: [{metric, status, value, floor, ...}], regressions,
    gated}``."""
    default_tol = float(baseline.get("default_tolerance", 0.1))
    specs: Mapping[str, Mapping[str, Any]] = baseline.get("metrics", {})
    dead = _is_dead_backend(records)
    # newest record per metric wins (a retried bench prints twice)
    by_metric: Dict[str, Mapping[str, Any]] = {}
    for r in records:
        name = r.get("metric")
        if name and "error" not in r:
            by_metric[name] = r
    have_numbers = any("value" in r for r in by_metric.values())
    results: List[Dict[str, Any]] = []
    regressions = gated = 0
    for name, spec in sorted(specs.items()):
        # `metric` lets two gate entries share one record (e.g. the gpt
        # bench's tokens/sec AND its mfu field); the key stays unique
        rec = by_metric.get(spec.get("metric", name))
        field = spec.get("field", "value")
        base = float(spec["baseline"])
        tol = float(spec.get("tolerance", default_tol))
        lower_better = spec.get("direction") == "lower"
        bound = base * (1.0 + tol) if lower_better else base * (1.0 - tol)
        row: Dict[str, Any] = {
            "metric": name, "field": field, "baseline": base,
            "tolerance": tol,
            ("ceiling" if lower_better else "floor"): round(bound, 6),
        }
        value = rec.get(field) if rec is not None else None
        if not isinstance(value, (int, float)):
            # absent from this window: a dead-backend window legitimately
            # lacks its accelerator metrics; either way the metric is
            # UNGATED and listed — absence never reads as a pass
            row["status"] = UNGATED
            row["reason"] = ("dead-backend window" if dead
                            else "metric absent from window")
            results.append(row)
            continue
        row["value"] = value
        gated += 1
        ok = value <= bound if lower_better else value >= bound
        row["status"] = PASS if ok else REGRESSION
        if not ok:
            regressions += 1
        results.append(row)
    if regressions:
        status = REGRESSION
    elif not have_numbers or not gated:
        # zero value-bearing records (the BENCH_r04/r05 shape) or nothing
        # this baseline knows how to gate: UNGATED, never silently green
        status = UNGATED
    else:
        status = PASS
    return {"status": status, "dead_backend": dead,
            "gated": gated, "regressions": regressions,
            "results": results}


def _read_input(path: Optional[str]) -> tuple:
    """(label, text) of the window to gate: an explicit file, '-' for
    stdin, or the newest committed BENCH_r*.json."""
    if path == "-":
        return "<stdin>", sys.stdin.read()
    if path:
        with open(path) as f:
            return path, f.read()
    newest = newest_window()
    if newest is None:
        return "<none>", ""
    with open(newest) as f:
        return newest, f.read()


def run(input_path: Optional[str] = None,
        baseline_path: str = DEFAULT_BASELINE,
        as_json: bool = False, out=None) -> int:
    out = out if out is not None else sys.stdout  # late-bound: capturable
    label, text = _read_input(input_path)
    records = parse_window(text)
    try:
        with open(baseline_path) as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        print(f"perf gate: cannot read baseline {baseline_path}: {e}",
              file=sys.stderr)
        return 2
    report = gate_records(records, baseline)
    report["window"] = label
    report["baseline_file"] = baseline_path
    if as_json:
        print(json.dumps(report, indent=1), file=out)
    else:
        print(f"perf gate [{report['status']}] window={label} "
              f"gated={report['gated']} "
              f"regressions={report['regressions']}"
              + (" (dead-backend window: CPU-fallback metrics only)"
                 if report["dead_backend"] else ""), file=out)
        for row in report["results"]:
            bound = row.get("floor", row.get("ceiling"))
            val = row.get("value", "-")
            print(f"  {row['status']:<10} {row['metric']:<46} "
                  f"value={val} bound={bound}"
                  + (f" ({row['reason']})" if "reason" in row else ""),
                  file=out)
    return {PASS: 0, REGRESSION: 1, UNGATED: 2}[report["status"]]


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--input", default=None,
                   help="bench window to gate: a bench.py stdout capture "
                        "or BENCH_r*.json archive; '-' for stdin "
                        "(default: newest committed BENCH_r*.json)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="pinned floors file (PERF_BASELINE.json)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    a = p.parse_args(argv)
    return run(a.input, a.baseline, as_json=a.json)


if __name__ == "__main__":
    sys.exit(main())
