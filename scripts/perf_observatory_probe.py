"""Perf-observatory probe on a forced-host-platform 8-device CPU mesh.

Self-contained: forces ``JAX_PLATFORMS=cpu`` with 8 virtual devices
BEFORE importing jax, so it produces a real number on any machine —
including one whose accelerator backend is wedged, which is exactly when
bench.py falls back to it.

One training run + one elastic run exercise all three ledgers
(telemetry/perf.py), and everything lands in a ``run_report.json`` and
a Prometheus export:

1. **StepTimeline** — a compressed-FSDP fit (int8 reduce-scatter +
   bf16 all-gather over fsdp=8) with the observatory attached: per-step
   wall partitioned into h2d / compile / compute / ckpt / other.  The
   headline value is the NAMED-phase coverage of measured step wall
   (the `other` remainder is exported, not hidden) — the acceptance bar
   is phases summing to within 10% of step wall.
2. **HbmLedger** — params / opt_state / exchange-buffer / device-cache
   / prefetch pools vs the live placed-array total; the probe reports
   the attributed fraction and the pool table.
3. **GoodputLedger** — an ``ElasticRunner`` run over a 2-worker pool
   with ONE injected preemption (chaos ``preempt@rank0:step1:once``):
   the drained attempt resumes from its checkpoint, the runner accounts
   restart/boot, the workers report their productive/checkpoint split,
   and one goodput fraction comes out.

Emits one bench.py-shaped JSON line on stdout, with the bench-honesty
compile-count record and the telemetry snapshot printed BEFORE it (the
parser takes the newest value-bearing line)."""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _goodput_train_body(rank, ckpt_dir, total_steps):
    """Checkpointing trainable honoring the preemption contract (the
    test_preemption shape, jax-free so worker boot stays cheap): poll
    the notice each step boundary, persist the step, return the rank's
    measured productive/checkpoint seconds for the goodput ledger."""
    import json as _json
    import os as _os
    import time as _time
    from ray_lightning_accelerators_tpu.runtime import preemption
    notice = preemption.get_notice()
    path = _os.path.join(ckpt_dir, "state.json")
    start = 0
    if _os.path.exists(path):
        with open(path) as f:
            start = _json.load(f)["step"]
    productive = ckpt = 0.0
    for step in range(start, total_steps):
        if notice.requested():
            raise preemption.Preempted.at_step(step, path,
                                               source=notice.source)
        t0 = _time.monotonic()
        _time.sleep(0.04)  # the "step"
        productive += _time.monotonic() - t0
        if rank == 0:
            t0 = _time.monotonic()
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                _json.dump({"step": step + 1}, f)
            _os.replace(tmp, path)
            ckpt += _time.monotonic() - t0
    return {"rank": rank, "productive_s": productive,
            "checkpoint_s": ckpt}


def _run_goodput(workdir: str):
    """ElasticRunner over 2 workers with one injected preemption;
    returns (runner, per-rank breakdowns)."""
    from ray_lightning_accelerators_tpu.runtime.actors import ActorPool
    from ray_lightning_accelerators_tpu.runtime.elastic import \
        ElasticRunner
    ckpt = os.path.join(workdir, "goodput-ckpt")
    ns = os.path.join(workdir, "chaos-ns")
    os.makedirs(ckpt)
    os.makedirs(ns)
    env = {"RLA_TPU_CHAOS": "preempt@rank0:step2:once",
           "RLA_TPU_CHAOS_NS": ns,
           "RLA_TPU_PREEMPT_GRACE_S": "60"}
    pool = ActorPool(2, env_per_worker=[dict(env), dict(env)])
    try:
        # warm-up dispatch (chaos step 1 skipped by the :step2 spec):
        # worker-process boot lands OUTSIDE the goodput wall, so the
        # fraction measures the run, not the spawn
        for f in pool.execute_all(lambda: None):
            f.result(timeout=120)
        runner = ElasticRunner(pool, max_failures=0, max_preemptions=2)
        out = runner.run(_goodput_train_body,
                         args_per_worker=lambda a: [(r, ckpt, 30)
                                                    for r in range(2)])
        # the interior split: ONE rank's breakdown (absorbing all ranks
        # would double-count seconds against one driver wall)
        r0 = next(o for o in out if o["rank"] == 0)
        runner.goodput.account("productive", r0["productive_s"])
        runner.goodput.account("checkpoint", r0["checkpoint_s"])
        from ray_lightning_accelerators_tpu.telemetry import get_recorder
        runner.goodput.absorb_events(get_recorder().events())
        return runner, out
    finally:
        pool.shutdown()


def main() -> None:
    import numpy as np  # noqa: F401  (keeps the mesh import order tidy)

    from ray_lightning_accelerators_tpu import (DataLoader,
                                                RayTPUAccelerator,
                                                Trainer)
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.models.mnist import (
        MNISTClassifier, synthetic_mnist)
    from ray_lightning_accelerators_tpu.telemetry import (HbmLedger,
                                                          PerfObservatory,
                                                          registry as treg)
    from ray_lightning_accelerators_tpu.utils.profiler import Profiler

    cg.install()
    workdir = tempfile.mkdtemp(prefix="rla_perf_observatory_")

    # -- ledgers 1+2: compressed-FSDP fit with the observatory attached -
    perf = PerfObservatory(hbm=HbmLedger(sample_min_s=0.0))
    x, y = synthetic_mnist(1024, seed=0)
    loader = DataLoader(ArrayDataset(x, y), batch_size=128, shuffle=True)
    model = MNISTClassifier({"layer_1": 128, "layer_2": 128, "lr": 1e-3,
                             "batch_size": 128})
    trainer = Trainer(max_epochs=3, precision="f32", seed=0,
                      accelerator=RayTPUAccelerator(use_fsdp=True),
                      grad_compression="int8",
                      enable_checkpointing=True,
                      checkpoint_format="sharded",
                      log_every_n_steps=10 ** 9,
                      profiler=Profiler(sync=True),
                      perf_observatory=perf,
                      # force the HBM-resident dataset cache (auto skips
                      # it on CPU): the dominant placed pool becomes an
                      # attributed one, and the cached-gather step path
                      # gets timeline coverage too
                      cache_dataset_on_device=True,
                      default_root_dir=os.path.join(workdir, "fit"))
    t_fit = time.perf_counter()
    trainer.fit(model, loader)
    fit_wall = time.perf_counter() - t_fit

    tl = perf.timeline.snapshot()
    hbm = perf.hbm.snapshot()
    phase_coverage = tl["phase_sum_over_wall"]   # == 1.0 by construction
    named_coverage = tl["attributed_fraction"]   # the non-`other` share

    # -- ledger 3: goodput across an elastic run with one preemption ----
    runner, _ = _run_goodput(workdir)
    # driver-side context the runner cannot see: the run's own fit phase
    # split feeds productive/compile/checkpoint for the TRAINING run too
    gp = runner.goodput.snapshot()

    # -- unified export + run report ------------------------------------
    reg = trainer.build_metrics_registry()
    reg.add_goodput(runner.goodput)   # the elastic run's ledger
    prom_lines = reg.prometheus_text().splitlines()
    report_path = treg.write_run_report(
        os.path.join(workdir, "run_report.json"),
        trace_id=trainer.trace_id, registry=reg,
        extra={"probe": "perf_observatory", "fit_wall_s": fit_wall})
    with open(report_path) as f:
        report = json.load(f)
    ledgers = set((report.get("metrics") or {}).get("perf") or {})

    record = {
        "metric": "perf_observatory_phase_coverage",
        "value": round(named_coverage, 4),
        "unit": "fraction",
        "steps": tl["steps"],
        "mean_step_ms": tl["mean_step_ms"],
        "phase_sum_over_wall": phase_coverage,
        "phases_ms": {k: round(v["total_s"] * 1e3, 2)
                      for k, v in tl["phases"].items()},
        "between_step_phases_ms": {
            k: round(v["total_s"] * 1e3, 2)
            for k, v in tl["between_step_phases"].items()},
        "hbm_attributed_fraction": hbm["attributed_fraction"],
        "hbm_total_bytes": hbm["total_bytes"],
        "hbm_pools_bytes": {k: v["bytes"]
                            for k, v in hbm["pools"].items()},
        "hbm_samples": hbm["samples"],
        "goodput_fraction": gp["goodput_fraction"],
        "goodput_seconds": gp["seconds"],
        "goodput_wall_s": gp["wall_s"],
        "elastic_attempts": gp["attempts"],
        "preemptions_injected": 1,
        "preemptions_observed": len(runner.preempt_events),
        "run_report": report_path,
        "run_report_ledgers": sorted(ledgers),
        "prometheus_lines": len(prom_lines),
        "platform": "cpu-forced-host",
        "note": "value = named-phase coverage of measured step wall "
                "(the `other` remainder is exported, not hidden); "
                "in-step phases sum to wall by construction "
                "(phase_sum_over_wall)",
        # the bar: named phases cover >= ~0.86 of step wall (the
        # within-10% acceptance criterion, PERF_BASELINE.json floor)
        "vs_baseline": round(named_coverage / 0.855, 3),
    }
    compile_rec = cg.compile_count_record("perf_observatory")
    print(json.dumps(compile_rec), flush=True)
    from ray_lightning_accelerators_tpu.telemetry import (
        probe_snapshot_record)
    print(json.dumps(probe_snapshot_record("perf_observatory")),
          flush=True)
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
