"""graftlint CLI: lint the package tree, exit nonzero on findings.

Usage::

    python scripts/graftlint.py [PATH ...] [--verbose] [--format json]

Defaults to the ``ray_lightning_accelerators_tpu`` package next to this
script.  ``--verbose`` also prints pragma-suppressed findings (the
deliberate, documented violations).  ``--format json`` prints ONE
machine-readable object (schema 1: every finding with rule/path/line/
col/message/suppressed, plus active/suppressed counts and the exit
code) — the shape CI and ``scripts/sharding_audit.py`` consume.  Wired
into ``format.sh`` and run as a tier-1 test (``pytest -m analysis``).

Repeated runs in one process (multiple PATH targets, the audit script)
reuse the mtime-keyed per-module AST parse cache in ``analysis.lint``.

Import note: only ``analysis.lint`` is loaded (stdlib-only AST work) —
linting never initializes a jax backend, so this is safe on a machine
whose accelerator is wedged.
"""

import importlib
import importlib.util
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO_ROOT, "ray_lightning_accelerators_tpu")


def _load_lint():
    """Load analysis.lint WITHOUT importing the package __init__ (which
    pulls in jax): the analysis subpackage is a dependency leaf, so it
    mounts cleanly as its own top-level package."""
    pkg_dir = os.path.join(PACKAGE, "analysis")
    spec = importlib.util.spec_from_file_location(
        "_graftlint_analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_graftlint_analysis"] = mod
    spec.loader.exec_module(mod)
    return importlib.import_module("_graftlint_analysis.lint")


def main(argv) -> int:
    import json

    lint = _load_lint()

    verbose = "--verbose" in argv
    fmt = "human"
    args = list(argv)
    if "--format" in args:
        i = args.index("--format")
        if i + 1 >= len(args) or args[i + 1] not in ("human", "json"):
            print("graftlint: --format takes 'human' or 'json'",
                  file=sys.stderr)
            return 2
        fmt = args[i + 1]
        del args[i:i + 2]
    paths = [a for a in args if not a.startswith("--")]
    if not paths:
        paths = [PACKAGE]
    rc = 0
    if fmt == "json":
        merged = None
        for path in paths:
            payload = lint.report_json(lint.lint_path(path), target=path)
            if merged is None:
                merged = payload
            else:  # multi-target: one object, findings concatenated
                merged["findings"] += payload["findings"]
                merged["active"] += payload["active"]
                merged["suppressed"] += payload["suppressed"]
                merged["target"] = None
            rc = max(rc, payload["exit_code"])
        merged = merged or lint.report_json([])
        merged["exit_code"] = rc
        print(json.dumps(merged, indent=2, sort_keys=True))
        return rc
    for path in paths:
        findings = lint.lint_path(path)
        text, code = lint.report(findings, verbose=verbose)
        print(f"== graftlint: {path}")
        print(text)
        rc = max(rc, code)
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
