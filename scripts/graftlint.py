"""graftlint CLI: lint the package tree, exit nonzero on findings.

Usage::

    python scripts/graftlint.py [PATH ...] [--verbose]

Defaults to the ``ray_lightning_accelerators_tpu`` package next to this
script.  ``--verbose`` also prints pragma-suppressed findings (the
deliberate, documented violations).  Wired into ``format.sh`` and run
as a tier-1 test (``pytest -m analysis``).

Import note: only ``analysis.lint`` is loaded (stdlib-only AST work) —
linting never initializes a jax backend, so this is safe on a machine
whose accelerator is wedged.
"""

import importlib
import importlib.util
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO_ROOT, "ray_lightning_accelerators_tpu")


def _load_lint():
    """Load analysis.lint WITHOUT importing the package __init__ (which
    pulls in jax): the analysis subpackage is a dependency leaf, so it
    mounts cleanly as its own top-level package."""
    pkg_dir = os.path.join(PACKAGE, "analysis")
    spec = importlib.util.spec_from_file_location(
        "_graftlint_analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_graftlint_analysis"] = mod
    spec.loader.exec_module(mod)
    return importlib.import_module("_graftlint_analysis.lint")


def main(argv) -> int:
    lint = _load_lint()

    verbose = "--verbose" in argv
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        paths = [PACKAGE]
    rc = 0
    for path in paths:
        findings = lint.lint_path(path)
        text, code = lint.report(findings, verbose=verbose)
        print(f"== graftlint: {path}")
        print(text)
        rc = max(rc, code)
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
