#!/usr/bin/env python
"""rla_top: live per-rank view of a running rla-tpu job (stdlib only).

Polls the DRIVER's live-telemetry ``/statusz`` endpoint
(telemetry/live.py; enabled with ``RLA_TPU_METRICS_PORT``) and renders
a refreshing table: one row for the driver, one per fan-out rank from
the driver's ClusterView — health, global step, events/sec, serve
throughput/burn-rate where an engine is live.

Discovery order:
  --url URL                  explicit driver endpoint
  --dir TELEMETRY_DIR        read driver.port.json under the dir
  (default)                  $RLA_TPU_TELEMETRY_DIR

Usage:
  python scripts/rla_top.py                 # watch, 2s refresh
  python scripts/rla_top.py --interval 0.5
  python scripts/rla_top.py --once          # one snapshot, no screen
                                            # control (scriptable)

Never imports jax (or the package): a wedged backend cannot take the
console view down with it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

COLS = ("rank", "health", "beat_age", "step", "ev/s", "serve tok/s",
        "slo burn", "detail")


# proxy-free opener: the driver endpoint is loopback, and a host-level
# http_proxy would otherwise swallow every poll
_OPENER = urllib.request.build_opener(urllib.request.ProxyHandler({}))


def fetch(url: str, timeout: float = 2.0):
    try:
        with _OPENER.open(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}", "url": url}


def discover_url(args) -> str:
    if args.url:
        return args.url.rstrip("/")
    tdir = args.dir or os.environ.get("RLA_TPU_TELEMETRY_DIR")
    if not tdir:
        sys.exit("rla_top: pass --url, --dir, or set "
                 "RLA_TPU_TELEMETRY_DIR")
    path = os.path.join(tdir, "driver.port.json")
    try:
        with open(path) as f:
            rec = json.load(f)
        return rec["url"].rstrip("/")
    except (OSError, ValueError, KeyError):
        sys.exit(f"rla_top: no readable driver portfile at {path} "
                 "(is the run up with RLA_TPU_METRICS_PORT set?)")


def _num(x, fmt="{:.1f}", dash="-"):
    return fmt.format(x) if isinstance(x, (int, float)) else dash


def _serve_cells(serve: dict):
    """(tok/s, burn) summed/maxed across a rank's engines."""
    if not serve:
        return "-", "-"
    tok = sum(s.get("throughput_tok_s") or 0.0 for s in serve.values())
    burns = [s.get("slo_burn_rate") for s in serve.values()
             if isinstance(s.get("slo_burn_rate"), (int, float))]
    return _num(tok), (_num(max(burns), "{:.2f}") if burns else "-")


def rows_from_statusz(status: dict):
    """One row per rank: the driver itself + its cluster view ranks."""
    rows = []

    def row_of(label, r):
        health = r.get("health") or {}
        serve = r.get("serve") or {}
        tok, burn = _serve_cells(serve)
        rows.append((
            str(label),
            health.get("status", "?"),
            _num(health.get("beat_age_s"), "{:.1f}s"),
            str(r.get("global_step", "-")),
            _num(r.get("events_per_second"), "{:.1f}"),
            tok, burn,
            (health.get("detail") or "")[:40],
        ))

    drv = dict(status)
    drv["serve"] = status.get("serve") or {}
    row_of(status.get("rank", "driver"), drv)
    cluster = (status.get("cluster") or {}).get("ranks") or {}
    for label in sorted(cluster, key=lambda x: (len(x), x)):
        row_of(label, cluster[label])
    return rows


def render(status: dict) -> str:
    lines = []
    if "error" in status:
        return (f"rla_top: driver unreachable — {status['error']}\n"
                f"  ({status.get('url', '?')})")
    refreshed = (status.get("cluster") or {}).get("refreshed_age_s")
    head = (f"trace={status.get('trace_id') or '-'}  "
            f"step={status.get('global_step', '-')}  "
            f"ranks_refreshed="
            f"{_num(refreshed, '{:.1f}s') if refreshed is not None else '-'}")
    lines.append(head)
    rows = rows_from_statusz(status)
    widths = [max(len(str(c)), *(len(r[i]) for r in rows))
              for i, c in enumerate(COLS)]
    fmt = "  ".join("{:<%d}" % w for w in widths)
    lines.append(fmt.format(*COLS))
    for r in rows:
        lines.append(fmt.format(*r))
    tl = status.get("step_timeline")
    if tl:
        lines.append(
            f"timeline: {tl.get('steps', 0)} steps, "
            f"mean {_num(tl.get('mean_step_ms'), '{:.1f}')}ms, "
            f"attributed {_num(tl.get('attributed_fraction'), '{:.2f}')}")
    hbm = status.get("hbm")
    if hbm:
        pools = ", ".join(f"{k}={v / 1e6:.1f}MB"
                          for k, v in sorted(
                              (hbm.get("pools") or {}).items())
                          if isinstance(v, (int, float)) and v)
        lines.append(f"hbm: total {hbm.get('total_bytes', 0) / 1e6:.1f}MB"
                     f" ({pools})" + (
                         f"  LEAK ALARMS={hbm['leak_alarms']}"
                         if hbm.get("leak_alarms") else ""))
    gp = status.get("goodput")
    if gp:
        frac = _num(gp.get("goodput_fraction"), "{:.2f}")
        lines.append(f"goodput: {frac} over "
                     f"{_num(gp.get('wall_s'))}s wall")
    slo = status.get("slo")
    if slo:
        for label, t in sorted(slo.items()):
            fams = ", ".join(
                f"{k}:{v.get('violations', 0)}/{v.get('observations', 0)}"
                for k, v in sorted((t.get("families") or {}).items()))
            lines.append(f"slo[{label}]: burn "
                         f"{_num(t.get('burn_rate'), '{:.2f}')} ({fams})")
    rc = status.get("replica_controller")
    if rc:
        lines.extend(replica_table(rc))
    return "\n".join(lines)


REPLICA_COLS = ("replica", "state", "lane", "in-flight", "dispatched",
                "retries", "hedges", "revived", "p99ms", "burn",
                "pfx-hit", "detail")


def replica_table(rc: dict):
    """The serve tier's per-replica controller table (state, in-flight
    depth, retries, hedges, revivals) fed from the controller snapshot
    on /statusz (serve/controller.py)."""
    lines = [
        f"serve tier: queue {rc.get('queue_depth', 0)}/"
        f"{rc.get('queue_cap', 0)} "
        f"(shed at {rc.get('brownout_watermark', '-')}), "
        f"burn {_num(rc.get('max_burn'), '{:.2f}')}, "
        f"replicas {len(rc.get('replicas') or {})}"
        + (f"/{rc['max_replicas']}" if rc.get("max_replicas") else "")]
    aff = rc.get("affinity") or {}
    if aff.get("enabled"):
        res = aff.get("residency") or {}
        lines.append(
            f"affinity ring: vnodes {aff.get('vnodes', '-')}, "
            "resident keys "
            + (", ".join(f"{k}:{v}" for k, v in sorted(res.items()))
               or "-"))
    rows = []
    for label in sorted((rc.get("replicas") or {}),
                        key=lambda x: (len(x), x)):
        r = rc["replicas"][label]
        rows.append((
            str(label),
            str(r.get("state", "?")),
            str(r.get("lane", "-")),
            f"{r.get('inflight_requests', 0)}r/"
            f"{r.get('inflight_chunks', 0)}c",
            str(r.get("dispatched_chunks", "-")),
            str(r.get("retries", "-")),
            str(r.get("hedges", "-")),
            str(r.get("revivals", "-")),
            _num(r.get("p99_step_ms")),
            _num(r.get("slo_burn"), "{:.2f}"),
            _num(r.get("prefix_hit_rate"), "{:.2f}"),
            (r.get("detail") or "")[:32],
        ))
    if rows:
        widths = [max(len(str(c)), *(len(r[i]) for r in rows))
                  for i, c in enumerate(REPLICA_COLS)]
        fmt = "  ".join("{:<%d}" % w for w in widths)
        lines.append(fmt.format(*REPLICA_COLS))
        for r in rows:
            lines.append(fmt.format(*r))
    return lines


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default=None,
                    help="driver endpoint, e.g. http://127.0.0.1:9090")
    ap.add_argument("--dir", default=None,
                    help="telemetry dir holding driver.port.json "
                         "(default: $RLA_TPU_TELEMETRY_DIR)")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (no screen "
                         "control; scriptable)")
    args = ap.parse_args()
    url = discover_url(args)
    if args.once:
        print(render(fetch(url + "/statusz")))
        return
    try:
        while True:
            frame = render(fetch(url + "/statusz"))
            # clear + home, then the frame — plain ANSI, no curses dep
            sys.stdout.write("\x1b[2J\x1b[H"
                             + time.strftime("%H:%M:%S ") + url + "\n"
                             + frame + "\n")
            sys.stdout.flush()
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
