"""Sharding inventory audit: one JSON map of every PartitionSpec/axis
declaration — the reconnaissance artifact for the ShardingPlan refactor.

ROADMAP item 5 (unified ShardingPlan) needs one answer to "where does
this repo declare layouts?".  Today the answer is scattered across the
five parallel modules plus the trainer and accelerator seams; this
script extracts it statically (AST only — never imports jax, safe on a
wedged machine) into ``SHARDING_INVENTORY.json``:

- per inventoried module: every ``PartitionSpec(...)`` / ``P(...)``
  construction (line, source text), every ``shard_map`` /
  ``shard_map_compat`` call site, and the module's axis-name constants;
- the canonical axis registry from ``parallel/mesh.py`` (string + tuple
  constants — ``DATA_AXIS`` ... ``BATCH_AXES``);
- totals, so diffs of the committed artifact show inventory drift in
  review.

Drift gate: the ``sharding-inventory`` graftlint rule flags any
PartitionSpec literal OUTSIDE the inventoried modules.  This script
reuses the lint findings in their machine-readable ``--format json``
shape (``lint.report_json`` — same payload the CLI prints, produced
in-process so the mtime parse cache warmed by the extraction pass is
reused instead of re-parsing in a subprocess) and exits nonzero when
such a finding is active — wired into ``format.sh``, so new sharding
logic cannot silently grow off the audited surface.

Usage::

    python scripts/sharding_audit.py [--out SHARDING_INVENTORY.json]
                                     [--no-write] [--quiet]
                                     [--skip-drift]

Exit codes: 0 clean, 1 uninventoried PartitionSpec literals (listed).
``--skip-drift`` extracts the inventory only (no lint pass) — what
``format.sh`` uses, because its graftlint step one line earlier ALREADY
fails on any active ``sharding-inventory`` finding; standalone runs
keep the built-in gate.
"""

import ast
import importlib
import importlib.util
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO_ROOT, "ray_lightning_accelerators_tpu")
DEFAULT_OUT = os.path.join(REPO_ROOT, "SHARDING_INVENTORY.json")


def _load_lint():
    """analysis.lint without the package __init__ (no jax import)."""
    pkg_dir = os.path.join(PACKAGE, "analysis")
    spec = importlib.util.spec_from_file_location(
        "_audit_analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_audit_analysis"] = mod
    spec.loader.exec_module(mod)
    return importlib.import_module("_audit_analysis.lint")


def _unparse(node, lines):
    """Source text of an AST node: ast.unparse when available, the
    stripped source line otherwise."""
    try:
        return ast.unparse(node)
    except Exception:
        return lines[node.lineno - 1].strip()


def _spec_call_names(info):
    """Names bound to PartitionSpec in one module — THE rule's own alias
    table (analysis/rules/sharding_inventory.py), imported rather than
    mirrored so the audit and the lint can never drift."""
    rule = importlib.import_module(
        "_audit_analysis.rules.sharding_inventory")
    return rule._spec_aliases(info)


def extract_inventory(lint):
    """The inventory dict (schema 1) over the configured modules."""
    modules, errors = lint.discover_modules(PACKAGE)
    config = lint.LintConfig.for_tree(
        {k: "\n".join(m.lines) for k, m in modules.items()})
    inv_modules = {}
    total_specs = total_shard_maps = 0
    for key in config.inventory_modules:
        info = modules.get(key)
        if info is None:
            inv_modules[key] = {"missing": True}
            continue
        aliases = _spec_call_names(info)
        specs, shard_maps = [], []
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            # lint.dotted IS the analyzer's name resolution — reusing it
            # keeps the audit's counts from drifting off the rule's
            fname = lint.dotted(node.func) or ""
            leaf = fname.split(".")[-1] if fname else ""
            if leaf == "PartitionSpec" or fname in aliases:
                specs.append({"line": node.lineno,
                              "text": _unparse(node, info.lines)})
            elif leaf in ("shard_map", "shard_map_compat"):
                shard_maps.append({"line": node.lineno})
        axis_consts = {n: v for n, v in info.consts.items()
                       if key == config.axes_module}
        tuple_consts = {n: list(v) for n, v in info.tuple_consts.items()
                        if key == config.axes_module}
        inv_modules[key] = {
            "partition_specs": specs,
            "shard_map_sites": shard_maps,
        }
        if axis_consts or tuple_consts:
            inv_modules[key]["axis_constants"] = axis_consts
            inv_modules[key]["axis_tuples"] = tuple_consts
        total_specs += len(specs)
        total_shard_maps += len(shard_maps)
    return {
        "schema": 1,
        "axis_names": sorted(config.spmd_axis_names),
        "inventory_modules": list(config.inventory_modules),
        "modules": inv_modules,
        "totals": {"partition_spec_literals": total_specs,
                   "shard_map_sites": total_shard_maps,
                   "modules": len(config.inventory_modules)},
        "parse_errors": [f.format() for f in errors],
    }


def drift_findings(lint):
    """Active sharding-inventory findings in the ``--format json``
    payload shape (lint.report_json — the machine-readable contract CI
    and this script share).  Runs in-process: the extraction pass
    already warmed the mtime parse cache, so this lint reparses
    nothing."""
    payload = lint.report_json(lint.lint_path(PACKAGE), target=PACKAGE)
    return [f for f in payload["findings"]
            if f["rule"] == "sharding-inventory"
            and not f["suppressed"]]


def main(argv) -> int:
    out_path = DEFAULT_OUT
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    write = "--no-write" not in argv
    quiet = "--quiet" in argv

    lint = _load_lint()
    inventory = extract_inventory(lint)
    # the committed artifact always records the drift verdict; only the
    # redundant-lint case (format.sh, gated by graftlint one step
    # earlier) skips the pass
    drift = [] if "--skip-drift" in argv else drift_findings(lint)
    inventory["uninventoried"] = drift

    if write:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(inventory, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, out_path)

    # bench-style artifact line (value-less on purpose: bench.py's
    # newest-value-bearing-line parser must never pick this up)
    record = {
        "kind": "sharding_audit",
        "partition_spec_literals":
            inventory["totals"]["partition_spec_literals"],
        "shard_map_sites": inventory["totals"]["shard_map_sites"],
        "modules": inventory["totals"]["modules"],
        "axis_names": len(inventory["axis_names"]),
        # None = drift pass skipped (format.sh: graftlint already gated)
        "uninventoried": (None if "--skip-drift" in argv else len(drift)),
        "out": out_path if write else None,
    }
    print(json.dumps(record, sort_keys=True))
    if drift:
        if not quiet:
            print("sharding_audit: PartitionSpec literals OUTSIDE the "
                  "inventoried modules (add a reasoned pragma, or move "
                  "the layout behind parallel/sharding.py):",
                  file=sys.stderr)
            for f in drift:
                print(f"  {f['path']}:{f['line']}: {f['message'][:100]}",
                      file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
