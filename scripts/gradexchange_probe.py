"""Gradient-exchange microbench on a forced-host-platform CPU mesh.

Self-contained: forces ``JAX_PLATFORMS=cpu`` with 8 virtual devices
BEFORE importing jax (jax 0.4.37 has no ``jax_num_cpu_devices``; the
XLA_FLAGS override must land before backend init), so it produces a real
number on any machine — including one whose TPU backend is wedged, which
is exactly when bench.py falls back to it.  The numbers are honest about
what they are: CPU "collectives" are memcpys, so the headline is the
measured BYTES-ON-WIRE reduction (the quantity that transfers to real
interconnects), with fp32/int8/bf16 step times as supporting fields.

Emits one bench.py-shaped JSON line on stdout.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_REPS = 20


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_lightning_accelerators_tpu.parallel import collectives as C
    from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.build_mesh()
    n = C.dp_size(mesh)
    rng = np.random.default_rng(0)
    # one transformer-block-sized leaf + one bias-sized leaf (the fp32
    # threshold path), stacked per-replica like the train step's local
    # grads
    params = {"w": np.zeros((1024, 1024), np.float32),
              "b": np.zeros((64,), np.float32)}
    grads = {"w": rng.normal(size=(n, 1024, 1024)).astype(np.float32),
             "b": rng.normal(size=(n, 64)).astype(np.float32)}
    lead = NamedSharding(mesh, P(mesh_lib.BATCH_AXES))
    gd = jax.tree.map(lambda a: jax.device_put(jnp.asarray(a), lead), grads)

    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg

    cg.install()  # count from before the first exchange compiles
    window_compiles = [0]  # compiles landing inside the timed reps

    def timed(fn, *args):
        out = fn(*args)
        jax.block_until_ready(out)  # compile + warmup
        w0 = cg.compile_count()
        t0 = time.perf_counter()
        for _ in range(N_REPS):
            out = fn(*args)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / N_REPS
        window_compiles[0] += cg.compile_count() - w0
        return dt

    results = {}
    cfgs = {"fp32": C.ExchangeConfig(mode=None),
            "int8": C.ExchangeConfig(mode="int8"),
            "bf16": C.ExchangeConfig(mode="bf16")}
    for name, cfg in cfgs.items():
        res = jax.tree.map(lambda a: jax.device_put(a, lead),
                           C.residual_zeros(params, n, cfg))
        ex = jax.jit(C.build_exchange(mesh, cfg))
        results[name] = timed(ex, gd, res)

    wire = C.wire_bytes_per_step(params, n, C.ExchangeConfig(mode="int8"))
    record = {
        "metric": "gradexchange_int8_wire_bytes_reduction",
        "value": wire["compression_ratio"],
        "unit": "x",
        "fp32_step_ms": round(results["fp32"] * 1e3, 2),
        "int8_step_ms": round(results["int8"] * 1e3, 2),
        "bf16_step_ms": round(results["bf16"] * 1e3, 2),
        "bytes_fp32_per_step": wire["baseline_fp32_bytes_per_step"],
        "bytes_int8_per_step": wire["exchange_bytes_per_step"],
        "devices": n,
        "platform": "cpu-forced-host",
        "note": "CPU collectives are memcpys; wire-bytes ratio is the "
                "transferable claim, step times are CPU-local context",
        # ideal block-int8 reduction is 4x; report achieved fraction
        "vs_baseline": round(wire["compression_ratio"] / 4.0, 3),
    }
    # bench-honesty tie-in: nonzero timed-window compiles = a retrace
    # landed inside a measured rep and the step times above are polluted.
    # Printed BEFORE the metric record: bench.py takes the LAST JSON line
    # of probe stdout as the bench result.
    compile_rec = dict(cg.compile_count_record("gradexchange"),
                       measured_window_compiles=window_compiles[0])
    print(json.dumps(compile_rec), flush=True)
    # unified telemetry snapshot (telemetry/registry.py): value-less and
    # kind-tagged, printed before the metric so the newest value-bearing
    # line stays the bench result either way
    from ray_lightning_accelerators_tpu.telemetry import (
        probe_snapshot_record)
    print(json.dumps(probe_snapshot_record("gradexchange")), flush=True)
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
