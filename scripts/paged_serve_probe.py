"""Paged-serve probe: concurrency per HBM byte, measured, on a forced
host-platform CPU mesh.

Self-contained: forces ``JAX_PLATFORMS=cpu`` with 8 virtual devices
BEFORE importing jax (matching the other CPU-mesh fallback probes), so
it produces a real number on any machine — including one whose
accelerator backend is wedged, which is exactly when bench.py falls
back to it.

Two measured phases, both over the production-shaped mixed-length
workload (lognormal prompt lengths):

1. **Concurrency per placed byte**: the SAME request stream is driven
   through the dense allocator (``paged=False``, one full
   ``max_total_len`` row per slot) and through a paged pool holding the
   equivalent block capacity, and the headline is
   ``(paged peak concurrent / paged placed bytes) / (dense peak
   concurrent / dense placed bytes)`` — placed bytes read off the real
   cache arrays, peak concurrency off the engines' own watermarks.
   ``vs_baseline`` is against the 1.5x driver bar.
2. **Prefix TTFT**: a shared-system-prompt workload with the prefix
   index ON vs OFF (cold request excluded from both means) — the
   measured TTFT reduction prefix reuse buys.

Output (compile-count line, telemetry line, metric line LAST —
the bench parser contract)::

    {"probe": "paged_serve", "kind": "compile_count", ...}
    {"probe": "paged_serve", "kind": "telemetry", ...}
    {"metric": "paged_serve_concurrency_per_hbm_ratio", "value": ...,
     "unit": "x", "vs_baseline": ..., "ttft_prefix_reduction": ..., ...}
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

MAX_TOTAL_LEN = 192
BLOCK_LEN = 16
DENSE_SLOTS = 4
PAGED_SLOTS = 16
N_REQUESTS = 20
PREFIX_LEN = 96
PREFIX_REQUESTS = 8
CONCURRENCY_BAR = 1.5


def _build_model(seed: int):
    import jax

    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)

    cfg = TransformerConfig(vocab_size=512, d_model=128, n_heads=4,
                            d_ff=256, n_layers=4, max_seq_len=256)
    model = GPT(cfg)
    return model, model.init_params(jax.random.PRNGKey(seed))


def _drive(engine, reqs):
    handles = [engine.submit(p, n) for p, n in reqs]
    for h in handles:
        h.result(timeout=600)
    return [h for h in handles]


def _warm(engine, rng, vocab, lengths, budget=2):
    import numpy as np
    for s0 in lengths:
        p = rng.integers(0, vocab, size=(s0,)).astype(np.int32)
        engine.submit(p, budget).result(timeout=600)


def probe(seed: int) -> tuple:
    import numpy as np

    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg
    from ray_lightning_accelerators_tpu.serve import ServeEngine

    cg.install()
    model, params = _build_model(seed)
    vocab = model.cfg.vocab_size
    rng = np.random.default_rng(seed)

    from serve_probe import mixed_prompts  # shared workload shape
    reqs = [(p, int(rng.integers(8, 17)))
            for p in mixed_prompts(rng, N_REQUESTS, vocab, 120)]

    # -- phase 1: concurrency per placed byte, dense vs paged ---------- #
    # dense: 4 full-length rows.  paged: the same block capacity split
    # over 16 slots (4 slots x 12 blocks + the reserved garbage block).
    per_slot_blocks = -(-MAX_TOTAL_LEN // BLOCK_LEN)
    n_blocks = DENSE_SLOTS * per_slot_blocks + 1
    with ServeEngine(model, params, max_slots=DENSE_SLOTS,
                     queue_depth=2 * N_REQUESTS, paged=False,
                     max_total_len=MAX_TOTAL_LEN) as dense:
        _warm(dense, rng, vocab, range(7, 121, 8))
        dense.metrics.reset()
        _drive(dense, reqs)
        dense_snap = dense.stats()
        dense_bytes = dense._pool_bytes
        dense_peak = dense_snap["max_batch"]

    with ServeEngine(model, params, max_slots=PAGED_SLOTS,
                     queue_depth=2 * N_REQUESTS,
                     max_total_len=MAX_TOTAL_LEN, block_len=BLOCK_LEN,
                     n_blocks=n_blocks, pool_overcommit=2.0) as paged:
        _warm(paged, rng, vocab, range(7, 121, 16))
        paged.metrics.reset()
        window_start = cg.compile_count()
        _drive(paged, reqs)
        paged_snap = paged.stats()
        paged_bytes = paged._pool_bytes
        paged_peak = paged_snap["peak_concurrent"]
        compile_rec = cg.compile_count_record("paged_serve", window_start)

    ratio = ((paged_peak / paged_bytes) / (dense_peak / dense_bytes)
             if dense_peak and paged_bytes else 0.0)

    # -- phase 2: prefix-reuse TTFT, index ON vs OFF ------------------- #
    shared = rng.integers(0, vocab, size=(PREFIX_LEN,)).astype(np.int32)
    pre_reqs = [(p, 4) for p in mixed_prompts(
        rng, PREFIX_REQUESTS, vocab, 120, shared=shared)]

    def ttft_mean(prefix_cache):
        eng = ServeEngine(model, params, max_slots=2,
                          queue_depth=2 * PREFIX_REQUESTS,
                          max_total_len=MAX_TOTAL_LEN,
                          block_len=BLOCK_LEN,
                          prefix_cache=prefix_cache)
        with eng:
            # warm every bucket this workload hits: full-prompt buckets
            # (cold/off path) AND, with the index on, the hit path's
            # suffix buckets (seed request + one hit per suffix edge)
            for sfx in (2, 16):
                for _ in range(2 if prefix_cache else 1):
                    p = np.concatenate([shared, rng.integers(
                        0, vocab, size=(sfx,)).astype(np.int32)])
                    eng.submit(p, 2).result(timeout=600)
            eng.metrics.reset()
            # serialized submissions: TTFT must measure prefill, not
            # queue wait behind the previous request
            ttfts = []
            for p, n in pre_reqs:
                r = eng.submit(p, n)
                r.result(timeout=600)
                ttfts.append(r.ttft_s)
            snap = eng.stats()
        return float(np.mean(ttfts)), snap

    ttft_off, _ = ttft_mean(False)
    ttft_on, on_snap = ttft_mean(True)
    reduction = ttft_off / ttft_on if ttft_on > 0 else 0.0

    from ray_lightning_accelerators_tpu.telemetry import (
        probe_snapshot_record)
    telemetry_rec = probe_snapshot_record("paged_serve", serve=on_snap)

    return compile_rec, telemetry_rec, {
        "metric": "paged_serve_concurrency_per_hbm_ratio",
        "value": round(ratio, 3),
        "unit": "x",
        "vs_baseline": round(ratio / CONCURRENCY_BAR, 3),
        "dense_peak_concurrent": int(dense_peak),
        "paged_peak_concurrent": int(paged_peak),
        "dense_cache_bytes": int(dense_bytes),
        "paged_cache_bytes": int(paged_bytes),
        "requests": N_REQUESTS,
        "block_len": BLOCK_LEN,
        "peak_used_blocks": int(paged_snap["peak_used_blocks"]),
        "cache_waste_ratio": round(
            1.0 - paged_snap["peak_used_blocks"]
            / (paged_peak * per_slot_blocks), 4) if paged_peak else 0.0,
        "ttft_prefix_off_ms": round(1e3 * ttft_off, 3),
        "ttft_prefix_on_ms": round(1e3 * ttft_on, 3),
        "ttft_prefix_reduction": round(reduction, 3),
        "prefix_hits": int(on_snap["prefix_hits"]),
        "prefix_hit_blocks": int(on_snap["prefix_hit_blocks"]),
    }


def main() -> None:
    compile_rec = telemetry_rec = None
    try:
        compile_rec, telemetry_rec, rec = probe(
            int(sys.argv[sys.argv.index("--seed") + 1])
            if "--seed" in sys.argv else 0)
    except Exception as e:
        rec = {"metric": "paged_serve_concurrency_per_hbm_ratio",
               "value": 0, "unit": "x", "vs_baseline": 0.0,
               "error": f"{type(e).__name__}: {e}"[:400]}
    if compile_rec is not None:
        print(json.dumps(compile_rec), flush=True)
    if telemetry_rec is not None:
        print(json.dumps(telemetry_rec), flush=True)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
