"""Live-telemetry-plane probe on a forced-host-platform 8-device CPU mesh.

Self-contained: forces ``JAX_PLATFORMS=cpu`` with 8 virtual devices
BEFORE importing jax, so it produces a real number on any machine —
including one whose accelerator backend is wedged, which is exactly when
bench.py falls back to it.

Four measurements, one run (telemetry/live.py + serve/slo.py):

1. **Scrape-under-load validity + overhead** — a training fit with the
   live plane enabled while a scraper thread hammers ``/metrics`` +
   ``/statusz`` (~20 Hz).  EVERY ``/metrics`` body is validated against
   the Prometheus exposition grammar (the same validator
   tests/test_telemetry.py applies to the end-of-run export); the
   headline value is the fraction of scrapes that came back valid
   (bar: 1.0 — a live scrape that tears or 500s under load is a
   correctness bug, not noise).  A/B against an identical unscraped fit
   reports the step-wall overhead fraction (reported, not gated: CPU
   shared-host noise swamps the <1% bar the StepTimeline shows).
2. **Compile discipline** — the steady-state window compiles with the
   plane enabled (scraping included) must be 0.
3. **Serve SLO burn rate** — a mixed serve workload under an engine
   with deliberately tight targets reports a NONZERO burn rate + typed
   deadline sheds; the same workload under generous targets reports
   exactly zero (the signal has no false floor).
4. **ClusterView** — 2 spawned workers publish live endpoints via
   portfiles; the driver's ClusterView collects both and the merged
   driver ``/metrics`` carries rank-labeled samples.

Emits one bench.py-shaped JSON line on stdout, with the bench-honesty
compile-count record and the telemetry snapshot printed BEFORE it (the
parser takes the newest value-bearing line)."""

from __future__ import annotations

import json
import os
import re
import sys
import tempfile
import threading
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the exposition grammar check tests/test_telemetry.py pins, applied to
# every LIVE scrape here
_SAMPLE_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
                        r'(\{[a-zA-Z0-9_]+="[^"]*"'
                        r'(,[a-zA-Z0-9_]+="[^"]*")*\})? '
                        r"-?[0-9.eE+-]+(inf|nan)?$")


def exposition_valid(text: str) -> bool:
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        if not _SAMPLE_RE.match(line):
            return False
    return bool(text.strip())


class _Scraper:
    """Background /metrics + /statusz poller with validity accounting."""

    def __init__(self, url: str, hz: float = 20.0):
        self.url = url
        self.period = 1.0 / hz
        self.scrapes = 0
        self.valid = 0
        self.statusz_ok = 0
        self.latencies = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        from urllib.request import urlopen
        while not self._stop.wait(self.period):
            t0 = time.perf_counter()
            try:
                with urlopen(self.url + "/metrics", timeout=5) as r:
                    body = r.read().decode()
                self.scrapes += 1
                if exposition_valid(body):
                    self.valid += 1
                with urlopen(self.url + "/statusz", timeout=5) as r:
                    json.loads(r.read().decode())
                self.statusz_ok += 1
            except Exception:
                self.scrapes += 1
            self.latencies.append(time.perf_counter() - t0)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=10)


def _fit_once(workdir: str, tag: str, clock_cb):
    from ray_lightning_accelerators_tpu import (DataLoader,
                                                RayTPUAccelerator,
                                                Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.models.mnist import (
        MNISTClassifier, synthetic_mnist)
    from ray_lightning_accelerators_tpu.utils.profiler import Profiler
    x, y = synthetic_mnist(1024, seed=0)
    loader = DataLoader(ArrayDataset(x, y), batch_size=128, shuffle=True)
    model = MNISTClassifier({"layer_1": 128, "layer_2": 128, "lr": 1e-3,
                             "batch_size": 128})
    trainer = Trainer(max_epochs=3, precision="f32", seed=0,
                      accelerator=RayTPUAccelerator(),
                      enable_checkpointing=False,
                      log_every_n_steps=10 ** 9,
                      profiler=Profiler(sync=True),
                      perf_observatory=True,
                      prefetch_batches=2,
                      cache_dataset_on_device=False,
                      callbacks=[clock_cb],
                      default_root_dir=os.path.join(workdir, tag))
    trainer.fit(model, loader)
    return trainer


def _make_clock():
    from ray_lightning_accelerators_tpu import Callback
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg

    class Clock(Callback):
        def __init__(self):
            self.starts, self.ends = [], []
            self.c_start, self.c_end = [], []

        def on_train_epoch_start(self, trainer, module):
            self.starts.append(time.perf_counter())
            self.c_start.append(cg.compile_count())

        def on_train_epoch_end(self, trainer, module):
            self.ends.append(time.perf_counter())
            self.c_end.append(cg.compile_count())

        def steady_s(self):
            return self.ends[-1] - self.starts[1]

        def window_compiles(self):
            return self.c_end[-1] - self.c_start[1]

    return Clock()


def _serve_slo(overloaded: bool):
    """One mixed serve workload; returns the engine's final snapshot +
    deadline-shed count.  ``overloaded``: microsecond targets (every
    observation violates) and a deliberately stale queued request for a
    typed shed; else second-scale targets (nothing violates)."""
    import numpy as np

    import jax
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    from ray_lightning_accelerators_tpu.serve import (DeadlineExceeded,
                                                      ServeEngine,
                                                      SloPolicy)
    cfg = TransformerConfig(vocab_size=61, d_model=32, n_heads=2,
                            d_ff=64, n_layers=2, max_seq_len=64)
    model = GPT(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(7)
    if overloaded:
        pol = SloPolicy(ttft_target_s=1e-6, token_cadence_target_s=1e-6,
                        deadline_s=0.02)
    else:
        pol = SloPolicy(ttft_target_s=300.0,
                        token_cadence_target_s=300.0, deadline_s=300.0)
    engine = ServeEngine(model, params, max_slots=2, slo=pol)
    sheds = 0
    if overloaded:
        # a request that ages past its deadline while the engine is not
        # yet draining the queue -> shed typed before prefill
        stale = engine.submit(rng.integers(0, 61, size=(4,))
                              .astype(np.int32), 4)
        time.sleep(0.06)
    engine.start()
    try:
        from ray_lightning_accelerators_tpu.serve import QueueFull

        def submit_retry(prompt, n):
            # typed backpressure (QueueFull/PoolExhausted) is the
            # documented client contract: shed and retry after drain
            deadline = time.monotonic() + 120
            while True:
                try:
                    return engine.submit(prompt, n)
                except QueueFull:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.01)

        lens = rng.lognormal(1.5, 0.6, size=12).astype(int).clip(2, 24)
        handles = [submit_retry(rng.integers(0, 61, size=(int(n),))
                                .astype(np.int32),
                                int(rng.integers(2, 8)))
                   for n in lens]
        if overloaded:
            handles.append(stale)
        for h in handles:
            try:
                h.result(timeout=300)
            except DeadlineExceeded:
                # under the overloaded 20ms deadline, queue waits
                # legitimately shed requests typed — that IS the signal
                sheds += 1
        return engine.metrics.snapshot(), sheds
    finally:
        engine.stop()


def _cluster_rank_body(step_count):
    """Worker-side body: emit a few flight events so the live snapshot
    has something to show."""
    from ray_lightning_accelerators_tpu.telemetry import emit
    for i in range(step_count):
        emit("train_step", step=i)
        import time as _t
        _t.sleep(0.02)
    return step_count


def _run_cluster(tdir: str):
    """2 local workers with live endpoints; returns (ranks collected,
    driver /metrics rank-label check, merged families)."""
    from urllib.request import urlopen

    from ray_lightning_accelerators_tpu.runtime.actors import ActorPool
    from ray_lightning_accelerators_tpu.telemetry import live
    env = {"RLA_TPU_TELEMETRY_DIR": tdir, "RLA_TPU_METRICS_PORT": "0",
           "RLA_TPU_WORKER_HEARTBEAT_S": "0.1"}
    pool = ActorPool(2, env_per_worker=[dict(env), dict(env)])
    try:
        for f in pool.execute_all(_cluster_rank_body, 10):
            f.result(timeout=180)
        cv = live.ClusterView(workers=list(pool.workers), refresh_s=0.2)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len(cv.view()) < 2:
            cv.refresh()
            time.sleep(0.2)
        srv = live.get_server()
        srv.sources.bind_cluster_view(cv)
        with urlopen(srv.url + "/metrics", timeout=10) as r:
            body = r.read().decode()
        labeled = ('rla_tpu_rank_healthy{rank="0"}' in body
                   and 'rla_tpu_rank_healthy{rank="1"}' in body)
        return len(cv.view()), labeled and exposition_valid(body)
    finally:
        pool.shutdown()


def main() -> None:
    workdir = tempfile.mkdtemp(prefix="rla_live_plane_")
    tdir = os.path.join(workdir, "telemetry")
    os.makedirs(tdir)
    os.environ["RLA_TPU_TELEMETRY_DIR"] = tdir
    os.environ["RLA_TPU_METRICS_PORT"] = "0"

    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg
    from ray_lightning_accelerators_tpu.telemetry import live
    cg.install()

    # -- 1+2: scrape-under-load fit, then the unscraped A/B twin -------
    clock_a = _make_clock()
    trainer = None
    scraper = None

    # the server starts inside fit; poll for it from a side thread
    def attach_scraper():
        nonlocal scraper
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            srv = live.get_server()
            if srv is not None and srv.url:
                scraper = _Scraper(srv.url).__enter__()
                return
            time.sleep(0.05)

    attach_thread = threading.Thread(target=attach_scraper, daemon=True)
    attach_thread.start()
    trainer = _fit_once(workdir, "scraped", clock_a)
    attach_thread.join(timeout=5)
    if scraper is not None:
        scraper.__exit__()
    scraped_step_s = clock_a.steady_s()
    window_compiles = clock_a.window_compiles()

    clock_b = _make_clock()
    _fit_once(workdir, "plain", clock_b)
    plain_step_s = clock_b.steady_s()
    overhead = (scraped_step_s - plain_step_s) / plain_step_s \
        if plain_step_s > 0 else 0.0

    scrapes = scraper.scrapes if scraper else 0
    valid = scraper.valid if scraper else 0
    validity = (valid / scrapes) if scrapes else 0.0
    lat = sorted(scraper.latencies) if scraper else []
    lat_p50_ms = round(lat[len(lat) // 2] * 1e3, 2) if lat else None

    # -- 3: serve SLO burn rates ---------------------------------------
    hot, sheds = _serve_slo(overloaded=True)
    cold, _ = _serve_slo(overloaded=False)

    # -- 4: cluster view over 2 live worker endpoints ------------------
    cluster_ranks, cluster_labeled = _run_cluster(tdir)

    record = {
        "metric": "live_plane_scrape_validity",
        "value": round(validity, 4),
        "unit": "fraction",
        "scrapes": scrapes,
        "statusz_ok": scraper.statusz_ok if scraper else 0,
        "scrape_latency_p50_ms": lat_p50_ms,
        "scrape_overhead_fraction": round(overhead, 4),
        "scraped_steady_s": round(scraped_step_s, 3),
        "plain_steady_s": round(plain_step_s, 3),
        "measured_window_compiles": window_compiles,
        "slo_burn_rate_overloaded": hot.get("slo_burn_rate"),
        "slo_violations_overloaded": hot.get("slo_violations"),
        "slo_deadline_sheds": hot.get("slo_deadline_shed"),
        "deadline_shed_typed": sheds,
        "slo_burn_rate_light": cold.get("slo_burn_rate"),
        "slo_violations_light": cold.get("slo_violations"),
        "cluster_ranks_collected": cluster_ranks,
        "cluster_metrics_rank_labeled": cluster_labeled,
        "platform": "cpu-forced-host",
        "note": "value = fraction of live /metrics scrapes (~20Hz under "
                "a training fit) that parsed exposition-valid; overhead "
                "is the scraped-vs-plain steady-state A/B (reported, "
                "not gated — shared-CPU noise; the in-run StepTimeline "
                "is the <1% witness)",
        "vs_baseline": round(validity, 4),
    }
    compile_rec = cg.compile_count_record("live_plane")
    print(json.dumps(compile_rec), flush=True)
    from ray_lightning_accelerators_tpu.telemetry import (
        probe_snapshot_record)
    print(json.dumps(probe_snapshot_record(
        "live_plane", profiler=trainer.profiler)), flush=True)
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
