"""MPMD pipeline-bubble probe on forced-host-platform CPU workers.

Self-contained: forces ``JAX_PLATFORMS=cpu`` BEFORE importing jax, so it
produces a real number on any machine — including one whose accelerator
backend is wedged, which is exactly when bench.py falls back to it.

One PipelineRunner fit (parallel/mpmd/): S=2 stage groups over spawned
actor-pool workers, 1F1B over M=4 microbatches, activations handed off
through the shm object store.  Per-stage compute is sized so the matmul
chain dominates the mailbox/IPC handoff cost (tiny models measure the
transport, not the schedule) and the steady-state measured bubble
fraction lands on the analytic 1F1B bubble (S-1)/(M+S-1) = 1/5.

The headline value is the bubble accuracy

    1 - |measured - analytic| / analytic

over the steady-state steps (step 1 pays per-stage compiles and is
excluded).  The acceptance bar is > 0.8 — measured within 20% of
analytic — asserted here AND pinned as a PERF_BASELINE.json floor.  The
probe also asserts the cross-stage evidence trail: every per-step row
carries both stages' busy/wall ticks, and both ranks' spilled
``pipeline_tick`` events in run_report.json stitch under the run's one
trace id.

Emits one bench.py-shaped JSON line on stdout, with the bench-honesty
compile-count record and the telemetry snapshot printed BEFORE it (the
parser takes the newest value-bearing line)."""

from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

STAGES = 2
MICROBATCHES = 4
STEPS = 5
DIM = 1024       # every layer is a DIM x DIM matmul: compute-bound ticks
ROWS = 1024      # rows per batch -> ROWS/MICROBATCHES per microbatch

# One XLA compute thread per stage worker: the analytic bubble assumes
# CONSTANT tick time, but multi-threaded workers contend for host cores
# exactly when the schedule overlaps them (steady state) and run alone
# at full speed inside the bubble windows — which compresses the
# measured bubble below analytic.  Single-threaded workers on a
# multi-core host never contend, so tick time is overlap-independent.
_WORKER_XLA = ("--xla_force_host_platform_device_count=1 "
               "--xla_cpu_multi_thread_eigen=false "
               "intra_op_parallelism_threads=1")


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_lightning_accelerators_tpu import TpuModule, native
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg
    from ray_lightning_accelerators_tpu.parallel.mpmd.driver import (
        PipelineRunner)
    from ray_lightning_accelerators_tpu.parallel.mpmd.schedule import (
        analytic_bubble_fraction)

    if not native.available():
        raise RuntimeError(
            f"pipeline probe needs the native shm object store for "
            f"activation handoff: {native.build_error()}")

    cg.install()
    workdir = tempfile.mkdtemp(prefix="rla_pipeline_probe_")

    class ProbeModel(TpuModule):
        """Depth-4 tanh MLP, DIM x DIM per layer, cut into 2 stages of
        2 contiguous layers — uniform per-stage cost, so the analytic
        1F1B bubble applies directly."""

        DEPTH = 4

        def init_params(self, rng):
            keys = jax.random.split(rng, self.DEPTH)
            return {
                f"l{i}": {
                    "w": jax.random.normal(
                        keys[i], (DIM, DIM), jnp.float32) * 0.02,
                    "b": jnp.zeros((DIM,), jnp.float32),
                }
                for i in range(self.DEPTH)
            }

        @staticmethod
        def _layer_indices(layers):
            return sorted(int(name[1:]) for name in layers)

        def _apply(self, layers, x):
            for i in self._layer_indices(layers):
                p = layers[f"l{i}"]
                x = jnp.tanh(x @ p["w"] + p["b"])
            return x

        def forward(self, params, x):
            return self._apply(params, x)

        def training_step(self, params, batch, rng):
            loss = jnp.mean((self._apply(params, batch) - 1.0) ** 2)
            return loss, {"loss": loss}

        def configure_optimizers(self):
            return optax.sgd(0.01)

        def pipeline_stage_params(self, params, stage, num_stages):
            per = self.DEPTH // num_stages
            return {f"l{i}": params[f"l{i}"]
                    for i in range(stage * per, (stage + 1) * per)}

        def pipeline_stage_forward(self, stage_params, x, stage,
                                   num_stages):
            return self._apply(stage_params, x)

        def pipeline_loss(self, y, batch):
            loss = jnp.mean((y - 1.0) ** 2)
            return loss, {"loss": loss}

    rng = np.random.default_rng(0)
    batches = [rng.standard_normal((ROWS, DIM)).astype(np.float32)
               for _ in range(STEPS)]

    runner = PipelineRunner(
        ProbeModel(), num_stages=STAGES, num_microbatches=MICROBATCHES,
        schedule="1f1b", seed=0, workdir=workdir,
        ckpt_every=10 ** 9,  # checkpoint cadence off the measured path
        worker_env={"XLA_FLAGS": _WORKER_XLA})
    try:
        summary = runner.run(batches)
    finally:
        runner.shutdown()

    analytic = analytic_bubble_fraction(STAGES, MICROBATCHES)
    assert summary["analytic_bubble_fraction"] == analytic

    # steady state only: step 1's ticks carry every stage's compiles
    rows = summary["steps"][1:]
    measured = sum(r["bubble_frac"] for r in rows) / len(rows)
    accuracy = 1.0 - abs(measured - analytic) / analytic
    assert accuracy > 0.8, (
        f"measured bubble {measured:.4f} is not within 20% of analytic "
        f"{analytic:.4f} (accuracy {accuracy:.3f}) — per-stage compute "
        "no longer dominates the handoff cost")

    # zero steady-state retraces: the per-step compile count freezes
    compiles = [r["compiles"] for r in summary["steps"]]
    assert len(set(compiles[1:])) == 1, compiles

    # stitched cross-stage timeline: every step row carries both stages'
    # ticks, and both ranks' spilled tick events share the one trace id
    for row in summary["steps"]:
        keys = {k.split("/")[0] for k in row["per_stage"]}
        assert keys == {str(s) for s in range(STAGES)}, row["per_stage"]
    report = json.load(open(os.path.join(workdir, "run_report.json")))
    assert report["error"] is None
    assert report["trace_id"] == summary["trace_id"]
    for rank in (str(r) for r in range(STAGES)):
        ticks = [e for e in report["ranks"][rank]["events"]
                 if e.get("kind") == "pipeline_tick"]
        assert ticks, f"rank {rank} spilled no pipeline ticks"
        assert all(t["trace"] == summary["trace_id"] for t in ticks)

    record = {
        "metric": "pipeline_bubble_accuracy",
        "value": round(accuracy, 4),
        "unit": "frac",
        "measured_bubble_fraction": round(measured, 4),
        "analytic_bubble_fraction": round(analytic, 4),
        "schedule": summary["schedule"],
        "num_stages": STAGES,
        "num_microbatches": MICROBATCHES,
        "steady_steps": len(rows),
        "step_wall_s": round(sum(r["wall_s"] for r in rows) / len(rows), 4),
        "replays": summary["replays"],
        "trace_id": summary["trace_id"],
        "platform": "cpu-forced-host",
        "note": "value = 1 - |measured - analytic| / analytic for the "
                "1F1B bubble (S-1)/(M+S-1) over steady-state steps on "
                "2 stage groups x 4 microbatches; bar is > 0.8 "
                "(measured within 20% of analytic)",
        # the bar: within-20%-of-analytic (PERF_BASELINE.json floor)
        "vs_baseline": round(accuracy / 0.8, 3),
    }
    compile_rec = cg.compile_count_record("pipeline")
    print(json.dumps(compile_rec), flush=True)
    from ray_lightning_accelerators_tpu.telemetry import (
        probe_snapshot_record)
    print(json.dumps(probe_snapshot_record("pipeline")), flush=True)
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
