"""Live-resize downtime probe on a forced-host-platform 8-device CPU mesh.

Self-contained: forces ``JAX_PLATFORMS=cpu`` with 8 virtual devices
BEFORE importing jax, so it produces a real number on any machine —
including one whose accelerator backend is wedged, which is exactly when
bench.py falls back to it.

One dp=8 fit is interrupted at step 2, then the SAME dp=8→dp=4 shrink is
recovered both ways and the downtime (recovery entry → first completed
dp=4 step) is measured for each:

A. **Checkpoint round-trip** — the pre-PR-16 path: a fresh dp=4 trainer
   restores the saved checkpoint from disk (full state re-init, restore
   read, dp=4 recompile, one step).
B. **In-memory resize** — ``Trainer.resize_in_memory(4)`` +
   ``fit(ckpt_path="live")``: re-plan (parallel/plan.py), redistribute
   the live state in bounded waves (parallel/redistribute.py, no
   checkpoint file touched), dp=4 recompile, one step.

Both sides pay the dp=4 recompile and one productive step; the contrast
is the checkpoint round-trip itself.  The headline value is the downtime
ratio A/B — the factor the in-memory path is faster; the acceptance bar
is strictly > 1 (PERF_BASELINE.json gates it).

Emits one bench.py-shaped JSON line on stdout, with the bench-honesty
compile-count record and the telemetry snapshot printed BEFORE it (the
parser takes the newest value-bearing line)."""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from ray_lightning_accelerators_tpu import (DataLoader, RandomDataset,
                                                RayTPUAccelerator, Trainer,
                                                TpuModule)
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg

    cg.install()
    workdir = tempfile.mkdtemp(prefix="rla_resize_probe_")

    # state big enough that the checkpoint round-trip (serialize + write
    # + read + re-place ~48MB of params+adam moments) is the dominant
    # recovery cost, as on a real model — not the dp=4 recompile both
    # paths share
    DIM = 2048

    class ProbeModel(TpuModule):
        def init_params(self, rng):
            k = jax.random.normal(rng, (DIM, DIM), jnp.float32) * 0.02
            return {"layer": {"kernel": k,
                              "bias": jnp.zeros((DIM,), jnp.float32)}}

        def forward(self, params, x):
            return x @ params["layer"]["kernel"] + params["layer"]["bias"]

        def training_step(self, params, batch, rng):
            loss = jnp.mean((self.forward(params, batch) - 1.0) ** 2)
            return loss, {"loss": loss}

        def configure_optimizers(self):
            return optax.adam(1e-3)

    def make_loader():
        # batch 8 divides both dp=8 and dp=4 evenly
        return DataLoader(RandomDataset(DIM, 64), batch_size=8,
                          shuffle=True)

    def make_trainer(tag, num_workers, max_steps):
        return Trainer(default_root_dir=os.path.join(workdir, tag),
                       accelerator=RayTPUAccelerator(num_workers),
                       max_epochs=100, max_steps=max_steps,
                       precision="f32", seed=0,
                       enable_checkpointing=False,
                       log_every_n_steps=10 ** 9)

    # -- phase 0: the interrupted dp=8 run (shared prefix) --------------
    model = ProbeModel()
    trainer = make_trainer("fit8", 8, max_steps=2)
    trainer.fit(model, make_loader())
    ckpt = os.path.join(workdir, "mid.ckpt")
    trainer.save_checkpoint(ckpt)

    # -- A: checkpoint round-trip recovery into a dp=4 world ------------
    t0 = time.perf_counter()
    trainer_ckpt = make_trainer("restore4", 4, max_steps=3)
    trainer_ckpt.fit(ProbeModel(), make_loader(), ckpt_path=ckpt)
    downtime_ckpt = time.perf_counter() - t0
    assert trainer_ckpt.global_step == 3

    # -- B: in-memory resize of the LIVE dp=8 trainer -------------------
    t0 = time.perf_counter()
    stats = trainer.resize_in_memory(4)
    trainer.max_steps = 3
    trainer.fit(model, make_loader(), ckpt_path="live")
    downtime_inmem = time.perf_counter() - t0
    assert trainer.global_step == 3

    ratio = downtime_ckpt / max(downtime_inmem, 1e-9)
    p_ckpt = jax.device_get(trainer_ckpt._state.params)
    p_live = jax.device_get(trainer._state.params)
    drift = max(float(jnp.max(jnp.abs(a - b))) for a, b in
                zip(jax.tree.leaves(p_ckpt), jax.tree.leaves(p_live)))

    record = {
        "metric": "resize_inmem_vs_ckpt_downtime_ratio",
        "value": round(ratio, 3),
        "unit": "x",
        "downtime_ckpt_s": round(downtime_ckpt, 4),
        "downtime_inmem_s": round(downtime_inmem, 4),
        "redistribute_bytes_moved": stats["bytes_moved"],
        "redistribute_bytes_total": stats["bytes_total"],
        "redistribute_waves": stats["waves"],
        "redistribute_seconds": round(stats["seconds"], 4),
        "old_world": stats["old_world"],
        "new_world": stats["new_world"],
        "params_max_abs_drift": drift,
        "platform": "cpu-forced-host",
        "note": "value = checkpoint-restore downtime / in-memory resize "
                "downtime for the same dp=8->4 shrink (recovery entry "
                "-> first completed dp=4 step; both pay the dp=4 "
                "recompile + one step); bar is strictly > 1",
        # the bar: in-memory resize strictly faster than the checkpoint
        # round-trip (PERF_BASELINE.json floor; measured ~3.7x at
        # introduction)
        "vs_baseline": round(ratio / 3.2, 3),
    }
    compile_rec = cg.compile_count_record("resize")
    print(json.dumps(compile_rec), flush=True)
    from ray_lightning_accelerators_tpu.telemetry import (
        probe_snapshot_record)
    print(json.dumps(probe_snapshot_record("resize")), flush=True)
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
