"""Numeric-guard probe: in-step detection overhead A/B plus one full
trip-rewind-skip recovery, on a forced host-platform CPU mesh.

Self-contained: forces ``JAX_PLATFORMS=cpu`` with 8 virtual devices
BEFORE importing jax (matching the other CPU-mesh fallback probes), so
it produces a real number on any machine — including one whose
accelerator backend is wedged, which is exactly when bench.py falls
back to it.

Two parts:

1. **Overhead A/B**: the SAME tiny-GPT fit on the 8-device data mesh,
   once with the guard at its defaults (``RLA_TPU_GUARD`` on: loss /
   grad-norm finiteness, spike-vs-EMA envelope and update-ratio checks
   traced into the step, the [12]-wide guard vector riding the existing
   metrics readback) and once with ``guard=None`` (the pre-guardian
   step, bit-identical pytree).  Epoch 1 warms the compile; the
   headline is mean steady-state epoch wall time guarded/unguarded
   (gated ``direction=lower`` in PERF_BASELINE.json: the guard must
   cost <= 5%).  The measured window is compile-guard clean — the guard
   adds zero retraces.

2. **Recovery**: ``badbatch@stepK`` chaos (a NaN-poisoned host batch,
   claimed once through a private ``RLA_TPU_CHAOS_NS``) trips the
   guarded fit; the probe times the full loop — typed
   ``NumericAnomaly`` with ``blame=data``, quarantine ledger entry for
   the blamed (epoch, batch_idx) window, resumed fit skipping the
   quarantined batch to a clean finish — and reports it as
   ``recovery_s``.

Output (compile-count line, telemetry line, metric line LAST —
the bench parser contract)::

    {"probe": "anomaly_guard", "kind": "compile_count", ...}
    {"probe": "anomaly_guard", "kind": "telemetry", ...}
    {"metric": "anomaly_guard_overhead_ratio", "value": ...,
     "unit": "ratio", "vs_baseline": ..., "trip_blame": "data",
     "measured_window_compiles": 0, "recovery_s": ...}
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

WARM_EPOCHS = 1              # compile + EMA warmup, excluded from timing
MEASURE_EPOCHS = 3           # steady-state epochs per fit (min taken)
ARM_ROUNDS = 2               # interleaved A/B rounds (ordering bias)
ROWS = 512
SEQ = 16
BATCH = 16                   # 32 steps/epoch on the data=8 mesh
TRIP_STEP = 5                # 1-based global step the bad batch hits

_MODEL_CFG = dict(vocab_size=64, d_model=32, n_heads=4, d_ff=64,
                  n_layers=2, max_seq_len=SEQ)


def _epoch_timer():
    """Callback collecting per-epoch wall seconds (epoch boundaries are
    fenced by the trainer's epoch-end readback, so the window really
    covers the steps inside it)."""
    from ray_lightning_accelerators_tpu import Callback

    class _EpochTimer(Callback):
        def __init__(self):
            self.epochs = []
            self._t0 = None

        def on_train_epoch_start(self, trainer, module):
            self._t0 = time.perf_counter()

        def on_train_epoch_end(self, trainer, module):
            self.epochs.append(time.perf_counter() - self._t0)

    return _EpochTimer()


def _tokens(seed: int):
    import numpy as np
    return np.asarray(np.random.default_rng(seed).integers(
        0, _MODEL_CFG["vocab_size"], size=(ROWS, SEQ)), np.int32)


def _fit_arm(guard, tokens, root: str, cg) -> dict:
    """One timed arm: WARM_EPOCHS + MEASURE_EPOCHS epochs, returning the
    mean steady-state epoch seconds and the compiles that landed inside
    the measured window (must be 0 — the guard may not retrace)."""
    from ray_lightning_accelerators_tpu import Callback, DataLoader, Trainer
    from ray_lightning_accelerators_tpu.accelerators.base import Accelerator
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib

    timer = _epoch_timer()
    window = {"start": None}

    class _Window(Callback):
        # compile window opens AFTER the warm epoch's programs built
        def on_train_epoch_end(self, trainer, module):
            if len(timer.epochs) == WARM_EPOCHS:
                window["start"] = cg.compile_count()

    tr = Trainer(max_epochs=WARM_EPOCHS + MEASURE_EPOCHS, precision="f32",
                 seed=0, guard=guard, enable_checkpointing=False,
                 default_root_dir=root, log_every_n_steps=10 ** 9,
                 enable_progress_bar=False,
                 accelerator=Accelerator(mesh_lib.MeshConfig(data=8)),
                 callbacks=[timer, _Window()])
    tr.fit(GPT(TransformerConfig(**_MODEL_CFG)),
           DataLoader(ArrayDataset(tokens), batch_size=BATCH))
    measured = timer.epochs[WARM_EPOCHS:]
    # min over the steady-state epochs: the noise (prefetch hiccups, CPU
    # scheduling) is strictly additive, so min is the honest estimate
    return {"epoch_s": min(measured),
            "window_compiles": cg.compile_count() - window["start"],
            "final_loss": float(tr.callback_metrics["train_loss"])}


def _recovery(seed: int, root: str) -> dict:
    """Trip-rewind-skip loop under badbatch chaos: the guarded fit trips
    a typed data-blamed anomaly, the quarantine ledger records the
    blamed window, and a resumed fit skips it to a clean finish.  Uses a
    float-input regression module — badbatch poisons float batch leaves,
    and a token batch has none."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_lightning_accelerators_tpu import (DataLoader, Trainer,
                                                TpuModule)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.runtime import guardian

    class _Reg(TpuModule):
        def init_params(self, rng):
            return {"w": jax.random.normal(rng, (32, 2), jnp.float32)}

        def training_step(self, params, batch, rng):
            loss = jnp.mean((batch @ params["w"] - 1.0) ** 2)
            return loss, {"loss": loss}

        def configure_optimizers(self):
            return optax.sgd(0.05)

    data = np.random.default_rng(seed).standard_normal(
        (64, 32)).astype(np.float32)
    ns = tempfile.mkdtemp(prefix="anomaly-guard-ns-")
    os.environ["RLA_TPU_CHAOS"] = f"badbatch@step{TRIP_STEP}"
    os.environ["RLA_TPU_CHAOS_NS"] = ns
    out = {"tripped": False, "trip_blame": None, "recovery_s": 0.0,
           "quarantined": 0, "resumed_final_loss": None}

    def fit():
        tr = Trainer(max_epochs=1, precision="f32", seed=0,
                     enable_checkpointing=False, default_root_dir=root,
                     log_every_n_steps=1, enable_progress_bar=False)
        tr.fit(_Reg(), DataLoader(ArrayDataset(data), batch_size=8))
        return tr

    try:
        t0 = time.perf_counter()
        try:
            fit()
        except guardian.NumericAnomaly as e:
            out["tripped"] = True
            out["trip_blame"] = e.blame
            out["trip_step"] = e.step
        out["quarantined"] = len(
            guardian.load_quarantine(root)["entries"])
        tr = fit()  # resumed attempt: the quarantined window is skipped
        out["recovery_s"] = round(time.perf_counter() - t0, 3)
        out["resumed_final_loss"] = round(
            float(tr.callback_metrics["train_loss"]), 6)
        out["resumed_steps"] = int(tr.global_step)
    finally:
        os.environ.pop("RLA_TPU_CHAOS", None)
        os.environ.pop("RLA_TPU_CHAOS_NS", None)
    return out


def probe(seed: int) -> tuple:
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg

    cg.install()
    tokens = _tokens(seed)
    # interleaved A/B (guarded, unguarded, guarded, unguarded, ...):
    # min per arm across rounds cancels the slow drift that makes a
    # later-run arm read systematically slower on a shared CPU
    g_runs, u_runs = [], []
    for _ in range(ARM_ROUNDS):
        g_runs.append(_fit_arm("auto", tokens, tempfile.mkdtemp(), cg))
        u_runs.append(_fit_arm(None, tokens, tempfile.mkdtemp(), cg))
    guarded = min(g_runs, key=lambda r: r["epoch_s"])
    unguarded = min(u_runs, key=lambda r: r["epoch_s"])
    window_compiles = sum(r["window_compiles"] for r in g_runs + u_runs)
    ratio = (guarded["epoch_s"] / unguarded["epoch_s"]
             if unguarded["epoch_s"] else 0.0)
    rec_root = tempfile.mkdtemp(prefix="anomaly-guard-rec-")
    recovery = _recovery(seed, rec_root)

    compile_rec = cg.compile_count_record("anomaly_guard")
    from ray_lightning_accelerators_tpu.telemetry import (
        probe_snapshot_record)
    telemetry_rec = probe_snapshot_record("anomaly_guard")

    rec = {
        "metric": "anomaly_guard_overhead_ratio",
        "value": round(ratio, 4),
        "unit": "ratio",
        # gate baseline is 1.0 (free guard); <=1.05 passes
        "vs_baseline": round(ratio, 4),
        "guarded_epoch_ms": round(1e3 * guarded["epoch_s"], 2),
        "unguarded_epoch_ms": round(1e3 * unguarded["epoch_s"], 2),
        "steps_per_epoch": ROWS // BATCH,
        "measured_window_compiles": int(window_compiles),
        "loss_parity": bool(abs(guarded["final_loss"]
                                - unguarded["final_loss"]) < 1e-6),
        "devices": 8,
        "platform": "cpu-forced-host",
    }
    rec.update(recovery)
    return compile_rec, telemetry_rec, rec


def main() -> None:
    compile_rec = telemetry_rec = None
    try:
        compile_rec, telemetry_rec, rec = probe(
            int(sys.argv[sys.argv.index("--seed") + 1])
            if "--seed" in sys.argv else 0)
    except Exception as e:
        rec = {"metric": "anomaly_guard_overhead_ratio",
               "value": 0, "unit": "ratio", "vs_baseline": 0.0,
               "error": f"{type(e).__name__}: {e}"[:400]}
    if compile_rec is not None:
        print(json.dumps(compile_rec), flush=True)
    if telemetry_rec is not None:
        print(json.dumps(telemetry_rec), flush=True)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
