"""Compressed-FSDP exchange microbench on a forced-host-platform CPU mesh.

Self-contained: forces ``JAX_PLATFORMS=cpu`` with 8 virtual devices
BEFORE importing jax (jax 0.4.37 has no ``jax_num_cpu_devices``; the
XLA_FLAGS override must land before backend init), so it produces a real
number on any machine — including one whose TPU backend is wedged, which
is exactly when bench.py falls back to it.  The numbers are honest about
what they are: CPU "collectives" are memcpys, so the headlines are the
analytic BYTES-ON-WIRE reduction of the int8 reduce-scatter + bf16
param all-gather regime vs the fp32 allreduce (the quantity that
transfers to real interconnects) and the MEASURED per-shard peak state
bytes vs a replicated layout (params + Adam moments + error-feedback
residuals, read off the actual device arrays), with fp32/int8/bf16
exchange step times as supporting fields.

Emits one bench.py-shaped JSON line on stdout, with the bench-honesty
compile-count record and the telemetry snapshot printed BEFORE it (the
parser takes the newest value-bearing line).
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_REPS = 20


def _per_device_bytes(tree) -> int:
    """Peak state bytes ONE device holds for a pytree of placed arrays
    (sum of its addressable shard sizes — the memory claim FSDP makes)."""
    import jax
    total = 0
    for leaf in jax.tree.leaves(tree):
        shard = leaf.addressable_shards[0]
        total += shard.data.size * shard.data.dtype.itemsize
    return total


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_lightning_accelerators_tpu.parallel import collectives as C
    from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib
    from ray_lightning_accelerators_tpu.parallel import (
        sharding as sharding_lib)

    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, fsdp=8))
    n = C.dp_size(mesh)
    rng = np.random.default_rng(0)
    # one transformer-block-sized leaf + one bias-sized leaf (the fp32
    # threshold path), stacked per-replica like the train step's local
    # grads
    params = {"w": rng.normal(size=(1024, 1024)).astype(np.float32),
              "b": rng.normal(size=(64,)).astype(np.float32)}
    param_sh = sharding_lib.infer_fsdp_shardings(params, mesh)
    grads = {"w": rng.normal(size=(n, 1024, 1024)).astype(np.float32),
             "b": rng.normal(size=(n, 64)).astype(np.float32)}
    lead = NamedSharding(mesh, P(mesh_lib.BATCH_AXES))
    gd = jax.tree.map(lambda a: jax.device_put(jnp.asarray(a), lead), grads)

    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg

    cg.install()  # count from before the first exchange compiles
    window_compiles = [0]  # compiles landing inside the timed reps

    def timed(fn, *args):
        out = fn(*args)
        jax.block_until_ready(out)  # compile + warmup
        w0 = cg.compile_count()
        t0 = time.perf_counter()
        for _ in range(N_REPS):
            out = fn(*args)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / N_REPS
        window_compiles[0] += cg.compile_count() - w0
        return dt

    results = {}
    for name in ("fp32", "int8", "bf16"):
        cfg = C.ExchangeConfig(mode=None if name == "fp32" else name)
        res = jax.tree.map(lambda a: jax.device_put(a, lead),
                           C.fsdp_residual_zeros(params, param_sh, cfg))
        ex = jax.jit(C.build_fsdp_exchange(mesh, cfg, param_sh))
        results[name] = timed(ex, gd, res)

    # per-shard peak state bytes, measured off REAL placed arrays:
    # sharded params + Adam moments + shard-local residuals vs the same
    # state fully replicated
    cfg8 = C.ExchangeConfig(mode="int8")
    repl = NamedSharding(mesh, P())
    tx = optax.adam(1e-3)
    pd = jax.tree.map(lambda a, s: jax.device_put(jnp.asarray(a), s),
                      params, param_sh)
    opt = optax.tree_map_params(
        tx, lambda s, p_sh: jax.device_put(s, p_sh), tx.init(params),
        param_sh, transform_non_params=lambda s: jax.device_put(s, repl))
    res8 = jax.tree.map(lambda a: jax.device_put(a, lead),
                        C.fsdp_residual_zeros(params, param_sh, cfg8))
    sharded_bytes = (_per_device_bytes(pd) + _per_device_bytes(opt)
                     + _per_device_bytes(res8))
    pr = jax.tree.map(lambda a: jax.device_put(jnp.asarray(a), repl),
                      params)
    opt_r = optax.adam(1e-3).init(pr)
    res_r = jax.tree.map(
        lambda a: jax.device_put(a, lead),
        C.residual_zeros(params, n, cfg8))
    replicated_bytes = (_per_device_bytes(pr) + _per_device_bytes(opt_r)
                        + _per_device_bytes(res_r))

    wire = C.wire_bytes_per_step(params, n, cfg8, param_shardings=param_sh)
    record = {
        "metric": "fsdp_exchange_int8_wire_bytes_reduction",
        "value": wire["compression_ratio"],
        "unit": "x",
        "regime": wire["regime"],
        "fp32_step_ms": round(results["fp32"] * 1e3, 2),
        "int8_step_ms": round(results["int8"] * 1e3, 2),
        "bf16_step_ms": round(results["bf16"] * 1e3, 2),
        "bytes_fp32_per_step": wire["baseline_fp32_bytes_per_step"],
        "bytes_int8_per_step": wire["exchange_bytes_per_step"],
        "grad_reduce_scatter_bytes": wire[
            "grad_reduce_scatter_bytes_per_step"],
        "param_allgather_bytes": wire["param_allgather_bytes_per_step"],
        "per_shard_state_bytes": sharded_bytes,
        "replicated_state_bytes": replicated_bytes,
        "per_shard_state_fraction": round(
            sharded_bytes / replicated_bytes, 4),
        "devices": n,
        "fsdp": wire.get("fsdp"),
        "platform": "cpu-forced-host",
        "note": "CPU collectives are memcpys; wire-bytes ratio and "
                "per-shard peak bytes are the transferable claims, step "
                "times are CPU-local context",
        # fp32 RS+AG moves the same bytes as a ring allreduce; report
        # the achieved fraction of the ~2.65x int8-RS + bf16-AG ideal
        "vs_baseline": round(wire["compression_ratio"] / 2.65, 3),
    }
    # bench-honesty tie-in: nonzero timed-window compiles = a retrace
    # landed inside a measured rep and the step times above are polluted.
    # Printed BEFORE the metric record: bench.py takes the newest
    # value-bearing JSON line of probe stdout as the bench result.
    compile_rec = dict(cg.compile_count_record("fsdp_exchange"),
                       measured_window_compiles=window_compiles[0])
    print(json.dumps(compile_rec), flush=True)
    # unified telemetry snapshot (telemetry/registry.py): value-less and
    # kind-tagged, printed before the metric so the newest value-bearing
    # line stays the bench result either way
    from ray_lightning_accelerators_tpu.telemetry import (
        probe_snapshot_record)
    print(json.dumps(probe_snapshot_record("fsdp_exchange")), flush=True)
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
