"""Packaging (capability parity with reference setup.py:1-12)."""

from setuptools import find_packages, setup

setup(
    name="ray_lightning_accelerators_tpu",
    packages=find_packages(include=["ray_lightning_accelerators_tpu",
                                    "ray_lightning_accelerators_tpu.*"]),
    version="0.1.0",
    description="TPU-native distributed training accelerators with a "
                "Lightning-shaped trainer, mesh parallelism, and a Tune-style "
                "hyperparameter search subsystem",
    python_requires=">=3.10",
    install_requires=["jax", "flax", "optax", "numpy"],
    entry_points={
        "console_scripts": ["rla-tpu=ray_lightning_accelerators_tpu.cli:main"],
    },
)
