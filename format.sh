#!/usr/bin/env bash
# Lint / format gate (capability analog of the reference's format.sh, which
# ran yapf + flake8 over the diff vs mergebase; reference: format.sh +
# .style.yapf).  Usage:
#   ./format.sh          # check files changed vs origin/main (or HEAD~1)
#   ./format.sh --all    # check the whole tree
#
# Uses flake8 when installed (CI installs it); falls back to a byte-compile
# sweep so the script still gates syntax errors in minimal environments.

set -euo pipefail
cd "$(dirname "$0")"

if [[ "${1:-}" == "--all" ]]; then
    FILES=$(git ls-files '*.py')
else
    BASE=$(git merge-base origin/main HEAD 2>/dev/null || git rev-parse HEAD~1)
    FILES=$(git diff --name-only --diff-filter=ACMR "$BASE" -- '*.py')
fi

if [[ -z "$FILES" ]]; then
    echo "format.sh: no python files to check"
    exit 0
fi

if python -c 'import flake8' 2>/dev/null; then
    # E501 relaxed to 88 to match the prevailing style; E731/W503 match the
    # reference's flake8 tolerances for lambda-heavy framework code
    echo "$FILES" | xargs python -m flake8 \
        --max-line-length=88 --extend-ignore=E731,W503,E203
    echo "format.sh: flake8 clean"
else
    echo "$FILES" | xargs python -m py_compile
    echo "format.sh: flake8 not installed; byte-compile check passed"
fi

# graftlint: the JAX-aware invariant checks (host syncs in hot paths,
# retrace hazards, knob/wire registry drift, SPMD collective/rank-
# divergence safety) — exits nonzero on findings
python scripts/graftlint.py ray_lightning_accelerators_tpu
echo "format.sh: graftlint clean"

# sharding audit: regenerate SHARDING_INVENTORY.json (the ShardingPlan
# reconnaissance artifact).  Drift (a PartitionSpec literal outside the
# inventoried modules) already failed the graftlint step above as an
# active `sharding-inventory` finding, so the audit skips its own lint
# pass here — extraction only, one lint per format.sh run.
python scripts/sharding_audit.py --out SHARDING_INVENTORY.json --skip-drift
echo "format.sh: sharding inventory refreshed (drift gated by graftlint above)"

# perf gate: the newest bench window vs PERF_BASELINE.json floors
# (scripts/perf_gate.py).  rc 1 = a gated metric regressed -> fail here,
# where lint fails.  rc 2 = UNGATED (dead-backend/zero-numbers window):
# reported loudly, not fatal — a wedged tunnel must not block lint.
set +e
python bench.py --gate
gate_rc=$?
set -e
if [[ $gate_rc -eq 1 ]]; then
    echo "format.sh: perf gate REGRESSION (see report above)"
    exit 1
elif [[ $gate_rc -eq 2 ]]; then
    echo "format.sh: perf gate UNGATED — newest window has no gateable numbers"
else
    echo "format.sh: perf gate clean"
fi
