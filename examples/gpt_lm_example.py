"""GPT language-model example: char-level pretraining + generation.

Exercises the flagship end to end: packed LM data pipeline (data/lm.py),
fused streaming LM-head loss (ops/losses.py), warmup-cosine LR schedule
(utils/schedules.py), and KV-cache generation (models/transformer.py).
CLI mirrors the reference example's flag shape
(reference: examples/ray_ddp_example.py:118-150); model-parallel axes are
opt-in flags the reference (DP-only) never had.
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # runnable as a script from anywhere
from ray_lightning_accelerators_tpu import (DataLoader, RayTPUAccelerator,
                                            Trainer)
from ray_lightning_accelerators_tpu.data.lm import (BPETokenizer,
                                                    lm_dataset,
                                                    synthetic_corpus)
from ray_lightning_accelerators_tpu.models.transformer import (
    GPT, TransformerConfig)
from ray_lightning_accelerators_tpu.utils import schedules


def train_gpt(num_epochs=10, num_workers=None, use_fsdp=False, tensor=1,
              sequence=1, batch_size=32, seq_len=128, smoke=False,
              bpe=False):
    corpus = synthetic_corpus(60 if smoke else 2000)
    tokenizer = BPETokenizer(corpus, vocab_size=300) if bpe else None
    dataset, tok = lm_dataset(corpus, seq_len, tokenizer=tokenizer)
    # BPE compresses ~3-4x: a smoke corpus may pack to very few rows
    batch_size = max(1, min(batch_size, len(dataset)))
    loader = DataLoader(dataset, batch_size=batch_size, shuffle=True,
                        drop_last=True)
    steps = max(10, len(loader) * num_epochs)
    cfg = TransformerConfig(
        vocab_size=max(64, tok.vocab_size), d_model=128, n_heads=4,
        d_ff=512, n_layers=2 if smoke else 4, max_seq_len=seq_len,
        context_parallel="ring")
    model = GPT(cfg, lr=schedules.warmup_cosine(
        3e-3, total_steps=steps, warmup_steps=min(20, steps // 10 + 1)))
    trainer = Trainer(
        max_epochs=num_epochs, precision="bf16",
        accelerator=RayTPUAccelerator(num_workers=num_workers,
                                      use_fsdp=use_fsdp, tensor=tensor,
                                      sequence=sequence),
        default_root_dir=os.path.join(tempfile.gettempdir(), "rla_tpu_gpt"),
        enable_progress_bar=True)
    trainer.fit(model, loader)
    print("final metrics:", {k: round(v, 4)
                             for k, v in trainer.callback_metrics.items()})

    prompt = tok.encode("the pod ")
    import numpy as np
    out = model.generate(model.params, np.asarray([prompt], np.int32),
                         max_new_tokens=48)
    print("sample:", repr(tok.decode(list(map(int, out[0])))))
    return trainer


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=None,
                        help="data-parallel shards (default: all devices)")
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--use-fsdp", action="store_true")
    parser.add_argument("--tensor", type=int, default=1)
    parser.add_argument("--sequence", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--bpe", action="store_true",
                        help="byte-level BPE tokenizer instead of chars")
    parser.add_argument("--smoke-test", action="store_true")
    args = parser.parse_args()
    train_gpt(num_epochs=1 if args.smoke_test else args.num_epochs,
              num_workers=args.num_workers, use_fsdp=args.use_fsdp,
              tensor=args.tensor, sequence=args.sequence,
              batch_size=8 if args.smoke_test else args.batch_size,
              seq_len=64 if args.smoke_test else args.seq_len,
              smoke=args.smoke_test, bpe=args.bpe)
