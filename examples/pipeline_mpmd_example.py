"""MPMD pipeline-parallel training example (parallel/mpmd/).

A depth-4 MLP cut into 2 pipeline stage groups, each its own spawned
worker process with its own failure domain: ``Trainer(pipeline_stages=2)``
routes ``fit`` through the PipelineRunner — 1F1B (or GPipe) tick
programs per stage, activations handed off through the shm object
store, checkpoint replay on a stage crash.  Runs on plain CPU
(``JAX_PLATFORMS=cpu``); the schedule/fault machinery is identical on
accelerators.

    python examples/pipeline_mpmd_example.py --schedule 1f1b --steps 8
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # runnable as a script from anywhere


def build_model():
    import jax
    import jax.numpy as jnp
    import optax

    from ray_lightning_accelerators_tpu import TpuModule

    class PipelineMLP(TpuModule):
        """Four tanh layers; ``pipeline_stage_params`` slices contiguous
        layers per stage, so the same params train identically with 1,
        2 or 4 stage groups."""

        DEPTH = 4
        DIM, HIDDEN = 32, 64

        def init_params(self, rng):
            keys = jax.random.split(rng, self.DEPTH)
            sizes = ([self.DIM] + [self.HIDDEN] * (self.DEPTH - 1)
                     + [self.DIM])
            return {
                f"l{i}": {
                    "w": jax.random.normal(
                        keys[i], (sizes[i], sizes[i + 1]),
                        jnp.float32) * 0.3,
                    "b": jnp.zeros((sizes[i + 1],), jnp.float32),
                }
                for i in range(self.DEPTH)
            }

        def _apply(self, layers, x):
            for i in sorted(int(n[1:]) for n in layers):
                p = layers[f"l{i}"]
                x = jnp.tanh(x @ p["w"] + p["b"])
            return x

        # single-process path (pipeline_stages=1 / baselines)
        def forward(self, params, x):
            return self._apply(params, x)

        def training_step(self, params, batch, rng):
            loss = jnp.mean((self._apply(params, batch) - 1.0) ** 2)
            return loss, {"loss": loss}

        def configure_optimizers(self):
            return optax.sgd(0.05)

        # MPMD hooks: how the driver carves and runs one stage
        def pipeline_stage_params(self, params, stage, num_stages):
            per = self.DEPTH // num_stages
            return {f"l{i}": params[f"l{i}"]
                    for i in range(stage * per, (stage + 1) * per)}

        def pipeline_stage_forward(self, stage_params, x, stage,
                                   num_stages):
            return self._apply(stage_params, x)

        def pipeline_loss(self, y, batch):
            loss = jnp.mean((y - 1.0) ** 2)
            return loss, {"loss": loss}

    return PipelineMLP()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--stages", type=int, default=2)
    parser.add_argument("--schedule", default="1f1b",
                        choices=("1f1b", "gpipe"))
    parser.add_argument("--microbatches", type=int, default=4)
    parser.add_argument("--steps", type=int, default=8)
    args = parser.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from ray_lightning_accelerators_tpu import Trainer

    model = build_model()
    rng = np.random.default_rng(0)
    batches = [rng.standard_normal((64, model.DIM)).astype(np.float32)
               for _ in range(args.steps)]

    trainer = Trainer(
        max_steps=args.steps,
        pipeline_stages=args.stages,
        pipeline_schedule=args.schedule,
        pipeline_microbatches=args.microbatches,
        seed=0,
        enable_checkpointing=False,
        default_root_dir=os.path.join(tempfile.gettempdir(),
                                      "rla_tpu_pipeline_example"))
    trainer.fit(model, train_dataloaders=batches)

    summary = trainer.pipeline_summary
    print(f"schedule={summary['schedule']} stages={summary['num_stages']} "
          f"lanes={summary['num_lanes']} "
          f"microbatches={summary['num_microbatches']}")
    print(f"losses: {[round(l, 5) for l in summary['losses']]}")
    print(f"bubble: measured={summary['measured_bubble_fraction']:.3f} "
          f"analytic={summary['analytic_bubble_fraction']:.3f} "
          "(tiny models are handoff-bound; see scripts/pipeline_probe.py "
          "for a compute-bound measurement)")
    print(f"replays={summary['replays']} "
          f"stage budgets={summary['stage_failure_budget_used']} "
          f"trace={summary['trace_id']}")


if __name__ == "__main__":  # required: stage workers spawn
    main()
