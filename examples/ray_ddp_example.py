"""MNIST training example (capability parity with the reference's DDP example,
reference: examples/ray_ddp_example.py:61-168 -- same CLI flags, train or
tune entry, smoke mode).  TPU-native: the accelerator shards a global batch
over the device mesh instead of spawning DDP actors."""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # runnable as a script from anywhere
from ray_lightning_accelerators_tpu import (RayTPUAccelerator, Trainer,
                                            TuneReportCallback, tune)
from ray_lightning_accelerators_tpu.models.mnist import (MNISTClassifier,
                                                         MNISTDataModule)


def train_mnist(config, num_epochs=10, num_workers=1, callbacks=None,
                data_dir=None, smoke=False, agents=None):
    model = MNISTClassifier(config, data_dir)
    # real MNIST IDX files under data_dir (or $RLA_TPU_DATA_DIR) are parsed
    # directly; synthetic fallback otherwise (the reference downloads via
    # torchvision, examples/ray_ddp_example.py:37-42 -- no egress here)
    dm = MNISTDataModule(batch_size=config["batch_size"],
                         n_train=2048 if smoke else 55000,
                         n_val=512 if smoke else 5000,
                         data_dir=data_dir or os.environ.get(
                             "RLA_TPU_DATA_DIR"))
    accelerator = RayTPUAccelerator(
        num_workers=num_workers,
        num_hosts=len(agents) if agents else 1, agents=agents)
    trainer = Trainer(max_epochs=num_epochs,
                      callbacks=list(callbacks or []),
                      accelerator=accelerator,
                      default_root_dir=os.path.join(tempfile.gettempdir(),
                                                    "rla_tpu_mnist"),
                      enable_progress_bar=True)
    trainer.fit(model, datamodule=dm)
    return trainer


def tune_mnist(num_samples=10, num_epochs=10, num_workers=1, smoke=False,
               agents=None):
    config = {
        "layer_1": tune.choice([32, 64, 128]),
        "layer_2": tune.choice([64, 128, 256]),
        "lr": tune.loguniform(1e-4, 1e-1),
        "batch_size": tune.choice([32, 64, 128]),
    }
    metrics = {"loss": "ptl/val_loss", "acc": "ptl/val_accuracy"}
    callbacks = [TuneReportCallback(metrics, on="validation_end")]
    analysis = tune.run(
        lambda cfg: train_mnist(cfg, num_epochs, num_workers, callbacks,
                                smoke=smoke, agents=agents),
        config=config, num_samples=num_samples,
        metric="loss", mode="min",
        resources_per_trial={"cpu": 1, "extra_cpu": num_workers},
        name="tune_mnist")
    print("Best hyperparameters found were:", analysis.best_config)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=1,
                        help="Number of data-parallel shards (devices).")
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--num-samples", type=int, default=10,
                        help="Tune trials.")
    parser.add_argument("--use-gpu", action="store_true",
                        help="Accepted for reference parity; ignored on TPU.")
    parser.add_argument("--tune", action="store_true")
    parser.add_argument("--smoke-test", action="store_true")
    parser.add_argument("--address", type=str, default=None,
                        help="Comma-separated rla-tpu agent addresses "
                             "(host:port per machine) for multi-host runs; "
                             "the analog of the reference's ray cluster "
                             "address (reference: "
                             "examples/ray_ddp_example.py:160).")
    args = parser.parse_args()

    if args.smoke_test:
        args.num_epochs, args.num_samples = 1, 1
    agents = ([a.strip() for a in args.address.split(",") if a.strip()]
              if args.address else None)

    if args.tune:
        tune_mnist(args.num_samples, args.num_epochs, args.num_workers,
                   smoke=args.smoke_test, agents=agents)
    else:
        config = {"layer_1": 128, "layer_2": 256, "lr": 1e-3,
                  "batch_size": 128}
        trainer = train_mnist(config, args.num_epochs, args.num_workers,
                              smoke=args.smoke_test, agents=agents)
        print("final metrics:", trainer.callback_metrics)
