"""MNIST via the hosts x slots accelerator (capability parity with reference
examples/ray_horovod_example.py:63-196 -- same --num-hosts/--num-slots CLI).
On TPU the ring-allreduce protocol is XLA's collectives over ICI; the
hosts x slots topology maps to (DCN processes) x (local chips)."""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # runnable as a script from anywhere
from ray_lightning_accelerators_tpu import (HorovodRayAccelerator, Trainer,
                                            TuneReportCallback, tune)
from ray_lightning_accelerators_tpu.models.mnist import (MNISTClassifier,
                                                         MNISTDataModule)


def train_mnist(config, num_epochs=10, num_hosts=1, num_slots=1,
                callbacks=None, smoke=False):
    model = MNISTClassifier(config)
    dm = MNISTDataModule(batch_size=config["batch_size"],
                         n_train=2048 if smoke else 55000,
                         n_val=512 if smoke else 5000)
    trainer = Trainer(
        max_epochs=num_epochs, callbacks=list(callbacks or []),
        accelerator=HorovodRayAccelerator(num_hosts=num_hosts,
                                          num_slots=num_slots),
        default_root_dir=os.path.join(tempfile.gettempdir(),
                                      "rla_tpu_horovod"))
    trainer.fit(model, datamodule=dm)
    return trainer


def tune_mnist(num_samples=10, num_epochs=10, num_hosts=1, num_slots=1,
               smoke=False):
    config = {
        "layer_1": tune.choice([32, 64, 128]),
        "layer_2": tune.choice([64, 128, 256]),
        "lr": tune.loguniform(1e-4, 1e-1),
        "batch_size": tune.choice([32, 64, 128]),
    }
    metrics = {"loss": "ptl/val_loss", "acc": "ptl/val_accuracy"}
    callbacks = [TuneReportCallback(metrics, on="validation_end")]
    analysis = tune.run(
        lambda cfg: train_mnist(cfg, num_epochs, num_hosts, num_slots,
                                callbacks, smoke),
        config=config, num_samples=num_samples, metric="loss", mode="min",
        name="tune_mnist_horovod")
    print("Best hyperparameters found were:", analysis.best_config)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-hosts", type=int, default=1)
    parser.add_argument("--num-slots", type=int, default=1)
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--num-samples", type=int, default=10)
    parser.add_argument("--use-gpu", action="store_true",
                        help="Accepted for reference parity; ignored on TPU.")
    parser.add_argument("--tune", action="store_true")
    parser.add_argument("--smoke-test", action="store_true")
    args = parser.parse_args()
    if args.smoke_test:
        args.num_epochs, args.num_samples = 1, 1
    if args.tune:
        tune_mnist(args.num_samples, args.num_epochs, args.num_hosts,
                   args.num_slots, args.smoke_test)
    else:
        config = {"layer_1": 128, "layer_2": 256, "lr": 1e-3,
                  "batch_size": 128}
        trainer = train_mnist(config, args.num_epochs, args.num_hosts,
                              args.num_slots, smoke=args.smoke_test)
        print("final metrics:", trainer.callback_metrics)
