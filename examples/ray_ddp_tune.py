"""Tune-only MNIST example with a per-run init_hook (capability parity with
reference examples/ray_ddp_tune.py:17-125, whose init_hook FileLock-downloads
the dataset on every node :24-39)."""

import argparse
import os
import sys
import tempfile

from filelock import FileLock

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # runnable as a script from anywhere
from ray_lightning_accelerators_tpu import (RayTPUAccelerator, Trainer,
                                            TuneReportCallback, tune)
from ray_lightning_accelerators_tpu.models.mnist import (MNISTClassifier,
                                                         MNISTDataModule)

DATA_SENTINEL = os.path.join(tempfile.gettempdir(), "rla_tpu_mnist_ready")


def prepare_data():
    """Runs once per process under a lock -- the init_hook exemplar."""
    with FileLock(DATA_SENTINEL + ".lock"):
        if not os.path.exists(DATA_SENTINEL):
            open(DATA_SENTINEL, "w").write("ok")


def train_mnist(config, num_epochs=10, num_workers=1, smoke=False):
    model = MNISTClassifier(config)
    dm = MNISTDataModule(batch_size=config["batch_size"],
                         n_train=2048 if smoke else 55000,
                         n_val=512 if smoke else 5000)
    metrics = {"loss": "ptl/val_loss", "acc": "ptl/val_accuracy"}
    trainer = Trainer(
        max_epochs=num_epochs,
        callbacks=[TuneReportCallback(metrics, on="validation_end")],
        # tune.trial_devices() is this trial's device partition under
        # --parallel-trials; None (= all devices) otherwise
        accelerator=RayTPUAccelerator(num_workers=num_workers,
                                      devices=tune.trial_devices(),
                                      init_hook=prepare_data),
        default_root_dir=os.path.join(tempfile.gettempdir(), "rla_tpu_tune"))
    trainer.fit(model, datamodule=dm)


def _trial_main(cfg, num_epochs, num_workers, smoke):
    train_mnist(cfg, num_epochs, num_workers, smoke)


def tune_mnist(num_samples=10, num_epochs=10, num_workers=1, smoke=False,
               parallel_trials=1, use_tpe=False, agents=None):
    config = {
        "layer_1": tune.choice([32, 64, 128]),
        "layer_2": tune.choice([64, 128, 256]),
        "lr": tune.loguniform(1e-4, 1e-1),
        "batch_size": tune.choice([32, 64, 128]),
    }
    # --address places whole trials across cluster hosts (the reference's
    # trials-anywhere placement, examples/ray_ddp_example.py:101-113):
    # process-isolated trials round-robin over the agents, reporting
    # through the network queue
    import functools
    trainable = functools.partial(_trial_main, num_epochs=num_epochs,
                                  num_workers=num_workers, smoke=smoke)
    analysis = tune.run(
        trainable,
        config=config, num_samples=num_samples, metric="loss", mode="min",
        search_alg=tune.TPESearcher(seed=0) if use_tpe else None,
        max_concurrent_trials=parallel_trials,
        trial_executor="process" if agents else "thread",
        agents=agents,
        name="tune_mnist")
    print("Best hyperparameters found were:", analysis.best_config)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--num-samples", type=int, default=10)
    parser.add_argument("--parallel-trials", type=int, default=1,
                        help="run N trials concurrently on disjoint "
                             "device partitions")
    parser.add_argument("--tpe", action="store_true",
                        help="model-based TPE search instead of random")
    parser.add_argument("--address", default=None,
                        help="comma-separated host agents "
                             "(host:port,...) to place PROCESS trials "
                             "across machines")
    parser.add_argument("--smoke-test", action="store_true")
    args = parser.parse_args()
    if args.smoke_test:
        args.num_epochs, args.num_samples = 1, 1
    tune_mnist(args.num_samples, args.num_epochs, args.num_workers,
               args.smoke_test, parallel_trials=args.parallel_trials,
               use_tpe=args.tpe,
               agents=args.address.split(",") if args.address else None)
