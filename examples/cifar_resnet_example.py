"""CIFAR-10 ResNet-18 training example -- BASELINE config #3
("RayTPUAccelerator num_hosts=2 num_workers=8, CIFAR-10 ResNet18").

Single-host it data-shards over all visible chips; on a pod slice the same
script runs per-host under `runtime.bootstrap` and the mesh spans hosts
(DCN) x chips (ICI).  CLI mirrors the reference example's flags
(reference: examples/ray_ddp_example.py:118-150)."""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # runnable as a script from anywhere
from ray_lightning_accelerators_tpu import RayTPUAccelerator, Trainer
from ray_lightning_accelerators_tpu.models.resnet import (CIFAR10DataModule,
                                                          ResNet18)


def train_cifar(config, num_epochs=10, num_workers=None, use_fsdp=False,
                smoke=False):
    model = ResNet18(config)
    dm = CIFAR10DataModule(batch_size=config.get("batch_size", 256),
                           n_train=1024 if smoke else 50000,
                           n_val=256 if smoke else 10000)
    trainer = Trainer(max_epochs=num_epochs,
                      accelerator=RayTPUAccelerator(num_workers=num_workers,
                                                    use_fsdp=use_fsdp),
                      precision="bf16",
                      default_root_dir=os.path.join(tempfile.gettempdir(),
                                                    "rla_tpu_cifar"),
                      enable_progress_bar=True)
    trainer.fit(model, datamodule=dm)
    print("final metrics:", trainer.callback_metrics)
    return trainer


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-workers", type=int, default=None,
                        help="data-parallel shards (default: all devices)")
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--use-fsdp", action="store_true")
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--smoke-test", action="store_true")
    args = parser.parse_args()
    train_cifar({"lr": args.lr, "batch_size": args.batch_size},
                num_epochs=1 if args.smoke_test else args.num_epochs,
                num_workers=args.num_workers, use_fsdp=args.use_fsdp,
                smoke=args.smoke_test)
