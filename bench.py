"""Benchmarks through the full framework.  One JSON line per metric:
{"metric", "value", "unit", "vs_baseline", ...}.

- ``mnist``  (headline, BASELINE.json north star): imgs/sec/chip training
  the MNISTClassifier example end-to-end through Trainer +
  RayTPUAccelerator.  Baseline constant: 25_000 imgs/sec -- a single-A100
  PTL+DDP run of this 3-layer-MLP example is input-pipeline-bound in that
  regime (BASELINE.json target: ">= single-A100 DDP throughput").
- ``gpt``    (flagship compute bench): tokens/sec/chip + MFU training a
  GPT-2-small-class model (124M params, seq 1024, bf16, fused LM-head
  loss, flash attention).  FLOPs/token uses the PaLM-appendix formula
  6*N + 12*L*d_model*S (matmul params + attention); peak FLOP/s comes
  from utils.profiler.mfu's per-chip table (v5e-class: 197 TFLOP/s
  bf16).  vs_baseline is MFU against the 0.35 driver bar.
- ``cifar``  (BASELINE.md config #3, single-chip): ResNet18 imgs/sec/chip
  + val_acc.
- ``decode`` (inference): GPT-2-small greedy KV-cache decode tokens/sec
  (bf16 headline, int8 weight-only ratio), with vs_baseline measured
  against this chip's own weight-streaming roofline probed with a
  matmul-shaped read (the access pattern decode actually has).

- ``gradexchange`` / ``input_pipeline`` / ``fsdp_exchange`` /
  ``paged_serve`` / ``mfu_overlap`` / ``perf_observatory`` /
  ``live_plane`` / ``serve_resilience`` / ``long_context``
  (CPU-mesh subprocess benches):
  quantized-allreduce wire-bytes reduction, async-input-pipeline
  prefetch speedup, compressed-FSDP exchange, paged-KV-cache
  concurrency-per-HBM, the overlap-aware scan-gather + step autotune
  loop, the perf-observatory ledgers, the live telemetry plane, and
  the serve-tier chaos-resilience window, each measured by a
  self-contained probe script that forces an 8-device host-platform
  CPU mesh before backend init.  They double as the dead-backend
  fallback set: a window whose accelerator probe fails still emits
  their real metric lines and exits 0.

Each timed region is the steady state of a single public-API ``fit`` --
epoch 1 absorbs compile + the one-time device-cache shipment, later epochs
measure the loop the way a user runs it (device-resident gather feeding a
donated, jitted train step).

The reference publishes no numbers anywhere (BASELINE.md); baselines here
are the driver-defined bars.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

BASELINE_MNIST_IMGS_PER_SEC = 25_000.0
GPT_MFU_TARGET = 0.35
BASELINE_CIFAR_IMGS_PER_SEC = 2_500.0  # single-A100 PTL+DDP ResNet18/CIFAR

# Backend-death markers: one bench failing this way means every later
# bench would re-attempt (and possibly hang) the same dead init.
# _CERTAIN are init-phase failures (the backend never came up);
# _SUSPECT strings also appear in transient bench-local gRPC errors, so
# they abort only after a re-probe confirms the backend is really gone.
_BACKEND_DEAD_CERTAIN = ("Unable to initialize backend",
                         "failed to initialize backend")
_BACKEND_DEAD_SUSPECT = ("No visible devices", "UNAVAILABLE")

_PROBE_SRC = """
import jax, numpy as np
x = jax.numpy.ones((128, 128))
v = float(np.asarray(jax.device_get((x @ x).sum())))
print("PROBE_OK", v, [str(d) for d in jax.devices()], flush=True)
"""


def _terminate(proc) -> str:
    """SIGTERM-first kill: a SIGKILLed process mid-device-claim can
    wedge the tunnel harder (the claim is never released); give the
    child a grace period to run its handlers before the hard kill.
    Returns whatever stdout the child produced."""
    proc.terminate()
    try:
        out, _ = proc.communicate(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
    return out or ""


def _flight_diagnosis(child_out: str, child_err: str,
                      timed_out: bool = False) -> dict:
    """Wedge-vs-dead triage embedded in the ``backend_probe`` record, so
    the BENCH JSON alone distinguishes a wedged device tunnel from a
    plainly dead backend.  Stdlib-only by design: it reads the
    flight-recorder SPILL FILES (``RLA_TPU_TELEMETRY_DIR``) directly —
    this very record is written precisely when importing/initializing
    jax is what hangs.

    - ``stall``: classification from the probe child's own output — a
      child that printed NOTHING before the timeout hung inside backend
      init (the wedged-tunnel shape: the device claim never returns);
      one that produced output reached python and then stalled/failed
      (a dead or mid-run-dying backend).
    - ``flight_tail``: the last events of every rank's spill file from
      the most recent run on this machine (empty when no telemetry dir
      is configured) — the driver-side breadcrumb trail of whatever ran
      last against this backend."""
    produced = bool((child_out or "").strip() or (child_err or "").strip())
    # the wedge verdict needs BOTH signals: only a child that ran out
    # its whole timeout without producing anything looks like a hung
    # device claim — a fast silent death (segfault/OOM on import) is a
    # dead backend, not a wedge
    if timed_out and not produced:
        cls, detail = "wedged-tunnel", (
            "probe child produced no output before the timeout: hung "
            "inside backend init (device claim never returned)")
    elif timed_out:
        cls, detail = "dead-backend", (
            "probe child reached python and produced output before "
            "stalling past the timeout: backend answered, then died")
    else:
        cls, detail = "dead-backend", (
            "probe child exited promptly"
            + ("" if produced else " with no output (killed during "
               "init? segfault/OOM)")
            + ": backend failed rather than hung")
    diag: dict = {"stall": {
        "classification": cls,
        "detail": detail,
        "child_output_tail": ((child_err or "") + (child_out or ""))[-300:],
    }}
    tdir = os.environ.get("RLA_TPU_TELEMETRY_DIR")
    tails = {}
    if tdir and os.path.isdir(tdir):
        for fname in sorted(os.listdir(tdir)):
            if not fname.endswith(".events.json"):
                continue
            try:
                with open(os.path.join(tdir, fname)) as f:
                    snap = json.load(f)
            except (OSError, ValueError):
                continue  # torn mid-write: expected near a crash
            if isinstance(snap, dict):
                label = fname[:-len(".events.json")]
                tails[label] = (snap.get("events") or [])[-8:]
    if tails:
        diag["flight_tail"] = tails
    return diag


def _death_record(detail: str, failed_bench: str, probe_err: dict) -> str:
    return json.dumps(
        {"metric": "backend_probe", "value": 0, "unit": "alive",
         "vs_baseline": 0.0, "error": "backend died mid-run",
         "detail": detail[-500:], "failed_bench": failed_bench,
         **{"probe_" + k: v for k, v in probe_err.items()}})


def probe_backend(timeout_s: float) -> dict | None:
    """Bounded-time liveness check of the JAX backend, in a subprocess.

    A wedged device tunnel makes backend init hang indefinitely (the
    round-4 driver run burned 25 minutes on exactly that before its
    timeout killed the whole bench with zero output).  Touching the
    device from a child process first means a hang costs ``timeout_s``
    seconds, after which the parent -- which has not imported jax yet --
    can still emit machine-readable output.  Returns None when the
    backend is live, else an error record ready to print as JSON."""
    t0 = time.perf_counter()
    proc = subprocess.Popen([sys.executable, "-c", _PROBE_SRC],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        partial = _terminate(proc)
        # wedge-vs-dead triage + flight-recorder tail, embedded so the
        # BENCH JSON alone says WHICH failure mode this window hit
        return {"error": "backend unavailable",
                "detail": f"device probe hung > {timeout_s:.0f}s "
                          "(wedged tunnel?)",
                "probe_seconds": round(time.perf_counter() - t0, 1),
                **_flight_diagnosis(partial, "", timed_out=True)}
    if proc.returncode != 0 or "PROBE_OK" not in out:
        tail = (err or out).strip().splitlines()[-3:]
        return {"error": "backend unavailable",
                "detail": " | ".join(tail)[-500:],
                "probe_seconds": round(time.perf_counter() - t0, 1),
                **_flight_diagnosis(out, err)}
    return None


class _EpochClock:
    """Wall time at train-epoch boundaries, honestly device-synced.

    The sync is a 4-byte host readback of the step counter -- the scalar
    is produced by the epoch's last dispatched step, so reading it drains
    the device queue.  (``block_until_ready`` is NOT trusted here: through
    a tunneled PjRt client it can return before the device work ran.)
    Marks at epoch start AND end keep the timed window free of fit()'s
    final full-parameter download.

    Also snapshots the compile-guard counter at every boundary, so the
    steady-state window carries its own bench-honesty record: a nonzero
    ``window_compiles()`` means a retrace landed inside the timed epochs
    and the step time is polluted."""

    def __init__(self, base):
        import jax
        import numpy as np

        from ray_lightning_accelerators_tpu.analysis import (
            compile_guard as cg)

        class _CB(base):
            def __init__(cb_self):
                cb_self.starts = []
                cb_self.ends = []
                cb_self.compiles_at_start = []
                cb_self.compiles_at_end = []

            def _sync(cb_self, trainer):
                if trainer._state is not None:
                    int(np.asarray(jax.device_get(trainer._state.step)))
                return time.perf_counter()

            def on_train_epoch_start(cb_self, trainer, module):
                cb_self.starts.append(cb_self._sync(trainer))
                cb_self.compiles_at_start.append(cg.compile_count())

            def on_train_epoch_end(cb_self, trainer, module):
                cb_self.ends.append(cb_self._sync(trainer))
                cb_self.compiles_at_end.append(cg.compile_count())

        self.cb = _CB()

    def steady_state_seconds(self) -> float:
        """Epoch-2-start .. last-epoch-end (epoch 1 absorbs compile)."""
        return self.cb.ends[-1] - self.cb.starts[1]

    def window_compiles(self) -> int:
        """Backend compiles landing inside the timed window (0 = clean)."""
        return self.cb.compiles_at_end[-1] - self.cb.compiles_at_start[1]


def bench_mnist() -> dict:
    import jax
    import numpy as np

    from ray_lightning_accelerators_tpu import (Callback, DataLoader,
                                                RayTPUAccelerator, Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.models.mnist import (MNISTClassifier,
                                                             synthetic_mnist)

    import os

    n_devices = jax.device_count()
    batch_size = 1024 * n_devices
    n_images = batch_size * 24
    # real data source order: a mounted dir (RLA_TPU_DATA_DIR), then the
    # committed 1024-image real-MNIST IDX subset under tests/data/mnist
    # (the no-mount fallback, tiled to bench size below) -- the throughput
    # number should say "real" wherever real pixels are available, like
    # the reference's real-MNIST accuracy gate
    # (/root/reference/ray_lightning/tests/utils.py:137-152)
    from ray_lightning_accelerators_tpu.data import vision
    real = None
    source = None
    data_dir = os.environ.get("RLA_TPU_DATA_DIR")
    if data_dir:
        real = vision.load_mnist(data_dir, "train")
        source = "real"
    if real is None:
        bundled = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "tests", "data", "mnist")
        real = vision.load_mnist(bundled, "train")
        if real is not None:
            # distinct label: real pixels, but a small committed subset
            # tiled to bench size -- cross-round comparisons must be able
            # to tell this regime from a full mounted dataset
            source = f"real-tiled-{len(real[0])}"
    if real is not None:
        x, y = real
        reps = -(-n_images // len(x))  # tile up to the bench size
        x = np.tile(x, (reps, 1, 1))[:n_images]
        y = np.tile(y, reps)[:n_images]
    else:
        x, y = synthetic_mnist(n_images, seed=0)
        source = "synthetic"
    loader = DataLoader(ArrayDataset(x, y), batch_size=batch_size,
                        shuffle=True)

    model = MNISTClassifier({"layer_1": 128, "layer_2": 256, "lr": 1e-3,
                             "batch_size": batch_size})
    clock = _EpochClock(Callback)
    epochs = 5
    trainer = Trainer(max_epochs=epochs, accelerator=RayTPUAccelerator(),
                      precision="bf16", enable_checkpointing=False,
                      log_every_n_steps=10 ** 9, seed=0,
                      callbacks=[clock.cb],
                      default_root_dir="/tmp/rla_tpu_bench")
    trainer.fit(model, loader)

    steps_per_epoch = len(loader)
    dt = clock.steady_state_seconds()
    imgs = batch_size * steps_per_epoch * (epochs - 1)
    per_chip = imgs / dt / n_devices
    return {
        "metric": "mnist_mlp_train_imgs_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "imgs/sec/chip",
        "data": source,
        "vs_baseline": round(per_chip / BASELINE_MNIST_IMGS_PER_SEC, 3),
    }


def bench_gpt() -> dict:
    # tuned config (XPlane-traced, BASELINE.md roofline): 1024x1024 flash
    # blocks amortize per-grid-cell overhead (fwd 18 -> 9.6 ms/step);
    # 2048-row loss chunks pipeline the LM-head scan best (measured
    # faster than 1024/4096/8192); 24 steps/epoch amortizes the one
    # dispatch+sync each scanned epoch pays over the tunneled link.
    # Falls back to the round-3 config if the tuned kernels fail to
    # compile on this backend -- a conservative number beats none.
    try:
        return _bench_gpt(loss_chunk=2048, flash_block=1024,
                          steps_per_epoch=24)
    except Exception as e:
        print(f"bench gpt tuned config failed ({type(e).__name__}: {e}); "
              "retrying conservative config", file=sys.stderr, flush=True)
        out = _bench_gpt(loss_chunk=4096, flash_block=512,
                         steps_per_epoch=12)
        out["config"] = "fallback-r3"
        return out


def _bench_gpt(loss_chunk: int, flash_block: int,
               steps_per_epoch: int, per_chip_batch: int = 16,
               remat: bool = False, remat_policy: str = "nothing",
               tiny: bool = False, small: bool = False, epochs: int = 3,
               use_fsdp: bool = False, gather_mode: str = "tree",
               grad_compression: str | None = None,
               int8_matmul: bool = False,
               precision: str = "bf16") -> dict:
    """One bench-shaped GPT training measurement.  The extra knobs serve
    scripts/mfu_sweep.py's variant ladder; keeping them HERE means every
    sweep number is produced under exactly the timed-window/sync
    discipline the driver's bench uses (``tiny`` shrinks the model for
    CPU plumbing smokes; ``small`` is the CPU-mesh-measurable middle
    size the overlap probe uses — enough layers/params for the gather
    schedule to matter, small enough for an 8-device host CPU mesh;
    MFU is meaningless for both).

    ``use_fsdp``/``gather_mode``/``grad_compression`` engage the
    compressed-FSDP step (parallel/collectives.py): "tree" all-gathers
    the whole bf16 param tree before the forward, "scan" overlaps a
    layer-wise gather inside the transformer scan.  ``int8_matmul``
    routes the MLP projections through int8 forward matmuls with
    straight-through gradients (ops/quant.py)."""
    import jax
    import numpy as np

    from ray_lightning_accelerators_tpu import (Callback, DataLoader,
                                                RayTPUAccelerator, Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    from ray_lightning_accelerators_tpu.utils import profiler as prof

    n_devices = jax.device_count()
    seq = 256 if tiny else (128 if small else 1024)
    if tiny:
        per_chip_batch = min(per_chip_batch, 2)
    if small:
        per_chip_batch = min(per_chip_batch, 4)
    batch = per_chip_batch * n_devices
    if tiny:
        dims = dict(vocab_size=512, d_model=128, n_heads=4, d_ff=512,
                    n_layers=2)
    elif small:
        dims = dict(vocab_size=2048, d_model=192, n_heads=6, d_ff=768,
                    n_layers=6)
    else:
        dims = dict(vocab_size=50304, d_model=768, n_heads=12, d_ff=3072,
                    n_layers=12)
    cfg = TransformerConfig(**dims, max_seq_len=seq,
                            fused_loss=True, loss_chunk_rows=loss_chunk,
                            flash_block_q=flash_block,
                            flash_block_k=flash_block,
                            remat=remat, remat_policy=remat_policy)
    model = GPT(cfg, lr=3e-4)
    n_seqs = batch * steps_per_epoch
    tokens = np.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size,
                                          size=(n_seqs, seq)),
        dtype=np.int32)
    loader = DataLoader(ArrayDataset(tokens), batch_size=batch,
                        shuffle=False)

    clock = _EpochClock(Callback)
    trainer = Trainer(max_epochs=epochs,
                      accelerator=RayTPUAccelerator(use_fsdp=use_fsdp),
                      precision=precision, enable_checkpointing=False,
                      log_every_n_steps=10 ** 9, seed=0,
                      callbacks=[clock.cb],
                      grad_compression=grad_compression,
                      gather_mode=gather_mode, int8_matmul=int8_matmul,
                      default_root_dir="/tmp/rla_tpu_bench_gpt")
    trainer.fit(model, loader)

    dt = clock.steady_state_seconds()
    timed_steps = steps_per_epoch * (epochs - 1)
    tokens_done = batch * seq * timed_steps
    tok_per_sec_chip = tokens_done / dt / n_devices
    step_time = dt / timed_steps

    # PaLM-appendix train FLOPs: 6*N per matmul param-touch (fwd + 2x bwd)
    # + 12*L*d_model*S attention per token.  N counts matmul params (norm
    # scales are negligible; the tied embedding is counted once, covering
    # the unembedding matmul).
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(model.params))
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * cfg.d_model * seq
    flops_per_step = flops_per_token * batch * seq
    mfu = prof.mfu(flops_per_step / n_devices, step_time)
    rec = {
        "metric": "gpt2s_124m_train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        "mfu": round(mfu, 4),
        "step_ms": round(step_time * 1e3, 1),
        "params": n_params,
        "seq_len": seq,
        "measured_window_compiles": clock.window_compiles(),
        "peak_flops_note": "per-chip bf16 peak from device_kind "
                           "(v5e-class 197e12)",
        "vs_baseline": round(mfu / GPT_MFU_TARGET, 3),
    }
    if use_fsdp and grad_compression is not None:
        # the exposed-vs-hidden wire split for THIS step's gather mode
        # (collectives.wire_bytes_per_step via the trainer's record)
        comms = trainer.comms_per_step or {}
        for k in ("gather_mode", "exposed_bytes_per_step",
                  "hidden_bytes_per_step"):
            if k in comms:
                rec[k] = comms[k]
    return rec


def bench_cifar() -> dict:
    import jax
    import numpy as np

    from ray_lightning_accelerators_tpu import (Callback, DataLoader,
                                                RayTPUAccelerator, Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.models.resnet import (
        CIFAR10DataModule, ResNet18)

    import os

    n_devices = jax.device_count()
    batch = 256 * n_devices
    dm = CIFAR10DataModule(batch_size=batch, n_train=batch * 12,
                           n_val=batch * 2,
                           data_dir=os.environ.get("RLA_TPU_DATA_DIR"))
    dm.setup("fit")

    # lr 0.01: stable convergence on this short synthetic run -- higher
    # rates sit in a chaotic regime where val_acc depends on rounding
    # noise (verified: at 0.02-0.05 both executor paths land anywhere in
    # [0.09, 0.93] run to run)
    model = ResNet18({"lr": 0.01, "batch_size": batch})
    clock = _EpochClock(Callback)
    epochs = 4
    trainer = Trainer(max_epochs=epochs, accelerator=RayTPUAccelerator(),
                      precision="bf16", enable_checkpointing=False,
                      log_every_n_steps=10 ** 9, seed=0,
                      callbacks=[clock.cb],
                      default_root_dir="/tmp/rla_tpu_bench_cifar")
    # train-only fit so the timed window holds pure training steps;
    # validation runs once afterwards for the accuracy gate
    train_loader = dm.train_dataloader()
    trainer.fit(model, train_loader)
    steps_per_epoch = len(train_loader)
    dt = clock.steady_state_seconds()
    imgs = batch * steps_per_epoch * (epochs - 1)
    per_chip = imgs / dt / n_devices
    val_metrics = trainer.validate(model, dm.val_dataloader())[0]
    val_acc = float(val_metrics.get("val_accuracy", 0.0))
    return {
        "metric": "cifar_resnet18_train_imgs_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "imgs/sec/chip",
        "val_acc": round(val_acc, 4),
        # CIFAR10DataModule.source: "real" when local CIFAR-10 binaries
        # were found, "synthetic" otherwise
        "data": getattr(dm, "source", "synthetic"),
        "vs_baseline": round(per_chip / BASELINE_CIFAR_IMGS_PER_SEC, 3),
    }


def bench_decode() -> dict:
    """Autoregressive decode throughput on the GPT-2-small class model:
    batch-16 greedy generation through the single-scan KV-cache decode
    path, bf16 weights (headline) and int8 weight-only (ratio field).
    vs_baseline is decode efficiency against THIS chip's own
    weight-streaming roofline, measured in-bench: ideal tokens/sec =
    batch * HBM_GB/s / bf16_param_bytes (every token re-reads every
    weight) -- self-contained, no invented external bar."""
    import time as time_mod

    import jax
    import numpy as np

    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)

    import functools

    import jax.numpy as jnp

    cfg = TransformerConfig(vocab_size=50304, d_model=768, n_heads=12,
                            d_ff=3072, n_layers=12, max_seq_len=512)
    model = GPT(cfg, lr=3e-4)
    model.compute_dtype = jnp.bfloat16
    # bf16 STORAGE too (the deployment layout the headline claims; init
    # builds f32 masters)
    params = jax.device_put(jax.tree.map(
        lambda p: p.astype(jnp.bfloat16), model.init_params(
            jax.random.PRNGKey(0))))
    prompt = np.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (16, 128)),
        dtype=np.int32)
    new_tokens = 128

    # one compiled program per params-structure: jit the whole generate so
    # repetitions skip tracing and eager per-op dispatch
    gen = jax.jit(functools.partial(model.generate,
                                    max_new_tokens=new_tokens,
                                    temperature=0.0))

    def timed(p, n=3):
        np.asarray(gen(p, prompt))  # compile + warmup
        t0 = time_mod.perf_counter()
        for _ in range(n):
            out = gen(p, prompt)
        np.asarray(out)  # host readback = honest sync
        return (time_mod.perf_counter() - t0) / n

    dt_bf16 = timed(params)
    q8 = GPT.quantize_weights(params)
    q8_config = "q8-kernel"
    declined_before = set(GPT._q8_declined_shapes)
    try:
        dt_q8 = timed(q8)  # int8 Pallas kernels (ops/quant.py) on TPU
    except Exception as e:
        # kernel failed to compile on this backend: fall back to the XLA
        # dequant path so the headline still lands -- TAGGED in the
        # record, so an int8_ratio near 1.0 is self-explaining
        print(f"bench decode int8 kernel failed ({type(e).__name__}: "
              f"{e}); falling back to dequant", file=sys.stderr,
              flush=True)
        q8_config = "fallback-dequant"
        saved = os.environ.get("RLA_TPU_DISABLE_Q8_KERNEL")
        os.environ["RLA_TPU_DISABLE_Q8_KERNEL"] = "1"
        try:
            gen = jax.jit(functools.partial(model.generate,
                                            max_new_tokens=new_tokens,
                                            temperature=0.0))
            dt_q8 = timed(q8)
        finally:  # scope the override to this timing, not the process
            if saved is None:
                os.environ.pop("RLA_TPU_DISABLE_Q8_KERNEL", None)
            else:
                os.environ["RLA_TPU_DISABLE_Q8_KERNEL"] = saved
    if q8_config == "q8-kernel":
        # the kernels can be skipped WITHOUT raising: mode None (wrong
        # backend / env disable) or per-shape declines fall back to XLA
        # dequant silently -- the tag must say so, or an int8_ratio near
        # 1.0 looks like "kernels ran and didn't help"
        if model._q8_kernel_mode() is None:
            q8_config = "fallback-dequant"
        else:
            declines = GPT._q8_declined_shapes - declined_before
            if declines:
                q8_config = (f"q8-kernel-partial:"
                             f"{len(declines)}-shapes-declined")
    tps_bf16 = prompt.shape[0] * new_tokens / dt_bf16
    tps_q8 = prompt.shape[0] * new_tokens / dt_q8

    # this chip's own weight-streaming roofline, measured with a
    # MATMUL-shaped probe -- decode's actual access pattern is a small
    # activation block multiplying a stream of weight matrices into the
    # MXU, which this chip moves faster than a reduce-style read (round
    # 2's reduce probe under-read at 27 GB/s and made decode "beat" its
    # own roofline by 52%; a ratio > 1 against a physical ceiling is a
    # probe bug, not a win).  Chain several passes and sync ONCE at the
    # end -- a per-call sync would bill tunnel round-trips to bandwidth.
    L, d = 48, 2048
    w_stack = jnp.ones((L, d, d), jnp.bfloat16) / d  # 384 MB
    xact = jnp.ones((prompt.shape[0], d), jnp.bfloat16)

    def stream(x, s):
        def body(carry, w):
            return (carry @ w).astype(jnp.bfloat16), ()
        out, _ = jax.lax.scan(body, x, w_stack)
        return out.astype(jnp.float32).sum() + s

    reader = jax.jit(stream)
    float(reader(xact, jnp.float32(0)))  # warmup/compile
    reps = 12
    best = float("inf")
    for _ in range(3):
        t0 = time_mod.perf_counter()
        acc = jnp.float32(0)
        for _ in range(reps):
            acc = reader(xact, acc)
        float(acc)
        best = min(best, time_mod.perf_counter() - t0)
    stream_bps = reps * w_stack.nbytes / best
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(params))
    # ideal decode: every token re-reads every bf16 weight byte at the
    # measured matmul-stream rate (KV-cache traffic ignored -- it only
    # LOWERS attainable tokens/sec, keeping this a true ceiling)
    roofline_tps = prompt.shape[0] * stream_bps / (2 * n_params)
    return {
        "metric": "gpt2s_124m_decode_tokens_per_sec_per_chip",
        "value": round(tps_bf16, 1),
        "unit": "tokens/sec/chip",
        "int8_ratio": round(tps_q8 / tps_bf16, 3),
        "int8_config": q8_config,
        "batch": prompt.shape[0],
        "weight_stream_gbps_measured": round(stream_bps / 1e9, 1),
        "vs_baseline": round(tps_bf16 / roofline_tps, 3),
    }


def _last_metric_record(stdout: str):
    """Newest JSON line of probe stdout that is an actual METRIC record
    (has a ``value`` key) -- probes also emit bench-honesty compile-count
    records, which must never displace the metric.  Falls back to the
    newest JSON line of any kind so probe error records still surface."""
    fallback = None
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "value" in rec:
            return rec
        if fallback is None:
            fallback = rec
    return fallback


def _run_cpu_probe(script_name: str, label: str) -> dict:
    """Run one of the forced-host-platform CPU-mesh probe scripts in a
    FRESH subprocess and return its newest value-bearing JSON line.  The
    probes force ``JAX_PLATFORMS=cpu`` before backend init, so they
    produce a real number even on a machine whose accelerator backend is
    dead — which is why these benches double as the probe-failure
    fallback set in ``main`` and never touch a possibly-wedged tunnel."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", script_name)
    proc = subprocess.run([sys.executable, script], capture_output=True,
                          text=True, timeout=600)
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
        raise RuntimeError(
            f"{label} probe failed (rc {proc.returncode}): "
            + " | ".join(tail))
    rec = _last_metric_record(proc.stdout)
    if rec is None:
        raise RuntimeError(f"{label} probe produced no JSON record")
    return rec


def bench_gradexchange() -> dict:
    """Gradient-exchange microbench (fp32 implicit-psum vs int8/bf16
    quantized allreduce, parallel/collectives.py): step time + bytes
    moved on a forced-host-platform 8-device CPU mesh (see
    ``_run_cpu_probe``)."""
    return _run_cpu_probe("gradexchange_probe.py", "gradexchange")


def bench_input_pipeline() -> dict:
    """Async-input-pipeline bench (prefetch_batches=2 vs 0 steps/s on a
    synthetic input-bound loader, data/prefetch.py): see
    ``_run_cpu_probe``."""
    return _run_cpu_probe("input_pipeline_probe.py", "input_pipeline")


def bench_fsdp_exchange() -> dict:
    """Compressed-FSDP exchange bench (int8 reduce-scatter into the shard
    owner + bf16 param all-gather vs fp32, parallel/collectives.py):
    wire-bytes ratio + measured per-shard peak state bytes vs a
    replicated layout, on a forced-host-platform 8-device CPU mesh (see
    ``_run_cpu_probe``)."""
    return _run_cpu_probe("fsdp_exchange_probe.py", "fsdp_exchange")


def bench_paged_serve() -> dict:
    """Paged-KV-cache serve bench (block pool + prefix reuse,
    serve/engine.py): concurrent sequences per placed cache byte vs the
    dense allocator on a mixed-length lognormal workload, plus the
    measured TTFT reduction prefix hits buy — on a forced-host-platform
    8-device CPU mesh (see ``_run_cpu_probe``)."""
    return _run_cpu_probe("paged_serve_probe.py", "paged_serve")


def bench_mfu_overlap() -> dict:
    """Overlap-aware FSDP gather bench (layer-wise param all-gather
    inside the transformer scan vs whole-tree up-front,
    parallel/collectives.py + the tune.autotune_step closed loop):
    scan/tree step-time ratio under remat + the analytic exposed-comm
    reduction AND the measured exposed-comm crosscheck, on a
    forced-host-platform 8-device CPU mesh (see ``_run_cpu_probe``)."""
    return _run_cpu_probe("mfu_overlap_probe.py", "mfu_overlap")


def bench_live_plane() -> dict:
    """Live-telemetry-plane bench (telemetry/live.py + serve/slo.py):
    a training fit scraped at ~20Hz through the live /metrics+/statusz
    endpoints (every scrape exposition-validated; overhead A/B'd), a
    serve SLO burn-rate contrast (overloaded nonzero, light zero, typed
    deadline sheds), and a 2-worker ClusterView rank-labeled merge —
    on a forced-host-platform 8-device CPU mesh (see
    ``_run_cpu_probe``)."""
    return _run_cpu_probe("live_plane_probe.py", "live_plane")


def bench_serve_resilience() -> dict:
    """Serve-tier resilience bench (serve/controller.py + replicas):
    completed-request fraction and p99 TTFT across a replica chaos
    window (1 replica killed + 1 hung mid-run, circuit-breaker
    auto-revival, head-of-line requeue with retry backoff) vs a
    no-chaos baseline — on a forced-host-platform 8-device CPU mesh
    (see ``_run_cpu_probe``)."""
    return _run_cpu_probe("serve_resilience_probe.py",
                          "serve_resilience")


def bench_perf_observatory() -> dict:
    """Perf-observatory bench (telemetry/perf.py): one 8-dev CPU-mesh
    training run whose per-step phase timeline, HBM pool ledger and
    goodput fraction (over an ElasticRunner run with one injected
    preemption) all land in a ``run_report.json`` + Prometheus export;
    the headline value is the named-phase coverage of measured step
    wall time (see ``_run_cpu_probe``)."""
    return _run_cpu_probe("perf_observatory_probe.py", "perf_observatory")


def bench_resize() -> dict:
    """Live-resize downtime bench (parallel/plan.py +
    parallel/redistribute.py + Trainer.resize_in_memory): one dp=8 fit
    interrupted at step 2 is recovered into a dp=4 world both ways —
    checkpoint round-trip vs in-memory redistribution — and the value is
    the downtime ratio (recovery entry → first completed dp=4 step;
    must be strictly > 1), on a forced-host-platform 8-device CPU mesh
    (see ``_run_cpu_probe``)."""
    return _run_cpu_probe("resize_probe.py", "resize")


def bench_pipeline() -> dict:
    """MPMD pipeline-bubble bench (parallel/mpmd/): one 1F1B fit over 2
    stage groups x 4 microbatches on spawned CPU workers with compute
    sized to dominate the handoff cost; the value is the bubble accuracy
    1 - |measured - analytic| / analytic against the analytic 1F1B
    bubble (S-1)/(M+S-1), steady-state steps only (must be > 0.8 —
    within 20% of analytic; see ``_run_cpu_probe``)."""
    return _run_cpu_probe("pipeline_probe.py", "pipeline")


def bench_long_context() -> dict:
    """Long-context fast-path bench (serve/engine.py chunked prefill +
    core/trainer.py seq_parallel): inter-token p99 ratio
    blocking/chunked while two 40-block prompts join three live decode
    streams (must be strictly > 1 — chunking protects decode cadence),
    with token-identity and zero-measured-window-compile evidence, plus
    the seq_parallel=2 (ulysses) train-loss parity rel-err as a field —
    on a forced-host-platform 8-device CPU mesh (see
    ``_run_cpu_probe``)."""
    return _run_cpu_probe("long_context_probe.py", "long_context")


def bench_prefix_affinity() -> dict:
    """Prefix-affinity routing bench (serve/controller.py +
    serve/engine.py): a skewed shared-prefix workload (4 hot 384-token
    prefix families, shuffled arrivals) is served by a 3-replica tier
    twice — least-loaded spray vs prefix-affinity routing — and the
    value is the steady-state p99 TTFT ratio least-loaded/affinity
    (must be strictly > 1), plus a disaggregated 1-prefill/2-decode
    lane pass whose decode cadence and KV-handoff counts ride along as
    fields, on a forced-host-platform CPU mesh (see
    ``_run_cpu_probe``)."""
    return _run_cpu_probe("prefix_affinity_probe.py", "prefix_affinity")


def bench_anomaly_guard() -> dict:
    """Numeric-guard bench (runtime/guardian.py + core/trainer.py
    in-step hooks): steady-state epoch-time ratio guarded/unguarded of
    the same tiny-GPT fit on the 8-device CPU mesh (must stay <= 1.05 —
    detection rides the existing metrics readback with zero extra syncs
    and zero retraces, pinned by the measured-window compile count),
    plus one full badbatch trip -> data blame -> quarantine -> resumed
    skip recovery timed as ``recovery_s`` (see ``_run_cpu_probe``)."""
    return _run_cpu_probe("anomaly_guard_probe.py", "anomaly_guard")


BENCHES = {"mnist": bench_mnist, "gpt": bench_gpt, "cifar": bench_cifar,
           "decode": bench_decode, "gradexchange": bench_gradexchange,
           "input_pipeline": bench_input_pipeline,
           "fsdp_exchange": bench_fsdp_exchange,
           "paged_serve": bench_paged_serve,
           "mfu_overlap": bench_mfu_overlap,
           "perf_observatory": bench_perf_observatory,
           "live_plane": bench_live_plane,
           "serve_resilience": bench_serve_resilience,
           "resize": bench_resize, "pipeline": bench_pipeline,
           "prefix_affinity": bench_prefix_affinity,
           "long_context": bench_long_context,
           "anomaly_guard": bench_anomaly_guard}

if os.environ.get("RLA_TPU_BENCH_SELFTEST"):
    # jax-free fixtures for tests/test_bench_probe.py's isolation tests
    # (must exist in the CHILD processes too, hence env-gated, not
    # monkeypatched)
    BENCHES["selftest"] = lambda: {"metric": "selftest", "value": 1,
                                   "unit": "ok", "vs_baseline": 1.0}

    def _selftest_hang():
        time.sleep(600)

    BENCHES["selftest-hang"] = _selftest_hang

    def _selftest_dead():
        raise RuntimeError("Unable to initialize backend 'selftest'")

    BENCHES["selftest-dead"] = _selftest_dead


# benches that run on a forced host-platform CPU mesh in their own
# subprocess: they cannot be taken down by a dead accelerator backend,
# so they double as the probe-failure fallback set
_CPU_FALLBACK_BENCHES = ("gradexchange", "input_pipeline",
                         "fsdp_exchange", "paged_serve", "mfu_overlap",
                         "perf_observatory", "live_plane",
                         "serve_resilience", "resize", "pipeline",
                         "prefix_affinity", "long_context",
                         "anomaly_guard")


def _emit_cpu_fallbacks(done=()) -> int:
    """Real metric lines for a window whose accelerator backend died:
    every CPU-mesh subprocess bench not already produced this window
    runs now.  Returns how many real metric lines this window has
    (emitted here + already done) -- a window with at least one real
    line exits 0 so the driver records metrics instead of a bare rc=2
    (BENCH_r04/r05 were exactly that: one error line, zero numbers).  A
    fallback failure must never mask the death record."""
    emitted = len(tuple(done))
    for name in _CPU_FALLBACK_BENCHES:
        if name in done:
            continue
        try:
            # late-bound bench_<name> lookup: no hand-maintained second
            # registry to drift from _CPU_FALLBACK_BENCHES, and module-
            # level monkeypatching (tests) still takes effect
            print(json.dumps(globals()[f"bench_{name}"]()), flush=True)
            emitted += 1
        except Exception as e:
            print(f"{name} fallback failed: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)
    return emitted


def _run_isolated(names, per_bench_timeout: float,
                  probe_timeout: float) -> int:
    """Run each bench in ITS OWN subprocess with a hard timeout.

    The pre-flight probe only protects the START of the window; a
    backend that wedges MID-run leaves the process hung inside a jit
    dispatch that nothing in-process can interrupt (round 4: the gpt
    bench hung ~25 minutes after mnist failed, and the driver's own
    timeout produced zero output).  Here the parent never initializes
    JAX at all -- a hung bench costs its own timeout, is killed
    SIGTERM-first, becomes one machine-readable error record, and the
    remaining benches still run (after a confirming re-probe).
    Exit code: 0 all pass, 1 some failed, 2 backend declared dead AND no
    real metric line could be produced.  A declared-dead backend first
    runs every CPU-mesh fallback bench not already produced this window;
    when that yields at least one real metric line next to the death
    record, the window exits 0 (or 1 when an EARLIER bench genuinely
    failed) -- rc=2 is reserved for a window with no numbers at all
    (BENCH_r04/r05 shape)."""

    def death_exit(done, failed) -> int:
        if not _emit_cpu_fallbacks(done):
            return 2
        return 1 if failed else 0

    failed = False
    done = set()
    for name in names:
        cmd = [sys.executable, os.path.abspath(__file__),
               "--benches", name, "--no-isolate", "--probe-timeout", "0"]
        # children report backend death as a bare rc=2 and leave the
        # fallback emission to THIS parent (once per window)
        env = dict(os.environ, RLA_TPU_BENCH_CHILD="1")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                                env=env)
        timed_out = False
        try:
            out, _ = proc.communicate(timeout=per_bench_timeout)
        except subprocess.TimeoutExpired:
            timed_out = True
            out = _terminate(proc)
        for line in (out or "").splitlines():
            if line.strip():
                print(line, flush=True)  # child records pass through
        if timed_out:
            failed = True
            print(json.dumps(
                {"metric": name, "value": 0, "unit": "error",
                 "vs_baseline": 0.0, "error": "bench timed out",
                 "detail": f"no result within {per_bench_timeout:.0f}s "
                           "(mid-run wedge?)"}), flush=True)
            # a hang strongly suggests a dead backend: confirm before
            # burning the next bench's timeout on it too (probing
            # disabled via --probe-timeout 0 = keep going, same as the
            # in-process suspect-marker rule)
            if probe_timeout > 0:
                err = probe_backend(min(probe_timeout, 60))
                if err is not None:
                    print(_death_record("bench hang, probe confirmed",
                                        name, err), flush=True)
                    return death_exit(done, failed)
        elif proc.returncode == 2:
            # child already printed the death record
            return death_exit(done, failed)
        elif proc.returncode != 0:
            failed = True
        elif name in _CPU_FALLBACK_BENCHES:
            done.add(name)
    return 1 if failed else 0


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--benches",
        default="mnist,gpt,cifar,decode,gradexchange,input_pipeline,"
                "fsdp_exchange,paged_serve,mfu_overlap,perf_observatory,"
                "live_plane,serve_resilience,resize,pipeline,"
                "prefix_affinity,long_context",
        help=f"comma-separated subset of {sorted(BENCHES)}")
    parser.add_argument("--gate", action="store_true",
                        help="run no benches: gate a bench window "
                             "against PERF_BASELINE.json floors "
                             "(scripts/perf_gate.py) and exit 0 pass / "
                             "1 regression / 2 UNGATED (no numbers)")
    parser.add_argument("--gate-input", default=None,
                        help="window to gate: bench stdout capture or "
                             "BENCH_r*.json; '-' = stdin (default: "
                             "newest committed BENCH_r*.json)")
    parser.add_argument("--gate-baseline", default=None,
                        help="floors file (default: PERF_BASELINE.json)")
    parser.add_argument("--probe-timeout", type=float,
                        default=float(os.environ.get(
                            "RLA_TPU_PROBE_TIMEOUT", "120")),
                        help="seconds before the pre-flight backend probe "
                             "declares the backend dead (0 disables)")
    parser.add_argument("--no-isolate", action="store_true",
                        help="run benches in THIS process instead of one "
                             "subprocess each (isolation is the default "
                             "so a mid-run backend wedge costs one "
                             "bench's timeout, not the whole window)")
    parser.add_argument("--bench-timeout", type=float,
                        default=float(os.environ.get(
                            "RLA_TPU_BENCH_TIMEOUT", "1200")),
                        help="per-bench wall-clock limit in isolated "
                             "mode (seconds)")
    args = parser.parse_args()
    if args.gate:
        # regression gate: stdlib-only (scripts/perf_gate.py never
        # imports jax — it must run on the machine whose backend died)
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "scripts"))
        import perf_gate
        sys.exit(perf_gate.run(
            args.gate_input,
            args.gate_baseline or perf_gate.DEFAULT_BASELINE))
    if args.probe_timeout > 0:
        err = probe_backend(args.probe_timeout)
        if err is not None:
            print(json.dumps({"metric": "backend_probe", "value": 0,
                              "unit": "alive", "vs_baseline": 0.0, **err}),
                  flush=True)
            # a dead accelerator backend must not zero out the whole
            # window: the CPU-mesh subprocess benches (gradexchange,
            # input_pipeline) still produce real metric lines next to
            # the death record -- and a window WITH real metrics exits 0
            # so the driver records them (rc=2 = zero numbers, the
            # BENCH_r04/r05 failure shape)
            sys.exit(0 if _emit_cpu_fallbacks() else 2)
    names = [b.strip() for b in args.benches.split(",") if b.strip()]
    if not args.no_isolate:
        sys.exit(_run_isolated(names, args.bench_timeout,
                               args.probe_timeout))
    failed = False
    done = set()
    for name in names:
        try:
            print(json.dumps(BENCHES[name]()), flush=True)
            if name in _CPU_FALLBACK_BENCHES:
                done.add(name)
        except Exception as e:  # emit remaining benches; Ctrl-C still aborts
            msg = f"{type(e).__name__}: {e}"
            print(f"bench {name} failed: {msg}", file=sys.stderr,
                  flush=True)
            certain = any(m in str(e) for m in _BACKEND_DEAD_CERTAIN)
            suspect = any(m in str(e) for m in _BACKEND_DEAD_SUSPECT)
            if certain or suspect:
                # a certain init failure aborts outright; a suspect
                # marker (gRPC "UNAVAILABLE" can be a transient,
                # bench-local error) aborts only after a bounded
                # re-probe confirms the backend is really gone -- and
                # with probing disabled (--probe-timeout 0) a suspect
                # marker just moves on to the next bench
                err = {"detail": "init-phase failure, not re-probed"} \
                    if certain else (
                        probe_backend(min(args.probe_timeout, 60))
                        if args.probe_timeout > 0 else None)
                if err is not None:
                    print(_death_record(msg, name, err), flush=True)
                    if os.environ.get("RLA_TPU_BENCH_CHILD") == "1":
                        # isolated-mode child: a bare rc=2 tells the
                        # parent to stop the window and emit the CPU
                        # fallbacks ONCE for the whole window
                        sys.exit(2)
                    emitted = _emit_cpu_fallbacks(done)
                    if not emitted:
                        sys.exit(2)
                    sys.exit(1 if failed else 0)
            failed = True
    if failed:
        sys.exit(1)


if __name__ == "__main__":
    main()
