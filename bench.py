"""Benchmark: MNIST classifier training throughput through the full framework.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric matches BASELINE.json's north star (MNIST imgs/sec/chip; the reference
publishes no numbers, BASELINE.md): images/sec/chip training the
MNISTClassifier example end-to-end through Trainer + RayTPUAccelerator on the
default backend (the real TPU chip under the driver; CPU fallback keeps the
script runnable anywhere).  The timed region is epochs 2..N of a single
public-API ``fit`` — epoch 1 absorbs compile + the one-time device-cache
shipment, the steady-state epochs measure the training loop the way a user
runs it (device-resident gather feeding a donated, jitted train step).

Baseline constant: 25_000 imgs/sec — a single-A100 PTL+DDP run of this
3-layer-MLP example is input-pipeline-bound in that regime (BASELINE.json
target: ">= single-A100 DDP throughput").
"""

from __future__ import annotations

import json
import time

BASELINE_IMGS_PER_SEC = 25_000.0


def main() -> None:
    import jax

    from ray_lightning_accelerators_tpu import (Callback, DataLoader,
                                                RayTPUAccelerator, Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.models.mnist import (MNISTClassifier,
                                                             synthetic_mnist)

    class EpochClock(Callback):
        """Wall time at each train-epoch boundary (device-synced)."""

        def __init__(self):
            self.marks = []

        def _mark(self, trainer):
            if trainer._state is not None:
                jax.block_until_ready(trainer._state.params)
            self.marks.append(time.perf_counter())

        def on_train_epoch_start(self, trainer, module):
            self._mark(trainer)

        def on_fit_end(self, trainer, module):
            self._mark(trainer)

    n_devices = jax.device_count()
    batch_size = 1024 * n_devices
    n_images = batch_size * 24
    x, y = synthetic_mnist(n_images, seed=0)
    loader = DataLoader(ArrayDataset(x, y), batch_size=batch_size,
                        shuffle=True)

    model = MNISTClassifier({"layer_1": 128, "layer_2": 256, "lr": 1e-3,
                             "batch_size": batch_size})
    clock = EpochClock()
    epochs = 5
    trainer = Trainer(max_epochs=epochs, accelerator=RayTPUAccelerator(),
                      precision="bf16", enable_checkpointing=False,
                      log_every_n_steps=10 ** 9, seed=0, callbacks=[clock],
                      default_root_dir="/tmp/rla_tpu_bench")
    trainer.fit(model, loader)

    # steady state: epochs 2..N (epoch 1 paid compile + cache shipment)
    steps_per_epoch = len(loader)
    dt = clock.marks[-1] - clock.marks[1]
    imgs = batch_size * steps_per_epoch * (epochs - 1)
    per_chip = imgs / dt / n_devices
    print(json.dumps({
        "metric": "mnist_mlp_train_imgs_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMGS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
