"""Benchmark: MNIST classifier training throughput through the full framework.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric matches BASELINE.json's north star (MNIST imgs/sec/chip; the reference
publishes no numbers, BASELINE.md): images/sec/chip training the
MNISTClassifier example end-to-end through Trainer + RayTPUAccelerator --
including the input pipeline, sharded batch placement, and optimizer -- on
the default backend (the real TPU chip under the driver; CPU fallback keeps
the script runnable anywhere).

Baseline constant: 25_000 imgs/sec -- a single-A100 PTL+DDP run of this
3-layer-MLP example is input-pipeline-bound in that regime (BASELINE.json
target: ">= single-A100 DDP throughput").
"""

from __future__ import annotations

import json
import time

BASELINE_IMGS_PER_SEC = 25_000.0


def main() -> None:
    import jax

    from ray_lightning_accelerators_tpu import (RayTPUAccelerator, Trainer,
                                                DataLoader)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.models.mnist import (MNISTClassifier,
                                                             synthetic_mnist)

    n_devices = jax.device_count()
    batch_size = 1024 * n_devices
    n_images = batch_size * 24
    x, y = synthetic_mnist(n_images, seed=0)
    loader = DataLoader(ArrayDataset(x, y), batch_size=batch_size,
                        shuffle=True)

    model = MNISTClassifier({"layer_1": 128, "layer_2": 256, "lr": 1e-3,
                             "batch_size": batch_size})
    trainer = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                      precision="bf16", enable_checkpointing=False,
                      log_every_n_steps=10 ** 9, seed=0,
                      default_root_dir="/tmp/rla_tpu_bench")
    # warmup epoch: compile + cache
    trainer.fit(model, loader)

    # timed epochs through the same fitted trainer state
    steps_per_epoch = len(loader)
    epochs = 4
    t0 = time.perf_counter()
    state = trainer._state
    for _ in range(epochs):
        for batch in loader:
            state, metrics = trainer._train_step_fn(
                state, trainer._put_batch(batch))
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0

    imgs = batch_size * steps_per_epoch * epochs
    imgs_per_sec = imgs / dt
    per_chip = imgs_per_sec / n_devices
    print(json.dumps({
        "metric": "mnist_mlp_train_imgs_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMGS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
