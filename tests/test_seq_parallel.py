"""Sequence-parallel training (core/trainer.py seq_parallel,
parallel/ulysses.py, parallel/ring_attention.py): loss parity with the
non-SP baseline for BOTH attention strategies on the 8-device CPU mesh,
the sharded-attention entry points' numerics and typed refusals, the
sharded checkpoint round-trip under a sequence axis, and every
construction/compile-time refusal."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_lightning_accelerators_tpu import (ArrayDataset, DataLoader,
                                            ModelCheckpoint, Trainer,
                                            ring_attention_sharded,
                                            ulysses_attention_sharded)
from ray_lightning_accelerators_tpu.accelerators.base import Accelerator
from ray_lightning_accelerators_tpu.models.transformer import (
    GPT, TransformerConfig)
from ray_lightning_accelerators_tpu.ops.attention import flash_attention
from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib
from ray_lightning_accelerators_tpu.utils import sharded_checkpoint as sc

pytestmark = pytest.mark.long_context

VOCAB = 256


def _gpt(n_layers=4, n_heads=4, max_seq_len=32, **over):
    cfg = TransformerConfig(vocab_size=VOCAB, d_model=64, n_heads=n_heads,
                            d_ff=128, n_layers=n_layers,
                            max_seq_len=max_seq_len, fused_loss=True,
                            loss_chunk_rows=64, **over)
    return GPT(cfg)


def _loader(seq_len=32, n=32, bs=8):
    toks = np.random.default_rng(0).integers(
        0, VOCAB, size=(n, seq_len)).astype(np.int32)
    return DataLoader(ArrayDataset(toks), batch_size=bs, shuffle=False)


def _fit(seq_parallel, mode, mesh_cfg, model=None, seq_len=32, **kw):
    m = model or _gpt()
    tr = Trainer(max_epochs=2, precision="f32", seed=0,
                 enable_checkpointing=False, log_every_n_steps=10 ** 9,
                 accelerator=Accelerator(mesh_cfg),
                 seq_parallel=seq_parallel, seq_parallel_mode=mode, **kw)
    tr.fit(m, _loader(seq_len))
    return float(tr.callback_metrics["train_loss"]), tr, m


# --------------------------------------------------------------------- #
# Tentpole acceptance: loss parity with the non-SP run, both modes      #
# --------------------------------------------------------------------- #
def test_loss_parity_ulysses_and_ring_on_seq_axis():
    """Trainer(seq_parallel=2) over data=2 x fsdp=2 x sequence=2 must
    land the SAME multi-step Adam loss as the data=2 x fsdp=2 baseline
    for BOTH attention strategies -- the all_to_all head-scatter and the
    ring KV rotation are exact re-shardings, not approximations."""
    base, _, _ = _fit(1, None, mesh_lib.MeshConfig(data=2, fsdp=2))
    ul, tr_u, m_u = _fit(2, "ulysses", mesh_lib.MeshConfig(data=2, fsdp=2))
    ri, tr_r, m_r = _fit(2, "ring", mesh_lib.MeshConfig(data=2, fsdp=2))
    assert abs(ul - base) / abs(base) < 1e-4, (ul, base)
    assert abs(ri - base) / abs(base) < 1e-4, (ri, base)
    # the plan carries the axis and the module got the dispatch mode
    assert tr_u._plan.seq == 2 and tr_u._plan.describe()["seq"] == 2
    assert tr_r._plan.seq == 2
    assert m_u.cfg.context_parallel == "ulysses"
    assert m_r.cfg.context_parallel == "ring"


# --------------------------------------------------------------------- #
# Sharded attention entries: numerics + typed refusals + passthrough    #
# --------------------------------------------------------------------- #
def test_sharded_attention_entries_match_flash_reference():
    mesh = mesh_lib.build_mesh(
        mesh_lib.MeshConfig(data=2, fsdp=2, sequence=2))
    rng = np.random.default_rng(7)
    q, k, v = (jnp.asarray(rng.standard_normal((4, 4, 16, 8)),
                           jnp.float32) for _ in range(3))
    ref = flash_attention(q, k, v, True, None)
    np.testing.assert_allclose(
        np.asarray(ulysses_attention_sharded(q, k, v, mesh)),
        np.asarray(ref), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(ring_attention_sharded(q, k, v, mesh)),
        np.asarray(ref), rtol=1e-5, atol=1e-5)
    # ring has NO head-divisibility constraint: 3 heads over axis 2
    q3, k3, v3 = (jnp.asarray(rng.standard_normal((4, 3, 16, 8)),
                              jnp.float32) for _ in range(3))
    np.testing.assert_allclose(
        np.asarray(ring_attention_sharded(q3, k3, v3, mesh)),
        np.asarray(flash_attention(q3, k3, v3, True, None)),
        rtol=1e-5, atol=1e-5)


def test_sharded_attention_typed_refusals_and_seq1_passthrough():
    mesh = mesh_lib.build_mesh(
        mesh_lib.MeshConfig(data=2, fsdp=2, sequence=2))
    rng = np.random.default_rng(9)
    bad_seq = jnp.asarray(rng.standard_normal((4, 4, 9, 8)), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_sharded(bad_seq, bad_seq, bad_seq, mesh)
    with pytest.raises(ValueError, match="divisible"):
        ring_attention_sharded(bad_seq, bad_seq, bad_seq, mesh)
    bad_heads = jnp.asarray(rng.standard_normal((4, 3, 16, 8)),
                            jnp.float32)
    with pytest.raises(ValueError, match="ring attention instead"):
        ulysses_attention_sharded(bad_heads, bad_heads, bad_heads, mesh)
    # no sequence axis: both entries ARE plain flash attention
    flat = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=4, fsdp=2))
    q = jnp.asarray(rng.standard_normal((4, 4, 16, 8)), jnp.float32)
    ref = np.asarray(flash_attention(q, q, q, True, None))
    np.testing.assert_array_equal(
        np.asarray(ulysses_attention_sharded(q, q, q, flat)), ref)
    np.testing.assert_array_equal(
        np.asarray(ring_attention_sharded(q, q, q, flat)), ref)


# --------------------------------------------------------------------- #
# Sharded checkpoint round-trip under the sequence axis                 #
# --------------------------------------------------------------------- #
def test_sharded_checkpoint_roundtrip_under_seq_axis(tmp_path):
    """A fit over data x fsdp x sequence saves a restorable sharded
    checkpoint: params rebuilt via load_from_checkpoint match the live
    trained state leaf-for-leaf and the integrity record verifies."""
    model = _gpt(n_layers=2)
    cb = ModelCheckpoint(monitor=None)
    tr = Trainer(max_epochs=1, precision="f32", seed=0,
                 checkpoint_format="sharded", callbacks=[cb],
                 log_every_n_steps=10 ** 9,
                 default_root_dir=str(tmp_path),
                 accelerator=Accelerator(
                     mesh_lib.MeshConfig(data=2, fsdp=2)),
                 seq_parallel=2, seq_parallel_mode="ulysses")
    tr.fit(model, _loader())
    sc.wait_until_finished()
    best = cb.best_model_path
    assert sc.is_sharded_checkpoint(best), best
    assert sc.verify_checkpoint(best) == (True, "ok")
    loaded = GPT.load_from_checkpoint(best)
    live = jax.device_get(tr._state.params)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(loaded.params)[0],
            jax.tree_util.tree_flatten_with_path(live)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   err_msg=jax.tree_util.keystr(path))


# --------------------------------------------------------------------- #
# Typed refusals: construction and compile time                         #
# --------------------------------------------------------------------- #
def test_init_refusals_are_typed():
    with pytest.raises(ValueError, match="int >= 1"):
        Trainer(seq_parallel=0)
    with pytest.raises(ValueError, match="'ulysses' or 'ring'"):
        Trainer(seq_parallel_mode="flash")
    with pytest.raises(ValueError, match="pipeline_stages"):
        Trainer(seq_parallel=2, pipeline_stages=2,
                accelerator=Accelerator(mesh_lib.MeshConfig(data=2)))
    with pytest.raises(ValueError, match="grad_compression"):
        Trainer(seq_parallel=2, grad_compression="int8",
                accelerator=Accelerator(mesh_lib.MeshConfig(data=2)))
    with pytest.raises(ValueError, match="conflicts"):
        Trainer(seq_parallel=2, accelerator=Accelerator(
            mesh_lib.MeshConfig(data=2, sequence=4)))
    # the mode knob: env default honored, bad env value refused typed
    import os
    os.environ["RLA_TPU_SEQ_PARALLEL_MODE"] = "ring"
    try:
        assert Trainer(seq_parallel=2, accelerator=Accelerator(
            mesh_lib.MeshConfig(data=2))).seq_parallel_mode == "ring"
        os.environ["RLA_TPU_SEQ_PARALLEL_MODE"] = "flash"
        with pytest.raises(ValueError, match="'ulysses' or 'ring'"):
            Trainer(seq_parallel=2, accelerator=Accelerator(
                mesh_lib.MeshConfig(data=2)))
    finally:
        del os.environ["RLA_TPU_SEQ_PARALLEL_MODE"]


def test_fit_refusals_divisibility_and_module_awareness(tmp_path):
    # max_seq_len not divisible by the axis
    with pytest.raises(ValueError, match="not divisible"):
        _fit(4, "ring", mesh_lib.MeshConfig(data=2),
             model=_gpt(n_layers=2, max_seq_len=30), seq_len=30,
             default_root_dir=str(tmp_path))
    # ulysses head constraint names the ring alternative...
    with pytest.raises(ValueError, match="ring"):
        _fit(4, "ulysses", mesh_lib.MeshConfig(data=2),
             model=_gpt(n_layers=2, n_heads=2),
             default_root_dir=str(tmp_path))
    # ...and ring indeed trains that very head count
    loss, _, _ = _fit(4, "ring", mesh_lib.MeshConfig(data=2),
                      model=_gpt(n_layers=2, n_heads=2),
                      default_root_dir=str(tmp_path))
    assert np.isfinite(loss)
    # a module with no context_parallel dispatch refuses with the type
    from tests.utils import BoringModel, boring_loaders
    train, _ = boring_loaders()
    tr = Trainer(max_epochs=1, precision="f32", seed=0,
                 enable_checkpointing=False,
                 default_root_dir=str(tmp_path),
                 accelerator=Accelerator(mesh_lib.MeshConfig(data=2)),
                 seq_parallel=2)
    with pytest.raises(ValueError, match="context-parallel-aware"):
        tr.fit(BoringModel(), train)
