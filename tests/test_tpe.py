"""TPE searcher: convergence on known optima, categorical handling,
tune.run integration."""

import numpy as np
import pytest

from ray_lightning_accelerators_tpu import tune
from ray_lightning_accelerators_tpu.tune.search import TPESearcher


def test_tpe_concentrates_on_optimum():
    """Minimize (x-0.3)^2 over uniform(0,1): post-startup suggestions must
    concentrate near 0.3 and beat the startup phase."""
    searcher = TPESearcher(n_startup=8, seed=0)
    searcher.set_search_properties("loss", "min")
    spec = {"x": tune.uniform(0.0, 1.0)}
    xs = []
    for _ in range(40):
        cfg = searcher.suggest(spec)
        searcher.record(cfg, (cfg["x"] - 0.3) ** 2)
        xs.append(cfg["x"])
    startup_err = np.mean(np.abs(np.asarray(xs[:8]) - 0.3))
    late_err = np.mean(np.abs(np.asarray(xs[-10:]) - 0.3))
    assert late_err < startup_err
    assert late_err < 0.12
    best = min((cfg_x - 0.3) ** 2 for cfg_x in xs)
    assert best < 1e-3


def test_tpe_loguniform_and_randint():
    """Optimum at lr=1e-2, width=7; both dims must converge."""
    searcher = TPESearcher(n_startup=8, seed=1)
    searcher.set_search_properties("loss", "min")
    spec = {"lr": tune.loguniform(1e-4, 1.0), "width": tune.randint(1, 16)}
    for _ in range(50):
        cfg = searcher.suggest(spec)
        loss = (np.log10(cfg["lr"]) + 2) ** 2 + 0.1 * (cfg["width"] - 7) ** 2
        searcher.record(cfg, loss)
    hist = searcher._history
    best_cfg = min(hist, key=lambda t: t[1])[0]
    assert 1e-3 < best_cfg["lr"] < 1e-1
    assert 4 <= best_cfg["width"] <= 10
    assert isinstance(best_cfg["width"], int)


def test_tpe_categorical_prefers_good_choice():
    searcher = TPESearcher(n_startup=6, seed=2)
    searcher.set_search_properties("score", "max")
    spec = {"opt": tune.choice(["a", "b", "c"])}
    for _ in range(40):
        cfg = searcher.suggest(spec)
        searcher.record(cfg, {"a": 0.1, "b": 1.0, "c": 0.2}[cfg["opt"]])
    late = [searcher.suggest(spec)["opt"] for _ in range(20)]
    assert late.count("b") > 10


def test_tpe_static_values_pass_through():
    searcher = TPESearcher(n_startup=2, seed=0)
    cfg = searcher.suggest({"x": tune.uniform(0, 1), "epochs": 5})
    assert cfg["epochs"] == 5


def test_tune_run_with_search_alg(tmp_path):
    def trainable(config):
        tune.report(loss=(config["x"] - 0.7) ** 2)

    analysis = tune.run(trainable, config={"x": tune.uniform(0.0, 1.0)},
                        num_samples=25, metric="loss", mode="min",
                        search_alg=TPESearcher(n_startup=6, seed=0),
                        local_dir=str(tmp_path))
    assert abs(analysis.best_config["x"] - 0.7) < 0.15
    assert analysis.best_result["loss"] < 0.02
