"""Live elastic resharding (parallel/plan.py, parallel/redistribute.py,
Trainer.resize_in_memory, ElasticRunner(resize_in_memory=True)): survive
shrink AND grow without the checkpoint round-trip.

Layers covered:

1. ``ShardingPlan`` — the single producer of every placement decision —
   and the bounded-wave redistribution primitive (schedule packing,
   analytic moved-bytes accounting).
2. ``Trainer.resize_in_memory`` + ``fit(ckpt_path="live")``: a dp=8→4
   shrink whose continued run matches the checkpoint-restore path, and
   a dp=8→3 divisibility refusal that leaves the live state untouched.
3. The pool grow primitives (``drop``/``dropped_ranks``/``revive``,
   ``find_lost(classify=True)``) and the chaos ``rejoin`` kind /
   ``clear_lost`` that drive them in tests.
4. The ElasticRunner acceptance loop: a lost rank shrinks the world in
   memory, a rejoining host grows it back, and the descent trajectory
   continues bit-equal — no checkpoint file read anywhere; plus the
   fallback boundary (both ranks dying mid-attempt charges the failure
   budget ONCE and the retry resumes from the checkpoint chain).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu import (ElasticResizeError,
                                            RayTPUAccelerator, Trainer)
from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib
from ray_lightning_accelerators_tpu.parallel import plan as plan_lib
from ray_lightning_accelerators_tpu.parallel import redistribute as rd
from ray_lightning_accelerators_tpu.runtime.actors import ActorPool
from ray_lightning_accelerators_tpu.runtime.elastic import ElasticRunner
from ray_lightning_accelerators_tpu.testing import chaos as chaos_lib
from tests.utils import BoringModel, boring_loaders

HB = 0.05

pytestmark = pytest.mark.resize


# --------------------------------------------------------------------- #
# redistribute primitives                                               #
# --------------------------------------------------------------------- #

def test_wave_schedule_packs_under_budget_and_isolates_oversized():
    assert rd.wave_schedule([100, 100, 100], max_bytes=250) == [[0, 1], [2]]
    # an oversized leaf forms its own wave (the irreducible floor)
    assert rd.wave_schedule([300, 10, 10], max_bytes=250) == [[0], [1, 2]]
    assert rd.wave_schedule([], max_bytes=250) == []
    # order-preserving: no reordering even when repacking would be denser
    assert rd.wave_schedule([200, 100, 100], max_bytes=250) == [
        [0], [1, 2]]


def _mesh(n):
    return mesh_lib.build_mesh(mesh_lib.MeshConfig(data=n),
                               devices=jax.devices()[:n])


def test_leaf_moved_bytes_analytic():
    m8, m4 = _mesh(8), _mesh(4)
    x = jax.device_put(jnp.zeros((16, 4), jnp.float32),
                       plan_lib.replicated_sharding(m8))
    # replicated -> replicated-on-a-subset: every target device already
    # holds a full copy, nothing crosses a device boundary
    assert rd.leaf_moved_bytes(x, plan_lib.replicated_sharding(m4)) == 0
    # unchanged sharding: zero by the fast path
    assert rd.leaf_moved_bytes(x, plan_lib.replicated_sharding(m8)) == 0
    # dim0/8 -> dim0/4: device i's old 2-row block [2i, 2i+2) only
    # overlaps its new 4-row block [4i, 4i+4) for i=0, so 14 of the 16
    # rows cross a device boundary
    sharded8 = jax.device_put(
        jnp.zeros((16, 4), jnp.float32),
        jax.sharding.NamedSharding(m8, plan_lib.zero1_spec(m8, x)))
    moved = rd.leaf_moved_bytes(
        sharded8, jax.sharding.NamedSharding(m4,
                                             plan_lib.zero1_spec(m4, x)))
    assert moved == 14 * 4 * x.dtype.itemsize
    # a host leaf is all transfer
    host = np.zeros((8,), np.float32)
    assert rd.leaf_moved_bytes(
        host, plan_lib.replicated_sharding(m4)) == host.nbytes


def test_redistribute_tree_waves_and_stats():
    m8, m4 = _mesh(8), _mesh(4)
    repl8 = plan_lib.replicated_sharding(m8)
    tree = {"a": jax.device_put(jnp.arange(64.0).reshape(16, 4), repl8),
            "b": jax.device_put(jnp.ones((8,)), repl8)}
    sh = {"a": plan_lib.replicated_sharding(m4),
          "b": plan_lib.replicated_sharding(m4)}
    # tiny max_bytes: every leaf gets its own wave
    out, stats = rd.redistribute_tree(tree, sh, max_bytes=1)
    assert stats["waves"] == 2 and stats["leaves"] == 2
    assert stats["bytes_moved"] == 0  # replicated -> replicated subset
    assert stats["bytes_total"] == 16 * 4 * 4 + 8 * 4
    assert out["a"].sharding == sh["a"]
    np.testing.assert_array_equal(np.asarray(out["a"]),
                                  np.arange(64.0).reshape(16, 4))
    assert rd.resharding_bytes(tree, sh) == 0


# --------------------------------------------------------------------- #
# ShardingPlan                                                          #
# --------------------------------------------------------------------- #

def _live_trainer(tmpdir, workers, max_steps, **kw):
    return Trainer(default_root_dir=str(tmpdir),
                   accelerator=RayTPUAccelerator(workers),
                   max_epochs=100, max_steps=max_steps,
                   enable_checkpointing=False, precision="f32", seed=0,
                   log_every_n_steps=10 ** 9, **kw)


def test_build_plan_owns_trainer_state_shardings(tmpdir):
    trainer = _live_trainer(tmpdir, 8, max_steps=1,
                            shard_optimizer_state=True)
    train, _ = boring_loaders()
    trainer.fit(BoringModel(), train)
    plan = trainer._plan
    assert isinstance(plan, plan_lib.ShardingPlan)
    desc = plan.describe()
    assert desc["dp"] == 8 and desc["fsdp"] == 1
    assert desc["regime"] == "zero1"
    assert "residual" in plan.per_replica_fields
    assert "grad_accum" in plan.per_replica_fields
    # the plan's state shardings ARE the live state's placements
    sh = plan.state_shardings
    assert trainer._state.params["layer"]["kernel"].sharding == \
        sh.params["layer"]["kernel"]
    # ZeRO-1: divisible optimizer leaves sharded dim-0 over the batch axes
    zspec = plan_lib.zero1_spec(trainer._mesh,
                                trainer._state.params["layer"]["kernel"])
    assert zspec == jax.sharding.PartitionSpec(mesh_lib.BATCH_AXES)


# --------------------------------------------------------------------- #
# Trainer.resize_in_memory                                              #
# --------------------------------------------------------------------- #

def test_resize_in_memory_matches_checkpoint_restore(tmp_path):
    """The same dp=8→4 shrink recovered both ways lands on the same
    weights: run A restores a checkpoint into a fresh dp=4 trainer, run
    B resizes the live dp=8 trainer in memory and continues with
    ``fit(ckpt_path="live")`` — WITHOUT reading (or even having) any
    checkpoint file."""
    train, _ = boring_loaders()

    # run A: checkpoint round-trip (needs checkpointing enabled)
    model_a = BoringModel()
    trainer_a = Trainer(default_root_dir=str(tmp_path / "a"),
                        accelerator=RayTPUAccelerator(8), max_epochs=100,
                        max_steps=2, precision="f32", seed=0,
                        log_every_n_steps=10 ** 9)
    trainer_a.fit(model_a, train)
    ckpt = str(tmp_path / "mid.ckpt")
    trainer_a.save_checkpoint(ckpt)
    trainer_a2 = Trainer(default_root_dir=str(tmp_path / "a2"),
                         accelerator=RayTPUAccelerator(4), max_epochs=100,
                         max_steps=4, precision="f32", seed=0,
                         log_every_n_steps=10 ** 9)
    trainer_a2.fit(BoringModel(), train, ckpt_path=ckpt)
    assert trainer_a2.global_step == 4

    # run B: live in-memory resize of an identically-seeded fit
    model_b = BoringModel()
    trainer_b = _live_trainer(tmp_path / "b", 8, max_steps=2)
    trainer_b.fit(model_b, train)
    stats = trainer_b.resize_in_memory(4)
    assert stats["old_world"] == 8 and stats["new_world"] == 4
    assert stats["bytes_total"] > 0
    trainer_b.max_steps = 4
    trainer_b.fit(model_b, train, ckpt_path="live")
    assert trainer_b.global_step == 4
    assert mesh_lib.data_parallel_size(trainer_b._mesh) == 4
    # run B never produced or read a checkpoint file
    ckpts = [os.path.join(root, n)
             for root, _, names in os.walk(str(tmp_path / "b"))
             for n in names if n.endswith(".ckpt")]
    assert ckpts == []
    # weights within the elastic-resume tolerance of the restore path
    for a, b in zip(jax.tree.leaves(jax.device_get(trainer_a2._state.params)),
                    jax.tree.leaves(jax.device_get(trainer_b._state.params))):
        np.testing.assert_allclose(a, b, rtol=0, atol=1e-5)


def test_resize_refusal_is_typed_and_preserves_live_state(tmpdir):
    """dp=8→3 cannot divide the per-process batch: the refusal is a
    typed ElasticResizeError raised BEFORE any mutation — params stay
    bit-identical, the mesh stays dp=8, and the trainer can still
    resize to a legal world afterwards."""
    trainer = _live_trainer(tmpdir, 8, max_steps=2)
    train, _ = boring_loaders()
    trainer.fit(BoringModel(), train)
    before = jax.device_get(trainer._state.params)
    with pytest.raises(ElasticResizeError, match="divisible"):
        trainer.resize_in_memory(3)
    assert mesh_lib.data_parallel_size(trainer._mesh) == 8
    for a, b in zip(jax.tree.leaves(before),
                    jax.tree.leaves(jax.device_get(trainer._state.params))):
        np.testing.assert_array_equal(a, b)
    # surviving state is still usable: a legal resize goes through
    stats = trainer.resize_in_memory(4)
    assert stats["new_world"] == 4


def test_resize_without_live_fit_refuses():
    trainer = Trainer(accelerator=RayTPUAccelerator(8), max_steps=1,
                      enable_checkpointing=False, precision="f32", seed=0)
    with pytest.raises(ElasticResizeError, match="live"):
        trainer.resize_in_memory(4)


def test_live_resume_without_state_refuses(tmpdir):
    trainer = _live_trainer(tmpdir, 8, max_steps=1)
    train, _ = boring_loaders()
    with pytest.raises(ValueError, match="live"):
        trainer.fit(BoringModel(), train, ckpt_path="live")


# --------------------------------------------------------------------- #
# pool grow primitives                                                  #
# --------------------------------------------------------------------- #

def test_pool_drop_remembers_and_revive_replaces():
    pool = ActorPool(2)
    try:
        for f in pool.execute_all(lambda: None):
            f.result(timeout=120)
        assert pool.drop([1]) == [1]
        assert pool.dropped_ranks() == [1]
        assert len(pool) == 1
        w = pool.revive(1, probe_timeout_s=120.0)
        assert w is not None and w.rank == 1
        assert pool.dropped_ranks() == []
        assert [x.rank for x in pool.workers] == [0, 1]
        # the revived worker really serves dispatches
        for f in pool.execute_all(lambda: os.getpid()):
            assert f.result(timeout=120) > 0
        # a rank never dropped is not revivable
        assert pool.revive(0) is None
    finally:
        pool.shutdown()


def test_find_lost_classify_revives_restartable_rank():
    """classify=True gives a failed-probe rank one restart + re-probe:
    a plainly killed process (host fine) comes back as ``revived`` and
    stays in the pool; nothing is ``gone``."""
    pool = ActorPool(2)
    try:
        for f in pool.execute_all(lambda: None):
            f.result(timeout=120)
        pool.workers[1].kill()
        verdict = pool.find_lost(timeout_s=120.0, classify=True)
        assert verdict == {"gone": [], "revived": [1]}
        assert len(pool) == 2
        for f in pool.execute_all(lambda: None):
            f.result(timeout=120)
    finally:
        pool.shutdown()


# --------------------------------------------------------------------- #
# chaos rejoin / clear_lost                                             #
# --------------------------------------------------------------------- #

@pytest.mark.chaos
def test_clear_lost_removes_markers(tmp_path):
    ns = str(tmp_path / "ns")
    os.makedirs(ns)
    marker = os.path.join(ns, "lost-rank1-step2-r1.lost")
    open(marker, "w").close()
    assert chaos_lib.clear_lost(1, ns) == [marker]
    assert not os.path.exists(marker)
    assert chaos_lib.clear_lost(1, ns) == []  # idempotent
    # rank-keyed: another rank's marker is never touched
    other = os.path.join(ns, "lost-rank0-step2-r0.lost")
    open(other, "w").close()
    assert chaos_lib.clear_lost(1, ns) == []
    assert os.path.exists(other)


@pytest.mark.chaos
def test_rejoin_clears_lost_marker_after_k_boots(tmp_path):
    """``rejoin@rank1:step3`` counts BOOTS while the lost marker exists
    and lifts it on the third: the in-process analog of a host coming
    back after two failed respawns.  (Only the rejoin fault is
    installed here, so the lost death loop never fires.)"""
    ns = str(tmp_path / "ns")
    os.makedirs(ns)
    marker = os.path.join(ns, "lost-rank1-step2-r1.lost")
    open(marker, "w").close()
    faults = chaos_lib.parse_chaos("rejoin@rank1:step3")
    for boot in range(1, 3):  # boots 1-2: marker survives
        chaos_lib.ChaosInjector(faults, rank=1, ns_dir=ns)
        assert os.path.exists(marker), f"boot {boot} cleared too early"
    chaos_lib.ChaosInjector(faults, rank=1, ns_dir=ns)  # boot 3
    assert not os.path.exists(marker)
    # boots were counted in the namespace (crash-restart durable)
    boots = [n for n in os.listdir(ns) if n.endswith(".boots")]
    assert len(boots) == 1
    assert os.path.getsize(os.path.join(ns, boots[0])) == 3


@pytest.mark.chaos
def test_rejoin_requires_ns_dir_and_skips_dispatch():
    with pytest.raises(ValueError, match="rejoin"):
        chaos_lib.ChaosInjector(chaos_lib.parse_chaos("rejoin@rank0"),
                                rank=0, ns_dir=None)


# --------------------------------------------------------------------- #
# ElasticRunner(resize_in_memory=True) acceptance loops                 #
# --------------------------------------------------------------------- #

def _mem_world_body(logical_rank, world, wire_dir, total_steps):
    """Deterministic full-batch descent that RETAINS its state in
    process memory across dispatches (``builtins._rla_mem_state``) —
    the stand-in for a trainer's live device state under
    ``resize_in_memory``.  A fresh process (revived/respawned rank)
    has no memory and resumes from ``livestate.json``, the survivor-
    written live-state transfer file (the in-memory redistribution
    analog — NOT a checkpoint; nothing here ever reads one).  An SPMD-
    style barrier keyed by (step, world) makes a missing peer stall the
    step like a torn collective."""
    import builtins
    import json
    import os
    import time

    live = os.path.join(wire_dir, "livestate.json")
    bdir = os.path.join(wire_dir, "barrier")
    os.makedirs(bdir, exist_ok=True)
    state = getattr(builtins, "_rla_mem_state", None)
    resumed = "mem"
    if state is None:
        if os.path.exists(live):
            with open(live) as f:
                state = json.load(f)
            resumed = "wire"
        else:
            state = {"step": 0, "w": 1.0, "worlds": []}
            resumed = "fresh"
    if logical_rank == 0:
        # survivors publish their live state at dispatch entry so a
        # freshly grown rank can join without any checkpoint
        tmp = live + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, live)
    hiccup = os.path.join(wire_dir, "hiccup.flag")
    for step in range(state["step"], total_steps):
        open(os.path.join(bdir, f"s{step}.w{world}.r{logical_rank}"),
             "w").close()
        deadline = time.monotonic() + 15.0
        while not all(os.path.exists(
                os.path.join(bdir, f"s{step}.w{world}.r{r}"))
                for r in range(world)):
            if time.monotonic() > deadline:
                raise RuntimeError(f"step {step} barrier lost a peer "
                                   f"(world={world})")
            time.sleep(0.02)
        w = state["w"]
        state = {"step": step + 1, "w": w - 0.1 * (2.0 * w),
                 "worlds": state["worlds"] + [world]}
        builtins._rla_mem_state = state
        if world == 1 and not os.path.exists(hiccup):
            # engineered post-shrink failure: forces one more retry,
            # during which the rejoining host grows the world back
            open(hiccup, "w").close()
            raise RuntimeError("post-shrink hiccup")
    return (logical_rank, resumed, world, state["step"], state["w"],
            state["worlds"])


@pytest.mark.chaos
@pytest.mark.preempt
def test_chaos_shrink_then_rejoin_grows_back_without_checkpoints(tmp_path):
    """The live-resharding acceptance loop: ``lost@rank1:step2:once``
    shrinks
    the world 2→1 IN MEMORY (the survivor keeps its process and state —
    no restart_all, no checkpoint), ``rejoin@rank1:step3`` brings the
    host back on its third respawn and ``ActorPool.revive`` grows the
    world back to 2; the fresh rank joins from the survivor's published
    live state.  The descent trajectory continues bit-equal to an
    uninterrupted run, and no checkpoint file ever exists."""
    ns = str(tmp_path / "chaos_ns")
    wire = str(tmp_path / "wire")
    os.makedirs(wire)
    env = {"RLA_TPU_CHAOS": "lost@rank1:step2:once,rejoin@rank1:step3",
           "RLA_TPU_CHAOS_NS": ns,
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB)}
    pool = ActorPool(2, env_per_worker=[dict(env), dict(env)])
    failures = []
    try:
        # dispatch 1: both ranks run steps 0-2 at world 2, retaining
        # their state in process memory
        for f in pool.execute_per_worker(
                _mem_world_body, [(r, 2, wire, 3) for r in range(2)]):
            f.result(timeout=120)
        runner = ElasticRunner(pool, max_failures=2, allow_shrink=True,
                               resize_in_memory=True, min_workers=1,
                               probe_timeout_s=120.0,
                               on_failure=lambda a, e: failures.append(e))
        # attempt 1: rank 1's host is lost at dispatch; rank 0's barrier
        # raises.  Retry prep (in-memory): respawn dies (boot 1),
        # classify restart dies (boot 2) -> gone -> shrink to 1.
        # attempt 2: rank 0 alone runs step 3 at world 1, then the
        # engineered hiccup fails the attempt.  Retry prep: revive(1)
        # boots rank 1 a third time -> rejoin clears the lost marker ->
        # grow back to 2.  attempt 3: rank 0 continues from memory,
        # rank 1 joins from the published live state; steps 4-5 run at
        # world 2.
        out = runner.run(
            _mem_world_body,
            args_per_worker=lambda a, world: [(r, world, wire, 6)
                                              for r in range(world)])
        assert runner.attempts_used == 3
        assert len(failures) == 2  # lost rank + hiccup, within budget
        assert runner.shrink_events == [
            {"dropped": [1], "world_size": 1, "attempt": 2}]
        assert runner.grow_events == [
            {"revived": [1], "world_size": 2, "attempt": 3}]
        assert len(pool) == 2
        by_rank = {r[0]: r for r in out}
        assert by_rank[0][1] == "mem"    # survivor kept its process state
        assert by_rank[1][1] == "wire"   # grown rank joined from live state
        # the trajectory crossed shrink AND grow
        assert by_rank[0][5] == [2, 2, 2, 1, 2, 2]
        # bit-equal to the uninterrupted 6-step descent
        w = 1.0
        for _ in range(6):
            w = w - 0.1 * (2.0 * w)
        assert by_rank[0][4] == pytest.approx(w, abs=0.0)
        assert by_rank[1][4] == by_rank[0][4]
        # NO checkpoint file was ever written or read
        assert not [n for _, _, names in os.walk(str(tmp_path))
                    for n in names if n.endswith(".ckpt")]
        # the pause was accounted as the goodput ledger's resize phase
        assert runner.goodput.snapshot()["seconds"].get("resize", 0) > 0
        # and bracketed by resize telemetry
        from ray_lightning_accelerators_tpu.telemetry import get_recorder
        ends = [e for e in get_recorder().events()
                if e.get("kind") == "resize_end"]
        assert any(e.get("data", {}).get("new_world") == 2 for e in ends)
    finally:
        pool.shutdown()


def _ckpt_fallback_body(logical_rank, world, ckpt_dir, total_steps):
    """Retains state in memory like ``_mem_world_body`` but ALSO keeps
    the checkpoint chain current — the fallback contract: when no
    surviving process retains state, the attempt resumes from disk."""
    import builtins
    import json
    import os

    path = os.path.join(ckpt_dir, "state.json")
    state = getattr(builtins, "_rla_ckpt_mem", None)
    resumed = "mem"
    if state is None:
        if os.path.exists(path):
            with open(path) as f:
                state = json.load(f)
            resumed = "ckpt"
        else:
            state = {"step": 0, "w": 1.0}
            resumed = "fresh"
    for step in range(state["step"], total_steps):
        state = {"step": step + 1, "w": state["w"] - 0.1 * (2.0 * state["w"])}
        builtins._rla_ckpt_mem = state
        if logical_rank == 0:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(state, f)
            os.replace(tmp, path)
    return (logical_rank, resumed, state["step"], state["w"])


@pytest.mark.chaos
@pytest.mark.preempt
def test_mid_resize_death_falls_back_to_checkpoint_charging_once(tmp_path):
    """Fallback boundary: EVERY rank dies mid-attempt (no surviving
    in-memory state anywhere), so the in-memory path has nothing to
    resize from — the retry's fresh processes resume from the
    checkpoint chain, and the whole episode charges the failure budget
    exactly ONCE (one failed attempt), never twice."""
    ns = str(tmp_path / "chaos_ns")
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    env = {"RLA_TPU_CHAOS":
           "crash@rank0:step2:once,crash@rank1:step2:once",
           "RLA_TPU_CHAOS_NS": ns,
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB)}
    pool = ActorPool(2, env_per_worker=[dict(env), dict(env)])
    failures = []
    try:
        # dispatch 1: both ranks run steps 0-2, keeping state.json (the
        # checkpoint chain) current
        for f in pool.execute_per_worker(
                _ckpt_fallback_body, [(r, 2, ckpt, 3) for r in range(2)]):
            f.result(timeout=120)
        runner = ElasticRunner(pool, max_failures=1, allow_shrink=True,
                               resize_in_memory=True, min_workers=1,
                               probe_timeout_s=120.0,
                               on_failure=lambda a, e: failures.append(e))
        out = runner.run(
            _ckpt_fallback_body,
            args_per_worker=lambda a, world: [(r, world, ckpt, 6)
                                              for r in range(world)])
        # one failed attempt == one budget charge (max_failures=1 held)
        assert len(failures) == 1
        assert runner.attempts_used == 2
        assert runner.shrink_events == [] and runner.grow_events == []
        # the fresh processes resumed from the checkpoint chain
        assert {r[1] for r in out} == {"ckpt"}
        with open(os.path.join(ckpt, "state.json")) as f:
            assert json.load(f)["step"] == 6
        assert runner.goodput.snapshot()["seconds"].get("resize", 0) > 0
    finally:
        pool.shutdown()
