"""graftlint + compile-guard: the analyzer's rules on fixture snippets
(positive / negative / pragma-suppressed per rule), the knobs/wire
registries, and the runtime compile-count invariants — the serve
engine's 3-program lifecycle and the trainer's zero-retrace-after-
warmup.  All CPU, tier-1 fast."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu.analysis import knobs
from ray_lightning_accelerators_tpu.analysis import lint as L
from ray_lightning_accelerators_tpu.analysis.compile_guard import (
    CompileBudgetExceeded, compile_count, compile_guard)

pytestmark = pytest.mark.analysis

PKG_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "ray_lightning_accelerators_tpu")


def _findings(sources, rule=None, **cfg_kw):
    cfg = L.LintConfig(**cfg_kw) if cfg_kw else L.LintConfig.for_tree(sources)
    out = L.run_lint(sources, cfg)
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


def _active(findings):
    return [f for f in findings if not f.suppressed]


# --------------------------------------------------------------------- #
# host-sync                                                             #
# --------------------------------------------------------------------- #
HOT_CFG = dict(hot_roots={"hot.py": ("Engine.run",)})

HOT_POSITIVE = '''
import jax
import jax.numpy as jnp
import numpy as np

class Engine:
    def run(self, x):
        y = jnp.sum(x)
        a = float(y)                 # float on a device value
        b = y.item()                 # .item()
        c = np.asarray(y)            # host materialization
        d = jax.device_get(y)        # device_get
        jax.block_until_ready(y)     # block
        self.helper(y)
        return a, b, c, d

    def helper(self, y):
        return float(jnp.exp(y))     # reachable via self.run -> helper
'''

HOT_NEGATIVE = '''
import jax.numpy as jnp
import numpy as np

class Engine:
    def run(self, xs):
        n = int(len(xs))             # host int, not a device value
        toks = np.zeros((4,), np.int32)  # host buffer construction
        return jnp.sum(jnp.asarray(toks)) + n

class Cold:
    def elsewhere(self, y):
        return float(jnp.sum(y))     # not reachable from a hot root
'''


def test_host_sync_positives():
    found = _findings({"hot.py": HOT_POSITIVE}, rule="host-sync", **HOT_CFG)
    lines = {f.line for f in _active(found)}
    # float / item / asarray / device_get / block + the helper's float
    assert len(_active(found)) >= 6, found
    assert any(f.message.startswith("'float") for f in found)
    assert any(".item()" in f.message for f in found)
    assert any("np.asarray" in f.message for f in found)
    assert any("device_get" in f.message for f in found)
    assert any("Engine.helper" in f.message for f in found), \
        "reachability must follow self-method calls"
    assert all(f.path == "hot.py" for f in found)
    assert lines  # line numbers populated


def test_host_sync_negatives():
    found = _findings({"hot.py": HOT_NEGATIVE}, rule="host-sync", **HOT_CFG)
    assert _active(found) == [], found


def test_host_sync_pragma_suppression_requires_reason():
    src = (
        "import jax.numpy as jnp\n"
        "class Engine:\n"
        "    def run(self, x):\n"
        "        y = jnp.sum(x)\n"
        "        return float(y)  # graftlint: ok(host-sync) — feed gate\n"
        "    def bad(self, x):\n"
        "        pass  # graftlint: ok(host-sync)\n")
    out = L.run_lint({"hot.py": src}, L.LintConfig(**HOT_CFG))
    hs = [f for f in out if f.rule == "host-sync"]
    assert len(hs) == 1 and hs[0].suppressed
    # a reason-less pragma is itself a finding
    assert any(f.rule == "pragma" and not f.suppressed for f in out)


# --------------------------------------------------------------------- #
# retrace                                                               #
# --------------------------------------------------------------------- #
RETRACE_POSITIVE = '''
import jax
import jax.numpy as jnp
from functools import partial

def per_step(xs):
    outs = []
    for x in xs:
        f = jax.jit(lambda a: a + 1)     # jit constructed per iteration
        outs.append(f(x))
    y = jax.jit(lambda a: a * 2)(xs[0])  # jit used immediately
    return outs, y

@jax.jit
def branchy(x, flag):
    if flag:                              # python branch on traced arg
        return x + 1
    return x - 1

g = jax.jit(lambda a, cfg: a, static_argnums=(1,))
bad = g(jnp.zeros(3), [1, 2])             # unhashable static literal
'''

RETRACE_NEGATIVE = '''
import jax
import jax.numpy as jnp
from functools import partial

_step = jax.jit(lambda a: a + 1)          # constructed once, reused

def drive(xs):
    return [_step(x) for x in xs]

@partial(jax.jit, static_argnames=("mode",))
def ok_static(x, mode):
    if mode == "fast":                    # declared static: fine
        return x + 1
    return x - 1

@jax.jit
def ok_shape(x, y):
    if x.shape[0] > 4:                    # shapes are static under trace
        return x + 1
    if y is None:                         # identity check is static
        return x
    return x - 1
'''


def test_retrace_positives():
    found = _findings({"m.py": RETRACE_POSITIVE}, rule="retrace")
    msgs = "\n".join(f.message for f in _active(found))
    assert "inside a loop body" in msgs
    assert "used immediately" in msgs
    assert "traced value(s) ['flag']" in msgs
    assert "unhashable" in msgs


def test_retrace_negatives():
    found = _findings({"m.py": RETRACE_NEGATIVE}, rule="retrace")
    assert _active(found) == [], found


def test_retrace_pragma():
    src = ("import jax\n"
           "def f(xs):\n"
           "    for x in xs:\n"
           "        # graftlint: ok(retrace) — test fixture, cold path\n"
           "        g = jax.jit(lambda a: a)\n"
           "    return g\n")
    found = _findings({"m.py": src}, rule="retrace")
    assert found and all(f.suppressed for f in found)


# --------------------------------------------------------------------- #
# tracer-leak                                                           #
# --------------------------------------------------------------------- #
LEAK_POSITIVE = '''
import jax

class Model:
    @jax.jit
    def step(self, x):
        self.cache = x * 2       # tracer stored on self
        return x

_stash = None

def outer():
    def body(x):
        global _stash            # smuggling via global
        _stash = x
        return x
    return jax.jit(body)
'''

LEAK_NEGATIVE = '''
import jax

class Model:
    def host_side(self, x):
        self.cache = x           # not jitted: fine

    @jax.jit
    def step(self, x):
        y = x * 2                # local assign inside jit: fine
        return y
'''


def test_tracer_leak():
    pos = _findings({"m.py": LEAK_POSITIVE}, rule="tracer-leak")
    msgs = "\n".join(f.message for f in _active(pos))
    assert "self.cache" in msgs and "global" in msgs
    neg = _findings({"m.py": LEAK_NEGATIVE}, rule="tracer-leak")
    assert _active(neg) == [], neg


# --------------------------------------------------------------------- #
# knob-registry                                                         #
# --------------------------------------------------------------------- #
KNOB_CFG = dict(knob_names=frozenset({"RLA_TPU_REGISTERED"}))

KNOB_POSITIVE = '''
import os
MY_ENV = "RLA_TPU_SECRET_KNOB"
raw = os.environ.get("RLA_TPU_SECRET_KNOB")    # raw read, literal
via_const = os.environ[MY_ENV]                 # raw read via constant
dyn = os.getenv(raw)                           # dynamic key
from ray_lightning_accelerators_tpu.analysis import knobs
bad = knobs.get_int("RLA_TPU_UNREGISTERED", 1)  # getter, unregistered
'''

KNOB_NEGATIVE = '''
import os
from ray_lightning_accelerators_tpu.analysis import knobs
flags = os.environ.get("XLA_FLAGS", "")        # non-RLA name: allowed
os.environ["RLA_TPU_REGISTERED"] = "1"         # write: exempt
ok = knobs.get_int("RLA_TPU_REGISTERED", 1)    # registered getter
'''


def test_knob_registry_rule():
    pos = _findings({"m.py": KNOB_POSITIVE}, rule="knob-registry",
                    **KNOB_CFG)
    msgs = "\n".join(f.message for f in _active(pos))
    assert msgs.count("raw environ read") == 2
    assert "dynamic key" in msgs
    assert "RLA_TPU_UNREGISTERED" in msgs
    neg = _findings({"m.py": KNOB_NEGATIVE}, rule="knob-registry",
                    **KNOB_CFG)
    assert _active(neg) == [], neg


def test_knob_registry_resolves_imported_constants():
    consts = 'GRACE_ENV = "RLA_TPU_PREEMPT_GRACE_S"\n'
    user = ("import os\n"
            "from .consts import GRACE_ENV\n"
            "v = os.environ.get(GRACE_ENV)\n")
    found = _findings({"consts.py": consts, "user.py": user},
                      rule="knob-registry", **KNOB_CFG)
    active = _active(found)
    assert len(active) == 1 and "RLA_TPU_PREEMPT_GRACE_S" in \
        active[0].message


# --------------------------------------------------------------------- #
# wire-exception                                                        #
# --------------------------------------------------------------------- #
WIRE_CFG = dict(wire_names=frozenset({"Registered"}),
                worker_modules=("worker.py",))

WIRE_SRC = '''
class Registered(RuntimeError):
    pass

class Unregistered(RuntimeError):
    pass

def dispatched():
    raise Registered("typed, rebuilds fine")

def also_dispatched(flag):
    if flag:
        raise ValueError("builtins stay generic on purpose")
    raise Unregistered("typed but NOT in the wire registry")
'''


def test_wire_exception_rule():
    pos = _findings({"worker.py": WIRE_SRC}, rule="wire-exception",
                    **WIRE_CFG)
    active = _active(pos)
    assert len(active) == 1 and "Unregistered" in active[0].message
    # same code outside a worker module: out of scope
    neg = _findings({"driver.py": WIRE_SRC}, rule="wire-exception",
                    **WIRE_CFG)
    assert _active(neg) == [], neg


def test_wire_registry_consistent_with_rebuilders():
    from ray_lightning_accelerators_tpu.runtime import wire
    assert set(wire.WIRE_EXCEPTION_NAMES) == set(wire._rebuilders())


def test_rebuild_remote_types():
    from ray_lightning_accelerators_tpu.runtime.actors import RemoteError
    from ray_lightning_accelerators_tpu.runtime.elastic import (
        ElasticResizeError)
    from ray_lightning_accelerators_tpu.runtime.preemption import Preempted
    from ray_lightning_accelerators_tpu.runtime.watchdog import WorkerWedged
    from ray_lightning_accelerators_tpu.runtime.wire import rebuild_remote

    p = Preempted.at_step(7, "/tmp/ck")
    back = rebuild_remote("Preempted", str(p), "tb")
    assert isinstance(back, Preempted) and back.step == 7
    assert back.remote_typed  # came from a worker-raised payload
    w = WorkerWedged.for_rank(3, {"detail": "stuck"})
    back = rebuild_remote("WorkerWedged", str(w), "tb")
    assert isinstance(back, WorkerWedged) and back.rank == 3
    back = rebuild_remote("ElasticResizeError", "bad size", "tb")
    assert isinstance(back, ElasticResizeError)
    from ray_lightning_accelerators_tpu.runtime.guardian import (
        NumericAnomaly)
    a = NumericAnomaly.for_trip(step=9, blame="data", epoch=0, batch_idx=9,
                                flags={"loss_nonfinite": True})
    back = rebuild_remote("NumericAnomaly", str(a), "tb")
    assert isinstance(back, NumericAnomaly)
    assert back.step == 9 and back.blame == "data" and back.batch_idx == 9
    assert back.diagnosis["flags"] == {"loss_nonfinite": True}
    back = rebuild_remote("SomeRandomError", "boom", "tb")
    assert isinstance(back, RemoteError)


def test_replica_failure_triage_with_typed_rebuilds():
    """Regression (review finding): wire-rebuilt worker-raised app
    errors (stale ObjectStoreError) must NOT read as replica death —
    a poisoned request would cascade every replica into the down set."""
    from ray_lightning_accelerators_tpu.runtime.actors import RemoteError
    from ray_lightning_accelerators_tpu.runtime.watchdog import WorkerWedged
    from ray_lightning_accelerators_tpu.runtime.wire import rebuild_remote
    from ray_lightning_accelerators_tpu.serve.replicas import (
        _is_application_failure)

    assert _is_application_failure(RemoteError("ValueError", "x", "tb"))
    stale = rebuild_remote("ObjectStoreError", "stale ref", "tb")
    assert _is_application_failure(stale)  # typed app error: keep replica
    # infra stays infra: driver-side wedge, worker-raised wedge, death
    assert not _is_application_failure(
        WorkerWedged.for_rank(1, {"detail": "stuck"}))
    assert not _is_application_failure(
        rebuild_remote("WorkerWedged", "wedged", "tb"))
    assert not _is_application_failure(RuntimeError("worker 1 died"))


# --------------------------------------------------------------------- #
# the tree itself is clean (THE enforcement test)                       #
# --------------------------------------------------------------------- #
def test_package_tree_has_no_unsuppressed_findings():
    findings = L.lint_path(PKG_DIR)
    active = _active(findings)
    assert active == [], "\n" + "\n".join(f.format() for f in active)
    # the pragmas that do exist all carry reasons (rule 'pragma' active
    # findings would have shown above) and there are some — the rules
    # genuinely fire on this tree
    assert any(f.suppressed for f in findings)


def test_single_file_target_keeps_package_context(tmp_path):
    # a single-file target inside a package must resolve hot-root /
    # worker-module keys and the registries exactly like a package run
    # (a basename key would no-op every path-keyed rule: false clean)
    pkg = tmp_path / "pkg"
    (pkg / "core").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "core" / "__init__.py").write_text("")
    target = pkg / "core" / "trainer.py"
    target.write_text(
        "class Trainer:\n"
        "    def _fit_step(self, state, batch):\n"
        "        loss = self._step(state, batch)\n"
        "        return float(loss.item())\n")
    active = _active(L.lint_path(str(target)))
    assert any(f.rule == "host-sync" and f.path == "core/trainer.py"
               for f in active), active
    # and on the real tree: the file's pragma'd findings are DETECTED
    # (suppressed), not invisible
    real = L.lint_path(os.path.join(PKG_DIR, "core", "trainer.py"))
    assert real and all(f.path == "core/trainer.py" for f in real)
    assert any(f.suppressed and f.rule == "host-sync" for f in real)
    assert _active(real) == []


def test_cli_exits_zero_on_tree():
    import subprocess
    import sys
    script = os.path.join(os.path.dirname(PKG_DIR), "scripts",
                          "graftlint.py")
    proc = subprocess.run([sys.executable, script, PKG_DIR],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graftlint:" in proc.stdout


def test_cli_exits_nonzero_on_violation(tmp_path):
    import subprocess
    import sys
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nv = os.environ.get('RLA_TPU_OOPS')\n")
    script = os.path.join(os.path.dirname(PKG_DIR), "scripts",
                          "graftlint.py")
    proc = subprocess.run([sys.executable, script, str(bad)],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "knob-registry" in proc.stdout


# --------------------------------------------------------------------- #
# knobs registry runtime behavior                                       #
# --------------------------------------------------------------------- #
def test_knobs_typed_getters(monkeypatch):
    monkeypatch.setenv("RLA_TPU_FLASH_BLOCK_Q", "256")
    assert knobs.get_int("RLA_TPU_FLASH_BLOCK_Q", 512) == 256
    monkeypatch.setenv("RLA_TPU_FLASH_BLOCK_Q", "banana")
    assert knobs.get_int("RLA_TPU_FLASH_BLOCK_Q", 512) == 512
    monkeypatch.delenv("RLA_TPU_FLASH_BLOCK_Q")
    assert knobs.get_int("RLA_TPU_FLASH_BLOCK_Q", 512) == 512
    # distinct unset vs malformed defaults (the preemption-grace shape)
    monkeypatch.setenv("RLA_TPU_WEDGE_TIMEOUT_S", "nope")
    assert knobs.get_float("RLA_TPU_WEDGE_TIMEOUT_S", None,
                           malformed=30.0) == 30.0
    monkeypatch.delenv("RLA_TPU_WEDGE_TIMEOUT_S")
    assert knobs.get_float("RLA_TPU_WEDGE_TIMEOUT_S", None) is None
    # bool parsing + warn-and-default on junk
    monkeypatch.setenv("RLA_TPU_INSIDE_WORKER", "true")
    assert knobs.get_bool("RLA_TPU_INSIDE_WORKER") is True
    monkeypatch.setenv("RLA_TPU_INSIDE_WORKER", "2")
    assert knobs.get_bool("RLA_TPU_INSIDE_WORKER") is False
    # flag semantics: presence-truthiness (historical gates)
    monkeypatch.setenv("RLA_TPU_DISABLE_PALLAS", "0")
    assert knobs.get_flag("RLA_TPU_DISABLE_PALLAS") is True


def test_knobs_env_overlay(monkeypatch):
    monkeypatch.setenv("RLA_TPU_WORKER_HEARTBEAT_S", "5.0")
    assert knobs.get_float("RLA_TPU_WORKER_HEARTBEAT_S", 1.0) == 5.0
    # per-worker overlay wins when it HAS the key
    assert knobs.get_float("RLA_TPU_WORKER_HEARTBEAT_S", 1.0,
                           env={"RLA_TPU_WORKER_HEARTBEAT_S": "2.5"}) == 2.5
    # overlay with empty value = explicitly unset -> default, no
    # fall-through to the process env
    assert knobs.get_float("RLA_TPU_WORKER_HEARTBEAT_S", 1.0,
                           env={"RLA_TPU_WORKER_HEARTBEAT_S": ""}) == 1.0


def test_knobs_refuse_unregistered():
    with pytest.raises(LookupError, match="not registered"):
        knobs.get_str("RLA_TPU_TOTALLY_NEW_KNOB")


def test_every_package_rla_env_name_is_registered():
    """Belt-and-braces sweep: every RLA_TPU_* string literal in the
    package (reads, writes, docs aside) resolves to a registered knob —
    registry drift can't hide in a write-only site."""
    import re
    unknown = set()
    for dirpath, dirnames, filenames in os.walk(PKG_DIR):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn), encoding="utf-8") as f:
                for name in re.findall(r"RLA_TPU_[A-Z0-9_]+", f.read()):
                    if name not in knobs.KNOBS:
                        unknown.add((fn, name))
    # non-knob wire/protocol constants are the only sanctioned names
    allowed = {"RLA_TPU_AUTH1"}  # agent auth magic, not an env knob
    assert {n for _, n in unknown} <= allowed, unknown


# --------------------------------------------------------------------- #
# compile-guard runtime                                                 #
# --------------------------------------------------------------------- #
def test_compile_guard_counts_and_budgets():
    shape = (13, 29)  # unique: avoid riding another test's cache

    @jax.jit
    def f(x):
        return x * 3 + 1

    with compile_guard() as g:
        f(jnp.ones(shape))
    assert g.new_compiles >= 1
    with compile_guard(max_new_compiles=0, label="cached") as g:
        f(jnp.ones(shape))  # cache hit: no compile
    assert g.new_compiles == 0
    with pytest.raises(CompileBudgetExceeded, match="retracing"):
        with compile_guard(max_new_compiles=0):
            f(jnp.ones((17, 31)))  # new shape: retrace
    # an exception inside the block is never masked by the budget check
    with pytest.raises(RuntimeError, match="inner"):
        with compile_guard(max_new_compiles=0):
            f(jnp.ones((19, 37)))
            raise RuntimeError("inner")


def test_serve_engine_program_count_invariant():
    """The PR 2 prose, enforced through the paging indirection: a
    staggered join/retire workload over one prompt bucket runs the
    PAGED engine's whole lifecycle in exactly 2 compiled programs
    (bucketed chunk prefill-into-blocks, batched paged step — the slot
    join fused into prefill), the DENSE engine's in exactly 3 (bucket
    prefill, slot join, batched step), and a second wave adds zero to
    either."""
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    from ray_lightning_accelerators_tpu.serve import ServeEngine

    cfg = TransformerConfig(vocab_size=89, d_model=64, n_heads=2,
                            d_ff=128, n_layers=2, max_seq_len=48)
    model = GPT(cfg)
    params = model.init_params(jax.random.PRNGKey(3))
    rng = np.random.default_rng(11)
    # one prompt bucket: lengths 3..8 all pad to 8 (prompt_block and
    # block_len both 8)
    reqs = [(rng.integers(0, 89, size=(int(rng.integers(3, 9)),))
             .astype(np.int32), int(rng.integers(4, 10)))
            for _ in range(6)]
    for paged, expected, what in (
            (True, 2, "chunk prefill/step"),
            (False, 3, "prefill/join/step")):
        eng = ServeEngine(model, params, max_slots=3, queue_depth=32,
                          paged=paged, block_len=8, prefix_cache=False)
        eng.start()  # cache alloc outside the guard: it is not a program
        try:
            with compile_guard(max_new_compiles=expected,
                               label="serve-prog") as g:
                resps = []
                for i, (p, n) in enumerate(reqs):
                    resps.append(eng.submit(p, n))
                    if i % 2 == 1:
                        time.sleep(0.02)  # staggered: join/retire mid-flight
                for r in resps:
                    r.result(timeout=300)
            assert g.new_compiles == expected, (
                f"expected exactly {expected} compiled programs "
                f"({what}, paged={paged}), got {g.new_compiles}")
            # second wave: join + retire + decode reuse every program
            with compile_guard(max_new_compiles=0, label="serve-steady"):
                more = [eng.submit(p, n) for p, n in reqs[:3]]
                for r in more:
                    r.result(timeout=300)
        finally:
            eng.stop()
        snap = eng.stats()
        assert snap["completed"] == 9
        assert snap["steps_batch_gt1"] >= 1  # it genuinely batched


def test_trainer_no_retrace_after_warmup(tmpdir):
    """ROADMAP item 5's precondition, enforced: the train step compiles
    on step 1 and retraces ZERO times over the following >= 10 steps."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from utils import BoringModel, boring_loaders

    from ray_lightning_accelerators_tpu import (Callback,
                                                RayTPUAccelerator, Trainer)

    counts = []

    class CompileCounter(Callback):
        def on_train_batch_end(self, trainer, module, metrics, batch_idx):
            counts.append(compile_count())

    trainer = Trainer(default_root_dir=str(tmpdir), max_steps=12,
                      max_epochs=3, accelerator=RayTPUAccelerator(2),
                      precision="f32", seed=0, log_every_n_steps=4,
                      callbacks=[CompileCounter()],
                      enable_checkpointing=False)
    train, _ = boring_loaders()
    trainer.fit(BoringModel(), train)
    assert len(counts) == 12
    # step 1 absorbs every compile (placement + train step); steps 2..12
    # must add none — eleven consecutive steps, zero retraces
    assert counts[1:] == [counts[0]] * 11, counts