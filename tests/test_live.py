"""Live telemetry plane (telemetry/live.py) + serve SLO engine
(serve/slo.py).

The acceptance loops:

- during a LIVE fit, the driver's ``/metrics`` endpoint answers
  exposition-valid Prometheus spanning trainer + prefetch + HBM +
  goodput families while steps are still running (validated with the
  same grammar check test_telemetry applies to the end-of-run export),
  and the run stays zero-retrace with the plane enabled;
- a chaos ``hang@rank0`` flips that rank's own ``/healthz`` to wedged
  (HTTP 503) BEFORE any driver watchdog reaps it;
- a ClusterView over live worker endpoints merges rank-labeled
  (portfile scrape locally, the agent ``live`` wire op remotely);
- an overloaded serve workload reports a NONZERO SLO burn rate and
  typed deadline sheds before prefill; a light workload reports zero.
"""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from ray_lightning_accelerators_tpu.serve.slo import (DeadlineExceeded,
                                                      SloPolicy,
                                                      SloTracker)
from ray_lightning_accelerators_tpu.telemetry import live
from ray_lightning_accelerators_tpu.telemetry import recorder as R
from tests.utils import assert_prometheus_exposition

pytestmark = pytest.mark.live

HB = 0.05


@pytest.fixture(autouse=True)
def _fresh_live_plane():
    """Each test gets a clean process server + recorder."""
    live._reset_for_tests()
    R._reset_for_tests()
    yield
    live._reset_for_tests()
    R._reset_for_tests()


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


# --------------------------------------------------------------------- #
# TelemetryServer endpoints + portfile discovery                          #
# --------------------------------------------------------------------- #
def test_server_endpoints_and_portfile(tmp_path, monkeypatch):
    monkeypatch.setenv("RLA_TPU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("RLA_TPU_METRICS_PORT", "0")
    R.configure(trace_id="t-live")
    R.emit("fit_start", step=0)
    srv = live.maybe_start_from_env()
    assert srv is not None and srv.port and srv.url
    # starting again returns the SAME server (once per process)
    assert live.maybe_start_from_env() is srv

    code, body = _get(srv.url + "/metrics")
    assert code == 200
    assert_prometheus_exposition(body)
    assert 'rla_tpu_events_total{kind="fit_start"} 1' in body
    assert 'rla_tpu_rank_healthy{rank="driver"} 1' in body

    code, body = _get(srv.url + "/statusz")
    status = json.loads(body)
    assert status["rank"] == "driver" and status["trace_id"] == "t-live"
    assert status["flight_tail"][-1]["kind"] == "fit_start"
    assert status["health"]["status"] == "ok"

    code, body = _get(srv.url + "/healthz")
    assert code == 200 and json.loads(body)["status"] == "ok"

    code, body = _get(srv.url + "/snapshot")
    snap = json.loads(body)
    assert snap["rank"] == "driver"
    assert [e["kind"] for e in snap["events"]] == ["fit_start"]

    # 404 names the known paths
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(srv.url + "/nope")
    assert ei.value.code == 404

    # portfile discovery (the ClusterView/rla_top channel)
    path = live.portfile_for(None)
    assert path == str(tmp_path / "driver.port.json")
    rec = live.read_portfile(path)
    assert rec["port"] == srv.port and rec["rank"] == "driver"
    assert live.scrape_rank(None)["rank"] == "driver"
    # shutdown removes the portfile
    live.shutdown_server()
    assert live.read_portfile(path) is None


def test_server_disabled_without_knob(monkeypatch):
    monkeypatch.delenv("RLA_TPU_METRICS_PORT", raising=False)
    assert live.maybe_start_from_env() is None


def test_classify_health_matches_watchdog_thresholds():
    # no channel => liveness-only ok (the watchdog's no-false-positive
    # rule)
    assert live.classify_health(None)["status"] == "ok"
    ok = live.classify_health({"beat_age_s": 0.1, "busy_s": None,
                               "started": True}, wedge_timeout_s=1.0)
    assert ok["status"] == "ok"
    slow = live.classify_health({"beat_age_s": 0.1, "busy_s": 0.8,
                                 "started": True}, wedge_timeout_s=1.0)
    assert slow["status"] == "slow" and "straggler" in slow["detail"]
    wedged = live.classify_health({"beat_age_s": 1.5, "busy_s": None,
                                   "started": True}, wedge_timeout_s=1.0)
    assert wedged["status"] == "wedged"
    # booting rank: judged by boot grace, not the wedge timeout
    booting = live.classify_health({"beat_age_s": 1.5, "busy_s": None,
                                    "started": False},
                                   wedge_timeout_s=1.0,
                                   boot_grace_s=60.0)
    assert booting["status"] == "ok"
    # a configured dispatch deadline wedges a busy-past-it rank (the
    # watchdog's second wedged rule) and halves the slow trigger
    dl = live.classify_health({"beat_age_s": 0.1, "busy_s": 40.0,
                               "started": True}, wedge_timeout_s=60.0,
                              dispatch_deadline_s=30.0)
    assert dl["status"] == "wedged" and "deadline" in dl["detail"]
    dl_slow = live.classify_health({"beat_age_s": 0.1, "busy_s": 20.0,
                                    "started": True},
                                   wedge_timeout_s=60.0,
                                   dispatch_deadline_s=30.0)
    assert dl_slow["status"] == "slow"


# --------------------------------------------------------------------- #
# Satellites: recorder tail/rate, consistent ServeMetrics snapshot        #
# --------------------------------------------------------------------- #
def test_flight_recorder_tail_filter_and_rate():
    rec = R.FlightRecorder(capacity=64, rank=1)
    for i in range(10):
        rec.emit("train_step", step=i)
        rec.emit("serve_decode_step", active=1)
    tail = rec.tail(4, kind="train_step")
    assert [e["data"]["step"] for e in tail] == [6, 7, 8, 9]
    assert all(e["kind"] == "train_step" for e in tail)
    assert rec.tail(3) == rec.events()[-3:]
    assert rec.tail(0) == []  # n<=0 = no tail, never the whole ring
    assert rec.tail(-1) == []
    # 20 events just emitted within the window; floor-1s denominator
    assert rec.events_per_second(window_s=60.0) == pytest.approx(20.0)
    assert R.FlightRecorder().events_per_second() == 0.0


def test_serve_metrics_snapshot_never_tears_under_concurrent_writers():
    """Satellite: a live scrape racing concurrent observe_* calls must
    see reservoir counts and their paired counters move TOGETHER — the
    prefill reservoir can never lead/lag the prefills counter, steps
    likewise."""
    from ray_lightning_accelerators_tpu.serve.metrics import ServeMetrics
    m = ServeMetrics()
    stop = threading.Event()
    N = 3000

    def prefiller():
        for _ in range(N):
            m.observe_prefill(1e-6)

    def stepper():
        for _ in range(N):
            m.observe_step(1e-6, active=1)

    tears = []

    def reader():
        while not stop.is_set():
            snap = m.snapshot()
            pf = snap["prefill_s"]["count"] if snap["prefill_s"] else 0
            st = (snap["decode_step_s"]["count"]
                  if snap["decode_step_s"] else 0)
            if pf != snap["prefills"] or st != snap["steps"]:
                tears.append((pf, snap["prefills"], st, snap["steps"]))
            # tokens = prefills + steps (active=1) must never be ahead
            # of what the counters say
            if snap["tokens_generated"] != snap["prefills"] \
                    + snap["steps"]:
                tears.append(("tokens", snap["tokens_generated"],
                              snap["prefills"], snap["steps"]))

    writers = [threading.Thread(target=prefiller),
               threading.Thread(target=stepper)]
    rd = threading.Thread(target=reader)
    rd.start()
    for w in writers:
        w.start()
    for w in writers:
        w.join()
    stop.set()
    rd.join()
    assert not tears, f"snapshot tore {len(tears)}x, e.g. {tears[:3]}"
    final = m.snapshot()
    assert final["prefills"] == N and final["steps"] == N
    assert final["prefill_s"]["count"] == N
    assert final["decode_step_s"]["count"] == N


# --------------------------------------------------------------------- #
# Mid-fit live scrape (the acceptance slice)                              #
# --------------------------------------------------------------------- #
def test_live_metrics_midfit_scrape(tmp_path, monkeypatch):
    """While a fit is RUNNING, the driver /metrics answers exposition-
    valid Prometheus spanning trainer spans, prefetch accounting, HBM
    pools, step phases and goodput; /statusz carries timeline rows and
    global_step — and the plane adds zero retraces (compile-guard)."""
    from ray_lightning_accelerators_tpu import Callback, DataLoader, Trainer
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg
    from ray_lightning_accelerators_tpu.data.loader import RandomDataset
    from ray_lightning_accelerators_tpu.telemetry.perf import PerfObservatory
    from ray_lightning_accelerators_tpu.utils.profiler import Profiler
    from tests.utils import BoringModel

    monkeypatch.setenv("RLA_TPU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("RLA_TPU_METRICS_PORT", "0")
    cg.install()
    perf = PerfObservatory()
    perf.goodput.run_begin()  # feed the goodput ledger so it exports

    scraped = {}

    class MidFitScrape(Callback):
        def __init__(self):
            self.compiles = []

        def on_train_batch_end(self, trainer, module, metrics, idx):
            self.compiles.append(cg.compile_count())
            if trainer.global_step == 5 and not scraped:
                srv = live.get_server()
                assert srv is not None
                _, scraped["metrics"] = _get(srv.url + "/metrics")
                _, scraped["statusz"] = _get(srv.url + "/statusz")

    clock = MidFitScrape()
    trainer = Trainer(max_steps=12, precision="f32", seed=0,
                      enable_checkpointing=False,
                      prefetch_batches=2, perf_observatory=perf,
                      profiler=Profiler(),
                      cache_dataset_on_device=False,
                      log_every_n_steps=10 ** 9, callbacks=[clock],
                      default_root_dir=str(tmp_path))
    trainer.fit(BoringModel(),
                DataLoader(RandomDataset(32, 96), batch_size=8))
    perf.goodput.run_end()

    assert scraped, "mid-fit scrape never ran"
    body = scraped["metrics"]
    assert_prometheus_exposition(body)
    for needle in ('rla_tpu_span_seconds{span="train_step"',   # trainer
                   "rla_tpu_prefetch_depth",                   # prefetch
                   "rla_tpu_hbm_total_bytes",                  # HBM
                   "rla_tpu_step_phase_seconds_total",         # timeline
                   "rla_tpu_goodput_wall_seconds",             # goodput
                   'rla_tpu_rank_healthy{rank="driver"}',      # rank row
                   'rla_tpu_events_total{kind="train_step"}'):
        assert needle in body, f"{needle!r} missing from live scrape"
    status = json.loads(scraped["statusz"])
    assert status["global_step"] == 5
    assert status["step_timeline"]["steps"] >= 4
    assert status["recent_steps"], "no live timeline rows"
    assert status["hbm"]["total_bytes"] >= 0
    # the plane added ZERO retraces after warmup
    assert clock.compiles[-1] == clock.compiles[2], clock.compiles
    # the driver server stays scrapeable after fit (last state)
    srv = live.get_server()
    _, after = _get(srv.url + "/metrics")
    assert_prometheus_exposition(after)


# --------------------------------------------------------------------- #
# Chaos: a hung rank's own /healthz flips to wedged pre-reap              #
# --------------------------------------------------------------------- #
def _ok(x=1):
    return x * 2


@pytest.mark.chaos
def test_chaos_hang_flips_worker_healthz_before_watchdog_reap(tmp_path):
    from ray_lightning_accelerators_tpu.runtime.actors import Worker
    from ray_lightning_accelerators_tpu.runtime.watchdog import (
        Watchdog, WorkerWedged)
    env = {"RLA_TPU_CHAOS": "hang@rank0",
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB),
           "RLA_TPU_WEDGE_TIMEOUT_S": "0.6",
           "RLA_TPU_TELEMETRY_DIR": str(tmp_path),
           "RLA_TPU_METRICS_PORT": "0"}
    w = Worker(0, env=env)
    wd = None
    try:
        fut = w.execute(_ok)
        # the rank's own endpoint: poll until its frozen beat crosses
        # the wedge threshold — NO watchdog is running yet
        deadline = time.monotonic() + 60
        status = None
        while time.monotonic() < deadline:
            rec = live.read_portfile(live.portfile_for(0, env=env))
            if rec is not None:
                try:
                    _get(f"http://127.0.0.1:{rec['port']}/healthz",
                         timeout=2)
                except urllib.error.HTTPError as e:
                    if e.code == 503:  # wedged reads as NOT-ready
                        status = json.loads(e.read().decode())
                        break
                except Exception:
                    pass
            time.sleep(HB)
        assert status is not None, "healthz never flipped to wedged"
        assert status["status"] == "wedged"
        assert status["beat_age_s"] > 0.6
        # the watchdog reaps ONLY NOW — the live signal preceded it
        wd = Watchdog([w], wedge_timeout_s=0.6, poll_s=HB).start()
        with pytest.raises(WorkerWedged):
            fut.result(timeout=120)
    finally:
        if wd is not None:
            wd.stop()
        w.kill()


# --------------------------------------------------------------------- #
# ClusterView: local pool + agent relay                                   #
# --------------------------------------------------------------------- #
def _emit_steps(n):
    from ray_lightning_accelerators_tpu.telemetry import emit
    for i in range(n):
        emit("train_step", step=i)
    return n


def test_cluster_view_merges_rank_labeled(tmp_path):
    from ray_lightning_accelerators_tpu.runtime.actors import ActorPool
    env = {"RLA_TPU_TELEMETRY_DIR": str(tmp_path),
           "RLA_TPU_METRICS_PORT": "0",
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB)}
    pool = ActorPool(2, env_per_worker=[dict(env), dict(env)])
    try:
        for f in pool.execute_all(_emit_steps, 5):
            assert f.result(timeout=120) == 5
        cv = live.ClusterView(workers=list(pool.workers), refresh_s=0.2)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and len(cv.view()) < 2:
            cv.refresh()
            time.sleep(0.1)
        assert sorted(cv.view()) == ["0", "1"]
        txt = cv.merged_registry().prometheus_text()
        assert_prometheus_exposition(txt)
        assert 'rla_tpu_rank_healthy{rank="0"} 1' in txt
        assert 'rla_tpu_rank_healthy{rank="1"} 1' in txt
        assert 'rla_tpu_rank_events_per_second{rank="0"}' in txt
        # events merged into the per-kind tallies
        j = cv.merged_registry().to_json()
        assert j["events"].get("train_step", 0) >= 10
        assert j["ranks"]["0"]["health"]["status"] == "ok"
        # the compact last_view (run-report shape) carries status rows
        view = cv.last_view()
        assert sorted(view["ranks"]) == ["0", "1"]
        assert view["ranks"]["1"]["healthy"] == 1.0
        # a dead rank drops from fresh sweeps but its LAST snapshot
        # survives in the merged view (the before-death property)
        pool.workers[1].kill()
        cv.refresh()
        assert "1" in cv.view()
    finally:
        pool.shutdown()


def test_cluster_view_portfile_scan_without_pool(tmp_path):
    """The pool-independent mode (rla_top/serve): portfiles under the
    telemetry dir are discovered directly."""
    from ray_lightning_accelerators_tpu.runtime.actors import Worker
    env = {"RLA_TPU_TELEMETRY_DIR": str(tmp_path),
           "RLA_TPU_METRICS_PORT": "0",
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB)}
    w = Worker(0, env=env)
    try:
        assert w.execute(_emit_steps, 3).result(timeout=120) == 3
        cv = live.ClusterView(env=env)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not cv.view():
            cv.refresh()
            time.sleep(0.1)
        assert "0" in cv.view()
    finally:
        w.kill()


def test_live_wire_op_over_agent_relay(tmp_path):
    """The remote seam: a RemoteWorker's live_snapshot rides the agent
    ``live`` wire op (the scrape happens on the worker's own host)."""
    from ray_lightning_accelerators_tpu.runtime.agent import (HostAgent,
                                                              RemoteWorker)
    agent = HostAgent(port=0, bind="127.0.0.1")
    agent.serve_in_background()
    env = {"RLA_TPU_TELEMETRY_DIR": str(tmp_path),
           "RLA_TPU_METRICS_PORT": "0",
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB)}
    w = None
    try:
        w = RemoteWorker(f"127.0.0.1:{agent.port}", rank=1, env=env)
        assert w.execute(_emit_steps, 4).result(timeout=120) == 4
        deadline = time.monotonic() + 60
        snap = None
        while time.monotonic() < deadline:
            snap = w.live_snapshot()
            if snap:
                break
            time.sleep(0.1)
        assert snap is not None and snap["rank"] == "1"
        assert snap["status"]["health"]["status"] == "ok"
        cv = live.ClusterView(workers=[w], refresh_s=0.2)
        cv.refresh()
        assert "1" in cv.view()
    finally:
        if w is not None:
            w.kill()
        agent.shutdown()


def test_fanned_out_fit_wires_cluster_view(tmp_path, monkeypatch):
    """THE driver seam: a fanned-out fit with the plane enabled starts
    the driver server, aggregates the worker rank through a ClusterView
    (agent `live` wire op), re-exports it rank-labeled on the driver's
    /metrics, and keeps the last view for the run report."""
    from ray_lightning_accelerators_tpu import (DataLoader,
                                                HorovodRayAccelerator,
                                                Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.runtime.agent import HostAgent
    from tests.utils import BoringModel

    monkeypatch.setenv("RLA_TPU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("RLA_TPU_METRICS_PORT", "0")
    monkeypatch.setenv("RLA_TPU_LIVE_REFRESH_S", "0.2")
    agent = HostAgent(port=0, bind="127.0.0.1")
    agent.serve_in_background()
    trainer = None
    try:
        x = np.random.default_rng(0).normal(size=(32, 32)).astype(
            "float32")
        trainer = Trainer(max_epochs=2, precision="f32", seed=0,
                          enable_checkpointing=False,
                          accelerator=HorovodRayAccelerator(
                              num_hosts=1, num_slots=1,
                              agents=[f"127.0.0.1:{agent.port}"]),
                          default_root_dir=str(tmp_path))
        trainer.fit(BoringModel(),
                    DataLoader(ArrayDataset(x), batch_size=8,
                               shuffle=False))
        srv = live.get_server()
        assert srv is not None
        assert trainer._cluster_view is not None
        # the worker rank made it into the aggregated view (the agent
        # `live` op scraped its portfile-published endpoint)
        view = trainer._cluster_view.last_view()
        assert "0" in view["ranks"], view
        _, body = _get(srv.url + "/metrics")
        assert_prometheus_exposition(body)
        assert 'rla_tpu_rank_healthy{rank="driver"}' in body
        assert 'rla_tpu_rank_healthy{rank="0"}' in body
    finally:
        if trainer is not None:
            trainer.teardown()
        agent.shutdown()


# --------------------------------------------------------------------- #
# Serve SLO engine                                                        #
# --------------------------------------------------------------------- #
def test_slo_policy_validation_and_env(monkeypatch):
    with pytest.raises(ValueError):
        SloPolicy(ttft_target_s=0.0)
    with pytest.raises(ValueError):
        SloPolicy(ttft_target_s=1.0, target_fraction=1.0)
    assert SloPolicy().enabled is False
    assert SloPolicy.from_env() is None  # no knob set
    monkeypatch.setenv("RLA_TPU_SLO_TTFT_S", "0.25")
    monkeypatch.setenv("RLA_TPU_SLO_DEADLINE_S", "2.0")
    pol = SloPolicy.from_env()
    assert pol is not None and pol.ttft_target_s == 0.25
    assert pol.deadline_s == 2.0 and pol.target_fraction == 0.99


def test_slo_tracker_burn_rate_math():
    pol = SloPolicy(ttft_target_s=0.1, target_fraction=0.9)

    class Req:
        trace_id = "t"
        request_id = 0

    t = SloTracker(pol, window_s=60.0)
    for _ in range(8):
        t.observe_ttft(0.01, Req())   # ok
    assert t.burn_rate() == 0.0
    t.observe_ttft(0.5, Req())        # 1 violation / 9 obs
    t.observe_ttft(0.5, Req())        # 2 / 10
    # violation fraction 0.2 over allowed 0.1 => burn 2.0
    assert t.burn_rate() == pytest.approx(2.0)
    snap = t.snapshot()
    assert snap["families"]["ttft"]["violations"] == 2
    assert snap["families"]["ttft"]["observations"] == 10
    # violations emitted typed flight-recorder events
    kinds = [e["kind"] for e in R.get_recorder().events()]
    assert kinds.count("slo_violation") == 2


def test_deadline_propagates_through_requeue():
    from ray_lightning_accelerators_tpu.serve.batcher import (
        AdmissionController)
    pol = SloPolicy(deadline_s=5.0)
    ac = AdmissionController(queue_depth=4, max_total_len=64,
                             slo_policy=pol)
    resp = ac.submit(np.arange(4, dtype=np.int32), 4)
    req = resp.request
    assert req.deadline == pytest.approx(req.t_submit + 5.0)
    item = ac.pop()
    assert item[0] is req
    # an infra requeue keeps the ORIGINAL deadline (the client's clock
    # never resets on retry)
    ac.requeue(req, resp)
    req2, _ = ac.pop()
    assert req2 is req
    assert req2.deadline == pytest.approx(req.t_submit + 5.0)
    ac.shutdown()


def _tiny_gpt():
    import jax
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    cfg = TransformerConfig(vocab_size=61, d_model=32, n_heads=2,
                            d_ff=64, n_layers=2, max_seq_len=64)
    model = GPT(cfg)
    return model, model.init_params(jax.random.PRNGKey(0))


@pytest.mark.serve
def test_engine_sheds_expired_deadline_typed_before_prefill():
    from ray_lightning_accelerators_tpu.serve import ServeEngine
    model, params = _tiny_gpt()
    rng = np.random.default_rng(0)
    engine = ServeEngine(model, params, max_slots=1,
                         slo=SloPolicy(deadline_s=0.001))
    # submitted BEFORE start: the request ages past its deadline queued
    h = engine.submit(rng.integers(0, 61, size=(5,)).astype(np.int32), 4)
    time.sleep(0.05)
    engine.start()
    try:
        with pytest.raises(DeadlineExceeded):
            h.result(timeout=60)
        snap = engine.metrics.snapshot()
        assert snap["slo_deadline_shed"] == 1
        assert snap["failed"] == 1          # accounted terminally
        assert snap["prefills"] == 0        # shed BEFORE prefill
        # the shed IS a deadline-family violation (burn-rate fuel) AND
        # counts in its own dedicated shed counter
        assert snap["slo_violations"] == 1
        kinds = [e["kind"] for e in R.get_recorder().events()]
        assert "slo_violation" in kinds
    finally:
        engine.stop()


@pytest.mark.serve
def test_engine_burn_rate_overloaded_nonzero_light_zero():
    from ray_lightning_accelerators_tpu.serve import ServeEngine
    model, params = _tiny_gpt()
    rng = np.random.default_rng(0)

    def run(policy):
        with ServeEngine(model, params, max_slots=2,
                         slo=policy) as engine:
            hs = [engine.submit(rng.integers(0, 61, size=(5,))
                                .astype(np.int32), 4) for _ in range(4)]
            for h in hs:
                h.result(timeout=120)
            return engine.metrics.snapshot()

    hot = run(SloPolicy(ttft_target_s=1e-6, token_cadence_target_s=1e-6))
    assert hot["slo_burn_rate"] > 0
    assert hot["slo_violations"] >= 4       # every TTFT violated
    assert hot["completed"] == 4            # violations don't fail work
    cold = run(SloPolicy(ttft_target_s=300.0,
                         token_cadence_target_s=300.0))
    assert cold["slo_burn_rate"] == 0.0
    assert cold["slo_violations"] == 0
    # the gauges render typed through the registry export
    from ray_lightning_accelerators_tpu.telemetry.registry import (
        MetricsRegistry)
    reg = MetricsRegistry()
    reg.add_serve(hot, rank="e0")
    txt = reg.prometheus_text()
    assert_prometheus_exposition(txt)
    assert "# TYPE rla_tpu_serve_slo_burn_rate gauge" in txt
    assert "# TYPE rla_tpu_serve_slo_violations_total counter" in txt


@pytest.mark.serve
def test_engine_without_slo_has_no_slo_overhead_fields():
    from ray_lightning_accelerators_tpu.serve import ServeEngine
    model, params = _tiny_gpt()
    rng = np.random.default_rng(0)
    with ServeEngine(model, params, max_slots=1, slo=None) as engine:
        h = engine.submit(rng.integers(0, 61, size=(5,))
                          .astype(np.int32), 3)
        h.result(timeout=120)
        snap = engine.metrics.snapshot()
    assert engine._slo is None
    assert "slo_burn_rate" not in snap
    assert snap["slo_violations"] == 0  # counter exists, stays zero


# --------------------------------------------------------------------- #
# Failure report embeds the last live view                                #
# --------------------------------------------------------------------- #
def test_fit_failure_report_embeds_cluster_view(tmp_path):
    from ray_lightning_accelerators_tpu import DataLoader, Trainer
    from ray_lightning_accelerators_tpu.data.loader import RandomDataset
    from tests.utils import BoringModel

    class Poison(Exception):
        pass

    class Bomb:
        def __init__(self, inner):
            self.inner = inner

        def __iter__(self):
            yield from list(self.inner)[:2]
            raise Poison("poisoned batch 3")

        def __len__(self):
            return len(self.inner)

    trainer = Trainer(max_steps=8, precision="f32", seed=0,
                      enable_checkpointing=False, prefetch_batches=0,
                      cache_dataset_on_device=False,
                      log_every_n_steps=10 ** 9,
                      default_root_dir=str(tmp_path))
    # a cluster view with one collected rank (simulating the fan-out
    # driver's aggregator at death time)
    cv = live.ClusterView(workers=[], refresh_s=10.0)
    cv._view = {"0": {"status": {"rank": "0", "healthy": 1.0,
                                 "global_step": 7,
                                 "health": {"status": "ok"}}}}
    cv._refreshed_at = time.monotonic()
    trainer._cluster_view = cv
    with pytest.raises(Poison):
        trainer.fit(BoringModel(),
                    Bomb(DataLoader(RandomDataset(32, 64),
                                    batch_size=8)))
    rep = json.load(open(os.path.join(str(tmp_path),
                                      "run_report.json")))
    view = rep["extra"]["cluster_view"]
    assert view["ranks"]["0"]["global_step"] == 7
    # the merged metrics snapshot carries the rank-labeled status row
    assert rep["metrics"]["ranks"]["0"]["healthy"] == 1.0
