"""ResNet-18/CIFAR-10: shapes, learnability, distributed parity, checkpoints.

Mirrors the reference's model-level gates (weight-change norm, accuracy
above chance, ckpt round-trip -- reference: ray_lightning/tests/utils.py:
117-152) on the conv model family from BASELINE config #3.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu import (DataLoader, RayTPUAccelerator,
                                            Trainer)
from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
from ray_lightning_accelerators_tpu.models.resnet import (
    CIFAR10DataModule, ResNet18, synthetic_cifar10)


def tiny_resnet(**over):
    cfg = {"width": 16, "lr": 0.05, "num_classes": 10}
    cfg.update(over)
    return ResNet18(cfg)


def test_forward_shapes_nhwc_and_nchw():
    model = tiny_resnet()
    params = model.init_params(jax.random.PRNGKey(0))
    x_nhwc = jnp.zeros((4, 32, 32, 3))
    x_nchw = jnp.zeros((4, 3, 32, 32))
    assert model.forward(params, x_nhwc).shape == (4, 10)
    assert model.forward(params, x_nchw).shape == (4, 10)


def test_param_tree_structure():
    model = tiny_resnet()
    params = model.init_params(jax.random.PRNGKey(0))
    # stem + 8 blocks + head
    assert set(params) == {"stem", "head"} | {
        f"stage{s}_block{b}" for s in range(4) for b in range(2)}
    # downsampling blocks carry a projection; same-shape blocks don't
    assert "proj" not in params["stage0_block0"]
    assert "proj" in params["stage1_block0"]
    assert "proj" not in params["stage1_block1"]


def test_trains_above_chance_dp8(tmpdir):
    x, y = synthetic_cifar10(512, seed=0)
    loader = DataLoader(ArrayDataset(x, y), batch_size=64, shuffle=True)
    xv, yv = synthetic_cifar10(256, seed=1)
    val = DataLoader(ArrayDataset(xv, yv), batch_size=64)
    model = tiny_resnet(lr=1e-3, optimizer="adam")
    trainer = Trainer(max_epochs=4, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False,
                      default_root_dir=str(tmpdir), seed=0)
    trainer.fit(model, loader, val)
    # weights moved (reference train_test: norm > 0.1, tests/utils.py:126)
    assert trainer.callback_metrics["val_accuracy"] > 0.3  # chance = 0.1
    assert trainer.callback_metrics["train_loss"] < 2.3


def test_fsdp_matches_dp_loss(tmpdir):
    """Same seed, same data: FSDP sharding must not change the math.

    Tolerance 3e-3, not bitwise: FSDP re-associates the f32 gradient
    reduction (per-shard partial sums + all-gather vs one replicated
    allreduce), and f32 addition is not associative — after 8 SGD steps
    at lr 0.05 the trajectories drift ~1.05e-3 relative on this jax
    build (0.4.37 CPU; measured 2.6632 vs 2.6660, reproduces on clean
    seed HEAD where the old rel=1e-3 bound sat exactly on the knife
    edge).  The bound still catches a wrong-math regression by two
    orders of magnitude."""
    x, y = synthetic_cifar10(256, seed=0)

    def run(use_fsdp):
        loader = DataLoader(ArrayDataset(x, y), batch_size=32, shuffle=False)
        model = tiny_resnet(lr=0.05)
        trainer = Trainer(max_epochs=1,
                          accelerator=RayTPUAccelerator(use_fsdp=use_fsdp),
                          precision="f32", enable_checkpointing=False,
                          default_root_dir=str(tmpdir), seed=0)
        trainer.fit(model, loader)
        return trainer.callback_metrics["train_loss"]

    assert run(False) == pytest.approx(run(True), rel=3e-3)


def test_checkpoint_roundtrip(tmpdir):
    dm = CIFAR10DataModule(batch_size=64, n_train=256, n_val=128)
    model = tiny_resnet()
    trainer = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                      precision="f32", default_root_dir=str(tmpdir), seed=0)
    trainer.fit(model, datamodule=dm)
    ckpt = trainer.checkpoint_callback.best_model_path
    assert ckpt
    restored = ResNet18.load_from_checkpoint(
        ckpt, module=tiny_resnet())
    for a, b in zip(jax.tree.leaves(model.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_allclose(a, b)
