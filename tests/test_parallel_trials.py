"""Concurrent tune trials over disjoint device partitions."""

import threading

import jax
import numpy as np
import pytest

from ray_lightning_accelerators_tpu import (ArrayDataset, DataLoader,
                                            RayTPUAccelerator, Trainer, tune)
from tests.utils import BoringModel


def test_parallel_trials_partition_devices(tmp_path):
    seen = {}
    lock = threading.Lock()
    active = {"now": 0, "peak": 0}

    def trainable(config):
        devices = tune.trial_devices()
        with lock:
            active["now"] += 1
            active["peak"] = max(active["peak"], active["now"])
        try:
            assert devices is not None and len(devices) == 4
            x = np.random.default_rng(0).normal(
                size=(32, 32)).astype(np.float32)
            trainer = Trainer(
                max_epochs=1, precision="f32", seed=0,
                accelerator=RayTPUAccelerator(devices=devices),
                enable_checkpointing=False,
                default_root_dir=str(tmp_path / f"t{config['i']}"))
            trainer.fit(BoringModel(), DataLoader(ArrayDataset(x),
                                                  batch_size=8))
            with lock:
                seen[config["i"]] = tuple(d.id for d in devices)
            tune.report(loss=float(config["i"]))
        finally:
            with lock:
                active["now"] -= 1

    analysis = tune.run(trainable,
                        config={"i": tune.grid_search([0, 1, 2, 3])},
                        num_samples=1, metric="loss", mode="min",
                        max_concurrent_trials=2, devices_per_trial=4,
                        local_dir=str(tmp_path))
    assert len(analysis.trials) == 4
    assert all(t.status == "TERMINATED" for t in analysis.trials)
    assert analysis.best_result["loss"] == 0.0
    # two distinct 4-device partitions were used, never overlapping
    partitions = set(seen.values())
    assert len(partitions) == 2
    a, b = partitions
    assert set(a) & set(b) == set()
    assert active["peak"] == 2  # trials genuinely overlapped


def test_sequential_mode_has_no_partition(tmp_path):
    def trainable(config):
        assert tune.trial_devices() is None
        tune.report(x=1.0)

    analysis = tune.run(trainable, config={}, num_samples=2,
                        metric="x", mode="max", local_dir=str(tmp_path))
    assert all(t.status == "TERMINATED" for t in analysis.trials)


def test_search_alg_rejects_concurrency(tmp_path):
    with pytest.raises(ValueError, match="sequential"):
        tune.run(lambda c: None, config={"x": tune.uniform(0, 1)},
                 num_samples=2, metric="m", mode="min",
                 search_alg=tune.TPESearcher(), max_concurrent_trials=2,
                 local_dir=str(tmp_path))


def test_oversized_partition_rejected(tmp_path):
    with pytest.raises(ValueError, match="exceeds"):
        tune.run(lambda c: None, config={}, num_samples=1,
                 max_concurrent_trials=2, devices_per_trial=64,
                 local_dir=str(tmp_path))


def test_scheduler_with_concurrent_trials(tmp_path):
    """ASHA decisions across overlapping trials must not corrupt state."""
    def trainable(config):
        for step in range(6):
            tune.report(score=config["v"] + step * 0.01)
            if tune.trial_should_stop():
                return

    analysis = tune.run(
        trainable, config={"v": tune.grid_search([0.1, 0.2, 0.9, 1.0])},
        num_samples=1, metric="score", mode="max",
        scheduler=tune.ASHAScheduler(max_t=6, grace_period=2,
                                     reduction_factor=2),
        max_concurrent_trials=2, devices_per_trial=4,
        local_dir=str(tmp_path))
    assert analysis.best_result["score"] >= 1.0
    assert all(t.status in ("TERMINATED", "STOPPED")
               for t in analysis.trials)


def test_concurrent_fail_fast_cancels_pending(tmp_path):
    import time as _time

    def trainable(config):
        if config["i"] == 0:
            raise RuntimeError("boom")
        _time.sleep(0.4)
        tune.report(x=1.0)

    with pytest.raises(RuntimeError, match="boom"):
        tune.run(trainable,
                 config={"i": tune.grid_search(list(range(8)))},
                 num_samples=1, metric="x", mode="max",
                 max_concurrent_trials=2, devices_per_trial=4,
                 local_dir=str(tmp_path))
