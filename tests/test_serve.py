"""Serve engine: continuous-batching exactness, typed admission control,
and replica supervision (wedged replica -> requeue, no lost/duplicated
responses).  All CPU, tier-1 fast."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu.models.transformer import (
    GPT, TransformerConfig)
from ray_lightning_accelerators_tpu.serve import (QueueFull,
                                                  RequestRejected,
                                                  ServeCancelled,
                                                  ServeEngine,
                                                  ServeReplicas)

pytestmark = pytest.mark.serve


def _model(vocab=97, layers=2, max_seq_len=48, seed=0):
    cfg = TransformerConfig(vocab_size=vocab, d_model=64, n_heads=2,
                            d_ff=128, n_layers=layers,
                            max_seq_len=max_seq_len)
    m = GPT(cfg)
    return m, m.init_params(jax.random.PRNGKey(seed))


def _requests(rng, n, vocab=97, len_lo=3, len_hi=11, new_lo=4, new_hi=12):
    out = []
    for _ in range(n):
        s0 = int(rng.integers(len_lo, len_hi))
        out.append((rng.integers(0, vocab, size=(s0,)).astype(np.int32),
                    int(rng.integers(new_lo, new_hi))))
    return out


# --------------------------------------------------------------------- #
# Engine: continuous batching                                           #
# --------------------------------------------------------------------- #
def test_continuous_batching_token_identical_to_generate():
    """The acceptance loop: >= 8 concurrent requests with staggered
    arrivals and different lengths -> every response token-identical to a
    standalone greedy generate(), and the engine proves it actually
    batched (>= 1 step with batch > 1)."""
    model, params = _model()
    reqs = _requests(np.random.default_rng(7), 8)
    refs = [np.asarray(model.generate(params, jnp.asarray(p[None]),
                                      max_new_tokens=n))[0]
            for p, n in reqs]
    with ServeEngine(model, params, max_slots=4, queue_depth=32) as eng:
        resps = []
        for i, (p, n) in enumerate(reqs):
            resps.append(eng.submit(p, n))
            if i % 3 == 2:       # staggered arrivals: some join mid-flight
                time.sleep(0.02)
        outs = [r.result(timeout=300) for r in resps]
    for out, ref in zip(outs, refs):
        np.testing.assert_array_equal(out, ref)
    snap = eng.stats()
    assert snap["completed"] == 8
    assert snap["steps_batch_gt1"] >= 1, snap
    assert snap["max_batch"] >= 2
    assert all(r.ttft_s is not None and r.ttft_s >= 0 for r in resps)
    # tail-latency fields are present through p99/max; queue_wait splits
    # queueing from prefill (one observation per admitted request, and
    # the wait can never exceed the ttft that contains it)
    for fam in ("ttft_s", "queue_wait_s", "token_latency_s",
                "decode_step_s"):
        for k in ("p50_s", "p95_s", "p99_s", "max_s"):
            assert k in snap[fam]
    assert snap["queue_wait_s"]["count"] == 8
    assert snap["queue_wait_s"]["max_s"] <= snap["ttft_s"]["max_s"]


def test_single_token_budget_completes_at_prefill():
    model, params = _model()
    prompt = np.asarray([5, 9, 2], np.int32)
    ref = np.asarray(model.generate(params, jnp.asarray(prompt[None]),
                                    max_new_tokens=1))[0]
    with ServeEngine(model, params, max_slots=2) as eng:
        out = eng.submit(prompt, 1).result(timeout=120)
    np.testing.assert_array_equal(out, ref)


def test_admission_typed_backpressure():
    """QueueFull / RequestRejected are typed and counted; an unstarted
    engine never dequeues, so the bound is deterministic.  Paged
    admission judges against the BLOCK budgets: a request the old dense
    check would have refused against max_total_len is admitted when its
    blocks fit, and the typed rejection names both pool budgets."""
    model, params = _model(max_seq_len=48)
    # chunked_prefill off: a streaming engine widens the per-slot table
    # to the model's max_seq_len (tests/test_long_context_serve.py); the
    # W-bucket budget this test pins needs the blocking admission span
    eng = ServeEngine(model, params, max_slots=1, queue_depth=3,
                      max_total_len=24, block_len=16, n_blocks=9,
                      chunked_prefill=False)
    try:
        eng.submit(np.asarray([1, 2], np.int32), 4)
        eng.submit(np.asarray([3], np.int32), 4)
        # 20 + 10 = 30 tokens: the DENSE check (max_total_len=24) would
        # refuse this, but it needs only 2 blocks of 16 — admitted
        eng.submit(np.asarray([1] * 20, np.int32), 10)
        with pytest.raises(QueueFull, match="depth cap"):
            eng.submit(np.asarray([4], np.int32), 4)
        # genuinely infeasible: 3 blocks > the 2-block per-slot table
        # (total 40 <= the model's 48, so the BLOCK budgets reject);
        # the typed error names both budgets
        with pytest.raises(RequestRejected,
                           match="block-table budget"):
            eng.submit(np.asarray([1] * 20, np.int32), 20)
        with pytest.raises(RequestRejected, match="pool"):
            eng.submit(np.asarray([1] * 20, np.int32), 20)
        # block rounding grants the table 32 positions, but the MODEL
        # is shaped for 48 total — 20 + 30 = 50 must reject exactly
        # like generate() would, whatever the table could hold
        with pytest.raises(RequestRejected, match="max_seq_len"):
            eng.submit(np.asarray([1] * 20, np.int32), 30)
        with pytest.raises(RequestRejected, match="empty"):
            eng.submit(np.asarray([], np.int32), 4)
        with pytest.raises(RequestRejected, match="max_new_tokens"):
            eng.submit(np.asarray([1, 2], np.int32), 0)
        # QueueFull + five RequestRejected = 6 typed rejections counted
        assert eng.stats()["rejected"] == 6
    finally:
        eng.stop(cancel_active=True, timeout=5)


def test_stop_cancels_queued_typed():
    model, params = _model()
    eng = ServeEngine(model, params, max_slots=1, queue_depth=8)
    r1 = eng.submit(np.asarray([1, 2, 3], np.int32), 4)
    r2 = eng.submit(np.asarray([4, 5], np.int32), 4)
    eng.stop()  # never started: both requests still queued
    for r in (r1, r2):
        with pytest.raises(ServeCancelled, match="cancelled"):
            r.result(timeout=5)
    # idempotent shutdown underneath (the TrampolineQueue satellite)
    assert eng.batcher.shutdown() == 0


def test_sliding_window_model_rejected():
    cfg = TransformerConfig(vocab_size=61, d_model=32, n_heads=2, d_ff=64,
                            n_layers=1, max_seq_len=32, sliding_window=8)
    m = GPT(cfg)
    with pytest.raises(ValueError, match="sliding_window"):
        ServeEngine(m, m.init_params(jax.random.PRNGKey(0)))


# --------------------------------------------------------------------- #
# Replicas: supervision + requeue                                       #
# --------------------------------------------------------------------- #
_REPLICA_CFG = dict(vocab_size=61, d_model=32, n_heads=2, d_ff=64,
                    n_layers=2, max_seq_len=32)


def _replica_factory(np_params):
    """Engine factory executed inside each worker (cloudpickled closure;
    params travel as numpy)."""
    def make():
        from ray_lightning_accelerators_tpu.models.transformer import (
            GPT, TransformerConfig)
        from ray_lightning_accelerators_tpu.serve import ServeEngine
        model = GPT(TransformerConfig(**_REPLICA_CFG))
        return ServeEngine(model, np_params, max_slots=4, queue_depth=32)
    return make


@pytest.mark.chaos
def test_wedged_replica_requeues_inflight_no_loss_no_dup():
    """The acceptance chaos loop: a hang injected in replica rank 1
    (RLA_TPU_CHAOS=hang@rank1:step2 — its first serve chunk) freezes its
    heartbeat; the pool watchdog reaps it; the chunk future fails
    WorkerWedged; its in-flight requests re-queue and complete on the
    surviving replica — every response present exactly once and
    token-identical to standalone generate()."""
    model = GPT(TransformerConfig(**_REPLICA_CFG))
    params = model.init_params(jax.random.PRNGKey(0))
    np_params = jax.tree.map(np.asarray, params)
    rng = np.random.default_rng(3)
    reqs = _requests(rng, 6, vocab=61, len_lo=3, len_hi=7, new_lo=3,
                     new_hi=6)
    refs = [np.asarray(model.generate(params, jnp.asarray(p[None]),
                                      max_new_tokens=n))[0]
            for p, n in reqs]
    hb = {"RLA_TPU_WORKER_HEARTBEAT_S": "0.1"}
    envs = [dict(hb), dict(hb, RLA_TPU_CHAOS="hang@rank1:step2")]
    # hedging OFF: this test pins the REQUEUE recovery path, and a hedge
    # racing the watchdog reap can legitimately complete the hung
    # chunk's requests first (requeue then no-ops via resp.done()) —
    # hedged recovery is pinned separately in test_serve_resilience
    from ray_lightning_accelerators_tpu.serve import ControllerConfig
    group = ServeReplicas(_replica_factory(np_params), num_replicas=2,
                          chunk_size=2, wedge_timeout_s=1.5,
                          env_per_worker=envs,
                          controller=ControllerConfig(hedge=False))
    try:
        resps = [group.submit(p, n) for p, n in reqs]
        outs = [r.result(timeout=180) for r in resps]
    finally:
        group.shutdown()
    for out, ref in zip(outs, refs):
        np.testing.assert_array_equal(out, ref)
    snap = group.stats()
    # no response lost, none duplicated: 6 submitted, 6 completed, the
    # wedged chunk's requests re-queued (not failed, not double-counted)
    assert snap["submitted"] == 6
    assert snap["completed"] == 6
    assert snap["failed"] == 0
    assert snap["requeued"] >= 1
    assert snap["wedge_events"] >= 1
    assert 1 in snap["replicas_down"]
