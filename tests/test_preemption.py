"""Preemption-safe elastic resume: notice drain, typed Preempted, elastic
scale-down after a lost host, world-resize restore, checkpoint integrity.

The three recovery paths the ISSUE-5 tentpole adds on top of PR 1's
watchdog/elastic layer:

1. a SIGTERM/``RLA_TPU_PREEMPT_GRACE_S`` notice drains into an emergency
   checkpoint and a typed ``Preempted`` that ``ElasticRunner`` resumes
   WITHOUT charging the failure budget and ``fit(ckpt_path="last")``
   resumes at the exact step;
2. a permanently lost rank (chaos ``lost@rankN``) triggers an elastic
   scale-down: the pool rebuilds at the surviving size and the ZeRO-1 /
   per-replica state restores onto the smaller mesh;
3. per-leaf digests in ``meta.json`` make torn checkpoints detectable,
   ``latest_checkpoint`` walks back to the newest VERIFIED one, and
   ``keep_last_k`` GC never deletes the only verified resume anchor.
"""

import json
import os
import signal

import pytest

from ray_lightning_accelerators_tpu import (Callback, ElasticResizeError,
                                            ModelCheckpoint,
                                            Preempted, RayTPUAccelerator,
                                            Trainer, get_notice)
from ray_lightning_accelerators_tpu.runtime import preemption as preempt_lib
from ray_lightning_accelerators_tpu.runtime.actors import (ActorPool,
                                                           RemoteError)
from ray_lightning_accelerators_tpu.runtime.elastic import (ElasticRunner,
                                                            backoff_delay_s)
from ray_lightning_accelerators_tpu.testing.chaos import parse_chaos
from ray_lightning_accelerators_tpu.utils import checkpoint as ckpt_lib
from ray_lightning_accelerators_tpu.utils import \
    sharded_checkpoint as sharded_lib
from tests.utils import BoringModel, boring_loaders

HB = 0.05


# --------------------------------------------------------------------- #
# typed Preempted + notice plumbing (pure / in-process)                  #
# --------------------------------------------------------------------- #
def test_preempted_survives_the_wire():
    p = Preempted.at_step(7, "/ckpts/preempt-step7.ckpt",
                          source="signal-15")
    # worker pipe / agent relay ship (name, str(exc), tb); the typed
    # outcome must rebuild from the message alone
    relayed = RemoteError("Preempted", str(p), "remote tb")
    assert preempt_lib.is_preemption(p)
    assert preempt_lib.is_preemption(relayed)
    rebuilt = preempt_lib.as_preempted(relayed)
    assert rebuilt.step == 7
    assert rebuilt.ckpt_path == "/ckpts/preempt-step7.ckpt"
    assert not preempt_lib.is_preemption(RuntimeError("worker 1 died"))


def test_parse_new_chaos_kinds():
    lost, pre = parse_chaos("lost@rank1,preempt@rank0:step2")
    assert lost.kind == "lost" and lost.rank == 1
    assert pre.kind == "preempt" and pre.step == 2
    # crash/hang-style default: fire on the first dispatch
    assert lost.matches(rank=1, step=1) and not lost.matches(rank=1, step=2)
    with pytest.raises(ValueError, match="RLA_TPU_CHAOS_NS"):
        from ray_lightning_accelerators_tpu.testing.chaos import \
            ChaosInjector
        ChaosInjector(parse_chaos("lost@rank0"), rank=0, ns_dir=None)


def test_backoff_exponential_jitter_cap():
    # deterministic rng: low end of the jitter band is half the target
    assert backoff_delay_s(1, 2.0, rng=lambda: 0.0) == 1.0
    assert backoff_delay_s(1, 2.0, rng=lambda: 1.0) == 2.0
    assert backoff_delay_s(3, 2.0, rng=lambda: 1.0) == 8.0
    assert backoff_delay_s(10, 2.0, cap_s=6.0, rng=lambda: 1.0) == 6.0
    assert backoff_delay_s(5, 0.0) == 0.0  # base 0 = backoff disabled


@pytest.mark.preempt
def test_sigterm_sets_notice_and_flag_file(tmp_path, monkeypatch):
    monkeypatch.setenv(preempt_lib.PREEMPT_GRACE_ENV, "30")
    notice = get_notice()
    try:
        assert notice.install(flag_dir=str(tmp_path))
        notice.busy = True  # mid-dispatch: handler drains, never exits
        os.kill(os.getpid(), signal.SIGTERM)
        assert notice.requested()
        assert notice.source.startswith("signal-")
        assert os.path.exists(
            os.path.join(str(tmp_path), preempt_lib.FLAG_FILENAME))
        assert notice.remaining_s() <= 30.0
        # a second process-local notice sees the flag file alone
        other = preempt_lib.PreemptionNotice()
        other.attach_flag_dir(str(tmp_path))
        assert other.requested() and other.source == "flag-file"
    finally:
        notice.busy = False
        notice.clear()
        notice.uninstall()


# --------------------------------------------------------------------- #
# Trainer drain: emergency checkpoint + exact-step resume                #
# --------------------------------------------------------------------- #
class _RaiseNoticeAt(Callback):
    def __init__(self, step):
        self.step = step

    def on_train_batch_end(self, trainer, module, metrics, batch_idx):
        if trainer.global_step == self.step:
            get_notice().request_local("test-notice")


class _CountSteps(Callback):
    def __init__(self):
        self.steps = []

    def on_train_batch_end(self, trainer, module, metrics, batch_idx):
        self.steps.append(trainer.global_step)


@pytest.mark.preempt
def test_trainer_drains_and_resumes_at_exact_step(tmp_path, monkeypatch):
    """The fit-level acceptance loop: notice at step 3 -> emergency
    sharded checkpoint inside the grace budget -> typed Preempted ->
    a fresh fit(ckpt_path="last") resumes at step 4 and runs exactly
    the remaining steps."""
    monkeypatch.setenv(preempt_lib.PREEMPT_GRACE_ENV, "60")
    train, val = boring_loaders()
    tr = Trainer(max_steps=10, default_root_dir=str(tmp_path),
                 checkpoint_format="sharded", prefetch_batches=0,
                 callbacks=[_RaiseNoticeAt(3)])
    try:
        with pytest.raises(Preempted) as ei:
            tr.fit(BoringModel(), train, val)
    finally:
        get_notice().clear()
        get_notice().uninstall()
    assert ei.value.step == 3
    assert ei.value.ckpt_path and "preempt-step3" in ei.value.ckpt_path
    ok, why = sharded_lib.verify_checkpoint(ei.value.ckpt_path)
    assert ok, why
    meta = sharded_lib.read_metadata(ei.value.ckpt_path)
    assert meta["global_step"] == 3

    # "last" resolves to the (verified) emergency checkpoint
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == os.path.abspath(
        ei.value.ckpt_path)
    counter = _CountSteps()
    tr2 = Trainer(max_steps=10, default_root_dir=str(tmp_path),
                  checkpoint_format="sharded", prefetch_batches=0,
                  callbacks=[counter])
    tr2.fit(BoringModel(), train, val, ckpt_path="last")
    # exact-step resume: first post-restore step is 4, 7 steps run total
    assert counter.steps[0] == 4
    assert counter.steps == list(range(4, 11))
    assert tr2.global_step == 10


@pytest.mark.preempt
def test_stale_flag_file_does_not_redrain_fresh_fit(tmp_path, monkeypatch):
    """A flag file left by a PREVIOUS drain must not preempt the resumed
    run at its first step — fit clears stale flags at bind time (only a
    live local notice keeps one)."""
    monkeypatch.setenv(preempt_lib.PREEMPT_GRACE_ENV, "30")
    flag = tmp_path / preempt_lib.FLAG_FILENAME
    flag.write_text('{"source": "previous-drain"}')
    train, val = boring_loaders()
    tr = Trainer(max_steps=3, default_root_dir=str(tmp_path),
                 prefetch_batches=0, enable_checkpointing=False)
    try:
        tr.fit(BoringModel(), train, val)  # completes, no Preempted
    finally:
        get_notice().clear()
        get_notice().uninstall()
    assert tr.global_step == 3
    assert not flag.exists()


def test_build_args_arity_ignores_keyword_params():
    """Only genuinely positional second parameters receive world_size —
    (attempt, **opts) and keyword-only builders keep the 1-arg call."""
    class _StubPool:
        workers = [None, None]

        def __len__(self):
            return 2

    runner = ElasticRunner(_StubPool(), max_failures=0)
    legacy = runner._build_args(lambda a, **kw: [(a,), (a,)], 0)
    assert legacy == [(0,), (0,)]
    kwonly = runner._build_args(
        lambda a, *, log=None: [(a,), (a,)], 1)
    assert kwonly == [(1,), (1,)]
    # a DEFAULTED second positional param is not world-size-aware either
    # -- overwriting its default with the pool size would corrupt it
    defaulted = runner._build_args(
        lambda a, tag="x": [(a, tag), (a, tag)], 3)
    assert defaulted == [(3, "x"), (3, "x")]
    aware = runner._build_args(
        lambda a, world: [(a, world)] * world, 2)
    assert aware == [(2, 2), (2, 2)]


# --------------------------------------------------------------------- #
# Elastic resume onto a different world size                             #
# --------------------------------------------------------------------- #
def test_resume_zero1_checkpoint_onto_smaller_mesh(tmp_path):
    """A ZeRO-1 + int8-compression checkpoint saved on an 8-device mesh
    restores onto a 4-device mesh: global shapes redistribute through
    restore_sharded's abstract arrays, per-replica residuals reset with
    a warning, and training continues from the saved step."""
    train, val = boring_loaders()
    kwargs = dict(checkpoint_format="sharded", shard_optimizer_state=True,
                  grad_compression="int8", default_root_dir=str(tmp_path),
                  enable_checkpointing=False, prefetch_batches=0)
    tr = Trainer(max_steps=4,
                 accelerator=RayTPUAccelerator(num_workers=8), **kwargs)
    tr.fit(BoringModel(), train, val)
    path = str(tmp_path / "resize.ckpt")
    tr.save_checkpoint(path)
    assert sharded_lib.read_metadata(path)["world"]["dp"] == 8

    tr2 = Trainer(max_steps=8,
                  accelerator=RayTPUAccelerator(num_workers=4), **kwargs)
    tr2.fit(BoringModel(), train, val, ckpt_path=path)
    assert tr2._resumed_world_resize == (8, 4)
    assert tr2.global_step == 8  # resumed from 4, ran 4 more

    # typed refusal only when divisibility genuinely breaks: batch 8
    # cannot split over a 3-wide data axis
    tr3 = Trainer(max_steps=8,
                  accelerator=RayTPUAccelerator(num_workers=3), **kwargs)
    with pytest.raises(ElasticResizeError, match="not divisible"):
        tr3.fit(BoringModel(), train, val, ckpt_path=path)


# --------------------------------------------------------------------- #
# Checkpoint integrity + retention                                       #
# --------------------------------------------------------------------- #
def _truncate_one_shard(path):
    files = sharded_lib.read_metadata(path)["integrity"]["files"]
    rel = max(files, key=lambda r: files[r]["bytes"])
    fp = os.path.join(path, sharded_lib.STATE_DIR, rel)
    with open(fp, "r+b") as f:
        f.truncate(max(1, files[rel]["bytes"] // 2))
    return rel


def test_truncated_shard_detected_and_resume_falls_back(tmp_path):
    """The corrupt-checkpoint acceptance path: the NEWEST checkpoint is
    torn (truncated shard file); verify_checkpoint flags it,
    latest_checkpoint walks back to the previous verified one, and
    fit(ckpt_path="last") resumes from it instead of crashing."""
    train, val = boring_loaders()
    tr = Trainer(max_steps=3, default_root_dir=str(tmp_path),
                 checkpoint_format="sharded", prefetch_batches=0,
                 enable_checkpointing=False)
    tr.fit(BoringModel(), train, val)
    good = str(tmp_path / "step3.ckpt")
    tr.save_checkpoint(good)
    bad = str(tmp_path / "newer.ckpt")
    tr.save_checkpoint(bad)
    os.utime(bad)  # unambiguously newest

    rel = _truncate_one_shard(bad)
    ok, why = sharded_lib.verify_checkpoint(bad)
    assert not ok and rel in why
    assert sharded_lib.verify_checkpoint(good) == (True, "ok")
    # walk-back lands on the older verified checkpoint
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == os.path.abspath(good)

    counter = _CountSteps()
    tr2 = Trainer(max_steps=5, default_root_dir=str(tmp_path),
                  checkpoint_format="sharded", prefetch_batches=0,
                  enable_checkpointing=False, callbacks=[counter])
    tr2.fit(BoringModel(), train, val, ckpt_path="last")
    assert counter.steps[0] == 4  # resumed from the verified step-3 save
    assert tr2.global_step == 5


def test_meta_missing_dir_skipped_by_latest(tmp_path):
    torn = tmp_path / "torn.ckpt"
    (torn / "state").mkdir(parents=True)  # array commit landed, no meta
    (torn / "state" / "leaf").write_bytes(b"x" * 32)
    assert not sharded_lib.is_sharded_checkpoint(str(torn))
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) is None


def test_keep_last_k_never_deletes_only_verified(tmp_path):
    """Retention GC keeps the newest k, but when every checkpoint in the
    window is torn it must keep the newest VERIFIED one too — deleting
    it would destroy the only resume anchor."""
    import time

    import jax
    import jax.numpy as jnp
    paths = []
    for i in range(4):
        p = str(tmp_path / f"step{i}.ckpt")
        sharded_lib.save_sharded(p, {"w": jnp.ones((4,)) * i},
                                 {"global_step": i})
        os.utime(p, (time.time() + i, time.time() + i))
        paths.append(p)
    for p in paths[2:]:  # the two NEWEST are torn
        _truncate_one_shard(p)
    removed = ckpt_lib.prune_checkpoints(str(tmp_path), keep_last_k=2)
    # newest-verified (step1) survives outside the window; step0 is GC'd
    assert removed == [paths[0]]
    assert sorted(os.listdir(tmp_path)) == ["step1.ckpt", "step2.ckpt",
                                            "step3.ckpt"]
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == paths[1]
    del jax


def test_model_checkpoint_keep_last_k(tmp_path):
    """ModelCheckpoint(keep_last_k=...) GCs checkpoints its top-k
    bookkeeping does not track (emergency saves, prior runs' leftovers)
    while protecting its own snapshots."""
    import time

    import jax.numpy as jnp
    dirpath = tmp_path / "checkpoints"
    dirpath.mkdir()
    train, val = boring_loaders()
    strays = []
    for i in range(3):  # leftovers from an earlier (preempted) run
        p = str(dirpath / f"preempt-step{i}.ckpt")
        sharded_lib.save_sharded(p, {"w": jnp.ones((4,))},
                                 {"global_step": i})
        old = time.time() - 1000 + i
        os.utime(p, (old, old))
        strays.append(p)
    cb = ModelCheckpoint(monitor=None, save_top_k=1, keep_last_k=2,
                         dirpath=str(dirpath))
    tr = Trainer(max_epochs=1, limit_train_batches=2,
                 default_root_dir=str(tmp_path), prefetch_batches=0,
                 checkpoint_format="sharded", callbacks=[cb])
    tr.fit(BoringModel(), train, val)
    kept = ckpt_lib.list_checkpoints(str(dirpath))
    assert len(kept) == 2  # the fit's save + the newest stray
    assert os.path.abspath(cb.best_model_path) in {
        os.path.abspath(p) for p in kept}
    assert not os.path.exists(strays[0]) and not os.path.exists(strays[1])
    with pytest.raises(ValueError, match="keep_last_k"):
        ModelCheckpoint(keep_last_k=0)


def test_async_save_registers_exit_fence():
    sharded_lib._checkpointer(True)
    assert sharded_lib._atexit_registered


# --------------------------------------------------------------------- #
# chaos acceptance loops (worker processes)                              #
# --------------------------------------------------------------------- #
def _preempt_train_body(rank, ckpt_dir, total_steps):
    """A checkpointing trainable that honors the preemption contract:
    poll the notice at every step boundary, emergency-checkpoint, raise
    the typed Preempted (the Trainer.fit drain, minus jax so the loop
    stays tier-1 fast)."""
    import json
    import os
    from ray_lightning_accelerators_tpu.runtime import preemption
    notice = preemption.get_notice()
    path = os.path.join(ckpt_dir, "state.json")
    start = 0
    if os.path.exists(path):
        with open(path) as f:
            start = json.load(f)["step"]
    for step in range(start, total_steps):
        if notice.requested():
            if rank == 0:
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"step": step}, f)
                os.replace(tmp, path)
            raise preemption.Preempted.at_step(step, path,
                                               source=notice.source)
        if rank == 0:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"step": step + 1}, f)
            os.replace(tmp, path)
    return (rank, start, total_steps)


@pytest.mark.chaos
@pytest.mark.preempt
def test_chaos_preempt_drains_and_elastic_resumes_exact_step(tmp_path):
    """The preemption acceptance loop: ``preempt@rank0:step2`` SIGTERMs
    rank 0 on its second dispatch (the worker's notice handler is
    installed via RLA_TPU_PREEMPT_GRACE_S in its env); the body drains
    at its next step boundary into an emergency checkpoint and a typed
    Preempted; ElasticRunner resumes WITHOUT charging the failure
    budget; the retry picks up at the exact drained step."""
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    env = {"RLA_TPU_CHAOS": "preempt@rank0:step2",
           "RLA_TPU_PREEMPT_GRACE_S": "60",
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB)}
    pool = ActorPool(2, env_per_worker=[dict(env), dict(env)])
    failures = []
    try:
        # dispatch 1: both ranks run the first 3 steps cleanly
        for f in pool.execute_per_worker(
                _preempt_train_body, [(r, ckpt, 3) for r in range(2)]):
            f.result(timeout=120)
        runner = ElasticRunner(pool, max_failures=0,
                               on_failure=lambda a, e: failures.append(e))
        # dispatch 2: chaos preempts rank 0 AT dispatch -> the body sees
        # the notice at its first boundary (step 3, resumed from the
        # checkpoint) -> emergency save + Preempted; the restarted
        # process's dispatch counter resets, so the retry runs clean
        out = runner.run(_preempt_train_body,
                         args_per_worker=lambda a: [(r, ckpt, 6)
                                                    for r in range(2)])
        assert failures == []  # a drain is NOT a failure (max_failures=0)
        assert runner.attempts_used == 2
        (drain,) = runner.preempt_events
        assert drain.step == 3  # drained at the exact resumed boundary
        assert drain.info["source"].startswith("signal-")
        # the retry resumed at the drained step and completed.  Rank 0
        # OWNS the checkpoint, so its resume point is exact; rank 1 reads
        # whatever rank 0 last wrote at its own boot instant — with
        # near-instant steps that is a boot-skew race (flaky on pre-PR
        # HEAD too), so only bound it to valid resume points.
        by_rank = {r[0]: r for r in out}
        assert by_rank[0][1] == 3
        assert 3 <= by_rank[1][1] <= 6
        with open(os.path.join(ckpt, "state.json")) as f:
            assert json.load(f)["step"] == 6
    finally:
        pool.shutdown()


def _world_train_body(logical_rank, world, ckpt_dir, total_steps):
    """World-size-aware deterministic descent with an SPMD-style step
    barrier: every step, each logical rank posts a marker and waits for
    all ``world`` peers before applying the (full-batch, world-invariant)
    update — a missing peer stalls the step exactly like a torn
    collective, so a lost rank stops the survivors' progress until the
    pool shrinks and the barrier width matches the new world.  The world
    size of every executed step is recorded to prove the post-shrink
    steps really ran at N-1."""
    import json
    import os
    import time
    path = os.path.join(ckpt_dir, "state.json")
    bdir = os.path.join(ckpt_dir, "barrier")
    os.makedirs(bdir, exist_ok=True)
    state = {"step": 0, "w": 1.0, "worlds": []}
    if os.path.exists(path):
        with open(path) as f:
            state = json.load(f)
    w = state["w"]
    for step in range(state["step"], total_steps):
        open(os.path.join(bdir, f"s{step}.r{logical_rank}"), "w").close()
        deadline = time.monotonic() + 60.0
        while not all(os.path.exists(os.path.join(bdir, f"s{step}.r{r}"))
                      for r in range(world)):
            if time.monotonic() > deadline:
                raise RuntimeError(f"step {step} barrier lost a peer "
                                   f"(world={world})")
            time.sleep(0.02)
        w = w - 0.1 * (2.0 * w)  # dL/dw of L = w^2
        state = {"step": step + 1, "w": w,
                 "worlds": state["worlds"] + [world]}
        if logical_rank == 0:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(state, f)
            os.replace(tmp, path)
    return (logical_rank, world, state["step"], w)


@pytest.mark.chaos
@pytest.mark.preempt
def test_chaos_lost_rank_scales_down_and_resumes(tmp_path):
    """The lost-host acceptance loop: ``lost@rank1:step2`` kills rank 1
    with a persistent marker, so its respawn dies at boot; the probe
    finds it unrecoverable, the pool shrinks to the surviving rank, the
    retry dispatches with world_size=1, and the descent trajectory
    CONTINUES — steps 0-2 ran at world 2, steps 3-5 at world 1, final
    loss bit-equal to an uninterrupted run (the update is full-batch,
    world-invariant — the elastic contract)."""
    ns = str(tmp_path / "chaos_ns")
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    env = {"RLA_TPU_CHAOS": "lost@rank1:step2", "RLA_TPU_CHAOS_NS": ns,
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB)}
    pool = ActorPool(2, env_per_worker=[dict(env), dict(env)])
    try:
        # dispatch 1: both ranks run steps 0-2 together at world 2
        for f in pool.execute_per_worker(
                _world_train_body, [(r, 2, ckpt, 3) for r in range(2)]):
            f.result(timeout=120)
        runner = ElasticRunner(pool, max_failures=2, allow_shrink=True,
                               min_workers=1, probe_timeout_s=120.0)
        # dispatch 2: rank 1's host is lost AT dispatch; rank 0 stalls on
        # the step-3 barrier until the restart clears it, the respawned
        # rank 1 dies at boot, the probe drops it, and the retry runs
        # steps 3-5 alone at world 1
        out = runner.run(
            _world_train_body,
            args_per_worker=lambda a, world: [(r, world, ckpt, 6)
                                              for r in range(world)])
        assert runner.shrink_events == [
            {"dropped": [1], "world_size": 1, "attempt": 2}]
        assert len(pool) == 1 and pool.workers[0].rank == 0
        assert [r[1] for r in out] == [1]  # re-dispatched with world=1
        with open(os.path.join(ckpt, "state.json")) as f:
            final = json.load(f)
        assert final["step"] == 6
        # the trajectory crossed the shrink: world sizes per step
        assert final["worlds"] == [2, 2, 2, 1, 1, 1]
        # continuing loss: bit-equal to the uninterrupted descent
        w = 1.0
        for _ in range(6):
            w = w - 0.1 * (2.0 * w)
        assert final["w"] == pytest.approx(w, abs=0.0)
        # rank 1's lost marker survived in the namespace (host stays gone)
        assert any(n.endswith(".lost") for n in os.listdir(ns))
    finally:
        pool.shutdown()


def test_elastic_args_sizing_validated_against_pool():
    """args_per_worker sizing is validated against the live pool as a
    configuration error (never burned as a retry); no workers needed —
    the check fires before any dispatch."""
    class _StubPool:
        workers = [None]

        def __len__(self):
            return 1

    runner = ElasticRunner(_StubPool(), max_failures=0)
    with pytest.raises(ValueError, match="argument tuples"):
        runner.run(_world_train_body,
                   args_per_worker=lambda a, world: [
                       (r, world, "/tmp", 1) for r in range(3)])


@pytest.mark.chaos
@pytest.mark.preempt
def test_preemption_budget_exhausted_writes_run_report(tmp_path):
    """Exhausting max_preemptions is a TERMINAL exit like the failure
    budget: with report_dir set it must leave a run_report.json naming
    the final Preempted, not an empty directory (review finding: this
    was the only terminal ElasticRunner exit with no postmortem)."""
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    report_dir = str(tmp_path / "reports")
    env = {"RLA_TPU_CHAOS": "preempt@rank0",
           "RLA_TPU_PREEMPT_GRACE_S": "60",
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB)}
    pool = ActorPool(2, env_per_worker=[dict(env), dict(env)])
    try:
        runner = ElasticRunner(pool, max_failures=0, max_preemptions=0,
                               report_dir=report_dir)
        with pytest.raises(RuntimeError, match="max_preemptions"):
            runner.run(_preempt_train_body,
                       args_per_worker=lambda a: [(r, ckpt, 6)
                                                  for r in range(2)])
        rep = json.load(open(os.path.join(report_dir,
                                          "run_report.json")))
        assert rep["error"]["type"] == "Preempted"
        assert rep["extra"]["attempts_used"] == 1
    finally:
        pool.shutdown()
