"""ViT model family: shapes, patchify, learning, sharded fit."""

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_accelerators_tpu import (DataLoader, RayTPUAccelerator,
                                            Trainer)
from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
from ray_lightning_accelerators_tpu.models.resnet import synthetic_cifar10
from ray_lightning_accelerators_tpu.models.vit import ViT, ViTConfig


def _tiny(**kw):
    cfg = ViTConfig(image_size=16, patch_size=4, d_model=64, n_heads=2,
                    d_ff=128, n_layers=2, n_classes=10, **kw)
    m = ViT(cfg)
    return m, m.init_params(jax.random.PRNGKey(0))


def test_forward_shape():
    model, params = _tiny()
    x = jnp.zeros((4, 16, 16, 3))
    logits = model.forward(params, x)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_patchify_order():
    model, _ = _tiny()
    # image whose value encodes (row, col): patch rows must group spatially
    x = jnp.arange(16 * 16, dtype=jnp.float32).reshape(1, 16, 16, 1)
    model.cfg = ViTConfig(image_size=16, patch_size=4, channels=1)
    patches = model._patchify(x)
    assert patches.shape == (1, 16, 16)
    # first patch = rows 0..3 x cols 0..3
    expect = x[0, :4, :4, 0].reshape(-1)
    np.testing.assert_array_equal(np.asarray(patches[0, 0]),
                                  np.asarray(expect))


def test_learns_synthetic_cifar():
    x, y = synthetic_cifar10(512, seed=0)
    x16 = x[:, 8:24, 8:24, :]
    train = DataLoader(ArrayDataset(x16, y), batch_size=64, shuffle=True)
    model, _ = _tiny()
    trainer = Trainer(max_epochs=6, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir="/tmp/vit_test")
    trainer.fit(model, train)
    assert trainer.callback_metrics["accuracy"] > 0.5


def test_sharded_fit_dp_tp():
    x, y = synthetic_cifar10(128, seed=1)
    x16 = x[:, 8:24, 8:24, :]
    train = DataLoader(ArrayDataset(x16, y), batch_size=32, shuffle=False)
    model, _ = _tiny()
    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      accelerator=RayTPUAccelerator(4, tensor=2),
                      enable_checkpointing=False,
                      default_root_dir="/tmp/vit_tp_test")
    trainer.fit(model, train)
    assert trainer.global_step == 4
    # params actually sharded over the tensor axis
    wi = trainer._state.params["layers"]["mlp"]["wi"]
    assert len(wi.sharding.device_set) == 8
