"""Async input pipeline (data/prefetch.py + Trainer(prefetch_batches)):
order determinism, bounded depth, clean shutdown, typed error
propagation, IterableDataset round-robin preservation, and the
acceptance bar — train-loss BIT-IDENTITY between prefetch on/off over a
multi-step MNIST run (the pipeline changes where host work runs, never
what runs)."""

import threading
import time

import jax
import numpy as np
import pytest

from ray_lightning_accelerators_tpu import (ArrayDataset, DataLoader,
                                            RayTPUAccelerator, Trainer)
from ray_lightning_accelerators_tpu.core.callbacks import Callback
from ray_lightning_accelerators_tpu.data.loader import (IterableDataset,
                                                        default_collate)
from ray_lightning_accelerators_tpu.data.prefetch import (DevicePrefetcher,
                                                          PrefetchClosed,
                                                          PrefetchIterator,
                                                          prefetch_pipeline)
from ray_lightning_accelerators_tpu.models.mnist import (MNISTClassifier,
                                                         synthetic_mnist)
from ray_lightning_accelerators_tpu.utils.profiler import Profiler

from .utils import BoringModel, boring_loaders

pytestmark = pytest.mark.prefetch


def _prefetch_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("rla-prefetch") and t.is_alive()]


# --------------------------------------------------------------------- #
# PrefetchIterator / DevicePrefetcher unit behavior                      #
# --------------------------------------------------------------------- #
def test_order_preserved_and_thread_joined():
    it = PrefetchIterator(iter(range(100)), depth=3)
    assert list(it) == list(range(100))
    assert not _prefetch_threads()  # exhaustion joins the producer


def test_depth_bounds_producer_runahead():
    pulled = []

    def source():
        for i in range(50):
            pulled.append(i)
            yield i

    it = PrefetchIterator(source(), depth=3)
    try:
        consumed = 0
        for v in it:
            time.sleep(0.01)  # slow consumer: the producer races ahead
            consumed += 1
            # at most depth queued + 1 in the producer's hand
            assert len(pulled) <= consumed + 3 + 1
            if consumed == 20:
                break
    finally:
        it.close()
    assert not _prefetch_threads()


def test_close_mid_iteration_is_idempotent_and_final():
    it = PrefetchIterator(iter(range(1000)), depth=2)
    assert next(it) == 0
    it.close()
    it.close()  # idempotent
    assert not _prefetch_threads()
    with pytest.raises(PrefetchClosed):
        next(it)


def test_worker_exception_is_typed_and_in_order():
    def source():
        yield from (0, 1)
        raise ValueError("collate exploded")

    it = PrefetchIterator(source(), depth=4)
    assert next(it) == 0
    assert next(it) == 1
    with pytest.raises(ValueError, match="collate exploded"):
        next(it)  # ORIGINAL type + message, not a queue timeout
    assert not _prefetch_threads()


def test_device_prefetcher_place_error_surfaces_at_its_position():
    def place(i):
        if i == 3:
            raise RuntimeError("bad placement")
        return i * 10

    pipe = prefetch_pipeline(iter(range(6)), depth=4, place_fn=place)
    try:
        assert [next(pipe) for _ in range(3)] == [0, 10, 20]
        with pytest.raises(RuntimeError, match="bad placement"):
            next(pipe)  # items 0..2 consumed FIRST, then the failure
    finally:
        pipe.close()
    assert not _prefetch_threads()


def test_device_prefetcher_runs_ahead_of_consumer():
    placed = []
    pipe = prefetch_pipeline(iter(range(10)), depth=3,
                             place_fn=lambda i: placed.append(i) or i)
    try:
        got = [next(pipe) for _ in range(3)]
        time.sleep(0.3)  # let the host stage fill its queue
        next(pipe)
        # after 4 consumed, placement has been issued past the consumer
        assert got == [0, 1, 2] and len(placed) >= 5
    finally:
        pipe.close()


def test_device_prefetcher_close_handles_plain_iterators():
    # direct construction over a generator (no close-with-timeout, and
    # bare iterables with no close at all) must shut down cleanly
    d = DevicePrefetcher((i for i in range(5)), depth=2)
    assert next(d) == 0
    d.close()
    with DevicePrefetcher(iter([1, 2, 3]), depth=2) as d2:
        assert next(d2) == 1


def test_depth_validation():
    with pytest.raises(ValueError, match="depth"):
        PrefetchIterator(iter(()), depth=0)
    with pytest.raises(ValueError, match="prefetch_batches"):
        Trainer(prefetch_batches=-1)


# --------------------------------------------------------------------- #
# IterableDataset round-robin sharding (regression)                      #
# --------------------------------------------------------------------- #
class _EpochStream(IterableDataset):
    """Deterministic epoch-reshuffled stream of scalar rows."""

    def __init__(self, n: int):
        self.n = n
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __iter__(self):
        order = np.random.default_rng((99, self.epoch)).permutation(self.n)
        for i in order:
            yield np.asarray([i], np.float32)


def test_iterable_round_robin_shards_survive_prefetch():
    """Prefetch must reproduce the EXACT per-rank interleaved slices the
    unprefetched loader yields — per epoch, including set_epoch
    reshuffles — and the ranks must stay disjoint and cover the
    stream."""
    n, replicas, bs = 48, 2, 4
    for epoch in (0, 1):
        per_rank_plain, per_rank_pref = [], []
        for rank in range(replicas):
            def batches(prefetch: bool):
                ds = _EpochStream(n)
                loader = DataLoader(ds, batch_size=bs)
                loader._inject_sampler(num_replicas=replicas, rank=rank,
                                       shuffle=False)
                loader.set_epoch(epoch)
                if not prefetch:
                    return list(loader)
                it = PrefetchIterator(iter(loader), depth=2)
                try:
                    return list(it)
                finally:
                    it.close()

            plain, pref = batches(False), batches(True)
            assert len(plain) == len(pref) > 0
            for a, b in zip(plain, pref):
                np.testing.assert_array_equal(a, b)  # identical order
            per_rank_plain.append(np.concatenate(plain).ravel())
            per_rank_pref.append(np.concatenate(pref).ravel())
        flat = np.concatenate(per_rank_pref)
        assert len(set(flat.tolist())) == len(flat)  # disjoint shards
        # together the ranks cover every complete block of the stream
        covered = sorted(int(v) for v in flat)
        expected = sorted(
            int(v) for v in
            np.random.default_rng((99, epoch)).permutation(n)[
                :len(flat)])
        assert covered == expected
    # epochs genuinely reshuffled (set_epoch reached the stream)
    assert not np.array_equal(
        np.random.default_rng((99, 0)).permutation(n),
        np.random.default_rng((99, 1)).permutation(n))
    assert not _prefetch_threads()


# --------------------------------------------------------------------- #
# Trainer integration                                                    #
# --------------------------------------------------------------------- #
class _LossTrace(Callback):
    def __init__(self, key: str = "ptl/train_loss"):
        self.key = key
        self.losses = []

    def on_train_batch_end(self, trainer, module, metrics, batch_idx):
        self.losses.append(float(jax.device_get(metrics[self.key])))


def _mnist_fit(prefetch: int, **kwargs):
    x, y = synthetic_mnist(64 * 6, seed=0)
    loader = DataLoader(ArrayDataset(x, y), batch_size=64, shuffle=True)
    model = MNISTClassifier({"layer_1": 32, "layer_2": 32, "lr": 1e-3,
                             "batch_size": 64})
    trace = _LossTrace()
    trainer = Trainer(max_epochs=2, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False,
                      log_every_n_steps=10 ** 9, seed=0,
                      cache_dataset_on_device=False,
                      prefetch_batches=prefetch, callbacks=[trace],
                      **kwargs)
    trainer.fit(model, loader)
    return trainer, trace.losses


def test_train_loss_bit_identical_prefetch_on_off():
    """The acceptance bar: a multi-step MNIST run produces the EXACT
    same loss trajectory with prefetch 0 and 2 — the pipeline moves
    host work, it never changes batch content, order, or math."""
    t0, losses0 = _mnist_fit(0)
    t2, losses2 = _mnist_fit(2)
    assert t0.global_step == t2.global_step == 12
    assert len(losses0) == len(losses2) == 12
    assert losses0 == losses2  # bit-identical, not allclose
    assert not _prefetch_threads()


def test_early_stops_join_thread_and_match_unprefetched():
    # limit_train_batches redefines the epoch
    t0, l0 = _mnist_fit(0, limit_train_batches=3)
    t2, l2 = _mnist_fit(2, limit_train_batches=3)
    assert t0.global_step == t2.global_step == 6
    assert l0 == l2
    # max_steps breaks mid-epoch; the producer must still be joined
    t0, l0 = _mnist_fit(0, max_steps=4)
    t2, l2 = _mnist_fit(2, max_steps=4)
    assert t0.global_step == t2.global_step == 4
    assert l0 == l2
    assert not _prefetch_threads()


class _PoisonDataset(ArrayDataset):
    """Raises on one specific sample index — mid-epoch, after the
    example-batch probe."""

    def __init__(self, *arrays, poison_idx: int):
        super().__init__(*arrays)
        self.poison_idx = poison_idx

    def __getitem__(self, idx):
        if idx == self.poison_idx:
            raise ValueError("poisoned sample 42")
        return super().__getitem__(idx)

    def _native_arrays(self):
        return None  # force the host-fed python path


def test_mid_epoch_error_surfaces_typed_at_the_consuming_step():
    """A dataset failure at batch k surfaces as the ORIGINAL error (not
    a queue timeout / RuntimeError wrapper) and the trainer has
    consumed exactly k steps — identical to the unprefetched loop."""
    bs, n = 8, 64
    x = np.random.default_rng(0).standard_normal((n, 32)).astype(np.float32)
    steps = {}
    for prefetch in (0, 2):
        ds = _PoisonDataset(x, poison_idx=3 * bs)  # first sample of batch 3
        loader = DataLoader(ds, batch_size=bs, shuffle=False)
        trainer = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                          precision="f32", enable_checkpointing=False,
                          log_every_n_steps=10 ** 9, seed=0,
                          cache_dataset_on_device=False,
                          prefetch_batches=prefetch)
        with pytest.raises(ValueError, match="poisoned sample 42"):
            trainer.fit(BoringModel(), loader)
        steps[prefetch] = trainer.global_step
    assert steps[0] == steps[2] == 3  # batches 0..2 completed, then raise
    assert not _prefetch_threads()  # the finally joined the producer


def test_eval_and_predict_prefetch_parity():
    x = np.random.default_rng(3).standard_normal((44, 32)).astype(np.float32)
    model0 = BoringModel()
    model0.params = model0.init_params(jax.random.PRNGKey(0))
    results = {}
    for prefetch in (0, 2):
        trainer = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                          precision="f32", enable_checkpointing=False,
                          seed=0, prefetch_batches=prefetch)
        val = DataLoader(ArrayDataset(x), batch_size=8)
        metrics = trainer.validate(model0, val)[0]
        # drop_last=False leaves a ragged 4-sample tail: the pad/strip
        # path must behave identically under prefetch
        pred_loader = DataLoader(ArrayDataset(x), batch_size=8,
                                 drop_last=False)
        preds = trainer.predict(model0, pred_loader)
        results[prefetch] = (metrics, preds)
    m0, p0 = results[0]
    m2, p2 = results[2]
    assert m0 == m2
    assert len(p0) == len(p2)
    for a, b in zip(p0, p2):
        np.testing.assert_array_equal(a, b)
    assert sum(len(p) for p in p2) == len(x)  # tail stripped, not padded
    assert not _prefetch_threads()


def test_profiler_input_pipeline_accounting():
    """prefetch runs record h2d_wait spans, a prefetch_depth gauge and a
    starvation counter; describe() reports them."""
    prof = Profiler()
    x, y = synthetic_mnist(64 * 4, seed=0)

    def slow_collate(samples):
        time.sleep(0.02)  # input-bound on purpose: starvation must fire
        return default_collate(samples)

    loader = DataLoader(ArrayDataset(x, y), batch_size=64, shuffle=False,
                        collate_fn=slow_collate)
    trainer = Trainer(max_epochs=2, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False,
                      log_every_n_steps=10 ** 9, seed=0, profiler=prof,
                      prefetch_batches=2)
    trainer.fit(MNISTClassifier({"layer_1": 16, "layer_2": 16}), loader)
    s = prof.summary()
    assert s["h2d_wait"]["count"] == trainer.global_step == 8
    assert "h2d" not in s  # placement moved into the pipeline
    assert s["data_fetch"]["count"] >= trainer.global_step  # producer-side
    gauges = prof.gauges()
    assert gauges["prefetch_depth"]["count"] == trainer.global_step
    assert gauges["prefetch_depth"]["max"] <= 2 * 2 - 1
    starved = prof.counters()["prefetch_starved_steps"]
    assert starved >= 1  # the loader IS slower than the model
    text = prof.describe()
    assert "prefetch_starved_steps" in text
    assert "prefetch_depth" in text
    assert "input-bound" in text
    # reset clears the new accounting too
    prof.reset()
    assert prof.counters() == {} and prof.gauges() == {}


def test_prefetch_zero_keeps_the_synchronous_span_shape():
    prof = Profiler()
    train, val = boring_loaders()
    trainer = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False,
                      log_every_n_steps=10 ** 9, seed=0, profiler=prof,
                      cache_dataset_on_device=False, prefetch_batches=0)
    trainer.fit(BoringModel(), train, val)
    s = prof.summary()
    assert s["h2d"]["count"] == trainer.global_step > 0
    assert "h2d_wait" not in s
    assert prof.counters() == {}
    assert not _prefetch_threads()
