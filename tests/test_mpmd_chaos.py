"""Per-stage fault domains: chaos faults targeted at one stage group
(``crash@stage1`` / ``hang@stage1``) are attributed to that stage,
recovered via checkpoint replay, and charged against that stage's
budget only.  Chaos specs ride ``worker_env`` (never the driver env) and
use ``:once`` + a cross-restart claim namespace so a replayed worker
generation does not re-fire the fault."""

import json
import os

import numpy as np
import pytest

from ray_lightning_accelerators_tpu import native
from ray_lightning_accelerators_tpu.parallel.mpmd.driver import (
    PipelineRunner, PipelineStageFailed)
from tests.utils import PipelineBoringModel

pytestmark = [
    pytest.mark.pipeline_mpmd,
    pytest.mark.chaos,
    pytest.mark.skipif(not native.available(),
                       reason=f"native build: {native.build_error()}"),
]


@pytest.fixture
def batches():
    rng = np.random.default_rng(0)
    return [rng.standard_normal((8, 8)).astype(np.float32)
            for _ in range(3)]


def _chaos_env(tmpdir, spec):
    ns = os.path.join(str(tmpdir), "chaos-ns")
    os.makedirs(ns, exist_ok=True)
    return {"RLA_TPU_CHAOS": spec, "RLA_TPU_CHAOS_NS": ns}


def _clean_losses(batches):
    """What the unfaulted pipeline produces — replay must reproduce it."""
    import jax
    import jax.numpy as jnp
    import optax
    mod = PipelineBoringModel()
    params = mod.init_params(jax.random.PRNGKey(0))
    tx = mod.configure_optimizers()
    opt = tx.init(params)
    losses = []
    for batch in batches:
        g_acc = jax.tree.map(jnp.zeros_like, params)
        loss_sum = 0.0
        for mb in np.split(batch, 4):
            loss, g = jax.value_and_grad(
                lambda p, xb: mod.training_step(p, xb, None)[0])(params, mb)
            g_acc = jax.tree.map(jnp.add, g_acc, g)
            loss_sum += float(loss)
        updates, opt = tx.update(
            jax.tree.map(lambda a: a / 4, g_acc), opt, params)
        params = optax.apply_updates(params, updates)
        losses.append(loss_sum / 4)
    return losses


def test_crash_at_stage1_replays_within_stage_budget(tmpdir, batches):
    """crash@stage1 at training step 1: the run completes via checkpoint
    replay, the failure is charged to stage 1 and stage 0's budget is
    untouched, and the replayed trajectory is exact."""
    runner = PipelineRunner(
        PipelineBoringModel(), num_stages=2, num_microbatches=4, seed=0,
        workdir=str(tmpdir),
        worker_env=_chaos_env(tmpdir, "crash@stage1:step2:once"))
    try:
        summary = runner.run(batches)
    finally:
        runner.shutdown()
    assert summary["replays"] == 1
    assert summary["stage_failure_budget_used"] == [0, 1]
    np.testing.assert_allclose(summary["losses"], _clean_losses(batches),
                               rtol=1e-6)
    report = json.load(open(os.path.join(str(tmpdir), "run_report.json")))
    assert report["error"] is None


def test_hang_at_stage1_reaped_and_replayed(tmpdir, batches):
    """hang@stage1: the watchdog reaps the wedged stage-1 worker (stage
    0 only ever sees a handoff timeout, which must NOT win attribution)
    and the run completes via replay."""
    runner = PipelineRunner(
        PipelineBoringModel(), num_stages=2, num_microbatches=4, seed=0,
        workdir=str(tmpdir), handoff_timeout_s=12.0, wedge_timeout_s=4.0,
        worker_env=_chaos_env(tmpdir, "hang@stage1:step2:once"))
    try:
        summary = runner.run(batches[:2])
    finally:
        runner.shutdown()
    assert summary["replays"] == 1
    assert summary["stage_failure_budget_used"] == [0, 1]
    np.testing.assert_allclose(summary["losses"],
                               _clean_losses(batches)[:2], rtol=1e-6)


def test_exhausted_stage_budget_fails_typed_with_attribution(tmpdir,
                                                             batches):
    """Without ``:once`` the fault re-fires on every replayed generation;
    past max_stage_failures the run fails as PipelineStageFailed naming
    the faulting stage group."""
    runner = PipelineRunner(
        PipelineBoringModel(), num_stages=2, num_microbatches=4, seed=0,
        workdir=str(tmpdir), max_stage_failures=1,
        worker_env={"RLA_TPU_CHAOS": "crash@stage1:step2"})
    with pytest.raises(PipelineStageFailed) as exc_info:
        try:
            runner.run(batches)
        finally:
            runner.shutdown()
    err = exc_info.value
    assert err.stage == 1
    assert err.budget_used == [0, 2]  # stage 0 never cross-charged
    report = json.load(open(os.path.join(str(tmpdir), "run_report.json")))
    assert report["error"]["type"] == "PipelineStageFailed"
    assert report["extra"]["pipeline"]["stage_failure_budget_used"] == [0, 2]
