"""Self-healing serve tier (serve/controller.py): health/load-aware
routing, retry budgets on the shared backoff, hedging, circuit-breaker
auto-revival, SLO-burn autoscaling, brownout shedding, and replica-level
chaos.  All CPU; the controller units run on a fake group (no
subprocesses), the acceptance loops on real replica pools."""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from ray_lightning_accelerators_tpu.serve import (AdmissionController,
                                                  BrownoutShed,
                                                  ControllerConfig,
                                                  QueueFull,
                                                  ReplicaController,
                                                  ServeMetrics)
from ray_lightning_accelerators_tpu.serve.controller import (
    STATE_DRAINING, STATE_OK, STATE_OPEN, STATE_SLOW)

pytestmark = pytest.mark.serve_resilience

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------- #
# Satellite: the shared backoff module                                   #
# --------------------------------------------------------------------- #
def test_backoff_shared_with_elastic_and_sequence_pinned():
    """utils/backoff.py IS ElasticRunner's backoff (one implementation,
    re-exported) and the sequence pins the exact historical elastic
    math: min(cap, base * 2**(a-1)) scaled into [0.5, 1.0)."""
    from ray_lightning_accelerators_tpu.runtime.elastic import (
        backoff_delay_s as elastic_backoff)
    from ray_lightning_accelerators_tpu.utils.backoff import (
        backoff_delay_s)
    assert elastic_backoff is backoff_delay_s
    # the pinned sequence (mirrors the original elastic unit test)
    assert backoff_delay_s(1, 2.0, rng=lambda: 0.0) == 1.0
    assert backoff_delay_s(1, 2.0, rng=lambda: 1.0) == 2.0
    assert backoff_delay_s(3, 2.0, rng=lambda: 1.0) == 8.0
    assert backoff_delay_s(10, 2.0, cap_s=6.0, rng=lambda: 1.0) == 6.0
    assert backoff_delay_s(5, 0.0) == 0.0  # base 0 = disabled
    assert backoff_delay_s(0, 2.0) == 0.0  # attempts are 1-based
    # identical deterministic sequences for any shared rng
    seq = [backoff_delay_s(a, 0.5, cap_s=4.0, rng=lambda: 0.25)
           for a in range(1, 8)]
    assert seq == [elastic_backoff(a, 0.5, cap_s=4.0, rng=lambda: 0.25)
                   for a in range(1, 8)]
    assert seq[:4] == [0.3125, 0.625, 1.25, 2.5]  # then capped
    assert seq[4:] == [2.5, 2.5, 2.5]


# --------------------------------------------------------------------- #
# Satellite: requeue ordering under multi-replica failure               #
# --------------------------------------------------------------------- #
def test_requeue_lane_orders_before_new_admissions_multi_failure():
    """Chunks requeued head-of-line from TWO failed replicas dispatch
    before newly admitted requests, in requeue order, and repeated
    failures keep them at the head (no starvation) — today only the
    single-failure case was pinned in test_serve."""
    ctl = AdmissionController(queue_depth=16)
    subs = [ctl.submit(np.asarray([i + 1], np.int32), 2)
            for i in range(5)]
    # replica A took a, b; replica B took c, d; e still queued
    a, b, c, d = (ctl.pop() for _ in range(4))
    assert a[1] is subs[0] and d[1] is subs[3]
    # both replicas fail: requeue A's chunk, then B's
    assert ctl.requeue(*a) and ctl.requeue(*b)
    assert ctl.requeue(*c) and ctl.requeue(*d)
    order = [ctl.pop()[0].request_id for _ in range(5)]
    assert order == [a[0].request_id, b[0].request_id,
                     c[0].request_id, d[0].request_id,
                     subs[4].request.request_id]
    # repeated failure: the re-requeued request STILL beats the fresh
    # admission, and its requeue count grows (the budget's input)
    f = ctl.submit(np.asarray([9], np.int32), 2)
    assert ctl.requeue(a[0], a[1])
    assert ctl.pop()[0].request_id == a[0].request_id
    assert a[0].requeues == 2
    assert ctl.pop()[1] is f


def test_requeue_backoff_holds_lane_without_losing_position():
    """A retry backoff (not_before) HOLDS the requeue lane: pop returns
    None until it expires, and the newly admitted request can never
    overtake the retried one."""
    ctl = AdmissionController(queue_depth=8)
    r1 = ctl.submit(np.asarray([1], np.int32), 2)
    item = ctl.pop()
    r2 = ctl.submit(np.asarray([2], np.int32), 2)
    assert ctl.requeue(item[0], item[1], delay_s=0.15)
    assert ctl.pop() is None          # lane held, r2 must not overtake
    assert ctl.depth == 2
    deadline = time.monotonic() + 2.0
    got = None
    while got is None and time.monotonic() < deadline:
        got = ctl.pop()
        if got is None:
            time.sleep(0.01)
    assert got is not None and got[1] is r1
    assert ctl.pop()[1] is r2


# --------------------------------------------------------------------- #
# Satellite: replica-level chaos syntax                                 #
# --------------------------------------------------------------------- #
def test_chaos_replica_faults_parse_and_filter():
    from ray_lightning_accelerators_tpu.testing.chaos import (
        ChaosInjector, parse_chaos)
    f = parse_chaos("crash@replica1:chunk2:once,hang@replica0,"
                    "slow@replica2:1.5,hang@rank1:step2")
    assert [(x.kind, x.rank, x.step, x.layer, x.once) for x in f] == [
        ("crash", 1, 2, "replica", True),
        ("hang", 0, None, "replica", False),
        ("slow", 2, None, "replica", False),
        ("hang", 1, 2, "worker", False)]
    assert f[2].delay_s == 1.5
    # chunk-less crash fires on the first chunk; slow on every chunk
    assert f[1].matches(0, 1) and not f[1].matches(0, 2)
    assert f[2].matches(2, 1) and f[2].matches(2, 7)
    # replica claim tokens are layer-prefixed (never collide with a
    # worker dispatch claim of the same kind/step)
    assert f[0].token(1).startswith("replica-")
    assert f[3].token(1) == "hang-rank1-step2-r1"
    # each seam only honors its own layer
    wi = ChaosInjector(f, 1, ns_dir="/tmp")
    ri = ChaosInjector(f, 1, ns_dir="/tmp", layer="replica")
    assert [x.layer for x in wi.faults] == ["worker"]
    assert all(x.layer == "replica" for x in ri.faults)
    for bad in ("preempt@replica0", "lost@replica1",
                "crash@replica0:step2", "crash@rank0:chunk2",
                "crash@replica0:chunk0"):
        with pytest.raises(ValueError):
            parse_chaos(bad)


# --------------------------------------------------------------------- #
# Controller units (fake group — no subprocesses)                       #
# --------------------------------------------------------------------- #
class _FakeWorker:
    def __init__(self, rank, alive=True):
        self.rank = rank
        self.is_alive = alive


class _FakePool:
    def __init__(self, n):
        self.workers = [_FakeWorker(r) for r in range(n)]


class _FakeBatcher:
    def __init__(self):
        self.depth = 0


class _FakeGroup:
    queue_depth = 16

    def __init__(self, n=3):
        self.pool = _FakePool(n)
        self.batcher = _FakeBatcher()
        self.metrics = ServeMetrics()
        self.watchdog = None
        self.dispatched = []
        self.revive_results = []  # None = ok, exc = raise
        self.revived = []
        self.retired = []

    def _worker(self, rank):
        for w in self.pool.workers:
            if w.rank == rank:
                return w
        return None

    def _dispatch(self, rank, chunk, hedge_of=None):
        self.dispatched.append((rank, list(chunk), hedge_of))

    def _revive_replica(self, rank):
        outcome = (self.revive_results.pop(0)
                   if self.revive_results else None)
        if outcome is not None:
            raise outcome
        self.revived.append(rank)
        return {}

    def _add_replica(self):
        rank = max(w.rank for w in self.pool.workers) + 1
        self.pool.workers.append(_FakeWorker(rank))
        return rank

    def _retire_replica(self, rank):
        self.retired.append(rank)
        self.pool.workers = [w for w in self.pool.workers
                             if w.rank != rank]


def _fake_item():
    from ray_lightning_accelerators_tpu.serve.batcher import (
        ServeRequest, ServeResponse)
    req = ServeRequest(0, np.asarray([1], np.int32), 2, time.monotonic())
    return req, ServeResponse(req)


def test_routing_skips_unhealthy_and_weights_inflight():
    g = _FakeGroup(3)
    ctrl = ReplicaController(g, ControllerConfig(max_inflight_chunks=2))
    # load-aware: all healthy, all idle -> least-loaded (any); add load
    ctrl.on_dispatch(0, [_fake_item(), _fake_item()])
    assert ctrl.route() in (1, 2)
    ctrl.on_dispatch(1, [_fake_item()])
    assert ctrl.route() == 2
    # slow replicas are last-resort only
    ctrl._replicas[2].state = STATE_SLOW
    assert ctrl.route() == 1            # healthy-but-loaded beats slow
    ctrl._replicas[1].state = STATE_OPEN
    ctrl.on_dispatch(0, [_fake_item()])  # 0 at max_inflight_chunks
    assert ctrl.route() == 2            # only the slow one can take it
    ctrl._replicas[2].state = STATE_DRAINING
    assert ctrl.route() is None
    # a dead worker opens its circuit at routing time
    ctrl._replicas[2].state = STATE_OK
    g._worker(2).is_alive = False
    assert ctrl.route() is None
    assert ctrl._replicas[2].state == STATE_OPEN
    assert 2 in ctrl.down_ranks()


def test_circuit_breaker_opens_backs_off_and_half_open_probes():
    g = _FakeGroup(2)
    ctrl = ReplicaController(g, ControllerConfig(
        revive_backoff_s=0.2, revive_backoff_cap_s=2.0,
        breaker_window_s=5.0, breaker_failures=2))
    # one death holding TWO chunks = ONE breaker failure (the second
    # in-flight callback must not double-count the same death)
    cid = ctrl.on_dispatch(1, [_fake_item()])
    cid2 = ctrl.on_dispatch(1, [_fake_item()])
    ctrl.note_infra_failure(1, cid, RuntimeError("worker died"))
    ctrl.note_infra_failure(1, cid2, RuntimeError("worker died"))
    r = ctrl._replicas[1]
    assert len(r.failures) == 1
    assert r.inflight_chunks == 0
    assert r.state == STATE_OPEN and r.open_until > time.monotonic()
    first_open = r.open_until
    # before the backoff expires: no revival attempt
    assert ctrl.maybe_revive(now=time.monotonic()) == 0
    assert not g.revived
    # expired: half-open probe -> success closes the circuit
    r.open_until = time.monotonic() - 0.01
    assert ctrl.maybe_revive() == 1
    assert g.revived == [1]
    assert r.state == STATE_OK and r.revivals == 1
    assert g.metrics.snapshot()["revived"] == 1
    # open again; the breaker threshold (2) is now reached, so the
    # reopen delay grows: attempt 2 with half-jitter floors at
    # 0.5*base*2 = the attempt-1 max
    cid = ctrl.on_dispatch(1, [_fake_item()])
    ctrl.note_infra_failure(1, cid, RuntimeError("worker died"))
    assert r.state == STATE_OPEN
    assert len(r.failures) == 2
    assert r.open_until - time.monotonic() >= 0.18
    del first_open
    g.revive_results = [RuntimeError("still dead")]
    r.open_until = time.monotonic() - 0.01
    assert ctrl.maybe_revive() == 0
    assert r.state == STATE_OPEN and r.revive_attempts == 1
    assert r.open_until > time.monotonic()


def test_hedge_fires_once_per_chunk_to_healthy_replica():
    g = _FakeGroup(2)
    ctrl = ReplicaController(g, ControllerConfig(hedge_age_s=0.05))
    items = [_fake_item(), _fake_item()]
    cid = ctrl.on_dispatch(0, items)
    ctrl._replicas[0].state = STATE_SLOW
    chunk = ctrl._replicas[0].chunks[cid]
    chunk.t_dispatch -= 1.0  # old enough to hedge
    assert ctrl.maybe_hedge() == 1
    assert len(g.dispatched) == 1
    rank, hedged_items, hedge_of = g.dispatched[0]
    assert rank == 1 and hedge_of == (0, cid)
    assert [id(r) for r, _ in hedged_items] == [id(r) for r, _ in items]
    assert g.metrics.snapshot()["hedged"] == 1
    assert ctrl._replicas[0].hedges == 1
    # a chunk hedges at most once
    assert ctrl.maybe_hedge() == 0
    ctrl.note_success(0, cid)
    # already-done responses are excluded: nothing unresolved => no fire
    cid2 = ctrl.on_dispatch(0, items)
    ctrl._replicas[0].chunks[cid2].t_dispatch -= 1.0
    items[0][1]._complete(np.asarray([1, 2], np.int32))
    items[1][1]._complete(np.asarray([1, 2], np.int32))
    assert ctrl.maybe_hedge() == 0
    assert not ctrl._replicas[0].chunks[cid2].hedged  # retryable later
    ctrl.note_success(0, cid2)
    # and never onto a non-healthy target
    cid3 = ctrl.on_dispatch(0, [_fake_item()])
    ctrl._replicas[0].chunks[cid3].t_dispatch -= 1.0
    ctrl._replicas[1].state = STATE_OPEN
    assert ctrl.maybe_hedge() == 0
    assert not ctrl._replicas[0].chunks[cid3].hedged


def test_autoscale_up_on_burn_and_graceful_drain_on_idle():
    g = _FakeGroup(2)
    ctrl = ReplicaController(g, ControllerConfig(
        max_replicas=3, min_replicas=2, scale_up_burn=1.0,
        scale_sustain_s=0.2, idle_sustain_s=0.2, burn_stale_s=30.0))
    t0 = time.monotonic()
    # sustained burn (fresh reading) -> one scale-up, bounded by max
    ctrl._replicas[0].slo_burn = 2.0
    ctrl._replicas[0].burn_updated = t0
    ctrl.autoscale(now=t0)            # arms the sustain window
    assert len(g.pool.workers) == 2
    ctrl.autoscale(now=t0 + 0.3)      # sustained -> grow
    assert len(g.pool.workers) == 3
    assert ctrl._replicas[2].scaled
    assert g.metrics.snapshot()["scale_ups"] == 1
    ctrl._replicas[0].burn_updated = t0 + 0.3
    ctrl.autoscale(now=t0 + 0.4)
    ctrl.autoscale(now=t0 + 0.7)      # at max_replicas: no growth
    assert len(g.pool.workers) == 3
    # idle (stale burn, empty queue, nothing in flight) -> drain the
    # SCALED replica first, then retire it once empty
    ctrl._replicas[0].slo_burn = 0.0
    t1 = t0 + 1.0
    ctrl.autoscale(now=t1)            # arms idle
    # sustained idle -> the SCALED replica drains; empty, it retires in
    # the same sweep (a replica with in-flight work would sit DRAINING
    # until its chunks finish on the normal retire path)
    ctrl.autoscale(now=t1 + 0.3)
    assert g.retired == [2]
    assert 2 not in ctrl._replicas
    assert g.metrics.snapshot()["scale_downs"] == 1
    # at the min_replicas floor: never drains below
    ctrl.autoscale(now=t1 + 1.0)
    ctrl.autoscale(now=t1 + 2.0)
    assert sorted(ctrl._replicas) == [0, 1]
    assert all(r.state != STATE_DRAINING
               for r in ctrl._replicas.values())


def test_stale_burn_reads_as_zero():
    g = _FakeGroup(2)
    ctrl = ReplicaController(g, ControllerConfig(burn_stale_s=0.5))
    now = time.monotonic()
    ctrl._replicas[0].slo_burn = 5.0
    ctrl._replicas[0].burn_updated = now
    assert ctrl._overload_signals(now)[0] == 5.0
    assert ctrl._overload_signals(now + 1.0)[0] == 0.0


def test_brownout_decision_and_typed_shape():
    g = _FakeGroup(2)
    ctrl = ReplicaController(g, ControllerConfig(
        brownout_frac=0.5, max_replicas=None))
    g.batcher.depth = 7
    assert ctrl.should_shed() is None
    g.batcher.depth = 8                  # watermark = 0.5 * 16
    shed = ctrl.should_shed()
    assert shed == (8, 8, 16)
    # with scale-up headroom the tier grows instead of shedding
    ctrl2 = ReplicaController(g, ControllerConfig(
        brownout_frac=0.5, max_replicas=4))
    assert ctrl2.should_shed() is None
    exc = BrownoutShed(*shed)
    assert isinstance(exc, QueueFull)    # same retry-later contract
    assert "brownout" in str(exc) and "watermark" in str(exc)


# --------------------------------------------------------------------- #
# Observability: /statusz table, Prometheus family, rla_top             #
# --------------------------------------------------------------------- #
def test_controller_snapshot_statusz_and_prometheus_family():
    from ray_lightning_accelerators_tpu.telemetry.live import LiveSources
    from tests.utils import assert_prometheus_exposition

    g = _FakeGroup(2)
    ctrl = ReplicaController(g, ControllerConfig())
    cid = ctrl.on_dispatch(0, [_fake_item()])
    ctrl.note_success(0, cid, {"decode_step_s": {"p99_s": 0.012},
                               "slo_burn_rate": 1.5,
                               "compile_count": 4})
    snap = ctrl.snapshot()
    assert set(snap["replicas"]) == {"0", "1"}
    row = snap["replicas"]["0"]
    assert row["state"] == "ok" and row["dispatched_chunks"] == 1
    assert row["p99_step_ms"] == 12.0 and row["slo_burn"] == 1.5
    assert snap["brownout_watermark"] == 14  # 0.9 * 16
    json.dumps(snap)  # must stay JSON-able for /statusz

    src = LiveSources()
    src.bind_replica_controller(ctrl)
    statusz = src.statusz()
    assert statusz["replica_controller"]["replicas"]["0"][
        "completed_chunks"] == 1
    reg = src.build_registry()
    assert reg.to_json()["replica_controller"]["max_burn"] == 1.5
    text = reg.prometheus_text()
    assert_prometheus_exposition(text)
    assert 'rla_tpu_serve_replica_state{replica="0",state="ok"} 1' \
        in text
    assert 'rla_tpu_serve_replica_dispatched_chunks_total' \
           '{replica="0"} 1' in text
    assert "rla_tpu_serve_replica_count 2" in text
    assert "rla_tpu_serve_tier_queue_depth 0" in text
    # unbind: the table leaves the scrape
    src.bind_replica_controller(None)
    assert "replica_controller" not in src.statusz()
    # sibling-group safety: a shut-down group's unbind must not evict
    # a controller some OTHER group bound after it (last bound wins)
    ctrl2 = ReplicaController(_FakeGroup(1), ControllerConfig())
    src.bind_replica_controller(ctrl)
    src.bind_replica_controller(ctrl2)
    src.unbind_replica_controller(ctrl)   # no-op: not the bound one
    assert set(src.statusz()["replica_controller"]["replicas"]) == {"0"}
    src.unbind_replica_controller(ctrl2)
    assert "replica_controller" not in src.statusz()


def test_rla_top_renders_replica_table():
    spec = importlib.util.spec_from_file_location(
        "rla_top", os.path.join(_ROOT, "scripts", "rla_top.py"))
    rla_top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rla_top)
    g = _FakeGroup(2)
    ctrl = ReplicaController(g, ControllerConfig())
    cid = ctrl.on_dispatch(1, [_fake_item()])
    ctrl.note_infra_failure(1, cid, RuntimeError("worker died"))
    status = {"rank": "driver", "trace_id": "t", "health": {},
              "replica_controller": ctrl.snapshot()}
    out = rla_top.render(status)
    assert "serve tier: queue 0/16" in out
    assert "replica" in out and "state" in out
    lines = [ln for ln in out.splitlines()]
    row1 = next(ln for ln in lines if ln.startswith("1 "))
    assert "open" in row1
    row0 = next(ln for ln in lines if ln.startswith("0 "))
    assert "ok" in row0


# --------------------------------------------------------------------- #
# Real-pool acceptance loops                                            #
# --------------------------------------------------------------------- #
_REPLICA_CFG = dict(vocab_size=61, d_model=32, n_heads=2, d_ff=64,
                    n_layers=2, max_seq_len=48)


def _replica_factory(np_params, slo_ttft_s=None):
    """Engine factory executed inside each worker (cloudpickled closure;
    params travel as numpy)."""
    def make():
        from ray_lightning_accelerators_tpu.models.transformer import (
            GPT, TransformerConfig)
        from ray_lightning_accelerators_tpu.serve import (ServeEngine,
                                                          SloPolicy)
        model = GPT(TransformerConfig(**_REPLICA_CFG))
        slo = (SloPolicy(ttft_target_s=slo_ttft_s)
               if slo_ttft_s is not None else None)
        return ServeEngine(model, np_params, max_slots=4,
                           queue_depth=64, slo=slo)
    return make


def _model_and_np_params(seed=0):
    import jax

    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    model = GPT(TransformerConfig(**_REPLICA_CFG))
    params = model.init_params(jax.random.PRNGKey(seed))
    return model, params, jax.tree.map(np.asarray, params)


def test_auto_revive_republishes_portfile_and_heartbeat(tmp_path):
    """Satellite 2 + the breaker's end-to-end revive: kill a replica's
    process, submit — the circuit opens at routing, the breaker
    restarts it, and the REVIVED generation re-publishes its telemetry
    portfile (new pid) and heartbeat channel, so it reappears in
    ClusterView/rla_top and serves the queued request."""
    from ray_lightning_accelerators_tpu.serve import ServeReplicas
    from ray_lightning_accelerators_tpu.telemetry import live as live_lib

    tdir = str(tmp_path / "telemetry")
    env = {"RLA_TPU_WORKER_HEARTBEAT_S": "0.1",
           "RLA_TPU_TELEMETRY_DIR": tdir,
           "RLA_TPU_METRICS_PORT": "0"}
    model, params, np_params = _model_and_np_params()
    group = ServeReplicas(
        _replica_factory(np_params), num_replicas=1, chunk_size=2,
        env_per_worker=[env],
        controller=ControllerConfig(revive_backoff_s=0.1,
                                    revive_backoff_cap_s=0.5,
                                    poll_s=0.05))
    try:
        out = group.submit(np.asarray([1, 2, 3], np.int32), 3)
        np.testing.assert_array_equal(
            out.result(timeout=120),
            np.asarray(model.generate(
                params, np.asarray([[1, 2, 3]], np.int32),
                max_new_tokens=3))[0])
        portfile = os.path.join(tdir, "rank0.port.json")
        deadline = time.monotonic() + 30
        while not os.path.exists(portfile) \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        with open(portfile) as f:
            pid_before = json.load(f)["pid"]
        # kill the replica process outright; the next dispatch finds it
        w = group._worker(0)
        w.kill()
        assert not w.is_alive
        resp = group.submit(np.asarray([4, 5], np.int32), 3)
        tokens = resp.result(timeout=120)  # served after auto-revive
        np.testing.assert_array_equal(
            tokens, np.asarray(model.generate(
                params, np.asarray([[4, 5]], np.int32),
                max_new_tokens=3))[0])
        snap = group.stats()
        assert snap["revived"] >= 1
        assert snap["controller"]["replicas"]["0"]["revivals"] >= 1
        # the revived GENERATION re-published its portfile...
        with open(portfile) as f:
            rec = json.load(f)
        assert rec["pid"] != pid_before and rec["port"]
        # ...its endpoint scrapes (the ClusterView seam)...
        live_snap = live_lib.scrape_rank(0, env=env)
        assert live_snap and live_snap["rank"] == "0"
        # ...and its heartbeat channel is the new generation's (fresh
        # and beating, so the watchdog supervises the revived process)
        beat = w.heartbeat.snapshot()
        assert beat["started"] and beat["beat_age_s"] < 5.0
    finally:
        group.shutdown()


@pytest.mark.chaos
def test_brownout_sheds_typed_at_watermark():
    """A saturated tier (replica slowed by chaos, no scale-up headroom)
    sheds typed BrownoutShed at the watermark instead of queueing to
    the hard cap."""
    from ray_lightning_accelerators_tpu.serve import ServeReplicas

    _, _, np_params = _model_and_np_params()
    env = {"RLA_TPU_WORKER_HEARTBEAT_S": "0.2",
           "RLA_TPU_CHAOS": "slow@replica0:2.0"}
    group = ServeReplicas(
        _replica_factory(np_params), num_replicas=1, chunk_size=1,
        queue_depth=4,
        env_per_worker=[env],
        controller=ControllerConfig(brownout_frac=0.5, hedge=False,
                                    poll_s=0.05))
    try:
        shed = None
        for i in range(12):
            try:
                group.submit(np.asarray([1 + i % 7], np.int32), 2)
            except BrownoutShed as e:
                shed = e
                break
            time.sleep(0.02)
        assert shed is not None, "tier never shed at the watermark"
        assert isinstance(shed, QueueFull)  # retry-later contract
        assert shed.watermark == 2 and shed.depth >= 2
        snap = group.metrics.snapshot()
        assert snap["brownout_shed"] >= 1
        assert snap["rejected"] >= 1
    finally:
        group.shutdown()


def _compile_counts(group):
    rows = group.stats()["controller"]["replicas"]
    return {r: row["compile_count"] for r, row in rows.items()
            if row["compile_count"] is not None}


@pytest.mark.chaos
def test_acceptance_chaos_kill_hang_scale_and_drain(tmp_path):
    """THE acceptance loop: sustained mixed load with crash@replica0 and
    hang@replica1 (replica-level chaos, once each) — every admitted
    request resolves exactly once (accounting proves no loss/dup and
    every response is token-identical to generate()), both replicas
    auto-revive through the circuit breaker, the controller scales up
    on the forced SLO-burn overload (tiny TTFT target => burn
    saturates) and drains back down cleanly once idle — with zero
    steady-state recompiles per replica (compile counts ride every
    chunk's stats and are pinned flat across the final round)."""
    from ray_lightning_accelerators_tpu.serve import ServeReplicas

    model, params, np_params = _model_and_np_params()
    ns = str(tmp_path / "chaos-ns")
    hb = {"RLA_TPU_WORKER_HEARTBEAT_S": "0.1",
          "RLA_TPU_SLO_WINDOW_S": "3"}
    envs = [
        dict(hb, RLA_TPU_CHAOS="crash@replica0:chunk2:once",
             RLA_TPU_CHAOS_NS=ns),
        dict(hb, RLA_TPU_CHAOS="hang@replica1:chunk2:once",
             RLA_TPU_CHAOS_NS=ns),
    ]
    cfg = ControllerConfig(
        max_retries=4,
        retry_backoff_s=0.01, retry_backoff_cap_s=0.1,
        revive_backoff_s=0.2, revive_backoff_cap_s=1.0,
        max_replicas=3, min_replicas=2,
        scale_up_burn=1.0, occupancy_high=0.95,
        scale_sustain_s=0.4, idle_sustain_s=3.0, burn_stale_s=2.0,
        # hedge only genuinely stuck chunks: CPU chunks run ~1s, so the
        # default watchdog-derived age would hedge healthy work and the
        # chunk-count faults would land on hedge copies instead of the
        # requeue path this loop pins (hedging itself is unit-tested)
        hedge_age_s=5.0,
        poll_s=0.05)
    rng = np.random.default_rng(11)

    def mixed(n):
        # one prompt bucket (<=14 < block 16) so a warm engine never
        # compiles a new serve program mid-run (the zero-recompile
        # pin), and few driver-side shapes so the generate() reference
        # path compiles a bounded set too
        return [(rng.integers(0, 61, size=(
            int(rng.choice([4, 8, 12])),)).astype(np.int32),
            int(rng.choice([3, 4]))) for _ in range(n)]

    def drive_checked(n):
        """One wave: refs FIRST (driver-side generate), then a tight
        submission burst, then exactness — keeps the tier continuously
        busy during a wave so idle gaps between waves stay well under
        idle_sustain_s (a slow sequential ref+wait loop would starve
        the tier mid-test and read as a real idle watermark)."""
        pairs = mixed(n)
        refs = [np.asarray(model.generate(
            params, np.asarray(p[None]), max_new_tokens=k))[0]
            for p, k in pairs]
        handles = [group.submit(p, k) for p, k in pairs]
        for ref, h in zip(refs, handles):
            np.testing.assert_array_equal(h.result(timeout=300), ref)

    stop_feed = threading.Event()
    group = ServeReplicas(
        _replica_factory(np_params, slo_ttft_s=1e-4), num_replicas=2,
        chunk_size=2, heartbeat_s=0.1, wedge_timeout_s=1.2,
        queue_depth=64, env_per_worker=envs, controller=cfg,
        scale_env=dict(hb))
    try:
        # -- phase 1: sustained load provoking the kill + the hang ----- #
        # keep waves coming until both faulted replicas have revived
        # through the breaker (bounded); every wave checked exact
        deadline = time.monotonic() + 150
        revived_ok = False
        while time.monotonic() < deadline:
            drive_checked(4)
            if group.metrics.snapshot()["revived"] >= 2:
                revived_ok = True
                break
        assert revived_ok, group.stats()["controller"]
        snap = group.stats()
        assert snap["wedge_events"] >= 1          # the hang was a reap
        rows = snap["controller"]["replicas"]
        # both faults really fired (one infra failure each) and the
        # lost chunks' requests came back through the head-of-line
        # requeue lane
        assert rows["0"]["infra_failures"] >= 1
        assert rows["1"]["infra_failures"] >= 1
        assert snap["requeued"] >= 1, snap
        assert rows["0"]["revivals"] >= 1
        assert rows["1"]["revivals"] >= 1

        # continuous background feed through phases 2-3: wave cadence
        # alone can leave >idle_sustain_s gaps under host load, and an
        # idle tier legitimately drains — the feeder keeps the tier
        # busy so scale state only moves when the test means it to
        feed_p = rng.integers(0, 61, size=(8,)).astype(np.int32)
        feed_ref = np.asarray(model.generate(
            params, np.asarray(feed_p[None]), max_new_tokens=3))[0]
        feed_handles = []

        def feeder():
            while not stop_feed.is_set():
                try:
                    feed_handles.append(group.submit(feed_p, 3))
                except QueueFull:  # backpressure is fine, not a failure
                    pass
                except Exception:
                    return  # group torn down after a primary failure
                time.sleep(0.15)

        feed_thread = threading.Thread(target=feeder, daemon=True)
        feed_thread.start()

        # -- phase 2: forced SLO-burn overload scales the tier up ------ #
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline \
                and group.metrics.snapshot()["scale_ups"] < 1:
            drive_checked(6)
        assert group.metrics.snapshot()["scale_ups"] >= 1, \
            group.stats()["controller"]
        assert len(group.pool) == 3
        # the scale-up signal was the real SLO burn (every request
        # violates the 0.1ms TTFT target), not queue occupancy
        assert group.stats()["controller"]["max_burn"] >= 1.0

        # -- phase 3: zero steady-state recompiles, compile-guard style #
        # (every chunk result carries the replica's backend-compile
        # count; warm until flat, then pin the final round at zero)
        prev, stable = None, False
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            drive_checked(8)
            counts = _compile_counts(group)
            if prev is not None and counts == prev and len(counts) == 3:
                stable = True
                break
            prev = counts
        assert stable, f"compile counts never settled: {prev}"
        drive_checked(8)
        assert _compile_counts(group) == prev  # ZERO new compiles

        # -- phase 4: idle -> graceful drain back to min_replicas ------ #
        stop_feed.set()
        feed_thread.join(timeout=10)
        for h in feed_handles:  # the background stream was exact too
            np.testing.assert_array_equal(h.result(timeout=300),
                                          feed_ref)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline and len(group.pool) != 2:
            time.sleep(0.2)
        snap = group.stats()
        assert len(group.pool) == 2, snap["controller"]
        assert snap["scale_downs"] >= 1
        # the autoscaled replica(s) drained first; originals survive
        assert sorted(w.rank for w in group.pool.workers) == [0, 1]

        # -- exactly-once accounting over the WHOLE run ---------------- #
        # (every response was also asserted token-identical above)
        assert snap["failed"] == 0
        assert snap["cancelled"] == 0
        assert snap["completed"] == snap["submitted"]
    finally:
        stop_feed.set()
        group.shutdown()
