"""Overlap-aware FSDP (gather_mode="scan", parallel/collectives.py):
layer-wise bf16 param all-gather inside the transformer scan, exact
per-layer gradient reduce-scatter via the gather's autodiff transpose,
exposed-vs-hidden wire accounting, checkpoint portability across gather
modes, int8 forward matmuls in the train step, and the tune.autotune_step
closed loop — all on the suite's 8-device CPU mesh."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_lightning_accelerators_tpu import (ArrayDataset, DataLoader,
                                            RayTPUAccelerator, Trainer)
from ray_lightning_accelerators_tpu.models.transformer import (
    GPT, TransformerConfig, _int8_ste_matmul)
from ray_lightning_accelerators_tpu.parallel import collectives as C
from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib

pytestmark = pytest.mark.overlap

VOCAB = 256


def _gpt(n_layers=4, **over):
    cfg = TransformerConfig(vocab_size=VOCAB, d_model=64, n_heads=4,
                            d_ff=128, n_layers=n_layers, max_seq_len=32,
                            fused_loss=True, loss_chunk_rows=64, **over)
    return GPT(cfg, lr=1e-3)


def _loader(n=64, bs=16):
    toks = np.random.default_rng(0).integers(
        0, VOCAB, size=(n, 32)).astype(np.int32)
    return DataLoader(ArrayDataset(toks), batch_size=bs, shuffle=False)


def _fit(tmpdir, gather_mode, max_epochs=2, model=None, **kw):
    trainer = Trainer(max_epochs=max_epochs, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmpdir),
                      log_every_n_steps=10 ** 9,
                      accelerator=RayTPUAccelerator(num_workers=8,
                                                    use_fsdp=True),
                      grad_compression="int8", gather_mode=gather_mode,
                      **kw)
    trainer.fit(model or _gpt(), _loader())
    return trainer


# --------------------------------------------------------------------- #
# Numerics: scan-gather vs whole-tree gather                             #
# --------------------------------------------------------------------- #
def test_scan_gather_matches_tree_gather_over_adam_run(tmpdir):
    """Acceptance: a multi-step Adam run under the scan gather lands
    within tolerance of the whole-tree-gather run.  The schedules are
    not bit-equal by design — tree quantizes the layer-stack grads int8
    (error feedback), scan reduce-scatters them exactly through the
    gather's bf16 transpose — so the bound is the PR 8 int8-class
    tolerance, and the scan run may only be MORE faithful."""
    t_tree = _fit(tmpdir.join("tree"), "tree")
    t_scan = _fit(tmpdir.join("scan"), "scan")
    pt = jax.device_get(t_tree._state.params)
    ps = jax.device_get(t_scan._state.params)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(pt)[0][:50],
            jax.tree_util.tree_flatten_with_path(ps)[0][:50]):
        a, b = np.asarray(a), np.asarray(b)
        denom = max(float(np.linalg.norm(a)), 1e-9)
        rel = float(np.linalg.norm(a - b)) / denom
        assert rel < 2e-2, (jax.tree_util.keystr(path), rel)
    l_tree = float(t_tree.callback_metrics["train_loss"])
    l_scan = float(t_scan.callback_metrics["train_loss"])
    assert abs(l_scan - l_tree) / l_tree < 2e-2, (l_tree, l_scan)


def test_scan_gather_state_layouts_and_wire_report(tmpdir):
    """Scanned stacks stay 1/N-sharded as scan operands (the memory
    claim), their residuals are placeholders (no quantized exchange to
    feed), non-scanned fsdp leaves keep real residuals, and the wire
    record prices the in-scan traffic as hidden — exposed bytes drop
    vs tree mode."""
    trainer = _fit(tmpdir, "scan")
    st = trainer._state
    n = C.dp_size(trainer._mesh)
    w = st.params["layers"]["attn"]["wq"]       # [L, 64, 4, 16]
    assert not w.sharding.is_fully_replicated
    assert w.addressable_shards[0].data.shape[1] == 64 // n
    # scanned-leaf residuals are [n, 1] placeholders; the embed (up-front
    # gather + quantized RS path) keeps a real shard-local residual
    assert st.residual["layers"]["attn"]["wq"].shape == (n, 1)
    assert st.residual["embed"].shape[1] > 1
    comms = trainer.comms_per_step
    assert comms["gather_mode"] == "scan"
    assert comms["hidden_bytes_per_step"] > 0
    assert (comms["exposed_bytes_per_step"]
            + comms["hidden_bytes_per_step"]
            == comms["exchange_bytes_per_step"])
    tree_comms = _fit(tmpdir.join("t"), "tree").comms_per_step
    assert tree_comms["gather_mode"] == "tree"
    assert tree_comms["hidden_bytes_per_step"] == 0
    assert (comms["exposed_bytes_per_step"]
            < tree_comms["exposed_bytes_per_step"])


def test_scan_gather_composes_with_remat_dropout_and_accumulation(tmpdir):
    """The in-scan gather sits inside the remat body (the backward
    re-gathers) and inside the dropout-rng scan variant, and the
    post-exchange shard accumulator (ZeRO-2 window) still works — all
    three composed must train."""
    model = _gpt(remat=True, remat_policy="nothing", dropout=0.1)
    trainer = _fit(tmpdir, "scan", model=model,
                   accumulate_grad_batches=2)
    assert trainer.global_step > 0
    assert np.isfinite(float(trainer.callback_metrics["train_loss"]))
    # the accumulator is param-shaped (1/N) for scanned leaves too
    acc = trainer._state.grad_accum["layers"]["attn"]["wq"]
    assert acc.shape == trainer._state.params["layers"]["attn"]["wq"].shape


# --------------------------------------------------------------------- #
# Checkpoint portability across gather modes                             #
# --------------------------------------------------------------------- #
def test_checkpoint_resumes_across_gather_mode_change(tmpdir):
    """A sharded checkpoint saved under gather_mode='tree' resumes under
    'scan' (and the residual buffers re-shape through the template
    reconciliation chain — tree carries real layer-stack residuals,
    scan carries placeholders)."""
    t1 = _fit(tmpdir.join("a"), "tree", checkpoint_format="sharded")
    path = os.path.join(str(tmpdir), "x.ckpt")
    t1.save_checkpoint(path)
    t2 = Trainer(max_epochs=3, precision="f32", seed=0,
                 enable_checkpointing=False,
                 default_root_dir=str(tmpdir.join("b")),
                 log_every_n_steps=10 ** 9,
                 checkpoint_format="sharded",
                 accelerator=RayTPUAccelerator(num_workers=8,
                                               use_fsdp=True),
                 grad_compression="int8", gather_mode="scan")
    t2.fit(_gpt(), _loader(), ckpt_path=path)
    assert t2.global_step > t1.global_step
    n = C.dp_size(t2._mesh)
    assert t2._state.residual["layers"]["attn"]["wq"].shape == (n, 1)
    # params carried over: the resumed run trained FROM the checkpoint
    assert t2.comms_per_step["gather_mode"] == "scan"


# --------------------------------------------------------------------- #
# Compile discipline                                                     #
# --------------------------------------------------------------------- #
def test_scan_gather_zero_retraces_after_warmup(tmpdir, compile_guard):
    """The scan-gather step compiles once: ZERO new backend compiles
    over steps 2..12 (the same contract the tree-gather step and the
    mfu_overlap probe pin)."""
    from ray_lightning_accelerators_tpu import Callback
    from ray_lightning_accelerators_tpu.analysis.compile_guard import (
        compile_count)

    counts = []

    class CompileCounter(Callback):
        def on_train_batch_end(self, trainer, module, metrics, batch_idx):
            counts.append(compile_count())

    trainer = Trainer(max_steps=12, max_epochs=6, precision="f32",
                      seed=0, enable_checkpointing=False,
                      default_root_dir=str(tmpdir),
                      log_every_n_steps=4,
                      accelerator=RayTPUAccelerator(num_workers=8,
                                                    use_fsdp=True),
                      grad_compression="int8", gather_mode="scan",
                      callbacks=[CompileCounter()])
    trainer.fit(_gpt(n_layers=2), _loader(n=96, bs=8))
    assert len(counts) == 12
    assert counts[1:] == [counts[0]] * 11, counts


# --------------------------------------------------------------------- #
# Refusals + fallbacks                                                   #
# --------------------------------------------------------------------- #
def test_scan_gather_validation_refuses_bad_layouts():
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, fsdp=8))
    good = {"layers": {"w": NamedSharding(mesh, P(None, "fsdp"))},
            "embed": NamedSharding(mesh, P("fsdp", None))}
    C.validate_scan_gather(good, ("layers",))
    with pytest.raises(C.TensorShardedParamsError, match="top-level"):
        C.validate_scan_gather(good, ("missing",))
    bad = {"layers": {"w": NamedSharding(mesh, P("fsdp", None))}}
    with pytest.raises(C.TensorShardedParamsError, match="dim 0"):
        C.validate_scan_gather(bad, ("layers",))


def test_fsdp_shard_dim_ignores_size1_mesh_axes():
    """Rule-based logical shardings name every mesh axis (a GPT on a
    pure data x fsdp mesh still says pipeline/tensor); axes the mesh
    holds at size 1 shard nothing and must not trip the model-parallel
    refusal.  Bare specs (no mesh) keep the strict reading."""
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, fsdp=8))
    s = NamedSharding(mesh, P("pipeline", "fsdp", "tensor", None))
    assert C.fsdp_shard_dim(s) == 1
    with pytest.raises(C.TensorShardedParamsError):
        C.fsdp_shard_dim(P("pipeline", "fsdp", "tensor", None))


def test_scan_mode_falls_back_to_tree_for_unscanned_module(tmpdir):
    """A module without a layer scan (MNIST MLP) under
    gather_mode='scan' warns and falls back to the whole-tree gather —
    training proceeds, the wire record says tree."""
    from ray_lightning_accelerators_tpu.models.mnist import (
        MNISTClassifier, synthetic_mnist)
    x, y = synthetic_mnist(256, seed=0)
    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmpdir),
                      log_every_n_steps=10 ** 9,
                      accelerator=RayTPUAccelerator(num_workers=8,
                                                    use_fsdp=True),
                      grad_compression="int8", gather_mode="scan")
    trainer.fit(MNISTClassifier({"layer_1": 64, "layer_2": 64,
                                 "lr": 1e-3, "batch_size": 128}),
                DataLoader(ArrayDataset(x, y), batch_size=128))
    assert trainer.comms_per_step["gather_mode"] == "tree"
    assert trainer.global_step > 0


def test_trainer_rejects_unknown_gather_mode():
    with pytest.raises(ValueError, match="gather_mode"):
        Trainer(gather_mode="sideways")


# --------------------------------------------------------------------- #
# Wire accounting                                                        #
# --------------------------------------------------------------------- #
def test_wire_report_exposed_hidden_split():
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, fsdp=8))
    params = {"layers": {"w": np.zeros((4, 1024, 64), np.float32)},
              "embed": np.zeros((1024, 64), np.float32)}
    psh = {"layers": {"w": NamedSharding(mesh, P(None, "fsdp", None))},
           "embed": NamedSharding(mesh, P("fsdp", None))}
    cfg = C.ExchangeConfig(mode="int8")
    tree = C.wire_bytes_per_step(params, 8, cfg, param_shardings=psh)
    scan = C.wire_bytes_per_step(params, 8, cfg, param_shardings=psh,
                                 gather_mode="scan", scanned=("layers",))
    assert tree["hidden_bytes_per_step"] == 0
    assert tree["exposed_bytes_per_step"] \
        == tree["exchange_bytes_per_step"]
    assert scan["hidden_bytes_per_step"] > 0
    assert (scan["exposed_bytes_per_step"]
            + scan["hidden_bytes_per_step"]
            == scan["exchange_bytes_per_step"])
    # only the embed's up-front gather + quantized RS stays exposed
    assert scan["exposed_bytes_per_step"] \
        < tree["exposed_bytes_per_step"]
    with pytest.raises(ValueError, match="gather_mode"):
        C.wire_bytes_per_step(params, 8, cfg, gather_mode="sideways")
    # mixed data x fsdp mesh: the cross-data fp32 psum of the scanned
    # shards runs AFTER the backward (outside the scan), so it is
    # priced as exposed, not hidden — scan's exposed bytes grow by
    # exactly that term vs the data=1 layout
    mesh2 = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=2, fsdp=4))
    psh2 = {"layers": {"w": NamedSharding(mesh2, P(None, "fsdp", None))},
            "embed": NamedSharding(mesh2, P("fsdp", None))}
    scan2 = C.wire_bytes_per_step(params, 8, cfg, param_shardings=psh2,
                                  gather_mode="scan",
                                  scanned=("layers",))
    w_size = 4 * 1024 * 64
    data_psum = (2 * (2 - 1) / 2) * 4.0 * (w_size / 4)
    hidden2 = (3 / 4) * 2.0 * w_size * 2  # fwd AG + cotangent RS, bf16
    assert scan2["hidden_bytes_per_step"] == int(hidden2)
    assert scan2["exposed_bytes_per_step"] \
        >= int(data_psum)  # the psum is exposed (plus the embed leaf)
    assert (scan2["exposed_bytes_per_step"]
            + scan2["hidden_bytes_per_step"]
            == scan2["exchange_bytes_per_step"])


# --------------------------------------------------------------------- #
# int8 forward matmuls in the train step                                 #
# --------------------------------------------------------------------- #
def test_int8_ste_matmul_kernel_matches_dense_and_backprops():
    rng = np.random.default_rng(3)
    w = rng.normal(size=(128, 256)).astype(np.float32)
    x = rng.normal(size=(8, 128)).astype(np.float32)
    dense = _int8_ste_matmul(None, jnp.asarray(x), jnp.asarray(w))
    kern = _int8_ste_matmul("interpret", jnp.asarray(x), jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(kern), np.asarray(dense),
                               rtol=2e-5, atol=2e-4)
    # straight-through: gradients reach the f32 master and match the
    # dequant-dense backward
    gw = jax.grad(lambda ww: (_int8_ste_matmul(
        None, jnp.asarray(x), ww) ** 2).sum())(jnp.asarray(w))
    assert float(jnp.linalg.norm(gw)) > 0
    gw_k = jax.grad(lambda ww: (_int8_ste_matmul(
        "interpret", jnp.asarray(x), ww) ** 2).sum())(jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(gw_k), np.asarray(gw),
                               rtol=1e-3, atol=1e-3)


def test_int8_matmul_trainer_loss_tracks_fp32(tmpdir):
    """Trainer(int8_matmul=True): the int8-forward run's loss stays
    within the PR 3 int8 tolerance (2%) of the fp32 run."""
    def fit(flag, root):
        trainer = Trainer(max_epochs=2, precision="f32", seed=0,
                          enable_checkpointing=False,
                          default_root_dir=str(root),
                          log_every_n_steps=10 ** 9,
                          accelerator=RayTPUAccelerator(num_workers=8),
                          int8_matmul=flag)
        trainer.fit(_gpt(), _loader())
        return float(trainer.callback_metrics["train_loss"])

    l_fp = fit(False, tmpdir.join("fp"))
    l_q8 = fit(True, tmpdir.join("q8"))
    assert abs(l_q8 - l_fp) / l_fp < 0.02, (l_fp, l_q8)


# --------------------------------------------------------------------- #
# The autotune closed loop                                               #
# --------------------------------------------------------------------- #
def test_autotune_step_best_never_slower_than_default():
    """tune.autotune_step drives the TPE searcher against a measured
    objective; the default config is trial 0, so the returned best can
    only match or beat it — and with a landscape where scan+remat wins,
    the search finds it."""
    from ray_lightning_accelerators_tpu import tune

    def measure(config):
        dt = 1.0
        if config["gather_mode"] == "scan":
            dt -= 0.3
        if config["remat_policy"] == "nothing":
            dt -= 0.2
        if config["flash_block_q"] == 128:
            dt -= 0.05
        return dt

    space = {
        "remat_policy": tune.choice(["none", "nothing"]),
        "flash_block_q": tune.choice([64, 128]),
        "gather_mode": tune.choice(["tree", "scan"]),
    }
    default = {"remat_policy": "none", "flash_block_q": 64,
               "gather_mode": "tree"}
    out = tune.autotune_step(measure, space=space,
                             default_config=default, n_trials=16, seed=0)
    assert out["n_trials"] == 16
    assert out["default_step_time_s"] == pytest.approx(1.0)
    assert out["best_step_time_s"] <= out["default_step_time_s"]
    assert out["speedup_vs_default"] >= 1.0
    # the search actually moved off the default on this landscape
    assert out["best_config"]["gather_mode"] == "scan"
    assert out["best_step_time_s"] == pytest.approx(
        min(t["step_time_s"] for t in out["trials"]))


def test_autotune_step_survives_failing_configs():
    """A config whose measurement raises scores inf and the loop keeps
    going (a flash block larger than the sequence is a legal point in
    the space, not an abort)."""
    from ray_lightning_accelerators_tpu import tune

    calls = []

    def measure(config):
        calls.append(dict(config))
        if config["flash_block_q"] == 1024:
            raise RuntimeError("Mosaic: block exceeds sequence")
        return 0.5 if config["gather_mode"] == "scan" else 1.0

    space = {"flash_block_q": tune.choice([128, 1024]),
             "remat_policy": tune.choice(["none"]),
             "gather_mode": tune.choice(["tree", "scan"])}
    out = tune.autotune_step(
        measure, space=space,
        default_config={"flash_block_q": 1024, "remat_policy": "none",
                        "gather_mode": "tree"},
        n_trials=10, seed=1)
    assert out["default_step_time_s"] == float("inf")
    assert out["best_step_time_s"] < float("inf")
    assert len(calls) == 10
    failed = [t for t in out["trials"]
              if t["step_time_s"] == float("inf")]
    assert failed  # the default at least
