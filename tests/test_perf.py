"""Perf observatory (telemetry/perf.py) + bench regression gate
(scripts/perf_gate.py): phase-timeline accounting, HBM pool attribution
and leak alarm, goodput partition, the trainer integration's zero-
retrace and bounded-overhead guarantees, and the gate's
regression/wobble/dead-window verdicts."""

import json
import os
import sys
import time

import numpy as np
import pytest

from ray_lightning_accelerators_tpu.telemetry import (GoodputLedger,
                                                      HbmLedger,
                                                      MetricsRegistry,
                                                      PerfObservatory,
                                                      StepTimeline,
                                                      exposed_comm_crosscheck)
from ray_lightning_accelerators_tpu.telemetry import recorder as R

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts"))
import perf_gate  # noqa: E402  (scripts/ is not a package)

pytestmark = pytest.mark.perf


# --------------------------------------------------------------------- #
# StepTimeline                                                           #
# --------------------------------------------------------------------- #
def test_timeline_phases_sum_to_step_wall():
    tl = StepTimeline(ring=8)
    for _ in range(5):
        tl.step_begin()
        with tl.phase("h2d"):
            time.sleep(0.001)
        with tl.phase("compute"):
            time.sleep(0.004)
        tl.step_end()
    snap = tl.snapshot()
    assert snap["steps"] == 5
    # in-step phases sum to wall by construction (`other` absorbs the
    # remainder) and the hooks cover nearly all of it here
    assert snap["phase_sum_over_wall"] == pytest.approx(1.0, abs=1e-6)
    assert snap["attributed_fraction"] > 0.9
    assert snap["phases"]["compute"]["total_s"] > \
        snap["phases"]["h2d"]["total_s"]


def test_timeline_ring_bounded_and_out_of_step_phases():
    tl = StepTimeline(ring=4)
    for _ in range(10):
        tl.step_begin()
        tl.step_end()
    with tl.phase("ckpt"):
        time.sleep(0.001)
    snap = tl.snapshot()
    assert len(snap["recent_steps"]) == 4
    assert snap["recent_steps"][-1]["step"] == 10
    assert snap["between_step_phases"]["ckpt"]["count"] == 1
    assert "ckpt" not in snap["phases"]  # outside any step bracket


def test_timeline_compile_split_out_of_containing_phase():
    clock = {"s": 0.0}
    tl = StepTimeline(ring=4, compile_seconds_fn=lambda: clock["s"])
    tl.step_begin()
    with tl.phase("compute"):
        clock["s"] += 0.5  # a "compile" lands inside the dispatch
        time.sleep(0.002)
    tl.step_end()
    snap = tl.snapshot()
    # compile is its own phase, clamped to the containing measured
    # phase, and the sum-to-wall invariant survives the split
    assert "compile" in snap["phases"]
    assert snap["phases"]["compile"]["total_s"] <= \
        snap["step_wall_total_s"] + 1e-9
    # abs=1e-3: the snapshot rounds phase totals to 1us, which on a
    # 2ms step is a ~1e-3 relative quantization
    assert snap["phase_sum_over_wall"] == pytest.approx(1.0, abs=1e-3)


def test_timeline_scan_epoch_rows():
    tl = StepTimeline(ring=4)
    tl.observe_scan_epoch(0.8, 16)
    snap = tl.snapshot()
    assert snap["steps"] == 16
    assert snap["phases"]["compute"]["count"] == 16
    assert snap["recent_steps"][-1]["scanned_steps"] == 16


def test_timeline_overhead_bounded():
    """The recorder's <50us/emit spirit for the sampling seams: a full
    step bracket with two phases (6 perf_counter reads + dict ops) must
    stay far under the budget, or the observatory is not attachable to
    a hot loop."""
    tl = StepTimeline(ring=64)
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        tl.step_begin()
        with tl.phase("h2d"):
            pass
        with tl.phase("compute"):
            pass
        tl.step_end()
    per_step = (time.perf_counter() - t0) / n
    assert per_step < 50e-6, f"{per_step * 1e6:.1f}us per step bracket"


# --------------------------------------------------------------------- #
# HbmLedger                                                              #
# --------------------------------------------------------------------- #
def test_hbm_pools_attribute_against_total():
    state = {"params": 1000, "opt": 2000}
    led = HbmLedger(sample_min_s=0.0,
                    total_bytes_fn=lambda: sum(state.values()) + 300)
    led.register_pool("params", lambda: state["params"])
    led.register_pool("opt", lambda: state["opt"])
    out = led.sample()
    assert out["params"] == 1000 and out["opt"] == 2000
    assert out["other"] == 300 and out["total"] == 3300
    snap = led.snapshot()
    # pools + other == total exactly; attributed excludes `other`
    assert snap["attributed_bytes"] + snap["pools"]["other"]["bytes"] \
        == snap["total_bytes"]
    assert snap["attributed_fraction"] == pytest.approx(3000 / 3300,
                                                        abs=1e-3)
    # watermarks survive a shrink
    state["opt"] = 100
    led.sample()
    assert led.snapshot()["pools"]["opt"]["peak_bytes"] == 2000


def test_hbm_throttle_and_dead_reader():
    led = HbmLedger(sample_min_s=3600.0, total_bytes_fn=lambda: 10)
    led.register_pool("boom", lambda: (_ for _ in ()).throw(
        RuntimeError("dead")))
    assert led.sample()["boom"] == 0  # dead reader reports 0, no crash
    assert led.maybe_sample() is None  # inside the throttle window
    assert led.snapshot()["samples"] == 1


def test_hbm_leak_alarm_fires_once_per_streak():
    R.configure()  # fresh ring
    state = {"b": 1000}
    led = HbmLedger(sample_min_s=0.0, leak_samples=3, leak_min_bytes=500,
                    total_bytes_fn=lambda: state["b"])
    led.register_pool("pool", lambda: state["b"])
    for _ in range(6):  # strictly growing, past both thresholds
        led.sample()
        state["b"] += 300
    events = [e for e in R.get_recorder().events()
              if e["kind"] == "hbm_leak"]
    assert len(events) == 1  # one alarm per streak, not one per sample
    assert events[0]["data"]["suspect_pool"] == "pool"
    assert events[0]["data"]["growth_bytes"] >= 500
    # growth stops (below the LAST SAMPLE, not just the start) -> the
    # alarm re-arms -> a NEW streak fires again
    state["b"] -= 500
    led.sample()
    for _ in range(5):
        state["b"] += 400
        led.sample()
    events = [e for e in R.get_recorder().events()
              if e["kind"] == "hbm_leak"]
    assert len(events) == 2
    assert led.snapshot()["leak_alarms"] == 2


def test_hbm_below_thresholds_never_alarms():
    R.configure()
    state = {"b": 1000}
    led = HbmLedger(sample_min_s=0.0, leak_samples=5,
                    leak_min_bytes=10 ** 9,
                    total_bytes_fn=lambda: state["b"])
    for _ in range(20):
        led.sample()
        state["b"] += 1  # grows, but far under leak_min_bytes
    assert not [e for e in R.get_recorder().events()
                if e["kind"] == "hbm_leak"]


# --------------------------------------------------------------------- #
# GoodputLedger                                                          #
# --------------------------------------------------------------------- #
def test_goodput_partition_and_fraction():
    gl = GoodputLedger()
    gl.run_begin()
    with gl.measure("restart"):
        time.sleep(0.01)
    gl.account("productive", 0.03)
    gl.account("drain", 0.005)
    time.sleep(0.03)
    gl.run_end()
    snap = gl.snapshot()
    assert snap["wall_s"] >= 0.04
    assert set(snap["seconds"]) == {"restart", "productive", "drain"}
    assert 0.0 < snap["goodput_fraction"] <= 1.0
    assert snap["unattributed_s"] >= 0.0


def test_timeline_foreign_thread_observe_stays_out_of_open_step():
    """A serve loop sharing the timeline with a fitting trainer must
    not write into the trainer's open step bracket (review finding:
    the in-step branch keyed on _t_step alone, any thread)."""
    import threading
    tl = StepTimeline(ring=4)
    tl.step_begin()
    t = threading.Thread(target=lambda: tl.observe("decode", 0.5))
    t.start()
    t.join()
    with tl.phase("compute"):
        time.sleep(0.001)
    tl.step_end()
    snap = tl.snapshot()
    assert "decode" not in snap["phases"]  # foreign thread excluded
    assert snap["between_step_phases"]["decode"]["total_s"] == \
        pytest.approx(0.5)
    assert snap["phase_sum_over_wall"] == pytest.approx(1.0, abs=1e-3)


def test_goodput_rerun_resets_the_ledger():
    """A reused ElasticRunner's second run() must not compute wall from
    the first run's start (review finding: first-call-wins run_begin
    diluted the fraction with inter-run idle)."""
    gl = GoodputLedger()
    gl.run_begin()
    gl.note_attempt()
    gl.account("productive", 5.0)
    gl.run_end()
    time.sleep(0.02)  # inter-run idle that must NOT count
    gl.run_begin()
    gl.account("productive", 0.01)
    time.sleep(0.01)
    gl.run_end()
    snap = gl.snapshot()
    assert snap["wall_s"] < 0.02  # second run only
    assert snap["attempts"] == 0 and snap["seconds"]["productive"] \
        == pytest.approx(0.01)
    # run_begin while a run is OPEN stays a no-op
    gl2 = GoodputLedger()
    gl2.run_begin()
    time.sleep(0.01)
    gl2.run_begin()
    gl2.run_end()
    assert gl2.snapshot()["wall_s"] >= 0.01


def test_goodput_absorbs_timeline_and_events():
    gl = GoodputLedger()
    gl.run_begin()
    gl.absorb_timeline({
        "phases": {"compute": {"total_s": 2.0}, "h2d": {"total_s": 0.5},
                   "compile": {"total_s": 1.0}},
        "between_step_phases": {"ckpt": {"total_s": 0.25}}})
    gl.absorb_events([
        {"kind": "preempt_drain", "ts": 10.0},
        {"kind": "emergency_checkpoint", "ts": 10.4}])
    gl.run_end()
    s = gl.snapshot()["seconds"]
    assert s["productive"] == pytest.approx(2.5)
    assert s["compile"] == pytest.approx(1.0)
    assert s["checkpoint"] == pytest.approx(0.25)
    assert s["drain"] == pytest.approx(0.4)


# --------------------------------------------------------------------- #
# Exposed-comm crosscheck                                                #
# --------------------------------------------------------------------- #
def test_exposed_comm_crosscheck_direction_and_discrepancy():
    cc = exposed_comm_crosscheck(
        {"tree": 0.40, "scan": 0.30},
        {"tree": {"exchange_bytes_per_step": 100,
                  "exposed_bytes_per_step": 100},
         "scan": {"exchange_bytes_per_step": 100,
                  "exposed_bytes_per_step": 10}})
    assert cc["direction_agrees"]
    assert cc["measured_order"] == ["scan", "tree"]
    t = cc["modes"]["tree"]
    assert t["measured_exposed_fraction"] == pytest.approx(0.25)
    assert t["analytic_exposed_fraction"] == 1.0
    assert t["discrepancy"] == pytest.approx(-0.75)
    assert cc["modes"]["scan"]["measured_exposed_fraction"] == 0.0
    # a disagreement is EXPORTED, not asserted away
    cc2 = exposed_comm_crosscheck(
        {"tree": 0.30, "scan": 0.40},
        {"tree": {"exchange_bytes_per_step": 100,
                  "exposed_bytes_per_step": 100},
         "scan": {"exchange_bytes_per_step": 100,
                  "exposed_bytes_per_step": 10}})
    assert not cc2["direction_agrees"]
    with pytest.raises(ValueError, match=">= 2 modes"):
        exposed_comm_crosscheck({"tree": 0.1}, {"tree": {}})


# --------------------------------------------------------------------- #
# Registry export                                                        #
# --------------------------------------------------------------------- #
def test_registry_exports_all_three_ledgers():
    tl = StepTimeline(ring=4)
    tl.step_begin()
    with tl.phase("compute"):
        pass
    tl.step_end()
    led = HbmLedger(sample_min_s=0.0, total_bytes_fn=lambda: 100)
    led.register_pool("params", lambda: 80)
    led.sample()
    gl = GoodputLedger()
    gl.run_begin()
    gl.account("productive", 0.5)
    gl.run_end()
    reg = MetricsRegistry()
    reg.add_step_timeline(tl)
    reg.add_hbm(led)
    reg.add_goodput(gl)
    j = reg.to_json()
    assert set(j["perf"]) == {"step_timeline", "hbm", "goodput"}
    txt = reg.prometheus_text()
    for needle in ("rla_tpu_steps_total",
                   'rla_tpu_step_phase_seconds_total{phase="compute"}',
                   'rla_tpu_hbm_pool_bytes{pool="params"}',
                   "rla_tpu_hbm_attributed_fraction",
                   'rla_tpu_goodput_seconds_total{category="productive"}',
                   "rla_tpu_goodput_fraction"):
        assert needle in txt, needle


# --------------------------------------------------------------------- #
# Trainer integration                                                    #
# --------------------------------------------------------------------- #
def _mnist_fit(tmpdir, perf, **kw):
    from ray_lightning_accelerators_tpu import (DataLoader,
                                                RayTPUAccelerator,
                                                Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.models.mnist import (
        MNISTClassifier, synthetic_mnist)
    x, y = synthetic_mnist(256, seed=0)
    loader = DataLoader(ArrayDataset(x, y), batch_size=64, shuffle=False)
    model = MNISTClassifier({"layer_1": 32, "layer_2": 32, "lr": 1e-3,
                             "batch_size": 64})
    trainer = Trainer(max_epochs=3, precision="f32", seed=0,
                      accelerator=RayTPUAccelerator(),
                      enable_checkpointing=True,
                      log_every_n_steps=10 ** 9,
                      perf_observatory=perf,
                      default_root_dir=str(tmpdir), **kw)
    trainer.fit(model, loader)
    return trainer


def test_trainer_observatory_timeline_hbm_and_report(tmp_path):
    perf = PerfObservatory(hbm=HbmLedger(sample_min_s=0.0))
    trainer = _mnist_fit(tmp_path, perf)
    tl = perf.timeline.snapshot()
    assert tl["steps"] == trainer.global_step == 12
    assert tl["phase_sum_over_wall"] == pytest.approx(1.0, abs=1e-6)
    # the acceptance bar: named phases cover >= 90% of step wall
    assert tl["attributed_fraction"] >= 0.9, tl["phases"]
    assert "compute" in tl["phases"]
    assert tl["between_step_phases"]["ckpt"]["total_s"] > 0  # saves
    hbm = perf.hbm.snapshot()
    assert hbm["pools"]["params"]["bytes"] > 0
    assert hbm["pools"]["opt_state"]["bytes"] > 0
    # pools + other == the live placed-array total, exactly
    assert hbm["attributed_bytes"] + hbm["pools"]["other"]["bytes"] \
        == hbm["total_bytes"]
    reg = trainer.build_metrics_registry()
    j = reg.to_json()
    assert "step_timeline" in j["perf"] and "hbm" in j["perf"]


@pytest.mark.analysis
def test_trainer_zero_retraces_with_observatory(tmp_path):
    """The observatory must be attachable to the hot loop for free: the
    12-step fit compiles its programs once and retraces ZERO times in
    steady state with the timeline + HBM sampler live (same contract
    the PR 6 compile-guard test pins for the bare trainer)."""
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg
    from ray_lightning_accelerators_tpu.core.callbacks import Callback

    counts = []

    class Snap(Callback):
        def on_train_batch_end(self, trainer, module, metrics, idx):
            counts.append(cg.compile_count())

    perf = PerfObservatory(hbm=HbmLedger(sample_min_s=0.0))
    _mnist_fit(tmp_path, perf, callbacks=[Snap()])
    assert len(counts) == 12
    # steady state: after the warmup step every later step compiles 0
    assert counts[-1] == counts[1], (
        f"retrace with observatory enabled: {counts}")


# --------------------------------------------------------------------- #
# Perf gate                                                              #
# --------------------------------------------------------------------- #
_BASE = {"default_tolerance": 0.1,
         "metrics": {
             "m_up": {"baseline": 100.0, "tolerance": 0.1},
             "m_down": {"baseline": 10.0, "tolerance": 0.2,
                        "direction": "lower"},
             "m_field": {"metric": "m_up", "field": "aux",
                         "baseline": 0.5, "tolerance": 0.1}}}


def _recs(up=100.0, down=10.0, aux=0.5):
    return [{"metric": "m_up", "value": up, "aux": aux},
            {"metric": "m_down", "value": down}]


def test_gate_passes_within_tolerance_wobble():
    rep = perf_gate.gate_records(_recs(up=92.0, down=11.5, aux=0.47),
                                 _BASE)
    assert rep["status"] == "PASS"
    assert rep["regressions"] == 0 and rep["gated"] == 3


def test_gate_fails_injected_regression():
    rep = perf_gate.gate_records(_recs(up=85.0), _BASE)  # < 90 floor
    assert rep["status"] == "REGRESSION"
    bad = [r for r in rep["results"] if r["status"] == "REGRESSION"]
    assert [r["metric"] for r in bad] == ["m_up"]
    # direction=lower regresses UPWARD
    rep2 = perf_gate.gate_records(_recs(down=13.0), _BASE)  # > 12 ceiling
    assert rep2["status"] == "REGRESSION"
    # a regression in a non-`value` field is caught too
    rep3 = perf_gate.gate_records(_recs(aux=0.3), _BASE)
    assert rep3["status"] == "REGRESSION"


def test_gate_dead_backend_window_gates_fallbacks_only():
    records = [{"metric": "backend_probe", "value": 0,
                "error": "backend unavailable"},
               {"metric": "m_up", "value": 98.0, "aux": 0.5}]
    rep = perf_gate.gate_records(records, _BASE)
    assert rep["dead_backend"]
    assert rep["status"] == "PASS"  # the fallback metric gated and passed
    by = {r["metric"]: r for r in rep["results"]}
    assert by["m_down"]["status"] == "UNGATED"
    assert by["m_down"]["reason"] == "dead-backend window"


def test_gate_zero_numbers_window_is_ungated_never_green():
    records = [{"metric": "backend_probe", "value": 0,
                "error": "backend unavailable",
                "detail": "device probe hung > 120s"}]
    rep = perf_gate.gate_records(records, _BASE)
    assert rep["status"] == "UNGATED"
    assert all(r["status"] == "UNGATED" for r in rep["results"])
    # and the CLI maps it to rc 2 (never 0)
    assert perf_gate.run.__defaults__ is not None  # sanity


def test_gate_cli_roundtrip(tmp_path, capsys):
    window = tmp_path / "window.jsonl"
    window.write_text("\n".join(json.dumps(r) for r in _recs()))
    base = tmp_path / "base.json"
    base.write_text(json.dumps(_BASE))
    rc = perf_gate.main(["--input", str(window),
                         "--baseline", str(base)])
    assert rc == 0
    assert "perf gate [PASS]" in capsys.readouterr().out
    window.write_text("\n".join(json.dumps(r)
                                for r in _recs(up=50.0)))
    assert perf_gate.main(["--input", str(window),
                           "--baseline", str(base)]) == 1
    # a BENCH_r*.json driver archive (records inside `tail`) parses too
    arch = tmp_path / "BENCH_r99.json"
    arch.write_text(json.dumps(
        {"rc": 0, "tail": "\n".join(json.dumps(r) for r in _recs())}))
    assert perf_gate.main(["--input", str(arch),
                           "--baseline", str(base)]) == 0


def test_gate_parse_window_skips_chatter():
    text = ("WARNING: some log line\n"
            + json.dumps({"metric": "m_up", "value": 1}) + "\n"
            + "not json {\n")
    recs = perf_gate.parse_window(text)
    assert recs == [{"metric": "m_up", "value": 1}]


# --------------------------------------------------------------------- #
# Elastic goodput integration (stub pool, no processes)                  #
# --------------------------------------------------------------------- #
def test_elastic_runner_owns_a_goodput_ledger():
    from ray_lightning_accelerators_tpu.runtime.elastic import \
        ElasticRunner

    class _F:
        def __init__(self, v):
            self._v = v

        def done(self):
            return True

        def exception(self):
            return None

        def result(self, timeout=None):
            return self._v

    class _StubPool:
        def __init__(self):
            self.workers = []

        def __len__(self):
            return 1

        def execute_all(self, fn):
            return [_F(fn())]

    runner = ElasticRunner(_StubPool(), max_failures=0)
    out = runner.run(lambda: 7)
    assert out == [7]
    snap = runner.goodput.snapshot()
    assert snap["attempts"] == 1 and snap["preemptions"] == 0
    assert snap["wall_s"] > 0.0
    # deterministic regardless of the stub run's (sub-millisecond,
    # 1us-quantized) wall: over-accounting clamps the fraction to 1.0
    runner.goodput.account("productive", snap["wall_s"] + 1.0)
    assert runner.goodput.snapshot()["goodput_fraction"] == 1.0
