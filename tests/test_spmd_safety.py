"""SPMD safety analyzer: the three static rules (collective axis
consistency, rank divergence, sharding inventory), the cross-rank
collective sanitizer + typed ``CollectiveMismatch`` across both wire
paths, the driver-side sequence checker seams (trainer fan-out /
elastic attempts), the sharding audit, and the graftlint CLI speed/JSON
satellites.

The acceptance loop: a fan-out where one rank traces a DIVERGENT
collective sequence (the silent-deadlock failure mode) surfaces as a
typed ``CollectiveMismatch`` whose diagnosis names the first divergent
call — instead of a generic wedge."""

import json
import os
import subprocess
import sys
import time
import types

import pytest

from ray_lightning_accelerators_tpu.analysis import lint as L
from ray_lightning_accelerators_tpu.testing import spmd_sanitizer as S

pytestmark = pytest.mark.spmd

PKG_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "ray_lightning_accelerators_tpu")
SCRIPTS = os.path.join(os.path.dirname(PKG_DIR), "scripts")

AXES = dict(spmd_axis_names=frozenset({"data", "fsdp", "tensor"}))


def _findings(sources, rule=None, **cfg_kw):
    cfg = L.LintConfig(**cfg_kw) if cfg_kw else L.LintConfig.for_tree(sources)
    out = L.run_lint(sources, cfg)
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


def _active(findings):
    return [f for f in findings if not f.suppressed]


# --------------------------------------------------------------------- #
# rule: spmd-collective                                                 #
# --------------------------------------------------------------------- #
MESH_SRC = '''
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
BATCH_AXES = (DATA_AXIS, FSDP_AXIS)
'''

SPMD_POSITIVE = '''
import jax

def bad_literal(x):
    return jax.lax.psum(x, "batch")          # undeclared axis name

def bad_tuple(x):
    return jax.lax.all_gather(x, ("data", "model"), axis=0, tiled=True)

def bad_unresolvable(x, cfg):
    axes = cfg.lookup()                      # opaque: not axis-derived
    return jax.lax.pmean(x, axes)

def bad_index():
    return jax.lax.axis_index("replica")     # undeclared, axis arg 0
'''

SPMD_NEGATIVE = '''
import jax
from .meshmod import BATCH_AXES, FSDP_AXIS

def dp_axes(mesh):
    return tuple(BATCH_AXES)                 # an axis function

def fine_literal(x):
    return jax.lax.psum(x, "data")

def fine_constants(x):
    own = jax.lax.axis_index(FSDP_AXIS)
    return jax.lax.all_gather(x, BATCH_AXES, axis=0, tiled=True) + own

def fine_derived(x, mesh):
    axes = dp_axes(mesh)
    data_axes = tuple(a for a in axes if a != FSDP_AXIS)
    part = jax.lax.psum(x, data_axes)
    return jax.lax.pmean(part, axes)

def fine_param(x, axis_name):
    # shard_map-body convention: the axis flows from checked call sites
    return jax.lax.psum(x, axis_name)

def fine_kwarg(x):
    return jax.lax.psum_scatter(x, axis_name="data", tiled=True)
'''


def test_spmd_collective_positives():
    found = _findings({"m.py": SPMD_POSITIVE}, rule="spmd-collective",
                      **AXES)
    active = _active(found)
    msgs = "\n".join(f.message for f in active)
    assert len(active) == 4, found
    assert "['batch']" in msgs
    assert "['model']" in msgs          # only the undeclared half named
    assert "does not resolve" in msgs   # the opaque cfg.lookup() case
    assert "['replica']" in msgs
    assert {f.line for f in active}     # positions populated


def test_spmd_collective_negatives():
    found = _findings({"meshmod.py": MESH_SRC, "m.py": SPMD_NEGATIVE},
                      rule="spmd-collective", **AXES)
    assert _active(found) == [], found


def test_spmd_collective_disabled_without_axis_registry():
    # no declared axes (default config, no axes module in the tree):
    # the rule stays silent instead of flagging everything
    found = _findings({"m.py": SPMD_POSITIVE}, rule="spmd-collective")
    assert found == []


def test_spmd_collective_pragma():
    src = ("import jax\n"
           "def f(x):\n"
           "    # graftlint: ok(spmd-collective) — test fixture axis\n"
           "    return jax.lax.psum(x, 'weird')\n")
    found = _findings({"m.py": src}, rule="spmd-collective", **AXES)
    assert found and all(f.suppressed for f in found)


# --------------------------------------------------------------------- #
# rule: rank-divergence                                                 #
# --------------------------------------------------------------------- #
RANK_POSITIVE = '''
import time
import random
import jax
from jax.experimental import multihost_utils

def gated_collective(x):
    if jax.process_index() == 0:             # rank branch over psum
        x = jax.lax.psum(x, "data")
    return x

def gated_barrier():
    r = jax.process_index()
    if r != 0:                               # via a rank-valued local
        multihost_utils.sync_global_devices("x")

def gated_commit(state, save_sharded):
    if jax.process_index() == 0:
        save_sharded("/ckpt", state, {})     # collective commit, gated

@jax.jit
def nondet_step(x):
    return x * time.time()                   # trace-time host value

def outer():
    def body(x):
        return x + _jitter()
    return jax.jit(body)

def _jitter():
    return random.random()                   # reachable from jitted body
'''

RANK_NEGATIVE = '''
import time
import jax
from jax.experimental import multihost_utils

def count_gated():
    if jax.process_count() > 1:              # uniform across ranks: fine
        multihost_utils.sync_global_devices("ok")

def rank_gated_logging(metrics, log):
    if jax.process_index() == 0:             # host-local work only
        log.info("metrics: %s", metrics)

def host_timing():
    t0 = time.monotonic()                    # not under trace
    return time.monotonic() - t0

@jax.jit
def clean_step(x, rng):
    noise = jax.random.normal(rng, x.shape)  # seeded PRNG: fine
    return x + noise
'''


def test_rank_divergence_positives():
    found = _findings({"m.py": RANK_POSITIVE}, rule="rank-divergence")
    active = _active(found)
    msgs = "\n".join(f.message for f in active)
    assert "collective lax.psum" in msgs
    assert "sync_global_devices" in msgs
    assert "checkpoint commit 'save_sharded'" in msgs
    assert "time.time" in msgs and "TRACE time" in msgs
    assert "random.random" in msgs  # through the within-module closure
    assert len(active) >= 5, found


def test_rank_divergence_flags_elif_arms():
    """Regression (review finding): an elif/else arm of a rank-gated if
    executes only on the COMPLEMENT rank subset — equally divergent."""
    src = ("import jax\n"
           "def f(x, flag):\n"
           "    if jax.process_index() == 0:\n"
           "        pass\n"
           "    elif flag:\n"
           "        x = jax.lax.psum(x, 'data')\n"
           "    return x\n"
           "def g(x):\n"
           "    if jax.process_index() == 0:\n"
           "        pass\n"
           "    else:\n"
           "        x = jax.lax.psum(x, 'data')\n"
           "    return x\n")
    found = _active(_findings({"m.py": src}, rule="rank-divergence"))
    # both the elif and the else spelling are caught
    assert len(found) >= 2, found


def test_rank_divergence_negatives():
    found = _findings({"m.py": RANK_NEGATIVE}, rule="rank-divergence")
    assert _active(found) == [], found


def test_rank_divergence_pragma():
    src = ("import jax\n"
           "def f(state, save_sharded):\n"
           "    # graftlint: ok(rank-divergence) — single-writer meta\n"
           "    if jax.process_index() == 0:\n"
           "        save_sharded('/p', state, {})\n")
    found = _findings({"m.py": src}, rule="rank-divergence")
    assert found and all(f.suppressed for f in found)


# --------------------------------------------------------------------- #
# rule: sharding-inventory                                              #
# --------------------------------------------------------------------- #
SPEC_SRC = '''
import jax
from jax.sharding import PartitionSpec as P

PS = jax.sharding.PartitionSpec

def layouts():
    a = P("data", None)                      # imported-alias spelling
    b = jax.sharding.PartitionSpec(None)     # dotted spelling
    c = PS("fsdp")                           # local-alias spelling
    return a, b, c
'''


def test_sharding_inventory_flags_uninventoried_modules():
    found = _findings({"models/thing.py": SPEC_SRC},
                      rule="sharding-inventory")
    active = _active(found)
    assert len(active) == 3, found  # all three spellings caught
    assert all("uninventoried" in f.message for f in active)


def test_sharding_inventory_allows_inventoried_modules():
    for key in ("parallel/sharding.py", "core/trainer.py",
                "accelerators/base.py"):
        found = _findings({key: SPEC_SRC}, rule="sharding-inventory")
        assert found == [], (key, found)


def test_sharding_inventory_pragma():
    src = ("from jax.sharding import PartitionSpec as P\n"
           "# graftlint: ok(sharding-inventory) — test fixture layout\n"
           "spec = P('data')\n")
    found = _findings({"serve/engine.py": src}, rule="sharding-inventory")
    assert found and all(f.suppressed for f in found)


# --------------------------------------------------------------------- #
# the real tree: new rules enabled, clean, and genuinely firing         #
# --------------------------------------------------------------------- #
def test_tree_is_clean_with_spmd_rules_and_they_fire():
    findings = L.lint_path(PKG_DIR)
    for rule in ("spmd-collective", "rank-divergence",
                 "sharding-inventory"):
        assert [f for f in findings
                if f.rule == rule and not f.suppressed] == [], rule
    # the inventory + divergence rules genuinely fire on this tree
    # (deliberate, pragma'd violations — the paper trail)
    assert any(f.rule == "sharding-inventory" and f.suppressed
               for f in findings)
    assert any(f.rule == "rank-divergence" and f.suppressed
               for f in findings)


# --------------------------------------------------------------------- #
# graftlint CLI satellites: JSON output + parse cache                   #
# --------------------------------------------------------------------- #
def test_cli_format_json_on_tree():
    script = os.path.join(SCRIPTS, "graftlint.py")
    proc = subprocess.run(
        [sys.executable, script, PKG_DIR, "--format", "json"],
        capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["schema"] == 1 and payload["active"] == 0
    assert payload["exit_code"] == 0 and payload["suppressed"] > 0
    rows = payload["findings"]
    assert rows and all(
        set(r) >= {"rule", "path", "line", "col", "message", "suppressed"}
        for r in rows)


def test_cli_format_json_nonzero_on_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nv = os.environ.get('RLA_TPU_OOPS')\n")
    script = os.path.join(SCRIPTS, "graftlint.py")
    proc = subprocess.run(
        [sys.executable, script, str(bad), "--format", "json"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)  # JSON still lands on violation
    assert payload["exit_code"] == 1
    assert any(r["rule"] == "knob-registry" for r in payload["findings"])


def test_parse_cache_is_mtime_keyed(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    target = pkg / "mod.py"
    target.write_text("import os\nx = os.environ.get('RLA_TPU_NOPE')\n")
    L.lint_path(str(pkg))
    path = str(target)
    assert path in L._MODULE_CACHE
    first = L._MODULE_CACHE[path][3]
    L.lint_path(str(pkg))
    assert L._MODULE_CACHE[path][3] is first  # cache hit: same object
    # a rewrite (new mtime) reparses — and the findings track the edit
    time.sleep(0.01)
    target.write_text("import os\nx = os.environ.get('XLA_FLAGS')\n")
    found = L.lint_path(str(pkg))
    assert L._MODULE_CACHE[path][3] is not first
    assert not any(f.rule == "knob-registry" for f in found)


# --------------------------------------------------------------------- #
# sanitizer: interception, ring, spill                                  #
# --------------------------------------------------------------------- #
def test_sanitizer_records_traced_collectives(spmd_sanitizer):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from ray_lightning_accelerators_tpu.parallel.sharding import (
        shard_map_compat)

    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))

    def f(x):
        own = jax.lax.axis_index("data")
        return jax.lax.psum(x, "data") + own

    out = shard_map_compat(f, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data"),
                           check_rep=False)(jnp.arange(4, dtype=jnp.float32))
    assert out.shape == (4,)
    san = spmd_sanitizer.get_sanitizer()
    seq = san.sequence()
    ops = [e["op"] for e in seq]
    assert "axis_index" in ops and "psum" in ops, seq
    psum = seq[ops.index("psum")]
    assert psum["axes"] == ["data"]
    assert psum["dtype"] == "float32"
    assert psum["site"] and "test_spmd_safety.py" in psum["site"]
    # spill landed under the fixture's telemetry dir, driver-labeled
    snaps = spmd_sanitizer.gather_sequences()
    assert "driver" in snaps
    assert [e["op"] for e in snaps["driver"]["events"]] == ops
    # each record also mirrors into the flight recorder's timeline (the
    # sanitizer's own spill stays the authoritative diff channel)
    from ray_lightning_accelerators_tpu.telemetry import recorder as R
    kinds = [e["kind"] for e in R.get_recorder().events()]
    assert "spmd_collective" in kinds


def test_sanitizer_uninstall_restores_jax_lax(spmd_sanitizer):
    import jax
    assert getattr(jax.lax.psum, "_rla_spmd_wrapped", False)
    spmd_sanitizer.uninstall()
    assert not getattr(jax.lax.psum, "_rla_spmd_wrapped", False)
    assert spmd_sanitizer.get_sanitizer() is None
    # double-uninstall is a no-op; fixture teardown tolerates it too
    spmd_sanitizer.uninstall()


def test_sanitizer_reinstall_rebinds_ring_without_double_wrap():
    import jax
    try:
        a = S.install(S.SpmdSanitizer(capacity=8))
        b = S.install(S.SpmdSanitizer(capacity=8))
        jax.lax.axis_index  # patched attr exists
        # one wrapper layer only: recording goes to the NEW ring
        S.get_sanitizer()
        assert S.get_sanitizer() is b
        b.record("psum", "data")
        assert a.sequence() == []
        assert len(b.sequence()) == 1
    finally:
        S.uninstall()
    assert not getattr(jax.lax.psum, "_rla_spmd_wrapped", False)


def test_sanitizer_ring_keeps_absolute_indices():
    san = S.SpmdSanitizer(capacity=4)
    for i in range(10):
        san.record("psum", "data", site=f"m.py:{i}")
    seq = san.sequence()
    assert len(seq) == 4
    assert [e["i"] for e in seq] == [6, 7, 8, 9]
    assert san.snapshot()["n"] == 10


def test_maybe_install_honors_knob(monkeypatch):
    monkeypatch.delenv(S.SANITIZER_ENV, raising=False)
    assert S.maybe_install_from_env() is None
    try:
        san = S.maybe_install_from_env(
            rank=3, env={S.SANITIZER_ENV: "1",
                         "RLA_TPU_SPMD_SEQ_EVENTS": "16"})
        assert san is not None and san.capacity == 16 and san.rank == 3
    finally:
        S.uninstall()


# --------------------------------------------------------------------- #
# checker: diff + typed CollectiveMismatch                              #
# --------------------------------------------------------------------- #
def _seq_snapshot(rank, ops, start=0):
    events = [{"i": start + j, "op": op, "axes": ["data"], "shape": [4],
               "dtype": "float32",
               "site": f"parallel/x.py:{10 + start + j}"}
              for j, op in enumerate(ops)]
    return {"rank": rank, "pid": 1, "n": start + len(ops),
            "capacity": 512, "events": events}


def _write_seq(tdir, rank, ops, start=0):
    os.makedirs(str(tdir), exist_ok=True)
    path = os.path.join(str(tdir), f"rank{rank}.collectives.json")
    with open(path, "w") as f:
        json.dump(_seq_snapshot(rank, ops, start), f)


def test_diff_sequences_agreement_and_divergence():
    same = {"rank0": _seq_snapshot(0, ["psum", "all_gather"]),
            "rank1": _seq_snapshot(1, ["psum", "all_gather"])}
    assert S.diff_sequences(same) is None
    div = {"rank0": _seq_snapshot(0, ["psum", "all_gather"]),
           "rank1": _seq_snapshot(1, ["psum", "pmean"])}
    d = S.diff_sequences(div)
    assert d["first_divergence"] == 1
    assert d["per_rank"]["rank0"]["op"] == "all_gather"
    assert d["per_rank"]["rank1"]["op"] == "pmean"
    # one rank's stream ENDING early is a divergence too
    short = {"rank0": _seq_snapshot(0, ["psum", "pmean"]),
             "rank1": _seq_snapshot(1, ["psum"])}
    d = S.diff_sequences(short)
    assert d["first_divergence"] == 1
    assert d["per_rank"]["rank1"] is None
    # fewer than two rank sequences: nothing to diff (driver excluded)
    assert S.diff_sequences({"rank0": _seq_snapshot(0, ["psum"]),
                             "driver": _seq_snapshot(None, [])}) is None


def test_diff_sequences_aligns_after_ring_drop():
    # rank0's ring dropped entries 0..5; overlap still compares aligned
    full = _seq_snapshot(0, ["pmean"] * 4, start=6)
    other = _seq_snapshot(1, ["psum"] * 6 + ["pmean"] * 4)
    assert S.diff_sequences({"rank0": full, "rank1": other}) is None
    diverged = _seq_snapshot(1, ["psum"] * 6 + ["pmean"] * 3
                             + ["all_gather"])
    d = S.diff_sequences({"rank0": full, "rank1": diverged})
    assert d["first_divergence"] == 9 and d["ring_dropped"]


def test_checker_raises_typed_mismatch(tmp_path):
    _write_seq(tmp_path, 0, ["psum", "all_gather"])
    _write_seq(tmp_path, 1, ["psum", "pmean"])
    with pytest.raises(S.CollectiveMismatch) as ei:
        S.check_collective_sequences(str(tmp_path))
    exc = ei.value
    assert exc.diagnosis["first_divergence"] == 1
    assert "all_gather" in str(exc) and "pmean" in str(exc)
    assert "parallel/x.py:11" in str(exc)  # the divergent call SITE
    # non-raising form for postmortem seams
    back = S.check_collective_sequences(str(tmp_path),
                                        raise_on_mismatch=False)
    assert isinstance(back, S.CollectiveMismatch)


def test_clear_spills_removes_only_sequence_files(tmp_path):
    _write_seq(tmp_path, 0, ["psum"])
    _write_seq(tmp_path, 3, ["pmean"])
    other = os.path.join(str(tmp_path), "rank0.events.json")
    with open(other, "w") as f:
        f.write("{}")
    S.clear_spills(str(tmp_path))
    assert S.gather_sequences(str(tmp_path)) == {}
    assert os.path.exists(other)  # flight-recorder spills untouched


def test_elastic_decodes_hangs_only(tmp_path, monkeypatch):
    """The elastic seam must never read a crash-truncated spill as a
    deterministic divergence — only hang-shaped failures decode."""
    from ray_lightning_accelerators_tpu.runtime.elastic import ElasticRunner
    from ray_lightning_accelerators_tpu.runtime.watchdog import WorkerWedged
    _write_seq(tmp_path, 0, ["psum", "all_gather"])
    _write_seq(tmp_path, 1, ["psum"])           # truncated mid-trace
    monkeypatch.setenv(S.SANITIZER_ENV, "1")
    monkeypatch.setenv("RLA_TPU_TELEMETRY_DIR", str(tmp_path))
    runner = ElasticRunner(types.SimpleNamespace(workers=[]))
    assert runner._collective_mismatch(RuntimeError("worker died")) is None
    wedge = WorkerWedged.for_rank(1, {"detail": "stuck"})
    got = runner._collective_mismatch(wedge)
    assert isinstance(got, S.CollectiveMismatch)


def test_check_world_collectives_is_gated(tmp_path, monkeypatch):
    _write_seq(tmp_path, 0, ["psum"])
    _write_seq(tmp_path, 1, ["pmean"])
    monkeypatch.setenv("RLA_TPU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.delenv(S.SANITIZER_ENV, raising=False)
    assert S.check_world_collectives() is None   # knob off: no-op
    monkeypatch.setenv(S.SANITIZER_ENV, "1")
    with pytest.raises(S.CollectiveMismatch):
        S.check_world_collectives()


# --------------------------------------------------------------------- #
# wire: CollectiveMismatch crosses the local pipe AND the agent relay   #
# --------------------------------------------------------------------- #
def _raise_mismatch():
    from ray_lightning_accelerators_tpu.testing.spmd_sanitizer import (
        CollectiveMismatch)
    raise CollectiveMismatch.from_divergence({
        "first_divergence": 2,
        "per_rank": {"rank0": {"op": "psum", "axes": ["data"],
                               "shape": [8], "dtype": "float32",
                               "site": "parallel/collectives.py:200"},
                     "rank1": None},
        "lengths": {"rank0": 3, "rank1": 2}})


def test_mismatch_rebuilds_typed_over_local_pipe():
    from ray_lightning_accelerators_tpu.runtime.actors import ActorPool
    with ActorPool(1) as pool:
        fut = pool.execute_all(_raise_mismatch)[0]
        with pytest.raises(S.CollectiveMismatch) as ei:
            fut.result(timeout=120)
    exc = ei.value
    assert exc.remote_typed  # rebuilt from the wire payload
    assert exc.diagnosis["first_divergence"] == 2
    assert exc.diagnosis["per_rank"]["rank1"] is None


def test_mismatch_rebuilds_typed_over_agent_relay():
    from ray_lightning_accelerators_tpu.runtime.agent import (HostAgent,
                                                              RemoteWorker)
    agent = HostAgent(port=0, bind="127.0.0.1")
    agent.serve_in_background()
    w = None
    try:
        w = RemoteWorker(f"127.0.0.1:{agent.port}", rank=0)
        with pytest.raises(S.CollectiveMismatch) as ei:
            w.execute(_raise_mismatch).result(timeout=120)
        exc = ei.value
        assert exc.remote_typed
        assert exc.diagnosis["first_divergence"] == 2
        assert "collectives.py:200" in str(exc)
    finally:
        if w is not None:
            w.kill()
        agent.shutdown()


def test_wire_registry_roundtrips_every_name():
    """Registry<->rebuilder consistency, now including the sanitizer's
    type: every registered name rebuilds to ITS class (the shared
    rebuild_remote both the local collector and the agent relay call)."""
    from ray_lightning_accelerators_tpu.runtime import wire
    assert set(wire.WIRE_EXCEPTION_NAMES) == set(wire._rebuilders())
    assert "CollectiveMismatch" in wire.WIRE_EXCEPTION_NAMES
    for name, build in wire._rebuilders().items():
        sample = (S.CollectiveMismatch.from_divergence(
            {"first_divergence": 0, "per_rank": {}})
            if name == "CollectiveMismatch" else None)
        msg = str(sample) if sample is not None else f"{name}: boom"
        back = wire.rebuild_remote(name, msg, "tb")
        assert type(back).__name__ == name, (name, type(back))
        assert back.remote_typed


# --------------------------------------------------------------------- #
# fan-out acceptance: injected rank-divergent collective               #
# --------------------------------------------------------------------- #
def _trace_rank_collectives(rank, divergent_rank):
    """Worker body: trace a tiny shard_map program whose collective
    sequence DEPENDS ON THE RANK when rank == divergent_rank — the
    injected drift the sanitizer exists to catch.  The sanitizer was
    installed at worker boot from the env overlay."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from ray_lightning_accelerators_tpu.parallel.sharding import (
        shard_map_compat)

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))

    def f(x):
        y = jax.lax.psum(x, "data")
        if rank == divergent_rank:   # the rank-divergent collective
            y = jax.lax.pmean(y, "data")
        return y

    out = shard_map_compat(f, mesh=mesh, in_specs=P(None),
                           out_specs=P(None),
                           check_rep=False)(jnp.ones((4,), jnp.float32))
    return float(np.asarray(out)[0])


def _sanitizer_env(tdir):
    return {"RLA_TPU_SPMD_SANITIZER": "1",
            "RLA_TPU_TELEMETRY_DIR": str(tdir)}


def test_fanout_divergence_caught_typed(tmp_path):
    """Two workers trace rank-dependent collective sequences; the
    driver's post-run diff raises the typed CollectiveMismatch naming
    the first divergent call."""
    from ray_lightning_accelerators_tpu.runtime.actors import ActorPool
    env = _sanitizer_env(tmp_path)
    with ActorPool(2, env_per_worker=[dict(env), dict(env)]) as pool:
        futs = pool.execute_per_worker(_trace_rank_collectives,
                                       [(0, 1), (1, 1)])
        assert [f.result(timeout=300) for f in futs] == [1.0, 1.0]
        snaps = S.gather_sequences(str(tmp_path))
        assert set(snaps) == {"rank0", "rank1"}
        with pytest.raises(S.CollectiveMismatch) as ei:
            S.check_collective_sequences(str(tmp_path))
    diag = ei.value.diagnosis
    assert diag["first_divergence"] == 1
    assert diag["per_rank"]["rank0"] is None      # rank0 never made call 1
    assert diag["per_rank"]["rank1"]["op"] == "pmean"
    assert "test_spmd_safety.py" in diag["per_rank"]["rank1"]["site"]


def _warm_jax():
    import jax
    return len(jax.devices())


def _divergent_then_hang(rank):
    _trace_rank_collectives(rank, 1)
    if rank == 1:
        time.sleep(3600)   # the deadlock the divergence would cause
    return rank


@pytest.mark.chaos
def test_elastic_wedge_decodes_to_collective_mismatch(tmp_path,
                                                      monkeypatch):
    """THE acceptance loop: a chaos-style run where the rank-divergent
    rank hangs (as a real mismatched collective would) is reaped as a
    wedge — and the ElasticRunner surfaces the typed CollectiveMismatch
    postmortem TERMINALLY instead of burning retries on a deterministic
    divergence."""
    from ray_lightning_accelerators_tpu.runtime.actors import ActorPool
    from ray_lightning_accelerators_tpu.runtime.elastic import ElasticRunner
    env = _sanitizer_env(tmp_path)
    env["RLA_TPU_WORKER_HEARTBEAT_S"] = "0.05"
    # the driver-side checker reads the same knobs from the process env
    for k, v in _sanitizer_env(tmp_path).items():
        monkeypatch.setenv(k, v)
    pool = ActorPool(2, env_per_worker=[dict(env), dict(env)])
    try:
        for f in pool.execute_all(_warm_jax):   # jax import off the clock
            f.result(timeout=300)
        runner = ElasticRunner(pool, max_failures=2,
                               dispatch_deadline_s=6.0,
                               watchdog_poll_s=0.1)
        with pytest.raises(S.CollectiveMismatch) as ei:
            runner.run(_divergent_then_hang,
                       args_per_worker=lambda a: [(r,) for r in range(2)])
        diag = ei.value.diagnosis
        assert diag["first_divergence"] == 1
        assert diag["per_rank"]["rank1"]["op"] == "pmean"
        # terminal, not retried: the wedge burned ONE attempt
        assert runner.attempts_used == 1
        assert isinstance(ei.value.__cause__, BaseException)
    finally:
        pool.shutdown()


# --------------------------------------------------------------------- #
# trainer seam: fan-out failure decodes to the typed mismatch          #
# --------------------------------------------------------------------- #
class _SeqWorld:
    """Fake world: 'workers' write their (divergent) collective spills
    DURING run() — after the seam's run-entry spill reset, exactly like
    real tracing workers — then wedge or complete."""

    last_stall = ()

    def __init__(self, tdir, wedge=False):
        self.tdir = tdir
        self.wedge = wedge
        self.shut = False

    def run(self, body, queue=None, deadline_s=None):
        _write_seq(self.tdir, 0, ["psum", "all_gather"])
        _write_seq(self.tdir, 1, ["psum", "pmean"])
        if self.wedge:
            from ray_lightning_accelerators_tpu.runtime.watchdog import (
                WorkerWedged)
            raise WorkerWedged.for_rank(1, {"detail": "stopped making "
                                                      "progress"})
        return [{"ok": True}, {"ok": True}]

    def shutdown(self):
        self.shut = True


def _seam_trainer(tmp_path):
    from ray_lightning_accelerators_tpu import Trainer
    return Trainer(max_steps=1, precision="f32", seed=0,
                   enable_checkpointing=False,
                   default_root_dir=str(tmp_path))


def test_trainer_wedge_decodes_to_mismatch(tmp_path, monkeypatch):
    tdir = tmp_path / "telemetry"
    # a STALE spill from a previous run: the run-entry reset must clear
    # it so only what "this run's workers" write below is diffed
    _write_seq(tdir, 7, ["all_to_all"])
    monkeypatch.setenv(S.SANITIZER_ENV, "1")
    monkeypatch.setenv("RLA_TPU_TELEMETRY_DIR", str(tdir))
    trainer = _seam_trainer(tmp_path)
    module = types.SimpleNamespace()
    from ray_lightning_accelerators_tpu.runtime.watchdog import WorkerWedged
    with pytest.raises(S.CollectiveMismatch) as ei:
        trainer._run_in_world(_SeqWorld(tdir, wedge=True), module,
                              None, None)
    # chained off the wedge: both the decoded cause and the raw reap
    # survive in one postmortem
    assert isinstance(ei.value.__cause__, WorkerWedged)
    diag = ei.value.diagnosis
    assert diag["per_rank"]["rank1"]["op"] == "pmean"
    assert "rank7" not in diag["per_rank"]  # stale spill was cleared
    # the failure report carries the DECODED error type
    rep = json.load(open(os.path.join(str(tmp_path), "run_report.json")))
    assert rep["error"]["type"] == "CollectiveMismatch"


def test_trainer_completed_run_still_checked(tmp_path, monkeypatch):
    tdir = tmp_path / "telemetry"
    monkeypatch.setenv(S.SANITIZER_ENV, "1")
    monkeypatch.setenv("RLA_TPU_TELEMETRY_DIR", str(tdir))
    trainer = _seam_trainer(tmp_path)
    world = _SeqWorld(tdir)
    with pytest.raises(S.CollectiveMismatch):
        trainer._run_in_world(world, types.SimpleNamespace(), None, None)
    # unlike the failure path, the world was still ALIVE: the seam must
    # end it, not leak it
    assert world.shut
    # knob off: the same divergent spills are ignored (opt-in contract)
    monkeypatch.delenv(S.SANITIZER_ENV)
    trainer2 = _seam_trainer(tmp_path)
    out = trainer2._run_in_world(_SeqWorld(tdir), types.SimpleNamespace(),
                                 None, None)
    assert out == [{"ok": True}, {"ok": True}]


def test_trainer_crash_failures_are_not_decoded(tmp_path, monkeypatch):
    """A CRASH-shaped failure legitimately truncates a rank's spill
    mid-trace: it must stay the original (retryable) error, never read
    as a deterministic collective divergence."""
    tdir = tmp_path / "telemetry"
    monkeypatch.setenv(S.SANITIZER_ENV, "1")
    monkeypatch.setenv("RLA_TPU_TELEMETRY_DIR", str(tdir))

    class _CrashWorld(_SeqWorld):
        def run(self, body, queue=None, deadline_s=None):
            _write_seq(self.tdir, 0, ["psum", "all_gather"])
            _write_seq(self.tdir, 1, ["psum"])   # truncated mid-trace
            raise RuntimeError("worker 1 died")

    trainer = _seam_trainer(tmp_path)
    with pytest.raises(RuntimeError, match="worker 1 died"):
        trainer._run_in_world(_CrashWorld(tdir), types.SimpleNamespace(),
                              None, None)


# --------------------------------------------------------------------- #
# sharding audit                                                        #
# --------------------------------------------------------------------- #
def test_sharding_audit_inventory_covers_parallel_modules(tmp_path):
    out = tmp_path / "inv.json"
    script = os.path.join(SCRIPTS, "sharding_audit.py")
    proc = subprocess.run(
        [sys.executable, script, "--out", str(out), "--quiet"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert record["kind"] == "sharding_audit"
    assert "value" not in record  # bench-parser contract: value-less
    assert record["uninventoried"] == 0
    # --skip-drift (the format.sh mode: graftlint already gated) skips
    # the lint pass and says so in the record
    proc = subprocess.run(
        [sys.executable, script, "--no-write", "--quiet", "--skip-drift"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0
    rec2 = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec2["uninventoried"] is None
    inv = json.load(open(str(out)))
    assert inv["schema"] == 1
    for mod in ("parallel/collectives.py", "parallel/sharding.py",
                "parallel/ulysses.py", "parallel/ring_attention.py",
                "parallel/pipeline.py"):
        assert mod in inv["modules"], mod
        assert not inv["modules"][mod].get("missing")
    assert inv["totals"]["partition_spec_literals"] > 10
    assert set(inv["axis_names"]) >= {"data", "fsdp", "pipeline",
                                      "sequence", "tensor", "expert"}
    assert inv["uninventoried"] == []
    # committed artifact stays in sync with the tree (format.sh rewrites
    # it; a stale checkout diff shows up in review)
    committed = os.path.join(os.path.dirname(PKG_DIR),
                             "SHARDING_INVENTORY.json")
    assert os.path.exists(committed)
    assert json.load(open(committed))["totals"] == inv["totals"]


def test_sharding_audit_drift_exits_nonzero(monkeypatch):
    """An uninventoried PartitionSpec literal fails the audit (the
    format.sh gate): exercised through main() with the lint findings
    injected, so no package mutation is needed."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_audit_for_test", os.path.join(SCRIPTS, "sharding_audit.py"))
    audit = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(audit)
    monkeypatch.setattr(audit, "drift_findings", lambda lint: [
        {"rule": "sharding-inventory", "path": "serve/engine.py",
         "line": 10, "col": 0, "suppressed": False,
         "message": "PartitionSpec literal in uninventoried module"}])
    assert audit.main(["--no-write", "--quiet"]) == 1
    monkeypatch.setattr(audit, "drift_findings", lambda lint: [])
    assert audit.main(["--no-write", "--quiet"]) == 0
