"""Tune subsystem tests — behavioral port of the reference's Tune suite
(reference: ray_lightning/tests/test_tune.py — iteration counts :33-58,
checkpoint existence :61-88) plus search-space and trampoline unit coverage."""

import os

import numpy as np
import pytest

from ray_lightning_accelerators_tpu import (HorovodRayAccelerator,
                                            RayTPUAccelerator,
                                            TuneReportCallback,
                                            TuneReportCheckpointCallback,
                                            tune)
from ray_lightning_accelerators_tpu.runtime import session as session_lib
from ray_lightning_accelerators_tpu.runtime.queue import (TrampolineQueue,
                                                          drain_queue)
from ray_lightning_accelerators_tpu.tune.search import generate_trial_configs

from .utils import BoringModel, boring_loaders, get_trainer


def train_func(dir, accelerator_factory, callbacks=None):
    def _inner_train(config):
        model = BoringModel()
        trainer = get_trainer(dir, accelerator=accelerator_factory(),
                              callbacks=list(callbacks or []), **config)
        train, val = boring_loaders()
        trainer.fit(model, train, val)

    return _inner_train


def tune_test(dir, accelerator_factory):
    callbacks = [TuneReportCallback(on="validation_end")]
    analysis = tune.run(
        train_func(dir, accelerator_factory, callbacks=callbacks),
        config={"max_epochs": tune.choice([1, 2, 3])},
        num_samples=2, local_dir=str(dir))
    df = analysis.results_df
    assert all(df["training_iteration"] == df["config.max_epochs"])


def test_tune_iteration_ddp(tmpdir):
    tune_test(tmpdir, lambda: RayTPUAccelerator(2))


def test_tune_iteration_horovod(tmpdir):
    tune_test(tmpdir, lambda: HorovodRayAccelerator(num_hosts=1, num_slots=2))


def checkpoint_test(dir, accelerator_factory):
    callbacks = [TuneReportCheckpointCallback(on="validation_end")]
    analysis = tune.run(
        train_func(dir, accelerator_factory, callbacks=callbacks),
        config={"max_epochs": 2},
        num_samples=1, local_dir=str(dir),
        metric="val_loss", mode="min")
    assert analysis.best_checkpoint and os.path.exists(analysis.best_checkpoint)


def test_checkpoint_ddp(tmpdir):
    checkpoint_test(tmpdir, lambda: RayTPUAccelerator(2))


def test_checkpoint_horovod(tmpdir):
    checkpoint_test(tmpdir, lambda: HorovodRayAccelerator(1, 2))


def test_best_config_metric_selection(tmpdir):
    def trainable(config):
        tune.report(score=config["x"] ** 2)

    analysis = tune.run(trainable, config={"x": tune.grid_search([3, -1, 2])},
                        metric="score", mode="min", local_dir=str(tmpdir))
    assert analysis.best_config["x"] == -1
    assert analysis.best_result["score"] == 1


def test_metric_mapping(tmpdir):
    """dict-form metrics map tune-name -> trainer-name
    (reference: tune.py:77-95 + README.md:73-75)."""
    callbacks = [TuneReportCallback({"loss": "val_loss"},
                                    on="validation_end")]
    analysis = tune.run(
        train_func(tmpdir, lambda: RayTPUAccelerator(1), callbacks=callbacks),
        config={"max_epochs": 1}, local_dir=str(tmpdir),
        metric="loss", mode="min")
    assert analysis.best_result["loss"] == 1.0


def test_grid_and_samples_expansion():
    cfgs = generate_trial_configs(
        {"a": tune.grid_search([1, 2]), "b": tune.choice([7]), "c": 5},
        num_samples=3)
    assert len(cfgs) == 6
    assert all(c["b"] == 7 and c["c"] == 5 for c in cfgs)
    assert sorted(c["a"] for c in cfgs) == [1, 1, 1, 2, 2, 2]


def test_loguniform_bounds():
    cfgs = generate_trial_configs({"lr": tune.loguniform(1e-4, 1e-1)}, 50)
    vals = [c["lr"] for c in cfgs]
    assert all(1e-4 <= v <= 1e-1 for v in vals)
    assert np.std(np.log(vals)) > 0.5  # actually spread in log space


def test_failed_trial_raises(tmpdir):
    def bad(config):
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        tune.run(bad, config={}, local_dir=str(tmpdir))
    analysis = tune.run(bad, config={}, local_dir=str(tmpdir),
                        raise_on_failed_trial=False)
    assert analysis.trials[0].status == "ERROR"


def test_queue_trampoline_order():
    q = TrampolineQueue()
    out = []
    q.put((0, lambda: out.append(1)))
    q.put((1, lambda: out.append(2)))
    assert drain_queue(q) == 2 and out == [1, 2]


def test_session_lifecycle():
    assert not session_lib.session_exists()
    session_lib.init_session(rank=3)
    assert session_lib.get_actor_rank() == 3
    with pytest.raises(ValueError):
        session_lib.init_session(rank=0)
    with pytest.raises(ValueError):  # no queue attached
        session_lib.put_queue(lambda: None)
    session_lib.shutdown_session()
    assert not session_lib.session_exists()
