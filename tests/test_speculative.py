"""Speculative decoding: exactness vs target-only greedy, acceptance
statistics, chunk-scorer correctness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu.models.speculative import (
    speculative_generate)
from ray_lightning_accelerators_tpu.models.transformer import (
    GPT, TransformerConfig)


def _model(layers, seed, heads=2, kv=None):
    cfg = TransformerConfig(vocab_size=61, d_model=64, n_heads=heads,
                            d_ff=128, n_layers=layers, max_seq_len=64,
                            n_kv_heads=kv)
    m = GPT(cfg)
    return m, m.init_params(jax.random.PRNGKey(seed))


def test_chunk_scorer_matches_stepwise():
    """_decode_chunk over n tokens == n sequential _decode_token calls."""
    model, params = _model(2, 0)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 61, size=(1, 6)), jnp.int32)
    total = 16
    _, cache_a = model._prefill(params, prompt, total)
    _, cache_b = model._prefill(params, prompt, total)
    toks = jnp.asarray([[7, 11, 13]], jnp.int32)
    chunk_logits, _ = model._decode_chunk(params, cache_a, toks, 5)
    step_logits = []
    for i in range(3):
        lg, cache_b = model._decode_token(params, cache_b, toks[:, i],
                                          jnp.asarray(5 + i))
        step_logits.append(lg)
    np.testing.assert_allclose(np.asarray(chunk_logits[0]),
                               np.asarray(jnp.stack(step_logits, 1)[0]),
                               atol=2e-4, rtol=2e-4)


def _assert_greedy_equivalent(target, tp, out, ref, tie_tol=1e-3):
    """Outputs must match token-for-token, except that a divergence is
    allowed at a genuine logit near-tie (the chunk and step scorers use
    different einsum reduction orders, so fp ties may break differently —
    after a tie the contexts legitimately differ)."""
    out, ref = np.asarray(out), np.asarray(ref)
    if np.array_equal(out, ref):
        return
    first = int(np.argmax(out[0] != ref[0]))
    # re-score the shared prefix with the target; the two tokens chosen
    # at the divergence must be (near-)tied under the target
    logits = np.asarray(target.forward(tp, jnp.asarray(ref[:, :first])))
    last = logits[0, -1]
    gap = abs(float(last[out[0, first]]) - float(last[ref[0, first]]))
    assert gap < tie_tol, (
        f"divergence at {first} is not a logit tie (gap={gap})")


@pytest.mark.parametrize("draft_layers,k", [(1, 4), (2, 3)])
def test_speculative_exact_vs_greedy(draft_layers, k):
    target, tp = _model(3, 0)
    draft, dp = _model(draft_layers, 1)
    prompt = jnp.asarray(
        np.random.default_rng(2).integers(0, 61, size=(1, 8)), jnp.int32)
    ref = target.generate(tp, prompt, max_new_tokens=14)
    out, stats = speculative_generate(target, tp, draft, dp, prompt,
                                      max_new_tokens=14, k=k)
    assert out.shape == ref.shape
    _assert_greedy_equivalent(target, tp, out, ref)
    assert stats["rounds"] >= 1
    assert 0.0 <= stats["accept_rate"] <= 1.0


def test_speculative_self_draft_accepts_everything():
    """Draft == target: every proposal matches, so rounds ~= tokens/k."""
    target, tp = _model(2, 0)
    prompt = jnp.ones((1, 4), jnp.int32)
    out, stats = speculative_generate(target, tp, target, tp, prompt,
                                      max_new_tokens=12, k=4)
    ref = target.generate(tp, prompt, max_new_tokens=12)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert stats["accept_rate"] > 0.7
    assert stats["rounds"] <= 4


def test_speculative_with_gqa_target():
    target, tp = _model(2, 0, heads=4, kv=2)
    draft, dp = _model(1, 3)
    prompt = jnp.ones((1, 5), jnp.int32)
    ref = target.generate(tp, prompt, max_new_tokens=10)
    out, _ = speculative_generate(target, tp, draft, dp, prompt,
                                  max_new_tokens=10, k=4)
    _assert_greedy_equivalent(target, tp, out, ref)


def test_speculative_rejects_batch_and_window():
    target, tp = _model(1, 0)
    draft, dp = _model(1, 1)
    with pytest.raises(ValueError, match="single-stream"):
        speculative_generate(target, tp, draft, dp,
                             jnp.ones((2, 4), jnp.int32), 4)
    # the check is explicit about SHAPE, not just batch: a 1-D prompt
    # must not slip through as "batch == seq_len" confusion
    with pytest.raises(ValueError, match=r"\[1, prompt_len\]"):
        speculative_generate(target, tp, draft, dp,
                             jnp.ones((4,), jnp.int32), 4)
    swcfg = TransformerConfig(vocab_size=61, d_model=64, n_heads=2,
                              d_ff=128, n_layers=1, max_seq_len=64,
                              sliding_window=8)
    sw = GPT(swcfg)
    with pytest.raises(NotImplementedError, match="sliding_window"):
        speculative_generate(sw, sw.init_params(jax.random.PRNGKey(0)),
                             draft, dp, jnp.ones((1, 4), jnp.int32), 4)
