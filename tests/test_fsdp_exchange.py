"""Compressed FSDP (ZeRO-2/3, parallel/collectives.py): quantized
reduce-scatter into the shard owner, shard-local (1/N) error-feedback
residuals and optimizer state, bf16 param all-gather — numerics, sharding
layouts, checkpoint resize, typed refusals and the zero-retrace contract,
all on the suite's 8-device CPU mesh."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_lightning_accelerators_tpu import (ArrayDataset, DataLoader,
                                            RayTPUAccelerator, Trainer)
from ray_lightning_accelerators_tpu.parallel import collectives as C
from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib
from ray_lightning_accelerators_tpu.parallel import sharding as sharding_lib

pytestmark = pytest.mark.fsdp


def _fsdp_mesh(nf=8, nd=1):
    return mesh_lib.build_mesh(mesh_lib.MeshConfig(data=nd, fsdp=nf))


def _put_stacked(mesh, tree):
    lead = NamedSharding(mesh, P(mesh_lib.BATCH_AXES))
    return jax.tree.map(lambda a: jax.device_put(jnp.asarray(a), lead), tree)


def _exchange_once(mesh, cfg, params, grads):
    param_sh = sharding_lib.infer_fsdp_shardings(params, mesh)
    res = _put_stacked(mesh, C.fsdp_residual_zeros(params, param_sh, cfg))
    ex = jax.jit(C.build_fsdp_exchange(mesh, cfg, param_sh))
    out, new_res = ex(_put_stacked(mesh, grads), res)
    return param_sh, out, new_res


# --------------------------------------------------------------------- #
# Exchange numerics + shard-local layouts                                #
# --------------------------------------------------------------------- #
def test_int8_fsdp_exchange_error_bound_and_shard_local_residuals():
    """Acceptance: one int8 reduce-scatter of random grads lands within
    the SAME 1e-2 relative bound the replicated exchange meets, the
    reduced grads come back in the param (owner) layout, and the
    error-feedback residual is genuinely 1/N per device."""
    mesh = _fsdp_mesh()
    n = C.dp_size(mesh)
    cfg = C.ExchangeConfig(mode="int8")
    rng = np.random.default_rng(0)
    params = {"w": np.zeros((1024, 64), np.float32),   # fsdp-sharded
              "u": np.zeros((1001, 63), np.float32),   # indivisible dims
              "b": np.zeros((7,), np.float32)}         # fp32 psum path
    grads = {k: rng.normal(size=(n,) + v.shape).astype(np.float32)
             for k, v in params.items()}
    param_sh, out, new_res = _exchange_once(mesh, cfg, params, grads)
    assert C.fsdp_shard_dim(param_sh["w"]) == 0
    assert C.fsdp_shard_dim(param_sh["u"]) is None  # warn-and-replicate
    true = jax.tree.map(lambda a: a.mean(0), grads)
    for key in ("w", "u"):
        t = true[key]
        rel = np.linalg.norm(np.asarray(out[key]) - t) / np.linalg.norm(t)
        assert rel < 1e-2, (key, rel)
    # sub-threshold leaf rides the fp32 psum: exact (up to psum rounding)
    np.testing.assert_allclose(np.asarray(out["b"]), true["b"], rtol=1e-6)
    # the reduce-scattered grad lands in the OWNER layout (1/N shards);
    # the replicated-leaf outputs stay replicated
    assert not out["w"].sharding.is_fully_replicated
    assert out["w"].addressable_shards[0].data.shape == (1024 // n, 64)
    assert out["u"].sharding.is_fully_replicated
    # residuals: shard-local [n, chunk] for the scattered leaf (1/N per
    # device — the memory claim), full [n, size] only for the leaf that
    # stayed on the two-phase allreduce, [n, 1] placeholder for fp32
    chunk = (1024 * 64) // n
    assert new_res["w"].shape == (n, chunk)
    assert new_res["w"].addressable_shards[0].data.shape == (1, chunk)
    assert float(jnp.linalg.norm(new_res["w"])) > 0.0
    assert new_res["u"].shape == (n, 1001 * 63)
    assert new_res["b"].shape == (n, 1)
    assert float(jnp.abs(new_res["b"]).max()) == 0.0


def test_bf16_fsdp_exchange_error_bound():
    mesh = _fsdp_mesh()
    n = C.dp_size(mesh)
    cfg = C.ExchangeConfig(mode="bf16")
    rng = np.random.default_rng(2)
    params = {"w": np.zeros((512, 64), np.float32)}
    grads = {"w": rng.normal(size=(n, 512, 64)).astype(np.float32)}
    _, out, new_res = _exchange_once(mesh, cfg, params, grads)
    true = grads["w"].mean(0)
    rel = np.linalg.norm(np.asarray(out["w"]) - true) / np.linalg.norm(true)
    assert rel < 5e-3
    # bf16 chunks need no block padding: residual is exactly size/n
    assert new_res["w"].shape == (n, (512 * 64) // n)


def test_fsdp_exchange_on_mixed_data_fsdp_mesh():
    """data=2 x fsdp=4: the reduce-scatter runs over fsdp, the fp32
    psum of the 1/nf reduced shard folds in the cross-data replicas —
    the mean must still cover all 8 replicas."""
    mesh = _fsdp_mesh(nf=4, nd=2)
    n = C.dp_size(mesh)
    cfg = C.ExchangeConfig(mode="int8")
    rng = np.random.default_rng(3)
    params = {"w": np.zeros((512, 128), np.float32)}
    grads = {"w": rng.normal(size=(n, 512, 128)).astype(np.float32)}
    _, out, _ = _exchange_once(mesh, cfg, params, grads)
    true = grads["w"].mean(0)
    rel = np.linalg.norm(np.asarray(out["w"]) - true) / np.linalg.norm(true)
    assert rel < 1e-2
    assert out["w"].addressable_shards[0].data.shape == (512 // 4, 128)


def test_param_gather_bf16_compute_view():
    """build_param_gather returns the replicated-for-compute view: bf16
    is what crossed the wire (values == bf16 roundtrip), dtype and
    non-float leaves are preserved."""
    mesh = _fsdp_mesh()
    rng = np.random.default_rng(4)
    params = {"w": rng.normal(size=(1024, 64)).astype(np.float32),
              "step": np.arange(8 * 1024, dtype=np.int32).reshape(1024, 8)}
    param_sh = {"w": NamedSharding(mesh, P(mesh_lib.FSDP_AXIS, None)),
                "step": NamedSharding(mesh, P(mesh_lib.FSDP_AXIS, None))}
    pd = jax.tree.map(lambda a, s: jax.device_put(jnp.asarray(a), s),
                      params, param_sh)
    out = jax.jit(C.build_param_gather(mesh, param_sh))(pd)
    assert out["w"].sharding.is_fully_replicated
    assert out["w"].dtype == jnp.float32
    np.testing.assert_array_equal(
        np.asarray(out["w"]),
        np.asarray(params["w"].astype(jnp.bfloat16).astype(np.float32)))
    np.testing.assert_array_equal(np.asarray(out["step"]), params["step"])


def test_wire_report_reduce_scatter_regime():
    mesh = _fsdp_mesh()
    params = {"w": np.zeros((1024, 1024), np.float32),
              "b": np.zeros((64,), np.float32)}
    psh = sharding_lib.infer_fsdp_shardings(params, mesh)
    cfg = C.ExchangeConfig(mode="int8")
    rep = C.wire_bytes_per_step(params, 8, cfg, param_shardings=psh)
    assert rep["regime"] == "reduce_scatter_all_gather"
    assert rep["fsdp"] == 8 and rep["reduce_scattered_leaves"] == 1
    # int8 RS + bf16 AG vs fp32 ring allreduce: ~2.65x at block 256
    assert 2.4 <= rep["compression_ratio"] <= 2.66
    assert (rep["grad_reduce_scatter_bytes_per_step"]
            + rep["param_allgather_bytes_per_step"]
            <= rep["exchange_bytes_per_step"])
    # the replicated regime is untouched (allreduce accounting)
    rep_dp = C.wire_bytes_per_step(params, 8, cfg)
    assert rep_dp["regime"] == "allreduce"
    assert rep_dp["compressed_ratio"] >= 3.5


def test_typed_refusal_for_model_parallel_specs():
    assert C.fsdp_shard_dim(P(None, None)) is None
    assert C.fsdp_shard_dim(P("fsdp", None)) == 0
    assert C.fsdp_shard_dim(P(None, ("fsdp",))) == 1
    with pytest.raises(C.TensorShardedParamsError, match="model-parallel"):
        C.fsdp_shard_dim(P("tensor", None))
    with pytest.raises(C.TensorShardedParamsError):
        C.fsdp_shard_dim(P(("data", "fsdp"), None))  # fsdp mixed in a dim
    with pytest.raises(C.TensorShardedParamsError):
        C.fsdp_shard_dim(P("fsdp", "fsdp"))  # two sharded dims


def test_fsdp_fallback_emits_telemetry_event():
    """accelerators/base.py fallback path: a large leaf with no
    fsdp-divisible dim warn-and-replicates AND leaves evidence — a
    telemetry event (kind fsdp_fallback) and last_fsdp_fallbacks for
    the trainer's profiler counter."""
    from ray_lightning_accelerators_tpu.telemetry import recorder

    mesh = _fsdp_mesh()
    acc = RayTPUAccelerator(num_workers=8, use_fsdp=True)
    params = {"odd": np.zeros((1001, 63), np.float32),
              "even": np.zeros((1024, 64), np.float32)}
    rec = recorder.get_recorder()
    before = len([e for e in rec.events() if e["kind"] == "fsdp_fallback"])
    sh = acc.param_shardings(mesh, params)
    events = [e for e in rec.events() if e["kind"] == "fsdp_fallback"]
    assert len(events) == before + 1
    assert "odd" in events[-1]["data"]["param"]
    assert acc.last_fsdp_fallbacks and \
        acc.last_fsdp_fallbacks[0]["shape"] == [1001, 63]
    assert sh["even"].spec == P(mesh_lib.FSDP_AXIS, None)
    # the probe call (trainer residual-init path) stays quiet
    acc.param_shardings(mesh, params, report_fallbacks=False)
    events2 = [e for e in rec.events() if e["kind"] == "fsdp_fallback"]
    assert len(events2) == len(events)


# --------------------------------------------------------------------- #
# Through the Trainer                                                    #
# --------------------------------------------------------------------- #
def _mnist_loader(n=512, bs=128):
    from ray_lightning_accelerators_tpu.models.mnist import synthetic_mnist
    x, y = synthetic_mnist(n, seed=0)
    return DataLoader(ArrayDataset(x, y), batch_size=bs, shuffle=True)


def _mnist_model():
    from ray_lightning_accelerators_tpu.models.mnist import MNISTClassifier
    return MNISTClassifier({"layer_1": 64, "layer_2": 64, "lr": 1e-3,
                            "batch_size": 128})


def _fit_fsdp(tmpdir, num_workers=8, max_epochs=1, **kw):
    trainer = Trainer(max_epochs=max_epochs, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmpdir),
                      accelerator=RayTPUAccelerator(num_workers=num_workers,
                                                    use_fsdp=True),
                      grad_compression="int8", **kw)
    trainer.fit(_mnist_model(), _mnist_loader())
    return trainer


def test_fsdp_trainer_state_is_shard_local_and_resumes_on_fewer_shards(
        tmpdir):
    """The flag-to-wire acceptance path: Trainer(grad_compression='int8')
    with use_fsdp=True trains end-to-end on the 8-dev mesh with
    1/N-sized param/opt/residual/accum buffers (asserted via sharding
    specs), round-trips a sharded checkpoint, and that checkpoint
    restores onto an fsdp=4 mesh through the template-reconciliation
    chain (residual/accum reset, params/opt redistributed)."""
    trainer = _fit_fsdp(tmpdir.join("f8"), accumulate_grad_batches=2,
                        checkpoint_format="sharded")
    st = trainer._state
    n = C.dp_size(trainer._mesh)
    w = st.params["dense_0"]["kernel"]          # (784, 64), fsdp dim 0
    assert w.sharding.spec == P(mesh_lib.FSDP_AXIS, None)
    assert w.addressable_shards[0].data.shape == (784 // n, 64)
    # ZeRO-2/3: Adam moments inherit the 1/N layout
    sharded_moments = [
        leaf for leaf in jax.tree.leaves(st.opt_state)
        if hasattr(leaf, "sharding")
        and not leaf.sharding.is_fully_replicated]
    assert len(sharded_moments) >= 4  # mu+nu for both hidden kernels
    assert sharded_moments[0].addressable_shards[0].data.shape[0] \
        == sharded_moments[0].shape[0] // n
    # shard-local residual: padded chunk of 784*64/8, held [1, chunk]
    res = st.residual["dense_0"]["kernel"]
    chunk = res.shape[1]
    assert chunk < (784 * 64) // n + 256 and chunk >= (784 * 64) // n
    assert res.addressable_shards[0].data.shape == (1, chunk)
    # post-exchange accumulator: param-shaped, so 1/N-sharded too
    acc = st.grad_accum["dense_0"]["kernel"]
    assert acc.shape == (784, 64)
    assert acc.addressable_shards[0].data.shape == (784 // n, 64)
    # the analytic wire record reports the RS/AG regime
    assert trainer.comms_per_step["regime"] == "reduce_scatter_all_gather"
    assert trainer.comms_per_step["param_allgather_bytes_per_step"] > 0

    # sharded checkpoint round-trip (same world)
    from ray_lightning_accelerators_tpu.utils import \
        sharded_checkpoint as sharded_lib
    path = os.path.join(str(tmpdir), "f8.ckpt")
    trainer.save_checkpoint(path)
    restored = sharded_lib.restore_sharded(path, template=st)
    for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # resume onto HALF the shards (fsdp=8 -> 4): params/opt arrive via
    # global shapes, residual/accum rebuild from the recorded saved-world
    # shapes and reset to zero
    trainer2 = Trainer(max_epochs=2, precision="f32", seed=0,
                       enable_checkpointing=False,
                       default_root_dir=str(tmpdir.join("f4")),
                       checkpoint_format="sharded",
                       accelerator=RayTPUAccelerator(num_workers=4,
                                                     use_fsdp=True),
                       grad_compression="int8", accumulate_grad_batches=2)
    trainer2.fit(_mnist_model(), _mnist_loader(), ckpt_path=path)
    assert trainer2.global_step > trainer.global_step
    w2 = trainer2._state.params["dense_0"]["kernel"]
    assert w2.addressable_shards[0].data.shape == (784 // 4, 64)
    res2 = trainer2._state.residual["dense_0"]["kernel"]
    assert res2.shape[0] == 4
    assert float(jnp.abs(trainer2._state.grad_accum["dense_0"]
                         ["kernel"]).max()) >= 0.0  # rebuilt, usable


def test_fsdp_trainer_zero_retraces_after_warmup(tmpdir, compile_guard):
    """The donated fsdp train step (gather + local grads + reduce-scatter
    + shard-local update) compiles once: ZERO new backend compiles over
    steps 2..12 (the compile_guard contract the probe also enforces)."""
    from ray_lightning_accelerators_tpu import Callback
    from ray_lightning_accelerators_tpu.analysis.compile_guard import (
        compile_count)

    counts = []

    class CompileCounter(Callback):
        def on_train_batch_end(self, trainer, module, metrics, batch_idx):
            counts.append(compile_count())

    trainer = Trainer(max_steps=12, max_epochs=6, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmpdir),
                      accelerator=RayTPUAccelerator(num_workers=8,
                                                    use_fsdp=True),
                      grad_compression="int8", log_every_n_steps=4,
                      callbacks=[CompileCounter()])
    trainer.fit(_mnist_model(), _mnist_loader())
    assert len(counts) == 12
    # step 1 absorbs every compile; steps 2..12 must add none
    assert counts[1:] == [counts[0]] * 11, counts


@pytest.mark.slow
def test_fsdp_int8_loss_tracks_replicated_int8_dp(tmpdir):
    """Acceptance (heavy): a 3-epoch MNIST run under compressed FSDP
    reaches a final loss within the PR 3 int8 tolerance (2%) of the
    replicated-int8 DP baseline — the bf16 compute view plus the
    shard-local-EF reduce-scatter is as faithful as the allreduce."""
    from ray_lightning_accelerators_tpu.models.mnist import synthetic_mnist
    x, y = synthetic_mnist(2048, seed=0)

    def fit(root, use_fsdp):
        loader = DataLoader(ArrayDataset(x, y), batch_size=256,
                            shuffle=True)
        trainer = Trainer(max_epochs=3, precision="f32", seed=0,
                          enable_checkpointing=False,
                          default_root_dir=str(root),
                          accelerator=RayTPUAccelerator(
                              num_workers=8, use_fsdp=use_fsdp),
                          grad_compression="int8")
        from ray_lightning_accelerators_tpu.models.mnist import (
            MNISTClassifier)
        trainer.fit(MNISTClassifier({"layer_1": 64, "layer_2": 64,
                                     "lr": 1e-3, "batch_size": 256}),
                    loader)
        return trainer.callback_metrics["train_loss"]

    l_dp = fit(tmpdir.join("dp"), False)
    l_fs = fit(tmpdir.join("fsdp"), True)
    assert abs(l_fs - l_dp) / l_dp < 0.02, (l_dp, l_fs)
