"""Flagship GPT: trains under every parallelism mix on the 8-device mesh and
its params actually land sharded where the logical rules say."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu import (Accelerator, DataLoader,
                                            MeshConfig, Trainer)
from ray_lightning_accelerators_tpu.data.loader import Dataset
from ray_lightning_accelerators_tpu.models.transformer import (GPT,
                                                               TransformerConfig)

VOCAB = 128


class TokenDataset(Dataset):
    """Deterministic repeating-pattern token sequences (learnable LM task)."""

    def __init__(self, n: int = 128, seq: int = 64, seed: int = 0):
        rng = np.random.default_rng(seed)
        starts = rng.integers(0, VOCAB, size=n)
        ramp = np.arange(seq)[None, :]
        self.data = ((starts[:, None] + ramp) % VOCAB).astype(np.int32)

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


def tiny_cfg(**kw):
    base = dict(vocab_size=VOCAB, d_model=64, n_heads=4, d_ff=128,
                n_layers=2, max_seq_len=64)
    base.update(kw)
    return TransformerConfig(**base)


def _fit(tmpdir, mesh_config, batch_size=16, max_epochs=2, **cfg_kw):
    model = GPT(tiny_cfg(**cfg_kw), lr=1e-2)
    trainer = Trainer(max_epochs=max_epochs,
                      accelerator=Accelerator(mesh_config),
                      default_root_dir=str(tmpdir), precision="f32",
                      enable_checkpointing=False, seed=0)
    loader = DataLoader(TokenDataset(), batch_size=batch_size, shuffle=True)
    val = DataLoader(TokenDataset(seed=1), batch_size=batch_size)
    trainer.fit(model, loader, val)
    return trainer, model


@pytest.mark.parametrize("mesh_config", [
    MeshConfig(data=8),
    MeshConfig(data=2, fsdp=2, tensor=2),
    MeshConfig(data=1, fsdp=2, sequence=2, tensor=2),
], ids=["dp8", "dp2-fsdp2-tp2", "fsdp2-sp2-tp2"])
def test_gpt_trains_under_parallelism(tmpdir, mesh_config):
    trainer, model = _fit(tmpdir, mesh_config)
    assert trainer.callback_metrics["val_loss"] < jnp.log(VOCAB)  # < chance
    assert model.params is not None


def test_gpt_params_sharded_by_rules(tmpdir):
    trainer, model = _fit(tmpdir, MeshConfig(data=1, fsdp=2, tensor=4))
    wi = trainer._state.params["layers"]["mlp"]["wi"]  # (layers, d, ff)
    # mlp axis -> tensor(4), embed axis -> fsdp(2): 8 distinct shards
    assert len(wi.sharding.device_set) == 8
    assert not wi.sharding.is_fully_replicated
    spec = wi.sharding.spec
    assert spec[1] == "fsdp" and spec[2] == "tensor"
    # optimizer moments carry the same layout
    leaves = [l for l in jax.tree.leaves(trainer._state.opt_state)
              if hasattr(l, "shape") and l.shape == wi.shape]
    assert leaves and all(l.sharding == wi.sharding for l in leaves)


def test_gpt_learns_pattern(tmpdir):
    trainer, model = _fit(tmpdir, MeshConfig(data=4), max_epochs=8)
    assert trainer.callback_metrics["val_accuracy"] > 0.9


def test_gpt_remat_matches(tmpdir):
    t1, m1 = _fit(tmpdir, MeshConfig(data=2), max_epochs=1)
    t2, m2 = _fit(tmpdir, MeshConfig(data=2), max_epochs=1, remat=True)
    a = jax.tree.leaves(m1.params)
    b = jax.tree.leaves(m2.params)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=1e-5, rtol=1e-5)


def test_remat_policies_match_no_remat():
    import pytest as _pytest
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(2, 32)), jnp.int32)
    losses = {}
    for remat, policy in ((False, "nothing"), (True, "nothing"),
                          (True, "dots")):
        cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=2,
                                d_ff=128, n_layers=2, max_seq_len=32,
                                remat=remat, remat_policy=policy)
        m = GPT(cfg)
        p = m.init_params(jax.random.PRNGKey(0))
        loss, _ = jax.jit(lambda pp, mm=m: mm.training_step(
            pp, toks, jax.random.PRNGKey(1)))(p)
        g = jax.jit(jax.grad(lambda pp, mm=m: mm.training_step(
            pp, toks, jax.random.PRNGKey(1))[0]))(p)
        losses[(remat, policy)] = (float(loss), g)
    base_loss, base_g = losses[(False, "nothing")]
    for key, (loss, g) in losses.items():
        assert loss == _pytest.approx(base_loss, rel=1e-5), key
        for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(base_g)):
            np.testing.assert_allclose(a, b, atol=2e-5)
    with _pytest.raises(ValueError, match="remat_policy"):
        GPT(TransformerConfig(vocab_size=64, d_model=64, n_heads=2,
                              d_ff=128, n_layers=1, max_seq_len=32,
                              remat=True, remat_policy="bogus")
            ).training_step(
                GPT(TransformerConfig(vocab_size=64, d_model=64, n_heads=2,
                                      d_ff=128, n_layers=1, max_seq_len=32)
                    ).init_params(jax.random.PRNGKey(0)),
                toks, jax.random.PRNGKey(0))


def test_gpt_checkpoint_hparams_roundtrip(tmp_path):
    """load_from_checkpoint must rebuild GPT from dict-serialized config
    and tolerate a schedule lr stored as its repr."""
    from ray_lightning_accelerators_tpu import (DataLoader as DL, Trainer,
                                                ModelCheckpoint)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.utils import schedules

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, d_ff=64,
                            n_layers=1, max_seq_len=16)
    model = GPT(cfg, lr=schedules.warmup_cosine(1e-3, 10, 2))
    toks = np.random.default_rng(0).integers(
        0, 64, size=(16, 16)).astype(np.int32)
    cb = ModelCheckpoint(monitor=None)
    tr = Trainer(max_epochs=1, precision="f32", seed=0, callbacks=[cb],
                 default_root_dir=str(tmp_path))
    tr.fit(model, DL(ArrayDataset(toks), batch_size=8))
    loaded = GPT.load_from_checkpoint(cb.best_model_path)
    assert isinstance(loaded.cfg, TransformerConfig)
    assert loaded.cfg.d_model == 32
    assert not callable(loaded.lr) or loaded.lr_schedule is None
    for a, b in zip(jax.tree.leaves(loaded.params),
                    jax.tree.leaves(model.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_generate_clears_training_mesh():
    """generate() after a sequence-parallel fit must not shard decode."""
    from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, d_ff=64,
                            n_layers=1, max_seq_len=32)
    m = GPT(cfg)
    m.mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=2, sequence=4))
    params = m.init_params(jax.random.PRNGKey(0))
    out = m.generate(params, jnp.ones((1, 5), jnp.int32), max_new_tokens=4)
    assert out.shape == (1, 9)
    assert m.mesh is not None  # restored afterwards


def test_dropout_train_vs_eval():
    import pytest as _pytest
    cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=2, d_ff=128,
                            n_layers=2, max_seq_len=32, dropout=0.5)
    m = GPT(cfg)
    p = m.init_params(jax.random.PRNGKey(0))
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(2, 32)), jnp.int32)
    # train mode: different rngs give different losses
    l1, _ = m.training_step(p, toks, jax.random.PRNGKey(1))
    l2, _ = m.training_step(p, toks, jax.random.PRNGKey(2))
    l1b, _ = m.training_step(p, toks, jax.random.PRNGKey(1))
    assert float(l1) != float(l2)
    assert float(l1) == float(l1b)  # same rng reproducible
    # eval: deterministic and unaffected by dropout
    v1 = m.validation_step(p, toks)
    v2 = m.validation_step(p, toks)
    assert float(v1["val_loss"]) == float(v2["val_loss"])
    # dropout=0 config: rng makes no difference
    cfg0 = TransformerConfig(vocab_size=64, d_model=64, n_heads=2, d_ff=128,
                             n_layers=2, max_seq_len=32, dropout=0.0)
    m0 = GPT(cfg0)
    a, _ = m0.training_step(p, toks, jax.random.PRNGKey(1))
    b, _ = m0.training_step(p, toks, jax.random.PRNGKey(2))
    assert float(a) == _pytest.approx(float(b))
    # grads flow through the dropout path
    g = jax.grad(lambda pp: m.training_step(
        pp, toks, jax.random.PRNGKey(1))[0])(p)
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(g))


def test_dropout_with_remat():
    cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=2, d_ff=128,
                            n_layers=2, max_seq_len=32, dropout=0.3,
                            remat=True, remat_policy="dots")
    m = GPT(cfg)
    p = m.init_params(jax.random.PRNGKey(0))
    toks = jnp.ones((2, 32), jnp.int32)
    loss, _ = jax.jit(lambda pp: m.training_step(
        pp, toks, jax.random.PRNGKey(1)))(p)
    assert np.isfinite(float(loss))


def test_gqa_under_tensor_parallelism():
    """kv_heads smaller than the tensor axis must replicate, not crash."""
    from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib
    cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=8, d_ff=128,
                            n_layers=1, max_seq_len=32, n_kv_heads=2)
    m = GPT(cfg)
    m.mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=2, tensor=4))
    p = m.init_params(jax.random.PRNGKey(0))
    toks = jnp.ones((4, 32), jnp.int32)
    loss, _ = jax.jit(lambda pp: m.training_step(
        pp, toks, jax.random.PRNGKey(0)))(p)
    assert np.isfinite(float(loss))
