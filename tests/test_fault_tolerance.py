"""Failure detection, worker restart, elastic retries, crash resume.

The reference is fail-fast by explicit design (SURVEY.md §5.3: default
restart policy, no_restart teardown, crash = raised exception at
util.py:103; §5.4: 'No mid-run resume of a crashed job').  These tests pin
the recovery layer this framework adds on top of those fail-fast semantics.
"""

import os

import numpy as np
import pytest

from ray_lightning_accelerators_tpu import (ModelCheckpoint,
                                            RayTPUAccelerator, Trainer)
from ray_lightning_accelerators_tpu.runtime.actors import ActorPool, Worker
from ray_lightning_accelerators_tpu.runtime.elastic import ElasticRunner
from ray_lightning_accelerators_tpu.utils import checkpoint as ckpt_lib
from tests.utils import BoringModel, boring_loaders


def _crash(code=3):
    os._exit(code)


def _ok(x=1):
    return x * 2


def test_worker_crash_detected_and_future_fails():
    w = Worker(0)
    try:
        fut = w.execute(_crash)
        with pytest.raises(RuntimeError, match="died"):
            fut.result(timeout=60)
        w._proc.join(timeout=10)
        assert not w.is_alive
        assert w.exitcode == 3
    finally:
        w.kill()


def test_worker_restart_after_crash():
    w = Worker(0)
    try:
        with pytest.raises(RuntimeError):
            w.execute(_crash).result(timeout=60)
        w.restart()
        assert w.is_alive
        assert w.execute(_ok, 21).result(timeout=60) == 42
    finally:
        w.shutdown()


def test_pool_health_check_and_restart_dead():
    pool = ActorPool(2)
    try:
        assert pool.health_check() == [True, True]
        with pytest.raises(RuntimeError):
            pool.workers[1].execute(_crash).result(timeout=60)
        pool.workers[1]._proc.join(timeout=10)
        assert pool.health_check() == [True, False]
        marker = {"ran": False}

        restarted = pool.restart_dead()
        assert restarted == [1]
        assert pool.health_check() == [True, True]
        assert pool.workers[1].execute(_ok).result(timeout=60) == 2
    finally:
        pool.shutdown()


def _flaky(attempt, rank, blowup_attempts):
    # crash rank 1 during early attempts; succeed afterwards
    if rank == 1 and attempt < blowup_attempts:
        os._exit(17)
    return (attempt, rank)


def test_elastic_runner_recovers_and_returns():
    pool = ActorPool(2)
    failures = []
    try:
        runner = ElasticRunner(pool, max_failures=3,
                               on_failure=lambda a, e: failures.append(a))
        out = runner.run(
            _flaky,
            args_per_worker=lambda attempt: [(attempt, r, 2)
                                             for r in range(2)])
        assert out == [(2, 0), (2, 1)]
        assert runner.attempts_used == 3
        assert failures == [0, 1]
    finally:
        pool.shutdown()


def test_elastic_runner_gives_up():
    pool = ActorPool(2)
    try:
        runner = ElasticRunner(pool, max_failures=1)
        with pytest.raises(RuntimeError, match="after 2 attempts"):
            runner.run(_flaky,
                       args_per_worker=lambda a: [(a, r, 99)
                                                  for r in range(2)])
    finally:
        pool.shutdown()


def test_latest_checkpoint_picks_newest_verified(tmp_path):
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) is None
    a = tmp_path / "ckpts" / "epoch=0-step=8.ckpt"
    b = tmp_path / "ckpts" / "epoch=1-step=16.ckpt"
    a.parent.mkdir()
    ckpt_lib.atomic_save({"global_step": 8}, str(a))
    ckpt_lib.atomic_save({"global_step": 16}, str(b))
    os.utime(a, (1, 1))
    os.utime(b, (2, 2))
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == str(b)
    # the newest is TORN (truncated pickle): the verified walk-back must
    # fall back to the older readable one instead of handing it over
    b.write_bytes(b.read_bytes()[:4])
    os.utime(b, (2, 2))
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == str(a)
    # verify=False restores the raw newest-by-mtime pick
    assert ckpt_lib.latest_checkpoint(str(tmp_path),
                                      verify=False) == str(b)


def test_trainer_resume_last_continues_training(tmp_path):
    train, val = boring_loaders()
    root = str(tmp_path / "run")
    model = BoringModel()
    t1 = Trainer(max_epochs=2, accelerator=RayTPUAccelerator(),
                 precision="f32", default_root_dir=root, seed=0,
                 callbacks=[ModelCheckpoint(monitor=None, save_top_k=1)])
    t1.fit(model, train, val)
    steps_after_2 = t1.global_step
    w_after_2 = np.asarray(model.params["layer"]["kernel"]).copy()

    # simulated crash recovery: a fresh trainer + fresh module resume from
    # the newest checkpoint and continue to epoch 4
    model2 = BoringModel()
    t2 = Trainer(max_epochs=4, accelerator=RayTPUAccelerator(),
                 precision="f32", default_root_dir=root, seed=0,
                 callbacks=[ModelCheckpoint(monitor=None, save_top_k=1)])
    t2.fit(model2, train, val, ckpt_path="last")
    assert t2.current_epoch == 4
    assert t2.global_step == 2 * steps_after_2
    # resumed run continued FROM the saved weights, not from re-init
    assert not np.allclose(np.asarray(model2.params["layer"]["kernel"]),
                           w_after_2)


def _sleep_forever():
    import time
    time.sleep(10_000)


def test_restart_all_recovers_wedged_survivors():
    # rank 0 dies, rank 1 stays alive-but-wedged (the broken-collective
    # failure mode); restart_all must bring BOTH back to a dequeuing state
    pool = ActorPool(2)
    try:
        f0 = pool.workers[0].execute(_crash)
        f1 = pool.workers[1].execute(_sleep_forever)
        with pytest.raises(RuntimeError):
            f0.result(timeout=60)
        assert pool.workers[1].is_alive  # wedged, not dead
        pool.restart_all()
        assert pool.health_check() == [True, True]
        outs = [f.result(timeout=60) for f in pool.execute_all(_ok, 5)]
        assert outs == [10, 10]
        with pytest.raises(RuntimeError):  # old wedged future was failed
            f1.result(timeout=60)
    finally:
        pool.shutdown()


def _elastic_fit_body(coord, attempt, rank, root):
    """One attempt of a 2-process distributed fit; rank 1 hard-crashes
    mid-fit on the first attempt, after at least one checkpoint exists."""
    import os

    from ray_lightning_accelerators_tpu.runtime.bootstrap import (
        initialize_worker)
    initialize_worker(coord, 2, rank, platform="cpu",
                      cpu_devices_per_process=1)
    import numpy as np
    from ray_lightning_accelerators_tpu import (Callback, DataLoader,
                                                ModelCheckpoint, Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from tests.utils import BoringModel

    class CrashMidFit(Callback):
        def on_train_epoch_end(self, trainer, module):
            # fires before current_epoch increments: epoch index 1 ending
            # means two epochs ran and a save_last checkpoint exists
            if attempt == 0 and rank == 1 and trainer.current_epoch == 1:
                os._exit(23)

    x = np.random.default_rng(0).normal(size=(64, 32)).astype("float32")
    model = BoringModel()
    trainer = Trainer(max_epochs=3, precision="f32", seed=0,
                      default_root_dir=root,
                      callbacks=[ModelCheckpoint(monitor=None,
                                                 save_last=True),
                                 CrashMidFit()])
    trainer.fit(model, DataLoader(ArrayDataset(x), batch_size=8),
                ckpt_path="last")
    leaf = np.asarray(model.params["layer"]["kernel"], dtype=np.float64)
    return (rank, trainer.current_epoch, trainer.global_step,
            float(leaf.sum()))


@pytest.mark.slow
def test_elastic_fit_recovers_over_agents(tmp_path):
    """Round-2 weak #4: elastic recovery proven OVER THE WIRE — a worker
    on a remote HostAgent dies mid-fit, the runner restarts every rank
    through the agents, a fresh jax.distributed world forms, and training
    resumes from the last checkpoint to completion."""
    from ray_lightning_accelerators_tpu.runtime.agent import (
        HostAgent, coordinator_address_on)

    hosts = [HostAgent(port=0, bind="127.0.0.1") for _ in range(2)]
    for a in hosts:
        a.serve_in_background()
    addrs = [f"127.0.0.1:{a.port}" for a in hosts]
    root = str(tmp_path / "elastic_run")
    env = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
           "RLA_TPU_INSIDE_WORKER": "1"}
    pool = ActorPool(2, env_per_worker=[dict(env), dict(env)], agents=addrs)
    try:
        runner = ElasticRunner(pool, max_failures=2)

        def args_for(attempt):
            # each attempt needs a FRESH coordinator on agent-0's host —
            # the old one died with rank 0's restart
            coord = coordinator_address_on(addrs[0])
            return [(coord, attempt, r, root) for r in range(2)]

        results = runner.run(_elastic_fit_body, args_per_worker=args_for)
        assert runner.attempts_used == 2  # one crash, one clean attempt
        by_rank = {r[0]: r for r in results}
        for rank in (0, 1):
            _, epoch, step, wsum = by_rank[rank]
            assert epoch == 3
            assert step == 12  # 64 rows / 2 procs / batch 8 x 3 epochs
        # both ranks agree on the final weights (the re-formed world
        # really trained SPMD from the resumed checkpoint)
        assert by_rank[0][3] == pytest.approx(by_rank[1][3], rel=1e-6)
    finally:
        pool.kill()
        for a in hosts:
            a.shutdown()


def test_save_last_resume_epoch_accounting(tmp_path):
    # save_last writes from on_fit_end (after the final epoch increment);
    # the stored epoch must still equal COMPLETED epochs, not one more
    train, val = boring_loaders()
    root = str(tmp_path / "run")
    t1 = Trainer(max_epochs=3, accelerator=RayTPUAccelerator(),
                 precision="f32", default_root_dir=root, seed=0,
                 callbacks=[ModelCheckpoint(monitor=None, save_last=True)])
    t1.fit(BoringModel(), train, val)
    last = t1.checkpoint_callback.last_model_path
    assert last
    assert ckpt_lib.read_checkpoint(last)["epoch"] == 3

    t2 = Trainer(max_epochs=5, accelerator=RayTPUAccelerator(),
                 precision="f32", default_root_dir=root, seed=0,
                 enable_checkpointing=False)
    t2.fit(BoringModel(), train, val, ckpt_path=last)
    assert t2.current_epoch == 5
    assert t2.global_step == 5 * len(train)


def test_max_steps_truncated_epoch_not_counted(tmp_path):
    train, val = boring_loaders()
    t = Trainer(max_steps=len(train) + 2, accelerator=RayTPUAccelerator(),
                precision="f32", default_root_dir=str(tmp_path), seed=0,
                enable_checkpointing=False)
    t.fit(BoringModel(), train, val)
    assert t.epochs_completed == 1  # second epoch was cut short
    assert ckpt_lib.read_checkpoint is not None


def test_trainer_resume_last_empty_dir_starts_fresh(tmp_path):
    train, val = boring_loaders()
    t = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                precision="f32", enable_checkpointing=False,
                default_root_dir=str(tmp_path / "empty"), seed=0)
    t.fit(BoringModel(), train, val, ckpt_path="last")
    assert t.current_epoch == 1


def test_trainer_resume_missing_path_raises(tmp_path):
    train, val = boring_loaders()
    t = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                precision="f32", enable_checkpointing=False, seed=0)
    with pytest.raises(FileNotFoundError):
        t.fit(BoringModel(), train, val,
              ckpt_path=str(tmp_path / "nope.ckpt"))
