"""Prefix-affinity routing + disaggregated prefill/decode lanes
(serve/controller.py, serve/engine.py, serve/replicas.py): the
consistent-hash ring with per-replica prefix residency, affinity-aware
routing that health always overrides, the KV block handoff between a
prefill-lane and a decode-lane engine (block-table remap + wave-bounded
object-store copy), and the lane/prefix observability surfaces.  All
CPU; the routing units run on a fake group (no subprocesses), the
handoff end-to-end on in-process engines, the crash-during-handoff
loop on a real replica pool."""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from ray_lightning_accelerators_tpu.serve import (ControllerConfig,
                                                  DeadlineExceeded,
                                                  ReplicaController,
                                                  ServeEngine,
                                                  ServeMetrics,
                                                  SloPolicy)
from ray_lightning_accelerators_tpu.serve.batcher import chain_prefix_keys
from ray_lightning_accelerators_tpu.serve.controller import (
    LANE_DECODE, LANE_PREFILL, STATE_OPEN, STATE_SLOW,
    PrefixAffinityRing)
from ray_lightning_accelerators_tpu.serve.engine import BlockAllocator

pytestmark = pytest.mark.prefix_affinity

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CFG = dict(vocab_size=61, d_model=32, n_heads=2, d_ff=64, n_layers=2,
            max_seq_len=48)


def _model(seed=0):
    import jax

    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    model = GPT(TransformerConfig(**_CFG))
    params = model.init_params(jax.random.PRNGKey(seed))
    return model, params


def _ref(model, params, prompt, max_new):
    return np.asarray(model.generate(
        params, np.asarray(prompt)[None, :], max_new_tokens=max_new))[0]


# --------------------------------------------------------------------- #
# The shared chain-hash: one definition for both sides of routing       #
# --------------------------------------------------------------------- #
def test_chain_prefix_keys_commit_to_the_whole_prefix():
    """Key j commits to tokens [0, (j+1)*block_len): equal keys imply
    equal prefixes, a divergence poisons every later key, and partial
    trailing blocks never get a key."""
    p = np.arange(20, dtype=np.int32)
    keys = chain_prefix_keys(p, 8)
    assert len(keys) == 2                       # 20 // 8, tail dropped
    assert keys == chain_prefix_keys(p, 8)      # deterministic
    assert chain_prefix_keys(p, 8, limit=1) == keys[:1]
    assert chain_prefix_keys(p[:7], 8) == []    # shorter than a block
    # same first block, different second -> key 0 shared, key 1 not
    q = p.copy()
    q[10] += 1
    keys_q = chain_prefix_keys(q, 8)
    assert keys_q[0] == keys[0] and keys_q[1] != keys[1]
    # a first-block divergence poisons the CHAIN: key 1 differs even
    # though tokens [8, 16) are identical
    r = p.copy()
    r[0] += 1
    keys_r = chain_prefix_keys(r, 8)
    assert keys_r[0] != keys[0] and keys_r[1] != keys[1]
    # input dtype/container must not change the hash (driver routes on
    # what the engine's allocator registered, byte-for-byte)
    assert chain_prefix_keys(list(range(20)), 8) == keys
    assert chain_prefix_keys(np.arange(20, dtype=np.int64), 8) == keys


def test_affinity_ring_ownership_residency_and_forgetting():
    ring = PrefixAffinityRing(vnodes=4, residency_cap=3)
    for r in (0, 1, 2):
        ring.add_rank(r)
    # consistent ownership: deterministic, and a fresh identical ring
    # agrees (the hash is the map, not instance state)
    ring2 = PrefixAffinityRing(vnodes=4, residency_cap=3)
    for r in (0, 1, 2):
        ring2.add_rank(r)
    owners = {k: ring.owner_among(k, (0, 1, 2))
              for k in ("alpha", "beta", "gamma", "delta")}
    assert owners == {k: ring2.owner_among(k, (0, 1, 2))
                      for k in owners}
    # the successor walk: excluding a key's owner moves it to ANOTHER
    # allowed rank, never None while any rank is allowed
    k, own = next(iter(owners.items()))
    fallback = ring.owner_among(k, tuple({0, 1, 2} - {own}))
    assert fallback is not None and fallback != own
    assert ring.owner_among(k, ()) is None
    # residency scores the longest CONSECUTIVE run from key 0
    ring.note(0, ["a", "b", "c"])
    assert ring.resident_run(0, ["a", "b", "c"]) == 3
    assert ring.resident_run(0, ["a", "x", "c"]) == 1   # gap stops it
    assert ring.resident_run(0, ["x", "a"]) == 0
    assert ring.resident_run(1, ["a"]) == 0
    # bounded LRU: admitting past the cap evicts the oldest key
    ring.note(0, ["d"])
    assert ring.resident_run(0, ["a"]) == 0
    assert ring.resident_run(0, ["b"]) == 1
    # a restarted replica comes back blank but KEEPS its keyspace
    ring.clear_rank(0)
    assert ring.resident_run(0, ["b"]) == 0
    assert ring.owner_among(k, (own,)) == own
    # a removed rank's points leave the ring entirely
    ring.remove_rank(own)
    assert ring.owner_among(k, (0, 1, 2)) != own
    json.dumps(ring.state())                     # snapshot-safe
    assert ring.state()["vnodes"] == 4


# --------------------------------------------------------------------- #
# Routing units (fake group -- no subprocesses)                         #
# --------------------------------------------------------------------- #
class _FakeWorker:
    def __init__(self, rank, alive=True):
        self.rank = rank
        self.is_alive = alive


class _FakePool:
    def __init__(self, n):
        self.workers = [_FakeWorker(r) for r in range(n)]


class _FakeBatcher:
    def __init__(self):
        self.depth = 0


class _FakeGroup:
    queue_depth = 16

    def __init__(self, n=3):
        self.pool = _FakePool(n)
        self.batcher = _FakeBatcher()
        self.metrics = ServeMetrics()
        self.watchdog = None
        self.dispatched = []

    def _worker(self, rank):
        for w in self.pool.workers:
            if w.rank == rank:
                return w
        return None

    def _dispatch(self, rank, chunk, hedge_of=None):
        self.dispatched.append((rank, list(chunk), hedge_of))


def _fake_item():
    from ray_lightning_accelerators_tpu.serve.batcher import (
        ServeRequest, ServeResponse)
    req = ServeRequest(0, np.asarray([1], np.int32), 2, time.monotonic())
    return req, ServeResponse(req)


def test_route_affinity_hits_misses_and_health_override():
    g = _FakeGroup(3)
    ctrl = ReplicaController(g, ControllerConfig(affinity_vnodes=8))
    keys = chain_prefix_keys(np.arange(32, dtype=np.int32), 8)
    # cold prefix: placed on its ring owner, counted as a MISS
    owner = ctrl.affinity.owner_among(keys[0], (0, 1, 2))
    first = ctrl.route(prefix_keys=keys)
    assert first == owner
    assert g.metrics.snapshot()["prefix_route_misses"] == 1
    # warm repeat: the resident run wins, counted as a HIT -- even when
    # the owner is now the MOST loaded replica (affinity beats load)
    ctrl.on_dispatch(first, [_fake_item()])
    assert ctrl.route(prefix_keys=keys) == first
    snap = g.metrics.snapshot()
    assert snap["prefix_route_hits"] == 1
    rows = ctrl.snapshot()["replicas"]
    assert rows[str(first)]["prefix_hits"] == 1
    assert rows[str(first)]["prefix_misses"] == 1
    assert rows[str(first)]["prefix_hit_rate"] == 0.5
    # health overrides affinity: the resident replica's open circuit
    # routes the SAME prefix elsewhere, honestly counted as a miss
    ctrl._replicas[first].state = STATE_OPEN
    moved = ctrl.route(prefix_keys=keys)
    assert moved is not None and moved != first
    assert g.metrics.snapshot()["prefix_route_misses"] == 2
    # ...and the new home becomes resident: the next route is a hit
    assert ctrl.route(prefix_keys=keys) == moved
    assert g.metrics.snapshot()["prefix_route_hits"] == 2
    # snapshot carries the ring state for /statusz
    snap = ctrl.snapshot()
    assert snap["affinity"]["enabled"] is True
    assert snap["affinity"]["ranks"] == [0, 1, 2]
    assert snap["config"]["affinity"] is True
    json.dumps(snap)
    # keyless or affinity-off requests never touch the counters
    g2 = _FakeGroup(2)
    ctrl2 = ReplicaController(g2, ControllerConfig(affinity=False))
    assert ctrl2.route(prefix_keys=keys) is not None
    assert ctrl2.route() is not None
    snap2 = g2.metrics.snapshot()
    assert snap2["prefix_route_hits"] == 0
    assert snap2["prefix_route_misses"] == 0


def test_breaker_open_clears_residency_so_reroutes_stick():
    """An opened circuit clears the replica's tracked residency (a
    restarted engine is blank): after revival its old prefixes do NOT
    pull traffic back on stale-residency hits."""
    g = _FakeGroup(2)
    ctrl = ReplicaController(g, ControllerConfig())
    keys = chain_prefix_keys(np.arange(16, dtype=np.int32), 8)
    home = ctrl.route(prefix_keys=keys)
    assert ctrl.affinity.resident_run(home, keys) == len(keys)
    cid = ctrl.on_dispatch(home, [_fake_item()])
    g._worker(home).is_alive = False
    ctrl.note_infra_failure(home, cid, RuntimeError("worker died"))
    assert ctrl._replicas[home].state == STATE_OPEN
    assert ctrl.affinity.resident_run(home, keys) == 0


def test_hedge_counts_as_deliberate_prefix_miss():
    g = _FakeGroup(2)
    ctrl = ReplicaController(g, ControllerConfig(hedge_age_s=0.05))
    cid = ctrl.on_dispatch(0, [_fake_item()])
    ctrl._replicas[0].state = STATE_SLOW
    ctrl._replicas[0].chunks[cid].t_dispatch -= 1.0
    assert ctrl.maybe_hedge() == 1
    rank, _, hedge_of = g.dispatched[0]
    assert rank == 1 and hedge_of == (0, cid)
    # the hedge abandoned locality on purpose -- the target is charged
    # a miss so the tier hit-rate stays honest about re-prefill cost
    assert ctrl._replicas[1].prefix_misses == 1
    assert g.metrics.snapshot()["prefix_route_misses"] == 1
    ctrl.note_success(0, cid)


def test_lane_assignment_filter_and_spill():
    g = _FakeGroup(3)
    ctrl = ReplicaController(g, ControllerConfig(prefill_replicas=1))
    rows = ctrl.snapshot()["replicas"]
    assert rows["0"]["lane"] == LANE_PREFILL
    assert rows["1"]["lane"] == rows["2"]["lane"] == LANE_DECODE
    assert ctrl.route(lane=LANE_PREFILL) == 0
    assert ctrl.route(lane=LANE_DECODE) in (1, 2)
    # availability beats disaggregation: an empty decode lane spills
    # onto the prefill replica rather than stalling the queue
    ctrl._replicas[1].state = STATE_OPEN
    ctrl._replicas[2].state = STATE_OPEN
    assert ctrl.route(lane=LANE_DECODE) == 0
    # lane gauges: replica counts + per-lane in-flight requests
    ctrl._replicas[1].state = STATE_OPEN  # still down
    ctrl.on_dispatch(0, [_fake_item(), _fake_item()])
    gauges = ctrl.lane_gauges()
    assert gauges["lane_prefill_replicas"] == 1.0
    assert gauges["lane_decode_replicas"] == 2.0
    assert gauges["lane_prefill_inflight"] == 2.0
    assert gauges["lane_decode_inflight"] == 0.0
    # lanes disabled: everyone reports under decode, gauges stay live
    ctrl2 = ReplicaController(_FakeGroup(2), ControllerConfig())
    g2 = ctrl2.lane_gauges()
    assert g2["lane_decode_replicas"] == 2.0
    assert g2["lane_prefill_replicas"] == 0.0


def test_note_import_moves_residency_without_counting_a_route():
    """A KV import landing on a decode replica records residency there
    (the decode replica now holds the blocks) but counts NO route: the
    request's hit/miss was charged where the prefill routed."""
    g = _FakeGroup(2)
    ctrl = ReplicaController(g, ControllerConfig())
    keys = chain_prefix_keys(np.arange(24, dtype=np.int32), 8)
    before = g.metrics.snapshot()
    ctrl.note_import(1, keys)
    assert ctrl.affinity.resident_run(1, keys) == len(keys)
    after = g.metrics.snapshot()
    assert after["prefix_route_hits"] == before["prefix_route_hits"]
    assert after["prefix_route_misses"] == before["prefix_route_misses"]
    # the next same-prefix route follows the KV to the import target
    assert ctrl.route(prefix_keys=keys) == 1
    assert g.metrics.snapshot()["prefix_route_hits"] == 1


# --------------------------------------------------------------------- #
# BlockAllocator lifetimes under handoff                                #
# --------------------------------------------------------------------- #
def test_allocator_handoff_holds_pin_blocks_until_release():
    a = BlockAllocator(n_blocks=6, block_len=8)   # 5 usable (+garbage)
    blocks = a.alloc(4)
    assert blocks is not None and len(blocks) == 4
    keys = [f"k{i}" for i in range(4)]
    for k, b in zip(keys, blocks):
        assert a.register(k, b)
    # mid-handoff the source still holds its reference: the registered
    # blocks are NOT eviction fodder, so a demand the free list cannot
    # cover fails instead of corrupting an in-flight copy
    spare = a.alloc(1)
    assert spare is not None
    assert a.alloc(1) is None
    assert a.stats()["cached"] == 0               # all still referenced
    # decode took ownership: the source releases -- registered blocks
    # stay CACHED (prefix-reusable) rather than returning to the free
    # list, and only now become LRU-evictable
    for b in blocks:
        a.release(b)
    a.release(spare[0])
    st = a.stats()
    assert st["cached"] == 4 and st["used"] == 0
    # a fresh demand evicts oldest-first: k0's chain run dies, later
    # keys survive individually
    got = a.alloc(2)
    assert got is not None and len(got) == 2
    assert a.lookup_run(keys, 8) == []            # k0 evicted => no run
    run1 = a.lookup_run(["k2"], 8)                # private query: alive
    assert len(run1) <= 1
    for b in run1:
        a.release(b)
    # first registration wins: neither an occupied key nor an
    # already-keyed block re-registers
    assert not a.register("k3", got[0])
    survivor = next(b for k, b in zip(keys, blocks)
                    if a.lookup_run([k], 8))
    a.release(survivor)                           # undo the probe retain
    assert not a.register("fresh-key", survivor)


# --------------------------------------------------------------------- #
# KV handoff end-to-end: two in-process engines                         #
# --------------------------------------------------------------------- #
def test_kv_handoff_token_identity_release_and_zero_recompiles():
    """A completed prefill ships its block span to a second engine as a
    block-id remap + wave-bounded object-store copy: greedy outputs are
    token-identical to generate(), the source hold releases exactly
    once, and a same-shape second handoff adds ZERO compiles (the
    gather/scatter programs are memoized per wave width)."""
    from ray_lightning_accelerators_tpu.analysis.compile_guard import (
        compile_guard, install)
    install()
    model, params = _model()
    rng = np.random.default_rng(3)
    p1 = rng.integers(1, 60, size=17).astype(np.int32)   # 2 full blocks
    p2 = rng.integers(1, 60, size=17).astype(np.int32)
    refs = [_ref(model, params, p, 5) for p in (p1, p2)]
    pre = ServeEngine(model, params, max_slots=2, block_len=8).start()
    dec = ServeEngine(model, params, max_slots=2, block_len=8).start()
    try:
        desc = pre.submit_handoff(p1, 5).result(timeout=300)
        assert desc["block_len"] == 8 and desc["bytes"] > 0
        assert len(desc["keys"]) == 2
        out = dec.submit_import(desc).result(timeout=300)
        np.testing.assert_array_equal(out, refs[0])
        pstats = pre.stats()
        assert pstats["kv_handoffs"] == 1
        assert pstats["kv_handoff_bytes"] == desc["bytes"]
        # the source hold releases exactly once (idempotent second call)
        assert pre.release_handoff(desc["handoff_id"]) is True
        assert pre.release_handoff(desc["handoff_id"]) is False
        # warm path: a same-shape handoff end-to-end compiles NOTHING
        with compile_guard(max_new_compiles=0, label="handoff-steady"):
            desc2 = pre.submit_handoff(p2, 5).result(timeout=300)
            out2 = dec.submit_import(desc2).result(timeout=300)
        np.testing.assert_array_equal(out2, refs[1])
        assert pre.release_handoff(desc2["handoff_id"]) is True
        # accounting: both engines completed their half of each request
        assert pre.stats()["completed"] == 2
        assert dec.stats()["completed"] == 2
        assert pre.stats()["failed"] == dec.stats()["failed"] == 0
    finally:
        pre.stop(cancel_active=True, timeout=10)
        dec.stop(cancel_active=True, timeout=10)


def test_handoff_descriptor_deadline_survives_the_hop():
    """The descriptor carries the request's absolute deadline across
    the hop: an import whose deadline passed in transit is shed typed
    BEFORE any decode compute, and the source hold still releases."""
    model, params = _model()
    rng = np.random.default_rng(5)
    p = rng.integers(1, 60, size=17).astype(np.int32)
    pre = ServeEngine(model, params, max_slots=2, block_len=8).start()
    dec = ServeEngine(model, params, max_slots=2, block_len=8,
                      slo=SloPolicy(ttft_target_s=5.0)).start()
    try:
        desc = pre.submit_handoff(p, 4).result(timeout=300)
        assert desc["t_submit"] is not None
        desc = dict(desc, deadline=time.monotonic() - 0.5)
        resp = dec.submit_import(desc)
        with pytest.raises(DeadlineExceeded):
            resp.result(timeout=300)
        assert dec.stats()["failed"] == 1
        assert pre.release_handoff(desc["handoff_id"]) is True
    finally:
        pre.stop(cancel_active=True, timeout=10)
        dec.stop(cancel_active=True, timeout=10)


def test_import_rejects_mismatched_block_geometry():
    """A descriptor from a different block geometry is refused typed at
    submission -- scattering foreign-sized blocks would corrupt the
    pool silently."""
    model, params = _model()
    rng = np.random.default_rng(7)
    p = rng.integers(1, 60, size=17).astype(np.int32)
    pre = ServeEngine(model, params, max_slots=2, block_len=8).start()
    dec = ServeEngine(model, params, max_slots=2, block_len=16).start()
    try:
        desc = pre.submit_handoff(p, 3).result(timeout=300)
        with pytest.raises(ValueError):
            dec.submit_import(desc)
        assert pre.release_handoff(desc["handoff_id"]) is True
    finally:
        pre.stop(cancel_active=True, timeout=10)
        dec.stop(cancel_active=True, timeout=10)


# --------------------------------------------------------------------- #
# Crash during handoff: the replica-tier acceptance loop               #
# --------------------------------------------------------------------- #
def _lane_factory(np_params):
    """Engine factory executed inside each worker (cloudpickled
    closure; params travel as numpy).  block_len=8 matches the group's
    affinity_block_len so driver-side chain keys agree with the
    engines' prefix indexes."""
    def make():
        from ray_lightning_accelerators_tpu.models.transformer import (
            GPT, TransformerConfig)
        from ray_lightning_accelerators_tpu.serve import ServeEngine
        model = GPT(TransformerConfig(**_CFG))
        return ServeEngine(model, np_params, max_slots=4,
                           queue_depth=64, block_len=8, slo=None)
    return make


@pytest.mark.chaos
def test_lanes_survive_decode_crash_during_handoff(tmp_path):
    """1 prefill + 1 decode replica; the decode replica crashes on its
    FIRST chunk -- which, with lanes on, is necessarily a KV import in
    flight.  The tier requeues the stranded requests head-of-line,
    re-prefills them from scratch (exactly-once: no loss, no dup), the
    breaker revives the crashed replica, and every response stays
    token-identical to generate()."""
    from ray_lightning_accelerators_tpu.serve import ServeReplicas
    import jax

    model, params = _model()
    np_params = jax.tree.map(np.asarray, params)
    ns = str(tmp_path / "chaos-ns")
    hb = {"RLA_TPU_WORKER_HEARTBEAT_S": "0.1"}
    envs = [dict(hb),
            dict(hb, RLA_TPU_CHAOS="crash@replica1:chunk1:once",
                 RLA_TPU_CHAOS_NS=ns)]
    cfg = ControllerConfig(
        hedge=False, prefill_replicas=1, handoff_min_blocks=1,
        max_retries=4, retry_backoff_s=0.01, retry_backoff_cap_s=0.1,
        revive_backoff_s=0.2, revive_backoff_cap_s=1.0, poll_s=0.05)
    rng = np.random.default_rng(13)

    def wave(n):
        return [(rng.integers(1, 60, size=int(s)).astype(np.int32),
                 int(m)) for s, m in zip(rng.integers(16, 25, size=n),
                                         rng.integers(3, 6, size=n))]

    group = ServeReplicas(
        _lane_factory(np_params), num_replicas=2, chunk_size=2,
        heartbeat_s=0.1, wedge_timeout_s=1.2, queue_depth=64,
        env_per_worker=envs, controller=cfg, affinity_block_len=8)
    try:
        # keep waves coming until the crash fired, its requests came
        # back through the requeue lane AND the replica revived through
        # the breaker (bounded); every wave checked exact
        deadline = time.monotonic() + 150
        healed = False
        while time.monotonic() < deadline:
            pairs = wave(4)
            refs = [_ref(model, params, p, m) for p, m in pairs]
            handles = [group.submit(p, m) for p, m in pairs]
            for ref, h in zip(refs, handles):
                np.testing.assert_array_equal(h.result(timeout=300),
                                              ref)
            snap = group.metrics.snapshot()
            if snap["requeued"] >= 1 and snap["revived"] >= 1:
                healed = True
                break
        assert healed, group.stats()["controller"]
        snap = group.stats()
        rows = snap["controller"]["replicas"]
        assert rows["1"]["infra_failures"] >= 1   # the crash fired
        assert rows["0"]["lane"] == LANE_PREFILL
        assert rows["1"]["lane"] == LANE_DECODE
        assert snap["kv_handoffs"] >= 1
        assert snap["kv_handoff_bytes"] > 0
        # exactly-once over the whole run (and every response above was
        # asserted token-identical)
        assert snap["failed"] == 0
        assert snap["cancelled"] == 0
        assert snap["completed"] == snap["submitted"]
    finally:
        group.shutdown()


# --------------------------------------------------------------------- #
# Observability: metrics contract, Prometheus typing, rla_top           #
# --------------------------------------------------------------------- #
def test_metrics_lane_gauges_and_reset_audit():
    m = ServeMetrics()
    for c in ("prefix_route_hits", "prefix_route_misses",
              "kv_handoffs"):
        m.inc(c)
    m.inc("kv_handoff_bytes", 4096)
    lanes = {"lane_prefill_replicas": 1, "lane_decode_replicas": 2,
             "lane_prefill_inflight": 0, "lane_decode_inflight": 3}
    m.bind_lanes(lambda: dict(lanes))
    snap = m.snapshot()
    assert snap["prefix_route_hits"] == 1
    assert snap["prefix_route_misses"] == 1
    assert snap["kv_handoffs"] == 1
    assert snap["kv_handoff_bytes"] == 4096
    assert snap["lane_decode_inflight"] == 3
    # reset clears the counters; bound lane gauges stay wired (they
    # read live controller state, not history)
    m.reset()
    snap = m.snapshot()
    for c in ("prefix_route_hits", "prefix_route_misses",
              "kv_handoffs", "kv_handoff_bytes"):
        assert snap[c] == 0, c
    assert snap["lane_prefill_replicas"] == 1
    # one-lock snapshot contract: a bound gauge fn may itself touch the
    # metrics object (the controller's lock never nests inside ours)
    m2 = ServeMetrics()
    m2.bind_lanes(lambda: (m2.inc("hedged"),
                           {"lane_prefill_replicas": 0})[1])
    assert m2.snapshot()["lane_prefill_replicas"] == 0


def test_prometheus_typing_for_prefix_and_lane_families():
    from ray_lightning_accelerators_tpu.telemetry.registry import (
        MetricsRegistry)
    from tests.utils import assert_prometheus_exposition

    m = ServeMetrics()
    m.inc("prefix_route_hits", 3)
    m.inc("kv_handoffs", 2)
    m.inc("kv_handoff_bytes", 8192)
    m.bind_lanes(lambda: {"lane_prefill_replicas": 1,
                          "lane_decode_replicas": 2,
                          "lane_prefill_inflight": 0,
                          "lane_decode_inflight": 1})
    g = _FakeGroup(2)
    ctrl = ReplicaController(g, ControllerConfig(prefill_replicas=1))
    keys = chain_prefix_keys(np.arange(16, dtype=np.int32), 8)
    home = ctrl.route(prefix_keys=keys)
    ctrl.route(prefix_keys=keys)                 # the warm hit
    reg = MetricsRegistry()
    reg.add_serve(m, rank=0)
    reg.add_replica_controller(ctrl)
    text = reg.prometheus_text()
    assert_prometheus_exposition(text)
    # tier tallies are counters (_total), lane occupancy gauges (bare)
    assert 'rla_tpu_serve_prefix_route_hits_total{rank="0"} 3' in text
    assert 'rla_tpu_serve_kv_handoffs_total{rank="0"} 2' in text
    assert 'rla_tpu_serve_kv_handoff_bytes_total{rank="0"} 8192' in text
    assert 'rla_tpu_serve_lane_decode_replicas{rank="0"} 2' in text
    assert "rla_tpu_serve_lane_decode_replicas_total" not in text
    # per-replica prefix tallies + hit-rate level, lane one-hot
    assert (f'rla_tpu_serve_replica_prefix_hits_total'
            f'{{replica="{home}"}} 1') in text
    assert (f'rla_tpu_serve_replica_prefix_hit_rate'
            f'{{replica="{home}"}} 0.5') in text
    assert 'rla_tpu_serve_replica_lane{replica="0",lane="prefill"} 1' \
        in text
    assert 'rla_tpu_serve_replica_lane{replica="1",lane="decode"} 1' \
        in text


def test_rla_top_renders_lane_and_prefix_columns():
    spec = importlib.util.spec_from_file_location(
        "rla_top", os.path.join(_ROOT, "scripts", "rla_top.py"))
    rla_top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rla_top)
    g = _FakeGroup(2)
    ctrl = ReplicaController(g, ControllerConfig(prefill_replicas=1))
    keys = chain_prefix_keys(np.arange(16, dtype=np.int32), 8)
    home = ctrl.route(prefix_keys=keys)
    ctrl.route(prefix_keys=keys)
    status = {"rank": "driver", "trace_id": "t", "health": {},
              "replica_controller": ctrl.snapshot()}
    out = rla_top.render(status)
    assert "serve tier: queue 0/16" in out
    assert "lane" in out and "pfx-hit" in out
    assert "affinity ring: vnodes" in out
    lines = out.splitlines()
    row0 = next(ln for ln in lines if ln.startswith("0 "))
    row1 = next(ln for ln in lines if ln.startswith("1 "))
    assert "prefill" in row0 and "decode" in row1
    hot = row0 if home == 0 else row1
    assert "0.50" in hot                          # 1 hit / 2 routes
    # affinity disabled: the ring line disappears, the table survives
    ctrl2 = ReplicaController(_FakeGroup(1),
                              ControllerConfig(affinity=False))
    out2 = rla_top.render({"rank": "driver", "health": {},
                           "replica_controller": ctrl2.snapshot()})
    assert "affinity ring" not in out2 and "pfx-hit" in out2
