"""Torch weight interop: dtype round-trips, Linear import, forward
equivalence torch vs jax, export round-trip."""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from ray_lightning_accelerators_tpu.models.mnist import MNISTClassifier
from ray_lightning_accelerators_tpu.utils import torch_interop as ti


def test_dtype_roundtrips():
    for dtype in (torch.float32, torch.bfloat16, torch.int32):
        t = torch.arange(6, dtype=torch.float32).reshape(2, 3).to(dtype)
        back = ti.to_torch(ti.from_torch(t))
        assert back.dtype == dtype
        assert torch.equal(back, t)


def test_jax_bf16_to_torch():
    a = jnp.asarray([[1.5, -2.25]], jnp.bfloat16)
    t = ti.to_torch(a)
    assert t.dtype == torch.bfloat16
    np.testing.assert_allclose(t.float().numpy(), [[1.5, -2.25]])


class _TorchMLP(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.net = torch.nn.Sequential(
            torch.nn.Linear(784, 128), torch.nn.ReLU(),
            torch.nn.Linear(128, 256), torch.nn.ReLU(),
            torch.nn.Linear(256, 10))

    def forward(self, x):
        return self.net(x)


def _mapping():
    m = {}
    for i, layer in enumerate((0, 2, 4)):
        m.update(ti.linear_mapping(f"dense_{i}", f"net.{layer}"))
    return m


def test_forward_equivalence():
    torch.manual_seed(0)
    tm = _TorchMLP().eval()
    model = MNISTClassifier({"layer_1": 128, "layer_2": 256})
    template = model.init_params(jax.random.PRNGKey(0))
    params = ti.import_state_dict(template, tm.state_dict(), _mapping())
    x = np.random.default_rng(0).normal(size=(4, 784)).astype(np.float32)
    with torch.no_grad():
        ref = tm(torch.from_numpy(x)).numpy()
    out = np.asarray(model.forward(params, x))
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_shape_mismatch_caught():
    tm = _TorchMLP()
    model = MNISTClassifier({"layer_1": 128, "layer_2": 256})
    template = model.init_params(jax.random.PRNGKey(0))
    bad = dict(_mapping())
    bad["dense_0/kernel"] = "net.0.weight"  # missing transpose
    with pytest.raises(ValueError, match="transpose"):
        ti.import_state_dict(template, tm.state_dict(), bad)


def test_strict_requires_full_mapping():
    tm = _TorchMLP()
    model = MNISTClassifier({"layer_1": 128, "layer_2": 256})
    template = model.init_params(jax.random.PRNGKey(0))
    partial = ti.linear_mapping("dense_0", "net.0")
    with pytest.raises(ValueError, match="unmapped"):
        ti.import_state_dict(template, tm.state_dict(), partial)
    out = ti.import_state_dict(template, tm.state_dict(), partial,
                               strict=False)
    # unmapped leaves keep template values
    np.testing.assert_array_equal(np.asarray(out["dense_2"]["kernel"]),
                                  np.asarray(template["dense_2"]["kernel"]))


def test_export_roundtrip():
    tm = _TorchMLP()
    model = MNISTClassifier({"layer_1": 128, "layer_2": 256})
    template = model.init_params(jax.random.PRNGKey(0))
    params = ti.import_state_dict(template, tm.state_dict(), _mapping())
    sd = ti.export_state_dict(params, _mapping())
    for k, v in sd.items():
        assert torch.allclose(v, tm.state_dict()[k], atol=1e-6), k


def test_from_torch_noncontiguous_bf16():
    t = torch.arange(12, dtype=torch.float32).reshape(3, 4).to(
        torch.bfloat16).t()  # transposed = non-contiguous
    a = ti.from_torch(t)
    assert a.shape == (4, 3)
    np.testing.assert_allclose(
        a.astype(np.float32),
        t.float().numpy())
