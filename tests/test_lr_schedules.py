"""LR schedule shapes + the trainer's per-step lr metric."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_lightning_accelerators_tpu.utils import schedules


def _eval(sched, steps):
    return np.asarray([float(sched(jnp.asarray(s))) for s in steps])


def test_warmup_cosine_shape():
    s = schedules.warmup_cosine(1.0, total_steps=100, warmup_steps=10,
                                end_lr=0.1)
    vals = _eval(s, [0, 5, 10, 55, 100])
    assert vals[0] == pytest.approx(0.0)
    assert vals[1] == pytest.approx(0.5, abs=0.05)   # mid-warmup
    assert vals[2] == pytest.approx(1.0)             # peak
    assert 0.1 < vals[3] < 1.0                       # decaying
    assert vals[4] == pytest.approx(0.1, abs=1e-6)   # floor


def test_warmup_linear_shape():
    s = schedules.warmup_linear(2.0, total_steps=100, warmup_steps=20)
    vals = _eval(s, [0, 10, 20, 60, 100])
    assert vals[0] == pytest.approx(0.0)
    assert vals[1] == pytest.approx(1.0)
    assert vals[2] == pytest.approx(2.0)
    assert vals[3] == pytest.approx(1.0)
    assert vals[4] == pytest.approx(0.0, abs=1e-6)


def test_step_decay():
    s = schedules.step_decay(1.0, {30: 0.1, 60: 0.1})
    vals = _eval(s, [0, 29, 31, 61])
    np.testing.assert_allclose(vals, [1.0, 1.0, 0.1, 0.01], rtol=1e-5)


def test_inverse_sqrt():
    s = schedules.inverse_sqrt(1.0, warmup_steps=16)
    vals = _eval(s, [0, 8, 16, 64])
    assert vals[0] == pytest.approx(1 / 16)  # step clamps to 1 in warmup
    assert vals[1] == pytest.approx(0.5)
    assert vals[2] == pytest.approx(1.0)
    assert vals[3] == pytest.approx(0.5)  # sqrt(16/64)


def test_wsd_plateau_and_decay():
    s = schedules.wsd(1.0, total_steps=100, warmup_steps=10, decay_steps=20,
                      end_lr=0.0)
    vals = _eval(s, [0, 5, 10, 50, 79, 90, 100])
    assert vals[0] == pytest.approx(0.0)
    assert vals[1] == pytest.approx(0.5)
    assert vals[2] == pytest.approx(1.0)
    assert vals[3] == pytest.approx(1.0)   # stable plateau
    assert vals[4] == pytest.approx(1.0, abs=0.06)
    assert 0.0 < vals[5] < 1.0             # decaying
    assert vals[6] == pytest.approx(0.0, abs=1e-6)


def test_schedule_is_jittable():
    s = schedules.wsd(3e-4, total_steps=1000, warmup_steps=100,
                      decay_steps=100)
    out = jax.jit(jax.vmap(s))(jnp.arange(0, 1000, 100))
    assert out.shape == (10,)


def test_trainer_logs_lr_metric():
    from ray_lightning_accelerators_tpu import (ArrayDataset, DataLoader,
                                                Trainer)
    from tests.utils import BoringModel

    sched = schedules.warmup_linear(1e-2, total_steps=8, warmup_steps=4)

    class SchedModel(BoringModel):
        def __init__(self):
            super().__init__()
            self.lr_schedule = sched

        def configure_optimizers(self):
            return optax.sgd(sched)

    x = np.random.default_rng(0).normal(size=(64, 32)).astype(np.float32)
    model = SchedModel()
    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      enable_checkpointing=False, log_every_n_steps=1,
                      default_root_dir="/tmp/lr_sched_test")
    trainer.fit(model, DataLoader(ArrayDataset(x), batch_size=8))
    assert "lr" in trainer.callback_metrics
    # last step index seen by the schedule inside the final update is 7
    assert trainer.callback_metrics["lr"] == pytest.approx(
        float(sched(jnp.asarray(7))), rel=1e-5)


def test_gpt_accepts_schedule():
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    sched = schedules.warmup_cosine(1e-3, total_steps=100, warmup_steps=10)
    model = GPT(TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                  d_ff=64, n_layers=1, max_seq_len=16),
                lr=sched)
    assert model.lr_schedule is sched
    tx = model.configure_optimizers()
    params = model.init_params(jax.random.PRNGKey(0))
    state = tx.init(params)
    toks = jnp.zeros((2, 16), jnp.int32)
    grads = jax.grad(lambda p: model.training_step(
        p, toks, jax.random.PRNGKey(0))[0])(params)
    updates, _ = tx.update(grads, state, params)
    assert jax.tree.leaves(updates)[0] is not None
