"""LM data pipeline: tokenizer round-trip, packing, example smoke."""

import numpy as np
import pytest

from ray_lightning_accelerators_tpu.data.lm import (CharTokenizer,
                                                    lm_dataset,
                                                    pack_sequences,
                                                    pack_stream,
                                                    synthetic_corpus)


def test_tokenizer_roundtrip():
    text = "hello mesh world"
    tok = CharTokenizer(text)
    ids = tok.encode(text)
    assert tok.decode(ids) == text
    assert min(ids) >= 2  # 0/1 reserved for pad/eos
    with pytest.raises(ValueError, match="not in vocabulary"):
        tok.encode("z!")


def test_packing_layout():
    docs = [[10, 11, 12], [20, 21], [30]]
    packed = pack_sequences(docs, seq_len=4, eos_id=1)
    # stream: 10 11 12 1 | 20 21 1 30 | (1 dropped)
    np.testing.assert_array_equal(
        packed, [[10, 11, 12, 1], [20, 21, 1, 30]])
    assert packed.dtype == np.int32


def test_packing_pad_remainder():
    packed = pack_sequences([[5, 6, 7]], seq_len=4, eos_id=None,
                            drop_remainder=False, pad_id=0)
    np.testing.assert_array_equal(packed, [[5, 6, 7, 0]])


def test_packing_no_eos():
    packed = pack_sequences([[1, 2], [3, 4]], seq_len=2, eos_id=None)
    np.testing.assert_array_equal(packed, [[1, 2], [3, 4]])


def test_lm_dataset_shapes():
    ds, tok = lm_dataset(synthetic_corpus(50), seq_len=64)
    rows = ds._native_arrays()[0]
    assert rows.shape[1] == 64
    assert rows.shape[0] > 1
    assert rows.max() < tok.vocab_size
    with pytest.raises(ValueError, match="too small"):
        lm_dataset("ab", seq_len=64)


def test_example_smoke():
    import examples.gpt_lm_example as ex
    trainer = ex.train_gpt(num_epochs=1, batch_size=8, seq_len=64,
                           smoke=True)
    assert trainer.callback_metrics["loss"] > 0


def test_pack_stream_matches_batch_packer():
    docs = [[10, 11, 12], [20, 21], [30, 31, 32, 33]]
    rows = list(pack_stream(iter(docs), seq_len=4))
    ref = pack_sequences(docs, seq_len=4)
    np.testing.assert_array_equal(np.stack(rows), ref)


def test_streaming_dataset_trains():
    import jax
    from ray_lightning_accelerators_tpu import DataLoader, Trainer
    from ray_lightning_accelerators_tpu.data.lm import StreamingLMDataset
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)

    def doc_factory(epoch):
        rng = np.random.default_rng(epoch)
        for _ in range(40):
            yield rng.integers(2, 60, size=rng.integers(5, 30)).tolist()

    ds = StreamingLMDataset(doc_factory, seq_len=32)
    loader = DataLoader(ds, batch_size=8)
    with pytest.raises(TypeError, match="no length"):
        len(loader)
    model = GPT(TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                  d_ff=64, n_layers=1, max_seq_len=32))
    trainer = Trainer(max_epochs=2, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir="/tmp/stream_lm_test")
    trainer.fit(model, loader)
    assert trainer.global_step > 0
    assert trainer.callback_metrics["loss"] > 0


def test_streaming_shard_round_robin():
    from ray_lightning_accelerators_tpu import DataLoader
    from ray_lightning_accelerators_tpu.data.lm import StreamingLMDataset

    def doc_factory(epoch):
        return iter([[i] * 8 for i in range(16)])

    rows_by_rank = {}
    for rank in (0, 1):
        ds = StreamingLMDataset(doc_factory, seq_len=8, eos_id=None)
        loader = DataLoader(ds, batch_size=2)
        loader._inject_sampler(num_replicas=2, rank=rank, shuffle=False)
        rows_by_rank[rank] = np.concatenate(list(loader))
    seen0 = set(rows_by_rank[0][:, 0].tolist())
    seen1 = set(rows_by_rank[1][:, 0].tolist())
    assert seen0 & seen1 == set()          # disjoint
    assert seen0 | seen1 == set(range(16))  # complete


def test_iterable_rejects_shuffle_and_sampler():
    from ray_lightning_accelerators_tpu import DataLoader
    from ray_lightning_accelerators_tpu.data.lm import StreamingLMDataset
    ds = StreamingLMDataset(lambda e: iter([]), seq_len=8)
    with pytest.raises(ValueError, match="shuffle"):
        DataLoader(ds, batch_size=2, shuffle=True)


def test_streaming_equal_batches_on_ragged_stream():
    """11 rows over 2 replicas must give BOTH ranks the same batch count
    (unequal counts would hang multi-process collectives)."""
    from ray_lightning_accelerators_tpu import DataLoader
    from ray_lightning_accelerators_tpu.data.lm import StreamingLMDataset

    def doc_factory(epoch):
        return iter([[i] * 8 for i in range(11)])

    counts = {}
    for rank in (0, 1):
        ds = StreamingLMDataset(doc_factory, seq_len=8, eos_id=None)
        loader = DataLoader(ds, batch_size=2)
        loader._inject_sampler(num_replicas=2, rank=rank, shuffle=False)
        counts[rank] = len(list(loader))
    assert counts[0] == counts[1] == 2


def test_pack_stream_generator_docs_constant_memory():
    """Documents may be generators (no slicing/len); packing must not
    require materializing a document."""
    from ray_lightning_accelerators_tpu.data.lm import pack_stream

    def one_huge_doc():
        yield (x % 250 + 2 for x in range(10_000))

    rows = list(pack_stream(one_huge_doc(), seq_len=128, eos_id=None))
    assert len(rows) == 10_000 // 128
    assert rows[0][0] == 2 and rows[1][0] == (128 % 250) + 2


def test_bpe_roundtrip_and_compression():
    from ray_lightning_accelerators_tpu.data.lm import BPETokenizer
    corpus = synthetic_corpus(100)
    tok = BPETokenizer(corpus, vocab_size=400)
    text = "the pod shards the batch. a chip compiles every gradient."
    ids = tok.encode(text)
    assert tok.decode(ids) == text
    # merges actually fire: shorter than byte-length
    assert len(ids) < len(text.encode("utf-8"))
    # unseen characters still round-trip (byte fallback)
    weird = "zebra Ω 字"
    assert tok.decode(tok.encode(weird)) == weird
    # ids stay within vocab and off the reserved range
    assert max(ids) < 400 and min(ids) >= 2


def test_bpe_vocab_floor():
    from ray_lightning_accelerators_tpu.data.lm import BPETokenizer
    with pytest.raises(ValueError, match="vocab_size"):
        BPETokenizer("abc", vocab_size=100)


def test_bpe_feeds_packer():
    from ray_lightning_accelerators_tpu.data.lm import (BPETokenizer,
                                                        pack_sequences)
    corpus = synthetic_corpus(50)
    tok = BPETokenizer(corpus, vocab_size=300)
    docs = [tok.encode(d) for d in corpus.split("\n\n")]
    rows = pack_sequences(docs, seq_len=32)
    assert rows.shape[1] == 32 and rows.shape[0] > 0
    assert rows.max() < 300
