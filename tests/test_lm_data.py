"""LM data pipeline: tokenizer round-trip, packing, example smoke."""

import numpy as np
import pytest

from ray_lightning_accelerators_tpu.data.lm import (CharTokenizer,
                                                    lm_dataset,
                                                    pack_sequences,
                                                    synthetic_corpus)


def test_tokenizer_roundtrip():
    text = "hello mesh world"
    tok = CharTokenizer(text)
    ids = tok.encode(text)
    assert tok.decode(ids) == text
    assert min(ids) >= 2  # 0/1 reserved for pad/eos
    with pytest.raises(ValueError, match="not in vocabulary"):
        tok.encode("z!")


def test_packing_layout():
    docs = [[10, 11, 12], [20, 21], [30]]
    packed = pack_sequences(docs, seq_len=4, eos_id=1)
    # stream: 10 11 12 1 | 20 21 1 30 | (1 dropped)
    np.testing.assert_array_equal(
        packed, [[10, 11, 12, 1], [20, 21, 1, 30]])
    assert packed.dtype == np.int32


def test_packing_pad_remainder():
    packed = pack_sequences([[5, 6, 7]], seq_len=4, eos_id=None,
                            drop_remainder=False, pad_id=0)
    np.testing.assert_array_equal(packed, [[5, 6, 7, 0]])


def test_packing_no_eos():
    packed = pack_sequences([[1, 2], [3, 4]], seq_len=2, eos_id=None)
    np.testing.assert_array_equal(packed, [[1, 2], [3, 4]])


def test_lm_dataset_shapes():
    ds, tok = lm_dataset(synthetic_corpus(50), seq_len=64)
    rows = ds._native_arrays()[0]
    assert rows.shape[1] == 64
    assert rows.shape[0] > 1
    assert rows.max() < tok.vocab_size
    with pytest.raises(ValueError, match="too small"):
        lm_dataset("ab", seq_len=64)


def test_example_smoke():
    import examples.gpt_lm_example as ex
    trainer = ex.train_gpt(num_epochs=1, batch_size=8, seq_len=64,
                           smoke=True)
    assert trainer.callback_metrics["loss"] > 0
