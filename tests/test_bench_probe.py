"""bench.py wedge-proofing: the pre-flight backend probe must turn a
dead/hung backend into ONE machine-readable record in bounded time (the
round-4 driver bench burned its whole window hanging on a wedged device
tunnel and produced zero output; these tests pin the machinery that
prevents a repeat).  The probe child is faked by monkeypatching the
probe source -- the logic under test is the parent's subprocess
handling, not JAX."""

import json
import subprocess
import sys

import pytest

import bench


def test_probe_passes_on_healthy_child(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "print('PROBE_OK 1.0 fake-devices')")
    assert bench.probe_backend(timeout_s=30) is None


def test_probe_reports_failing_child(monkeypatch):
    monkeypatch.setattr(
        bench, "_PROBE_SRC",
        "import sys; sys.stderr.write('Unable to initialize backend "
        "axon: UNAVAILABLE');\nraise SystemExit(1)")
    err = bench.probe_backend(timeout_s=30)
    assert err is not None
    assert err["error"] == "backend unavailable"
    assert "Unable to initialize" in err["detail"]
    assert err["probe_seconds"] < 30


def test_probe_kills_hung_child_within_timeout(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "import time; time.sleep(600)")
    err = bench.probe_backend(timeout_s=2)
    assert err is not None
    assert "hung" in err["detail"]
    # bounded: the whole point is not burning the driver's window
    assert err["probe_seconds"] < 30


def test_probe_stall_classification_wedge_vs_dead(monkeypatch):
    """The wedge-vs-dead triage embedded in the probe record: only a
    child that ran out its TIMEOUT with no output reads as a wedged
    tunnel — a fast silent death (segfault on import) and a noisy
    timeout are both dead-backend (review finding: presence-of-output
    alone misdiagnosed fast crashes as hangs)."""
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "import time; time.sleep(600)")
    err = bench.probe_backend(timeout_s=2)
    assert err["stall"]["classification"] == "wedged-tunnel"
    # fast silent exit: dead backend, NOT a wedge (no timeout occurred)
    monkeypatch.setattr(bench, "_PROBE_SRC", "raise SystemExit(1)")
    err = bench.probe_backend(timeout_s=30)
    assert err["stall"]["classification"] == "dead-backend"
    assert err["probe_seconds"] < 30
    # noisy timeout: the backend answered, then died
    assert bench._flight_diagnosis("partial output", "",
                                   timed_out=True)["stall"][
        "classification"] == "dead-backend"
    # spill tails ride along when a telemetry dir holds rank files
    tails = bench._flight_diagnosis("", "", timed_out=True)
    assert "flight_tail" not in tails  # no dir configured -> absent


def test_probe_rejects_child_without_marker(monkeypatch):
    # a child that exits 0 but never ran the device op must NOT pass
    monkeypatch.setattr(bench, "_PROBE_SRC", "print('something else')")
    assert bench.probe_backend(timeout_s=30) is not None


def test_dead_backend_emits_death_record_then_cpu_fallback(monkeypatch,
                                                           capsys):
    """main() with a dead backend: the death record comes FIRST, no
    accelerator bench ever ran -- and the CPU-mesh fallback benches
    (gradexchange/input_pipeline/fsdp_exchange/paged_serve/
    mfu_overlap/perf_observatory/live_plane/serve_resilience/resize/
    pipeline/prefix_affinity) still land REAL metric lines next
    to the death record, so the window exits 0 and the driver records
    numbers (all five earlier BENCH rounds were rc=2 with zero real
    numbers; this pins the fix).  The fallbacks are faked here (the
    real forced-CPU paths are covered by test_collectives /
    test_prefetch / the probe scripts); the failure mode is also
    pinned: with EVERY fallback broken there is no real line, so rc=2
    survives as the zero-numbers signal."""
    monkeypatch.setattr(bench, "_PROBE_SRC", "raise SystemExit(1)")
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--benches", "mnist",
                         "--probe-timeout", "5"])
    ran = []
    monkeypatch.setitem(bench.BENCHES, "mnist",
                        lambda: ran.append(1) or {})
    monkeypatch.setattr(
        bench, "bench_gradexchange",
        lambda: {"metric": "gradexchange_int8_wire_bytes_reduction",
                 "value": 3.9, "unit": "x", "vs_baseline": 0.98})
    monkeypatch.setattr(
        bench, "bench_input_pipeline",
        lambda: {"metric": "input_pipeline_prefetch_speedup",
                 "value": 1.8, "unit": "x", "vs_baseline": 1.2})
    monkeypatch.setattr(
        bench, "bench_fsdp_exchange",
        lambda: {"metric": "fsdp_exchange_int8_wire_bytes_reduction",
                 "value": 2.65, "unit": "x", "vs_baseline": 1.0})
    monkeypatch.setattr(
        bench, "bench_paged_serve",
        lambda: {"metric": "paged_serve_concurrency_per_hbm_ratio",
                 "value": 3.9, "unit": "x", "vs_baseline": 2.6})
    monkeypatch.setattr(
        bench, "bench_mfu_overlap",
        lambda: {"metric": "mfu_overlap_scan_vs_tree_step_time_ratio",
                 "value": 1.3, "unit": "x", "vs_baseline": 1.3})
    monkeypatch.setattr(
        bench, "bench_perf_observatory",
        lambda: {"metric": "perf_observatory_phase_coverage",
                 "value": 0.97, "unit": "fraction", "vs_baseline": 1.13})
    monkeypatch.setattr(
        bench, "bench_live_plane",
        lambda: {"metric": "live_plane_scrape_validity",
                 "value": 1.0, "unit": "fraction", "vs_baseline": 1.0})
    monkeypatch.setattr(
        bench, "bench_serve_resilience",
        lambda: {"metric": "serve_resilience_completed_fraction",
                 "value": 1.0, "unit": "fraction", "vs_baseline": 1.0})
    monkeypatch.setattr(
        bench, "bench_resize",
        lambda: {"metric": "resize_inmem_vs_ckpt_downtime_ratio",
                 "value": 3.7, "unit": "x", "vs_baseline": 1.16})
    monkeypatch.setattr(
        bench, "bench_pipeline",
        lambda: {"metric": "pipeline_bubble_accuracy",
                 "value": 0.96, "unit": "frac", "vs_baseline": 1.2})
    monkeypatch.setattr(
        bench, "bench_prefix_affinity",
        lambda: {"metric": "prefix_affinity_ttft_ratio",
                 "value": 3.1, "unit": "ratio", "vs_baseline": 3.1})
    monkeypatch.setattr(
        bench, "bench_long_context",
        lambda: {"metric": "long_context_cadence_ratio",
                 "value": 2.6, "unit": "ratio", "vs_baseline": 2.6})
    monkeypatch.setattr(
        bench, "bench_anomaly_guard",
        lambda: {"metric": "anomaly_guard_overhead_ratio",
                 "value": 1.01, "unit": "ratio", "vs_baseline": 1.01})
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0  # real metric lines landed
    assert not ran
    lines = [json.loads(ln) for ln
             in capsys.readouterr().out.splitlines() if ln.strip()]
    assert len(lines) == 14
    assert lines[0]["metric"] == "backend_probe"
    assert lines[0]["error"] == "backend unavailable"
    assert lines[1]["metric"] == "gradexchange_int8_wire_bytes_reduction"
    assert lines[2]["metric"] == "input_pipeline_prefetch_speedup"
    assert lines[3]["metric"] == "fsdp_exchange_int8_wire_bytes_reduction"
    assert lines[4]["metric"] == "paged_serve_concurrency_per_hbm_ratio"
    assert lines[5]["metric"] == "mfu_overlap_scan_vs_tree_step_time_ratio"
    assert lines[6]["metric"] == "perf_observatory_phase_coverage"
    assert lines[7]["metric"] == "live_plane_scrape_validity"
    assert lines[8]["metric"] == "serve_resilience_completed_fraction"
    assert lines[9]["metric"] == "resize_inmem_vs_ckpt_downtime_ratio"
    assert lines[10]["metric"] == "pipeline_bubble_accuracy"
    assert lines[11]["metric"] == "prefix_affinity_ttft_ratio"
    assert lines[12]["metric"] == "long_context_cadence_ratio"
    assert lines[13]["metric"] == "anomaly_guard_overhead_ratio"
    assert all("error" not in r for r in lines[1:])

    # one fallback crashing must not take the others (or exit 0) down
    monkeypatch.setattr(bench, "bench_gradexchange",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    with pytest.raises(SystemExit) as e2:
        bench.main()
    assert e2.value.code == 0
    lines2 = [json.loads(ln) for ln
              in capsys.readouterr().out.splitlines() if ln.strip()]
    assert [r["metric"] for r in lines2] == [
        "backend_probe", "input_pipeline_prefetch_speedup",
        "fsdp_exchange_int8_wire_bytes_reduction",
        "paged_serve_concurrency_per_hbm_ratio",
        "mfu_overlap_scan_vs_tree_step_time_ratio",
        "perf_observatory_phase_coverage",
        "live_plane_scrape_validity",
        "serve_resilience_completed_fraction",
        "resize_inmem_vs_ckpt_downtime_ratio",
        "pipeline_bubble_accuracy",
        "prefix_affinity_ttft_ratio",
        "long_context_cadence_ratio",
        "anomaly_guard_overhead_ratio"]

    # EVERY fallback crashed: death record survives, and rc=2 keeps
    # meaning "this window produced zero real numbers"
    monkeypatch.setattr(bench, "bench_input_pipeline",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    monkeypatch.setattr(bench, "bench_fsdp_exchange",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    monkeypatch.setattr(bench, "bench_paged_serve",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    monkeypatch.setattr(bench, "bench_mfu_overlap",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    monkeypatch.setattr(bench, "bench_perf_observatory",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    monkeypatch.setattr(bench, "bench_live_plane",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    monkeypatch.setattr(bench, "bench_serve_resilience",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    monkeypatch.setattr(bench, "bench_resize",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    monkeypatch.setattr(bench, "bench_pipeline",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    monkeypatch.setattr(bench, "bench_prefix_affinity",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    monkeypatch.setattr(bench, "bench_long_context",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    monkeypatch.setattr(bench, "bench_anomaly_guard",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    with pytest.raises(SystemExit) as e3:
        bench.main()
    assert e3.value.code == 2
    lines3 = [json.loads(ln) for ln
              in capsys.readouterr().out.splitlines() if ln.strip()]
    assert len(lines3) == 1 and lines3[0]["metric"] == "backend_probe"


def test_backend_death_mid_run_stops_remaining_benches(monkeypatch,
                                                       capsys):
    """A bench raising a CERTAIN backend-death marker aborts the rest
    with a machine-readable record (no probe needed), emits the CPU
    fallbacks, and exits 0 because real metric lines landed."""
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--benches", "a,b",
                         "--probe-timeout", "0", "--no-isolate"])

    def dead():
        raise RuntimeError("Unable to initialize backend 'axon'")

    ran = []
    monkeypatch.setitem(bench.BENCHES, "a", dead)
    monkeypatch.setitem(bench.BENCHES, "b", lambda: ran.append(1) or {})
    monkeypatch.setattr(
        bench, "bench_gradexchange",
        lambda: {"metric": "gradexchange_int8_wire_bytes_reduction",
                 "value": 3.9, "unit": "x", "vs_baseline": 0.98})
    monkeypatch.setattr(
        bench, "bench_input_pipeline",
        lambda: {"metric": "input_pipeline_prefetch_speedup",
                 "value": 1.8, "unit": "x", "vs_baseline": 1.2})
    monkeypatch.setattr(
        bench, "bench_fsdp_exchange",
        lambda: {"metric": "fsdp_exchange_int8_wire_bytes_reduction",
                 "value": 2.65, "unit": "x", "vs_baseline": 1.0})
    monkeypatch.setattr(
        bench, "bench_paged_serve",
        lambda: {"metric": "paged_serve_concurrency_per_hbm_ratio",
                 "value": 3.9, "unit": "x", "vs_baseline": 2.6})
    monkeypatch.setattr(
        bench, "bench_mfu_overlap",
        lambda: {"metric": "mfu_overlap_scan_vs_tree_step_time_ratio",
                 "value": 1.3, "unit": "x", "vs_baseline": 1.3})
    monkeypatch.setattr(
        bench, "bench_perf_observatory",
        lambda: {"metric": "perf_observatory_phase_coverage",
                 "value": 0.97, "unit": "fraction", "vs_baseline": 1.13})
    monkeypatch.setattr(
        bench, "bench_live_plane",
        lambda: {"metric": "live_plane_scrape_validity",
                 "value": 1.0, "unit": "fraction", "vs_baseline": 1.0})
    monkeypatch.setattr(
        bench, "bench_serve_resilience",
        lambda: {"metric": "serve_resilience_completed_fraction",
                 "value": 1.0, "unit": "fraction", "vs_baseline": 1.0})
    monkeypatch.setattr(
        bench, "bench_resize",
        lambda: {"metric": "resize_inmem_vs_ckpt_downtime_ratio",
                 "value": 3.7, "unit": "x", "vs_baseline": 1.16})
    monkeypatch.setattr(
        bench, "bench_pipeline",
        lambda: {"metric": "pipeline_bubble_accuracy",
                 "value": 0.96, "unit": "frac", "vs_baseline": 1.2})
    monkeypatch.setattr(
        bench, "bench_prefix_affinity",
        lambda: {"metric": "prefix_affinity_ttft_ratio",
                 "value": 3.1, "unit": "ratio", "vs_baseline": 3.1})
    monkeypatch.setattr(
        bench, "bench_long_context",
        lambda: {"metric": "long_context_cadence_ratio",
                 "value": 2.6, "unit": "ratio", "vs_baseline": 2.6})
    monkeypatch.setattr(
        bench, "bench_anomaly_guard",
        lambda: {"metric": "anomaly_guard_overhead_ratio",
                 "value": 1.01, "unit": "ratio", "vs_baseline": 1.01})
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0
    assert not ran  # b never ran against the dead backend
    lines = [json.loads(ln) for ln
             in capsys.readouterr().out.splitlines() if ln.strip()]
    rec = lines[0]
    assert rec["error"] == "backend died mid-run"
    assert rec["failed_bench"] == "a"
    assert [r["metric"] for r in lines[1:]] == [
        "gradexchange_int8_wire_bytes_reduction",
        "input_pipeline_prefetch_speedup",
        "fsdp_exchange_int8_wire_bytes_reduction",
        "paged_serve_concurrency_per_hbm_ratio",
        "mfu_overlap_scan_vs_tree_step_time_ratio",
        "perf_observatory_phase_coverage",
        "live_plane_scrape_validity",
        "serve_resilience_completed_fraction",
        "resize_inmem_vs_ckpt_downtime_ratio",
        "pipeline_bubble_accuracy",
        "prefix_affinity_ttft_ratio",
        "long_context_cadence_ratio",
        "anomaly_guard_overhead_ratio"]

    # an EARLIER genuinely-failed bench keeps the window at exit 1
    # (death + fallbacks must not mask it)
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--benches", "plain,a,b",
                         "--probe-timeout", "0", "--no-isolate"])
    monkeypatch.setitem(bench.BENCHES, "plain",
                        lambda: (_ for _ in ()).throw(RuntimeError("oops")))
    with pytest.raises(SystemExit) as e2:
        bench.main()
    assert e2.value.code == 1
    capsys.readouterr()

    # isolated-mode CHILDREN report a bare rc=2 instead (the parent
    # emits the fallbacks once per window)
    monkeypatch.setenv("RLA_TPU_BENCH_CHILD", "1")
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--benches", "a,b",
                         "--probe-timeout", "0", "--no-isolate"])
    with pytest.raises(SystemExit) as e3:
        bench.main()
    assert e3.value.code == 2
    lines3 = [json.loads(ln) for ln
              in capsys.readouterr().out.splitlines() if ln.strip()]
    assert len(lines3) == 1  # death record only; no fallback in the child


def test_suspect_marker_with_probe_disabled_continues(monkeypatch,
                                                      capsys):
    """A transient-looking gRPC 'UNAVAILABLE' with probing disabled
    must NOT kill the remaining benches."""
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--benches", "a,b",
                         "--probe-timeout", "0", "--no-isolate"])

    def flaky():
        raise RuntimeError("DEADLINE_EXCEEDED then UNAVAILABLE retry")

    ran = []
    monkeypatch.setitem(bench.BENCHES, "a", flaky)
    monkeypatch.setitem(
        bench.BENCHES, "b",
        lambda: ran.append(1) or {"metric": "b", "value": 1,
                                  "unit": "x", "vs_baseline": 1})
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 1  # a failed, but b still ran
    assert ran
    out = capsys.readouterr().out
    assert '"metric": "b"' in out


def test_isolated_mode_survives_a_hung_bench(monkeypatch, capsys):
    """Default (isolated) mode: a bench that HANGS -- the failure mode
    no in-process machinery can interrupt -- costs its own timeout,
    becomes an error record, and when the backend probe still passes,
    the remaining benches run."""
    monkeypatch.setenv("RLA_TPU_BENCH_SELFTEST", "1")
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "print('PROBE_OK 1.0 fake')")  # probe stays alive
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--benches",
                         "selftest-hang,selftest",
                         "--probe-timeout", "5", "--bench-timeout", "3"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 1  # hang recorded as failure; selftest ran
    lines = [json.loads(ln) for ln
             in capsys.readouterr().out.splitlines() if ln.strip()]
    by_metric = {r["metric"]: r for r in lines}
    assert by_metric["selftest-hang"]["error"] == "bench timed out"
    assert by_metric["selftest"]["value"] == 1


def test_isolated_mode_death_still_emits_cpu_fallback(monkeypatch,
                                                      capsys):
    """Mid-run backend death in the DEFAULT (isolated) mode: the child's
    death record passes through, later benches stop -- and the CPU-mesh
    fallbacks still land real metric lines, so the window exits 0 and
    the driver records numbers (pre-flight probe alone does not protect
    a backend that dies after it passed)."""
    monkeypatch.setenv("RLA_TPU_BENCH_SELFTEST", "1")
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "print('PROBE_OK 1.0 fake')")  # pre-flight passes
    monkeypatch.setattr(
        bench, "bench_gradexchange",
        lambda: {"metric": "gradexchange_int8_wire_bytes_reduction",
                 "value": 3.9, "unit": "x", "vs_baseline": 0.98})
    monkeypatch.setattr(
        bench, "bench_input_pipeline",
        lambda: {"metric": "input_pipeline_prefetch_speedup",
                 "value": 1.8, "unit": "x", "vs_baseline": 1.2})
    monkeypatch.setattr(
        bench, "bench_fsdp_exchange",
        lambda: {"metric": "fsdp_exchange_int8_wire_bytes_reduction",
                 "value": 2.65, "unit": "x", "vs_baseline": 1.0})
    monkeypatch.setattr(
        bench, "bench_paged_serve",
        lambda: {"metric": "paged_serve_concurrency_per_hbm_ratio",
                 "value": 3.9, "unit": "x", "vs_baseline": 2.6})
    monkeypatch.setattr(
        bench, "bench_mfu_overlap",
        lambda: {"metric": "mfu_overlap_scan_vs_tree_step_time_ratio",
                 "value": 1.3, "unit": "x", "vs_baseline": 1.3})
    monkeypatch.setattr(
        bench, "bench_perf_observatory",
        lambda: {"metric": "perf_observatory_phase_coverage",
                 "value": 0.97, "unit": "fraction", "vs_baseline": 1.13})
    monkeypatch.setattr(
        bench, "bench_live_plane",
        lambda: {"metric": "live_plane_scrape_validity",
                 "value": 1.0, "unit": "fraction", "vs_baseline": 1.0})
    monkeypatch.setattr(
        bench, "bench_serve_resilience",
        lambda: {"metric": "serve_resilience_completed_fraction",
                 "value": 1.0, "unit": "fraction", "vs_baseline": 1.0})
    monkeypatch.setattr(
        bench, "bench_resize",
        lambda: {"metric": "resize_inmem_vs_ckpt_downtime_ratio",
                 "value": 3.7, "unit": "x", "vs_baseline": 1.16})
    monkeypatch.setattr(
        bench, "bench_prefix_affinity",
        lambda: {"metric": "prefix_affinity_ttft_ratio",
                 "value": 3.1, "unit": "ratio", "vs_baseline": 3.1})
    monkeypatch.setattr(
        bench, "bench_long_context",
        lambda: {"metric": "long_context_cadence_ratio",
                 "value": 2.6, "unit": "ratio", "vs_baseline": 2.6})
    monkeypatch.setattr(
        bench, "bench_anomaly_guard",
        lambda: {"metric": "anomaly_guard_overhead_ratio",
                 "value": 1.01, "unit": "ratio", "vs_baseline": 1.01})
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--benches", "selftest-dead,selftest",
                         "--probe-timeout", "5"])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0  # fallback metrics landed next to the record
    lines = [json.loads(ln) for ln
             in capsys.readouterr().out.splitlines() if ln.strip()]
    metrics = [r["metric"] for r in lines]
    assert "gradexchange_int8_wire_bytes_reduction" in metrics
    assert "input_pipeline_prefetch_speedup" in metrics
    assert "fsdp_exchange_int8_wire_bytes_reduction" in metrics
    assert "paged_serve_concurrency_per_hbm_ratio" in metrics
    assert "mfu_overlap_scan_vs_tree_step_time_ratio" in metrics
    assert "perf_observatory_phase_coverage" in metrics
    assert "live_plane_scrape_validity" in metrics
    assert "serve_resilience_completed_fraction" in metrics
    assert "resize_inmem_vs_ckpt_downtime_ratio" in metrics
    assert "prefix_affinity_ttft_ratio" in metrics
    assert "long_context_cadence_ratio" in metrics
    assert "anomaly_guard_overhead_ratio" in metrics
    assert any(r.get("error") == "backend died mid-run" for r in lines)
    assert "selftest" not in metrics  # nothing ran after the death


def test_isolated_mode_passes_through_child_records(monkeypatch,
                                                    capsys):
    monkeypatch.setenv("RLA_TPU_BENCH_SELFTEST", "1")
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "print('PROBE_OK 1.0 fake')")
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--benches", "selftest",
                         "--probe-timeout", "5"])
    try:
        bench.main()
        code = 0
    except SystemExit as e:
        code = e.code
    assert code == 0
    rec = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert rec == {"metric": "selftest", "value": 1, "unit": "ok",
                   "vs_baseline": 1.0}


def test_last_metric_record_skips_compile_count_lines():
    # probes print a bench-honesty compile-count record alongside the
    # metric; whichever order they land in, the bench result must be the
    # record that actually carries a value
    metric = {"metric": "wire_bytes", "value": 3.9, "unit": "x",
              "vs_baseline": 0.98}
    compile_rec = {"probe": "gradexchange", "kind": "compile_count",
                   "total_compiles": 7}
    out = "\n".join(["warmup chatter",
                     json.dumps(metric),
                     json.dumps(compile_rec)])
    assert bench._last_metric_record(out) == metric
    out = "\n".join([json.dumps(compile_rec), json.dumps(metric)])
    assert bench._last_metric_record(out) == metric
    # no metric record at all: newest JSON line still surfaces (error
    # records), and pure chatter yields None
    assert bench._last_metric_record(
        json.dumps(compile_rec))["kind"] == "compile_count"
    assert bench._last_metric_record("no json here") is None


def test_last_metric_record_survives_telemetry_snapshot_line():
    """Probes now end with a ``kind="telemetry"`` MetricsRegistry
    snapshot (PR 7).  It is value-less by contract, so the newest
    VALUE-BEARING line — the real metric — still wins the parse in
    either print order (the PR 6 contract, re-pinned against the new
    line)."""
    metric = {"metric": "serve_throughput", "value": 120.5, "unit":
              "tok/s", "vs_baseline": 1.1}
    compile_rec = {"probe": "serve", "kind": "compile_count",
                   "total_backend_compiles": 9}
    telemetry_rec = {"probe": "serve", "kind": "telemetry",
                     "snapshot": {"spans": {}, "counters": {"x": 1},
                                  "compile": {"total_backend_compiles": 9}}}
    # value-bearing metric: it wins regardless of print order
    out = "\n".join(json.dumps(r) for r in
                    (metric, compile_rec, telemetry_rec))
    assert bench._last_metric_record(out) == metric
    # gradexchange-style order: bookkeeping first, metric last
    out = "\n".join(json.dumps(r) for r in
                    (compile_rec, telemetry_rec, metric))
    assert bench._last_metric_record(out) == metric
    # the REAL serve metric record has no "value" key — it only wins by
    # POSITION, which is why serve_probe prints it last (pinned here
    # with the actual record shape, not a value-bearing stand-in)
    serve_metric = {"probe": "serve", "requests": 16,
                    "throughput_tok_s": 120.5, "steps": 40}
    out = "\n".join(json.dumps(r) for r in
                    (compile_rec, telemetry_rec, serve_metric))
    assert bench._last_metric_record(out) == serve_metric
    # a window that died before the metric: the telemetry record may be
    # the fallback surfaced, never mistaken for a value
    rec = bench._last_metric_record(json.dumps(telemetry_rec))
    assert rec["kind"] == "telemetry" and "value" not in rec
