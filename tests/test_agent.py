"""Multi-machine launch path: per-host agents + RemoteWorkers.

Behavioral analog of the reference's multi-node capability (reference:
README.md:57-62 -- cluster fan-out; ray_lightning/ray_ddp.py:92-97 actor
placement on remote nodes; tests/test_ddp_gpu.py:106-117 the opt-in
multi-node test).  Two HostAgents on localhost stand in for two machines:
every byte between driver and worker crosses a real TCP socket, so the
same code path serves genuinely remote hosts.
"""

import os
import time

import numpy as np
import pytest

from ray_lightning_accelerators_tpu.runtime.actors import (ActorPool,
                                                           RemoteError)
from ray_lightning_accelerators_tpu.runtime.agent import (HostAgent,
                                                          RemoteWorker,
                                                          assign_agents,
                                                          coordinator_address_on)
from ray_lightning_accelerators_tpu.runtime.queue import (QueueClient,
                                                          QueueServer,
                                                          TrampolineQueue)


@pytest.fixture()
def two_agents():
    agents = [HostAgent(port=0, bind="127.0.0.1") for _ in range(2)]
    for a in agents:
        a.serve_in_background()
    yield [f"127.0.0.1:{a.port}" for a in agents]
    for a in agents:
        a.shutdown()


def _pid():
    return os.getpid()


def _sq(x):
    return x * x


def _getenv(k):
    return os.environ.get(k)


def _boom():
    raise ValueError("remote worker exploded")


def _die():
    os._exit(13)


def test_remote_worker_executes(two_agents):
    w = RemoteWorker(two_agents[0], rank=0, env={"RLA_AGENT_T": "x"})
    try:
        assert w.execute(_sq, 6).result(timeout=60) == 36
        assert w.execute(_getenv, "RLA_AGENT_T").result(timeout=60) == "x"
        assert w.is_alive
        assert w.get_node_ip()  # resolves without error
    finally:
        w.shutdown()


def test_remote_error_carries_traceback(two_agents):
    w = RemoteWorker(two_agents[0], rank=0)
    try:
        with pytest.raises(RemoteError, match="remote worker exploded"):
            w.execute(_boom).result(timeout=60)
        # the worker survives an exception and keeps serving
        assert w.execute(_sq, 3).result(timeout=60) == 9
    finally:
        w.shutdown()


def test_remote_worker_death_fails_future_and_restarts(two_agents):
    w = RemoteWorker(two_agents[0], rank=0)
    try:
        with pytest.raises(RuntimeError, match="died"):
            w.execute(_die).result(timeout=60)
        deadline = time.time() + 10
        while w.is_alive and time.time() < deadline:
            time.sleep(0.05)
        assert not w.is_alive
        w.restart()
        assert w.execute(_sq, 4).result(timeout=60) == 16
    finally:
        w.shutdown()


def test_pool_over_agents_places_block_per_agent(two_agents):
    with ActorPool(4, agents=two_agents) as pool:
        pids = [f.result(timeout=60) for f in pool.execute_all(_pid)]
        assert len(set(pids)) == 4
        assert all(p != os.getpid() for p in pids)
        # contiguous block assignment: workers 0,1 -> agent 0; 2,3 -> agent 1
        addrs = [w.address for w in pool.workers]
        assert addrs == [two_agents[0], two_agents[0],
                         two_agents[1], two_agents[1]]
        assert pool.local_ranks() == [0, 1, 2, 3]  # same IP on localhost


def test_assign_agents_uneven_balanced():
    # heterogeneous layouts place like the reference's resource-driven
    # scheduling (reference: ray_ddp.py:92-97): 3 over 2 hosts -> 2+1
    assert assign_agents(["a:1", "b:2"], 3) == ["a:1", "a:1", "b:2"]
    assert assign_agents(["a:1", "b:2", "c:3"], 1) == ["a:1"]
    assert assign_agents(["a:1", "b:2"], 4) == ["a:1", "a:1", "b:2", "b:2"]


def test_assign_agents_explicit_counts():
    assert assign_agents(["a:1*1", "b:2*3"], 4) == \
        ["a:1", "b:2", "b:2", "b:2"]
    with pytest.raises(ValueError, match="sum to"):
        assign_agents(["a:1*1", "b:2*1"], 4)
    with pytest.raises(ValueError, match="mix"):
        assign_agents(["a:1*1", "b:2"], 2)


def test_agent_auth_handshake(monkeypatch):
    from ray_lightning_accelerators_tpu.runtime.agent import (
        TOKEN_ENV, AgentConnection)

    monkeypatch.delenv(TOKEN_ENV, raising=False)
    agent = HostAgent(port=0, bind="127.0.0.1", token="s3cret")
    agent.serve_in_background()
    addr = f"127.0.0.1:{agent.port}"
    try:
        # no token: the connection is dropped BEFORE the agent unpickles
        # anything (unpickling an untrusted frame would itself be the
        # RCE).  Depending on when the RST lands relative to the first
        # op's send, the drop surfaces as "lost connection", "connection
        # closed", or a plain socket error -- all are the refusal.
        refusal = ("lost connection|connection closed|unreachable|"
                   "Broken pipe|reset")
        with pytest.raises(Exception, match=refusal):
            RemoteWorker(addr, rank=0)
        # wrong token: dropped the same way; surfaces on the first op
        with pytest.raises(Exception, match=refusal):
            AgentConnection(addr, token="wrong").call("ping", timeout=10)
        # right token (picked up from the env like `rla-tpu launch` does)
        monkeypatch.setenv(TOKEN_ENV, "s3cret")
        w = RemoteWorker(addr, rank=0)
        try:
            assert w.execute(_sq, 5).result(timeout=60) == 25
        finally:
            w.shutdown()
    finally:
        agent.shutdown()


def test_tokened_client_talks_to_open_agent(two_agents, monkeypatch):
    # a driver with RLA_TPU_AGENT_TOKEN exported must still work against
    # an agent that requires none (the auth frame is accepted + ignored)
    from ray_lightning_accelerators_tpu.runtime.agent import TOKEN_ENV

    monkeypatch.setenv(TOKEN_ENV, "extra")
    w = RemoteWorker(two_agents[0], rank=0)
    try:
        assert w.execute(_sq, 7).result(timeout=60) == 49
    finally:
        w.shutdown()


def test_queue_server_auth(monkeypatch):
    from ray_lightning_accelerators_tpu.runtime.agent import TOKEN_ENV

    monkeypatch.setenv(TOKEN_ENV, "qtok")
    q = TrampolineQueue()
    server = QueueServer(q)
    _SEEN.clear()
    try:
        client = QueueClient(server.address)  # env token -> accepted
        client.put((1, _remote_mark))
        client.flush()
        rank, thunk = q.get_nowait()
        thunk()
        assert rank == 1 and _SEEN == ["remote"]
        client.shutdown()

        monkeypatch.setenv(TOKEN_ENV, "wrong")
        bad = QueueClient(server.address)
        with pytest.raises((ConnectionError, OSError)):
            bad.put((2, _remote_mark))
            bad.flush()  # server dropped the connection; the ack never comes
        bad.shutdown()
        assert q.empty()
    finally:
        server.close()


def test_queue_server_without_token_skips_auth_frame(monkeypatch):
    # workers inherit the agent host's token env even when the driver has
    # none; the token-less server must skip (not enqueue!) the auth frame
    from ray_lightning_accelerators_tpu.runtime.agent import TOKEN_ENV

    monkeypatch.delenv(TOKEN_ENV, raising=False)
    q = TrampolineQueue()
    server = QueueServer(q)
    _SEEN.clear()
    try:
        monkeypatch.setenv(TOKEN_ENV, "worker-side-token")
        client = QueueClient(server.address)  # sends the auth frame
        client.put((4, _remote_mark))
        client.flush()
        rank, thunk = q.get_nowait()
        thunk()
        assert rank == 4 and _SEEN == ["remote"]
        client.shutdown()
    finally:
        server.close()


def test_coordinator_address_on_agent_host(two_agents):
    coord = coordinator_address_on(two_agents[0])
    host, port = coord.rsplit(":", 1)
    assert host and 0 < int(port) < 65536


def _remote_mark():
    # module-global so the cloudpickled thunk resolves it by reference in
    # the receiving process (a closed-over local would arrive as a copy)
    _SEEN.append("remote")


def test_queue_crosses_the_network():
    q = TrampolineQueue()
    server = QueueServer(q)
    _SEEN.clear()
    try:
        client = QueueClient(server.address)
        client.put((3, _remote_mark))
        deadline = time.time() + 10
        while q.empty() and time.time() < deadline:
            time.sleep(0.01)
        rank, thunk = q.get_nowait()
        thunk()
        assert rank == 3 and _SEEN == ["remote"]
        client.shutdown()
    finally:
        server.close()


def test_pool_env_and_health_over_agents(two_agents):
    with ActorPool(2, env_per_worker=[{"RLA_HOSTV": "h0"},
                                      {"RLA_HOSTV": "h1"}],
                   agents=two_agents) as pool:
        vals = [f.result(timeout=60)
                for f in pool.execute_all(_getenv, "RLA_HOSTV")]
        assert vals == ["h0", "h1"]
        assert pool.health_check() == [True, True]


# ------------------------------------------------------------------ #
# End-to-end distributed launches through agents (slow)               #
# ------------------------------------------------------------------ #
def _distributed_psum_agent(process_id):
    import jax
    import jax.numpy as jnp
    from ray_lightning_accelerators_tpu.parallel.sharding import (
        shard_map_compat)

    assert jax.process_count() == 2
    out = shard_map_compat(
        lambda x: jax.lax.psum(x, "i"),
        mesh=jax.sharding.Mesh(jax.devices(), ("i",)),
        in_specs=jax.sharding.PartitionSpec("i"),
        out_specs=jax.sharding.PartitionSpec())(jnp.arange(2.0))
    return float(np.asarray(out)[0])


@pytest.mark.slow
def test_launch_distributed_through_agents(two_agents):
    """launch_distributed(agents=...) forms a REAL 2-process
    jax.distributed world with one worker per 'host'."""
    from ray_lightning_accelerators_tpu.runtime.bootstrap import (
        launch_distributed)

    results = launch_distributed(
        _distributed_psum_agent, num_processes=2, platform="cpu",
        cpu_devices_per_process=1,
        env={"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""},
        agents=two_agents)
    assert results == [1.0, 1.0]


def _distributed_fit_agent(process_id):
    import jax
    import numpy as np
    from ray_lightning_accelerators_tpu import DataLoader, Trainer
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.runtime import session as session_lib
    from tests.utils import BoringModel

    # device-binding contract (the reference pins the device/env mapping,
    # reference: tests/test_ddp_gpu.py:89-95): each process sees exactly
    # its devices, and the global view spans both processes
    assert len(jax.local_devices()) == 2
    assert jax.device_count() == 4
    assert jax.process_index() == process_id

    # the trampoline session reaches the driver over the network; a
    # partial of a module-level function pickles BY REFERENCE, so the
    # executed thunk mutates the DRIVER's module globals (a lambda would
    # pickle by value and mutate a copy)
    import functools
    session_lib.put_queue(functools.partial(_mark_rank, process_id))

    x = np.random.default_rng(0).normal(size=(64, 32)).astype("float32")
    model = BoringModel()
    trainer = Trainer(max_epochs=2, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=f"/tmp/agent_fit_{process_id}")
    trainer.fit(model, DataLoader(ArrayDataset(x), batch_size=8))
    leaf = np.asarray(jax.tree.leaves(model.params)[0], dtype=np.float64)
    return (trainer.global_step, float(leaf.sum()),
            float(trainer.callback_metrics["loss"]))


_SEEN: list = []  # driver-side sink for trampolined thunks


def _mark_rank(pid):
    _SEEN.append(pid)


@pytest.mark.slow
def test_full_fit_through_agents(two_agents):
    """A complete Trainer.fit across two agent-hosted processes: sampler
    shards per process, gradient psum crosses the (local) network, both
    ranks agree on steps and final weights, and worker thunks reach the
    driver queue."""
    from ray_lightning_accelerators_tpu.runtime.bootstrap import (
        launch_distributed)

    _SEEN.clear()
    q = TrampolineQueue()
    results = launch_distributed(
        _distributed_fit_agent, num_processes=2, platform="cpu",
        cpu_devices_per_process=2,
        env={"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""},
        agents=two_agents, queue=q)
    steps0, wsum0, loss0 = results[0]
    steps1, wsum1, loss1 = results[1]
    assert steps0 == steps1 == 8  # 64 / 2 replicas / batch 8 x 2 epochs
    assert wsum0 == pytest.approx(wsum1, rel=1e-6)
    assert loss0 == pytest.approx(loss1, rel=1e-5)
    assert sorted(_SEEN) == [0, 1]  # one thunk per rank reached the driver


def _distributed_cached_fit_agent(cache, process_id):
    import jax
    import numpy as np
    from ray_lightning_accelerators_tpu import DataLoader, Trainer
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from tests.utils import BoringModel

    x = np.random.default_rng(3).standard_normal((64, 32)).astype("float32")
    model = BoringModel()
    trainer = Trainer(max_epochs=2, precision="f32", seed=0,
                      enable_checkpointing=False,
                      cache_dataset_on_device=cache,
                      log_every_n_steps=10 ** 9,
                      default_root_dir=f"/tmp/cached_fit_{cache}_{process_id}")
    trainer.fit(model, DataLoader(ArrayDataset(x), batch_size=8,
                                  shuffle=True))
    used_cache = trainer._device_cache is not None
    used_scan = trainer._epoch_scan_fn is not None
    leaf = np.asarray(jax.tree.leaves(model.params)[0], dtype=np.float64)
    return (used_cache, used_scan, trainer.global_step, float(leaf.sum()))


@pytest.mark.slow
def test_cached_fit_matches_host_fed_through_agents(two_agents):
    """The device cache + whole-epoch scan run under a REAL 2-process world
    (round-2 gap: the fast path and the multi-host path were disjoint
    code); the cached multi-process fit must match the host-fed one."""
    import functools

    from ray_lightning_accelerators_tpu.runtime.bootstrap import (
        launch_distributed)

    env = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""}
    host = launch_distributed(
        functools.partial(_distributed_cached_fit_agent, False),
        num_processes=2, platform="cpu", cpu_devices_per_process=2,
        env=env, agents=two_agents)
    cached = launch_distributed(
        functools.partial(_distributed_cached_fit_agent, True),
        num_processes=2, platform="cpu", cpu_devices_per_process=2,
        env=env, agents=two_agents)
    assert [r[0] for r in host] == [False, False]
    assert [r[0] for r in cached] == [True, True]
    assert [r[1] for r in cached] == [True, True]  # epoch scan compiled
    assert cached[0][2] == host[0][2] == 8  # same step count
    # both ranks agree, and cached == host-fed on final weights
    assert cached[0][3] == pytest.approx(cached[1][3], rel=1e-6)
    assert cached[0][3] == pytest.approx(host[0][3], rel=1e-5)


def _worker_topology_probe(process_id):
    """Inside a 2-process world, a mismatched num_hosts must raise."""
    from ray_lightning_accelerators_tpu import (HorovodRayAccelerator,
                                                Trainer, DataLoader)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from tests.utils import BoringModel
    import numpy as np
    import pytest as pt

    x = np.zeros((16, 32), dtype="float32")
    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      enable_checkpointing=False,
                      accelerator=HorovodRayAccelerator(num_hosts=3,
                                                        num_slots=1),
                      default_root_dir=f"/tmp/topo_probe_{process_id}")
    with pt.raises(ValueError, match="num_hosts=3"):
        trainer.fit(BoringModel(),
                    DataLoader(ArrayDataset(x), batch_size=8))
    return "raised"


@pytest.mark.slow
def test_num_hosts_mismatch_raises_in_distributed_world(two_agents):
    from ray_lightning_accelerators_tpu.runtime.bootstrap import (
        launch_distributed)

    results = launch_distributed(
        _worker_topology_probe, num_processes=2, platform="cpu",
        cpu_devices_per_process=1,
        env={"JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
             "RLA_TPU_INSIDE_WORKER": "1"},
        agents=two_agents)
    assert results == ["raised", "raised"]


@pytest.mark.slow
def test_driver_mode_fit_through_agents(two_agents, tmp_path):
    """The reference's headline flow, multi-machine: the DRIVER calls
    trainer.fit once; the framework fans out one process per host agent,
    trains SPMD across them, and re-hydrates rank-0 weights + metrics into
    the driver's module (reference: ray_lightning/ray_ddp.py:169-193)."""
    import numpy as np
    from ray_lightning_accelerators_tpu import (HorovodRayAccelerator,
                                                Trainer, DataLoader)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from tests.utils import BoringModel

    x = np.random.default_rng(0).normal(size=(64, 32)).astype("float32")
    model = BoringModel()
    assert model.params is None
    trainer = Trainer(max_epochs=4, precision="f32", seed=0,
                      enable_checkpointing=False,
                      accelerator=HorovodRayAccelerator(
                          num_hosts=2, num_slots=2, agents=two_agents),
                      default_root_dir=str(tmp_path))
    trainer.fit(model, DataLoader(ArrayDataset(x), batch_size=8))

    # rank-0 state re-hydrated into the driver's objects
    assert trainer.global_step == 16  # 64 / 2 procs / batch 8 x 4 epochs
    assert trainer.epochs_completed == 4
    assert "loss" in trainer.callback_metrics
    assert model.params is not None
    # weights really trained: loss at re-hydrated params beats init,
    # and the model is directly usable driver-side
    out = np.asarray(model.forward(model.params, x[:4]))
    assert out.shape == (4, 2)
    assert float(np.mean((out - 1.0) ** 2)) < 1.0  # moved toward target


@pytest.mark.slow
def test_distributed_eval_through_agents(two_agents, tmp_path):
    """trainer.test / predict with num_hosts=2 fan out through the agents
    (the reference's fit/test multi-call contract, reference:
    README.md:34-36) and match a single-process run on the SAME params."""
    import jax
    from ray_lightning_accelerators_tpu import (HorovodRayAccelerator,
                                                Trainer, DataLoader)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from tests.utils import BoringModel

    x = np.random.default_rng(1).normal(size=(64, 32)).astype("float32")

    def loader():
        return DataLoader(ArrayDataset(x), batch_size=8, shuffle=False)

    # single-process baseline on fixed params
    model = BoringModel()
    model.params = jax.tree.map(np.asarray,
                                model.init_params(jax.random.key(7)))
    t_local = Trainer(max_epochs=1, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmp_path / "local"))
    local_metrics = t_local.test(model, loader())[0]
    local_preds = np.concatenate(
        [np.asarray(o) for o in t_local.predict(model, loader())])

    # the same params, evaluated through two agent-hosted processes
    model2 = BoringModel()
    model2.params = jax.tree.map(np.asarray,
                                 model.init_params(jax.random.key(7)))
    t_dist = Trainer(max_epochs=1, precision="f32", seed=0,
                     enable_checkpointing=False,
                     accelerator=HorovodRayAccelerator(
                         num_hosts=2, num_slots=2, agents=two_agents),
                     default_root_dir=str(tmp_path / "dist"))
    dist_metrics = t_dist.test(model2, loader())[0]
    assert set(dist_metrics) == set(local_metrics)
    for k, v in local_metrics.items():
        assert dist_metrics[k] == pytest.approx(v, rel=1e-5), k
    # metrics re-hydrated driver-side (BoringModel.test_step logs "y")
    assert t_dist.callback_metrics["y"] == pytest.approx(
        local_metrics["y"], rel=1e-5)

    dist_preds = np.concatenate(
        [np.asarray(o) for o in t_dist.predict(model2, loader())])
    np.testing.assert_allclose(dist_preds, local_preds, rtol=1e-5,
                               atol=1e-6)


@pytest.mark.slow
def test_world_persists_across_entry_points(tmp_path):
    """fit -> test -> fit through the same agents reuses ONE persistent
    world: each agent spawns its worker exactly once for the whole span
    (the reference's actors live setup -> teardown and serve every stage,
    reference: ray_lightning/ray_ddp.py:99-121), and the same worker
    process (same pid) serves every entry point."""
    from ray_lightning_accelerators_tpu import (Callback, DataLoader,
                                                HorovodRayAccelerator,
                                                Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from tests.utils import BoringModel

    class PidCb(Callback):
        def on_fit_end(self, trainer, module):
            trainer.callback_metrics["worker_pid"] = float(os.getpid())

        def on_test_end(self, trainer, module):
            trainer.callback_metrics["worker_pid"] = float(os.getpid())

    agents = [HostAgent(port=0, bind="127.0.0.1") for _ in range(2)]
    for a in agents:
        a.serve_in_background()
    addrs = [f"127.0.0.1:{a.port}" for a in agents]
    try:
        x = np.random.default_rng(0).normal(size=(64, 32)).astype("float32")

        def loader():
            return DataLoader(ArrayDataset(x), batch_size=8, shuffle=False)

        model = BoringModel()
        trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                          enable_checkpointing=False, callbacks=[PidCb()],
                          accelerator=HorovodRayAccelerator(
                              num_hosts=2, num_slots=1, agents=addrs),
                          default_root_dir=str(tmp_path))
        trainer.fit(model, loader())
        fit_pid = trainer.callback_metrics["worker_pid"]
        trainer.test(model, loader())
        test_pid = trainer.callback_metrics["worker_pid"]
        trainer.fit(model, loader())  # refit reuses the world too
        refit_pid = trainer.callback_metrics["worker_pid"]

        assert fit_pid == test_pid == refit_pid  # same rank-0 process
        # one spawn per rank EVER, not per entry point
        assert sum(a.spawn_count for a in agents) == 2
        assert [a.spawn_count for a in agents] == [1, 1]
        # the dataset shipped ONCE: later entry points over byte-identical
        # loaders hit the worker-side content cache
        stats = trainer._world.ship_stats
        assert stats["sent"] >= 1
        assert stats["reused"] >= 1, stats

        # full teardown() ends the world too (the reference's teardown
        # ends its actors, ray_ddp.py:109-121); a fresh entry point after
        # it builds a new world rather than dispatching into a dead one
        world = trainer._world
        trainer.teardown()
        assert trainer._world is None
        assert world.pool is None  # shut down, not leaked
    finally:
        for a in agents:
            a.shutdown()


@pytest.mark.slow
def test_unreachable_agent_leaves_driver_intact(tmp_path, monkeypatch):
    """An unreachable agent fails the fan-out BEFORE the driver's
    module/trainer are stripped for shipment: the module stays bound and
    trainable locally afterwards (round-3 weak #3)."""
    import socket as socket_mod

    from ray_lightning_accelerators_tpu import (DataLoader,
                                                HorovodRayAccelerator,
                                                Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from tests.utils import BoringModel

    monkeypatch.setenv("RLA_TPU_AGENT_CONNECT_TIMEOUT", "2")
    live = HostAgent(port=0, bind="127.0.0.1")
    live.serve_in_background()
    # a port with no listener: refused instantly, retried ~2s, then raises
    probe = socket_mod.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()
    addrs = [f"127.0.0.1:{live.port}", f"127.0.0.1:{dead_port}"]
    try:
        x = np.random.default_rng(0).normal(size=(64, 32)).astype("float32")

        def loader():
            return DataLoader(ArrayDataset(x), batch_size=8, shuffle=False)

        model = BoringModel()
        trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                          enable_checkpointing=False,
                          accelerator=HorovodRayAccelerator(
                              num_hosts=2, num_slots=1, agents=addrs),
                          default_root_dir=str(tmp_path / "dist"))
        with pytest.raises(Exception):
            trainer.fit(model, loader())

        # nothing was stripped mid-flight: a plain local fit on the same
        # module works
        local = Trainer(max_epochs=1, precision="f32", seed=0,
                        enable_checkpointing=False,
                        default_root_dir=str(tmp_path / "local"))
        local.fit(model, loader())
        assert local.global_step > 0
        assert model.params is not None
    finally:
        live.shutdown()


@pytest.mark.slow
def test_dead_world_respawns_on_next_entry_point(two_agents, tmp_path):
    """A worker process dying between entry points poisons the world; the
    next entry point detects it (world.alive() False) and respawns a
    fresh one instead of dispatching into dead processes."""
    from ray_lightning_accelerators_tpu import (DataLoader,
                                                HorovodRayAccelerator,
                                                Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from tests.utils import BoringModel

    x = np.random.default_rng(0).normal(size=(64, 32)).astype("float32")

    def loader():
        return DataLoader(ArrayDataset(x), batch_size=8, shuffle=False)

    model = BoringModel()
    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      enable_checkpointing=False,
                      accelerator=HorovodRayAccelerator(
                          num_hosts=2, num_slots=1, agents=two_agents),
                      default_root_dir=str(tmp_path))
    trainer.fit(model, loader())
    world = trainer._world
    assert world is not None and world.alive()
    world.pool.workers[1].kill()  # simulate a crash between entry points
    deadline = time.time() + 10
    while world.alive() and time.time() < deadline:
        time.sleep(0.05)
    assert not world.alive()

    metrics = trainer.test(model, loader())[0]  # respawns transparently
    assert metrics
    assert trainer._world is not world and trainer._world.alive()
    trainer.shutdown_workers()


def test_single_host_agent_fans_out(tmp_path):
    """num_hosts=1 WITH an agent configured still fans out -- "run my
    training on that one (possibly remote, chip-holding) host" is the
    single-host analog of the reference placing its one actor wherever
    the resources are (reference: ray_ddp.py:92-97).  Previously
    launch_spec() silently ignored explicit agents when num_hosts <= 1.
    This is also the exact layout of the on-chip world gate
    (test_tpu_world.py) with a CPU worker standing in for the chip."""
    from ray_lightning_accelerators_tpu import (Callback, DataLoader,
                                                HorovodRayAccelerator,
                                                Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from tests.utils import BoringModel

    class PidCb(Callback):
        def on_fit_end(self, trainer, module):
            trainer.callback_metrics["worker_pid"] = float(os.getpid())

    agent = HostAgent(port=0, bind="127.0.0.1")
    agent.serve_in_background()
    try:
        x = np.random.default_rng(0).normal(size=(64, 32)).astype(
            "float32")

        def loader():
            return DataLoader(ArrayDataset(x), batch_size=8,
                              shuffle=False)

        model = BoringModel()
        trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                          enable_checkpointing=False, callbacks=[PidCb()],
                          accelerator=HorovodRayAccelerator(
                              num_hosts=1, num_slots=1,
                              agents=[f"127.0.0.1:{agent.port}"]),
                          default_root_dir=str(tmp_path))
        trainer.fit(model, loader())
        assert trainer.callback_metrics["worker_pid"] != float(os.getpid())
        assert model.params is not None
        preds = trainer.predict(model, loader())
        assert sum(np.shape(p)[0] for p in preds) == len(x)
        assert agent.spawn_count == 1  # one persistent worker, reused
        trainer.teardown()
    finally:
        agent.shutdown()


def test_queue_server_binds_loopback_by_default():
    """Without remote agents in play the trampoline endpoint must not
    open a network-reachable port (round-3 advisor finding: thunks
    EXECUTE driver-side)."""
    q = TrampolineQueue()
    server = QueueServer(q)
    try:
        assert server.address.startswith("127.0.0.1:")
    finally:
        server.close()


def test_host_agent_refuses_tokenless_wide_bind(monkeypatch):
    """Agents execute arbitrary thunks as this user -- the QueueServer's
    tokenless-wide-bind refusal applies to them identically."""
    monkeypatch.delenv("RLA_TPU_AGENT_TOKEN", raising=False)
    monkeypatch.delenv("RLA_TPU_ALLOW_TOKENLESS_BIND", raising=False)
    with pytest.raises(RuntimeError, match="RLA_TPU_AGENT_TOKEN"):
        HostAgent(port=0, bind="0.0.0.0")
    # a token makes the wide bind legitimate
    agent = HostAgent(port=0, bind="0.0.0.0", token="s3cret")
    agent.shutdown()
    # ... as does the explicit opt-out
    monkeypatch.setenv("RLA_TPU_ALLOW_TOKENLESS_BIND", "1")
    agent = HostAgent(port=0, bind="0.0.0.0")
    agent.shutdown()


def test_queue_server_refuses_tokenless_wide_bind(monkeypatch):
    """An unauthenticated 0.0.0.0 bind is an RCE surface (queued frames
    are unpickled and executed driver-side): without RLA_TPU_AGENT_TOKEN
    the server must refuse, not warn-and-proceed (round-4 advisor
    finding) -- unless the explicit opt-out is set."""
    monkeypatch.delenv("RLA_TPU_AGENT_TOKEN", raising=False)
    monkeypatch.delenv("RLA_TPU_ALLOW_TOKENLESS_BIND", raising=False)
    with pytest.raises(RuntimeError, match="RLA_TPU_AGENT_TOKEN"):
        QueueServer(TrampolineQueue(), bind="0.0.0.0")
    monkeypatch.setenv("RLA_TPU_ALLOW_TOKENLESS_BIND", "1")
    server = QueueServer(TrampolineQueue(), bind="0.0.0.0")
    server.close()


def test_queue_bind_for_agents_stays_loopback_for_local_agents():
    """Single-machine agent setups (every agent on 127.x) keep the
    trampoline on loopback; any non-loopback agent needs the wide bind
    (and then the tokenless refusal above applies)."""
    from ray_lightning_accelerators_tpu.runtime.agent import \
        queue_bind_for_agents
    assert queue_bind_for_agents(None) is None
    assert queue_bind_for_agents([]) is None
    assert queue_bind_for_agents(["127.0.0.1:7777", "localhost:7778*2"]) \
        is None
    assert queue_bind_for_agents(["127.0.0.1:7777", "10.0.0.5:7777"]) \
        == "0.0.0.0"


def _hang_remote():
    import time
    time.sleep(10_000)


def test_remote_worker_heartbeat_and_wedge_reap(two_agents):
    """Watchdog parity over the wire: heartbeat snapshots are taken
    agent-side (only ages cross the network), a wedged remote rank is
    reaped through the agent, and its future fails with the TYPED
    WorkerWedged -- diagnosis intact -- after crossing the relay as
    (name, message, tb)."""
    from ray_lightning_accelerators_tpu.runtime.watchdog import (Watchdog,
                                                                 WorkerWedged)
    w = RemoteWorker(two_agents[0], rank=0,
                     env={"RLA_TPU_WORKER_HEARTBEAT_S": "0.05"})
    wd = None
    try:
        assert w.execute(_sq, 3).result(timeout=60) == 9
        snap = w.heartbeat.snapshot()
        assert snap is not None
        assert snap["started"]
        assert snap["dispatches"] == 1
        fut = w.execute(_hang_remote)
        wd = Watchdog([w], wedge_timeout_s=30.0, dispatch_deadline_s=0.4,
                      poll_s=0.05).start()
        with pytest.raises(WorkerWedged) as ei:
            fut.result(timeout=120)
        assert ei.value.rank == 0
        assert "deadline" in ei.value.diagnosis["detail"]
        # the slot stays restartable through the same agent connection
        w.restart()
        assert w.execute(_sq, 4).result(timeout=60) == 16
    finally:
        if wd is not None:
            wd.stop()
        w.kill()


def test_is_loopback_classification():
    """Round-5 advisor fix: the RCE gate must not be foolable by the old
    startswith('127.') prefix check, and IPv6 loopback must count."""
    from ray_lightning_accelerators_tpu.runtime.agent import is_loopback
    assert is_loopback("127.0.0.1")
    assert is_loopback("127.9.9.9")
    assert is_loopback("localhost")
    assert is_loopback("::1")
    assert is_loopback("[::1]")
    assert not is_loopback("10.0.0.5")
    assert not is_loopback("::2")
    assert not is_loopback("0.0.0.0")
    # a '127.'-PREFIXED hostname is not an address: it must resolve (and
    # be loopback) or be refused -- unresolvable fails closed
    assert not is_loopback("127.evil.example.invalid")
