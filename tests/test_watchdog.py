"""Progress-based liveness: heartbeat channel, ok/slow/wedged/dead
classification, wedge reaping, and elastic recovery from hangs.

The actor runtime's original failure detection was process-liveness only
(SURVEY.md §5.3: the reference has none at all); these tests pin the
upgrade from "process exited" to "process stopped making progress" --
the failure mode that cost two bench rounds (VERDICT.md: wedged tunnel,
25-minute silent hang).  All assertions are event- or monotonic-deadline
based (future results, condition-signaled watchdog states): no
sleep-poll flakes, no TPU, no jax computation.
"""

import time
from concurrent.futures import Future

import pytest

from ray_lightning_accelerators_tpu.runtime.actors import ActorPool, Worker
from ray_lightning_accelerators_tpu.runtime.elastic import ElasticRunner
from ray_lightning_accelerators_tpu.runtime.queue import process_results
from ray_lightning_accelerators_tpu.runtime.watchdog import (
    STATE_DEAD, STATE_OK, STATE_SLOW, STATE_WEDGED, HeartbeatChannel,
    Watchdog, WorkerWedged, stall_record)

HB = 0.05  # fast heartbeat for tests


def _ok(x=1):
    return x * 2


def _crash(code=3):
    import os
    os._exit(code)


def _sleep_forever():
    import time
    time.sleep(10_000)


def _sleep(s):
    import time
    time.sleep(s)
    return s


# --------------------------------------------------------------------- #
# channel + record shapes (pure, no subprocesses)                        #
# --------------------------------------------------------------------- #
def test_heartbeat_channel_semantics():
    ch = HeartbeatChannel()
    snap = ch.snapshot()
    assert snap["busy_s"] is None
    assert snap["dispatches"] == 0
    assert not snap["started"]  # no worker has stamped yet
    ch.stamp()
    assert ch.snapshot()["started"]
    ch.begin_dispatch()
    snap = ch.snapshot()
    assert snap["dispatches"] == 1
    assert snap["busy_s"] is not None
    ch.end_dispatch()
    snap = ch.snapshot()
    assert snap["busy_s"] is None
    assert snap["beat_age_s"] < 5.0


def test_worker_wedged_message_roundtrip():
    diag = {"detail": "heartbeat stale 1.20s > wedge timeout 1.00s",
            "beat_age_s": 1.2, "busy_s": None, "dispatches": 4}
    e = WorkerWedged.for_rank(3, diag)
    assert e.rank == 3
    assert e.diagnosis["dispatches"] == 4
    # the agent relay ships exceptions as (name, str, tb): the message
    # alone must reconstruct the typed wedge with its diagnosis
    back = WorkerWedged.from_message(str(e))
    assert back.rank == 3
    assert back.diagnosis["beat_age_s"] == 1.2
    assert "stale" in back.diagnosis["detail"]


def test_stall_record_mirrors_death_record_shape():
    e = WorkerWedged.for_rank(1, {"detail": "dispatch busy 9s > deadline",
                                  "busy_s": 9.0})
    rec = stall_record(e, "fit")
    assert rec["metric"] == "worker_stall"
    assert rec["error"] == "worker wedged"
    assert rec["stage"] == "fit"
    assert rec["rank"] == 1
    assert rec["stall_busy_s"] == 9.0
    assert len(rec["detail"]) <= 500
    rec = stall_record(TimeoutError("5 of 8 futures unresolved"), "test")
    assert rec["error"] == "attempt deadline exceeded"


def test_process_results_deadline_backstop():
    # driver-side hard stop for when supervision itself is broken: a
    # never-resolving future must raise, not hang the driver forever
    with pytest.raises(TimeoutError, match="unresolved"):
        process_results([Future()], None, poll_s=0.01, deadline_s=0.2)


# --------------------------------------------------------------------- #
# live workers                                                           #
# --------------------------------------------------------------------- #
def test_worker_heartbeat_stamps_and_counts_dispatches():
    w = Worker(0, heartbeat_s=HB)
    try:
        assert w.execute(_ok, 21).result(timeout=60) == 42
        snap = w.heartbeat.snapshot()
        assert snap["started"]
        assert snap["dispatches"] == 1
        assert snap["busy_s"] is None  # idle between dispatches
    finally:
        w.kill()


def test_busy_marker_while_dispatch_runs():
    w = Worker(0, heartbeat_s=HB)
    try:
        assert w.execute(_ok).result(timeout=60) == 2  # worker fully up
        w.execute(_sleep_forever)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            snap = w.heartbeat.snapshot()
            if snap["busy_s"] is not None and snap["dispatches"] == 2:
                break
            time.sleep(0.02)
        else:
            pytest.fail(f"busy marker never appeared: "
                        f"{w.heartbeat.snapshot()}")
    finally:
        w.kill()


def test_watchdog_classifies_dead_worker():
    w = Worker(0, heartbeat_s=HB)
    try:
        with pytest.raises(RuntimeError, match="died"):
            w.execute(_crash).result(timeout=60)
        w._proc.join(timeout=30)
        wd = Watchdog([w], wedge_timeout_s=5.0, auto_reap=False)
        state, info = wd.classify(w)
        assert state == STATE_DEAD
        assert "exitcode" in info["detail"]
    finally:
        w.kill()


def test_watchdog_reaps_hung_dispatch_as_wedged():
    w = Worker(0, heartbeat_s=HB)
    wd = None
    try:
        assert w.execute(_ok).result(timeout=60) == 2
        fut = w.execute(_sleep_forever)
        wd = Watchdog([w], wedge_timeout_s=10.0, dispatch_deadline_s=0.4,
                      poll_s=HB).start()
        with pytest.raises(WorkerWedged) as ei:
            fut.result(timeout=60)
        e = ei.value
        assert e.rank == 0
        assert "deadline" in e.diagnosis["detail"]
        assert e.diagnosis["busy_s"] > 0.4
        assert len(wd.reaped) == 1
        assert wd.reaped[0]["error"] == "worker wedged"
        # after the reap the process is gone
        assert wd.wait_for_state(0, STATE_DEAD, timeout=30)
    finally:
        if wd is not None:
            wd.stop()
        w.kill()


def test_watchdog_slow_straggler_not_killed():
    w = Worker(0, heartbeat_s=HB)
    wd = None
    try:
        assert w.execute(_ok).result(timeout=60) == 2
        fut = w.execute(_sleep, 1.0)
        wd = Watchdog([w], wedge_timeout_s=60.0, dispatch_deadline_s=60.0,
                      slow_after_s=0.15, poll_s=HB).start()
        assert wd.wait_for_state(0, STATE_SLOW, timeout=30)
        assert fut.result(timeout=60) == 1.0  # completed, never reaped
        assert wd.wait_for_state(0, STATE_OK, timeout=30)
        assert wd.reaped == []
    finally:
        if wd is not None:
            wd.stop()
        w.kill()


def test_watchdog_boot_grace_no_false_positive_kill():
    # a freshly spawned worker spends seconds importing before its first
    # beat; a tiny wedge timeout must not reap it during boot
    w = Worker(0, heartbeat_s=HB)
    wd = None
    try:
        wd = Watchdog([w], wedge_timeout_s=0.2, poll_s=0.05).start()
        assert w.execute(_ok, 5).result(timeout=120) == 10
        assert wd.reaped == []
    finally:
        if wd is not None:
            wd.stop()
        w.kill()


def test_heartbeat_survives_worker_restart():
    w = Worker(0, heartbeat_s=HB)
    try:
        assert w.execute(_ok).result(timeout=60) == 2
        old_hb = w.heartbeat
        w.restart()
        assert w.heartbeat is not old_hb  # fresh channel per generation
        assert w.execute(_ok, 3).result(timeout=60) == 6
        snap = w.heartbeat.snapshot()
        assert snap["started"]
        assert snap["dispatches"] == 1  # counter reset with the process
    finally:
        w.shutdown()


def test_pool_watch_helper_states():
    pool = ActorPool(2)
    wd = None
    try:
        for f in pool.execute_all(_ok):
            f.result(timeout=60)
        wd = pool.watch(wedge_timeout_s=30.0, poll_s=0.05)
        states = wd.poll_once()
        assert states == {0: STATE_OK, 1: STATE_OK}
    finally:
        if wd is not None:
            wd.stop()
        pool.shutdown()


def _hang_on_first_attempt(attempt, rank):
    if attempt == 0 and rank == 1:
        import time
        time.sleep(10_000)
    return (attempt, rank)


def test_elastic_runner_recovers_from_wedged_rank():
    """Wedge -> WorkerWedged -> restart_all -> clean retry: hangs retry
    exactly like crashes instead of hanging the driver forever."""
    pool = ActorPool(2, env_per_worker=[
        {"RLA_TPU_WORKER_HEARTBEAT_S": str(HB)} for _ in range(2)])
    failures = []
    try:
        runner = ElasticRunner(
            pool, max_failures=2, dispatch_deadline_s=0.5,
            watchdog_poll_s=HB,
            on_failure=lambda a, e: failures.append(e))
        out = runner.run(_hang_on_first_attempt,
                         args_per_worker=lambda a: [(a, r)
                                                    for r in range(2)])
        assert out == [(1, 0), (1, 1)]
        assert runner.attempts_used == 2
        assert len(failures) == 1
        assert isinstance(failures[0], WorkerWedged)
        assert runner.wedge_events
        assert runner.wedge_events[0]["rank"] == 1
    finally:
        pool.shutdown()
