"""KV-cache autoregressive decode vs naive full re-forward generation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu.models.transformer import (
    GPT, TransformerConfig)


def _model(vocab=97, heads=2, experts=1, max_seq_len=48,
           capacity_factor=1.25):
    cfg = TransformerConfig(vocab_size=vocab, d_model=64, n_heads=heads,
                            d_ff=128, n_layers=3, max_seq_len=max_seq_len,
                            num_experts=experts,
                            moe_capacity_factor=capacity_factor)
    m = GPT(cfg)
    params = m.init_params(jax.random.PRNGKey(0))
    return m, params


def _naive_generate(model, params, prompt, n_new):
    """Re-forward the whole prefix for every token: the reference semantics
    the cache path must reproduce exactly."""
    toks = jnp.asarray(prompt, jnp.int32)
    for _ in range(n_new):
        logits = model.forward(params, toks)
        nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return toks


def test_cached_decode_matches_naive():
    model, params = _model()
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 97, size=(2, 8)), jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=10)
    ref = _naive_generate(model, params, prompt, 10)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_generate_is_jittable():
    model, params = _model()
    prompt = jnp.ones((1, 4), jnp.int32)
    gen = jax.jit(lambda p, t: model.generate(p, t, max_new_tokens=6))
    out = gen(params, prompt)
    assert out.shape == (1, 10)
    ref = _naive_generate(model, params, prompt, 6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_single_new_token():
    model, params = _model()
    prompt = jnp.ones((2, 4), jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=1)
    assert out.shape == (2, 5)
    ref = _naive_generate(model, params, prompt, 1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_sampling_reproducible_and_in_vocab():
    model, params = _model()
    prompt = jnp.ones((2, 4), jnp.int32)
    a = model.generate(params, prompt, max_new_tokens=8, temperature=0.8,
                       top_k=10, rng=jax.random.PRNGKey(7))
    b = model.generate(params, prompt, max_new_tokens=8, temperature=0.8,
                       top_k=10, rng=jax.random.PRNGKey(7))
    c = model.generate(params, prompt, max_new_tokens=8, temperature=0.8,
                       top_k=10, rng=jax.random.PRNGKey(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    assert np.all(np.asarray(a) >= 0) and np.all(np.asarray(a) < 97)


def test_overflow_raises():
    model, params = _model(max_seq_len=16)
    with pytest.raises(ValueError, match="max_seq_len"):
        model.generate(params, jnp.ones((1, 10), jnp.int32),
                       max_new_tokens=10)


def test_moe_decode_matches_naive():
    # capacity high enough that no token is ever dropped: routing is then
    # per-token independent, so full-seq prefill and 1-token decode agree
    # (with drops, routing depends on batch composition and exact match is
    # not a well-defined expectation)
    model, params = _model(experts=4, heads=2, capacity_factor=8.0)
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, 97, size=(2, 8)), jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=6)
    ref = _naive_generate(model, params, prompt, 6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_int8_quantized_generate():
    """Weight-only int8 decode: quantization error bound per leaf, logits
    close to full precision, and the cached decode still matches a naive
    quantized re-forward exactly."""
    model, params = _model()
    qparams = model.quantize_weights(params)
    # per-channel symmetric error bound: |w - dq| <= scale/2
    flat = {"q": qparams["layers"]["attn"]["wq"],
            "orig": params["layers"]["attn"]["wq"]}
    dq = flat["q"]["q8"].astype(np.float32) * flat["q"]["scale"]
    err = np.abs(np.asarray(dq) - np.asarray(flat["orig"]))
    bound = np.asarray(flat["q"]["scale"]) / 2 + 1e-7
    assert (err <= bound).all()
    # 1D leaves stay dense
    assert not isinstance(qparams["ln_f"], dict)

    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, 97, size=(2, 8)), jnp.int32)
    logits_full = model.forward(params, prompt)
    logits_q = model.forward(qparams, prompt)
    # int8 logits track full precision closely
    np.testing.assert_allclose(np.asarray(logits_q),
                               np.asarray(logits_full), atol=0.35)
    out = model.generate(qparams, prompt, max_new_tokens=8)
    ref = _naive_generate(model, qparams, prompt, 8)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert out.shape == (2, 16)


def test_quantized_tree_is_half_the_bytes():
    model, params = _model()
    qparams = model.quantize_weights(params)

    def nbytes(t):
        return sum(np.asarray(x).nbytes for x in jax.tree.leaves(t))

    assert nbytes(qparams) < 0.5 * nbytes(params)


def test_gqa_decode_matches_naive():
    """Grouped-query attention: cached decode must match full re-forward,
    and the cache holds only kv_heads (not n_heads)."""
    cfg = TransformerConfig(vocab_size=97, d_model=64, n_heads=4,
                            d_ff=128, n_layers=2, max_seq_len=48,
                            n_kv_heads=2)
    model = GPT(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    assert params["layers"]["attn"]["wk"].shape == (2, 64, 2, 16)
    prompt = jnp.asarray(
        np.random.default_rng(5).integers(0, 97, size=(2, 8)), jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=10)
    ref = _naive_generate(model, params, prompt, 10)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_gqa_trains_and_quantizes():
    cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=4, d_ff=128,
                            n_layers=2, max_seq_len=32, n_kv_heads=1)
    model = GPT(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    toks = jnp.ones((2, 32), jnp.int32)
    g = jax.grad(lambda p: model.training_step(
        p, toks, jax.random.PRNGKey(0))[0])(params)
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(g))
    qp = model.quantize_weights(params)
    out = model.generate(qp, jnp.ones((1, 4), jnp.int32), max_new_tokens=4)
    assert out.shape == (1, 8)


def test_gqa_indivisible_heads_rejected():
    with pytest.raises(AssertionError, match="divisible"):
        GPT(TransformerConfig(vocab_size=64, d_model=64, n_heads=4,
                              d_ff=128, n_layers=1, max_seq_len=32,
                              n_kv_heads=3)).init_params(
                                  jax.random.PRNGKey(0))


def test_top_p_sampling():
    """Nucleus sampling restricts to the smallest prob mass >= top_p."""
    # distribution: one dominant token -> tiny top_p acts like greedy
    logits = jnp.asarray([[8.0, 1.0, 0.5, 0.1]])
    for seed in range(5):
        t = GPT._sample(logits, 1.0, 0, 0.5, jax.random.PRNGKey(seed))
        assert int(t[0]) == 0
    # top_p=1.0: all tokens reachable over enough seeds
    seen = {int(GPT._sample(jnp.asarray([[1.0, 1.0, 1.0, 1.0]]), 1.0, 0,
                            1.0, jax.random.PRNGKey(s))[0])
            for s in range(40)}
    assert len(seen) >= 3
    # generate() accepts top_p and stays reproducible per key
    model, params = _model()
    prompt = jnp.ones((1, 4), jnp.int32)
    a = model.generate(params, prompt, max_new_tokens=6, temperature=0.9,
                       top_p=0.8, rng=jax.random.PRNGKey(3))
    b = model.generate(params, prompt, max_new_tokens=6, temperature=0.9,
                       top_p=0.8, rng=jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sliding_window_decode_matches_naive():
    cfg = TransformerConfig(vocab_size=97, d_model=64, n_heads=2, d_ff=128,
                            n_layers=2, max_seq_len=48, sliding_window=6)
    model = GPT(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    prompt = jnp.asarray(
        np.random.default_rng(9).integers(0, 97, size=(2, 10)), jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=12)
    ref = _naive_generate(model, params, prompt, 12)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_top_p_zero_is_greedy():
    logits = jnp.asarray([[1.0, 3.0, 2.0, 0.5]])
    for seed in range(5):
        t = GPT._sample(logits, 1.0, 0, 0.0, jax.random.PRNGKey(seed))
        assert int(t[0]) == 1  # argmax survives, everything else masked


def test_sliding_window_rolling_cache_deep_wrap():
    """Cache is sized to the window and wraps many times; tokens must stay
    exact against naive full re-forward."""
    cfg = TransformerConfig(vocab_size=97, d_model=64, n_heads=2, d_ff=128,
                            n_layers=2, max_seq_len=64, sliding_window=4)
    model = GPT(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    prompt = jnp.asarray(
        np.random.default_rng(11).integers(0, 97, size=(2, 10)), jnp.int32)
    # cache shape is the window, not total
    _, cache = model._prefill(params, prompt, 4)
    assert cache["k"].shape[3] == 4
    out = model.generate(params, prompt, max_new_tokens=20)
    ref = _naive_generate(model, params, prompt, 20)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_sliding_window_prompt_shorter_than_window():
    cfg = TransformerConfig(vocab_size=97, d_model=64, n_heads=2, d_ff=128,
                            n_layers=2, max_seq_len=64, sliding_window=16)
    model = GPT(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    prompt = jnp.asarray(
        np.random.default_rng(12).integers(0, 97, size=(1, 4)), jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=24)
    ref = _naive_generate(model, params, prompt, 24)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_beam_size_one_is_greedy():
    model, params = _model()
    prompt = jnp.asarray(
        np.random.default_rng(21).integers(0, 97, size=(1, 6)), jnp.int32)
    beam = model.generate_beam(params, prompt, max_new_tokens=8, beam_size=1)
    greedy = model.generate(params, prompt, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(beam), np.asarray(greedy))


def test_beam_finds_global_optimum_when_exhaustive():
    """With beam_size >= all prefixes, beam search is exhaustive and must
    return the argmax-total-logprob sequence (brute-forced)."""
    cfg = TransformerConfig(vocab_size=8, d_model=32, n_heads=2, d_ff=64,
                            n_layers=1, max_seq_len=16)
    model = GPT(cfg)
    params = model.init_params(jax.random.PRNGKey(3))
    prompt = jnp.asarray([[2, 5]], jnp.int32)
    n_new = 3
    out = model.generate_beam(params, prompt, max_new_tokens=n_new,
                              beam_size=64)

    # brute force all 8^3 continuations in ONE batched forward
    import itertools
    seqs = np.asarray(list(itertools.product(range(8), repeat=n_new)),
                      np.int32)                       # [512, 3]
    toks = np.concatenate(
        [np.tile(np.asarray(prompt), (len(seqs), 1)), seqs], axis=1)
    logits = jax.jit(model.forward)(params, jnp.asarray(toks))
    logp = np.asarray(jax.nn.log_softmax(logits))
    s0 = prompt.shape[1]
    scores = sum(logp[np.arange(len(seqs)), s0 - 1 + i, seqs[:, i]]
                 for i in range(n_new))
    best_seq = seqs[int(np.argmax(scores))]
    np.testing.assert_array_equal(np.asarray(out)[0, 2:], best_seq)


def test_beam_rejects_batch():
    model, params = _model()
    with pytest.raises(ValueError, match="batch"):
        model.generate_beam(params, jnp.ones((2, 4), jnp.int32), 4)


def test_zero_new_tokens_rejected():
    model, params = _model()
    prompt = jnp.ones((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="max_new_tokens"):
        model.generate(params, prompt, max_new_tokens=0)
    with pytest.raises(ValueError, match="max_new_tokens"):
        model.generate_beam(params, prompt, max_new_tokens=0)


def test_repetition_penalty_suppresses_repeats():
    model, params = _model()
    prompt = jnp.ones((1, 4), jnp.int32)
    plain = model.generate(params, prompt, max_new_tokens=16)
    pen = model.generate(params, prompt, max_new_tokens=16,
                         repetition_penalty=1e6)

    def repeats(seq):
        seq = list(map(int, np.asarray(seq)[0]))
        return len(seq) - len(set(seq))

    # an extreme penalty forbids reuse: every generated token (and the
    # prompt token) appears at most once
    assert repeats(pen) <= repeats(plain)
    gen_part = list(map(int, np.asarray(pen)[0, 4:]))
    assert len(set(gen_part)) == len(gen_part)
    assert 1 not in gen_part  # prompt token penalized too
    # penalty=1.0 is the identity
    same = model.generate(params, prompt, max_new_tokens=16,
                          repetition_penalty=1.0)
    np.testing.assert_array_equal(np.asarray(same), np.asarray(plain))
