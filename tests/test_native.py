"""Native C++ data engine: build, correctness vs the Python path, sharding.

Mirrors the sampler contracts the reference pins in
ray_lightning/tests/test_ddp.py:52-72 (disjoint shards, shuffle flags,
rank/num_replicas), applied to the in-repo native batcher.
"""

import numpy as np
import pytest

from ray_lightning_accelerators_tpu import native
from ray_lightning_accelerators_tpu.data.loader import (ArrayDataset,
                                                        DataLoader,
                                                        ShardedSampler)


pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native build: {native.build_error()}")


def _ds(n=64, d=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d), dtype=np.float32)
    y = rng.integers(0, 10, size=(n,)).astype(np.int32)
    return x, y


def test_builds():
    assert native.available(), native.build_error()


@pytest.mark.parametrize("shuffle", [False, True])
def test_matches_python_path_bit_exact(shuffle):
    # sampling stays in Python, so native batches are bit-identical to the
    # Python path even when shuffling
    x, y = _ds()
    ds = ArrayDataset(x, y)
    py = DataLoader(ds, batch_size=8, shuffle=shuffle, seed=5,
                    use_native=False)
    nat = DataLoader(ds, batch_size=8, shuffle=shuffle, seed=5,
                     use_native=True)
    py.set_epoch(3)
    nat.set_epoch(3)
    py_batches = list(py)
    nat_batches = list(nat)
    assert len(py_batches) == len(nat_batches) == len(py)
    for (px, pyy), (nx, ny) in zip(py_batches, nat_batches):
        np.testing.assert_array_equal(px, nx)
        np.testing.assert_array_equal(pyy, ny)
        assert nx.dtype == np.float32 and ny.dtype == np.int32


def test_shuffle_is_permutation_and_deterministic():
    x, y = _ds()
    eng = native.DataEngine([x, y], batch_size=8, shuffle=True, seed=3)
    seen = np.concatenate([bx[:, 0] for bx, _ in eng.epoch(0)])
    assert sorted(seen.tolist()) == sorted(x[:, 0].tolist())
    seen2 = np.concatenate([bx[:, 0] for bx, _ in eng.epoch(0)])
    np.testing.assert_array_equal(seen, seen2)  # same (seed, epoch)
    seen3 = np.concatenate([bx[:, 0] for bx, _ in eng.epoch(1)])
    assert not np.array_equal(seen, seen3)  # new epoch reshuffles
    eng.close()


def test_rank_shards_are_disjoint_and_cover():
    x, y = _ds(n=64)
    shards = []
    for rank in range(4):
        eng = native.DataEngine([x, y], batch_size=4, shuffle=True, seed=7,
                                num_replicas=4, rank=rank)
        shards.append(np.concatenate(
            [by for _, by in eng.epoch(2)] or [np.empty(0)]))
        assert eng.num_batches() == 64 // 4 // 4
        eng.close()
    # together the 4 rank shards hold each row exactly once
    rows = np.concatenate([np.concatenate(
        [bx[:, 0] for bx, _ in native.DataEngine(
            [x, y], 4, shuffle=True, seed=7, num_replicas=4,
            rank=r).epoch(2)]) for r in range(4)])
    assert sorted(rows.tolist()) == sorted(x[:, 0].tolist())


def test_partial_batch_no_drop_last():
    x, y = _ds(n=21)
    nat = DataLoader(ArrayDataset(x, y), batch_size=8, shuffle=False,
                     drop_last=False, use_native=True)
    sizes = [len(bx) for bx, _ in nat]
    assert sizes == [8, 8, 5]


def test_single_array_dataset_yields_bare_array():
    x, _ = _ds()
    nat = DataLoader(ArrayDataset(x), batch_size=8, use_native=True)
    batch = next(iter(nat))
    assert isinstance(batch, np.ndarray) and batch.shape == (8, 5)


def test_break_mid_epoch_then_reiterate():
    x, y = _ds(n=64)
    loader = DataLoader(ArrayDataset(x, y), batch_size=8, shuffle=True,
                        use_native=True)
    it = iter(loader)
    next(it), next(it)  # abandon mid-epoch (limit_train_batches pattern)
    batches = list(loader)
    assert len(batches) == len(loader) == 8


def test_sampler_injection_reshapes_engine():
    x, y = _ds(n=64)
    loader = DataLoader(ArrayDataset(x, y), batch_size=8, shuffle=True,
                        use_native=True)
    assert len(list(loader)) == 8
    loader._inject_sampler(num_replicas=2, rank=1, shuffle=True)
    assert len(list(loader)) == 4  # engine rebuilt for the 2-replica shard


def test_pickle_roundtrip_drops_engine():
    import cloudpickle
    x, y = _ds()
    loader = DataLoader(ArrayDataset(x, y), batch_size=8, use_native=True)
    list(loader)
    loader2 = cloudpickle.loads(cloudpickle.dumps(loader))
    assert loader2._engine is None
    assert len(list(loader2)) == len(loader)


def test_user_sampler_subclass_uses_its_indices():
    # custom sampler semantics flow through: the engine consumes the
    # sampler's index order verbatim
    class EveryOther(ShardedSampler):
        def __iter__(self):
            return iter(range(0, self.dataset_len, 2))

    x, y = _ds()
    loader = DataLoader(ArrayDataset(x, y), batch_size=8,
                        sampler=EveryOther(64, 1, 0, shuffle=False),
                        use_native=True)
    batches = list(loader)
    np.testing.assert_array_equal(batches[0][0], x[0:16:2])


def test_object_dtype_rejected():
    objs = np.array([object() for _ in range(16)], dtype=object)
    ds = ArrayDataset(objs, np.arange(16))
    loader = DataLoader(ds, batch_size=4)
    assert loader._native_engine() is None  # auto mode: silent fallback
    with pytest.raises(RuntimeError, match="numeric"):
        DataLoader(ds, batch_size=4, use_native=True)._native_engine()


def test_explicit_native_with_custom_collate_raises():
    x, y = _ds()
    loader = DataLoader(ArrayDataset(x, y), batch_size=8, use_native=True,
                        collate_fn=lambda b: b)
    with pytest.raises(RuntimeError, match="collate_fn"):
        next(iter(loader))


def test_concurrent_iteration_is_safe():
    x, y = _ds(n=64)
    loader = DataLoader(ArrayDataset(x, y), batch_size=8, shuffle=True,
                        use_native=True)
    # zip over two live iterators: second falls back to the Python path,
    # both see the full epoch in the same order
    pairs = list(zip(loader, loader))
    assert len(pairs) == 8
    for (ax, ay), (bx, by) in pairs:
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)


def test_many_epochs_stress():
    x, y = _ds(n=256, d=16)
    eng = native.DataEngine([x, y], batch_size=16, shuffle=True, seed=0,
                            num_threads=4, prefetch=3)
    for epoch in range(20):
        total = 0
        for bx, by in eng.epoch(epoch):
            total += len(bx)
        assert total == 256
    eng.close()
