"""Device-resident dataset cache: training parity with the host-fed path,
eligibility gating, partial batches (the TPU-idiomatic input pipeline for
datasets that fit HBM — SURVEY.md §7.4 hard part 4)."""

import numpy as np
import pytest

from ray_lightning_accelerators_tpu import (DataLoader, RayTPUAccelerator,
                                            Trainer)
from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
from tests.utils import BoringModel, boring_loaders


def _fit(cache, max_epochs=2, drop_last=True, use_fsdp=False):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((70, 32)).astype(np.float32)
    train = DataLoader(ArrayDataset(x), batch_size=8, shuffle=True,
                       drop_last=drop_last)
    model = BoringModel()
    trainer = Trainer(max_epochs=max_epochs,
                      accelerator=RayTPUAccelerator(use_fsdp=use_fsdp),
                      precision="f32", enable_checkpointing=False, seed=0,
                      cache_dataset_on_device=cache,
                      log_every_n_steps=10 ** 9)
    trainer.fit(model, train)
    return trainer, model


def test_cached_matches_host_fed_training():
    t_host, m_host = _fit(cache=False)
    t_cached, m_cached = _fit(cache=True)
    assert t_cached._device_cache is not None
    assert t_cached.global_step == t_host.global_step
    np.testing.assert_allclose(
        np.asarray(m_cached.params["layer"]["kernel"]),
        np.asarray(m_host.params["layer"]["kernel"]), rtol=1e-5, atol=1e-6)


def test_cached_matches_host_fed_with_fsdp_mesh():
    t_host, m_host = _fit(cache=False, use_fsdp=True)
    t_cached, m_cached = _fit(cache=True, use_fsdp=True)
    assert t_cached._device_cache is not None
    np.testing.assert_allclose(
        np.asarray(m_cached.params["layer"]["kernel"]),
        np.asarray(m_host.params["layer"]["kernel"]), rtol=1e-5, atol=1e-6)


def test_partial_trailing_batch_uses_host_path():
    # 70 rows / batch 8 -> 8 full cached steps + 1 host-fed partial of 6...
    # but the partial (6 rows) must still divide the 8-way dp axis, so use
    # a 64+8k split instead: 72 rows -> 9 full batches exactly; then 80 rows
    # with drop_last=False -> 10 full, still exact. Use batch 16 over 72:
    # 4 full + partial 8 (divisible by dp=8).
    rng = np.random.default_rng(0)
    x = rng.standard_normal((72, 32)).astype(np.float32)
    train = DataLoader(ArrayDataset(x), batch_size=16, shuffle=False,
                       drop_last=False)
    trainer = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False, seed=0,
                      cache_dataset_on_device=True)
    trainer.fit(BoringModel(), train)
    assert trainer.global_step == 5  # 4 cached + 1 host partial


def test_auto_respects_size_threshold(monkeypatch):
    monkeypatch.setattr(Trainer, "_CACHE_AUTO_ON_CPU", True)
    monkeypatch.setattr(Trainer, "_CACHE_MAX_BYTES", 64)
    t_auto, _ = _fit(cache="auto")
    assert t_auto._device_cache is None  # dataset over the auto cap
    monkeypatch.setattr(Trainer, "_CACHE_MAX_BYTES", 1 << 30)
    t_auto2, _ = _fit(cache="auto")
    assert t_auto2._device_cache is not None  # under the cap: cached
    t_forced, _ = _fit(cache=True)
    assert t_forced._device_cache is not None  # explicit True overrides


def test_auto_disabled_on_cpu_backend():
    t_auto, _ = _fit(cache="auto")
    assert t_auto._device_cache is None  # CPU backend: replication loses


def test_ineligible_datasets_fall_back():
    train, val = boring_loaders()  # RandomDataset exposes arrays -> eligible
    trainer = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False, seed=0,
                      cache_dataset_on_device=True)
    trainer.fit(BoringModel(), train, val)
    assert trainer._device_cache is not None

    class NoArrays:
        def __len__(self):
            return 64

        def __getitem__(self, i):
            return np.zeros(32, np.float32)

    loader = DataLoader(NoArrays(), batch_size=8)
    t2 = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                 precision="f32", enable_checkpointing=False, seed=0,
                 cache_dataset_on_device=True)
    t2.fit(BoringModel(), loader)
    assert t2._device_cache is None
    assert t2.global_step == 8


def test_epoch_reshuffle_respected_when_cached():
    # deterministic parity across both paths over multiple shuffled epochs
    # is already asserted above; here make sure two epochs don't reuse one
    # index order (sampler.set_epoch flows through _cached_epoch_source)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 32)).astype(np.float32)
    train = DataLoader(ArrayDataset(x), batch_size=8, shuffle=True)
    trainer = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False, seed=0,
                      cache_dataset_on_device=True)
    trainer.fit(BoringModel(), train)
    train.set_epoch(0)
    first = np.fromiter(train.sampler, np.int64)
    train.set_epoch(1)
    second = np.fromiter(train.sampler, np.int64)
    assert not np.array_equal(first, second)


def test_scanned_epoch_engages_and_skips_per_step_dispatch():
    """With the cache active and no per-step host needs, the whole epoch
    runs as ONE lax.scan dispatch: the per-step cached fn is never
    called."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 32)).astype(np.float32)
    train = DataLoader(ArrayDataset(x), batch_size=8, shuffle=True)
    trainer = Trainer(max_epochs=2, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False, seed=0,
                      cache_dataset_on_device=True,
                      log_every_n_steps=10 ** 9)
    calls = []
    orig_compile = trainer._compile

    def probe_compile(*a, **kw):
        orig_compile(*a, **kw)
        fn = trainer._train_step_cached_fn
        trainer._train_step_cached_fn = \
            lambda *args: calls.append(1) or fn(*args)

    trainer._compile = probe_compile
    trainer.fit(BoringModel(), train)
    assert trainer.global_step == 16
    assert calls == []  # scan path: zero per-step dispatches


def test_scanned_epoch_respects_max_steps():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 32)).astype(np.float32)
    train = DataLoader(ArrayDataset(x), batch_size=8, shuffle=False)
    trainer = Trainer(max_steps=11, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False, seed=0,
                      cache_dataset_on_device=True)
    trainer.fit(BoringModel(), train)
    assert trainer.global_step == 11   # 8 (epoch 1) + 3 (truncated epoch 2)
    assert trainer.epochs_completed == 1


def test_batch_end_callback_falls_back_to_step_loop():
    from ray_lightning_accelerators_tpu import Callback

    class PerStep(Callback):
        def __init__(self):
            self.n = 0

        def on_train_batch_end(self, trainer, module, metrics, batch_idx):
            self.n += 1

    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 32)).astype(np.float32)
    train = DataLoader(ArrayDataset(x), batch_size=8, shuffle=False)
    cb = PerStep()
    trainer = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False, seed=0,
                      cache_dataset_on_device=True, callbacks=[cb])
    trainer.fit(BoringModel(), train)
    assert not trainer._can_scan_epoch()
    assert cb.n == 4  # per-step callback still fires, on the loop path


def test_scanned_epoch_logs_on_cadence():
    from ray_lightning_accelerators_tpu.utils.logging import InMemoryLogger

    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 32)).astype(np.float32)
    train = DataLoader(ArrayDataset(x), batch_size=8, shuffle=False)
    logger = InMemoryLogger()
    trainer = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False, seed=0,
                      cache_dataset_on_device=True, logger=logger,
                      log_every_n_steps=3)
    trainer.fit(BoringModel(), train)
    steps = [row["step"] for row in logger.history if "train_loss" in row]
    assert steps == [3, 6]  # 8 steps, cadence 3


def test_scanned_epoch_max_steps_at_last_batch_parity():
    """max_steps landing exactly on the last full batch must not run the
    trailing partial batch nor mark the epoch complete (step-loop
    parity)."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((68, 32)).astype(np.float32)

    def run(cache):
        train = DataLoader(ArrayDataset(x), batch_size=8, shuffle=False,
                           drop_last=False)
        trainer = Trainer(max_steps=8, accelerator=RayTPUAccelerator(),
                          precision="f32", enable_checkpointing=False,
                          seed=0, cache_dataset_on_device=cache)
        trainer.fit(BoringModel(), train)
        return (trainer.global_step, trainer.epochs_completed,
                trainer.should_stop)

    assert run(True) == run(False) == (8, 0, True)


def test_instance_attribute_batch_end_hook_disables_scan():
    from ray_lightning_accelerators_tpu import Callback

    hits = []
    cb = Callback()
    cb.on_train_batch_end = \
        lambda trainer, module, metrics, batch_idx: hits.append(batch_idx)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 32)).astype(np.float32)
    train = DataLoader(ArrayDataset(x), batch_size=8, shuffle=False)
    trainer = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False, seed=0,
                      cache_dataset_on_device=True, callbacks=[cb])
    trainer.fit(BoringModel(), train)
    assert hits == [0, 1, 2, 3]  # hook fired; scan path stood down
