"""Shared-memory object store: roundtrips, zero-copy, lifecycle,
cross-process deref through the actor runtime (the ray.put/ray.get analog,
reference: ray_lightning/ray_ddp.py:169-182)."""

import numpy as np
import pytest

from ray_lightning_accelerators_tpu import native
from ray_lightning_accelerators_tpu.runtime.object_store import (
    ObjectRef, ObjectStore, ObjectStoreError)

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native build: {native.build_error()}")


def _tree():
    rng = np.random.default_rng(0)
    return {
        "params": {"w": rng.standard_normal((256, 256), dtype=np.float32),
                   "b": rng.standard_normal(8, dtype=np.float32)},  # inline
        "step": 7,
        "tag": "hello",
    }


def test_roundtrip_mixed_tree():
    with ObjectStore() as store:
        tree = _tree()
        ref = store.put(tree)
        assert isinstance(ref, ObjectRef)
        assert len(ref.segments) == 1  # only the 256x256 leaf crosses shm
        assert ref.total_shm_bytes() == 256 * 256 * 4
        out = store.get(ref)
        np.testing.assert_array_equal(out["params"]["w"],
                                      tree["params"]["w"])
        np.testing.assert_array_equal(out["params"]["b"],
                                      tree["params"]["b"])
        assert out["step"] == 7 and out["tag"] == "hello"
        out["params"]["w"][0, 0] = 123.0  # copies are independent
        assert store.get(ref)["params"]["w"][0, 0] != 123.0


def test_zero_copy_views_are_readonly():
    with ObjectStore() as store:
        tree = _tree()
        ref = store.put(tree)
        out = store.get(ref, copy=False)
        np.testing.assert_array_equal(out["params"]["w"],
                                      tree["params"]["w"])
        with pytest.raises(ValueError):
            out["params"]["w"][0, 0] = 1.0


def test_delete_then_get_raises():
    store = ObjectStore()
    ref = store.put({"w": np.zeros((512, 512), dtype=np.float32)})
    store.delete(ref)
    with pytest.raises(ObjectStoreError, match="does not exist"):
        store.get(ref)
    store.shutdown()


def test_jax_array_leaf():
    import jax.numpy as jnp
    with ObjectStore() as store:
        ref = store.put({"x": jnp.arange(65536, dtype=jnp.float32)})
        out = store.get(ref)
        assert isinstance(out["x"], np.ndarray)
        np.testing.assert_array_equal(out["x"],
                                      np.arange(65536, dtype=np.float32))


def test_shutdown_unlinks_segments():
    store = ObjectStore()
    ref = store.put({"w": np.ones((512, 512), dtype=np.float32)})
    store.shutdown()
    with pytest.raises(ObjectStoreError):
        ObjectStore().get(ref)


def _sum_resolved(arr):
    # runs in the worker; receives the already-dereferenced array
    assert isinstance(arr, np.ndarray)
    return float(arr.sum())


def test_cross_process_deref_via_actor():
    from ray_lightning_accelerators_tpu.runtime.actors import Worker
    with ObjectStore() as store:
        big = np.ones((1024, 256), dtype=np.float32)
        ref = store.put(big)
        w = Worker(0)
        try:
            assert w.execute(_sum_resolved, ref).result(timeout=60) == \
                float(big.sum())
        finally:
            w.shutdown()


def test_total_shm_bytes_tracks_put_and_delete():
    """The ``object_store_shm`` gauge: live bytes rise on put, fall on
    delete, and the module-level reader never instantiates a store."""
    from ray_lightning_accelerators_tpu.runtime import object_store as osl
    with ObjectStore() as store:
        assert store.total_shm_bytes() == 0
        ref1 = store.put({"w": np.zeros((256, 256), dtype=np.float32)})
        ref2 = store.put({"w": np.zeros((128, 128), dtype=np.float32)})
        assert store.total_shm_bytes() == 256 * 256 * 4 + 128 * 128 * 4
        store.delete(ref1)
        assert store.total_shm_bytes() == 128 * 128 * 4
        store.delete(ref2)
        assert store.total_shm_bytes() == 0


def test_global_shm_bytes_reader_never_builds_a_store():
    from ray_lightning_accelerators_tpu.runtime import object_store as osl
    before = osl._GLOBAL
    assert osl.global_shm_bytes() >= 0
    assert osl._GLOBAL is before  # sampling must not instantiate one


def test_release_unmaps_one_refs_views_only():
    """release(ref) drops exactly that ref's copy=False mappings (the
    pipeline receiver's step-boundary cleanup); other refs' views stay
    valid, and a released ref can be re-mapped by a later get."""
    with ObjectStore() as store:
        ref_a = store.put({"w": np.full((256, 256), 3.0, dtype=np.float32)})
        ref_b = store.put({"w": np.full((256, 256), 7.0, dtype=np.float32)})
        va = store.get(ref_a, copy=False)
        vb = store.get(ref_b, copy=False)
        store.release(ref_a)
        # b's view survives a's release
        assert float(vb["w"][0, 0]) == 7.0
        # a remains stored: a fresh get re-maps it
        again = store.get(ref_a)
        assert float(again["w"][0, 0]) == 3.0
        store.release(ref_b)
