"""Kernel correctness: pallas flash attention (interpreter mode) vs the XLA
reference, including causal masking and the custom-vjp gradient path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu.ops.attention import (
    attention_reference, flash_attention, flash_attention_interpret)


# CPU runs both paths in strict f32; on real TPU the MXU's default matmul
# precision (bf16-grade passes) plus the online-softmax accumulation order
# shifts values by up to ~1e-2 absolute on O(1) outputs
_ON_CPU = jax.default_backend() == "cpu"
_TOL = (dict(atol=2e-5, rtol=2e-5) if _ON_CPU
        else dict(atol=2e-2, rtol=5e-2))
_GRAD_TOL = (dict(atol=1e-4, rtol=1e-4) if _ON_CPU
             else dict(atol=5e-2, rtol=1e-1))


def _qkv(b=2, h=2, s=256, d=64, seed=0, dtype=jnp.float32):
    rng = jax.random.PRNGKey(seed)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, h, s, d), dtype)
    k = jax.random.normal(kk, (b, h, s, d), dtype)
    v = jax.random.normal(kv, (b, h, s, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    ref = attention_reference(q, k, v, causal=causal)
    out = flash_attention_interpret(q, k, v, causal=causal,
                                    block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), **_TOL)


def test_flash_uneven_blocks():
    q, k, v = _qkv(s=384)
    ref = attention_reference(q, k, v, causal=True)
    out = flash_attention_interpret(q, k, v, causal=True,
                                    block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), **_TOL)


def test_flash_gradients_match():
    q, k, v = _qkv(b=1, h=2, s=128, d=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   **_GRAD_TOL)


def test_cpu_dispatch_falls_back():
    """On the CPU test backend the public entry must route to XLA."""
    q, k, v = _qkv(s=64)
    out = flash_attention(q, k, v, False)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def _brute_window(q, k, v, window):
    ql = q.shape[2]
    qi = np.arange(ql)[:, None]
    ki = np.arange(ql)[None, :]
    mask = (qi >= ki) & (qi - ki < window)
    logits = np.einsum("bhqd,bhkd->bhqk", np.asarray(q), np.asarray(k)) \
        * q.shape[-1] ** -0.5
    logits = np.where(mask[None, None], logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, np.asarray(v))


@pytest.mark.parametrize("window", [64, 100, 256])
def test_sliding_window_reference(window):
    q, k, v = _qkv(s=256)
    out = attention_reference(q, k, v, causal=True, window=window)
    ref = _brute_window(q, k, v, window)
    np.testing.assert_allclose(np.asarray(out), ref, **_TOL)


@pytest.mark.parametrize("window", [64, 100])
def test_sliding_window_kernel_matches(window):
    q, k, v = _qkv(s=256)
    out = flash_attention_interpret(q, k, v, causal=True, block_q=128,
                                    block_k=128, window=window)
    ref = attention_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), **_TOL)


def test_sliding_window_gradients():
    q, k, v = _qkv(b=1, h=2, s=128, d=64)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, window=48) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True,
                                           window=48) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   **_GRAD_TOL)


@pytest.mark.parametrize("block_k", [128, 256])
@pytest.mark.parametrize("causal,window", [(False, None), (True, None),
                                           (True, 96)])
def test_flash_backward_kernels_match(causal, window, block_k):
    """The hand-written backward kernels must reproduce XLA autodiff of
    the reference: block_k=128 exercises the split dq + dkv passes,
    block_k=256 (== k_len) the FUSED single-k-block kernel that shares
    the score recompute."""
    from ray_lightning_accelerators_tpu.ops.attention import (
        flash_attention_grads_interpret)

    q, k, v = _qkv(b=2, h=2, s=256, d=64)
    g = jax.random.normal(jax.random.PRNGKey(7), q.shape, q.dtype)

    def ref(q_, k_, v_):
        return attention_reference(q_, k_, v_, causal=causal, window=window)

    _, vjp = jax.vjp(ref, q, k, v)
    want = vjp(g)
    got = flash_attention_grads_interpret(q, k, v, g, causal=causal,
                                          block_q=128, block_k=block_k,
                                          window=window)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **_GRAD_TOL)
