"""Fused linear cross-entropy: numerics + grads vs the materialized path.

The reference has no loss ops of its own (losses live in the user's torch
module, reference: ray_lightning/tests/utils.py:33-37); these tests pin the
framework's streaming LM-head op against optax / the naive matmul path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_lightning_accelerators_tpu.ops.losses import (
    fused_linear_cross_entropy, linear_cross_entropy_reference)


def _case(rows=100, d=32, v=257, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.normal(size=(rows, d)), dtype)
    w = jnp.asarray(rng.normal(size=(d, v)) * d ** -0.5, dtype)
    t = jnp.asarray(rng.integers(0, v, size=(rows,)), jnp.int32)
    return h, w, t


def test_matches_reference_loss_and_acc():
    h, w, t = _case()
    loss_f, acc_f = fused_linear_cross_entropy(h, w, t, 32)
    loss_r, acc_r = linear_cross_entropy_reference(h, w, t)
    np.testing.assert_allclose(loss_f, loss_r, rtol=1e-5)
    np.testing.assert_allclose(acc_f, acc_r, rtol=1e-6)


def test_matches_optax():
    h, w, t = _case(rows=64)
    loss_f, _ = fused_linear_cross_entropy(h, w, t, 64)
    logits = h @ w
    loss_o = optax.softmax_cross_entropy_with_integer_labels(logits, t).mean()
    np.testing.assert_allclose(loss_f, loss_o, rtol=1e-5)


@pytest.mark.parametrize("chunk", [16, 100, 128])
def test_chunking_invariance(chunk):
    h, w, t = _case(rows=100)
    loss_f, acc_f = fused_linear_cross_entropy(h, w, t, chunk)
    loss_r, acc_r = linear_cross_entropy_reference(h, w, t)
    np.testing.assert_allclose(loss_f, loss_r, rtol=1e-5)
    np.testing.assert_allclose(acc_f, acc_r, rtol=1e-6)


def test_grads_match_naive():
    h, w, t = _case(rows=96, d=16, v=99)

    def fused(h_, w_):
        return fused_linear_cross_entropy(h_, w_, t, 32)[0]

    def naive(h_, w_):
        return optax.softmax_cross_entropy_with_integer_labels(
            h_ @ w_, t).mean()

    gh_f, gw_f = jax.grad(fused, argnums=(0, 1))(h, w)
    gh_n, gw_n = jax.grad(naive, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(gh_f, gh_n, atol=1e-6)
    np.testing.assert_allclose(gw_f, gw_n, atol=1e-6)


def test_masked_targets_ignored():
    h, w, t = _case(rows=64)
    t_masked = t.at[10:20].set(-1)
    loss_f, acc_f = fused_linear_cross_entropy(h, w, t_masked, 16)
    keep = np.r_[0:10, 20:64]
    loss_r, acc_r = linear_cross_entropy_reference(h[keep], w, t[keep])
    np.testing.assert_allclose(loss_f, loss_r, rtol=1e-5)
    np.testing.assert_allclose(acc_f, acc_r, rtol=1e-6)
    # masked rows get zero grad
    gh = jax.grad(
        lambda h_: fused_linear_cross_entropy(h_, w, t_masked, 16)[0])(h)
    np.testing.assert_allclose(gh[10:20], np.zeros((10, h.shape[1])))


def test_bf16_inputs_close_to_f32():
    h, w, t = _case(dtype=jnp.bfloat16)
    loss_f, _ = fused_linear_cross_entropy(h, w, t, 32)
    loss_r, _ = linear_cross_entropy_reference(
        h.astype(jnp.float32), w.astype(jnp.float32), t)
    np.testing.assert_allclose(float(loss_f), float(loss_r), rtol=2e-2)


def test_sharded_matches_unsharded():
    from ray_lightning_accelerators_tpu.parallel.mesh import (MeshConfig,
                                                              build_mesh)
    if jax.device_count() < 4:
        pytest.skip("needs >=4 devices")
    mesh = build_mesh(MeshConfig(data=-1, fsdp=2))
    h, w, t = _case(rows=64, d=16, v=99)

    def sharded(h_, w_):
        return fused_linear_cross_entropy(h_, w_, t, 8, mesh=mesh)[0]

    def local(h_, w_):
        return fused_linear_cross_entropy(h_, w_, t, 8)[0]

    P = jax.sharding.PartitionSpec
    hs = jax.device_put(h, jax.sharding.NamedSharding(
        mesh, P(("data", "fsdp"), None)))
    loss_s, acc_s = jax.jit(
        lambda h_, w_: fused_linear_cross_entropy(h_, w_, t, 8, mesh=mesh)
    )(hs, w)
    loss_l, acc_l = fused_linear_cross_entropy(h, w, t, 8)
    np.testing.assert_allclose(loss_s, loss_l, rtol=1e-5)
    np.testing.assert_allclose(acc_s, acc_l, rtol=1e-6)
    gh_s, gw_s = jax.jit(jax.grad(sharded, argnums=(0, 1)))(hs, w)
    gh_l, gw_l = jax.grad(local, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(jax.device_get(gh_s), gh_l, atol=1e-6)
    np.testing.assert_allclose(jax.device_get(gw_s), gw_l, atol=1e-6)


def test_gpt_fused_vs_naive_loss():
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, size=(2, 32)), jnp.int32)
    outs = {}
    for fused in (True, False):
        cfg = TransformerConfig(vocab_size=128, d_model=64, n_heads=2,
                                d_ff=128, n_layers=2, max_seq_len=32,
                                fused_loss=fused)
        model = GPT(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        loss, metrics = model.training_step(params, toks,
                                            jax.random.PRNGKey(1))
        grads = jax.grad(
            lambda p: model.training_step(p, toks, jax.random.PRNGKey(1))[0]
        )(params)
        outs[fused] = (float(loss), float(metrics["accuracy"]), grads)
    assert outs[True][0] == pytest.approx(outs[False][0], rel=1e-4)
    assert outs[True][1] == pytest.approx(outs[False][1], abs=1e-6)
    for a, b in zip(jax.tree.leaves(outs[True][2]),
                    jax.tree.leaves(outs[False][2])):
        np.testing.assert_allclose(a, b, atol=2e-5)


def test_label_smoothing_and_z_loss_values():
    """Fused op with eps/z matches the explicit formula on f32 inputs."""
    h, w, t = _case(rows=64, d=16, v=99)
    eps, zl = 0.1, 1e-3
    loss_f, _ = fused_linear_cross_entropy(h, w, t, 16,
                                           label_smoothing=eps, z_loss=zl)
    logits = np.asarray(h @ w, np.float64)
    lse = np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1)) \
        + logits.max(-1)
    tgt = logits[np.arange(64), np.asarray(t)]
    expect = (lse - (1 - eps) * tgt - (eps / 99) * logits.sum(-1)
              + zl * lse ** 2).mean()
    np.testing.assert_allclose(float(loss_f), expect, rtol=1e-5)
    # eps=z=0 reproduces the plain path exactly
    plain, _ = fused_linear_cross_entropy(h, w, t, 16)
    ref, _ = linear_cross_entropy_reference(h, w, t)
    np.testing.assert_allclose(plain, ref, rtol=1e-5)


def test_label_smoothing_z_loss_grads_match_autodiff():
    h, w, t = _case(rows=48, d=16, v=53)
    eps, zl = 0.05, 1e-2

    def fused(h_, w_):
        return fused_linear_cross_entropy(h_, w_, t, 16,
                                          label_smoothing=eps, z_loss=zl)[0]

    def naive(h_, w_):
        logits = h_ @ w_
        lse = jax.nn.logsumexp(logits, -1)
        tgt = jnp.take_along_axis(logits, t[:, None], -1)[:, 0]
        return (lse - (1 - eps) * tgt - (eps / 53) * logits.sum(-1)
                + zl * lse ** 2).mean()

    gh_f, gw_f = jax.grad(fused, argnums=(0, 1))(h, w)
    gh_n, gw_n = jax.grad(naive, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(gh_f, gh_n, atol=1e-6)
    np.testing.assert_allclose(gw_f, gw_n, atol=1e-6)


def test_gpt_loss_shaping_fused_matches_naive():
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    toks = jnp.asarray(
        np.random.default_rng(4).integers(0, 128, size=(2, 32)), jnp.int32)
    losses = {}
    for fused in (True, False):
        cfg = TransformerConfig(vocab_size=128, d_model=64, n_heads=2,
                                d_ff=128, n_layers=2, max_seq_len=32,
                                fused_loss=fused, label_smoothing=0.1,
                                z_loss=1e-3)
        model = GPT(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        loss, _ = model.training_step(params, toks, jax.random.PRNGKey(1))
        losses[fused] = float(loss)
    assert losses[True] == pytest.approx(losses[False], rel=1e-4)
