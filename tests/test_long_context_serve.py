"""Chunked long-prompt prefill interleaved with live decode
(serve/engine.py _PrefillCursor): token identity through the streaming
cursor (staggered joins, prefix hits mid-stream), the widened admission
window, zero steady-state recompiles with a long prefill in flight,
exactly-once block release on cancel, pool-starved cursors waiting on
their blocks-so-far, the new chunk metrics, and a replica-crash chaos
loop over all-chunk-eligible traffic.  All CPU, tier-1 fast."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu.models.transformer import (
    GPT, TransformerConfig)
from ray_lightning_accelerators_tpu.serve import (RequestRejected,
                                                  ServeCancelled,
                                                  ServeEngine)

pytestmark = [pytest.mark.serve, pytest.mark.paged,
              pytest.mark.long_context]


def _model(vocab=61, layers=2, max_seq_len=192, seed=0, d_model=32,
           n_heads=2, d_ff=64):
    cfg = TransformerConfig(vocab_size=vocab, d_model=d_model,
                            n_heads=n_heads, d_ff=d_ff, n_layers=layers,
                            max_seq_len=max_seq_len)
    m = GPT(cfg)
    return m, m.init_params(jax.random.PRNGKey(seed))


def _refs(model, params, reqs):
    return [np.asarray(model.generate(params, jnp.asarray(p[None]),
                                      max_new_tokens=n))[0]
            for p, n in reqs]


# --------------------------------------------------------------------- #
# Token identity through the streaming cursor                           #
# --------------------------------------------------------------------- #
def test_chunked_token_identical_staggered_long_and_short():
    """Long prompts (> chunk_blocks * block_len tokens) stream through
    the prefill cursor while short ones take the whole-prompt path and
    decode slots join/retire around them -- every response
    token-identical to standalone generate()."""
    model, params = _model()
    rng = np.random.default_rng(3)
    sizes = [70, 12, 97, 5, 120, 20]     # 3 chunk-eligible, 3 whole-path
    reqs = [(rng.integers(1, 60, size=(s,)).astype(np.int32),
             int(rng.integers(4, 9))) for s in sizes]
    refs = _refs(model, params, reqs)
    eng = ServeEngine(model, params, max_slots=3, queue_depth=32,
                      block_len=8, prefix_cache=False, slo=None)
    eng.start()
    try:
        resps = []
        for p, n in reqs:
            resps.append(eng.submit(p, n))
            time.sleep(0.02)             # stagger: cursors + live decode
        outs = [r.result(timeout=300) for r in resps]
    finally:
        eng.stop()
    for out, ref in zip(outs, refs):
        np.testing.assert_array_equal(out, ref)
    snap = eng.metrics.snapshot()
    assert snap["completed"] == 6
    # each long prompt took >= 2 chunk-prefill calls, each short exactly 1
    assert snap["prefill_chunks"] >= 9
    assert snap["longest_prefill_tokens"] == 120
    assert snap["active_long_prefills"] == 0   # every cursor promoted


def test_prefix_hit_starts_cursor_past_shared_run():
    """A second long prompt sharing a block-aligned prefix with an
    already-served one starts its cursor PAST the shared run (the hit's
    blocks are exact KV): it prefills in a single final chunk where the
    cold request streamed several, and stays token-identical."""
    model, params = _model()
    rng = np.random.default_rng(5)
    a = rng.integers(1, 60, size=(96,)).astype(np.int32)
    b = np.concatenate([a[:80],
                        rng.integers(1, 60, size=(17,)).astype(np.int32)])
    ref_a, ref_b = _refs(model, params, [(a, 4), (b, 4)])
    eng = ServeEngine(model, params, max_slots=2, queue_depth=8,
                      block_len=8, slo=None)   # prefix cache ON (default)
    eng.start()
    try:
        np.testing.assert_array_equal(
            eng.submit(a, 4).result(timeout=300), ref_a)
        chunks_cold = eng.metrics.snapshot()["prefill_chunks"]
        assert chunks_cold >= 2                # a genuinely streamed
        np.testing.assert_array_equal(
            eng.submit(b, 4).result(timeout=300), ref_b)
    finally:
        eng.stop()
    snap = eng.metrics.snapshot()
    assert snap["prefix_hits"] == 1
    assert snap["prefix_hit_blocks"] == 10     # 80 shared tokens / 8
    # the warm cursor skipped the shared run: one chunk, not a stream
    assert snap["prefill_chunks"] - chunks_cold == 1


# --------------------------------------------------------------------- #
# Admission window: the table span widens to the model's max_seq_len    #
# --------------------------------------------------------------------- #
def test_admission_accepts_past_bucket_up_to_model_max():
    """With chunked prefill on, a prompt far past the max_total_len
    bucket admits (and stays exact); past the MODEL's max_seq_len it
    still refuses typed; and with chunking off the same prompt refuses
    at the per-slot block-table budget."""
    model, params = _model(max_seq_len=128)
    rng = np.random.default_rng(11)
    p = rng.integers(1, 60, size=(100,)).astype(np.int32)
    ref = _refs(model, params, [(p, 4)])[0]
    eng = ServeEngine(model, params, max_slots=2, queue_depth=8,
                      max_total_len=64, block_len=8, slo=None)
    eng.start()
    try:
        np.testing.assert_array_equal(
            eng.submit(p, 4).result(timeout=300), ref)
        with pytest.raises(RequestRejected):   # 124 + 8 > max_seq_len
            eng.submit(rng.integers(1, 60, size=(124,)).astype(np.int32),
                       8)
    finally:
        eng.stop()
    blocking = ServeEngine(model, params, max_slots=2, queue_depth=8,
                           max_total_len=64, block_len=8, slo=None,
                           chunked_prefill=False)
    with pytest.raises(RequestRejected):       # 13 blocks > 8-block slot
        blocking.submit(p, 4)


# --------------------------------------------------------------------- #
# Compile hygiene: one program family, zero steady-state recompiles     #
# --------------------------------------------------------------------- #
def test_zero_steady_state_recompiles_with_long_prefill_in_flight():
    """The streaming cursor reuses the whole-prompt path's chunk-prefill
    program family: after warming every bucket a chunk can take (block
    multiples up to the big quantum), a long prompt streaming between
    live decode waves compiles NOTHING new."""
    from ray_lightning_accelerators_tpu.analysis.compile_guard import (
        compile_guard, install)
    install()
    model, params = _model()
    rng = np.random.default_rng(17)
    eng = ServeEngine(model, params, max_slots=3, queue_depth=32,
                      block_len=8, prefix_cache=False, slo=None)
    eng.start()
    try:
        # warm: whole-path prompts at every chunk bucket 8..64 (the
        # chunk quantum C is always one of these, and the final padded
        # tail rounds into them) -- 8 prefill programs + the paged step
        with compile_guard(max_new_compiles=9, label="lc-warm") as g:
            outs = [eng.submit(
                rng.integers(1, 60, size=(s,)).astype(np.int32), 4)
                for s in range(8, 65, 8)]
            for r in outs:
                r.result(timeout=300)
        assert g.new_compiles == 9, (
            "expected 8 chunk-prefill buckets + 1 paged step, got "
            f"{g.new_compiles}")
        # steady state: a 120-token prompt streams through the cursor
        # while two decode streams run live -- zero new programs
        reqs = [(rng.integers(1, 60, size=(11,)).astype(np.int32), 12),
                (rng.integers(1, 60, size=(29,)).astype(np.int32), 12),
                (rng.integers(1, 60, size=(120,)).astype(np.int32), 6)]
        refs = _refs(model, params, reqs)
        with compile_guard(max_new_compiles=0, label="lc-steady"):
            resps = []
            for p, n in reqs:
                resps.append(eng.submit(p, n))
                time.sleep(0.02)
            outs2 = [r.result(timeout=300) for r in resps]
    finally:
        eng.stop()
    for out, ref in zip(outs2, refs):
        np.testing.assert_array_equal(out, ref)


# --------------------------------------------------------------------- #
# Block accounting: exactly-once release, blocks-so-far under pressure  #
# --------------------------------------------------------------------- #
def test_cancel_mid_stream_releases_cursor_blocks_exactly_once(
        monkeypatch):
    """Stopping the engine with a prefill cursor mid-stream fails the
    request typed and releases its blocks-so-far exactly once: the pool
    drains back to pristine (free == total, nothing leaked, nothing
    double-freed)."""
    monkeypatch.setenv("RLA_TPU_SERVE_CHUNK_BLOCKS", "1")  # 8-token chunks
    model, params = _model()
    rng = np.random.default_rng(23)
    p = rng.integers(1, 60, size=(160,)).astype(np.int32)
    eng = ServeEngine(model, params, max_slots=2, queue_depth=8,
                      block_len=8, prefix_cache=False, slo=None)
    eng.start()
    resp = eng.submit(p, 4)
    # catch the cursor live (20 chunks; the first compiles, so this
    # window is wide on CPU)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if eng.metrics.snapshot()["active_long_prefills"] >= 1:
            break
        time.sleep(0.002)
    else:
        pytest.fail("prefill cursor never became visible")
    eng.stop(cancel_active=True, timeout=30)
    with pytest.raises(ServeCancelled):
        resp.result(timeout=10)
    st = eng.allocator.stats()
    assert st["used"] == 0 and st["cached"] == 0
    assert st["free"] == st["total"]
    assert eng.metrics.snapshot()["cancelled"] == 1


def test_pool_starved_cursor_waits_holding_blocks_so_far():
    """A cursor that exhausts the pool mid-stream WAITS holding only its
    blocks-so-far (no deadlock, no upfront reservation): decode retires
    free blocks, the stream resumes, and both responses stay exact."""
    model, params = _model(max_seq_len=128)
    rng = np.random.default_rng(29)
    short = (rng.integers(1, 60, size=(8,)).astype(np.int32), 56)
    long_ = (rng.integers(1, 60, size=(104,)).astype(np.int32), 8)
    refs = _refs(model, params, [short, long_])
    # 17 blocks = 16 usable; short holds 8, the long stream needs 14 --
    # admission overcommits (22 <= 1.5 * 16), so the cursor MUST stall
    # at the full pool and finish only after the retire frees blocks
    eng = ServeEngine(model, params, max_slots=2, queue_depth=8,
                      block_len=8, n_blocks=17, prefix_cache=False,
                      pool_overcommit=1.5, slo=None)
    eng.start()
    try:
        r_short = eng.submit(*short)       # FIFO: admitted first
        r_long = eng.submit(*long_)
        # the starved state is observable: pool pegged while the cursor
        # is still live (decode has ~40 steps of slack past that point)
        deadline = time.monotonic() + 120
        pegged = False
        while time.monotonic() < deadline and not pegged:
            snap = eng.metrics.snapshot()
            pegged = (snap["block_pool_used"] == snap["block_pool_total"]
                      and snap["active_long_prefills"] >= 1)
            time.sleep(0.002)
        assert pegged, "cursor never hit the full pool"
        np.testing.assert_array_equal(r_short.result(timeout=300),
                                      refs[0])
        np.testing.assert_array_equal(r_long.result(timeout=300),
                                      refs[1])
    finally:
        eng.stop()
    # (exhaustion itself was proven by the pegged live gauge above --
    # peak_used_blocks only samples at admit/retire, not mid-stream)
    assert eng.metrics.snapshot()["completed"] == 2


# --------------------------------------------------------------------- #
# Observability: chunk metrics reset audit + Prometheus typing          #
# --------------------------------------------------------------------- #
def test_chunk_metrics_reset_audit_and_prometheus_typing():
    from ray_lightning_accelerators_tpu.serve.metrics import ServeMetrics
    from ray_lightning_accelerators_tpu.telemetry import MetricsRegistry
    m = ServeMetrics()
    m.bind_chunks(lambda: {"active_long_prefills": 2})
    for _ in range(3):
        m.inc("prefill_chunks")
    m.observe_long_prefill(320)
    m.observe_long_prefill(40)               # watermark keeps the max
    before = m.snapshot()
    assert before["prefill_chunks"] == 3
    assert before["active_long_prefills"] == 2
    assert before["longest_prefill_tokens"] == 320
    reg = MetricsRegistry()
    reg.add_serve(m, rank="driver")
    text = reg.prometheus_text()
    assert "# TYPE rla_tpu_serve_prefill_chunks_total counter" in text
    assert "# TYPE rla_tpu_serve_active_long_prefills gauge" in text
    assert "# TYPE rla_tpu_serve_longest_prefill_tokens gauge" in text
    m.reset()
    snap = m.snapshot()
    for k in ServeMetrics._COUNTERS:
        assert snap[k] == 0, f"reset missed counter {k!r}"
    assert snap["longest_prefill_tokens"] == 0   # watermark clears
    assert snap["active_long_prefills"] == 2     # live gauge, still bound


# --------------------------------------------------------------------- #
# Chaos: replica crash with every request chunk-eligible                #
# --------------------------------------------------------------------- #
_CHAOS_CFG = dict(vocab_size=61, d_model=32, n_heads=2, d_ff=64,
                  n_layers=2, max_seq_len=128)


def _chunked_factory(np_params):
    """Engine factory executed inside each worker (cloudpickled closure;
    params travel as numpy).  Chunked prefill stays at its default ON --
    every prompt below is long enough to stream."""
    def make():
        from ray_lightning_accelerators_tpu.models.transformer import (
            GPT, TransformerConfig)
        from ray_lightning_accelerators_tpu.serve import ServeEngine
        model = GPT(TransformerConfig(**_CHAOS_CFG))
        return ServeEngine(model, np_params, max_slots=4,
                           queue_depth=64, block_len=8, slo=None)
    return make


@pytest.mark.chaos
def test_tier_survives_replica_crash_with_long_prompts(tmp_path):
    """2 replicas, every prompt chunk-eligible (> chunk_blocks *
    block_len tokens), replica 1 crashes ONCE on its first chunk -- the
    stranded streaming-prefill requests requeue head-of-line, re-prefill
    from scratch exactly-once on the survivor's cursor, the breaker
    revives the crashed replica, and every response stays
    token-identical to generate()."""
    from ray_lightning_accelerators_tpu.serve import (ControllerConfig,
                                                      ServeReplicas)

    model = GPT(TransformerConfig(**_CHAOS_CFG))
    params = model.init_params(jax.random.PRNGKey(0))
    np_params = jax.tree.map(np.asarray, params)
    ns = str(tmp_path / "chaos-ns")
    hb = {"RLA_TPU_WORKER_HEARTBEAT_S": "0.1"}
    envs = [dict(hb),
            dict(hb, RLA_TPU_CHAOS="crash@replica1:chunk1:once",
                 RLA_TPU_CHAOS_NS=ns)]
    cfg = ControllerConfig(
        hedge=False, max_retries=4, retry_backoff_s=0.01,
        retry_backoff_cap_s=0.1, revive_backoff_s=0.2,
        revive_backoff_cap_s=1.0, poll_s=0.05)
    rng = np.random.default_rng(31)

    def wave(n):
        return [(rng.integers(1, 60, size=int(s)).astype(np.int32),
                 int(m)) for s, m in zip(rng.integers(70, 101, size=n),
                                         rng.integers(3, 6, size=n))]

    group = ServeReplicas(
        _chunked_factory(np_params), num_replicas=2, chunk_size=2,
        heartbeat_s=0.1, wedge_timeout_s=1.2, queue_depth=64,
        env_per_worker=envs, controller=cfg)
    try:
        # waves of long prompts until the crash fired AND its requests
        # came back through the requeue lane; every wave checked exact
        deadline = time.monotonic() + 150
        healed = False
        while time.monotonic() < deadline:
            pairs = wave(4)
            refs = _refs(model, params, pairs)
            handles = [group.submit(p, m) for p, m in pairs]
            for ref, h in zip(refs, handles):
                np.testing.assert_array_equal(h.result(timeout=300), ref)
            snap = group.metrics.snapshot()
            if snap["requeued"] >= 1:
                healed = True
                break
        assert healed, group.stats()["controller"]
        snap = group.stats()
        assert snap["controller"]["replicas"]["1"]["infra_failures"] >= 1
        # exactly-once over the whole run (and every response above was
        # asserted token-identical)
        assert snap["failed"] == 0
        assert snap["cancelled"] == 0
        assert snap["completed"] == snap["submitted"]
    finally:
        group.shutdown()
