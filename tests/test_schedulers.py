"""Trial schedulers: ASHA rung decisions, median stopping, cooperative
trainer stop through the Tune callbacks."""

import numpy as np
import pytest

from ray_lightning_accelerators_tpu import (RayTPUAccelerator, Trainer,
                                            tune)
from ray_lightning_accelerators_tpu.tune import (ASHAScheduler,
                                                 MedianStoppingRule,
                                                 TuneReportCallback)
from tests.utils import BoringModel, boring_loaders


class _T:
    """Minimal trial stand-in for unit-level scheduler calls."""
    trial_id = "t"


def _res(it, loss):
    return {"training_iteration": it, "loss": loss}


def test_asha_rungs_and_cutoffs():
    s = ASHAScheduler(metric="loss", mode="min", max_t=16, grace_period=1,
                      reduction_factor=4)
    assert s.rungs == [1, 4]
    # first rf-1 results at a rung continue optimistically
    assert s.on_result(_T(), _res(1, 5.0)) == s.CONTINUE
    assert s.on_result(_T(), _res(1, 1.0)) == s.CONTINUE
    assert s.on_result(_T(), _res(1, 4.0)) == s.CONTINUE
    # 4th result: cutoff = best 1/4 of [5,1,4,x]
    assert s.on_result(_T(), _res(1, 0.5)) == s.CONTINUE  # new best
    assert s.on_result(_T(), _res(1, 9.0)) == s.STOP      # clearly worst
    # non-rung iterations never stop
    assert s.on_result(_T(), _res(2, 99.0)) == s.CONTINUE
    # max_t always stops
    assert s.on_result(_T(), _res(16, 0.0)) == s.STOP


def test_asha_max_mode():
    s = ASHAScheduler(metric="acc", mode="max", max_t=8, grace_period=1,
                      reduction_factor=2)
    for v in (0.1, 0.9):
        s.on_result(_T(), {"training_iteration": 1, "acc": v})
    assert s.on_result(
        _T(), {"training_iteration": 1, "acc": 0.95}) == s.CONTINUE
    assert s.on_result(
        _T(), {"training_iteration": 1, "acc": 0.05}) == s.STOP


def test_median_stopping_rule():
    s = MedianStoppingRule(metric="loss", mode="min", grace_period=1)
    for v in (1.0, 2.0, 3.0):
        s.on_result(_T(), _res(2, v))
    assert s.on_result(_T(), _res(2, 10.0)) == s.STOP
    assert s.on_result(_T(), _res(2, 0.1)) == s.CONTINUE


def test_tune_run_with_asha_stops_bad_trials():
    # trainable reports a loss equal to its config value every iteration for
    # 6 iterations; with grid [0.1, 5.0, 6.0, 7.0] and rungs at 1,2,4 the
    # bad configs stop early while the best runs to completion
    def trainable(config):
        for _ in range(6):
            tune.report(loss=config["lr"])
            if tune.trial_should_stop():
                return

    analysis = tune.run(
        trainable,
        config={"lr": tune.grid_search([0.1, 5.0, 6.0, 7.0])},
        metric="loss", mode="min",
        scheduler=ASHAScheduler(max_t=6, grace_period=1,
                                reduction_factor=2),
        local_dir="/tmp/rla_tune_sched", name="asha_unit")
    iters = {t.config["lr"]: t.training_iteration for t in analysis.trials}
    assert analysis.best_config["lr"] == 0.1
    assert iters[0.1] == 6                      # survivor runs out max_t
    assert iters[6.0] < 6 and iters[7.0] < 6    # losers stopped early
    assert all(t.status in ("STOPPED", "TERMINATED")
               for t in analysis.trials)
    # the early-stopped losers are distinguishable from full runs
    assert analysis.trials[2].status == "STOPPED"


def test_scheduler_stops_trainer_via_callback():
    # end-to-end: Trainer + TuneReportCallback under tune.run with a
    # scheduler that stops everything after the first report
    class StopAll(tune.TrialScheduler):
        metric = "val_loss"

        def on_result(self, trial, result):
            return self.STOP

    def trainable(config):
        train, val = boring_loaders()
        trainer = Trainer(max_epochs=50, accelerator=RayTPUAccelerator(),
                          precision="f32", enable_checkpointing=False,
                          callbacks=[TuneReportCallback(["val_loss"])],
                          seed=0)
        trainer.fit(BoringModel(), train, val)
        return trainer.current_epoch

    analysis = tune.run(trainable, config={"x": 1}, metric="val_loss",
                        mode="min", scheduler=StopAll(),
                        local_dir="/tmp/rla_tune_sched", name="stopall")
    t = analysis.trials[0]
    assert t.status == "STOPPED"
    # trainer ended long before max_epochs=50
    assert t.training_iteration <= 3
