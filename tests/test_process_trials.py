"""Process-isolated tune trials: each trial in a fresh subprocess, crash ->
ERROR while the experiment completes (the reference's trial isolation --
Tune trials are separate processes, reference:
examples/ray_ddp_example.py:101-113)."""

import os

import numpy as np
import pytest

from ray_lightning_accelerators_tpu import tune

_ENV = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""}


def _report_pid(config):
    tune.report(loss=config["x"] ** 2, pid=float(os.getpid()))
    return "done"


def _crash_or_report(config):
    if config["x"] > 1.5:
        os._exit(7)  # hard crash: no exception, no cleanup
    tune.report(loss=config["x"])


def _trainer_trial(config):
    from ray_lightning_accelerators_tpu import (Trainer,
                                                TuneReportCheckpointCallback)
    from tests.utils import BlobsDataModule, LinearClassifier

    dm = BlobsDataModule(n=128, batch_size=16)
    trainer = Trainer(max_epochs=2, precision="f32", seed=0,
                      enable_checkpointing=False,
                      callbacks=[TuneReportCheckpointCallback(
                          {"loss": "val_loss"})],
                      default_root_dir=f"/tmp/proc_trial_{os.getpid()}")
    trainer.fit(LinearClassifier(lr=config["lr"]), datamodule=dm)


def test_process_trials_isolated(tmp_path):
    analysis = tune.run(_report_pid,
                        config={"x": tune.grid_search([1.0, 2.0, 3.0])},
                        num_samples=1, metric="loss", mode="min",
                        local_dir=str(tmp_path),
                        trial_executor="process", trial_env=_ENV)
    assert len(analysis.trials) == 3
    pids = {t.last_result["pid"] for t in analysis.trials}
    assert len(pids) == 3  # one fresh process per trial
    assert os.getpid() not in {int(p) for p in pids}
    assert analysis.best_config["x"] == 1.0
    assert all(t.status == "TERMINATED" for t in analysis.trials)


def test_crashed_trial_is_error_and_experiment_completes(tmp_path):
    analysis = tune.run(_crash_or_report,
                        config={"x": tune.grid_search([1.0, 2.0, 0.5])},
                        num_samples=1, metric="loss", mode="min",
                        local_dir=str(tmp_path),
                        raise_on_failed_trial=False,
                        trial_executor="process", trial_env=_ENV)
    by_x = {t.config["x"]: t for t in analysis.trials}
    assert by_x[2.0].status == "ERROR"
    assert by_x[2.0].error is not None
    assert by_x[1.0].status == "TERMINATED"
    assert by_x[0.5].status == "TERMINATED"
    assert analysis.best_config["x"] == 0.5  # survivors still ranked


def test_crashed_trial_raises_when_requested(tmp_path):
    with pytest.raises(Exception, match="died|exit"):
        tune.run(_crash_or_report,
                 config={"x": tune.grid_search([2.0])}, num_samples=1,
                 metric="loss", mode="min", local_dir=str(tmp_path),
                 raise_on_failed_trial=True,
                 trial_executor="process", trial_env=_ENV)


@pytest.mark.slow
def test_trainer_with_checkpoint_callback_in_process_trial(tmp_path):
    """The full report+checkpoint trampoline crosses the process boundary:
    metrics land in trial.results and the checkpoint is written
    DRIVER-side under the trial dir (reference: tune.py:128-142)."""
    analysis = tune.run(_trainer_trial,
                        config={"lr": tune.grid_search([0.05, 0.1])},
                        num_samples=1, metric="loss", mode="min",
                        local_dir=str(tmp_path),
                        trial_executor="process", trial_env=_ENV)
    assert len(analysis.trials) == 2
    for t in analysis.trials:
        assert t.status == "TERMINATED"
        assert t.training_iteration == 2  # one report per epoch
        assert np.isfinite(t.last_result["loss"])
    best = analysis.best_checkpoint
    assert best is not None and os.path.exists(best)
    assert str(tmp_path) in best  # written under the DRIVER's trial dir


def _loopy_trial(config):
    """Reports up to 12 times, polling the scheduler's stop decision after
    every report.  Event-driven: a process trial's report is a synchronous
    query (the driver records it AND runs the scheduler before it
    returns), so the immediately following poll deterministically sees the
    decision for THAT report -- no sleeps, no drain-timing tolerance."""
    from ray_lightning_accelerators_tpu import tune as tune_mod

    for _ in range(12):
        tune_mod.report(loss=config["loss"])
        if tune_mod.trial_should_stop():
            return "stopped"
    return "completed"


def test_scheduler_stop_ends_process_trial_early(tmp_path):
    """An ASHA STOP actually ends a process-isolated trial early (round-2
    weak #5: the decision was recorded but the trial burned its full
    budget).  Sequential trials + synchronous reports make the stop point
    exact: the bad trial reaches the rung-2 cutoff (good trial's 0.1
    already recorded there), is STOPped on that report, and sees the
    decision on its very next poll."""
    sched = tune.ASHAScheduler(metric="loss", mode="min",
                               grace_period=2, reduction_factor=2)
    analysis = tune.run(_loopy_trial,
                        config={"loss": tune.grid_search([0.1, 1.0])},
                        num_samples=1, metric="loss", mode="min",
                        local_dir=str(tmp_path), scheduler=sched,
                        trial_executor="process", trial_env=_ENV)
    by_loss = {t.config["loss"]: t for t in analysis.trials}
    good, bad = by_loss[0.1], by_loss[1.0]
    assert good.status == "TERMINATED"
    assert good.training_iteration == 12
    assert bad.status == "STOPPED"
    assert bad.training_iteration == 2  # stopped AT the rung-2 decision


def test_process_trials_over_agents(tmp_path):
    """Trial subprocesses place round-robin over host agents (the
    reference's trials-anywhere-on-the-cluster placement); a crashed trial
    is contained as ERROR while the experiment completes."""
    from ray_lightning_accelerators_tpu.runtime.agent import HostAgent

    hosts = [HostAgent(port=0, bind="127.0.0.1") for _ in range(2)]
    for a in hosts:
        a.serve_in_background()
    addrs = [f"127.0.0.1:{a.port}" for a in hosts]
    try:
        analysis = tune.run(
            _crash_or_report,
            config={"x": tune.grid_search([1.0, 2.0, 0.5, 0.7])},
            num_samples=1, metric="loss", mode="min",
            local_dir=str(tmp_path), raise_on_failed_trial=False,
            trial_executor="process", trial_env=_ENV, agents=addrs)
        by_x = {t.config["x"]: t for t in analysis.trials}
        assert by_x[2.0].status == "ERROR"
        assert by_x[2.0].error is not None
        for x in (1.0, 0.5, 0.7):
            assert by_x[x].status == "TERMINATED", x
        assert analysis.best_config["x"] == 0.5
    finally:
        for a in hosts:
            a.shutdown()


def test_resources_per_trial_caps_concurrency(tmp_path):
    # cpu request exceeding the host -> capped to 1, still completes
    analysis = tune.run(_report_pid,
                        config={"x": tune.grid_search([1.0, 2.0])},
                        num_samples=1, metric="loss", mode="min",
                        local_dir=str(tmp_path),
                        max_concurrent_trials=8,
                        resources_per_trial={"cpu": 10 ** 6},
                        trial_executor="process", trial_env=_ENV)
    assert all(t.status == "TERMINATED" for t in analysis.trials)


def _nested_fit_trial(config):
    """Trainable for a PROCESS trial that itself fans a 2-process
    distributed fit out through host agents.  Reports ride the fit-level
    queue's query channel and are FORWARDED to the tune driver one level
    up (runtime/bootstrap._nested_query_handler); a scheduler STOP
    reaches the fit workers the same way and ends training at the next
    epoch boundary."""
    import numpy as np

    from ray_lightning_accelerators_tpu import (DataLoader,
                                                HorovodRayAccelerator,
                                                Trainer, TuneReportCallback)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from tests.utils import BoringModel

    class ScoredModel(BoringModel):
        def __init__(self, score):
            super().__init__()
            self._score = float(score)

        def training_step(self, params, batch, rng):
            out = super().training_step(params, batch, rng)
            loss, metrics = out if isinstance(out, tuple) else (out, {})
            metrics = dict(metrics)
            # a constant, config-controlled metric so the ASHA decision
            # is deterministic
            metrics["score"] = jnp.full((), self._score)
            return loss, metrics

    import jax.numpy as jnp

    x = np.random.default_rng(0).normal(size=(32, 32)).astype("float32")
    trainer = Trainer(
        max_epochs=6, precision="f32", seed=0, enable_checkpointing=False,
        callbacks=[TuneReportCallback({"score": "score"},
                                      on="train_epoch_end")],
        accelerator=HorovodRayAccelerator(num_hosts=2, num_slots=1,
                                          agents=config["agents"]),
        default_root_dir=f"/tmp/nested_trial_{os.getpid()}")
    trainer.fit(ScoredModel(config["score"]),
                DataLoader(ArrayDataset(x), batch_size=8))
    return trainer.epochs_completed


@pytest.mark.slow
def test_scheduler_stop_reaches_fit_nested_in_process_trial(tmp_path):
    """Round-3 advisor finding: a STOP decision must reach a distributed
    fit nested inside a process trial (the fit-level QueueServer used to
    answer None -> the trial burned its full budget).  Reports forward up
    through the nested query handler, arrive exactly once per epoch
    (rank-0 gated), and the STOP ends the bad trial's fit early."""
    from ray_lightning_accelerators_tpu.runtime.agent import HostAgent

    agents = [HostAgent(port=0, bind="127.0.0.1") for _ in range(2)]
    for a in agents:
        a.serve_in_background()
    addrs = [f"127.0.0.1:{a.port}" for a in agents]
    sched = tune.ASHAScheduler(metric="score", mode="min",
                               grace_period=2, reduction_factor=2)
    try:
        analysis = tune.run(
            _nested_fit_trial,
            config={"score": tune.grid_search([0.1, 1.0]),
                    "agents": addrs},
            num_samples=1, metric="score", mode="min",
            local_dir=str(tmp_path), scheduler=sched,
            trial_executor="process", trial_env=_ENV)
        by = {t.config["score"]: t for t in analysis.trials}
        good, bad = by[0.1], by[1.0]
        assert good.status == "TERMINATED"
        assert good.training_iteration == 6   # one report per epoch
        assert bad.status == "STOPPED"
        # stopped AT the rung-2 decision: reported twice, fit ended at
        # that epoch boundary
        assert bad.training_iteration == 2
    finally:
        for a in agents:
            a.shutdown()
