"""Data pipeline tests: sharded sampler disjointness/determinism and collation
(contract from the reference's sampler assertions,
reference: ray_lightning/tests/test_ddp.py:45-79)."""

import numpy as np
import pytest

from ray_lightning_accelerators_tpu import (ArrayDataset, DataLoader,
                                            RandomDataset, ShardedSampler)


def test_sharded_sampler_disjoint_cover():
    n, reps = 64, 4
    shards = [list(ShardedSampler(n, reps, r, shuffle=False)) for r in range(reps)]
    flat = sorted(i for s in shards for i in s)
    assert flat == list(range(n))
    assert all(len(s) == n // reps for s in shards)


def test_sharded_sampler_shuffle_epochs():
    s = ShardedSampler(64, 2, 0, shuffle=True, seed=1)
    s.set_epoch(0)
    e0 = list(s)
    s.set_epoch(1)
    e1 = list(s)
    s.set_epoch(0)
    assert list(s) == e0  # deterministic per epoch
    assert e0 != e1      # varies across epochs


def test_sharded_sampler_pad_wraps():
    s = ShardedSampler(10, 4, 3, shuffle=False, drop_last=False)
    assert len(list(s)) == len(s) == 3


def test_dataloader_batches():
    dl = DataLoader(RandomDataset(8, 40), batch_size=16)
    batches = list(dl)
    assert len(batches) == 2 and batches[0].shape == (16, 8)


def test_array_dataset_collate():
    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    y = np.arange(10)
    dl = DataLoader(ArrayDataset(x, y), batch_size=5, shuffle=False)
    bx, by = next(iter(dl))
    assert bx.shape == (5, 2) and by.shape == (5,)
    np.testing.assert_array_equal(by, np.arange(5))


def test_injection_respected_for_user_sampler():
    ds = RandomDataset(8, 32)
    sampler = ShardedSampler(32, 2, 1, shuffle=False)
    dl = DataLoader(ds, batch_size=4, sampler=sampler)
    dl._inject_sampler(num_replicas=4, rank=0, shuffle=True)
    assert dl.sampler is sampler  # user samplers are never overridden


def test_sampler_rank_bounds():
    with pytest.raises(ValueError):
        ShardedSampler(10, 2, 5)
