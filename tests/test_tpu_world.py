"""On-chip persistent-world gate (opt-in, real TPU required).

Run with ``RLA_TPU_WORKER_PLATFORM=axon`` (or ``tpu``) and the driver
left on the default CPU test platform:

    RLA_TPU_WORKER_PLATFORM=axon python -m pytest tests/test_tpu_world.py -q

All other world-persistence evidence is CPU-gloo
(``test_agent.py::test_world_persists_across_entry_points``); this is
the one place the TPU *runtime claim* is exercised where a second claim
could actually conflict — the worker owns the chip for the whole
fit→test→predict span while the driver stays on CPU, mirroring the
reference's actors holding their GPUs from setup to teardown
(reference: ray_lightning/ray_ddp.py:99-121).  A respawn between entry
points would re-claim the device; ship-once reuse proves the dataset
crossed the tunnel once.
"""

import os

import numpy as np
import pytest

from ray_lightning_accelerators_tpu.runtime.agent import HostAgent

# conftest.py pops the var out of the ambient environment (so it cannot
# rewrite every other fan-out test's worker platform) and stashes it for
# this module to re-apply inside its own test scope
from tests.conftest import WORKER_PLATFORM_STASH as _WORKER_PLATFORM

pytestmark = pytest.mark.skipif(
    _WORKER_PLATFORM not in ("tpu", "axon"),
    reason="needs RLA_TPU_WORKER_PLATFORM=tpu|axon and a real chip")


def test_single_chip_world_persists_across_entry_points(tmp_path,
                                                        monkeypatch):
    monkeypatch.setenv("RLA_TPU_WORKER_PLATFORM", _WORKER_PLATFORM)
    from ray_lightning_accelerators_tpu import (Callback, DataLoader,
                                                HorovodRayAccelerator,
                                                Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from tests.utils import BoringModel

    class WorkerInfoCb(Callback):
        """Runs worker-side; records the worker's pid and backend into
        the metrics the driver re-hydrates."""

        def _stamp(self, trainer):
            import jax
            trainer.callback_metrics["worker_pid"] = float(os.getpid())
            trainer.callback_metrics["worker_on_tpu"] = float(
                jax.default_backend() in ("tpu", "axon"))

        def on_fit_end(self, trainer, module):
            self._stamp(trainer)

        def on_test_end(self, trainer, module):
            self._stamp(trainer)

    agent = HostAgent(port=0, bind="127.0.0.1")
    agent.serve_in_background()
    try:
        x = np.random.default_rng(0).normal(size=(64, 32)).astype(
            "float32")

        def loader():
            return DataLoader(ArrayDataset(x), batch_size=8,
                              shuffle=False)

        model = BoringModel()
        trainer = Trainer(max_epochs=1, precision="bf16", seed=0,
                          enable_checkpointing=False,
                          callbacks=[WorkerInfoCb()],
                          accelerator=HorovodRayAccelerator(
                              num_hosts=1, num_slots=1,
                              agents=[f"127.0.0.1:{agent.port}"]),
                          default_root_dir=str(tmp_path))
        trainer.fit(model, loader())
        assert trainer.callback_metrics["worker_on_tpu"] == 1.0
        fit_pid = trainer.callback_metrics["worker_pid"]
        assert fit_pid != float(os.getpid())  # really ran in the worker
        assert model.params is not None

        trainer.test(model, loader())
        assert trainer.callback_metrics["worker_on_tpu"] == 1.0
        assert trainer.callback_metrics["worker_pid"] == fit_pid

        preds = trainer.predict(model, loader())
        assert sum(np.shape(p)[0] for p in preds) == len(x)

        # the chip-holding worker spawned exactly once for the whole
        # fit -> test -> predict span (no re-claim between entry points)
        assert agent.spawn_count == 1
        stats = trainer._world.ship_stats
        assert stats["sent"] >= 1 and stats["reused"] >= 1, stats

        # teardown releases the world -- and with it the device claim --
        # so a fresh world (fresh claim) can form afterwards
        trainer.teardown()
        assert trainer._world is None
    finally:
        agent.shutdown()
