"""Actor runtime tests — behavioral port of the reference's actor-lifecycle
assertions (reference: tests/test_ddp.py:29-42 actor counts + DEAD-after-fit;
ray_ddp.py:21-27 env RPC; util.py:96-109 result pump) on the from-scratch
multiprocessing actor system, plus a real 2-process jax.distributed
all-reduce."""

import os
import time

import numpy as np
import pytest

from ray_lightning_accelerators_tpu.runtime.actors import (ActorPool,
                                                           RemoteError,
                                                           Worker)
from ray_lightning_accelerators_tpu.runtime.queue import (TrampolineQueue,
                                                          process_results)


def _sq(x):
    return x * x


def _getenv(k):
    return os.environ.get(k)


def _boom():
    raise ValueError("worker exploded")


def _pid():
    return os.getpid()


def _echo_big(arr):
    return arr * 2


def test_large_payloads_do_not_deadlock():
    """Requests/results far beyond the OS pipe buffer (~64KiB) must flow
    while earlier results are still in flight (regression: a single lock held
    across a blocking send could three-way-deadlock sender/collector/worker).
    """
    big = np.ones(1_000_000, dtype=np.float32)  # ~4MB each way
    with ActorPool(1) as pool:
        w = pool.workers[0]
        futs = [w.execute(_echo_big, big) for _ in range(4)]
        for f in futs:
            np.testing.assert_array_equal(f.result(timeout=60), big * 2)


def test_pool_executes_in_parallel_processes():
    with ActorPool(2) as pool:
        futs = pool.execute_all(_pid)
        pids = [f.result(timeout=60) for f in futs]
    assert len(set(pids)) == 2
    assert all(p != os.getpid() for p in pids)


def test_execute_returns_results_in_order():
    with ActorPool(1) as pool:
        futs = [pool.workers[0].execute(_sq, i) for i in range(5)]
        assert [f.result(timeout=60) for f in futs] == [0, 1, 4, 9, 16]


def test_env_propagation_prefork_and_rpc():
    """Env must be settable pre-fork (TPU topology vars) and via RPC
    (reference: ray_ddp.py:21-23,154-159)."""
    with ActorPool(2, env_per_worker=[{"RLA_T": "a"}, {"RLA_T": "b"}]) as pool:
        vals = [f.result(timeout=60)
                for f in pool.execute_all(_getenv, "RLA_T")]
        assert vals == ["a", "b"]
        pool.set_env_vars({"RLA_T2": "77"})
        vals = [f.result(timeout=60)
                for f in pool.execute_all(_getenv, "RLA_T2")]
        assert vals == ["77", "77"]


def test_remote_exception_carries_traceback():
    with ActorPool(1) as pool:
        fut = pool.workers[0].execute(_boom)
        with pytest.raises(RemoteError, match="worker exploded"):
            fut.result(timeout=60)


def test_closures_ship_via_cloudpickle():
    factor = 7
    with ActorPool(1) as pool:
        fut = pool.workers[0].execute(lambda x: x * factor, 6)
        assert fut.result(timeout=60) == 42


def test_local_ranks_census():
    with ActorPool(3) as pool:
        assert pool.local_ranks() == [0, 1, 2]  # same node -> 0,1,2


def test_workers_dead_after_shutdown():
    pool = ActorPool(2)
    procs = [w._proc for w in pool.workers]
    pool.shutdown()
    deadline = time.time() + 10
    while time.time() < deadline and any(p.is_alive() for p in procs):
        time.sleep(0.1)
    assert not any(p.is_alive() for p in procs)


def test_queue_shutdown_idempotent_drains_and_rejects():
    """TrampolineQueue.shutdown(): safe with requests still enqueued —
    drains them unexecuted (the caller cancels them typed), rejects later
    put()s with QueueShutdown, and is idempotent.  The serve engine's
    cancellation path rides this."""
    from ray_lightning_accelerators_tpu.runtime.queue import QueueShutdown

    ran = []
    q = TrampolineQueue()
    q.put((0, lambda: ran.append("a")))
    q.put((1, lambda: ran.append("b")))
    drained = q.shutdown()
    assert [r for r, _ in drained] == [0, 1]
    assert ran == []                      # drained, never executed
    assert q.closed
    assert q.get_nowait() is None
    assert q.shutdown() == []             # idempotent no-op
    with pytest.raises(QueueShutdown):
        q.put((2, lambda: ran.append("c")))
    assert ran == []


def test_process_results_pumps_queue_during_run():
    q = TrampolineQueue()
    seen = []
    q.put((0, lambda: seen.append("early")))
    with ActorPool(1) as pool:
        futs = pool.execute_all(time.sleep, 0.3)
        q.put((0, lambda: seen.append("mid")))
        process_results(futs, q)
    assert seen == ["early", "mid"]


def _distributed_psum(process_id, coord, nprocs):
    from ray_lightning_accelerators_tpu.runtime.bootstrap import (
        initialize_worker)
    initialize_worker(coord, nprocs, process_id, platform="cpu",
                      cpu_devices_per_process=1)
    import jax
    import jax.numpy as jnp
    from ray_lightning_accelerators_tpu.parallel.sharding import (
        shard_map_compat)

    assert jax.process_count() == nprocs
    out = shard_map_compat(
        lambda x: jax.lax.psum(x, "i"),
        mesh=jax.sharding.Mesh(jax.devices(), ("i",)),
        in_specs=jax.sharding.PartitionSpec("i"),
        out_specs=jax.sharding.PartitionSpec())(
            jnp.arange(float(nprocs)))
    return float(np.asarray(out)[0])


@pytest.mark.slow
def test_two_process_jax_distributed_allreduce():
    """The L1 bootstrap really forms a 2-process world whose psum crosses
    process boundaries (the reference's init_process_group analog,
    ray_ddp.py:222-237)."""
    from ray_lightning_accelerators_tpu.runtime.bootstrap import (
        pick_coordinator_address)

    coord = pick_coordinator_address()
    env = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""}
    with ActorPool(2, env_per_worker=[dict(env), dict(env)]) as pool:
        futs = pool.execute_per_worker(
            _distributed_psum, [(0, coord, 2), (1, coord, 2)])
        results = [f.result(timeout=180) for f in futs]
    assert results == [1.0, 1.0]  # 0 + 1 summed across processes


def _distributed_fit(process_id, coord, nprocs):
    from ray_lightning_accelerators_tpu.runtime.bootstrap import (
        initialize_worker)
    initialize_worker(coord, nprocs, process_id, platform="cpu",
                      cpu_devices_per_process=2)
    import jax
    import numpy as np

    # device-binding contract (reference pins the device/env mapping,
    # reference: tests/test_ddp_gpu.py:89-95): each process sees exactly
    # its cpu_devices_per_process devices, the global mesh spans all
    # processes' devices, and the rank mapping holds
    assert len(jax.local_devices()) == 2
    assert jax.device_count() == 2 * nprocs
    assert jax.process_index() == process_id
    assert {d.process_index for d in jax.devices()} == set(range(nprocs))
    from ray_lightning_accelerators_tpu import DataLoader, Trainer
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from tests.utils import BoringModel

    x = np.random.default_rng(0).normal(size=(64, 32)).astype("float32")
    model = BoringModel()
    trainer = Trainer(max_epochs=2, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=f"/tmp/dist_fit_{process_id}")
    trainer.fit(model, DataLoader(ArrayDataset(x), batch_size=8))
    leaf = np.asarray(jax.tree.leaves(model.params)[0], dtype=np.float64)
    return (trainer.global_step, float(leaf.sum()),
            float(trainer.callback_metrics["loss"]))


@pytest.mark.slow
def test_two_process_full_training():
    """End-to-end Trainer.fit across a REAL 2-process jax.distributed world
    (2 procs x 2 cpu devices = 4-device mesh): per-process sampler shards,
    cross-process batch assembly, gradient psum via sharding.  Both ranks
    must agree on step count and final (SPMD-replicated) weights -- the
    multi-host analog of the reference's DDP weight-sync guarantee."""
    from ray_lightning_accelerators_tpu.runtime.bootstrap import (
        pick_coordinator_address)

    coord = pick_coordinator_address()
    env = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""}
    with ActorPool(2, env_per_worker=[dict(env), dict(env)]) as pool:
        futs = pool.execute_per_worker(
            _distributed_fit, [(0, coord, 2), (1, coord, 2)])
        results = [f.result(timeout=300) for f in futs]
    steps0, wsum0, loss0 = results[0]
    steps1, wsum1, loss1 = results[1]
    # 64 samples / 2 replicas / batch 8 = 4 steps/epoch x 2 epochs
    assert steps0 == steps1 == 8
    assert wsum0 == pytest.approx(wsum1, rel=1e-6)
    assert loss0 == pytest.approx(loss1, rel=1e-5)
