"""Profiler subsystem: spans, sync mode, summaries, trainer integration,
device traces (the first-class tracing subsystem SURVEY.md §5.1 calls for —
the reference has none)."""

import glob
import os
import time

import jax
import jax.numpy as jnp
import pytest

from ray_lightning_accelerators_tpu import (Profiler, RayTPUAccelerator,
                                            Trainer, device_memory_stats)
from tests.utils import BoringModel, boring_loaders


def test_spans_nest_and_count():
    prof = Profiler()
    for _ in range(3):
        with prof.span("outer"):
            with prof.span("inner"):
                time.sleep(0.001)
    s = prof.summary()
    assert s["outer"]["count"] == 3
    assert s["outer/inner"]["count"] == 3
    assert s["outer"]["total_s"] >= s["outer/inner"]["total_s"] > 0
    for k in ("count", "total_s", "mean_s", "p50_s", "p95_s"):
        assert k in s["outer"]
    assert "outer/inner" in prof.describe()
    prof.reset()
    assert prof.summary() == {}


def test_sync_span_blocks_on_device_outputs():
    prof = Profiler(sync=True)

    @jax.jit
    def work(x):
        for _ in range(20):
            x = x @ x
        return x

    x = jnp.ones((512, 512)) * 0.001
    work(x).block_until_ready()  # compile outside the span
    with prof.span("dispatch_only"):
        y = work(x)
    y.block_until_ready()
    with prof.span("synced") as h:
        h.set(work(x))
    s = prof.summary()
    # the synced span includes device compute; dispatch-only does not
    assert s["synced"]["total_s"] >= s["dispatch_only"]["total_s"]


def test_trainer_profiler_integration():
    # host-fed path (cache off): fetch/h2d/step spans per batch
    prof = Profiler()
    train, val = boring_loaders()
    trainer = Trainer(max_epochs=2, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False,
                      profiler=prof, log_every_n_steps=10 ** 9, seed=0,
                      cache_dataset_on_device=False)
    trainer.fit(BoringModel(), train, val)
    s = prof.summary()
    assert s["train_step"]["count"] == trainer.global_step > 0
    assert s["data_fetch"]["count"] >= trainer.global_step
    assert s["h2d"]["count"] == trainer.global_step
    assert s["validation"]["count"] == 2


def test_trainer_profiler_integration_cached_path():
    # device-cached path: train_step spans only (no per-batch h2d)
    prof = Profiler()
    train, val = boring_loaders()
    trainer = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False,
                      profiler=prof, log_every_n_steps=10 ** 9, seed=0,
                      cache_dataset_on_device=True)
    trainer.fit(BoringModel(), train, val)
    s = prof.summary()
    assert s["train_step"]["count"] == trainer.global_step > 0
    assert "h2d" not in s


def test_device_trace_roundtrip(tmp_path):
    prof = Profiler()
    log_dir = str(tmp_path / "trace")
    with prof.trace(log_dir):
        jnp.ones((64, 64)).sum().block_until_ready()
    produced = glob.glob(os.path.join(log_dir, "**", "*"), recursive=True)
    assert any(os.path.isfile(p) for p in produced), produced
    # a second trace works after the first closed
    with prof.trace(str(tmp_path / "trace2")):
        pass


def test_trace_double_start_raises(tmp_path):
    prof = Profiler()
    prof.start_trace(str(tmp_path / "t"))
    try:
        with pytest.raises(RuntimeError, match="already running"):
            prof.start_trace(str(tmp_path / "t2"))
    finally:
        prof.stop_trace()
    assert prof.stop_trace() is None  # idempotent


def test_device_memory_stats_shape():
    stats = device_memory_stats()
    assert len(stats) == len(jax.local_devices())
    assert all(isinstance(d, dict) for d in stats)


def test_flops_estimate_and_mfu():
    import jax.numpy as jnp
    from ray_lightning_accelerators_tpu.utils.profiler import (flops_estimate,
                                                               mfu)

    def f(a, b):
        return a @ b

    a = jnp.ones((128, 256), jnp.float32)
    b = jnp.ones((256, 64), jnp.float32)
    fl = flops_estimate(f, a, b)
    if fl is not None:  # cpu backend may omit cost analysis
        # matmul flops = 2*M*N*K
        assert fl == pytest.approx(2 * 128 * 256 * 64, rel=0.5)
    # explicit peak: 1 TFLOP/s peak, 1e9 flops in 1ms = 100% MFU
    assert mfu(1e9, 1e-3, peak_flops=1e12) == pytest.approx(1.0)
