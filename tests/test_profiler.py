"""Profiler subsystem: spans, sync mode, summaries, trainer integration,
device traces (the first-class tracing subsystem SURVEY.md §5.1 calls for —
the reference has none)."""

import glob
import os
import time

import jax
import jax.numpy as jnp
import pytest

from ray_lightning_accelerators_tpu import (Profiler, RayTPUAccelerator,
                                            Trainer, device_memory_stats)
from tests.utils import BoringModel, boring_loaders


def test_spans_nest_and_count():
    prof = Profiler()
    for _ in range(3):
        with prof.span("outer"):
            with prof.span("inner"):
                time.sleep(0.001)
    s = prof.summary()
    assert s["outer"]["count"] == 3
    assert s["outer/inner"]["count"] == 3
    assert s["outer"]["total_s"] >= s["outer/inner"]["total_s"] > 0
    for k in ("count", "total_s", "mean_s", "p50_s", "p95_s", "p99_s",
              "max_s"):
        assert k in s["outer"]
    assert "outer/inner" in prof.describe()
    prof.reset()
    assert prof.summary() == {}


def test_tail_percentiles_and_exact_max():
    """p99 sits in the tail of the reservoir and max_s is the EXACT
    maximum (it must survive even when the reservoir would evict it)."""
    prof = Profiler()
    for i in range(1, 101):           # 1ms..100ms, deterministic
        prof.observe("op", i / 1000.0)
    s = prof.summary()["op"]
    assert s["count"] == 100
    assert s["p50_s"] == pytest.approx(0.050, abs=0.002)
    assert s["p95_s"] == pytest.approx(0.095, abs=0.002)
    assert s["p99_s"] == pytest.approx(0.099, abs=0.002)
    assert s["p99_s"] >= s["p95_s"] >= s["p50_s"]
    assert s["max_s"] == pytest.approx(0.100)
    # beyond the reservoir cap the exact max still survives
    prof.observe("op", 9.9)
    for _ in range(5000):
        prof.observe("op", 0.001)
    assert prof.summary()["op"]["max_s"] == pytest.approx(9.9)
    # describe() renders the new tail columns
    head = prof.describe().splitlines()[0]
    assert "p99" in head and "max" in head


def test_sync_span_blocks_on_device_outputs():
    prof = Profiler(sync=True)

    @jax.jit
    def work(x):
        for _ in range(20):
            x = x @ x
        return x

    x = jnp.ones((512, 512)) * 0.001
    work(x).block_until_ready()  # compile outside the span
    with prof.span("dispatch_only"):
        y = work(x)
    y.block_until_ready()
    with prof.span("synced") as h:
        h.set(work(x))
    s = prof.summary()
    # the synced span includes device compute; dispatch-only does not
    assert s["synced"]["total_s"] >= s["dispatch_only"]["total_s"]


def test_trainer_profiler_integration():
    # SYNCHRONOUS host-fed path (cache off, prefetch off): fetch/h2d/step
    # spans per batch.  The async-pipeline span shape (h2d_wait /
    # prefetch_depth / starvation) is pinned in test_prefetch.py.
    prof = Profiler()
    train, val = boring_loaders()
    trainer = Trainer(max_epochs=2, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False,
                      profiler=prof, log_every_n_steps=10 ** 9, seed=0,
                      cache_dataset_on_device=False, prefetch_batches=0)
    trainer.fit(BoringModel(), train, val)
    s = prof.summary()
    assert s["train_step"]["count"] == trainer.global_step > 0
    assert s["data_fetch"]["count"] >= trainer.global_step
    assert s["h2d"]["count"] == trainer.global_step
    assert s["validation"]["count"] == 2


def test_trainer_profiler_integration_cached_path():
    # device-cached path: train_step spans only (no per-batch h2d)
    prof = Profiler()
    train, val = boring_loaders()
    trainer = Trainer(max_epochs=1, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False,
                      profiler=prof, log_every_n_steps=10 ** 9, seed=0,
                      cache_dataset_on_device=True)
    trainer.fit(BoringModel(), train, val)
    s = prof.summary()
    assert s["train_step"]["count"] == trainer.global_step > 0
    assert "h2d" not in s


def test_device_trace_roundtrip(tmp_path):
    prof = Profiler()
    log_dir = str(tmp_path / "trace")
    with prof.trace(log_dir):
        jnp.ones((64, 64)).sum().block_until_ready()
    produced = glob.glob(os.path.join(log_dir, "**", "*"), recursive=True)
    assert any(os.path.isfile(p) for p in produced), produced
    # a second trace works after the first closed
    with prof.trace(str(tmp_path / "trace2")):
        pass


def test_trace_double_start_raises(tmp_path):
    prof = Profiler()
    prof.start_trace(str(tmp_path / "t"))
    try:
        with pytest.raises(RuntimeError, match="already running"):
            prof.start_trace(str(tmp_path / "t2"))
    finally:
        prof.stop_trace()
    assert prof.stop_trace() is None  # idempotent


def test_device_memory_stats_shape():
    stats = device_memory_stats()
    assert len(stats) == len(jax.local_devices())
    assert all(isinstance(d, dict) for d in stats)


def test_flops_estimate_and_mfu():
    import jax.numpy as jnp
    from ray_lightning_accelerators_tpu.utils.profiler import (flops_estimate,
                                                               mfu)

    def f(a, b):
        return a @ b

    a = jnp.ones((128, 256), jnp.float32)
    b = jnp.ones((256, 64), jnp.float32)
    fl = flops_estimate(f, a, b)
    if fl is not None:  # cpu backend may omit cost analysis
        # matmul flops = 2*M*N*K
        assert fl == pytest.approx(2 * 128 * 256 * 64, rel=0.5)
    # explicit peak: 1 TFLOP/s peak, 1e9 flops in 1ms = 100% MFU
    assert mfu(1e9, 1e-3, peak_flops=1e12) == pytest.approx(1.0)


def test_trace_op_summary_parses_device_events(tmp_path):
    """trace_op_summary reads an XPlane-exported trace.json.gz, keeps only
    device-clock events, resolves nesting (a scan's children don't
    double-count against it), and reports achieved GB/s / TF/s."""
    import gzip
    import json

    from ray_lightning_accelerators_tpu.utils.profiler import (
        trace_events, trace_op_summary)

    # synthetic trace: one while(0..1000us) containing two fusions
    # (400us @ 1GB read, 500us of matmul flops), plus a host event that
    # must be ignored (no device_duration_ps)
    def dev(name, cat, off_us, dur_us, nbytes=0, flops=0):
        return {"ph": "X", "name": name, "pid": 3, "ts": off_us,
                "dur": dur_us,
                "args": {"device_offset_ps": str(int(off_us * 1e6)),
                         "device_duration_ps": str(int(dur_us * 1e6)),
                         "hlo_category": cat,
                         "raw_bytes_accessed": str(nbytes),
                         "model_flops": str(flops)}}

    trace = {"traceEvents": [
        dev("while.1", "while", 0, 1000),
        dev("fusion.1", "loop fusion", 10, 400, nbytes=10 ** 9),
        dev("fusion.2", "convolution fusion", 450, 500,
            flops=50 * 10 ** 12 * 500 // 10 ** 6),
        {"ph": "X", "name": "host_thing", "pid": 701, "ts": 0, "dur": 5},
        # a SECOND device timeline overlapping the first: concurrent
        # chips must not read as parent/child of chip 0's while
        {**dev("other_chip_op", "data formatting", 100, 300), "pid": 4},
    ]}
    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump(trace, f)

    evs = trace_events(str(tmp_path))
    assert [e["name"] for e in evs] == ["while.1", "fusion.1",
                                       "other_chip_op", "fusion.2"]

    s = trace_op_summary(str(tmp_path))
    # chip 0's 1000us + chip 1's 300us, nothing double-counted
    assert s["total_ms"] == pytest.approx(1.3, rel=1e-6)
    by = s["by_category"]
    # while self time = 1000 - 900 nested on ITS OWN timeline = 100us
    # (the other chip's overlapping 300us op must not subtract)
    assert by["while"]["self_ms"] == pytest.approx(0.1, rel=1e-6)
    # 1 GB in 400us = 2500 GB/s
    assert by["loop fusion"]["gbps"] == pytest.approx(2500.0, rel=1e-3)
    assert by["convolution fusion"]["tfs"] == pytest.approx(50.0, rel=1e-3)
    names = [o["name"] for o in s["ops"]]
    assert "host_thing" not in names
