"""Real-dataset ingestion: MNIST IDX + CIFAR-10 binary parsers and their
datamodule integration (reference trains/gates on actual MNIST,
reference: examples/ray_ddp_example.py:37-42,
ray_lightning/tests/utils.py:137-152 -- here the files are parsed directly
with no torchvision and no downloads)."""

import gzip
import os
import struct

import numpy as np
import pytest

from ray_lightning_accelerators_tpu.data import vision
from ray_lightning_accelerators_tpu.models.mnist import (MNISTClassifier,
                                                         MNISTDataModule)
from ray_lightning_accelerators_tpu.models.resnet import CIFAR10DataModule


def _write_idx(dirpath, stem, images, labels, gz=False):
    n, r, c = images.shape
    img_blob = struct.pack(">IIII", 0x803, n, r, c) + images.tobytes()
    lbl_blob = struct.pack(">II", 0x801, n) + labels.tobytes()
    op = (lambda p: gzip.open(p, "wb")) if gz else (lambda p: open(p, "wb"))
    suffix = ".gz" if gz else ""
    with op(os.path.join(dirpath, f"{stem}-images-idx3-ubyte{suffix}")) as f:
        f.write(img_blob)
    with op(os.path.join(dirpath, f"{stem}-labels-idx1-ubyte{suffix}")) as f:
        f.write(lbl_blob)


def _fake_mnist_dir(tmp_path, n=64, gz=False):
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(n, 28, 28), dtype=np.uint8)
    y = rng.integers(0, 10, size=(n,), dtype=np.uint8)
    _write_idx(str(tmp_path), "train", x, y, gz=gz)
    _write_idx(str(tmp_path), "t10k", x[: n // 2], y[: n // 2], gz=gz)
    return x, y


@pytest.mark.parametrize("gz", [False, True])
def test_mnist_idx_roundtrip(tmp_path, gz):
    x, y = _fake_mnist_dir(tmp_path, gz=gz)
    got = vision.load_mnist(str(tmp_path), "train")
    assert got is not None
    gx, gy = got
    assert gx.shape == (64, 28, 28) and gx.dtype == np.float32
    np.testing.assert_allclose(gx, x.astype(np.float32) / 255.0)
    np.testing.assert_array_equal(gy, y.astype(np.int32))
    tx, ty = vision.load_mnist(str(tmp_path), "test")
    assert len(tx) == 32 and (ty == y[:32]).all()


def test_mnist_idx_bad_magic(tmp_path):
    p = tmp_path / "train-images-idx3-ubyte"
    p.write_bytes(struct.pack(">IIII", 0xDEAD, 1, 28, 28) + b"\0" * 784)
    with pytest.raises(ValueError, match="magic"):
        vision.read_idx_images(str(p))


def test_mnist_missing_returns_none(tmp_path):
    assert vision.load_mnist(str(tmp_path), "train") is None


def _fake_cifar_dir(tmp_path, per_batch=8):
    rng = np.random.default_rng(1)
    d = tmp_path / "cifar-10-batches-bin"
    d.mkdir()
    all_x, all_y = [], []
    for name in [f"data_batch_{i}.bin" for i in range(1, 6)] + \
                ["test_batch.bin"]:
        y = rng.integers(0, 10, size=(per_batch,), dtype=np.uint8)
        x = rng.integers(0, 256, size=(per_batch, 3, 32, 32), dtype=np.uint8)
        rec = np.concatenate(
            [y[:, None], x.reshape(per_batch, -1)], axis=1).astype(np.uint8)
        (d / name).write_bytes(rec.tobytes())
        if name.startswith("data"):
            all_x.append(x)
            all_y.append(y)
    return np.concatenate(all_x), np.concatenate(all_y)


def test_cifar_binary_roundtrip(tmp_path):
    x, y = _fake_cifar_dir(tmp_path)
    got = vision.load_cifar10(str(tmp_path), "train")
    assert got is not None
    gx, gy = got
    assert gx.shape == (40, 32, 32, 3) and gx.dtype == np.float32
    # channel-major on disk -> NHWC in memory
    np.testing.assert_allclose(
        gx, x.transpose(0, 2, 3, 1).astype(np.float32) / 255.0)
    np.testing.assert_array_equal(gy, y.astype(np.int32))
    tx, _ = vision.load_cifar10(str(tmp_path), "test")
    assert tx.shape == (8, 32, 32, 3)


def test_cifar_missing_returns_none(tmp_path):
    assert vision.load_cifar10(str(tmp_path), "train") is None


def test_mnist_datamodule_prefers_real(tmp_path):
    _fake_mnist_dir(tmp_path)
    dm = MNISTDataModule(batch_size=8, n_train=48, n_val=16,
                         data_dir=str(tmp_path))
    dm.setup("fit")
    assert dm.source == "real"
    xb, yb = next(iter(dm.train_dataloader()))
    assert xb.shape == (8, 28, 28)
    # the t10k split backs test_dataloader
    test_x, _ = next(iter(dm.test_dataloader()))
    assert test_x.shape[1:] == (28, 28)

    dm2 = MNISTDataModule(batch_size=8, n_train=48, n_val=16)
    dm2.setup("fit")
    assert dm2.source == "synthetic"


def test_cifar_datamodule_prefers_real(tmp_path):
    _fake_cifar_dir(tmp_path, per_batch=16)
    dm = CIFAR10DataModule(batch_size=8, n_train=64, n_val=16,
                           data_dir=str(tmp_path))
    dm.setup("fit")
    assert dm.source == "real"
    xb, yb = next(iter(dm.train_dataloader()))
    assert xb.shape == (8, 32, 32, 3)
    dm2 = CIFAR10DataModule(batch_size=8, n_train=64, n_val=16,
                            data_dir=str(tmp_path / "nope"))
    dm2.setup("fit")
    assert dm2.source == "synthetic"


def test_predict_gate_on_real_mnist(tmp_path):
    """predict_test (the reference's accuracy >= 0.5 gate) over the
    real-data path.  Uses generated IDX files standing in for mounted
    MNIST; with genuine files ($RLA_TPU_DATA_DIR) the same code runs on
    the true digits."""
    from ray_lightning_accelerators_tpu import Trainer
    from tests.utils import predict_test

    data_dir = os.environ.get("RLA_TPU_DATA_DIR")
    if not data_dir or vision.load_mnist(data_dir, "train") is None:
        # deterministic learnable stand-in: class-striped images
        rng = np.random.default_rng(2)
        y = rng.integers(0, 10, size=(512,), dtype=np.uint8)
        x = np.zeros((512, 28, 28), dtype=np.uint8)
        for i, yi in enumerate(y):
            x[i, yi * 2: yi * 2 + 3, :] = 255
        x += rng.integers(0, 40, size=x.shape, dtype=np.uint8)
        _write_idx(str(tmp_path), "train", x, y)
        _write_idx(str(tmp_path), "t10k", x[:128], y[:128])
        data_dir = str(tmp_path)

    dm = MNISTDataModule(batch_size=32, n_train=448, n_val=64,
                         data_dir=data_dir)
    dm.setup("fit")
    assert dm.source == "real"
    model = MNISTClassifier({"lr": 1e-3, "batch_size": 32})
    # 8 epochs: 4 epochs (56 steps) left the gate on a knife edge --
    # measured 0.4765 vs the 0.5 bar on this jax build, deterministic
    # run-to-run, reproduced on clean HEAD; the smoke gate's intent is
    # "the pipeline learns real data", not "converge in 56 steps"
    trainer = Trainer(max_epochs=8, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmp_path / "run"))
    predict_test(trainer, model, dm)


def test_bundled_real_mnist_subset_loads():
    """The committed real-MNIST IDX subset (tests/data/mnist) parses as
    genuine MNIST: balanced digits, [0,1] float pixels, matching splits.
    bench.py uses it as the no-mount real-data fallback."""
    import os

    from ray_lightning_accelerators_tpu.data import vision

    here = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "mnist")
    train = vision.load_mnist(here, "train")
    test = vision.load_mnist(here, "test")
    assert train is not None and test is not None
    x, y = train
    assert x.shape == (1024, 28, 28) and y.shape == (1024,)
    assert x.dtype == np.float32 and 0.0 <= x.min() and x.max() <= 1.0
    # every digit present, none dominating (a real sample, not stripes)
    counts = np.bincount(y, minlength=10)
    assert counts.min() >= 50 and counts.max() <= 200
    xt, yt = test
    assert len(xt) == len(yt) and len(xt) >= 128
