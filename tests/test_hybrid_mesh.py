"""Hybrid ICI x DCN mesh construction (multi-slice layout math)."""

import numpy as np
import pytest

from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib


class FakeDev:
    """Minimal stand-in with the attributes mesh_utils consults."""

    def __init__(self, i, slice_index, per_slice):
        self.id = i
        self.slice_index = slice_index
        self.process_index = slice_index
        self.platform = "cpu"
        self.device_kind = "fake-cpu"
        self.coords = None

    def __repr__(self):
        return f"d{self.id}@s{self.slice_index}"


def _fake_slices(n_slices, per_slice):
    return [FakeDev(s * per_slice + i, s, per_slice)
            for s in range(n_slices) for i in range(per_slice)]


def test_single_slice_falls_back_to_plain_mesh():
    m = mesh_lib.build_hybrid_mesh(mesh_lib.MeshConfig(data=-1),
                                   dcn_data=1, dcn_pipeline=1)
    assert dict(m.shape)["data"] > 0


def test_hybrid_array_groups_ici_within_slice():
    devs = _fake_slices(n_slices=2, per_slice=4)
    ici = (4, 1, 1, 1, 1, 1)   # data=4 within slice
    dcn = (2, 1, 1, 1, 1, 1)   # data crosses slices
    arr = mesh_lib.hybrid_device_array(ici, dcn, devs)
    assert arr.shape == (8, 1, 1, 1, 1, 1)
    col = arr.reshape(8)
    # outer (DCN) position varies slice, inner 4 stay within one slice
    slices = [d.slice_index for d in col]
    assert slices == [0, 0, 0, 0, 1, 1, 1, 1]


def test_hybrid_array_pipeline_over_dcn():
    devs = _fake_slices(n_slices=2, per_slice=4)
    ici = (2, 1, 1, 1, 1, 2)   # data=2 x tensor=2 within slice
    dcn = (1, 1, 2, 1, 1, 1)   # pipeline crosses slices
    arr = mesh_lib.hybrid_device_array(ici, dcn, devs)
    assert arr.shape == (2, 1, 2, 1, 1, 2)
    # every (data, tensor) fiber crosses slices only along pipeline
    for di in range(2):
        for ti in range(2):
            fiber = [arr[di, 0, pi, 0, 0, ti].slice_index for pi in range(2)]
            assert fiber == [0, 1]


def test_dcn_size_mismatch_raises():
    devs = _fake_slices(n_slices=3, per_slice=2)
    with pytest.raises(ValueError):
        mesh_lib.hybrid_device_array((2, 1, 1, 1, 1, 1),
                                     (2, 1, 1, 1, 1, 1), devs)


def test_build_hybrid_mesh_indivisible_raises():
    with pytest.raises(ValueError, match="divisible"):
        mesh_lib.build_hybrid_mesh(mesh_lib.MeshConfig(data=-1),
                                   dcn_data=3)


def test_accelerator_exposes_dcn_axes():
    from ray_lightning_accelerators_tpu import RayTPUAccelerator
    acc = RayTPUAccelerator(dcn_data=2)
    assert acc.dcn_data == 2
    # 8 CPU devices in one process = one granule; 2 DCN groups must fail
    # loudly rather than silently building a wrong mesh
    with pytest.raises(ValueError):
        acc.build_mesh()
