"""Pipeline parallelism: GPipe schedule must equal sequential layer apply,
forward and backward, standalone and inside the GPT model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu import (Accelerator, DataLoader,
                                            MeshConfig, Trainer)
from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib
from ray_lightning_accelerators_tpu.parallel.pipeline import pipeline_apply

from .test_transformer import TokenDataset, _fit, tiny_cfg


def _layers_params(n_layers=4, d=16, seed=0):
    k = jax.random.split(jax.random.PRNGKey(seed), n_layers)
    return {"w": jax.vmap(lambda kk: jax.random.normal(kk, (d, d)) * 0.3)(k),
            "b": jnp.zeros((n_layers, d))}


def _stage_fn(params, x):
    def one(carry, lp):
        return jnp.tanh(carry @ lp["w"] + lp["b"]), None

    out, _ = jax.lax.scan(one, x, params)
    return out


@pytest.mark.parametrize("stages,microbatches", [(2, 4), (4, 2), (4, 8)])
def test_pipeline_matches_sequential(stages, microbatches):
    mesh = Accelerator(MeshConfig(data=1, pipeline=stages)).build_mesh()
    params = _layers_params()
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 16))
    ref = _stage_fn(params, x)
    out = jax.jit(lambda p, x: pipeline_apply(
        _stage_fn, p, x, mesh, microbatches))(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match():
    mesh = Accelerator(MeshConfig(data=1, pipeline=4)).build_mesh()
    params = _layers_params()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))

    def loss_pp(p):
        return jnp.sum(pipeline_apply(_stage_fn, p, x, mesh, 4) ** 2)

    def loss_seq(p):
        return jnp.sum(_stage_fn(p, x) ** 2)

    g1 = jax.jit(jax.grad(loss_pp))(params)
    g2 = jax.grad(loss_seq)(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


@pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5),
    reason="jax 0.4.x XLA limitation: the dp>1 x pp>1 composition "
           "lowers a PartitionId instruction inside the pipeline's "
           "partial-manual shard_map, which 0.4.x SPMD partitioning "
           "rejects as ambiguous ('UNIMPLEMENTED: PartitionId "
           "instruction is not supported for SPMD partitioning'). "
           "Environmental, not a repo regression: reproduces on clean "
           "seed HEAD, and the dp=1 pipeline tests above cover the "
           "schedule itself on this jax.  Re-enable on jax >= 0.5.")
def test_gpt_trains_with_pipeline(tmpdir):
    """Full model under dp2 x pp2: trains below chance loss; stage params
    actually sharded over the pipeline axis."""
    trainer, model = _fit(tmpdir, MeshConfig(data=2, pipeline=2),
                          batch_size=16, max_epochs=2,
                          n_layers=2, pipeline_microbatches=4)
    assert trainer.callback_metrics["val_loss"] < jnp.log(128)
    wq = trainer._state.params["layers"]["attn"]["wq"]
    assert wq.sharding.spec[0] == "pipeline"


def test_gpt_pipeline_matches_plain(tmpdir):
    """pp2 and plain dp give the same learning trajectory on the same data
    (same global batches, same init)."""
    t1, m1 = _fit(tmpdir, MeshConfig(data=1, pipeline=2), batch_size=8,
                  max_epochs=1, n_layers=2, pipeline_microbatches=2)
    t2, m2 = _fit(tmpdir, MeshConfig(data=1), batch_size=8,
                  max_epochs=1, n_layers=2)
    for a, b in zip(jax.tree.leaves(m1.params), jax.tree.leaves(m2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-3)


@pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) >= (0, 5),
    reason="the dp>1 x pp>1 SPMD composition works on jax >= 0.5; the "
           "typed refusal only guards 0.4.x")
def test_dp_times_pp_refused_typed_on_jax04():
    """Regression for the skipif above (test_gpt_trains_with_pipeline):
    on jax 0.4.x the dp>1 x pp>1 composition must fail EAGERLY as a
    PipelineCompatError naming the alternatives, not as a deep XLA
    'PartitionId instruction is not supported' crash mid-compile."""
    from ray_lightning_accelerators_tpu.parallel.pipeline import (
        PipelineCompatError)
    mesh = Accelerator(MeshConfig(data=2, pipeline=2)).build_mesh()
    params = _layers_params(n_layers=4)
    x = jnp.ones((8, 16))
    with pytest.raises(PipelineCompatError) as exc_info:
        jax.jit(lambda p, xx: pipeline_apply(
            _stage_fn, p, xx, mesh, 4))(params, x)
    msg = str(exc_info.value)
    assert "jax >= 0.5" in msg
    assert "pipeline_stages" in msg  # points at the MPMD alternative
