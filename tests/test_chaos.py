"""Deterministic fault injection (testing/chaos.py) and the full
hang-recovery loop it exists to prove.

The acceptance loop for the watchdog subsystem: inject ``hang@rank1``,
the watchdog classifies the rank wedged within the configured timeout,
pending futures fail with ``WorkerWedged``, ``ElasticRunner`` restarts
every rank, and the retry completes from checkpoint -- all on CPU, no
TPU, no timing races.  Chaos specs are passed through ``env_per_worker``
(never the driver's environment), so injection cannot leak into other
tests; conftest guards the driver env regardless.
"""

import json
import os

import pytest

from ray_lightning_accelerators_tpu.runtime.actors import ActorPool, Worker
from ray_lightning_accelerators_tpu.runtime.elastic import ElasticRunner
from ray_lightning_accelerators_tpu.runtime.watchdog import (Watchdog,
                                                             WorkerWedged)
from ray_lightning_accelerators_tpu.testing.chaos import (CHAOS_EXIT_CODE,
                                                          ChaosFault,
                                                          ChaosInjector,
                                                          parse_chaos)

HB = 0.05


def _ok(x=1):
    return x * 2


# --------------------------------------------------------------------- #
# spec parsing (pure)                                                    #
# --------------------------------------------------------------------- #
def test_parse_full_spec():
    faults = parse_chaos("crash@rank1:step3,hang@rank0,slow@all:2.5")
    assert faults == [
        ChaosFault("crash", 1, 3, None, False),
        ChaosFault("hang", 0, None, None, False),
        ChaosFault("slow", None, None, 2.5, False),
    ]


def test_parse_once_and_step_qualifiers():
    (f,) = parse_chaos("hang@rank1:once")
    assert f.once and f.rank == 1 and f.step is None
    (f,) = parse_chaos("slow@rank2:1.5:step2")
    assert f.delay_s == 1.5 and f.step == 2 and f.rank == 2


def test_parse_rejects_malformed_specs():
    for bad in ("explode@rank0",       # unknown kind
                "crash@node1",          # bad target
                "slow@all",             # slow without delay
                "crash@rank0:2.5",      # delay on non-slow
                "hang@rank0:stepx",     # unknown qualifier
                "crash"):               # no target at all
        with pytest.raises(ValueError):
            parse_chaos(bad)


def test_fault_matching_defaults():
    crash = parse_chaos("crash@rank1:step3")[0]
    assert crash.matches(rank=1, step=3)
    assert not crash.matches(rank=1, step=2)
    assert not crash.matches(rank=0, step=3)
    hang = parse_chaos("hang@rank0")[0]  # crash/hang default: first dispatch
    assert hang.matches(rank=0, step=1)
    assert not hang.matches(rank=0, step=2)
    slow = parse_chaos("slow@all:0.5")[0]  # slow default: every dispatch
    assert slow.matches(rank=7, step=1) and slow.matches(rank=7, step=9)


def test_once_requires_namespace_dir():
    with pytest.raises(ValueError, match="RLA_TPU_CHAOS_NS"):
        ChaosInjector(parse_chaos("hang@rank1:once"), rank=1, ns_dir=None)


def test_once_claim_is_exclusive(tmp_path):
    faults = parse_chaos("crash@rank0:once")
    inj = ChaosInjector(faults, rank=0, ns_dir=str(tmp_path))
    assert inj._claim_once(faults[0])       # first claim fires
    assert not inj._claim_once(faults[0])   # replays (restarts) skip
    # a different rank's claim is independent
    inj2 = ChaosInjector(parse_chaos("hang@all:once"), rank=1,
                         ns_dir=str(tmp_path))
    assert inj2._claim_once(inj2.faults[0])


# --------------------------------------------------------------------- #
# live injection                                                         #
# --------------------------------------------------------------------- #
@pytest.mark.chaos
def test_chaos_crash_at_step():
    w = Worker(0, env={"RLA_TPU_CHAOS": "crash@rank0:step2"},
               heartbeat_s=HB)
    try:
        assert w.execute(_ok, 21).result(timeout=60) == 42  # step 1: fine
        with pytest.raises(RuntimeError, match="died"):
            w.execute(_ok).result(timeout=60)               # step 2: boom
        w._proc.join(timeout=30)
        assert w.exitcode == CHAOS_EXIT_CODE
    finally:
        w.kill()


@pytest.mark.chaos
def test_chaos_bad_spec_surfaces_on_future():
    # a broken spec must fail the dispatch visibly, not vanish worker-side
    w = Worker(0, env={"RLA_TPU_CHAOS": "explode@rank0"}, heartbeat_s=HB)
    try:
        with pytest.raises(Exception, match="chaos fault"):
            w.execute(_ok).result(timeout=60)
    finally:
        w.kill()


@pytest.mark.chaos
def test_chaos_slow_straggler_completes_without_kill():
    # a straggler is SLOW, never wedged: it must finish and return its
    # result -- the false-positive guard for the reaping path
    w = Worker(0, env={"RLA_TPU_CHAOS": "slow@all:1.0"}, heartbeat_s=HB)
    wd = None
    try:
        fut = w.execute(_ok, 4)
        wd = Watchdog([w], wedge_timeout_s=60.0, dispatch_deadline_s=60.0,
                      slow_after_s=0.2, poll_s=HB).start()
        assert wd.wait_for_state(0, "slow", timeout=60)
        assert fut.result(timeout=60) == 8
        assert wd.reaped == []
    finally:
        if wd is not None:
            wd.stop()
        w.kill()


@pytest.mark.chaos
def test_chaos_hang_freezes_heartbeat_and_watchdog_reaps():
    # 'hang' freezes the beat thread too: the stale-heartbeat path (a
    # fully frozen process) fires even with no dispatch deadline set
    w = Worker(0, env={"RLA_TPU_CHAOS": "hang@rank0",
                       "RLA_TPU_WORKER_HEARTBEAT_S": str(HB)})
    wd = None
    try:
        fut = w.execute(_ok)
        wd = Watchdog([w], wedge_timeout_s=0.6, poll_s=HB).start()
        with pytest.raises(WorkerWedged) as ei:
            fut.result(timeout=120)
        assert "stale" in ei.value.diagnosis["detail"]
        assert wd.reaped and wd.reaped[0]["rank"] == 0
    finally:
        if wd is not None:
            wd.stop()
        w.kill()


def _ckpt_train_body(rank, ckpt_dir, total_steps):
    """A checkpointing trainable: rank 0 persists progress per step; every
    rank resumes from the latest checkpoint (the Trainer.fit(ckpt_path=
    "last") analog, minus jax so the loop stays tier-1 fast)."""
    import json
    import os
    path = os.path.join(ckpt_dir, "state.json")
    start = 0
    if os.path.exists(path):
        with open(path) as f:
            start = json.load(f)["step"]
    for step in range(start, total_steps):
        if rank == 0:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"step": step + 1}, f)
            os.replace(tmp, path)  # atomic: a mid-write kill can't corrupt
    return (rank, start, total_steps)


@pytest.mark.chaos
def test_chaos_hang_elastic_restart_resumes_from_checkpoint(tmp_path):
    """The acceptance loop, end to end on CPU: inject ``hang@rank1:once``,
    the watchdog classifies rank 1 wedged within the configured timeout,
    its pending future fails with WorkerWedged, ElasticRunner restarts
    every rank, and the retry completes from the checkpoint rank 0 wrote
    before the wedge was detected."""
    ns = str(tmp_path / "chaos_ns")
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    env = {"RLA_TPU_CHAOS": "hang@rank1:once",
           "RLA_TPU_CHAOS_NS": ns,
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB)}
    pool = ActorPool(2, env_per_worker=[dict(env), dict(env)])
    failures = []
    try:
        runner = ElasticRunner(
            pool, max_failures=2, wedge_timeout_s=0.6, watchdog_poll_s=HB,
            on_failure=lambda a, e: failures.append(e))
        out = runner.run(
            _ckpt_train_body,
            args_per_worker=lambda a: [(r, ckpt, 6) for r in range(2)])

        # one wedged attempt, one clean retry
        assert runner.attempts_used == 2
        assert len(failures) == 1
        assert isinstance(failures[0], WorkerWedged)
        assert failures[0].rank == 1
        # the watchdog's wedge classification, machine-readable
        (reap,) = runner.wedge_events
        assert reap["rank"] == 1
        assert reap["state"] == "wedged"
        assert "stale" in reap["detail"]
        # the retry COMPLETED and resumed from checkpoint: rank 0 finished
        # its steps during attempt 1 (the hang wedged only rank 1), so the
        # retry started past step 0 instead of redoing the work
        by_rank = {r[0]: r for r in out}
        assert set(by_rank) == {0, 1}
        starts = {by_rank[0][1], by_rank[1][1]}
        assert len(starts) == 1  # both ranks agreed on the resume point
        assert starts.pop() >= 1
        with open(os.path.join(ckpt, "state.json")) as f:
            assert json.load(f)["step"] == 6  # training ran to completion
    finally:
        pool.shutdown()
