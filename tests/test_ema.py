"""Parameter EMA: transform math, extraction through wrappers, trainer
integration (incl. FSDP sharding of the shadow)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_lightning_accelerators_tpu import (ArrayDataset, DataLoader,
                                            RayTPUAccelerator, Trainer)
from ray_lightning_accelerators_tpu.utils.ema import (ema_params,
                                                      ema_tracker)
from tests.utils import BoringModel, boring_loaders


def test_tracker_math():
    params = {"w": jnp.ones((4,))}
    tx = optax.chain(optax.sgd(0.5), ema_tracker(decay=0.5))
    state = tx.init(params)
    grads = {"w": jnp.ones((4,))}
    updates, state = tx.update(grads, state, params)
    new_params = optax.apply_updates(params, updates)
    # sgd: w 1.0 -> 0.5; ema: 0.5*1.0 + 0.5*0.5 = 0.75
    np.testing.assert_allclose(np.asarray(new_params["w"]), 0.5)
    np.testing.assert_allclose(np.asarray(ema_params(state)["w"]), 0.75)
    # and updates flowed through unchanged by the tracker
    np.testing.assert_allclose(np.asarray(updates["w"]), -0.5)


def test_extraction_through_multisteps():
    params = {"w": jnp.ones((2,))}
    tx = optax.MultiSteps(optax.chain(optax.sgd(0.1), ema_tracker(0.9)), 2)
    state = tx.init(params)
    assert ema_params(state) is not None
    # accumulation micro-step must NOT advance the shadow
    g = {"w": jnp.ones((2,))}
    _, state = tx.update(g, state, params)
    np.testing.assert_allclose(np.asarray(ema_params(state)["w"]), 1.0)
    # window commit advances it
    _, state = tx.update(g, state, params)
    assert float(np.asarray(ema_params(state)["w"])[0]) < 1.0


def test_no_tracker_returns_none():
    params = {"w": jnp.ones((2,))}
    tx = optax.adam(1e-3)
    assert ema_params(tx.init(params)) is None


def test_trainer_ema_eval_uses_averaged_weights():
    train, val = boring_loaders()
    model = BoringModel()
    trainer = Trainer(max_epochs=2, precision="f32", seed=0,
                      ema_decay=0.98, ema_eval=True,
                      enable_checkpointing=False,
                      default_root_dir="/tmp/ema_test")
    trainer.fit(model, train, val)
    avg = trainer.ema_params()
    assert avg is not None
    raw = trainer._state.params
    # shadow lags the raw weights (they moved every step)
    diffs = [float(np.abs(np.asarray(a) - np.asarray(b)).max())
             for a, b in zip(jax.tree.leaves(avg), jax.tree.leaves(raw))]
    assert max(diffs) > 0


def test_ema_eval_requires_decay():
    with pytest.raises(ValueError, match="ema_decay"):
        Trainer(ema_eval=True)


def test_ema_state_sharded_under_fsdp():
    x = np.random.default_rng(0).normal(size=(64, 32)).astype(np.float32)

    class WideModel(BoringModel):
        def init_params(self, rng):
            k = jax.random.normal(rng, (32, 128), jnp.float32) * 0.1
            return {"layer": {"kernel": k,
                              "bias": jnp.zeros((128,), jnp.float32)}}

        def forward(self, params, x):
            return x @ params["layer"]["kernel"] + params["layer"]["bias"]

        def validation_step(self, params, batch):
            return {"val_loss": jnp.mean(self.forward(params, batch) ** 2)}

        def training_step(self, params, batch, rng):
            loss = jnp.mean((self.forward(params, batch) - 1.0) ** 2)
            return loss, {"loss": loss}

    model = WideModel()
    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      accelerator=RayTPUAccelerator(use_fsdp=True),
                      ema_decay=0.99, enable_checkpointing=False,
                      default_root_dir="/tmp/ema_fsdp_test")
    trainer.fit(model, DataLoader(ArrayDataset(x), batch_size=8))
    avg = trainer.ema_params()
    kernel = avg["layer"]["kernel"]
    # the shadow inherited the param's FSDP sharding (not replicated)
    assert not kernel.sharding.is_fully_replicated


def test_degenerate_decay_rejected():
    for bad in (0.0, 1.0, -0.5, 1.5):
        with pytest.raises(ValueError, match="ema_decay"):
            Trainer(ema_decay=bad)
