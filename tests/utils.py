"""Shared fixtures: behavioral ports of the reference's test harness
(reference: ray_lightning/tests/utils.py — BoringModel :24-91, get_trainer
:94-114, train_test :117-126, load_test :129-134, predict_test :137-152)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import optax

from ray_lightning_accelerators_tpu import (ArrayDataset, DataLoader,
                                            DataModule, ModelCheckpoint,
                                            RandomDataset, Trainer, TpuModule)


class BoringModel(TpuModule):
    """1-linear-layer model whose loss really moves weights, with a constant
    val_loss=1.0 and a val_epoch counter persisted through checkpoint hooks
    (mirrors reference BoringModel semantics)."""

    def __init__(self):
        super().__init__()
        self.val_epoch = 0

    def init_params(self, rng):
        k = jax.random.normal(rng, (32, 2), jnp.float32) * 0.5
        return {"layer": {"kernel": k, "bias": jnp.zeros((2,), jnp.float32)}}

    def forward(self, params, x):
        return x @ params["layer"]["kernel"] + params["layer"]["bias"]

    def training_step(self, params, batch, rng):
        out = self.forward(params, batch)
        loss = jnp.mean((out - 1.0) ** 2)
        return loss, {"loss": loss}

    def validation_step(self, params, batch):
        self.forward(params, batch)
        return {"val_loss": jnp.asarray(1.0)}

    def test_step(self, params, batch):
        out = self.forward(params, batch)
        return {"y": jnp.mean((out - 1.0) ** 2)}

    def on_validation_epoch_end(self):
        self.val_epoch += 1

    def configure_optimizers(self):
        return optax.sgd(0.1)

    def on_save_checkpoint(self, checkpoint):
        checkpoint["val_epoch"] = self.val_epoch

    def on_load_checkpoint(self, checkpoint):
        self.val_epoch = checkpoint.get("val_epoch", self.val_epoch)


def boring_loaders(batch_size: int = 8):
    train = DataLoader(RandomDataset(32, 64), batch_size=batch_size,
                       shuffle=True)
    val = DataLoader(RandomDataset(32, 64), batch_size=batch_size)
    return train, val


class BlobsDataModule(DataModule):
    """Linearly separable 4-class blobs: the synthetic stand-in for the
    reference's MNIST accuracy gate (no dataset downloads in this env)."""

    def __init__(self, n: int = 512, dim: int = 32, classes: int = 4,
                 batch_size: int = 16, seed: int = 0):
        rng = np.random.default_rng(seed)
        centers = rng.standard_normal((classes, dim)).astype(np.float32) * 4.0
        y = rng.integers(0, classes, size=n)
        x = centers[y] + rng.standard_normal((n, dim)).astype(np.float32)
        split = int(n * 0.75)
        self._train = (x[:split], y[:split].astype(np.int32))
        self._test = (x[split:], y[split:].astype(np.int32))
        self.batch_size = batch_size

    def train_dataloader(self):
        return DataLoader(ArrayDataset(*self._train),
                          batch_size=self.batch_size, shuffle=True)

    def val_dataloader(self):
        return DataLoader(ArrayDataset(*self._test),
                          batch_size=self.batch_size)

    def test_dataloader(self):
        return DataLoader(ArrayDataset(*self._test),
                          batch_size=self.batch_size, drop_last=False)


class LinearClassifier(TpuModule):
    def __init__(self, dim: int = 32, classes: int = 4, lr: float = 0.05):
        super().__init__()
        self.save_hyperparameters(dim=dim, classes=classes, lr=lr)
        self.dim, self.classes, self.lr = dim, classes, lr

    def init_params(self, rng):
        return {"w": jax.random.normal(rng, (self.dim, self.classes)) * 0.01,
                "b": jnp.zeros((self.classes,))}

    def forward(self, params, x):
        return x @ params["w"] + params["b"]

    def _loss(self, params, batch):
        x, y = batch
        logits = self.forward(params, x)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        acc = jnp.mean(jnp.argmax(logits, -1) == y)
        return loss, acc

    def training_step(self, params, batch, rng):
        loss, acc = self._loss(params, batch)
        return loss, {"loss": loss, "acc": acc}

    def validation_step(self, params, batch):
        loss, acc = self._loss(params, batch)
        return {"val_loss": loss, "val_accuracy": acc}

    def predict_step(self, params, batch):
        x = batch[0] if isinstance(batch, tuple) else batch
        return self.forward(params, x)

    def configure_optimizers(self):
        return optax.adam(self.lr)


def get_trainer(dir, accelerator, max_epochs: int = 1,
                limit_train_batches: int = 10, limit_val_batches: int = 10,
                callbacks=None, **kwargs) -> Trainer:
    callbacks = list(callbacks or [])
    if not any(isinstance(c, ModelCheckpoint) for c in callbacks):
        callbacks.append(ModelCheckpoint(monitor="val_loss"))
    return Trainer(default_root_dir=str(dir), max_epochs=max_epochs,
                   limit_train_batches=limit_train_batches,
                   limit_val_batches=limit_val_batches,
                   accelerator=accelerator, callbacks=callbacks,
                   precision="f32", seed=0, **kwargs)


def _abs_sums(params):
    return np.array([float(jnp.sum(jnp.abs(x)))
                     for x in jax.tree.leaves(params)])


def train_test(trainer, model, train_loader=None, val_loader=None):
    """Weights must actually change after fit (reference: utils.py:117-126)."""
    if train_loader is None:
        train_loader, val_loader = boring_loaders()
    initial = _abs_sums(model.init_params(jax.random.PRNGKey(trainer.seed)))
    trainer.fit(model, train_loader, val_loader)
    post = _abs_sums(model.params)
    assert model.params is not None, "trainer failed"
    assert np.linalg.norm(initial - post) > 0.1, \
        "model unchanged post-training"


def load_test(trainer, model, cls=BoringModel):
    """Best-checkpoint round trip (reference: utils.py:129-134)."""
    train_loader, val_loader = boring_loaders()
    trainer.fit(model, train_loader, val_loader)
    best = trainer.checkpoint_callback.best_model_path
    assert best, "no best_model_path recorded"
    trained = cls.load_from_checkpoint(best)
    assert trained is not None and trained.params is not None


def predict_test(trainer, model, dm):
    """Trained accuracy >= 0.5 on held-out data (reference: utils.py:137-152)."""
    trainer.fit(model, datamodule=dm)
    dm.setup("test")
    correct, total = 0, 0
    for batch in dm.test_dataloader():
        x, y = batch
        y_hat = np.asarray(model((x, y)))
        correct += int((y_hat.argmax(-1) == y).sum())
        total += len(y)
    acc = correct / total
    assert acc >= 0.5, f"expected accuracy >= 0.5, got {acc}"


# --------------------------------------------------------------------- #
# Prometheus exposition-format validation (shared by test_telemetry's   #
# end-of-run export checks and test_live's live-scrape checks)          #
# --------------------------------------------------------------------- #
import re  # noqa: E402

PROM_SAMPLE_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
                            r'(\{[a-zA-Z0-9_]+="[^"]*"'
                            r'(,[a-zA-Z0-9_]+="[^"]*")*\})? '
                            r"-?[0-9.eE+-]+(inf|nan)?$")


def assert_prometheus_exposition(text: str) -> None:
    """Every non-comment line must be a well-formed sample
    (``name{labels} value``), and the text must not be empty."""
    assert text.strip(), "empty exposition body"
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        assert PROM_SAMPLE_RE.match(line), \
            f"malformed exposition line: {line!r}"


class PipelineBoringModel(TpuModule):
    """BoringModel stretched to a depth-4 tanh MLP cut into contiguous
    pipeline stages: the MPMD parity/chaos fixture (tests/test_mpmd_*).

    Stage hooks slice the layer dict by global layer index, so the same
    params train identically through the single-process baseline
    (training_step) and the PipelineRunner (pipeline_stage_*)."""

    DEPTH = 4

    def __init__(self, dim: int = 8, hidden: int = 16, lr: float = 0.1):
        super().__init__()
        self.dim, self.hidden, self.lr = dim, hidden, lr

    def init_params(self, rng):
        keys = jax.random.split(rng, self.DEPTH)
        sizes = [self.dim] + [self.hidden] * (self.DEPTH - 1) + [self.dim]
        return {
            f"l{i}": {
                "w": jax.random.normal(
                    keys[i], (sizes[i], sizes[i + 1]), jnp.float32) * 0.3,
                "b": jnp.zeros((sizes[i + 1],), jnp.float32),
            }
            for i in range(self.DEPTH)
        }

    @staticmethod
    def _layer_indices(layers):
        return sorted(int(name[1:]) for name in layers)

    def _apply(self, layers, x):
        for i in self._layer_indices(layers):
            p = layers[f"l{i}"]
            x = jnp.tanh(x @ p["w"] + p["b"])
        return x

    # -- single-process baseline path ---------------------------------- #
    def forward(self, params, x):
        return self._apply(params, x)

    def training_step(self, params, batch, rng):
        loss = jnp.mean((self._apply(params, batch) - 1.0) ** 2)
        return loss, {"loss": loss}

    def configure_optimizers(self):
        return optax.sgd(self.lr)

    # -- MPMD pipeline hooks ------------------------------------------- #
    def pipeline_stage_params(self, params, stage, num_stages):
        if self.DEPTH % num_stages:
            raise ValueError(
                f"{self.DEPTH} layers do not divide into "
                f"{num_stages} stages")
        per = self.DEPTH // num_stages
        return {f"l{i}": params[f"l{i}"]
                for i in range(stage * per, (stage + 1) * per)}

    def pipeline_stage_forward(self, stage_params, x, stage, num_stages):
        return self._apply(stage_params, x)

    def pipeline_loss(self, y, batch):
        loss = jnp.mean((y - 1.0) ** 2)
        return loss, {"loss": loss}
