"""Paged KV cache + prefix reuse + speculative serve lane: block
allocator semantics, token-exactness through the block-table
indirection (joins, retires, block growth, prefix hits, speculative
routing), typed pool admission, and the new pool metrics.  All CPU,
tier-1 fast."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu.models.transformer import (
    GPT, TransformerConfig)
from ray_lightning_accelerators_tpu.serve import (BlockAllocator,
                                                  PoolExhausted,
                                                  QueueFull,
                                                  RequestRejected,
                                                  ServeEngine,
                                                  blocks_for_request)

pytestmark = [pytest.mark.serve, pytest.mark.paged]


def _model(vocab=97, layers=2, max_seq_len=64, seed=0, d_model=64,
           n_heads=2, d_ff=128):
    cfg = TransformerConfig(vocab_size=vocab, d_model=d_model,
                            n_heads=n_heads, d_ff=d_ff, n_layers=layers,
                            max_seq_len=max_seq_len)
    m = GPT(cfg)
    return m, m.init_params(jax.random.PRNGKey(seed))


def _refs(model, params, reqs):
    return [np.asarray(model.generate(params, jnp.asarray(p[None]),
                                      max_new_tokens=n))[0]
            for p, n in reqs]


# --------------------------------------------------------------------- #
# BlockAllocator                                                        #
# --------------------------------------------------------------------- #
def test_block_allocator_alloc_release_refcount():
    a = BlockAllocator(n_blocks=6, block_len=4)  # 5 usable (0 reserved)
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got
    assert a.stats() == {"total": 5, "used": 3, "cached": 0, "free": 2}
    # exhaustion: no cached blocks to evict -> None, nothing consumed
    assert a.alloc(3) is None
    assert a.stats()["free"] == 2
    for b in got:
        a.release(b)
    assert a.stats() == {"total": 5, "used": 0, "cached": 0, "free": 5}
    assert len(a.alloc(5)) == 5


def test_block_allocator_prefix_sharing_and_lru_eviction():
    a = BlockAllocator(n_blocks=5, block_len=4)   # 4 usable
    b1, b2 = a.alloc(2)
    a.register("k1", b1)
    a.register("k2", b2)
    # a sharer retains the full run; a miss stops the run
    run = a.lookup_run(["k1", "k2", "k-miss"], max_blocks=8)
    assert run == [b1, b2]
    assert a.stats()["used"] == 2
    # owner releases: blocks stay used (the sharer holds them)
    a.release(b1), a.release(b2)
    assert a.stats()["used"] == 2
    # sharer releases: registered blocks become CACHED, not free
    a.release(b1), a.release(b2)
    assert a.stats() == {"total": 4, "used": 0, "cached": 2, "free": 2}
    # allocation pressure evicts the LRU cached block (k1 was refreshed
    # to MRU by the lookup... both released; k1 was moved to end first,
    # then k2 -> k1 is older? move_to_end order: k1 then k2 -> LRU = k1)
    got = a.alloc(3)
    assert len(got) == 3
    st = a.stats()
    assert st["cached"] == 1 and st["used"] == 3
    # the surviving key still hits; the evicted one misses
    hits = a.lookup_run(["k1"], max_blocks=8)
    rem = a.lookup_run(["k2"], max_blocks=8)
    assert (len(hits), len(rem)) in ((0, 1), (1, 0))  # exactly one left
    # referenced cached blocks are never evicted
    assert a.alloc(2) is None


def test_block_allocator_first_registration_wins():
    a = BlockAllocator(n_blocks=5, block_len=4)
    b1, b2 = a.alloc(2)
    assert a.register("k", b1) is True
    assert a.register("k", b2) is False        # duplicate key
    assert a.lookup_run(["k"], 8) == [b1]


def test_blocks_for_request_math():
    # covers the padded prompt AND every decode-fed position
    assert blocks_for_request(3, 1, block_len=4) == 1
    assert blocks_for_request(4, 1, block_len=4) == 1
    assert blocks_for_request(4, 2, block_len=4) == 2   # feed at pos 4
    assert blocks_for_request(3, 6, block_len=4) == 2   # top pos 7
    assert blocks_for_request(3, 7, block_len=4) == 3   # top pos 8
    # speculative headroom extends the top position
    assert blocks_for_request(3, 6, block_len=4, headroom=4) == 3


# --------------------------------------------------------------------- #
# Engine: paged exactness                                               #
# --------------------------------------------------------------------- #
def test_paged_token_identical_across_join_retire_growth():
    """The tentpole acceptance loop: staggered arrivals over a paged
    pool, budgets long enough that every row's position crosses >= 1
    block boundary mid-decode (block growth) -> every response
    token-identical to standalone generate(), with real batching."""
    model, params = _model()
    rng = np.random.default_rng(7)
    reqs = []
    for _ in range(8):
        s0 = int(rng.integers(3, 11))
        reqs.append((rng.integers(0, 97, size=(s0,)).astype(np.int32),
                     int(rng.integers(6, 14))))   # crosses 4-token blocks
    refs = _refs(model, params, reqs)
    with ServeEngine(model, params, max_slots=4, queue_depth=32,
                     block_len=4) as eng:
        resps = []
        for i, (p, n) in enumerate(reqs):
            resps.append(eng.submit(p, n))
            if i % 3 == 2:
                time.sleep(0.02)
        outs = [r.result(timeout=300) for r in resps]
    for out, ref in zip(outs, refs):
        np.testing.assert_array_equal(out, ref)
    snap = eng.stats()
    assert snap["completed"] == 8
    assert snap["steps_batch_gt1"] >= 1
    assert snap["max_batch"] >= 2
    # pool gauges present and sane
    assert snap["block_pool_total"] > 0
    assert snap["peak_used_blocks"] > 0
    assert snap["peak_concurrent"] >= 2
    assert snap["hbm_cache_bytes"] > 0


def test_paged_prefix_reuse_exact_and_shared():
    """Two waves sharing a long system prompt: the second wave maps the
    cached prefix blocks copy-on-write (same PHYSICAL blocks, refcounted)
    instead of re-prefilling, and stays token-identical to generate()."""
    model, params = _model()
    rng = np.random.default_rng(3)
    sysp = rng.integers(0, 97, size=(18,)).astype(np.int32)  # 4 full blocks of 4
    reqs = []
    for _ in range(4):
        sfx = rng.integers(0, 97, size=(int(rng.integers(2, 6)),)
                           ).astype(np.int32)
        reqs.append((np.concatenate([sysp, sfx]),
                     int(rng.integers(4, 9))))
    refs = _refs(model, params, reqs)
    with ServeEngine(model, params, max_slots=2, queue_depth=16,
                     block_len=4) as eng:
        # wave 1 seeds the prefix index
        out0 = eng.submit(*reqs[0]).result(timeout=300)
        np.testing.assert_array_equal(out0, refs[0])
        snap0 = eng.stats()
        # wave 2: every request hits the shared prefix
        resps = [eng.submit(p, n) for p, n in reqs[1:]]
        outs = [r.result(timeout=300) for r in resps]
    for out, ref in zip(outs, refs[1:]):
        np.testing.assert_array_equal(out, ref)
    snap = eng.stats()
    assert snap0["prefix_hit_blocks"] == 0      # nothing cached yet
    assert snap["prefix_hits"] >= 3
    # each of the 3 sharers reused all 4 full system-prompt blocks
    assert snap["prefix_hit_blocks"] >= 9
    assert snap["prefix_lookups"] == 4


def test_paged_pool_backpressure_and_flow_control():
    """PoolExhausted fires typed at submit when the admitted set's
    worst-case demand overcommits the pool; an engine whose pool is
    momentarily full keeps the head request WAITING (flow control, not
    failure) and serves it once retires free blocks."""
    model, params = _model()
    # pool: 1 slot's worth of blocks (max_total_len 32 / block_len 8 ->
    # 4 blocks + garbage)
    eng = ServeEngine(model, params, max_slots=2, queue_depth=8,
                      max_total_len=32, block_len=8, n_blocks=5)
    try:
        r1 = eng.submit(np.asarray([1, 2, 3], np.int32), 10)  # 2 blocks
        r2 = eng.submit(np.asarray([4, 5], np.int32), 12)     # 2 blocks
        with pytest.raises(PoolExhausted) as ei:
            eng.submit(np.asarray([6], np.int32), 10)         # +2 > 4
        assert ei.value.needed == 2
        assert ei.value.total == 4
        assert isinstance(ei.value, QueueFull)  # retryable backpressure
        assert eng.stats()["pool_exhausted"] == 1
        eng.start()
        # both requests complete: the pool serves them (possibly
        # sequentially via head-of-line flow control)
        assert r1.result(timeout=300).shape[0] == 13
        assert r2.result(timeout=300).shape[0] == 14
        # demand released: the pool admits again
        assert eng.submit(np.asarray([7], np.int32), 4
                          ).result(timeout=300).shape[0] == 5
    finally:
        eng.stop(cancel_active=True, timeout=10)


def test_paged_zero_recompiles_after_warmup():
    """The no-recompile invariant through the indirection, pinned: after
    one bucket's warmup, joins, retires and block-boundary growth all
    reuse the two compiled programs (chunk prefill + paged step)."""
    from ray_lightning_accelerators_tpu.analysis.compile_guard import (
        compile_guard, install)
    install()
    model, params = _model()
    rng = np.random.default_rng(11)
    # one suffix bucket: lengths 3..8 pad to 8 (block_len=8); budgets
    # cross into later blocks mid-decode (growth)
    reqs = [(rng.integers(0, 97, size=(int(rng.integers(3, 9)),))
             .astype(np.int32), int(rng.integers(8, 15)))
            for _ in range(6)]
    refs = _refs(model, params, reqs)
    eng = ServeEngine(model, params, max_slots=3, queue_depth=32,
                      block_len=8)
    eng.start()
    try:
        with compile_guard(max_new_compiles=2, label="paged-2prog") as g:
            outs = [eng.submit(p, n) for p, n in reqs[:2]]
            for r in outs:
                r.result(timeout=300)
        assert g.new_compiles == 2, (
            "expected exactly 2 compiled programs (chunk prefill + "
            f"paged step), got {g.new_compiles}")
        # steady state: staggered joins/retires/growth add ZERO compiles
        with compile_guard(max_new_compiles=0, label="paged-steady"):
            resps = []
            for i, (p, n) in enumerate(reqs):
                resps.append(eng.submit(p, n))
                if i % 2 == 1:
                    time.sleep(0.02)
            outs2 = [r.result(timeout=300) for r in resps]
    finally:
        eng.stop()
    for out, ref in zip(outs2, refs):
        np.testing.assert_array_equal(out, ref)


def test_paged_metrics_reset_audit():
    """The reset-audit discipline extended to the pool fields: every new
    counter and watermark clears; bound gauges stay wired (they read
    live allocator state, not history)."""
    from ray_lightning_accelerators_tpu.serve.metrics import ServeMetrics
    m = ServeMetrics()
    m.bind_pool(lambda: {"block_pool_total": 4, "block_pool_used": 2,
                         "cache_waste_ratio": 0.5})
    for c in ("prefix_lookups", "prefix_hits", "prefix_hit_blocks",
              "speculative_requests", "speculative_tokens_accepted",
              "pool_exhausted"):
        m.inc(c)
    m.observe_pool(used_blocks=7, concurrent=3)
    m.observe_spec_round(0.01, tokens=4)
    before = m.snapshot()
    assert before["peak_used_blocks"] == 7
    assert before["peak_concurrent"] == 3
    assert before["speculative_rounds"] == 1
    assert before["tokens_generated"] == 4
    assert before["block_pool_used"] == 2      # gauge rides the binding
    m.reset()
    snap = m.snapshot()
    for k in ServeMetrics._COUNTERS:
        assert snap[k] == 0, f"reset missed counter {k!r}"
    assert snap["peak_used_blocks"] == 0
    assert snap["peak_concurrent"] == 0
    assert snap["busy_s"] == 0.0
    assert snap["block_pool_used"] == 2        # live gauge, still bound


def test_paged_pool_gauges_export_to_prometheus_as_gauges():
    from ray_lightning_accelerators_tpu.serve.metrics import ServeMetrics
    from ray_lightning_accelerators_tpu.telemetry import MetricsRegistry
    m = ServeMetrics()
    m.bind_pool(lambda: {"block_pool_used": 3, "cache_waste_ratio": 0.75,
                         "hbm_cache_bytes": 4096})
    m.inc("completed")
    reg = MetricsRegistry()
    reg.add_serve(m, rank="driver")
    text = reg.prometheus_text()
    assert "# TYPE rla_tpu_serve_block_pool_used gauge" in text
    assert "# TYPE rla_tpu_serve_cache_waste_ratio gauge" in text
    assert "# TYPE rla_tpu_serve_completed_total counter" in text
    assert 'rla_tpu_serve_cache_waste_ratio{rank="driver"} 0.75' in text
    js = reg.to_json()
    assert js["serve"]["driver"]["hbm_cache_bytes"] == 4096


# --------------------------------------------------------------------- #
# Speculative lane                                                      #
# --------------------------------------------------------------------- #
def _draft(vocab=97, seed=5):
    cfg = TransformerConfig(vocab_size=vocab, d_model=32, n_heads=2,
                            d_ff=64, n_layers=1, max_seq_len=128)
    m = GPT(cfg)
    return m, m.init_params(jax.random.PRNGKey(seed))


def test_speculative_lane_exact_through_engine():
    """Single-stream requests routed through the engine's speculative
    lane (idle engine + draft model): token-identical to target-only
    greedy generate(), with round/acceptance evidence and prefix reuse
    engaged on the second request."""
    from ray_lightning_accelerators_tpu.models.speculative import (
        serve_speculative)
    model, params = _model()
    draft, dparams = _draft()
    rng = np.random.default_rng(9)
    sysp = rng.integers(0, 97, size=(9,)).astype(np.int32)
    p1 = np.concatenate([sysp, rng.integers(0, 97, size=(3,)
                                            ).astype(np.int32)])
    p2 = np.concatenate([sysp, rng.integers(0, 97, size=(4,)
                                            ).astype(np.int32)])
    refs = _refs(model, params, [(p1, 9), (p2, 7)])
    with ServeEngine(model, params, max_slots=2, queue_depth=8,
                     block_len=4, draft_model=draft,
                     draft_params=dparams, spec_k=4) as eng:
        out1 = serve_speculative(eng, p1, 9, timeout=300)
        out2 = serve_speculative(eng, p2, 7, timeout=300)
    np.testing.assert_array_equal(out1, refs[0])
    np.testing.assert_array_equal(out2, refs[1])
    snap = eng.stats()
    assert snap["speculative_requests"] == 2
    assert snap["speculative_rounds"] >= 2
    assert snap["completed"] == 2
    assert snap["prefix_hits"] >= 1           # p2 reused p1's sys blocks
    assert snap["tokens_generated"] == 9 + 7


def test_speculative_hint_needs_draft_and_falls_back_when_busy():
    """speculative=True without a draft model rejects typed; with a
    draft but a BUSY engine the request serves through a normal slot —
    same tokens either way (the routing is invisible to clients)."""
    model, params = _model()
    with ServeEngine(model, params, max_slots=2, block_len=4) as eng:
        with pytest.raises(RequestRejected, match="draft model"):
            eng.submit(np.asarray([1, 2, 3], np.int32), 4,
                       speculative=True)
    draft, dparams = _draft()
    rng = np.random.default_rng(2)
    p_bg = rng.integers(0, 97, size=(5,)).astype(np.int32)
    p_sp = rng.integers(0, 97, size=(6,)).astype(np.int32)
    refs = _refs(model, params, [(p_bg, 24), (p_sp, 6)])
    with ServeEngine(model, params, max_slots=2, queue_depth=8,
                     block_len=4, draft_model=draft,
                     draft_params=dparams) as eng:
        r_bg = eng.submit(p_bg, 24)          # long-running occupant
        deadline = time.monotonic() + 30
        while eng.stats()["prefills"] < 1:   # occupant actually placed
            if time.monotonic() > deadline:
                raise AssertionError("occupant never admitted")
            time.sleep(0.005)
        r_sp = eng.submit(p_sp, 6, speculative=True)
        out_sp = r_sp.result(timeout=300)
        out_bg = r_bg.result(timeout=300)
    np.testing.assert_array_equal(out_bg, refs[0])
    np.testing.assert_array_equal(out_sp, refs[1])
    snap = eng.stats()
    # the busy engine routed the hinted request through a normal slot
    assert snap["completed"] == 2


def test_stop_cancel_active_interrupts_speculative_lane():
    """stop(cancel_active=True) must interrupt an in-flight speculative
    request at its next round boundary — fast teardown cannot wait out
    a large budget."""
    from ray_lightning_accelerators_tpu.serve import ServeCancelled
    model, params = _model()
    draft, dparams = _draft()
    eng = ServeEngine(model, params, max_slots=2, block_len=4,
                      draft_model=draft, draft_params=dparams, spec_k=4)
    orig = eng._d_propose

    def slow_propose(*a):
        time.sleep(0.05)   # stretch each round: a wide cancel window
        return orig(*a)

    eng._d_propose = slow_propose
    eng.start()
    try:
        p = np.asarray([1, 2, 3, 4], np.int32)
        r = eng.submit(p, 40, speculative=True)   # >= 8 rounds of work
        deadline = time.monotonic() + 30
        while eng.stats()["speculative_rounds"] < 1:
            if time.monotonic() > deadline:
                raise AssertionError("speculative lane never started")
            time.sleep(0.005)
        eng.stop(cancel_active=True, timeout=30)
        with pytest.raises(ServeCancelled, match="speculative"):
            r.result(timeout=5)
        assert eng.stats()["cancelled"] >= 1
        assert eng.allocator.stats()["used"] == 0   # blocks released
    finally:
        eng.stop(cancel_active=True, timeout=5)


def test_admission_failure_fails_the_popped_request_typed():
    """A prefill that dies mid-admission must fail THAT request's future
    (it is in neither the queue nor a slot) and release its blocks —
    not leave the client hanging until timeout."""
    model, params = _model()
    eng = ServeEngine(model, params, max_slots=2, block_len=4)

    def boom(_padded_len):
        raise RuntimeError("prefill exploded")

    eng._chunk_prefill_fn = boom
    eng.start()
    try:
        r = eng.submit(np.asarray([1, 2, 3], np.int32), 4)
        with pytest.raises(RuntimeError, match="prefill exploded"):
            r.result(timeout=30)
        assert eng.stats()["failed"] == 1
        # the failed request's blocks went back to the pool
        assert eng.allocator.stats()["used"] == 0
    finally:
        eng._thread = None  # loop already died; stop() must not join it
        eng.stop(cancel_active=True, timeout=5)


def test_dense_mode_still_exact_and_program_counted():
    """paged=False keeps the PR 2 dense engine intact (the probe's
    placed-bytes baseline): exactness + no pool fields in the snapshot."""
    model, params = _model()
    rng = np.random.default_rng(4)
    reqs = [(rng.integers(0, 97, size=(int(rng.integers(3, 9)),))
             .astype(np.int32), int(rng.integers(4, 9)))
            for _ in range(4)]
    refs = _refs(model, params, reqs)
    with ServeEngine(model, params, max_slots=2, paged=False) as eng:
        outs = [eng.submit(p, n).result(timeout=300) for p, n in reqs]
    for out, ref in zip(outs, refs):
        np.testing.assert_array_equal(out, ref)
    snap = eng.stats()
    assert snap["completed"] == 4
    assert "block_pool_total" not in snap
