"""Test env: force an 8-device virtual CPU mesh BEFORE any backend init.

The reference tested multi-worker logic by CPU oversubscription on localhost
with the Gloo backend (reference: ray_lightning/tests/test_ddp.py:17-21 +
ray_ddp.py:227).  The XLA analog: 8 virtual CPU devices, so every
mesh/sharding path runs in CI without TPUs; real-TPU runs are env-gated the
way the reference gated GPU tests (reference: tests/test_ddp_gpu.py:106-109)
via RLA_TPU_TEST_PLATFORM=tpu.

Note: a TPU plugin loaded from sitecustomize may force `jax_platforms` via
config (not env), so we override the config explicitly after import.
"""

import os

os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

_platform = os.environ.get("RLA_TPU_TEST_PLATFORM", "cpu")
jax.config.update("jax_platforms", _platform)
if _platform == "cpu":
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass  # pre-0.5 jax: the XLA_FLAGS device-count override above applies

# RLA_TPU_WORKER_PLATFORM is scoped to the one test that gates on it
# (test_tpu_world.py re-sets it from the stash inside the test): left
# ambient, it would rewrite the platform of EVERY fan-out in the suite
# -- with a real chip, two CPU-gloo tests' workers would contend for the
# single device claim and deadlock.
WORKER_PLATFORM_STASH = os.environ.pop("RLA_TPU_WORKER_PLATFORM", None)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _chaos_leak_guard(request):
    """``RLA_TPU_CHAOS`` makes every spawned worker crash/hang/stall on
    purpose: ambient in the driver env it would poison EVERY fan-out in
    the suite.  Only ``@pytest.mark.chaos`` tests may see it set, and no
    test may leave it behind."""
    is_chaos = request.node.get_closest_marker("chaos") is not None
    if not is_chaos:
        assert "RLA_TPU_CHAOS" not in os.environ, (
            f"RLA_TPU_CHAOS leaked into non-chaos test {request.node.nodeid}"
            " -- chaos specs belong in env_per_worker or a chaos-marked "
            "test's monkeypatched env")
    yield
    assert "RLA_TPU_CHAOS" not in os.environ, (
        f"{request.node.nodeid} left RLA_TPU_CHAOS set in the driver env; "
        "later fan-outs would inherit the fault injection")
