"""Test env: force an 8-device virtual CPU mesh BEFORE any backend init.

The reference tested multi-worker logic by CPU oversubscription on localhost
with the Gloo backend (reference: ray_lightning/tests/test_ddp.py:17-21 +
ray_ddp.py:227).  The XLA analog: 8 virtual CPU devices, so every
mesh/sharding path runs in CI without TPUs; real-TPU runs are env-gated the
way the reference gated GPU tests (reference: tests/test_ddp_gpu.py:106-109)
via RLA_TPU_TEST_PLATFORM=tpu.

Note: a TPU plugin loaded from sitecustomize may force `jax_platforms` via
config (not env), so we override the config explicitly after import.
"""

import os

os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

_platform = os.environ.get("RLA_TPU_TEST_PLATFORM", "cpu")
jax.config.update("jax_platforms", _platform)
if _platform == "cpu":
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass  # pre-0.5 jax: the XLA_FLAGS device-count override above applies

# RLA_TPU_WORKER_PLATFORM is scoped to the one test that gates on it
# (test_tpu_world.py re-sets it from the stash inside the test): left
# ambient, it would rewrite the platform of EVERY fan-out in the suite
# -- with a real chip, two CPU-gloo tests' workers would contend for the
# single device claim and deadlock.
WORKER_PLATFORM_STASH = os.environ.pop("RLA_TPU_WORKER_PLATFORM", None)

import pytest  # noqa: E402


@pytest.fixture
def compile_guard():
    """The compile-count guard factory (analysis/compile_guard.py):

        with compile_guard(max_new_compiles=3, label="serve"):
            ...  # raises CompileBudgetExceeded past the budget

    Counting is process-global (one jax.monitoring listener installed on
    first use), so guarded blocks must not overlap other tests' compiles
    — fine under the suite's in-process sequential execution."""
    from ray_lightning_accelerators_tpu.analysis.compile_guard import (
        compile_guard as guard)
    return guard


@pytest.fixture
def spmd_sanitizer(tmp_path, monkeypatch):
    """Opt-in SPMD collective sanitizer (testing/spmd_sanitizer.py) for
    THIS process: sets the knob + a telemetry dir, installs the jax.lax
    interception, yields the module (sanitizer at ``get_sanitizer()``),
    and uninstalls afterwards so later tests trace unwrapped
    collectives.  Fan-out tests instead put RLA_TPU_SPMD_SANITIZER in
    env_per_worker — worker boot installs it rank-keyed."""
    from ray_lightning_accelerators_tpu.testing import spmd_sanitizer as S
    tdir = tmp_path / "spmd_telemetry"
    monkeypatch.setenv("RLA_TPU_SPMD_SANITIZER", "1")
    monkeypatch.setenv("RLA_TPU_TELEMETRY_DIR", str(tdir))
    S.install(rank=None)
    try:
        yield S
    finally:
        S.uninstall()


@pytest.fixture
def cpu_mesh_subprocess():
    """Run a python script in a SPAWNED subprocess whose backend comes up
    with an 8-device virtual CPU mesh.

    The in-process suite already forces 8 devices (module top), but some
    tests must prove behavior under a CLEAN backend init — e.g. the
    collectives suite's claim that an exchange compiles on a fresh
    8-device mesh without inheriting this process's jax config.  jax
    0.4.37 has no ``jax_num_cpu_devices`` config option, so the ONLY
    lever is ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` set
    in the child's env BEFORE its backend initializes (which is why this
    is a subprocess, not a fixture-scoped config tweak).

    Returns ``run(script, timeout=120) -> CompletedProcess`` (asserts
    exit 0, stderr in the failure message)."""
    import subprocess
    import sys

    def run(script: str, timeout: float = 120.0, env_extra=None):
        env = dict(os.environ)
        env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "JAX_PLATFORMS": "cpu",
            # the child must not inherit fan-out / chaos state
            "RLA_TPU_INSIDE_WORKER": "",
        })
        env.update(env_extra or {})
        proc = subprocess.run([sys.executable, "-c", script],
                              capture_output=True, text=True,
                              timeout=timeout, env=env)
        assert proc.returncode == 0, (
            f"cpu_mesh_subprocess script failed (rc {proc.returncode}):\n"
            f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")
        return proc

    return run


# long-lived service threads owned by third-party libraries (orbax's
# async-checkpoint machinery keeps these for the process lifetime after
# the first async save; they are joined at interpreter exit by the
# library's own atexit hooks) -- not leaks a test can or should close
_THIRD_PARTY_THREAD_PREFIXES = ("metadata_store", "base_pytree_ch",
                                "ocdbt_", "orbax")


@pytest.fixture(autouse=True)
def _thread_leak_guard(request):
    """Fail any test that leaks a live NON-daemon thread (a leaked
    prefetch producer would hang interpreter shutdown and silently
    serialize every later test).  Prefetch threads are non-daemon BY
    DESIGN so this guard has teeth: every exit path out of an epoch must
    close() its pipeline.  Daemon threads (agent/queue/watchdog service
    loops) and known third-party service threads are exempt."""
    import threading

    before = set(threading.enumerate())
    yield
    leaked = [t for t in threading.enumerate()
              if t not in before and t.is_alive() and not t.daemon
              and not t.name.startswith(_THIRD_PARTY_THREAD_PREFIXES)]
    for t in leaked:  # grace: a joining thread may be mid-exit
        t.join(timeout=2.0)
    leaked = [t for t in leaked if t.is_alive()]
    assert not leaked, (
        f"{request.node.nodeid} leaked non-daemon thread(s) "
        f"{[t.name for t in leaked]}; prefetch pipelines (and anything "
        "else spawning non-daemon threads) must be close()d on every "
        "exit path")


@pytest.fixture(autouse=True)
def _chaos_leak_guard(request):
    """``RLA_TPU_CHAOS`` makes every spawned worker crash/hang/stall on
    purpose (now including ``preempt@...``/``lost@...`` faults and the
    numeric layer — ``nanloss``/``gradspike``/``badbatch``/``bitflip`` —
    which corrupts training numerics in-step): ambient in the driver env
    it would poison EVERY fan-out in the suite.  Only
    ``@pytest.mark.chaos`` (or ``@pytest.mark.preempt``, whose tests
    drive the preemption/lost-host kinds) tests may see it set, and no
    test may leave it behind.  ``RLA_TPU_PREEMPT_GRACE_S`` gets the same
    treatment: left ambient it would install SIGTERM notice handlers in
    every spawned worker of unrelated tests; so does
    ``RLA_TPU_CHAOS_NS`` (the once-only claim namespace) — left behind,
    a later chaos test would silently inherit spent claim tokens and
    never fire its faults."""
    allowed = (request.node.get_closest_marker("chaos") is not None
               or request.node.get_closest_marker("preempt") is not None
               or request.node.get_closest_marker("pipeline_mpmd")
               is not None)
    if not allowed:
        assert "RLA_TPU_CHAOS" not in os.environ, (
            f"RLA_TPU_CHAOS leaked into non-chaos test {request.node.nodeid}"
            " -- chaos specs belong in env_per_worker or a chaos/preempt-"
            "marked test's monkeypatched env")
        assert "RLA_TPU_PREEMPT_GRACE_S" not in os.environ, (
            f"RLA_TPU_PREEMPT_GRACE_S leaked into non-preempt test "
            f"{request.node.nodeid} -- preemption grace belongs in "
            "env_per_worker or a preempt-marked test's monkeypatched env")
    yield
    assert "RLA_TPU_CHAOS" not in os.environ, (
        f"{request.node.nodeid} left RLA_TPU_CHAOS set in the driver env; "
        "later fan-outs would inherit the fault injection")
    assert "RLA_TPU_PREEMPT_GRACE_S" not in os.environ, (
        f"{request.node.nodeid} left RLA_TPU_PREEMPT_GRACE_S set in the "
        "driver env; later fan-outs would install preemption handlers")
    assert "RLA_TPU_CHAOS_NS" not in os.environ, (
        f"{request.node.nodeid} left RLA_TPU_CHAOS_NS set in the driver "
        "env; later chaos tests would inherit its spent claim tokens")
