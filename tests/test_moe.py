"""MoE routing + expert-parallel training (no reference analog; SURVEY.md
§2.4 records EP absent upstream — here it is first-class on the `expert`
mesh axis)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu import MeshConfig
from ray_lightning_accelerators_tpu.ops.moe import (expert_capacity,
                                                    init_moe_params,
                                                    moe_mlp, top_k_routing)
from tests.test_transformer import VOCAB, _fit


def test_topk_routing_dispatches_to_argmax_expert():
    # ample capacity, k=1: every token occupies exactly one slot of its
    # argmax expert, with combine weight == renormalized gate == 1
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 16, 4)), jnp.float32)
    dispatch, combine, aux = top_k_routing(logits, top_k=1, capacity=16)
    assert dispatch.shape == (2, 16, 4, 16)
    # one slot per token
    np.testing.assert_allclose(np.asarray(dispatch.sum((2, 3))), 1.0)
    chosen = np.asarray(dispatch.sum(3).argmax(-1))
    np.testing.assert_array_equal(chosen, np.asarray(logits.argmax(-1)))
    # Switch-style k=1: combine weight is the RAW gate probability (keeps
    # task-loss gradient flowing to the router), not renormalized to 1
    probs = np.asarray(jax.nn.softmax(logits, axis=-1).max(-1))
    np.testing.assert_allclose(np.asarray(combine.sum((2, 3))), probs,
                               atol=1e-5)
    assert float(aux) > 0


def test_topk_routing_respects_capacity():
    # force every token to expert 0 with capacity 2: only 2 tokens kept
    logits = jnp.zeros((1, 8, 4)).at[..., 0].set(10.0)
    dispatch, combine, _ = top_k_routing(logits, top_k=1, capacity=2)
    kept = np.asarray(dispatch.sum((1, 3)))  # per-expert token counts
    assert kept[0, 0] == 2.0 and kept[0, 1:].sum() == 0.0
    # slots are unique: each (expert, slot) filled at most once
    assert np.asarray(dispatch.sum(1)).max() <= 1.0


def test_topk2_combine_weights_renormalized():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(2, 8, 4)), jnp.float32)
    dispatch, combine, _ = top_k_routing(logits, top_k=2, capacity=8)
    np.testing.assert_allclose(np.asarray(dispatch.sum((2, 3))), 2.0)
    np.testing.assert_allclose(np.asarray(combine.sum((2, 3))), 1.0,
                               atol=1e-5)


def test_moe_single_expert_equals_dense_mlp():
    # E=1, k=1, ample capacity: MoE reduces to the dense GELU MLP (the raw
    # Switch gate is softmax over one expert == exactly 1)
    rng = jax.random.PRNGKey(0)
    params = init_moe_params(rng, 32, 64, 1)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32), jnp.float32)
    y, aux = moe_mlp(x, params, top_k=1, capacity_factor=8.0,
                     compute_dtype=jnp.float32)
    dense = jnp.einsum("bsf,fd->bsd",
                       jax.nn.gelu(jnp.einsum("bsd,df->bsf", x,
                                              params["wi"][0])),
                       params["wo"][0])
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense),
                               atol=1e-5, rtol=1e-4)


def test_expert_capacity_static():
    assert expert_capacity(64, 4, 2, 1.0) == 32
    assert expert_capacity(1, 8, 1, 1.0) == 1


def _fit_moe(tmpdir, mesh_config, max_epochs=2, **cfg_kw):
    return _fit(tmpdir, mesh_config, max_epochs=max_epochs,
                num_experts=4, moe_top_k=2, **cfg_kw)


@pytest.mark.parametrize("mesh_config", [
    MeshConfig(data=2, expert=4),
    MeshConfig(data=2, expert=2, tensor=2),
], ids=["dp2-ep4", "dp2-ep2-tp2"])
def test_moe_gpt_trains_expert_parallel(tmpdir, mesh_config):
    trainer, model = _fit_moe(tmpdir, mesh_config)
    assert trainer.callback_metrics["val_loss"] < float(jnp.log(VOCAB))
    assert "moe_aux_loss" in trainer.callback_metrics


def test_moe_expert_weights_sharded_on_expert_axis(tmpdir):
    trainer, _ = _fit_moe(tmpdir, MeshConfig(data=2, expert=4))
    wi = trainer._state.params["layers"]["mlp"]["wi"]  # (layers, E, d, f)
    spec = wi.sharding.spec
    assert spec[1] == "expert"
    assert not wi.sharding.is_fully_replicated


def test_moe_pipeline_raises(tmpdir):
    with pytest.raises(NotImplementedError):
        _fit_moe(tmpdir, MeshConfig(data=2, pipeline=2), n_layers=2)


def test_topk_exceeding_experts_raises():
    with pytest.raises(ValueError, match="top_k"):
        top_k_routing(jnp.zeros((1, 4, 2)), top_k=4, capacity=4)
