"""Gradient-exchange layer (parallel/collectives.py): quantized allreduce
exactness/error bounds, ZeRO-1 bit-identity, wire accounting, and the
Trainer flags that surface both -- all on the suite's 8-device CPU mesh."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_lightning_accelerators_tpu import (ArrayDataset, DataLoader,
                                            RayTPUAccelerator, Trainer)
from ray_lightning_accelerators_tpu.parallel import collectives as C
from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib

from .utils import BlobsDataModule, BoringModel, LinearClassifier, \
    boring_loaders

pytestmark = pytest.mark.collectives


def _lead_sharding(mesh):
    return NamedSharding(mesh, P(mesh_lib.BATCH_AXES))


def _put_stacked(mesh, tree):
    lead = _lead_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(jnp.asarray(a), lead), tree)


def _exchange_once(mesh, cfg, params, grads, residuals=None):
    n = C.dp_size(mesh)
    res = residuals if residuals is not None \
        else _put_stacked(mesh, C.residual_zeros(params, n, cfg))
    ex = jax.jit(C.build_exchange(mesh, cfg))
    return ex(_put_stacked(mesh, grads), res)


# --------------------------------------------------------------------- #
# Pure quantization                                                      #
# --------------------------------------------------------------------- #
def test_quantize_blocks_roundtrip_error():
    rng = np.random.default_rng(0)
    v = jnp.asarray(rng.normal(size=(4096,)).astype(np.float32))
    q, s = C.quantize_blocks(v, 256)
    assert q.dtype == jnp.int8 and s.shape == (16,)
    back = C.dequantize_blocks(q, s)
    rel = float(jnp.linalg.norm(back - v) / jnp.linalg.norm(v))
    assert rel < 1e-2
    # all-zero blocks must not divide by zero
    qz, sz = C.quantize_blocks(jnp.zeros((256,)), 256)
    assert float(jnp.abs(C.dequantize_blocks(qz, sz)).max()) == 0.0


def test_exchange_config_validates_mode():
    with pytest.raises(ValueError, match="grad_compression"):
        C.ExchangeConfig(mode="int4")
    with pytest.raises(ValueError, match="block"):
        C.ExchangeConfig(mode="int8", block=0)


# --------------------------------------------------------------------- #
# Exchange numerics on the 8-device mesh                                 #
# --------------------------------------------------------------------- #
def test_int8_exchange_single_step_error_bound():
    """Acceptance bound: one int8 exchange of random grads lands within
    1e-2 relative error of the true fp32 mean, per leaf."""
    mesh = mesh_lib.build_mesh()
    n = C.dp_size(mesh)
    cfg = C.ExchangeConfig(mode="int8")
    rng = np.random.default_rng(0)
    params = {"w": np.zeros((512, 64), np.float32),
              "b": np.zeros((7,), np.float32)}
    grads = {"w": rng.normal(size=(n, 512, 64)).astype(np.float32),
             "b": rng.normal(size=(n, 7)).astype(np.float32)}
    out, new_res = _exchange_once(mesh, cfg, params, grads)
    true = jax.tree.map(lambda a: a.mean(0), grads)
    for key in ("w", "b"):
        t = np.asarray(true[key])
        rel = np.linalg.norm(np.asarray(out[key]) - t) / np.linalg.norm(t)
        assert rel < 1e-2, f"{key}: rel err {rel}"
    # sub-threshold leaf rides the fp32 psum: exact (up to psum rounding)
    np.testing.assert_allclose(np.asarray(out["b"]), true["b"], rtol=1e-6)
    # residuals: real buffers only for the compressed leaf
    assert np.asarray(new_res["w"]).shape == (n, 512 * 64)
    assert np.asarray(new_res["b"]).shape == (n, 1)
    assert float(jnp.abs(new_res["b"]).max()) == 0.0
    assert float(jnp.linalg.norm(new_res["w"])) > 0.0


def test_error_feedback_reduces_bias_across_steps():
    """Feeding the residual back must push the RUNNING MEAN of exchanged
    grads toward the true mean -- the property that keeps SGD convergent
    under lossy exchange."""
    mesh = mesh_lib.build_mesh()
    n = C.dp_size(mesh)
    cfg = C.ExchangeConfig(mode="int8")
    rng = np.random.default_rng(1)
    params = {"w": np.zeros((256, 64), np.float32)}
    grads = {"w": rng.normal(size=(n, 256, 64)).astype(np.float32)}
    gd = _put_stacked(mesh, grads)
    res = _put_stacked(mesh, C.residual_zeros(params, n, cfg))
    ex = jax.jit(C.build_exchange(mesh, cfg))
    true = grads["w"].mean(0)
    outs = []
    for _ in range(4):
        out, res = ex(gd, res)
        outs.append(np.asarray(out["w"]))
    err1 = np.linalg.norm(outs[0] - true) / np.linalg.norm(true)
    err4 = np.linalg.norm(np.mean(outs, 0) - true) / np.linalg.norm(true)
    assert err4 < err1 * 0.75, (err1, err4)


def test_bf16_exchange_error_bound():
    mesh = mesh_lib.build_mesh()
    n = C.dp_size(mesh)
    cfg = C.ExchangeConfig(mode="bf16")
    rng = np.random.default_rng(2)
    params = {"w": np.zeros((512, 64), np.float32)}
    grads = {"w": rng.normal(size=(n, 512, 64)).astype(np.float32)}
    out, _ = _exchange_once(mesh, cfg, params, grads)
    true = grads["w"].mean(0)
    rel = np.linalg.norm(np.asarray(out["w"]) - true) / np.linalg.norm(true)
    assert rel < 5e-3


def test_wire_bytes_report():
    params = {"w": np.zeros((512, 512), np.float32),   # compressed
              "b": np.zeros((64,), np.float32)}        # fp32 path
    r8 = C.wire_bytes_per_step(params, 8, C.ExchangeConfig(mode="int8"))
    # acceptance: >= 3.5x on the large (compressed) leaves
    assert r8["compressed_ratio"] >= 3.5
    assert r8["compressed_leaves"] == 1 and r8["fp32_leaves"] == 1
    assert r8["exchange_bytes_per_step"] < r8["baseline_fp32_bytes_per_step"]
    rb = C.wire_bytes_per_step(params, 8, C.ExchangeConfig(mode="bf16"))
    assert abs(rb["compressed_ratio"] - 2.0) < 1e-6
    rn = C.wire_bytes_per_step(params, 8, C.ExchangeConfig(mode=None))
    assert rn["compression_ratio"] == 1.0 and rn["compressed_leaves"] == 0


def test_compression_rejects_model_parallel_mesh():
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=4, tensor=2))
    with pytest.raises(ValueError, match="pure data-parallel"):
        C.validate_mesh_for_compression(mesh)
    # and through the public Trainer surface
    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir="/tmp/collectives_tp",
                      accelerator=RayTPUAccelerator(num_workers=4, tensor=2),
                      grad_compression="int8")
    train, val = boring_loaders()
    with pytest.raises(ValueError, match="pure data-parallel"):
        trainer.fit(BoringModel(), train, val)


def test_trainer_rejects_unknown_compression_mode():
    with pytest.raises(ValueError, match="grad_compression"):
        Trainer(grad_compression="fp8")


def test_compression_rejects_model_parallel_params(tmpdir):
    """fsdp-sharded params now RIDE the compressed exchange (PR 8,
    tests/test_fsdp_exchange.py); the boundary that remains is
    model-parallel sharding — tensor/sequence-sharded gradients are not
    replicas, so the refusal stays, typed."""
    from ray_lightning_accelerators_tpu.parallel.collectives import (
        TensorShardedParamsError)

    class TPBoring(BoringModel):
        def param_logical_axes(self):
            return {"layer": {"kernel": ("embed", "mlp"),
                              "bias": None}}

    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmpdir),
                      accelerator=RayTPUAccelerator(num_workers=4,
                                                    tensor=2),
                      grad_compression="int8")
    x = np.random.default_rng(0).normal(size=(64, 32)).astype(np.float32)
    loader = DataLoader(ArrayDataset(x), batch_size=16)
    with pytest.raises(TensorShardedParamsError,
                       match="fsdp-sharded params only"):
        trainer.fit(TPBoring(), loader)


def test_profiler_reset_clears_comms():
    from ray_lightning_accelerators_tpu.utils.profiler import Profiler
    prof = Profiler()
    prof.record_comms({"mode": "int8", "compression_ratio": 3.9})
    assert prof.comms() is not None
    prof.reset()
    assert prof.comms() is None


# --------------------------------------------------------------------- #
# ZeRO-1                                                                 #
# --------------------------------------------------------------------- #
def _fit_linear(tmpdir, max_epochs=2, **kw):
    trainer = Trainer(max_epochs=max_epochs, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmpdir),
                      accelerator=RayTPUAccelerator(), **kw)
    model = LinearClassifier()
    dm = BlobsDataModule(n=256, batch_size=32)
    trainer.fit(model, datamodule=dm)
    return trainer, jax.device_get(trainer._state.params)


def _adam_moment(opt_state, shape):
    for leaf in jax.tree.leaves(opt_state):
        if hasattr(leaf, "shape") and tuple(leaf.shape) == shape:
            return leaf
    raise AssertionError(f"no moment leaf of shape {shape}")


def test_zero1_bit_identical_to_replicated(tmpdir):
    """Acceptance: params after K steps with shard_optimizer_state=True
    are BIT-identical to the replicated baseline (same seed/data), and
    the Adam moments are genuinely 1/N-sharded on device."""
    t0, p0 = _fit_linear(tmpdir.join("repl"))
    t1, p1 = _fit_linear(tmpdir.join("zero1"), shard_optimizer_state=True)
    for key in p0:
        assert np.array_equal(np.asarray(p0[key]), np.asarray(p1[key])), key
    n = C.dp_size(t1._mesh)
    mu = _adam_moment(t1._state.opt_state, (32, 4))
    assert not mu.sharding.is_fully_replicated
    assert mu.addressable_shards[0].data.shape == (32 // n, 4)
    # baseline moments replicated
    mu0 = _adam_moment(t0._state.opt_state, (32, 4))
    assert mu0.sharding.is_fully_replicated
    # non-divisible leaves (bias moments, counts) stay replicated
    b_mu = _adam_moment(t1._state.opt_state, (4,))
    assert b_mu.sharding.is_fully_replicated


def test_zero1_sharded_checkpoint_roundtrip(tmpdir):
    """Acceptance: a sharded-opt-state checkpoint round-trips through
    save_sharded/restore_sharded."""
    from ray_lightning_accelerators_tpu.utils import \
        sharded_checkpoint as sharded_lib

    trainer, params = _fit_linear(tmpdir, shard_optimizer_state=True,
                                  checkpoint_format="sharded")
    path = os.path.join(str(tmpdir), "z1.ckpt")
    trainer.save_checkpoint(path)
    assert sharded_lib.is_sharded_checkpoint(path)
    restored = sharded_lib.restore_sharded(path, template=trainer._state)
    for a, b in zip(jax.tree.leaves(trainer._state),
                    jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and a fresh trainer resumes training from it
    trainer2 = Trainer(max_epochs=3, precision="f32", seed=0,
                       enable_checkpointing=False,
                       default_root_dir=str(tmpdir),
                       accelerator=RayTPUAccelerator(),
                       shard_optimizer_state=True)
    model2 = LinearClassifier()
    trainer2.fit(model2, datamodule=BlobsDataModule(n=256, batch_size=32),
                 ckpt_path=path)
    assert trainer2.current_epoch == 3


# --------------------------------------------------------------------- #
# Compression through the Trainer                                        #
# --------------------------------------------------------------------- #
def _fit_mnist(tmpdir, **kw):
    from ray_lightning_accelerators_tpu.models.mnist import (MNISTClassifier,
                                                             synthetic_mnist)
    x, y = synthetic_mnist(2048, seed=0)
    loader = DataLoader(ArrayDataset(x, y), batch_size=256, shuffle=True)
    model = MNISTClassifier({"layer_1": 64, "layer_2": 64, "lr": 1e-3,
                             "batch_size": 256})
    trainer = Trainer(max_epochs=3, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmpdir),
                      accelerator=RayTPUAccelerator(), **kw)
    trainer.fit(model, loader)
    return trainer


def test_int8_training_tracks_fp32_loss(tmpdir):
    """Acceptance: a short MNIST run under int8 exchange reaches a final
    loss within 2% of the fp32 baseline, and the comms accounting
    reports the >= 3.5x large-leaf wire reduction."""
    from ray_lightning_accelerators_tpu.utils.profiler import Profiler

    base = _fit_mnist(tmpdir.join("fp32"))
    prof = Profiler()
    comp = _fit_mnist(tmpdir.join("int8"), grad_compression="int8",
                      profiler=prof)
    l0 = base.callback_metrics["train_loss"]
    l1 = comp.callback_metrics["train_loss"]
    assert abs(l1 - l0) / l0 < 0.02, (l0, l1)
    report = comp.comms_per_step
    assert report is not None and report["mode"] == "int8"
    assert report["compressed_ratio"] >= 3.5
    # the two large MLP kernels (784x64, 64x64); the 64x10 head and the
    # biases sit below min_compress_size and ride the fp32 path
    assert report["compressed_leaves"] == 2
    assert prof.comms() == report
    assert f"{report['compressed_ratio']}x" in prof.describe()


def test_compression_accumulation_applies_at_boundary(tmpdir):
    """With accumulate_grad_batches=2 the exchange+update run only at
    window boundaries: params after 3 micro-steps equal params after 2
    (the odd step only accumulates), and differ after 4."""
    def fit(max_steps):
        trainer = Trainer(max_steps=max_steps, max_epochs=10,
                          precision="f32", seed=0,
                          enable_checkpointing=False,
                          default_root_dir=str(tmpdir),
                          accumulate_grad_batches=2,
                          grad_compression="int8",
                          accelerator=RayTPUAccelerator())
        x = np.random.default_rng(0).normal(size=(64, 32)).astype(np.float32)
        loader = DataLoader(ArrayDataset(x), batch_size=8, shuffle=False)
        model = BoringModel()
        trainer.fit(model, loader)
        return jax.device_get(trainer._state.params)

    p2, p3, p4 = fit(2), fit(3), fit(4)
    for key in ("kernel", "bias"):
        np.testing.assert_array_equal(p2["layer"][key], p3["layer"][key])
    assert not np.array_equal(p3["layer"]["kernel"], p4["layer"]["kernel"])


def test_compression_accumulation_matches_multisteps_on_exact_path(tmpdir):
    """BoringModel's leaves sit below min_compress_size, so the exchange
    is a plain psum-mean (lossless): the compressed-path accumulator must
    reproduce the MultiSteps baseline to float tolerance."""
    def fit(**kw):
        trainer = Trainer(max_epochs=2, precision="f32", seed=0,
                          enable_checkpointing=False,
                          default_root_dir=str(tmpdir),
                          accumulate_grad_batches=2,
                          accelerator=RayTPUAccelerator(), **kw)
        x = np.random.default_rng(0).normal(size=(64, 32)).astype(np.float32)
        loader = DataLoader(ArrayDataset(x), batch_size=8, shuffle=False)
        model = BoringModel()
        trainer.fit(model, loader)
        return jax.device_get(trainer._state.params)

    base = fit()
    comp = fit(grad_compression="int8")
    for key in ("kernel", "bias"):
        np.testing.assert_allclose(comp["layer"][key], base["layer"][key],
                                   rtol=1e-5, atol=1e-6)


def test_compression_composes_with_zero1_and_checkpoint(tmpdir):
    """int8 exchange + ZeRO-1 + sharded checkpointing in one run: the
    full flag-to-wire path, including residual state surviving a
    save/restore."""
    from ray_lightning_accelerators_tpu.utils import \
        sharded_checkpoint as sharded_lib

    trainer = _fit_mnist(tmpdir, grad_compression="int8",
                         shard_optimizer_state=True,
                         checkpoint_format="sharded")
    assert trainer._state.residual is not None
    path = os.path.join(str(tmpdir), "both.ckpt")
    trainer.save_checkpoint(path)
    restored = sharded_lib.restore_sharded(path, template=trainer._state)
    for a, b in zip(jax.tree.leaves(trainer._state),
                    jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_checkpoint_restores_into_compression_enabled_run(tmpdir):
    """Turning grad_compression ON over a sharded checkpoint saved
    WITHOUT it: orbax restore is structure-checked, so the trainer
    retries with a stripped template and keeps fresh zero residuals
    (the drift case docs/API.md promises for the sharded format)."""
    trainer, params = _fit_linear(tmpdir, checkpoint_format="sharded")
    path = os.path.join(str(tmpdir), "plain.ckpt")
    trainer.save_checkpoint(path)
    trainer2 = Trainer(max_epochs=3, precision="f32", seed=0,
                       enable_checkpointing=False,
                       default_root_dir=str(tmpdir),
                       accelerator=RayTPUAccelerator(),
                       grad_compression="int8")
    model2 = LinearClassifier()
    trainer2.fit(model2, datamodule=BlobsDataModule(n=256, batch_size=32),
                 ckpt_path=path)
    assert trainer2.current_epoch == 3
    assert trainer2._state.residual is not None
    # restored params really came from the checkpoint
    assert trainer2.global_step > trainer.global_step


def test_pickle_checkpoint_backcompat_without_residual_fields():
    """A pickle checkpoint written before the residual/grad_accum fields
    existed must restore into the new TrainState (fresh zeros), and a
    residual-carrying checkpoint must restore with compression off
    (residuals dropped)."""
    import optax

    from ray_lightning_accelerators_tpu.core.state import TrainState
    from ray_lightning_accelerators_tpu.utils import checkpoint as ckpt_lib

    params = {"w": jnp.ones((4, 2))}
    tx = optax.sgd(0.1)
    old_style = ckpt_lib.build_checkpoint(
        TrainState.create(params, tx, jax.random.PRNGKey(0)), 1, 10)
    # simulate the pre-PR payload: no residual/grad_accum keys at all
    old_style["state"].pop("residual", None)
    old_style["state"].pop("grad_accum", None)
    template = TrainState.create(
        params, tx, jax.random.PRNGKey(0),
        residual={"w": jnp.zeros((8, 8))})
    restored = ckpt_lib.restore_state(old_style, template)
    assert np.asarray(restored.residual["w"]).shape == (8, 8)
    np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                  np.ones((4, 2)))
    # reverse direction: saved residuals, compression now off
    new_style = ckpt_lib.build_checkpoint(template, 1, 10)
    plain = TrainState.create(params, tx, jax.random.PRNGKey(0))
    restored2 = ckpt_lib.restore_state(new_style, plain)
    assert restored2.residual is None


def test_exchange_in_clean_subprocess(cpu_mesh_subprocess):
    """The exchange must compile and hit its error bound under a FRESH
    backend init with the forced-host-platform flag -- the conftest
    fixture the collectives CI lane is built on."""
    cpu_mesh_subprocess("""
import jax, numpy as np, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from ray_lightning_accelerators_tpu.parallel import collectives as C
from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib
assert jax.device_count() == 8, jax.device_count()
mesh = mesh_lib.build_mesh()
n = C.dp_size(mesh)
cfg = C.ExchangeConfig(mode="int8")
rng = np.random.default_rng(0)
params = {"w": np.zeros((256, 64), np.float32)}
grads = {"w": rng.normal(size=(n, 256, 64)).astype(np.float32)}
lead = NamedSharding(mesh, P(mesh_lib.BATCH_AXES))
gd = jax.tree.map(lambda a: jax.device_put(jnp.asarray(a), lead), grads)
res = jax.tree.map(lambda a: jax.device_put(a, lead),
                   C.residual_zeros(params, n, cfg))
out, _ = jax.jit(C.build_exchange(mesh, cfg))(gd, res)
true = grads["w"].mean(0)
rel = np.linalg.norm(np.asarray(out["w"]) - true) / np.linalg.norm(true)
assert rel < 1e-2, rel
print("OK", rel)
""")
