"""Telemetry subsystem (telemetry/): flight recorder, trace IDs, the
unified MetricsRegistry export, and crash postmortem reports.

The acceptance loops:

- an induced ``hang@rank1`` chaos run produces a ``run_report.json``
  with per-rank event timelines sharing one trace id across
  driver -> worker, and the raised ``WorkerWedged.diagnosis`` embeds the
  wedged rank's flight-recorder tail — across BOTH wire rebuild paths
  (local pipe and agent relay, runtime/wire.py);
- one run's MetricsRegistry export (Prometheus text + JSON) carries
  trainer, prefetch, comms, serve and compile-count metrics together;
- the recorder adds zero retraces to a trainer run (compile-guard) and
  bounded step-time overhead.
"""

import json
import logging
import os
import time

import numpy as np
import pytest

from ray_lightning_accelerators_tpu.telemetry import recorder as R
from ray_lightning_accelerators_tpu.telemetry import registry as REG
from ray_lightning_accelerators_tpu.utils.profiler import Profiler

pytestmark = pytest.mark.telemetry

HB = 0.05


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Each test gets a clean process recorder (and leaves one behind)."""
    R._reset_for_tests()
    yield
    R._reset_for_tests()


def _ok(x=1):
    return x * 2


# --------------------------------------------------------------------- #
# Flight recorder (ring, traces, spill)                                  #
# --------------------------------------------------------------------- #
def test_ring_is_bounded_ordered_and_traced():
    rec = R.FlightRecorder(capacity=8, rank=2, trace_id="t0")
    for i in range(20):
        rec.emit("train_step", step=i)
    evts = rec.events()
    assert len(evts) == 8  # bounded: oldest 12 dropped
    assert [e["data"]["step"] for e in evts] == list(range(12, 20))
    assert all(e["rank"] == 2 and e["trace"] == "t0" for e in evts)
    assert [e["ts"] for e in evts] == sorted(e["ts"] for e in evts)
    # per-event trace override (serve's per-request ids)
    rec.emit("serve_admit", trace="req-1", request=7)
    last = rec.events()[-1]
    assert last["trace"] == "req-1" and last["data"]["request"] == 7
    assert rec.events(last_n=2)[-1] == last
    rec.clear()
    assert rec.events() == []


def test_disabled_recorder_is_a_noop(monkeypatch):
    rec = R.FlightRecorder(enabled=False)
    rec.emit("train_step", step=1)
    assert rec.events() == []
    # the knob path: RLA_TPU_TELEMETRY=0 disables the process recorder
    monkeypatch.setenv("RLA_TPU_TELEMETRY", "0")
    R.configure()
    R.emit("train_step", step=1)
    assert R.get_recorder().events() == []


def test_trace_mint_set_and_ambient():
    t1, t2 = R.mint_trace_id(), R.mint_trace_id()
    assert t1 != t2 and len(t1) == 16
    R.set_trace_id(t1)
    assert R.current_trace_id() == t1
    R.emit("fit_start")
    assert R.get_recorder().events()[-1]["trace"] == t1


def test_spill_roundtrip_and_torn_files(tmp_path, monkeypatch):
    monkeypatch.setenv("RLA_TPU_TELEMETRY_DIR", str(tmp_path))
    rec = R.configure(rank=5, trace_id="tr")
    rec.emit("dispatch_begin", n=1)  # first emit spills eagerly
    path = R.spill_path_for(5)
    assert path == str(tmp_path / "rank5.events.json")
    snap = R.read_spill(path)
    assert snap["rank"] == 5 and snap["trace_id"] == "tr"
    (tail,) = R.tail_events(snap, 1)
    assert tail["kind"] == "dispatch_begin" and tail["trace"] == "tr"
    # missing and torn files read as None, never raise
    assert R.read_spill(str(tmp_path / "nope.json")) is None
    torn = tmp_path / "rank9.events.json"
    torn.write_text("{not json")
    assert R.read_spill(str(torn)) is None
    # dir-wide gather skips the torn file, keys by rank
    tails = REG.gather_spill_dir(str(tmp_path))
    assert list(tails) == ["5"]


# --------------------------------------------------------------------- #
# Profiler.merge (reservoir/max/count semantics)                         #
# --------------------------------------------------------------------- #
def test_profiler_merge_exact_when_under_cap():
    p1, p2 = Profiler(), Profiler()
    for _ in range(10):
        p1.observe("s", 1.0)
    for _ in range(5):
        p2.observe("s", 3.0)
    p1.incr("c", 1)
    p2.incr("c", 2)
    p1.gauge("g", 5)
    p2.gauge("g", 9)
    p2.record_comms({"mode": "int8", "compression_ratio": 3.9})
    p1.merge(p2)  # live-object form
    s = p1.summary()["s"]
    assert s["count"] == 15
    assert abs(s["total_s"] - 25.0) < 1e-9
    assert s["max_s"] == 3.0
    assert s["p95_s"] == 3.0  # 5/15 of the union is 3.0
    assert p1.counters()["c"] == 3
    g = p1.gauges()["g"]
    assert (g["count"], g["min"], g["max"], g["last"]) == (2, 5.0, 9.0, 9.0)
    assert p1.comms()["compression_ratio"] == 3.9
    # export dict form merges identically
    p3 = Profiler()
    p3.merge(p1.export_state())
    assert p3.summary()["s"]["count"] == 15


def test_profiler_merge_reservoir_is_count_weighted():
    # one side summarizes 100k spans at ~1.0 with a full (capped)
    # reservoir; the other 10 spans at 100.0.  A naive concat would give
    # the tiny side ~0.25% of the sample; correct weighting keeps the
    # big side's median AND the exact global max.
    big = {"stats": {"x": {"count": 100_000, "total": 100_000.0,
                           "samples": [1.0] * 4096, "max": 1.0}},
           "counters": {}, "gauges": {}, "comms": None}
    small = {"stats": {"x": {"count": 10, "total": 1_000.0,
                             "samples": [100.0] * 10, "max": 100.0}},
             "counters": {}, "gauges": {}, "comms": None}
    p = Profiler()
    p.merge(big)
    p.merge(small)
    s = p.summary()["x"]
    assert s["count"] == 100_010
    assert abs(s["total_s"] - 101_000.0) < 1e-6
    assert s["max_s"] == 100.0  # exact max survives the reservoir
    assert s["p50_s"] == 1.0    # dominant population wins the median
    assert len(p.export_state()["stats"]["x"]["samples"]) <= 4096


# --------------------------------------------------------------------- #
# MetricsRegistry exports                                                #
# --------------------------------------------------------------------- #
def _populated_registry():
    prof = Profiler()
    for _ in range(4):
        prof.observe("train_step", 0.01)
    prof.incr("prefetch_starved_steps", 2)
    prof.gauge("prefetch_depth", 1)
    prof.record_comms({"mode": "int8", "compression_ratio": 3.9,
                       "exchange_bytes_per_step": 1000,
                       "baseline_fp32_bytes_per_step": 3900})
    reg = REG.MetricsRegistry(trace_id="abc")
    reg.add_profiler(prof, rank="driver")
    reg.add_serve({"completed": 4, "failed": 0, "queue_depth": 0,
                   "throughput_tok_s": 12.5}, rank=0)
    reg.add_compile_count(7, rank="driver")
    reg.add_events([{"kind": "train_step", "trace": "abc"},
                    {"kind": "train_step", "trace": "abc"},
                    {"kind": "serve_admit", "trace": "r1"}], rank="driver")
    return reg


def test_registry_json_export():
    j = _populated_registry().to_json()
    assert j["trace_id"] == "abc"
    assert j["spans"]["train_step"]["count"] == 4
    assert j["counters"]["prefetch_starved_steps"] == 2
    assert j["gauges"]["prefetch_depth"]["last"] == 1
    assert j["comms"]["compression_ratio"] == 3.9
    assert j["serve"]["0"]["completed"] == 4
    assert j["compile"]["total_backend_compiles"] == 7
    assert j["events"] == {"train_step": 2, "serve_admit": 1}
    json.dumps(j)  # the export is JSON-able end to end


def test_registry_prometheus_export():
    txt = _populated_registry().prometheus_text()
    assert 'rla_tpu_span_seconds{span="train_step",quantile="0.5"}' in txt
    assert "rla_tpu_span_seconds_count" in txt
    assert "rla_tpu_prefetch_starved_steps_total 2" in txt
    assert "rla_tpu_prefetch_depth 1" in txt
    assert "rla_tpu_comms_compression_ratio 3.9" in txt
    assert 'rla_tpu_serve_completed_total{rank="0"} 4' in txt
    assert 'rla_tpu_serve_throughput_tok_s{rank="0"} 12.5' in txt
    assert "rla_tpu_backend_compiles_total 7" in txt
    assert 'rla_tpu_events_total{kind="train_step"} 2' in txt
    # exposition-format sanity: every sample line is name{labels} value
    # (shared validator — test_live applies the SAME one to live scrapes)
    from tests.utils import assert_prometheus_exposition
    assert_prometheus_exposition(txt)


def test_serve_metrics_reset_clears_every_structure():
    # the PR 3/PR 4 lesson as a test: reset must miss NOTHING
    from ray_lightning_accelerators_tpu.serve.metrics import ServeMetrics
    m = ServeMetrics()
    m.inc("submitted")
    m.observe_ttft(0.1)
    m.observe_queue_wait(0.04)
    m.observe_prefill(0.05)
    m.observe_step(0.01, active=3)
    m.observe_token_latency(0.002)
    before = m.snapshot()
    assert before["submitted"] == 1 and before["max_batch"] == 3 \
        and before["busy_s"] > 0 and before["ttft_s"] is not None \
        and before["queue_wait_s"] is not None
    m.reset()
    snap = m.snapshot()
    for k in ServeMetrics._COUNTERS:
        assert snap[k] == 0, f"reset missed counter {k!r}"
    assert snap["max_batch"] == 0
    assert snap["busy_s"] == 0.0 and snap["throughput_tok_s"] == 0.0
    for fam in ("ttft_s", "queue_wait_s", "token_latency_s",
                "decode_step_s", "prefill_s"):
        assert snap[fam] is None, f"reset missed reservoir {fam!r}"
    assert m.profiler.summary() == {}


def test_run_report_write_and_schema(tmp_path):
    R.configure(trace_id="tr-77")
    R.emit("fit_start", step=0)
    err = RuntimeError("boom")
    err.rank = 1
    err.diagnosis = {"detail": "stale", "events": [{"kind": "x"}]}
    path = REG.write_run_report(
        str(tmp_path), error=err,
        rank_events={"1": {"events": [{"kind": "dispatch_begin",
                                       "trace": "tr-77"}]}},
        stall_diagnosis={"error": "worker wedged"},
        extra={"attempt": 2})
    assert path == str(tmp_path / "run_report.json")
    rep = json.load(open(path))
    assert rep["schema"] == REG.REPORT_SCHEMA
    assert rep["kind"] == "run_report" and rep["trace_id"] == "tr-77"
    assert rep["error"] == {"type": "RuntimeError", "message": "boom",
                            "rank": 1,
                            "diagnosis": err.diagnosis}
    assert rep["stall_diagnosis"]["error"] == "worker wedged"
    assert rep["extra"]["attempt"] == 2
    # driver timeline included automatically; named ranks preserved
    assert rep["ranks"]["driver"]["events"][0]["kind"] == "fit_start"
    assert rep["ranks"]["1"]["events"][0]["trace"] == "tr-77"
    assert "written_unix" in rep and "compile" in rep


# --------------------------------------------------------------------- #
# Logging satellite (rank/pid formatter + JSON mode)                     #
# --------------------------------------------------------------------- #
def test_log_formatter_rank_pid_and_json_mode(monkeypatch):
    from ray_lightning_accelerators_tpu.utils import logging as ulog
    record = ulog.log.makeRecord("ray_lightning_accelerators_tpu",
                                 logging.WARNING, "f.py", 1,
                                 "hello %s", ("world",), None)
    plain = ulog._RankFormatter(json_mode=False)
    s = plain.format(record)
    assert f"driver:{os.getpid()}" in s and "hello world" in s
    R.configure(rank=3)
    assert f" 3:{os.getpid()}" in plain.format(record)
    row = json.loads(ulog._RankFormatter(json_mode=True).format(record))
    assert row["rank"] == "3" and row["pid"] == os.getpid()
    assert row["level"] == "WARNING" and row["msg"] == "hello world"
    # the knob wires through configure_logging; restore afterwards
    try:
        monkeypatch.setenv("RLA_TPU_LOG_JSON", "1")
        ulog.configure_logging()
        h = next(h for h in ulog.log.handlers
                 if isinstance(h, logging.StreamHandler))
        assert h.formatter.json_mode is True
    finally:
        ulog.configure_logging(json_mode=False)


# --------------------------------------------------------------------- #
# Cross-process: worker events, tails, wedge diagnosis                   #
# --------------------------------------------------------------------- #
def test_worker_dispatch_events_reach_the_driver_tail(tmp_path):
    from ray_lightning_accelerators_tpu.runtime.actors import Worker
    env = {"RLA_TPU_TELEMETRY_DIR": str(tmp_path),
           "RLA_TPU_TRACE_ID": "tid-1",
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB)}
    w = Worker(0, env=env)
    try:
        assert w.execute(_ok, 21).result(timeout=60) == 42
        deadline = time.monotonic() + 10
        snap = None
        while time.monotonic() < deadline:  # dispatch_end spill is gated
            snap = w.telemetry_tail()
            if snap and len(snap.get("events", [])) >= 1:
                break
            time.sleep(0.05)
        assert snap is not None and snap["rank"] == 0
        kinds = [e["kind"] for e in snap["events"]]
        assert "dispatch_begin" in kinds
        assert all(e["trace"] == "tid-1" for e in snap["events"])
    finally:
        w.kill()


@pytest.mark.chaos
def test_wedged_diagnosis_embeds_events_local_pipe(tmp_path):
    """hang@rank0 -> watchdog reap -> the WorkerWedged that crosses the
    LOCAL pipe carries the wedged rank's flight-recorder tail."""
    from ray_lightning_accelerators_tpu.runtime.actors import Worker
    from ray_lightning_accelerators_tpu.runtime.watchdog import (
        Watchdog, WorkerWedged)
    from ray_lightning_accelerators_tpu.runtime.wire import rebuild_remote
    env = {"RLA_TPU_CHAOS": "hang@rank0",
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB),
           "RLA_TPU_TELEMETRY_DIR": str(tmp_path),
           "RLA_TPU_TRACE_ID": "tid-wedge"}
    w = Worker(0, env=env)
    wd = None
    try:
        fut = w.execute(_ok)
        wd = Watchdog([w], wedge_timeout_s=0.6, poll_s=HB).start()
        with pytest.raises(WorkerWedged) as ei:
            fut.result(timeout=120)
        diag = ei.value.diagnosis
        kinds = [e["kind"] for e in diag["events"]]
        assert "dispatch_begin" in kinds  # it entered the dispatch
        assert diag["trace_id"] == "tid-wedge"
        # the SAME payload survives the (name, message, tb) wire rebuild
        # used by the agent relay — both paths via runtime/wire.py
        rebuilt = rebuild_remote("WorkerWedged", str(ei.value), "")
        assert isinstance(rebuilt, WorkerWedged)
        assert [e["kind"] for e in rebuilt.diagnosis["events"]] == kinds
        assert rebuilt.diagnosis["trace_id"] == "tid-wedge"
    finally:
        if wd is not None:
            wd.stop()
        w.kill()


@pytest.mark.chaos
def test_wedged_diagnosis_crosses_agent_relay(tmp_path):
    """Same acceptance over the REAL agent relay: the HostAgent reads the
    wedged rank's spill file host-side (the ``telemetry`` wire op), the
    reap-built WorkerWedged relays as (name, message, tb), and the
    driver rebuild recovers the embedded events."""
    from ray_lightning_accelerators_tpu.runtime.agent import (HostAgent,
                                                              RemoteWorker)
    from ray_lightning_accelerators_tpu.runtime.watchdog import (
        Watchdog, WorkerWedged)
    agent = HostAgent(port=0, bind="127.0.0.1")
    agent.serve_in_background()
    env = {"RLA_TPU_CHAOS": "hang@rank1",
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB),
           "RLA_TPU_TELEMETRY_DIR": str(tmp_path),
           "RLA_TPU_TRACE_ID": "tid-relay"}
    w = wd = None
    try:
        w = RemoteWorker(f"127.0.0.1:{agent.port}", rank=1, env=env)
        fut = w.execute(_ok)
        wd = Watchdog([w], wedge_timeout_s=0.8, poll_s=HB).start()
        with pytest.raises(WorkerWedged) as ei:
            fut.result(timeout=120)
        diag = ei.value.diagnosis
        assert ei.value.rank == 1
        assert getattr(ei.value, "remote_typed", False) or diag["events"]
        assert "dispatch_begin" in [e["kind"] for e in diag["events"]]
        assert diag["trace_id"] == "tid-relay"
        # the wire op alone also answers (wedged rank, live agent)
        snap = w.telemetry_tail()
        assert snap and snap["trace_id"] == "tid-relay"
    finally:
        if wd is not None:
            wd.stop()
        if w is not None:
            w.kill()
        agent.shutdown()


def _report_body(rank):
    return rank * 10


@pytest.mark.chaos
def test_hang_chaos_run_writes_run_report(tmp_path):
    """THE acceptance loop: induced ``hang@rank1`` under ElasticRunner
    (report_dir set) produces a ``run_report.json`` whose per-rank
    timelines share ONE trace id spanning driver -> worker, whose error
    is the typed WorkerWedged with the wedged rank's events embedded —
    and the run still completes on the retry."""
    from ray_lightning_accelerators_tpu.runtime.actors import ActorPool
    from ray_lightning_accelerators_tpu.runtime.elastic import ElasticRunner
    from ray_lightning_accelerators_tpu.runtime.watchdog import WorkerWedged
    ns = str(tmp_path / "chaos_ns")
    tdir = str(tmp_path / "telemetry")
    report_dir = str(tmp_path / "reports")
    trace = R.mint_trace_id()
    R.set_trace_id(trace)  # driver side of the shared trace
    env = {"RLA_TPU_CHAOS": "hang@rank1:once",
           "RLA_TPU_CHAOS_NS": ns,
           "RLA_TPU_WORKER_HEARTBEAT_S": str(HB),
           "RLA_TPU_TELEMETRY_DIR": tdir,
           "RLA_TPU_TRACE_ID": trace}
    pool = ActorPool(2, env_per_worker=[dict(env), dict(env)])
    try:
        runner = ElasticRunner(pool, max_failures=2, wedge_timeout_s=0.6,
                               watchdog_poll_s=HB, report_dir=report_dir)
        out = runner.run(_report_body,
                         args_per_worker=lambda a: [(r,) for r in
                                                    range(2)])
        assert sorted(out) == [0, 10]
        assert runner.attempts_used == 2  # wedged attempt + clean retry
        rep = json.load(open(os.path.join(report_dir,
                                          "run_report.json")))
        # typed failure with the wedged rank's embedded tail
        assert rep["error"]["type"] == "WorkerWedged"
        assert rep["error"]["rank"] == 1
        diag = rep["error"]["diagnosis"]
        assert "dispatch_begin" in [e["kind"] for e in diag["events"]]
        # per-rank timelines with the SHARED trace id
        assert rep["trace_id"] == trace
        driver_events = rep["ranks"]["driver"]["events"]
        assert any(e["kind"] == "elastic_attempt" and e["trace"] == trace
                   for e in driver_events)
        assert any(e["kind"] == "watchdog_transition"
                   for e in driver_events)
        rank1 = rep["ranks"]["1"]["events"]
        assert rank1 and all(e["trace"] == trace for e in rank1)
        assert rep["stall_diagnosis"]["rank"] == 1
    finally:
        pool.shutdown()


# --------------------------------------------------------------------- #
# Trainer integration: one run -> one unified export; zero retraces      #
# --------------------------------------------------------------------- #
def _tiny_trainer(tmp_path, profiler=None, **kw):
    from ray_lightning_accelerators_tpu import Trainer
    return Trainer(max_steps=kw.pop("max_steps", 8), precision="f32",
                   enable_checkpointing=False, seed=0, profiler=profiler,
                   default_root_dir=str(tmp_path),
                   log_every_n_steps=10 ** 9, **kw)


def test_unified_registry_spans_trainer_prefetch_comms_serve_compile(
        tmp_path):
    """Acceptance: ONE MetricsRegistry export (JSON + Prometheus) holds
    trainer spans, prefetch accounting, comms wire records, serve
    metrics and compile counts from a single run."""
    import jax
    from ray_lightning_accelerators_tpu import DataLoader
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg
    from ray_lightning_accelerators_tpu.data.loader import RandomDataset
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    from ray_lightning_accelerators_tpu.serve import ServeEngine
    from tests.utils import BoringModel

    cg.install()  # count compiles from before the run's first trace
    profiler = Profiler()
    trainer = _tiny_trainer(tmp_path, profiler=profiler,
                            prefetch_batches=2, grad_compression="bf16",
                            cache_dataset_on_device=False)
    trainer.fit(BoringModel(),
                DataLoader(RandomDataset(32, 64), batch_size=8))
    assert trainer.trace_id

    cfg = TransformerConfig(vocab_size=61, d_model=32, n_heads=2,
                            d_ff=64, n_layers=2, max_seq_len=64)
    model = GPT(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    with ServeEngine(model, params, max_slots=2) as engine:
        handles = [engine.submit(rng.integers(0, 61, size=(5,))
                                 .astype(np.int32), 4) for _ in range(3)]
        for h in handles:
            h.result(timeout=120)
        reg = trainer.build_metrics_registry()
        reg.add_serve(engine.metrics, rank="serve0")

    j = reg.to_json()
    assert j["trace_id"] == trainer.trace_id
    assert j["spans"]["train_step"]["count"] >= 8          # trainer
    assert "h2d_wait" in j["spans"]                        # prefetch
    assert "prefetch_depth" in j["gauges"]                 # prefetch
    assert j["comms"]["mode"] == "bf16"                    # comms
    assert j["serve"]["serve0"]["completed"] == 3          # serve
    assert j["compile"]["total_backend_compiles"] >= 1     # compile
    assert j["events"].get("train_step", 0) >= 8
    assert j["events"].get("serve_respond", 0) == 3
    txt = reg.prometheus_text()
    for needle in ('rla_tpu_span_seconds{span="train_step"',
                   "rla_tpu_prefetch_depth",
                   "rla_tpu_comms_compression_ratio",
                   'rla_tpu_serve_completed_total{rank="serve0"} 3',
                   "rla_tpu_backend_compiles_total",
                   'rla_tpu_events_total{kind="serve_respond"} 3'):
        assert needle in txt, f"{needle!r} missing from:\n{txt}"


def test_recorder_on_zero_retraces_and_bounded_overhead(tmp_path):
    """Acceptance: a recorder-ON trainer run compiles once and never
    retraces after warmup (compile-guard), and the per-step overhead of
    emitting events is bounded.  The bound is deliberately generous —
    shared-CPU wall clocks are noisy — because the emit cost itself is
    microseconds (pinned separately below)."""
    from ray_lightning_accelerators_tpu import Callback, DataLoader
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg
    from ray_lightning_accelerators_tpu.data.loader import RandomDataset
    from tests.utils import BoringModel

    class StepClock(Callback):
        def __init__(self):
            self.t = []
            self.compiles = []

        def on_train_batch_end(self, trainer, module, metrics, idx):
            self.t.append(time.perf_counter())
            self.compiles.append(cg.compile_count())

    def run(enabled):
        R.configure(enabled=enabled)
        clock = StepClock()
        tr = _tiny_trainer(tmp_path / f"run{enabled}", max_steps=12,
                           prefetch_batches=0,
                           cache_dataset_on_device=False,
                           callbacks=[clock])
        tr.fit(BoringModel(),
               DataLoader(RandomDataset(32, 96), batch_size=8))
        # steady state = steps 3.. (step 1 compiles, 2 settles)
        steps = np.diff(clock.t[2:])
        return clock, float(np.mean(steps))

    clock_on, mean_on = run(True)
    # zero retraces with the recorder ON: compile count frozen after the
    # first step's warmup across the remaining 11 steps
    assert clock_on.compiles[-1] == clock_on.compiles[0], (
        f"recorder-ON run retraced: {clock_on.compiles}")
    _, mean_off = run(False)
    assert mean_on <= mean_off * 3 + 0.02, (
        f"recorder overhead too high: on={mean_on:.5f}s "
        f"off={mean_off:.5f}s per step")
    # and the emit itself is cheap in absolute terms
    rec = R.FlightRecorder(capacity=256)
    t0 = time.perf_counter()
    for i in range(20_000):
        rec.emit("train_step", step=i)
    per_emit = (time.perf_counter() - t0) / 20_000
    assert per_emit < 5e-5, f"emit costs {per_emit * 1e6:.1f}us"


def test_fit_failure_writes_run_report(tmp_path):
    """Any uncaught fit exception leaves a run_report.json under the run
    dir — with the typed error and the driver timeline — and re-raises
    the original exception untouched."""
    from ray_lightning_accelerators_tpu import DataLoader
    from ray_lightning_accelerators_tpu.data.loader import RandomDataset
    from tests.utils import BoringModel

    class Poison(Exception):
        pass

    class Bomb:
        def __init__(self, inner):
            self.inner = inner

        def __iter__(self):
            yield from list(self.inner)[:2]
            raise Poison("poisoned batch 3")

        def __len__(self):
            return len(self.inner)

    trainer = _tiny_trainer(tmp_path, prefetch_batches=0,
                            cache_dataset_on_device=False)
    loader = Bomb(DataLoader(RandomDataset(32, 64), batch_size=8))
    with pytest.raises(Poison):
        trainer.fit(BoringModel(), loader)
    rep = json.load(open(os.path.join(str(tmp_path), "run_report.json")))
    assert rep["error"]["type"] == "Poison"
    assert rep["trace_id"] == trainer.trace_id
    kinds = [e["kind"] for e in rep["ranks"]["driver"]["events"]]
    assert "fit_start" in kinds and "train_step" in kinds
    assert rep["metrics"] is not None  # registry snapshot rode along


def test_eval_fanout_ships_rank_telemetry_under_fresh_trace(tmp_path):
    """A fanned-out validate is a run of its own: it mints a FRESH trace
    id (not the fit's), makes it ambient inside the eval workers, and
    ships every rank's telemetry home so build_metrics_registry() covers
    the eval ranks too (review finding: the eval path used to neither
    propagate the trace nor repopulate _rank_telemetry)."""
    from ray_lightning_accelerators_tpu import (DataLoader,
                                                HorovodRayAccelerator,
                                                Trainer)
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from ray_lightning_accelerators_tpu.runtime.agent import HostAgent
    from tests.utils import BoringModel

    agent = HostAgent(port=0, bind="127.0.0.1")
    agent.serve_in_background()
    trainer = None
    try:
        x = np.random.default_rng(0).normal(size=(32, 32)).astype(
            "float32")

        def loader():
            return DataLoader(ArrayDataset(x), batch_size=8,
                              shuffle=False)

        model = BoringModel()
        trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                          enable_checkpointing=False,
                          accelerator=HorovodRayAccelerator(
                              num_hosts=1, num_slots=1,
                              agents=[f"127.0.0.1:{agent.port}"]),
                          default_root_dir=str(tmp_path))
        trainer.fit(model, loader())
        fit_trace = trainer.trace_id
        assert fit_trace
        assert any(trainer._rank_telemetry.values())  # fit home-ship

        trainer.validate(model, loader())
        assert trainer.trace_id and trainer.trace_id != fit_trace
        snap = trainer._rank_telemetry.get(0)
        assert snap and snap["events"], "eval rank shipped no telemetry"
        val_events = [e for e in snap["events"]
                      if e["kind"] == "validation"]
        assert val_events, "worker validate left no timeline event"
        # the eval trace id crossed the pickle into the worker's events
        assert all(e["trace"] == trainer.trace_id for e in val_events)
        reg = trainer.build_metrics_registry()
        j = reg.to_json()
        assert j["trace_id"] == trainer.trace_id
        assert j["events"].get("validation", 0) >= 1
    finally:
        if trainer is not None:
            trainer.teardown()
        agent.shutdown()
