"""Sharded (orbax) checkpointing: round-trip, resume, async, eviction,
sharded restore placement."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu import ModelCheckpoint, Trainer
from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib
from ray_lightning_accelerators_tpu.utils import checkpoint as ckpt_lib
from ray_lightning_accelerators_tpu.utils import sharded_checkpoint as sc
from tests.utils import BoringModel, boring_loaders


def _fit(tmp_path, fmt, max_epochs=2, **ckpt_kwargs):
    train, val = boring_loaders()
    model = BoringModel()
    cb = ModelCheckpoint(monitor=None, **ckpt_kwargs)
    trainer = Trainer(max_epochs=max_epochs, precision="f32", seed=0,
                      checkpoint_format=fmt, callbacks=[cb],
                      default_root_dir=str(tmp_path))
    trainer.fit(model, train, val)
    return trainer, model, cb


@pytest.mark.parametrize("fmt", ["sharded", "sharded-async"])
def test_roundtrip(tmp_path, fmt):
    trainer, model, cb = _fit(tmp_path, fmt)
    sc.wait_until_finished()
    best = cb.best_model_path
    assert sc.is_sharded_checkpoint(best), best
    loaded = BoringModel.load_from_checkpoint(best)
    for a, b in zip(jax.tree.leaves(loaded.params),
                    jax.tree.leaves(model.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # module-level hooks round-trip through meta.json
    assert loaded.val_epoch == model.val_epoch


def test_resume_continues(tmp_path):
    trainer, model, cb = _fit(tmp_path, "sharded", max_epochs=2)
    latest = ckpt_lib.latest_checkpoint(str(tmp_path))
    assert latest is not None and sc.is_sharded_checkpoint(latest)

    train, val = boring_loaders()
    model2 = BoringModel()
    trainer2 = Trainer(max_epochs=4, precision="f32", seed=0,
                      checkpoint_format="sharded", enable_checkpointing=False,
                      default_root_dir=str(tmp_path / "resume"))
    trainer2.fit(model2, train, val, ckpt_path=latest)
    # resumed from epoch 2, ran epochs 3 and 4
    assert trainer2.global_step == trainer.global_step * 2
    assert trainer2.epochs_completed == 4


def test_eviction_removes_directories(tmp_path):
    trainer, model, cb = _fit(tmp_path, "sharded", max_epochs=4,
                              save_top_k=1)
    ckpt_dir = os.path.join(str(tmp_path), "checkpoints")
    entries = [e for e in os.listdir(ckpt_dir)
               if sc.is_sharded_checkpoint(os.path.join(ckpt_dir, e))]
    assert len(entries) == 1, entries
    assert os.path.join(ckpt_dir, entries[0]) == cb.best_model_path


def test_restore_with_shardings(tmp_path):
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=-1, fsdp=2))
    path = str(tmp_path / "direct")
    tree = {"w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            "b": jnp.ones((8,), jnp.float32)}
    sc.save_sharded(path, tree, {"epoch": 1})
    sh = jax.sharding.NamedSharding(mesh,
                                    jax.sharding.PartitionSpec("fsdp"))
    out = sc.restore_sharded(path, template=tree,
                             shardings={"w": sh, "b": sh})
    assert out["w"].sharding.spec == sh.spec
    np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(tree["w"]))
    meta = sc.read_metadata(path)
    assert meta["epoch"] == 1
    # every save now carries the per-file integrity record, and the
    # freshly written tree verifies against it
    assert meta[sc.INTEGRITY_KEY]["algo"] == "sha256"
    assert sc.verify_checkpoint(path) == (True, "ok")


def test_unknown_format_rejected():
    with pytest.raises(ValueError, match="checkpoint_format"):
        Trainer(checkpoint_format="msgpack")
