"""Real-text LM pipeline gate: loss must decrease on REAL English prose
pushed through the full LM data path (BPE tokenizer -> blank-line doc
split -> pack_sequences -> DataLoader -> Trainer.fit on GPT).

The reference gates its training loop on real MNIST
(reference: ray_lightning/tests/utils.py:137-152); this is the same
bar for the LM path, on the committed corpus under tests/data/text/
(real license prose -- see its README.md).  The synthetic grammar
corpus cannot stand in here: its ~40-word vocabulary makes even a
broken pipeline look learnable.
"""

import os

import numpy as np
import pytest

import jax

from ray_lightning_accelerators_tpu import (DataLoader, RayTPUAccelerator,
                                            Trainer)
from ray_lightning_accelerators_tpu.data.lm import (BPETokenizer,
                                                    StreamingLMDataset,
                                                    lm_dataset,
                                                    pack_sequences)
from ray_lightning_accelerators_tpu.models.transformer import (
    GPT, TransformerConfig)

_CORPUS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "data", "text", "corpus.txt")


def _read_corpus() -> str:
    with open(_CORPUS, encoding="utf-8") as f:
        return f.read()


def test_corpus_is_real_prose():
    """The committed corpus is substantial English text, not a stub."""
    text = _read_corpus()
    assert len(text) > 100_000
    words = text.split()
    # real prose has a big vocabulary (the synthetic grammar has ~40)
    assert len(set(w.lower() for w in words)) > 1500


def test_loss_decreases_on_real_text():
    text = _read_corpus()
    tokenizer = BPETokenizer(text[:20_000], vocab_size=384)
    docs = [tokenizer.encode(d) for d in text[:60_000].split("\n\n") if d]
    packed = pack_sequences(docs, seq_len=128)
    assert len(packed) >= 64  # enough real rows to train on

    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    loader = DataLoader(ArrayDataset(packed), batch_size=16, shuffle=True)

    cfg = TransformerConfig(vocab_size=tokenizer.vocab_size, d_model=128,
                            n_heads=4, d_ff=512, n_layers=2,
                            max_seq_len=128)
    model = GPT(cfg, lr=3e-3)
    trainer = Trainer(max_epochs=4, accelerator=RayTPUAccelerator(),
                      precision="f32", enable_checkpointing=False,
                      log_every_n_steps=10 ** 9, seed=0,
                      default_root_dir="/tmp/rla_tpu_lm_realtext")

    # untrained loss straight through the module's own validation_step
    init_params = model.init_params(jax.random.PRNGKey(0))
    out = model.validation_step(init_params, jax.numpy.asarray(packed[:16]))
    before_loss = float(np.asarray(out["val_loss"]))

    # untrained loss must sit near uniform -- ln(384) ~= 5.95 -- which
    # also pins that vocab/packing wiring is sane (a tiny effective
    # vocab from broken packing would start far below uniform)
    assert before_loss > 0.8 * np.log(tokenizer.vocab_size)

    trainer.fit(model, loader)
    after = trainer.validate(model, loader)[0]
    after_loss = float(after["val_loss"])

    # real-text bar: the model must have learned real statistics --
    # clearly below both its own starting point and the unigram-ish
    # regime (a pipeline that shuffles targets or drops the shift
    # cannot pass this)
    assert after_loss < 0.7 * before_loss
    assert after_loss < 4.0


def test_streaming_packer_matches_batch_packer_on_real_text():
    """StreamingLMDataset over the real corpus yields exactly the rows
    pack_sequences produces (same doc split, same EOS policy)."""
    text = _read_corpus()[:30_000]
    tokenizer = BPETokenizer(text[:10_000], vocab_size=288)
    docs = [tokenizer.encode(d) for d in text.split("\n\n") if d]
    packed = pack_sequences(docs, seq_len=64)
    ds = StreamingLMDataset(lambda epoch: iter(docs), seq_len=64)
    streamed = np.stack(list(iter(ds)))
    np.testing.assert_array_equal(packed, streamed)


def test_lm_dataset_roundtrip_real_text():
    """lm_dataset on real text: decode(encode(x)) round-trips through
    the char tokenizer, and every packed id is in-vocab."""
    text = _read_corpus()[:5_000]
    ds, tok = lm_dataset(text, seq_len=64)
    rows = np.stack([ds[i] for i in range(len(ds))])
    assert rows.dtype == np.int32
    assert rows.min() >= 0 and rows.max() < tok.vocab_size
    sample = text.split("\n\n")[0]
    assert tok.decode(tok.encode(sample)) == sample
