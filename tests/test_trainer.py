"""Trainer loop behavior: checkpoint/resume, callbacks, metrics, precision,
grad accumulation — the surface the reference inherited from PTL and its
tests pinned (SURVEY.md §2.2)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu import (EarlyStopping, ModelCheckpoint,
                                            RayTPUAccelerator, Trainer)
from ray_lightning_accelerators_tpu.utils.logging import InMemoryLogger

from .utils import BoringModel, boring_loaders, get_trainer


def test_max_steps_stops_early(tmpdir):
    trainer = get_trainer(tmpdir, RayTPUAccelerator(2), max_epochs=100,
                          max_steps=5)
    train, val = boring_loaders()
    trainer.fit(BoringModel(), train, val)
    assert trainer.global_step == 5


def test_callback_metrics_bridge(tmpdir):
    """train + val metrics must land in callback_metrics as host floats
    (the bridge Tune harvested, reference: ray_lightning/tune.py:82-95)."""
    trainer = get_trainer(tmpdir, RayTPUAccelerator(2))
    train, val = boring_loaders()
    trainer.fit(BoringModel(), train, val)
    assert isinstance(trainer.callback_metrics["val_loss"], float)
    assert trainer.callback_metrics["val_loss"] == 1.0
    assert "loss" in trainer.callback_metrics


def test_checkpoint_resume(tmpdir):
    """Mid-run checkpoint restores step/epoch/params exactly."""
    model = BoringModel()
    trainer = get_trainer(tmpdir, RayTPUAccelerator(2), max_epochs=2)
    train, val = boring_loaders()
    trainer.fit(model, train, val)
    ckpt = os.path.join(str(tmpdir), "mid.ckpt")
    trainer.save_checkpoint(ckpt)
    params_before = jax.device_get(trainer._state.params)

    model2 = BoringModel()
    trainer2 = get_trainer(tmpdir, RayTPUAccelerator(2), max_epochs=4)
    trainer2.fit(model2, train, val, ckpt_path=ckpt)
    assert trainer2.current_epoch == 4
    assert model2.val_epoch >= model.val_epoch  # module state restored + grew
    # resumed run started from the saved params, not fresh init
    fresh = model2.init_params(jax.random.PRNGKey(0))
    saved_norm = sum(float(jnp.abs(a).sum())
                     for a in jax.tree.leaves(params_before))
    fresh_norm = sum(float(jnp.abs(a).sum()) for a in jax.tree.leaves(fresh))
    assert abs(saved_norm - fresh_norm) > 1e-3


def test_model_checkpoint_top_k(tmpdir):
    class DecreasingVal(BoringModel):
        def __init__(self):
            super().__init__()
            self._val = 10.0

        def validation_step(self, params, batch):
            return {"val_loss": jnp.asarray(self._val)}

        def on_validation_epoch_end(self):
            super().on_validation_epoch_end()
            self._val -= 1.0

    cb = ModelCheckpoint(monitor="val_loss", save_top_k=2)
    trainer = get_trainer(tmpdir, RayTPUAccelerator(1), max_epochs=4,
                          callbacks=[cb])
    train, val = boring_loaders()
    model = DecreasingVal()
    trainer.fit(model, train, val)
    assert cb.best_model_path and os.path.exists(cb.best_model_path)
    saved = [p for _, p in cb._saved]
    assert len(saved) == 2 and all(os.path.exists(p) for p in saved)


def test_logger_receives_metrics(tmpdir):
    logger = InMemoryLogger()
    trainer = Trainer(default_root_dir=str(tmpdir), max_epochs=1,
                      accelerator=RayTPUAccelerator(2), logger=logger,
                      log_every_n_steps=2, precision="f32",
                      limit_train_batches=8, seed=0)
    train, val = boring_loaders()
    trainer.fit(BoringModel(), train, val)
    assert any("train_loss" in row for row in logger.history)
    assert any("val_loss" in row for row in logger.history)


def test_grad_accumulation(tmpdir):
    trainer = Trainer(default_root_dir=str(tmpdir), max_epochs=1,
                      accelerator=RayTPUAccelerator(2),
                      accumulate_grad_batches=2, precision="f32", seed=0)
    train, val = boring_loaders()
    model = BoringModel()
    trainer.fit(model, train, val)
    assert model.params is not None


def test_gradient_clipping(tmpdir):
    trainer = Trainer(default_root_dir=str(tmpdir), max_epochs=1,
                      accelerator=RayTPUAccelerator(2),
                      gradient_clip_val=0.01, precision="f32", seed=0)
    train, val = boring_loaders()
    trainer.fit(BoringModel(), train, val)


def test_bf16_precision_flag(tmpdir):
    trainer = Trainer(default_root_dir=str(tmpdir), max_epochs=1,
                      accelerator=RayTPUAccelerator(2), precision="bf16",
                      seed=0)
    model = BoringModel()
    train, val = boring_loaders()
    trainer.fit(model, train, val)
    assert model.compute_dtype == jnp.bfloat16


def test_seed_env_propagation(tmpdir):
    get_trainer(tmpdir, RayTPUAccelerator(1), callbacks=[])
    assert os.environ.get("PL_GLOBAL_SEED") == "0"
    assert os.environ.get("RLA_TPU_GLOBAL_SEED") == "0"


def test_log_grad_norm_metric():
    from tests.utils import BoringModel, boring_loaders
    train, val = boring_loaders()
    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      log_grad_norm=True, enable_checkpointing=False,
                      default_root_dir="/tmp/gn_test")
    trainer.fit(BoringModel(), train, val)
    assert trainer.callback_metrics.get("grad_norm", 0.0) > 0.0


def test_log_grad_norm_is_micro_batch_norm_under_accumulation(tmp_path):
    """Regression pin for the documented semantics: with
    accumulate_grad_batches > 1 the logged "grad_norm" is the norm of
    each MICRO-batch's gradients (what feeds the accumulator), not the
    accumulated-window norm.  One window of 2 micro-steps: params are
    untouched until the boundary, so the final metric must equal the
    analytically computed norm of the SECOND micro-batch's grads at the
    INITIAL params."""
    import optax

    from ray_lightning_accelerators_tpu import ArrayDataset, DataLoader
    from ray_lightning_accelerators_tpu.utils.seed import rng_from_seed

    x = np.random.default_rng(7).normal(size=(16, 32)).astype(np.float32)
    loader = DataLoader(ArrayDataset(x), batch_size=8, shuffle=False)
    model = BoringModel()
    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      accumulate_grad_batches=2, log_grad_norm=True,
                      enable_checkpointing=False,
                      default_root_dir=str(tmp_path))
    trainer.fit(model, loader)
    logged = trainer.callback_metrics["grad_norm"]

    init_rng, _ = jax.random.split(rng_from_seed(0))
    p0 = model.init_params(init_rng)
    batch2 = x[8:16]  # shuffle=False: second micro-batch of the window

    def loss(params):
        out = batch2 @ params["layer"]["kernel"] + params["layer"]["bias"]
        return jnp.mean((out - 1.0) ** 2)

    expected = float(optax.global_norm(jax.grad(loss)(p0)))
    assert logged == pytest.approx(expected, rel=1e-4), (logged, expected)


def test_val_check_interval_mid_epoch():
    from tests.utils import BoringModel, boring_loaders
    train, val = boring_loaders()  # 64 samples / batch 8 = 8 steps/epoch

    class CountingModel(BoringModel):
        pass

    model = CountingModel()
    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      val_check_interval=2, enable_checkpointing=False,
                      default_root_dir="/tmp/vci_test")
    trainer.fit(model, train, val)
    # 4 mid-epoch validations (steps 2,4,6,8); the epoch-boundary pass is
    # suppressed because step 8 already validated these exact params
    assert model.val_epoch == 4
    assert "val_loss" in trainer.callback_metrics

    # interval NOT aligned with epoch end: mid-epoch passes at steps 3,6
    # plus the epoch-boundary pass
    model2 = CountingModel()
    trainer2 = Trainer(max_epochs=1, precision="f32", seed=0,
                       val_check_interval=3, enable_checkpointing=False,
                       default_root_dir="/tmp/vci_test2")
    trainer2.fit(model2, train, val)
    assert model2.val_epoch == 3


def test_predict_with_datamodule():
    from tests.utils import BlobsDataModule, LinearClassifier
    dm = BlobsDataModule(n=128, batch_size=16)
    model = LinearClassifier()
    trainer = Trainer(max_epochs=3, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir="/tmp/pred_dm_test")
    trainer.fit(model, datamodule=dm)
    preds = trainer.predict(model, datamodule=dm)
    assert len(preds) > 0
    assert all(np.asarray(p).shape[0] > 0 for p in preds)


def test_max_time_stops(tmpdir):
    import time as _time

    from ray_lightning_accelerators_tpu import Callback

    class SlowCb(Callback):
        def on_train_batch_end(self, trainer, module, metrics, batch_idx):
            _time.sleep(0.05)

    trainer = Trainer(default_root_dir=str(tmpdir), max_epochs=1000,
                      max_time=0.5, precision="f32", seed=0,
                      enable_checkpointing=False, callbacks=[SlowCb()])
    train, val = boring_loaders()
    t0 = _time.perf_counter()
    trainer.fit(BoringModel(), train, val)
    assert _time.perf_counter() - t0 < 30
    assert trainer.should_stop
    assert trainer.global_step >= 1


def test_wrap_pad_batch_contract():
    """predict()'s final-partial-batch padding: pads dim 0 to the mesh
    divisor (reusing an already-compiled size when offered), refuses
    trees without one consistent per-sample axis."""
    import jax
    import numpy as np

    from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib

    trainer = Trainer(precision="f32", enable_checkpointing=False)
    mesh = mesh_lib.build_mesh()  # 8 virtual devices, data axis
    trainer._batch_sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(("data", "fsdp")))

    # divisible: untouched
    b = {"x": np.ones((16, 3)), "y": np.arange(16)}
    out, true_n, padded_n = trainer._wrap_pad_batch(b)
    assert true_n is None and out is b

    # partial: wrap-padded to the minimal multiple of 8
    b = {"x": np.arange(10)[:, None] * np.ones((10, 3)),
         "y": np.arange(10)}
    out, true_n, padded_n = trainer._wrap_pad_batch(b)
    assert (true_n, padded_n) == (10, 16)
    np.testing.assert_array_equal(out["y"],
                                  np.arange(16) % 10)

    # partial with a known compiled size: pads up to THAT (no novel
    # shape -> no extra XLA compile), not the minimal multiple
    out, true_n, padded_n = trainer._wrap_pad_batch(b, 32)
    assert (true_n, padded_n) == (10, 32)
    assert out["x"].shape == (32, 3)

    # a target that isn't divisor-aligned falls back to minimal
    out, true_n, padded_n = trainer._wrap_pad_batch(b, 30)
    assert (true_n, padded_n) == (10, 16)

    # no consistent per-sample axis: refuse (predict returns unsliced)
    mixed = {"x": np.ones((10, 3)), "stats": np.ones((4,))}
    out, true_n, padded_n = trainer._wrap_pad_batch(mixed)
    assert true_n is None and out is mixed
    scalar = {"x": np.ones((10, 3)), "n": np.float32(3.0)}
    out, true_n, padded_n = trainer._wrap_pad_batch(scalar)
    assert true_n is None and out is scalar


def test_predict_pad_strip_requires_consistent_output_axis(tmp_path):
    """Round-5 advisor fix: the pad-strip must slice outputs ONLY when
    every leaf shares the padded per-sample axis.  A leaf whose leading
    dim merely coincides with the padded size (per-head stats) must not
    be silently truncated -- mixed outputs come back unsliced with a
    warning instead."""
    import jax.numpy as jnp

    from ray_lightning_accelerators_tpu import ArrayDataset, DataLoader
    from tests.utils import BoringModel, boring_loaders

    class PerSampleOnly(BoringModel):
        def predict_step(self, params, batch):
            return {"y": self.forward(params, batch)}

    class ScalarPlus(BoringModel):
        def predict_step(self, params, batch):
            # a scalar leaf has no leading axis to mis-truncate: it must
            # not veto the strip of the per-sample leaves
            return {"y": self.forward(params, batch),
                    "temp": jnp.float32(0.7)}

    class MixedOutputs(BoringModel):
        def predict_step(self, params, batch):
            # "stats" leading dim (4) is NOT the per-sample axis
            return {"y": self.forward(params, batch),
                    "stats": jnp.ones((4, 2))}

    train, val = boring_loaders()
    x = np.random.default_rng(0).normal(size=(10, 32)).astype("float32")
    loader = DataLoader(ArrayDataset(x), batch_size=10)

    model = PerSampleOnly()
    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmp_path / "a"))
    trainer.fit(model, train, val)
    preds = trainer.predict(model, loader)
    # 10 rows pad to the 8-device divisor (16); consistent outputs are
    # sliced back to the true count
    assert np.asarray(preds[0]["y"]).shape[0] == 10

    model = ScalarPlus()
    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmp_path / "s"))
    trainer.fit(model, train, val)
    preds = trainer.predict(model, loader)
    assert np.asarray(preds[0]["y"]).shape[0] == 10  # still stripped
    assert np.ndim(preds[0]["temp"]) == 0            # scalar untouched

    model = MixedOutputs()
    trainer = Trainer(max_epochs=1, precision="f32", seed=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmp_path / "b"))
    trainer.fit(model, train, val)
    preds = trainer.predict(model, loader)
    # mixed leading dims: nothing is sliced (warn-and-skip), padding kept
    assert np.asarray(preds[0]["y"]).shape[0] == 16
    assert np.asarray(preds[0]["stats"]).shape == (4, 2)
