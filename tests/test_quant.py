"""int8 weight-only matmul kernels: interpreter-mode exactness vs the
XLA dequant reference, plus the decode-path dispatch in GPT."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_lightning_accelerators_tpu.ops import quant


def _rand_q8(rng, shape):
    q = rng.integers(-127, 128, size=shape).astype(np.int8)
    return jnp.asarray(q)


def test_int8_matmul_matches_dequant_reference():
    rng = np.random.default_rng(0)
    m, k, n = 16, 256, 384
    x = jnp.asarray(rng.normal(size=(m, k)).astype(np.float32))
    wq = _rand_q8(rng, (k, n))
    scale = jnp.asarray(rng.uniform(0.01, 0.1, size=(n,)).astype(np.float32))
    out = quant.int8_matmul(x, wq, scale, interpret=True)
    ref = x @ (wq.astype(jnp.float32) * scale[None, :])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_int8_matmul_nt_matches_reference():
    rng = np.random.default_rng(1)
    m, k, n = 8, 384, 256
    x = jnp.asarray(rng.normal(size=(m, k)).astype(np.float32))
    wq = _rand_q8(rng, (n, k))
    out = quant.int8_matmul_nt(x, wq, interpret=True)
    ref = x @ wq.astype(jnp.float32).T
    # blockwise f32 accumulation reorders the sum vs the monolithic dot
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-3, atol=1e-2)


def test_int8_matmul_bf16_inputs():
    rng = np.random.default_rng(2)
    m, k, n = 16, 128, 128
    x = jnp.asarray(rng.normal(size=(m, k)).astype(np.float32)
                    ).astype(jnp.bfloat16)
    wq = _rand_q8(rng, (k, n))
    scale = jnp.asarray(rng.uniform(0.01, 0.05, size=(n,)).astype(np.float32))
    out = quant.int8_matmul(x, wq, scale, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = (x.astype(jnp.float32)
           @ (wq.astype(jnp.float32) * scale[None, :]))
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref), rtol=2e-2, atol=0.5)


def test_supported_shapes():
    assert quant.supported(16, 768, 768)
    assert quant.supported(16, 768, 50304)
    assert not quant.supported(16, 700, 768)   # k not 128-tileable
    assert not quant.supported(16, 768, 100)   # n not 128-tileable
    # whole-M-per-cell kernels: huge row counts must fall back (VMEM)
    assert not quant.supported(8192, 768, 768)


def test_supported_edge_shapes():
    # m boundaries: single decode row is in, zero/negative rows are out,
    # the VMEM bound is inclusive
    assert quant.supported(1, 128, 128)
    assert not quant.supported(0, 128, 128)
    assert not quant.supported(-1, 128, 128)
    assert quant.supported(quant._MAX_M, 128, 128)
    assert not quant.supported(quant._MAX_M + 1, 128, 128)
    # k: 128 is the smallest lane-tileable contraction; 96 is a multiple
    # of 32 (sublane tile) but has no 128-lane block; 160 divides into
    # neither
    assert quant.supported(8, 128, 128)
    assert not quant.supported(8, 96, 128)
    assert not quant.supported(8, 160, 128)
    # n: any multiple of a 128 block works, including non-powers of two
    assert quant.supported(8, 128, 384)
    assert not quant.supported(8, 128, 64)


def test_int8_matmul_typed_errors():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(8, 128)).astype(np.float32))
    wq = _rand_q8(rng, (128, 128))
    scale = jnp.ones((128,), jnp.float32)
    # contraction mismatch
    with pytest.raises(ValueError, match="contraction dim"):
        quant.int8_matmul(x, _rand_q8(rng, (256, 128)), scale,
                          interpret=True)
    # wrong scale layout
    with pytest.raises(ValueError, match="per-out-channel"):
        quant.int8_matmul(x, wq, jnp.ones((64,)), interpret=True)
    # untileable n
    with pytest.raises(ValueError, match="128-lane"):
        quant.int8_matmul(x, _rand_q8(rng, (128, 100)),
                          jnp.ones((100,)), interpret=True)
    # untileable k (multiple of 32 but below the 128-lane block)
    with pytest.raises(ValueError, match="not tileable"):
        quant.int8_matmul(jnp.ones((8, 96)), _rand_q8(rng, (96, 128)),
                          scale, interpret=True)
    # VMEM row bound
    with pytest.raises(ValueError, match="outside"):
        quant.int8_matmul(jnp.ones((quant._MAX_M + 1, 128)), wq, scale,
                          interpret=True)


def test_int8_matmul_nt_typed_errors():
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(8, 128)).astype(np.float32))
    with pytest.raises(ValueError, match="contraction dim"):
        quant.int8_matmul_nt(x, _rand_q8(rng, (128, 256)), interpret=True)
    with pytest.raises(ValueError, match="128-lane"):
        quant.int8_matmul_nt(x, _rand_q8(rng, (100, 128)), interpret=True)
    with pytest.raises(ValueError, match="outside"):
        quant.int8_matmul_nt(jnp.ones((0, 128)), _rand_q8(rng, (128, 128)),
                             interpret=True)


def test_q8_decode_matches_dequant_decode():
    """The int8 kernels (forced interpret here) and the XLA dequant
    fallback are the same computation up to f32 accumulation order: the
    decode-step LOGITS must agree to tolerance, and the argmax must
    agree wherever the top-1 margin exceeds that tolerance.  (Exact
    token-sequence equality is deliberately NOT asserted -- a near-tie
    logit can legitimately flip argmax between differently-ordered
    reductions, and one flipped token diverges the rest of a greedy
    decode.)"""
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)

    cfg = TransformerConfig(vocab_size=512, d_model=128, n_heads=4,
                            d_ff=256, n_layers=2, max_seq_len=64)
    model = GPT(cfg, lr=1e-3)
    params = model.init_params(jax.random.PRNGKey(0))
    q8 = jax.tree.map(jnp.asarray, GPT.quantize_weights(params))
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 512, (2, 8)), jnp.int32)
    dt = model.compute_dtype

    def decode_logits():
        """Prefill logits + one incremental decode-step logits, through
        whatever q8 path _force_q8_kernel selects."""
        h_last, cache = model._prefill(q8, prompt, cache_len=16)
        l0 = model._unembed_matmul(h_last, q8, dt)
        tok = jnp.argmax(l0, -1).astype(jnp.int32)
        l1, _ = model._decode_token(q8, cache, tok, prompt.shape[1])
        return np.asarray(l0, np.float32), np.asarray(l1, np.float32)

    base0, base1 = decode_logits()
    model._force_q8_kernel = "interpret"  # route through the kernels
    try:
        kern0, kern1 = decode_logits()
        # the whole generate loop still runs through the kernels
        toks = np.asarray(model.generate(q8, prompt, max_new_tokens=8))
    finally:
        model._force_q8_kernel = None
    assert toks.shape == (2, 16)

    # measured: f32 paths agree to ~6e-7 while top-1 margins sit at
    # 0.01-0.06 -- atol=1e-4 leaves two orders of headroom on both sides
    atol = 1e-4
    for base, kern in ((base0, kern0), (base1, kern1)):
        np.testing.assert_allclose(kern, base, rtol=1e-3, atol=atol)
        top2 = np.sort(base, axis=-1)[:, -2:]
        margin = top2[:, 1] - top2[:, 0]
        decisive = margin > 20 * atol
        assert decisive.any()  # the check must actually bite
        np.testing.assert_array_equal(
            np.argmax(kern, -1)[decisive], np.argmax(base, -1)[decisive])
