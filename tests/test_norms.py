"""Pallas RMSNorm/LayerNorm kernels vs jnp references (interpret mode on
CPU; the real kernel path when run with RLA_TPU_TEST_PLATFORM on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu.ops.norms import (
    layer_norm, layer_norm_interpret, layer_norm_reference, rms_norm,
    rms_norm_interpret, rms_norm_reference)

_ON_CPU = jax.default_backend() == "cpu"
_TOL = (dict(atol=1e-6, rtol=1e-6) if _ON_CPU
        else dict(atol=1e-2, rtol=2e-2))


def _x(shape=(4, 96, 256), seed=0, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype) * 3.0


@pytest.mark.parametrize("shape", [(4, 96, 256), (8, 128), (2, 7, 384)])
def test_rms_interpret_matches_reference(shape):
    x = _x(shape)
    scale = jnp.linspace(0.5, 1.5, shape[-1])
    out = rms_norm_interpret(x, scale)
    ref = rms_norm_reference(x, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


@pytest.mark.parametrize("shape", [(4, 96, 256), (8, 128)])
def test_ln_interpret_matches_reference(shape):
    x = _x(shape)
    scale = jnp.linspace(0.5, 1.5, shape[-1])
    bias = jnp.linspace(-1.0, 1.0, shape[-1])
    out = layer_norm_interpret(x, scale, bias)
    ref = layer_norm_reference(x, scale, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_public_entries_match_reference():
    """On CPU the public ops fall back to the reference; on TPU they run
    the Pallas kernels — either way values must agree."""
    x = _x((4, 64, 256))
    scale = jnp.ones((256,)) * 1.2
    bias = jnp.zeros((256,))
    np.testing.assert_allclose(
        np.asarray(rms_norm(x, scale)),
        np.asarray(rms_norm_reference(x, scale)), **_TOL)
    np.testing.assert_allclose(
        np.asarray(layer_norm(x, scale, bias)),
        np.asarray(layer_norm_reference(x, scale, bias)), **_TOL)


def test_rms_gradients_match():
    x = _x((2, 32, 256))
    scale = jnp.linspace(0.5, 1.5, 256)

    gx, gs = jax.grad(lambda x_, s_: jnp.sum(rms_norm(x_, s_) ** 2),
                      argnums=(0, 1))(x, scale)
    rx, rs = jax.grad(
        lambda x_, s_: jnp.sum(rms_norm_reference(x_, s_) ** 2),
        argnums=(0, 1))(x, scale)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), **_TOL)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(rs), **_TOL)


def test_bf16_stays_bf16():
    x = _x((4, 128), dtype=jnp.bfloat16)
    scale = jnp.ones((128,), jnp.bfloat16)
    assert rms_norm(x, scale).dtype == jnp.bfloat16
    assert rms_norm_interpret(x, scale).dtype == jnp.bfloat16
