"""MPMD pipeline integration: 1F1B/GPipe stage groups over the actor
runtime match the single-process baseline exactly (fp32 CPU, rtol 1e-6 —
the only drift is XLA fusion order across the stage seam), keep a fixed
per-stage program count with zero steady-state retraces, and leave a
stitched cross-stage timeline under one trace id in run_report.json."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from ray_lightning_accelerators_tpu import Trainer, native
from ray_lightning_accelerators_tpu.parallel.mpmd.driver import (
    PipelineConfigError, PipelineRunner)
from ray_lightning_accelerators_tpu.utils import checkpoint as ckpt_lib
from tests.utils import BoringModel, PipelineBoringModel

pytestmark = [
    pytest.mark.pipeline_mpmd,
    # activations cross stages through the shm object store
    pytest.mark.skipif(not native.available(),
                       reason=f"native build: {native.build_error()}"),
]

M = 4


@pytest.fixture(scope="module")
def batches():
    rng = np.random.default_rng(0)
    return [rng.standard_normal((8, 8)).astype(np.float32)
            for _ in range(4)]


@pytest.fixture(scope="module")
def baseline(batches):
    """Single-process reference: same microbatch split, accumulated
    mean gradient, one optimizer apply per batch — what every pipeline
    configuration must reproduce."""
    mod = PipelineBoringModel()
    params = mod.init_params(jax.random.PRNGKey(0))
    tx = mod.configure_optimizers()
    opt = tx.init(params)

    def loss_fn(p, xb):
        return mod.training_step(p, xb, None)[0]

    losses = []
    for batch in batches:
        g_acc = jax.tree.map(jnp.zeros_like, params)
        loss_sum = 0.0
        for mb in np.split(batch, M):
            loss, g = jax.value_and_grad(loss_fn)(params, mb)
            g_acc = jax.tree.map(jnp.add, g_acc, g)
            loss_sum += float(loss)
        grads = jax.tree.map(lambda a: a / M, g_acc)
        updates, opt = tx.update(grads, opt, params)
        params = optax.apply_updates(params, updates)
        losses.append(loss_sum / M)
    return losses, params


def _run(tmpdir, batches, **kw):
    runner = PipelineRunner(PipelineBoringModel(), num_microbatches=M,
                            seed=0, workdir=str(tmpdir), **kw)
    try:
        return runner.run(batches)
    finally:
        runner.shutdown()


def test_1f1b_matches_single_group_baseline(tmpdir, batches, baseline):
    base_losses, base_params = baseline
    summary = _run(tmpdir, batches, num_stages=2, ckpt_every=4)
    np.testing.assert_allclose(summary["losses"], base_losses, rtol=1e-6)

    # final per-stage params from the replay checkpoint match the
    # baseline's, sliced by the module's own stage hook
    payload = ckpt_lib.read_checkpoint(
        ckpt_lib.latest_checkpoint(os.path.join(str(tmpdir), "ckpt")))
    assert payload["global_step"] == len(batches)
    mod = PipelineBoringModel()
    for s in (0, 1):
        got = payload["pipeline_stage_states"][str(s)]["params"]
        want = mod.pipeline_stage_params(base_params, s, 2)
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)

    # compile stability: after the step-1 warmup, the per-step compile
    # count must not move (zero steady-state retraces in any stage)
    compiles = [row["compiles"] for row in summary["steps"]]
    assert len(set(compiles[1:])) == 1, compiles

    # one trace id stitches driver rows and every stage's tick stream
    report = json.load(open(os.path.join(str(tmpdir), "run_report.json")))
    assert report["error"] is None
    assert report["trace_id"] == summary["trace_id"]
    pipe = report["extra"]["pipeline"]
    assert pipe["analytic_bubble_fraction"] == pytest.approx(1 / 5)
    assert pipe["stage_failure_budget_used"] == [0, 0]
    for rank in ("0", "1"):
        events = report["ranks"][rank]["events"]
        ticks = [e for e in events if e.get("kind") == "pipeline_tick"]
        assert ticks, f"rank {rank} recorded no pipeline ticks"
        assert all(t["trace"] == summary["trace_id"] for t in ticks)


def test_gpipe_matches_baseline(tmpdir, batches, baseline):
    base_losses, _ = baseline
    summary = _run(tmpdir, batches[:2], num_stages=2, schedule="gpipe")
    np.testing.assert_allclose(summary["losses"], base_losses[:2],
                               rtol=1e-6)
    assert summary["schedule"] == "gpipe"


def test_two_lanes_match_baseline(tmpdir, batches, baseline):
    """2 stages x 2 data-parallel lanes (4 workers): the lane-grad
    exchange sums in lane order, so the trajectory is still exact."""
    base_losses, _ = baseline
    summary = _run(tmpdir, batches[:2], num_stages=2, num_workers=4)
    assert summary["num_lanes"] == 2
    np.testing.assert_allclose(summary["losses"], base_losses[:2],
                               rtol=1e-6)


class TestRefusals:
    def test_single_stage_refused(self, tmpdir):
        with pytest.raises(PipelineConfigError, match="pipeline_stages"):
            PipelineRunner(PipelineBoringModel(), num_stages=1,
                           workdir=str(tmpdir))

    def test_workers_not_multiple_of_stages(self, tmpdir):
        with pytest.raises(PipelineConfigError, match="multiple"):
            PipelineRunner(PipelineBoringModel(), num_stages=2,
                           num_workers=3, workdir=str(tmpdir))

    def test_microbatches_not_divisible_by_lanes(self, tmpdir):
        with pytest.raises(PipelineConfigError, match="microbatch"):
            PipelineRunner(PipelineBoringModel(), num_stages=2,
                           num_workers=6, num_microbatches=4,
                           workdir=str(tmpdir))

    def test_module_without_stage_hooks_refused(self, tmpdir):
        with pytest.raises(PipelineConfigError, match="pipeline_stage"):
            PipelineRunner(BoringModel(), num_stages=2,
                           workdir=str(tmpdir))

    def test_indivisible_layer_count_refused(self, tmpdir):
        # 4 layers over 3 stages: the module's own ValueError surfaces
        # as a config refusal, not a worker-side crash
        with pytest.raises(PipelineConfigError, match="divide"):
            PipelineRunner(PipelineBoringModel(), num_stages=3,
                           workdir=str(tmpdir))._stage_parameters()


class TestTrainerWiring:
    def test_fit_routes_through_pipeline_runner(self, tmpdir, batches,
                                                baseline):
        base_losses, _ = baseline
        trainer = Trainer(max_steps=2, default_root_dir=str(tmpdir),
                          pipeline_stages=2, pipeline_microbatches=M,
                          enable_checkpointing=False, seed=0)
        trainer.fit(PipelineBoringModel(), train_dataloaders=batches)
        assert trainer.global_step == 2
        np.testing.assert_allclose(
            trainer.pipeline_summary["losses"], base_losses[:2], rtol=1e-6)
        assert trainer.callback_metrics["train_loss"] == pytest.approx(
            base_losses[1], rel=1e-6)

    def test_ctor_refusals(self):
        with pytest.raises(ValueError, match="pipeline_schedule"):
            Trainer(pipeline_stages=2, pipeline_schedule="zigzag")
        with pytest.raises(ValueError, match="pipeline_stages"):
            Trainer(pipeline_stages=0)
        with pytest.raises(ValueError, match="grad_compression"):
            Trainer(pipeline_stages=2, grad_compression="int8")
        with pytest.raises(ValueError, match="ZeRO-1"):
            Trainer(pipeline_stages=2, shard_optimizer_state=True)
        with pytest.raises(ValueError, match="accumulate"):
            Trainer(pipeline_stages=2, accumulate_grad_batches=2)

    def test_ckpt_path_refused(self, tmpdir):
        trainer = Trainer(max_steps=1, default_root_dir=str(tmpdir),
                          pipeline_stages=2)
        with pytest.raises(ValueError, match="ckpt_path"):
            trainer.fit(PipelineBoringModel(), train_dataloaders=[],
                        ckpt_path="last.ckpt")
