"""Numeric anomaly guardian (runtime/guardian.py): traced guard vector,
blame taxonomy, quarantine skip ledger, ElasticRunner rewind loop, and
the serve-tier decode guard.

The acceptance loop for the subsystem: inject ``badbatch@stepK`` numeric
chaos, the in-step guard trips on the readback that was happening
anyway, blame lands on ``data``, the blamed (epoch, batch_idx) window is
quarantined in the rank/restart-deterministic skip ledger, and the
resumed fit skips exactly that window to a clean finish — all on CPU,
no TPU, no timing races.  Chaos specs are claimed through a private
``RLA_TPU_CHAOS_NS`` so retries replay clean; conftest guards the
driver env against leaks regardless.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_lightning_accelerators_tpu import (ArrayDataset, DataLoader,
                                            Callback, ModelCheckpoint,
                                            RayTPUAccelerator, Trainer)
from ray_lightning_accelerators_tpu.runtime import guardian
from ray_lightning_accelerators_tpu.runtime.actors import ActorPool
from ray_lightning_accelerators_tpu.runtime.elastic import ElasticRunner
from ray_lightning_accelerators_tpu.runtime.guardian import (GuardConfig,
                                                             Guardian,
                                                             NumericAnomaly)
from ray_lightning_accelerators_tpu.utils import checkpoint as ckpt_lib

from .utils import BoringModel

pytestmark = pytest.mark.guardian


def _data(rows=64, seed=0):
    return np.random.default_rng(seed).normal(
        size=(rows, 32)).astype(np.float32)


def _trainer(root, guard="auto", **kw):
    kw.setdefault("max_epochs", 1)
    kw.setdefault("precision", "f32")
    kw.setdefault("seed", 0)
    kw.setdefault("enable_checkpointing", False)
    kw.setdefault("enable_progress_bar", False)
    kw.setdefault("log_every_n_steps", 1)
    return Trainer(default_root_dir=str(root), guard=guard, **kw)


# --------------------------------------------------------------------- #
# Traced half (pure jnp, no fit)                                        #
# --------------------------------------------------------------------- #
def test_update_flags_trip_and_freeze_evidence():
    """The guard-state transition: healthy steps fold the EMA, the first
    unhealthy step pins the postmortem fields, later trips keep the
    sticky bit but never overwrite the evidence."""
    cfg = GuardConfig(spike_factor=10.0, ema_decay=0.5, warmup_steps=1,
                      update_ratio_max=0.5)
    g = jnp.asarray(guardian.fresh_state())
    # step 0: healthy — seeds the EMA, arms the warmup counter
    g, m = guardian.update(cfg, g, 0, 1.0, 2.0, 0.1)
    assert float(g[guardian.I_TRIPPED]) == 0.0
    assert float(g[guardian.I_EMA]) == 2.0
    assert float(g[guardian.I_COUNT]) == 1.0
    assert m.shape == (guardian.METRIC_WIDTH,)
    # step 1: gnorm 50 > 10 * EMA(2.0) — the spike flag trips and pins
    g, _ = guardian.update(cfg, g, 1, 1.0, 50.0, 0.1)
    assert float(g[guardian.I_TRIPPED]) == 1.0
    assert float(g[guardian.I_TRIP_STEP]) == 1.0
    assert float(g[guardian.I_FLAG_SPIKE]) == 1.0
    assert float(g[guardian.I_FLAG_LOSS]) == 0.0
    # unhealthy steps never fold into the EMA
    assert float(g[guardian.I_EMA]) == 2.0
    # step 2: NaN loss — sticky stays, but the FIRST trip's evidence wins
    g, _ = guardian.update(cfg, g, 2, float("nan"), 1.0, 0.1)
    assert float(g[guardian.I_TRIP_STEP]) == 1.0
    assert float(g[guardian.I_FLAG_LOSS]) == 0.0


def test_update_names_lone_suspect_replica():
    """A some-but-not-all per-replica badness vector names the suspect;
    every-replica-bad (a poisoned global batch) names nobody."""
    cfg = GuardConfig(warmup_steps=0)
    g = jnp.asarray(guardian.fresh_state())
    bad = jnp.asarray([0.0, 0.0, 1.0, 0.0])
    g, _ = guardian.update(cfg, g, 3, float("nan"), 1.0, 0.0, rank_bad=bad)
    assert float(g[guardian.I_SUSPECT]) == 2.0
    assert float(g[guardian.I_NBAD]) == 1.0
    g2 = jnp.asarray(guardian.fresh_state())
    g2, _ = guardian.update(cfg, g2, 3, float("nan"), 1.0, 0.0,
                            rank_bad=jnp.ones((4,)))
    assert float(g2[guardian.I_SUSPECT]) == -1.0
    assert float(g2[guardian.I_NBAD]) == 4.0


def test_per_replica_bad_flags_nan_and_norm_outlier():
    stacked = {"w": jnp.asarray(np.ones((4, 8), np.float32))}
    assert np.allclose(
        np.asarray(guardian.per_replica_bad(stacked, 10.0)), 0.0)
    poisoned = np.ones((4, 8), np.float32)
    poisoned[2, 0] = np.nan
    bad = np.asarray(guardian.per_replica_bad(
        {"w": jnp.asarray(poisoned)}, 10.0))
    assert bad.tolist() == [0.0, 0.0, 1.0, 0.0]
    spiky = np.ones((4, 8), np.float32)
    spiky[1] *= 1e6  # finite, but 1e6x the replica median norm
    bad = np.asarray(guardian.per_replica_bad(
        {"w": jnp.asarray(spiky)}, 10.0))
    assert bad.tolist() == [0.0, 1.0, 0.0, 0.0]


# --------------------------------------------------------------------- #
# Quarantine ledger (pure host)                                         #
# --------------------------------------------------------------------- #
def test_quarantine_ledger_roundtrip_and_anchor(tmp_path):
    root = str(tmp_path)
    assert guardian.load_quarantine(root) == {"entries": [], "anchor": None}
    guardian.add_quarantine(root, 0, 3, 11, anchor="/ck/a.ckpt")
    guardian.add_quarantine(root, 0, 3, 11)  # idempotent append
    guardian.add_quarantine(root, 1, 5, 21)
    doc = guardian.load_quarantine(root)
    assert len(doc["entries"]) == 2
    assert doc["anchor"] == "/ck/a.ckpt"
    # the skip set is a PURE function of the ledger, per epoch — every
    # rank and every restart computes the identical set
    assert guardian.skip_set(root, 0) == {3}
    assert guardian.skip_set(root, 1) == {5}
    assert guardian.skip_set(root, 2) == set()
    # pruning must protect the anchor whether the ledger sits at the
    # checkpoint dir itself or one directory up
    assert guardian.protected_paths(root) == ["/ck/a.ckpt"]
    assert guardian.protected_paths(
        os.path.join(root, "checkpoints")) == ["/ck/a.ckpt"]
    # releasing the anchor keeps the skip entries — the data is still bad
    guardian.release_anchor(root)
    doc = guardian.load_quarantine(root)
    assert doc["anchor"] is None and len(doc["entries"]) == 2


def test_rewind_anchor_never_selects_unverified(tmp_path):
    """The rewind anchor is ``latest_checkpoint``'s digest walk: a torn
    newest checkpoint is skipped, the older verified one is handed
    over — a rewind must never land on a checkpoint it cannot restore."""
    a = tmp_path / "ckpts" / "epoch=0-step=8.ckpt"
    b = tmp_path / "ckpts" / "epoch=1-step=16.ckpt"
    a.parent.mkdir()
    ckpt_lib.atomic_save({"global_step": 8}, str(a))
    ckpt_lib.atomic_save({"global_step": 16}, str(b))
    os.utime(a, (1, 1))
    os.utime(b, (2, 2))
    g = Guardian(GuardConfig(), str(tmp_path))
    assert g._rewind_anchor() == str(b)
    b.write_bytes(b.read_bytes()[:4])  # torn mid-write
    os.utime(b, (2, 2))
    assert g._rewind_anchor() == str(a)


def test_prune_keeps_quarantine_anchor_alive(tmp_path):
    """``ModelCheckpoint._prune`` must keep the rewind anchor while a
    quarantine is active — evicting it would turn a cheap rewind into a
    cold restart — and may GC it once the anchor is released."""
    root = str(tmp_path)
    ck = tmp_path / "checkpoints"
    ck.mkdir()
    paths = []
    for i in range(3):
        p = ck / f"epoch={i}.ckpt"
        ckpt_lib.atomic_save({"global_step": 8 * (i + 1)}, str(p))
        os.utime(p, (i + 1, i + 1))
        paths.append(p)
    guardian.add_quarantine(root, 0, 2, 5, anchor=str(paths[0]))
    mc = ModelCheckpoint(monitor=None, keep_last_k=1)
    mc.dirpath = str(ck)
    mc._prune()
    assert paths[0].exists()      # the anchor, oldest, survives
    assert not paths[1].exists()  # plain retention victim
    assert paths[2].exists()      # newest of keep_last_k=1
    guardian.release_anchor(root)
    mc._prune()
    assert not paths[0].exists()


# --------------------------------------------------------------------- #
# Fit-level trips: one per blame verdict                                #
# --------------------------------------------------------------------- #
@pytest.mark.chaos
def test_nanloss_trips_typed_with_sdc_blame(tmp_path):
    """``nanloss`` lives only in the compiled step, so the eager blame
    replay runs clean — not data, compression off — the designed verdict
    is a nondeterministic suspected-SDC trip, typed with the postmortem
    embedded in the message."""
    os.environ["RLA_TPU_CHAOS"] = "nanloss@rank0:step3"
    try:
        tr = _trainer(tmp_path)
        with pytest.raises(NumericAnomaly) as ei:
            tr.fit(BoringModel(),
                   DataLoader(ArrayDataset(_data()), batch_size=8))
    finally:
        os.environ.pop("RLA_TPU_CHAOS", None)
    e = ei.value
    assert e.step == 2  # 0-based TrainState.step of the 1-based step 3
    assert e.blame == "sdc"
    assert e.diagnosis["flags"]["loss_nonfinite"]
    assert NumericAnomaly._MARKER in str(e)
    # sdc blame never quarantines data
    assert guardian.load_quarantine(str(tmp_path))["entries"] == []


@pytest.mark.chaos
def test_gradspike_trips_spike_flag(tmp_path):
    os.environ["RLA_TPU_CHAOS"] = "gradspike@rank0:step5"
    try:
        tr = _trainer(tmp_path, guard=GuardConfig(warmup_steps=2))
        with pytest.raises(NumericAnomaly) as ei:
            tr.fit(BoringModel(),
                   DataLoader(ArrayDataset(_data()), batch_size=8))
    finally:
        os.environ.pop("RLA_TPU_CHAOS", None)
    e = ei.value
    assert e.step == 4
    flags = e.diagnosis["flags"]
    assert flags["spike"] or flags["update_ratio"], flags
    assert e.blame == "sdc"  # eager replay reproduces nothing


@pytest.mark.chaos
def test_badbatch_blames_data_and_quarantines(tmp_path):
    """blame=data end to end in one process: the recorded host batch is
    non-finite, the ledger gains the blamed window, and a second fit on
    the same root (claim spent through the namespace) skips exactly that
    batch to a clean finish with deterministic step accounting."""
    ns = tmp_path / "chaos_ns"
    os.environ["RLA_TPU_CHAOS"] = "badbatch@step3"
    os.environ["RLA_TPU_CHAOS_NS"] = str(ns)
    try:
        with pytest.raises(NumericAnomaly) as ei:
            _trainer(tmp_path).fit(
                BoringModel(),
                DataLoader(ArrayDataset(_data()), batch_size=8))
        e = ei.value
        assert e.blame == "data"
        assert (e.step, e.epoch, e.batch_idx) == (2, 0, 2)
        assert guardian.skip_set(str(tmp_path), 0) == {2}
        # resumed fit: the claim token is spent, the quarantined batch is
        # skipped WITHOUT breaking the epoch's batch enumeration
        tr = _trainer(tmp_path)
        tr.fit(BoringModel(),
               DataLoader(ArrayDataset(_data()), batch_size=8))
        assert tr.global_step == 7  # 8 batches - 1 quarantined
        assert np.isfinite(float(tr.callback_metrics["train_loss"]))
        # the skip entries survive the clean finish (the data is still
        # bad); only the prune-protection anchor is released
        doc = guardian.load_quarantine(str(tmp_path))
        assert len(doc["entries"]) == 1 and doc["anchor"] is None
    finally:
        os.environ.pop("RLA_TPU_CHAOS", None)
        os.environ.pop("RLA_TPU_CHAOS_NS", None)


@pytest.mark.chaos
@pytest.mark.collectives
def test_bitflip_under_compressed_dp_names_suspect(tmp_path):
    """SDC blame with a NAMED rank: a single-replica exponent-bit flip in
    the stacked local gradients diverges the per-replica badness vector
    (one replica bad, seven clean) — the signature a poisoned global
    batch can never produce."""
    os.environ["RLA_TPU_CHAOS"] = "bitflip@rank1:step5"
    try:
        tr = _trainer(tmp_path, guard=GuardConfig(warmup_steps=2),
                      accelerator=RayTPUAccelerator(num_workers=8),
                      grad_compression="int8")
        with pytest.raises(NumericAnomaly) as ei:
            tr.fit(BoringModel(),
                   DataLoader(ArrayDataset(_data()), batch_size=8))
    finally:
        os.environ.pop("RLA_TPU_CHAOS", None)
    e = ei.value
    assert e.blame == "sdc"
    assert e.suspect_rank == 1
    assert e.diagnosis["flags"]["grad_norm"] > 0


def test_guard_none_bit_identical_and_guarded_zero_retraces(tmp_path):
    """``guard=None`` must reproduce the pre-guardian trajectory exactly
    (the guard is pure observation), and the guarded fit must add zero
    retraces after its warmup epoch — the flags ride the readback that
    was happening anyway."""
    from ray_lightning_accelerators_tpu.analysis import compile_guard as cg
    cg.install()
    compiles = {"at_epoch_end": None, "fit_end": None}

    class _Window(Callback):
        def on_train_epoch_end(self, trainer, module):
            if trainer.current_epoch == 0:
                compiles["at_epoch_end"] = cg.compile_count()

    def fit(guard, cbs=()):
        tr = _trainer(tmp_path / ("g" if guard else "u"), guard=guard,
                      max_epochs=2, callbacks=list(cbs))
        tr.fit(BoringModel(),
               DataLoader(ArrayDataset(_data()), batch_size=8))
        return float(tr.callback_metrics["train_loss"])

    guarded = fit("auto", cbs=[_Window()])
    compiles["fit_end"] = cg.compile_count()
    unguarded = fit(None)
    assert guarded == unguarded  # bit-identical, not merely close
    assert compiles["fit_end"] == compiles["at_epoch_end"]


# --------------------------------------------------------------------- #
# ElasticRunner: rewind semantics (light bodies, no jax in workers)     #
# --------------------------------------------------------------------- #
def _anomaly_once_body(attempt):
    if attempt == 0:
        from ray_lightning_accelerators_tpu.runtime.guardian import (
            NumericAnomaly)
        raise NumericAnomaly.for_trip(step=5, blame="data", epoch=0,
                                      batch_idx=5)
    return "ok"


def test_runner_rewind_does_not_charge_failure_budget():
    """A tripped guard is a REWIND, not a failure: with max_failures=0 a
    one-shot anomaly still resumes — and the typed postmortem crossed the
    worker pipe intact (wire registry), not as a stringly RemoteError."""
    pool = ActorPool(2)
    charged = []
    try:
        runner = ElasticRunner(pool, max_failures=0,
                               on_failure=lambda a, e: charged.append(e))
        out = runner.run(_anomaly_once_body,
                         args_per_worker=lambda a: [(a,)] * 2)
        assert out == ["ok", "ok"]
        assert runner.attempts_used == 2
        assert charged == []
        (ev,) = runner.anomaly_events
        assert ev["blame"] == "data" and ev["step"] == 5
    finally:
        pool.shutdown()


def _anomaly_same_step_body(attempt):
    from ray_lightning_accelerators_tpu.runtime.guardian import (
        NumericAnomaly)
    raise NumericAnomaly.for_trip(step=7, blame="data", epoch=0,
                                  batch_idx=7)


def test_runner_same_data_step_twice_is_terminal():
    """A data-blamed step that trips again AFTER its window was
    quarantined proves the quarantine did not clear it — retrying cannot
    converge, so the loop refuses instead of burning rewinds."""
    pool = ActorPool(1)
    try:
        runner = ElasticRunner(pool, max_failures=0, max_rewinds=5)
        with pytest.raises(RuntimeError,
                           match="recurred after its data window"):
            runner.run(_anomaly_same_step_body,
                       args_per_worker=lambda a: [(a,)])
        assert runner.attempts_used == 2
    finally:
        pool.shutdown()


def _anomaly_roaming_body(attempt):
    from ray_lightning_accelerators_tpu.runtime.guardian import (
        NumericAnomaly)
    raise NumericAnomaly.for_trip(step=100 + attempt, blame="unknown")


def test_runner_max_rewinds_is_terminal():
    pool = ActorPool(1)
    try:
        runner = ElasticRunner(pool, max_failures=0, max_rewinds=2)
        with pytest.raises(RuntimeError,
                           match=r"tripped the numeric guard 3 times"):
            runner.run(_anomaly_roaming_body,
                       args_per_worker=lambda a: [(a,)])
        assert runner.attempts_used == 3
        assert len(runner.anomaly_events) == 3
    finally:
        pool.shutdown()


def _sdc_once_body(attempt, rank):
    if attempt == 0 and rank == 0:
        from ray_lightning_accelerators_tpu.runtime.guardian import (
            NumericAnomaly)
        raise NumericAnomaly.for_trip(step=9, blame="sdc", suspect_rank=2)
    return ("ok", rank)


def test_runner_sdc_demotes_named_suspect_rank():
    """An SDC verdict with a named rank demotes that rank via the elastic
    shrink path: the retry runs at world-1 without the suspect, floored
    by min_workers, without charging the failure budget."""
    pool = ActorPool(3)
    try:
        runner = ElasticRunner(pool, max_failures=0, allow_shrink=True,
                               min_workers=2)
        out = runner.run(
            _sdc_once_body,
            args_per_worker=lambda a, world: [(a, r)
                                              for r in range(world)])
        assert len(out) == 2
        (shrink,) = runner.shrink_events
        assert shrink["dropped"] == [2] and shrink["blame"] == "sdc"
        assert sorted(w.rank for w in pool.workers) == [0, 1]
    finally:
        pool.shutdown()


# --------------------------------------------------------------------- #
# The acceptance loop: chaos fit under the runner, end to end           #
# --------------------------------------------------------------------- #
def _guarded_fit_body(root):
    """One attempt of a guarded single-process fit (spawned worker; the
    runner's restart is the rewind)."""
    import numpy as np
    from ray_lightning_accelerators_tpu import DataLoader, Trainer
    from ray_lightning_accelerators_tpu.data.loader import ArrayDataset
    from tests.utils import BoringModel
    x = np.random.default_rng(0).normal(size=(64, 32)).astype("float32")
    tr = Trainer(max_epochs=2, precision="f32", seed=0,
                 default_root_dir=root, log_every_n_steps=1,
                 enable_checkpointing=False, enable_progress_bar=False)
    tr.fit(BoringModel(), DataLoader(ArrayDataset(x), batch_size=8),
           ckpt_path="last")
    return (tr.global_step,
            float(np.asarray(tr.callback_metrics["train_loss"])))


@pytest.mark.chaos
def test_elastic_rewind_and_skip_acceptance_loop(tmp_path):
    """End to end: ``badbatch@step3`` trips the guarded fit inside a
    worker, the typed ``NumericAnomaly`` crosses the pipe, the runner
    rewinds WITHOUT charging the failure budget, and the retried fit —
    its chaos claim spent, its quarantine ledger shared through the run
    dir — skips the blamed window to a clean two-epoch finish."""
    root = str(tmp_path / "run")
    os.makedirs(root)
    env = {"RLA_TPU_CHAOS": "badbatch@step3",
           "RLA_TPU_CHAOS_NS": str(tmp_path / "chaos_ns"),
           "JAX_PLATFORMS": "cpu"}
    pool = ActorPool(1, env_per_worker=[env])
    try:
        runner = ElasticRunner(pool, max_failures=0, max_rewinds=2)
        ((steps, loss),) = runner.run(
            _guarded_fit_body, args_per_worker=lambda a: [(root,)])
        assert runner.attempts_used == 2
        (ev,) = runner.anomaly_events
        assert ev["blame"] == "data" and ev["batch_idx"] == 2
        # 2 epochs x 8 batches, minus the one quarantined epoch-0 window
        assert steps == 15
        assert np.isfinite(loss)
        assert guardian.skip_set(root, 0) == {2}
    finally:
        pool.shutdown()


# --------------------------------------------------------------------- #
# Serve-tier decode guard                                               #
# --------------------------------------------------------------------- #
@pytest.mark.serve
def test_serve_decode_guard_fails_single_request_typed():
    """Non-finite decode logits fail ONLY the affected slot's request —
    typed ``NumericAnomaly``, ``numeric_anomalies`` counter bumped — and
    the other in-flight request completes token-identical to a
    standalone generate()."""
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    from ray_lightning_accelerators_tpu.serve import ServeEngine
    model = GPT(TransformerConfig(vocab_size=61, d_model=32, n_heads=2,
                                  d_ff=64, n_layers=2, max_seq_len=32))
    params = model.init_params(jax.random.PRNGKey(0))
    pa = np.asarray([1, 2, 3, 4], np.int32)
    pb = np.asarray([7, 8, 9], np.int32)
    ref_b = np.asarray(model.generate(params, jnp.asarray(pb[None]),
                                      max_new_tokens=6))[0]
    with ServeEngine(model, jax.tree.map(np.asarray, params),
                     max_slots=2, queue_depth=8) as eng:
        real = eng._step
        calls = {"n": 0}

        def chaotic(*a):
            toks, ok, cache = real(*a)
            calls["n"] += 1
            if calls["n"] >= 2:  # slot 0's second decode step onward
                ok = ok.at[0].set(False)
            return toks, ok, cache

        eng._step = chaotic
        ra = eng.submit(pa, 8)
        rb = eng.submit(pb, 6)
        with pytest.raises(NumericAnomaly,
                           match="non-finite logits"):
            ra.result(timeout=300)
        out_b = rb.result(timeout=300)
    np.testing.assert_array_equal(out_b, ref_b)
    snap = eng.stats()
    assert snap["numeric_anomalies"] == 1
    assert snap["completed"] == 1 and snap["failed"] == 1
