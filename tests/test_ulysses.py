"""Ulysses (all-to-all) sequence parallelism vs full attention on an
8-device mesh, mirroring tests/test_ring_attention.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_accelerators_tpu.ops.attention import attention_reference
from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib
from ray_lightning_accelerators_tpu.parallel.ulysses import (
    ulysses_attention_sharded)


def _qkv(b=2, h=8, s=256, d=64, seed=0):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(kq, (b, h, s, d)),
            jax.random.normal(kk, (b, h, s, d)),
            jax.random.normal(kv, (b, h, s, d)))


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(causal):
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, sequence=8))
    q, k, v = _qkv()
    ref = attention_reference(q, k, v, causal=causal)
    out = jax.jit(lambda q, k, v: ulysses_attention_sharded(
        q, k, v, mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_with_data_parallel_mix():
    """sequence=4 x data=2: batch and sequence sharded simultaneously."""
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=2, sequence=4))
    q, k, v = _qkv(b=4, s=128)
    ref = attention_reference(q, k, v, causal=True)
    out = jax.jit(lambda q, k, v: ulysses_attention_sharded(
        q, k, v, mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_gradients_flow():
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, sequence=8))
    q, k, v = _qkv(b=1, h=8, s=128, d=64)

    def loss(q, k, v):
        return jnp.sum(
            ulysses_attention_sharded(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, sequence=8))
    q, k, v = _qkv(h=4)  # 4 heads over 8-way sequence axis
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(lambda q, k, v: ulysses_attention_sharded(
            q, k, v, mesh, causal=True))(q, k, v)


def test_gpt_ulysses_matches_ring():
    """The flagship trains identically under either context-parallel
    strategy (same math, different collectives)."""
    from ray_lightning_accelerators_tpu.models.transformer import (
        GPT, TransformerConfig)
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=2, sequence=4))
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(4, 64)), jnp.int32)
    losses = {}
    for strategy in ("ring", "ulysses"):
        cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=4,
                                d_ff=128, n_layers=2, max_seq_len=64,
                                context_parallel=strategy)
        model = GPT(cfg)
        model.mesh = mesh
        params = model.init_params(jax.random.PRNGKey(0))
        loss, _ = jax.jit(lambda p: model.training_step(
            p, toks, jax.random.PRNGKey(1)))(params)
        losses[strategy] = float(loss)
    assert losses["ring"] == pytest.approx(losses["ulysses"], rel=1e-4)
